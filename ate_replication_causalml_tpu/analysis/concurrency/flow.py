"""Interprocedural lock-set analysis for graftrace (stdlib-only).

Layered on the per-module extraction (:mod:`.extract`) and graftlint's
function table (:mod:`..jaxast`), this builds one :class:`Analysis`
over the whole program:

* a call graph with receiver-type resolution (``self.m()``,
  ``self.attr.m()`` via ``__init__`` constructor types, cross-module
  dotted calls via import aliases, and a unique-bare-name fallback
  gated by a generic-name blocklist);
* per-function lock-set summaries from a lexical walk of ``with``
  blocks (held sets, acquisition sites, blocking calls, ``self.attr``
  writes, ``Condition.wait`` sites, collective-launcher sites);
* the fixpoints the rules consume — transitive acquisitions,
  may-block, thread-entry reachability, guaranteed-held-lock sets, and
  the lock acquisition-order graph.

Precision stance: edges and lock resolutions are *dropped* when a
receiver cannot be typed — a missed edge can miss a finding (bounded
by the fixture suite), while an invented edge would manufacture
deadlock cycles out of thin air and bury the report.
"""

from __future__ import annotations

import ast
import dataclasses

from ate_replication_causalml_tpu.analysis.core import ModuleInfo, Program
from ate_replication_causalml_tpu.analysis.jaxast import (
    MUTATOR_METHODS,
    collect_functions,
)
from ate_replication_causalml_tpu.analysis import scopes
from ate_replication_causalml_tpu.analysis.concurrency.extract import (
    ClassInfo,
    LockDef,
    ModuleConc,
    extract,
)

#: Method names far too common to resolve by bare-name uniqueness — a
#: one-definition coincidence must not create an interprocedural edge.
GENERIC_NAMES = frozenset({
    "get", "put", "set", "pop", "add", "update", "append", "extend",
    "remove", "clear", "close", "start", "stop", "run", "wait", "notify",
    "notify_all", "acquire", "release", "join", "submit", "send", "recv",
    "read", "write", "open", "items", "keys", "values", "copy", "emit",
    "inc", "observe", "register", "install", "describe", "snapshot",
    "evaluate", "tick", "fail", "resolve", "reset", "result", "fit",
    "exec", "beat", "ages", "active", "enabled", "state", "main", "next",
    "flush", "reload", "retry", "check", "build", "load", "dump", "step",
})

#: Collective launchers (dotted-suffix match): the artifact plane's
#: device-dispatching entry points plus shard_map itself.
COLLECTIVE_SUFFIXES = (
    "shardio.commit", "shardio.reshard", "shardio.gather_host",
    "shardio.host_bounce", ".shard_map", "shard_map.shard_map",
)

#: Attribute names whose zero-arg call blocks the calling thread.
_BLOCKING_ZERO_ARG = {"join", "get", "wait", "acquire"}
_BLOCKING_ALWAYS = {"accept", "recv", "recvfrom", "serve_forever"}
_DEVICE_BLOCKING = {"block_until_ready", "device_get"}


def is_lane_lock(lock_id: str) -> bool:
    """Locks that satisfy the collective-launch discipline (JGL018) and
    are exempt from blocking-under-lock (JGL016): the mesh-lane family
    exists precisely to serialize device dispatch."""
    return "lane" in lock_id.lower() or lock_id.endswith("_DEFAULT_MESH_LOCK")


@dataclasses.dataclass(frozen=True)
class FuncKey:
    rel: str
    qual: str

    @property
    def id(self) -> str:
        return f"{self.rel}::{self.qual}"


@dataclasses.dataclass
class CallSite:
    held: frozenset
    callee: FuncKey | None
    dotted: str | None
    name: str  # bare callable name (attr or id) for messages
    line: int


@dataclasses.dataclass
class BlockSite:
    held: frozenset
    what: str
    line: int


@dataclasses.dataclass
class WriteSite:
    cls: str  # class qualname
    attr: str
    held: frozenset
    line: int
    qual: str  # containing function qualname


@dataclasses.dataclass
class WaitSite:
    lock_id: str
    has_timeout: bool
    in_while: bool
    held_other: frozenset  # held locks minus the condition itself
    line: int


@dataclasses.dataclass
class Summary:
    key: FuncKey
    acquisitions: list = dataclasses.field(default_factory=list)  # (held, lock_id, line)
    calls: list = dataclasses.field(default_factory=list)  # CallSite
    blocking: list = dataclasses.field(default_factory=list)  # BlockSite
    writes: list = dataclasses.field(default_factory=list)  # WriteSite
    waits: list = dataclasses.field(default_factory=list)  # WaitSite
    collectives: list = dataclasses.field(default_factory=list)  # (held, name, line)


@dataclasses.dataclass
class Entry:
    id: str
    kind: str  # thread | pool | http-handler | public-api
    key: FuncKey | None
    file: str
    line: int
    target: str  # display form of the target


class Analysis:
    """Whole-program concurrency model + derived fixpoints."""

    def __init__(self, program: Program):
        self.modules: list[ModuleInfo] = [
            m for m in program.modules if scopes.CONCURRENCY.contains(m.relpath)
        ]
        self.conc: dict[str, ModuleConc] = {
            m.relpath: extract(m) for m in self.modules
        }
        self.locks: dict[str, LockDef] = {}
        self.funcs: dict[FuncKey, object] = {}
        self.summaries: dict[FuncKey, Summary] = {}
        self.entries: list[Entry] = []
        self._index()
        self._summarize()
        self._entries()
        self._fixpoints()

    # ── indexing ─────────────────────────────────────────────────────

    def _index(self) -> None:
        self._mod_dotted: dict[str, str] = {}  # dotted module -> relpath
        self._class_by_dotted: dict[str, tuple[str, str]] = {}
        self._by_bare: dict[str, list[FuncKey]] = {}
        for m in self.modules:
            dotted = m.relpath[:-3].replace("/", ".") if m.relpath.endswith(".py") else None
            if dotted:
                self._mod_dotted[dotted] = m.relpath
            conc = self.conc[m.relpath]
            for ld in conc.global_locks.values():
                self.locks[ld.id] = ld
            for info in conc.classes.values():
                for ld in info.attr_locks.values():
                    self.locks[ld.id] = ld
                if dotted:
                    self._class_by_dotted[f"{dotted}.{info.qualname}"] = (
                        m.relpath, info.qualname
                    )
                self._class_by_dotted.setdefault(
                    info.qualname, (m.relpath, info.qualname)
                )
            for ld in conc.lock_returners.values():
                self.locks.setdefault(ld.id, ld)
            for qual, rec in collect_functions(m).items():
                key = FuncKey(m.relpath, qual)
                self.funcs[key] = rec
                self._by_bare.setdefault(rec.name, []).append(key)

    def class_of(self, key: FuncKey) -> ClassInfo | None:
        if "." not in key.qual:
            return None
        cls_qual = key.qual.rsplit(".", 1)[0]
        return self.conc[key.rel].classes.get(cls_qual)

    def _class_info(self, dotted: str | None) -> tuple[str, ClassInfo] | None:
        if not dotted:
            return None
        hit = self._class_by_dotted.get(dotted)
        if hit is None:
            return None
        rel, qual = hit
        return rel, self.conc[rel].classes[qual]

    def _module_func(self, dotted: str) -> FuncKey | None:
        """``pkg.mod.fn`` / ``pkg.mod.Class.method`` -> FuncKey."""
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            rel = self._mod_dotted.get(".".join(parts[:cut]))
            if rel is None:
                continue
            qual = ".".join(parts[cut:])
            key = FuncKey(rel, qual)
            return key if key in self.funcs else None
        return None

    def resolve_target(
        self, conc: ModuleConc, enclosing: str | None, target: ast.expr
    ) -> FuncKey | None:
        """Resolve a Thread/submit target expression to a function."""
        m = conc.module
        if isinstance(target, ast.Name):
            if enclosing:  # nested def inside the spawning function
                key = FuncKey(m.relpath, f"{enclosing}.{target.id}")
                if key in self.funcs:
                    return key
            key = FuncKey(m.relpath, target.id)
            if key in self.funcs:
                return key
            return self._module_func(m.resolve(target) or "")
        if isinstance(target, ast.Attribute):
            base = target.value
            if isinstance(base, ast.Name) and enclosing:
                # self._run from inside a method of the same class
                cls_qual = enclosing.rsplit(".", 1)[0] if "." in enclosing else None
                info = conc.classes.get(cls_qual) if cls_qual else None
                if info is not None and base.id == "self":
                    qual = info.methods.get(target.attr)
                    if qual:
                        return FuncKey(m.relpath, qual)
            return self._module_func(m.resolve(target) or "")
        return None

    # ── per-function summaries ───────────────────────────────────────

    def _summarize(self) -> None:
        for m in self.modules:
            conc = self.conc[m.relpath]
            for qual, rec in sorted(collect_functions(m).items()):
                key = FuncKey(m.relpath, qual)
                self.summaries[key] = _FunctionWalker(self, conc, key, rec).walk()

    # ── entrypoints ──────────────────────────────────────────────────

    def _entries(self) -> None:
        seen: set[str] = set()

        def add(e: Entry) -> None:
            if e.id not in seen:
                seen.add(e.id)
                self.entries.append(e)

        for rel in sorted(self.conc):
            conc = self.conc[rel]
            for ref in conc.thread_refs:
                key = self.resolve_target(conc, ref.enclosing, ref.target)
                target = ast.unparse(ref.target)
                eid = (
                    key.id if key is not None
                    else f"{rel}::<{ref.kind}@{ref.line}:{target}>"
                )
                add(Entry(eid, ref.kind, key, rel, ref.line, target))
            for qual in conc.handler_entries:
                key = FuncKey(rel, qual)
                if key in self.funcs:
                    add(Entry(key.id, "http-handler", key, rel,
                              self.funcs[key].node.lineno, qual))
            # Public methods of lock/thread-owning classes: the surface
            # external threads call into (start/stop/submit/drain...).
            for cq in sorted(conc.classes):
                info = conc.classes[cq]
                if not info.owns_concurrency():
                    continue
                for name in sorted(info.methods):
                    if name.startswith("_"):
                        continue
                    key = FuncKey(rel, info.methods[name])
                    if key in self.funcs:
                        add(Entry(key.id, "public-api", key, rel,
                                  self.funcs[key].node.lineno, key.qual))

    # ── fixpoints ────────────────────────────────────────────────────

    def _fixpoints(self) -> None:
        # Call-graph edges (resolved callees only).
        self.edges: dict[FuncKey, list[CallSite]] = {
            k: [c for c in s.calls if c.callee is not None]
            for k, s in self.summaries.items()
        }
        callees: dict[FuncKey, set[FuncKey]] = {
            k: {c.callee for c in cs} for k, cs in self.edges.items()
        }
        callers: dict[FuncKey, set[FuncKey]] = {k: set() for k in self.summaries}
        for k, outs in callees.items():
            for o in outs:
                if o in callers:
                    callers[o].add(k)

        # Transitive lock acquisitions.
        acq: dict[FuncKey, set[str]] = {
            k: {lock for _, lock, _ in s.acquisitions}
            for k, s in self.summaries.items()
        }
        changed = True
        while changed:
            changed = False
            for k in self.summaries:
                for o in callees.get(k, ()):
                    extra = acq.get(o, set()) - acq[k]
                    if extra:
                        acq[k] |= extra
                        changed = True
        self.trans_acquires = acq

        # May-block (what + witness line for messages).
        blk: dict[FuncKey, str | None] = {}
        for k, s in self.summaries.items():
            direct = s.blocking + [
                BlockSite(w.held_other, "Condition.wait() without timeout", w.line)
                for w in s.waits if not w.has_timeout
            ]
            blk[k] = (
                f"{direct[0].what} at {k.rel}:{direct[0].line}" if direct else None
            )
        changed = True
        while changed:
            changed = False
            for k in self.summaries:
                if blk[k] is not None:
                    continue
                for c in self.edges.get(k, ()):
                    w = blk.get(c.callee)
                    if w is not None:
                        blk[k] = f"{c.name} -> {w}"
                        changed = True
                        break
        self.may_block = blk

        # Thread-entry reachability: func -> sorted entry ids.
        reach: dict[FuncKey, set[str]] = {k: set() for k in self.summaries}
        for e in self.entries:
            if e.key is None or e.key not in self.summaries:
                continue
            stack = [e.key]
            seen = set()
            while stack:
                k = stack.pop()
                if k in seen:
                    continue
                seen.add(k)
                reach[k].add(e.id)
                stack.extend(callees.get(k, ()))
        self.entry_reach = reach

        # Guaranteed-held: meet (intersection) over all call paths from
        # roots. Roots: entrypoints and functions with no in-scope
        # callers (called from outside the analyzed planes).
        guaranteed: dict[FuncKey, set[str]] = {}
        roots = {e.key for e in self.entries if e.key is not None}
        roots |= {k for k, cs in callers.items() if not cs}
        work = []
        for r in sorted(roots, key=lambda k: k.id):
            if r in self.summaries:
                guaranteed[r] = set()
                work.append(r)
        while work:
            k = work.pop()
            for c in self.edges.get(k, ()):
                ctx = guaranteed[k] | set(c.held)
                cur = guaranteed.get(c.callee)
                new = ctx if cur is None else (cur & ctx)
                if cur is None or new != cur:
                    guaranteed[c.callee] = set(new)
                    work.append(c.callee)
        self.guaranteed = guaranteed

        # Lock acquisition-order edges: held -> newly-acquired, both
        # directly and through calls that transitively acquire.
        order: dict[tuple[str, str], list[str]] = {}

        def edge(a: str, b: str, site: str) -> None:
            if a != b:
                order.setdefault((a, b), []).append(site)

        for k in sorted(self.summaries, key=lambda k: k.id):
            s = self.summaries[k]
            for held, lock, line in s.acquisitions:
                for h in sorted(held):
                    edge(h, lock, f"{k.rel}:{line}")
            for c in self.edges.get(k, ()):
                if not c.held:
                    continue
                for a in sorted(self.trans_acquires.get(c.callee, ())):
                    for h in sorted(c.held):
                        edge(h, a, f"{k.rel}:{c.line} (via {c.name})")
        self.order_edges = order

    # ── cycle detection (JGL015) ─────────────────────────────────────

    def lock_cycles(self) -> list[tuple[list[str], list[str]]]:
        """Strongly-connected components of ≥2 locks in the order
        graph: ``(sorted lock ids, witness sites)`` per cycle."""
        graph: dict[str, set[str]] = {}
        for a, b in self.order_edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            # Iterative Tarjan: analysis may run on deep lock graphs.
            call_stack = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while call_stack:
                node, it = call_stack[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        call_stack.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                call_stack.pop()
                if call_stack:
                    parent = call_stack[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)

        out = []
        for comp in sorted(sccs):
            members = set(comp)
            sites: list[str] = []
            for (a, b), where in sorted(self.order_edges.items()):
                if a in members and b in members:
                    sites.append(f"{a} -> {b} at {where[0]}")
            out.append((comp, sites))
        return out


class _FunctionWalker:
    """Lexical walk of one function body tracking the held lock set."""

    def __init__(self, analysis: Analysis, conc: ModuleConc, key: FuncKey, rec):
        self.an = analysis
        self.conc = conc
        self.key = key
        self.rec = rec
        self.module = conc.module
        self.summary = Summary(key=key)
        cls = analysis.class_of(key)
        self.cls: ClassInfo | None = cls
        args = rec.node.args.posonlyargs + rec.node.args.args
        self.self_name = args[0].arg if (cls is not None and args) else None
        self.local_locks: dict[str, str] = {}
        self.local_types: dict[str, str] = {}
        self._prescan()

    # -- local environment --------------------------------------------

    def _prescan(self) -> None:
        from ate_replication_causalml_tpu.analysis.jaxast import own_statements

        for node in own_statements(self.rec.node):
            if not isinstance(node, ast.Assign):
                continue
            lock = self._lock_of(node.value, allow_local=False)
            ctor = None
            if isinstance(node.value, ast.Call):
                ctor = self.module.resolve(node.value.func)
            attr_src = self._self_attr(node.value)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if lock is not None:
                    self.local_locks[t.id] = lock
                elif attr_src is not None and self.cls is not None:
                    ty = self.cls.attr_types.get(attr_src)
                    if ty:
                        self.local_types[t.id] = ty
                elif ctor:
                    self._note_local_type(t.id, ctor)

    def _note_local_type(self, name: str, ctor: str) -> None:
        if ctor in ("threading.Thread", "threading.Event"):
            self.local_types[name] = ctor
        elif self.an._class_info(ctor) is not None:
            self.local_types[name] = ctor

    def _self_attr(self, node: ast.expr) -> str | None:
        if (
            self.self_name is not None
            and isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self.self_name
        ):
            return node.attr
        return None

    # -- lock expression resolution -----------------------------------

    def _lock_of(self, expr: ast.expr, allow_local: bool = True) -> str | None:
        """Lock id acquired by ``with expr`` (None when unresolvable)."""
        if isinstance(expr, ast.Name):
            if allow_local and expr.id in self.local_locks:
                return self.local_locks[expr.id]
            ld = self.conc.global_locks.get(expr.id)
            return ld.id if ld else None
        attr = self._self_attr(expr)
        if attr is not None and self.cls is not None:
            ld = self.cls.attr_locks.get(attr)
            return ld.id if ld else None
        if isinstance(expr, ast.Call):
            return self._lock_of_call(expr)
        if isinstance(expr, ast.Attribute):
            dotted = self.module.resolve(expr)
            if dotted:
                parts = dotted.split(".")
                for cut in range(len(parts) - 1, 0, -1):
                    rel = self.an._mod_dotted.get(".".join(parts[:cut]))
                    if rel is None:
                        continue
                    rest = ".".join(parts[cut:])
                    ld = self.an.conc[rel].global_locks.get(rest)
                    return ld.id if ld else None
        return None

    def _lock_of_call(self, call: ast.Call) -> str | None:
        """``self._entry_lock(k)`` / ``self.cache.lane_lock(l)`` /
        ``module.fn(...)`` resolving to a lock-returning function."""
        func = call.func
        attr = self._self_attr(func)
        if attr is not None and self.cls is not None:
            qual = self.cls.methods.get(attr)
            if qual:
                ld = self.conc.lock_returners.get(qual)
                return ld.id if ld else None
        if isinstance(func, ast.Name):
            ld = self.conc.lock_returners.get(func.id)
            return ld.id if ld else None
        if isinstance(func, ast.Attribute):
            hit = self._receiver_class(func.value)
            if hit is not None:
                rel, info = hit
                qual = info.methods.get(func.attr)
                if qual:
                    ld = self.an.conc[rel].lock_returners.get(qual)
                    return ld.id if ld else None
        return None

    def _receiver_class(self, base: ast.expr) -> tuple[str, ClassInfo] | None:
        """Type the receiver expression of a method call."""
        attr = self._self_attr(base)
        if attr is not None and self.cls is not None:
            return self.an._class_info(self.cls.attr_types.get(attr))
        if isinstance(base, ast.Name):
            return self.an._class_info(self.local_types.get(base.id))
        return None

    def _receiver_type_name(self, base: ast.expr) -> str | None:
        attr = self._self_attr(base)
        if attr is not None and self.cls is not None:
            return self.cls.attr_types.get(attr)
        if isinstance(base, ast.Name):
            return self.local_types.get(base.id)
        return None

    # -- callee resolution --------------------------------------------

    def _resolve_call(self, call: ast.Call) -> tuple[FuncKey | None, str | None, str]:
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            for qual in self._enclosing_chain(name):
                key = FuncKey(self.key.rel, qual)
                if key in self.an.funcs:
                    return key, None, name
            dotted = self.module.resolve(func)
            if dotted and dotted != name:
                key = self.an._module_func(dotted)
                if key is not None:
                    return key, dotted, name
            return self._unique_fallback(name), dotted, name
        if isinstance(func, ast.Attribute):
            name = func.attr
            base = func.value
            if (
                isinstance(base, ast.Name)
                and base.id == self.self_name
                and self.cls is not None
            ):
                # self.method() — same class, maybe inherited (skip).
                qual = self.cls.methods.get(name)
                if qual:
                    return FuncKey(self.key.rel, qual), None, name
            attr = self._self_attr(base)  # self.attr.method(): typed receiver
            hit = self._receiver_class(func.value)
            if hit is not None:
                rel, info = hit
                qual = info.methods.get(name)
                if qual:
                    return FuncKey(rel, qual), None, name
                return None, None, name  # typed receiver, unknown method
            dotted = self.module.resolve(func)
            if dotted:
                key = self.an._module_func(dotted)
                if key is not None:
                    return key, dotted, name
            if attr is None and not isinstance(func.value, ast.Name):
                return None, dotted, name
            return self._unique_fallback(name), dotted, name
        return None, None, "<expr>"

    def _enclosing_chain(self, name: str):
        """Candidate qualnames for a bare call: nested def in this
        function, sibling nested def, then module function."""
        if self.rec.parent or "." in self.key.qual:
            yield f"{self.key.qual}.{name}"
        if self.rec.parent:
            yield f"{self.rec.parent}.{name}"
        yield name

    def _unique_fallback(self, name: str) -> FuncKey | None:
        if name in GENERIC_NAMES or name.startswith("__"):
            return None
        hits = self.an._by_bare.get(name, ())
        return hits[0] if len(hits) == 1 else None

    # -- the walk ------------------------------------------------------

    def walk(self) -> Summary:
        self._stmts(self.rec.node.body, frozenset(), in_while=False)
        return self.summary

    def _stmts(self, body, held: frozenset, in_while: bool) -> None:
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
                new_held = set(held)
                for item in st.items:
                    self._expr(item.context_expr, frozenset(new_held), in_while)
                    lock = self._lock_of(item.context_expr)
                    if lock is not None:
                        self.summary.acquisitions.append(
                            (frozenset(new_held), lock, item.context_expr.lineno)
                        )
                        new_held.add(lock)
                self._stmts(st.body, frozenset(new_held), in_while)
            elif isinstance(st, ast.While):
                self._expr(st.test, held, in_while)
                self._stmts(st.body, held, in_while=True)
                self._stmts(st.orelse, held, in_while)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._expr(st.iter, held, in_while)
                self._stmts(st.body, held, in_while=True)
                self._stmts(st.orelse, held, in_while)
            elif isinstance(st, ast.If):
                self._expr(st.test, held, in_while)
                self._stmts(st.body, held, in_while)
                self._stmts(st.orelse, held, in_while)
            elif isinstance(st, ast.Try):
                self._stmts(st.body, held, in_while)
                for h in st.handlers:
                    self._stmts(h.body, held, in_while)
                self._stmts(st.orelse, held, in_while)
                self._stmts(st.finalbody, held, in_while)
            else:
                if isinstance(st, ast.Assign):
                    self._record_write_targets(st.targets, held)
                elif isinstance(st, ast.AugAssign):
                    self._record_write_targets([st.target], held)
                for child in ast.iter_child_nodes(st):
                    if isinstance(child, ast.expr):
                        self._expr(child, held, in_while)

    def _record_write_targets(self, targets, held: frozenset) -> None:
        if self.cls is None:
            return
        for t in targets:
            node = t
            if isinstance(node, ast.Subscript):
                node = node.value
            attr = self._self_attr(node)
            if attr is not None:
                self.summary.writes.append(
                    WriteSite(self.cls.qualname, attr, held, t.lineno, self.key.qual)
                )

    def _expr(self, expr: ast.expr, held: frozenset, in_while: bool) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._call(node, held, in_while)

    def _call(self, call: ast.Call, held: frozenset, in_while: bool) -> None:
        func = call.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None
        )
        dotted = self.module.resolve(func) if name else None

        # Collective launchers (dotted suffix, or a launcher attr on a
        # shardio-shaped receiver like `_shardio().commit(...)`).
        display = dotted or (name or "<call>")
        if self._is_collective(call, name, dotted):
            self.summary.collectives.append((held, display, call.lineno))
            self.summary.blocking.append(
                BlockSite(held, f"device dispatch via {display}", call.lineno)
            )
            return

        # Condition.wait — classified against the resolved receiver.
        if name == "wait" and isinstance(func, ast.Attribute):
            recv_lock = self._lock_of(func.value)
            has_timeout = bool(call.args) or any(
                kw.arg == "timeout" for kw in call.keywords
            )
            if recv_lock is not None and (
                "condition" in self.an.locks.get(
                    recv_lock, LockDef(recv_lock, "", "", 0)
                ).kind
            ):
                self.summary.waits.append(
                    WaitSite(
                        recv_lock, has_timeout, in_while,
                        frozenset(h for h in held if h != recv_lock),
                        call.lineno,
                    )
                )
                return
            recv_ty = self._receiver_type_name(func.value)
            if recv_ty == "threading.Event":
                return  # Event.wait: a barrier, not a lock-holding wait
            if not has_timeout and recv_lock is None and recv_ty is None:
                self.summary.blocking.append(
                    BlockSite(held, "wait() without timeout", call.lineno)
                )
                return

        # Other direct blocking shapes.
        if name in _BLOCKING_ALWAYS:
            self.summary.blocking.append(
                BlockSite(held, f"{name}()", call.lineno)
            )
        elif name in _DEVICE_BLOCKING or dotted in (
            "jax.block_until_ready", "jax.device_get"
        ):
            self.summary.blocking.append(
                BlockSite(held, f"device sync {name}()", call.lineno)
            )
        elif name in _BLOCKING_ZERO_ARG and not call.args and not call.keywords:
            if name == "join" or name == "get" or name == "acquire":
                self.summary.blocking.append(
                    BlockSite(held, f"{name}() without timeout", call.lineno)
                )
        elif name == "join" and isinstance(func, ast.Attribute):
            recv_ty = self._receiver_type_name(func.value)
            if recv_ty == "threading.Thread":
                self.summary.blocking.append(
                    BlockSite(held, "Thread.join()", call.lineno)
                )

        callee, cdotted, cname = self._resolve_call(call)
        self.summary.calls.append(
            CallSite(held, callee, cdotted, cname, call.lineno)
        )

    def _is_collective(self, call: ast.Call, name, dotted) -> bool:
        if self.key.rel.endswith("parallel/shardio.py"):
            return False  # the plane's own implementation is the baseline
        if dotted and any(dotted.endswith(sfx) for sfx in COLLECTIVE_SUFFIXES):
            return True
        if (
            name in ("commit", "reshard", "gather_host", "host_bounce")
            and isinstance(call.func, ast.Attribute)
        ):
            try:
                recv = ast.unparse(call.func.value)
            except Exception:
                return False
            return "shardio" in recv.lower()
        return False


def analyze(program: Program) -> Analysis:
    """Build (and memoize on the program) the concurrency analysis."""
    cached = getattr(program, "_graftrace_analysis", None)
    if cached is None:
        cached = Analysis(program)
        program._graftrace_analysis = cached
    return cached

"""CONCURRENCY_MODEL.json builder — the committed concurrency model.

The model is a deterministic projection of :class:`~.flow.Analysis`:
the lock registry, the acquisition-order DAG, the thread entrypoints,
and each resolved entrypoint's transitive lock-set. It is committed at
the repo root and the static gate regenerates it and byte-compares
(``scripts/graftrace.py --check``), so any concurrency-shape change —
a new lock, a new thread, a changed acquisition order — shows up as a
reviewable diff instead of an invisible drift.

Determinism contract: everything is sorted, sites are capped, and the
serializer pins ``sort_keys``/``indent`` — byte-identical across runs
on the same tree (asserted in tier-1 tests).
"""

from __future__ import annotations

import json

from ate_replication_causalml_tpu.analysis.core import Program
from ate_replication_causalml_tpu.analysis.concurrency.flow import analyze

#: Bump when the model layout changes (validated by
#: ``scripts/check_concurrency_model.py``).
MODEL_SCHEMA_VERSION = 1

#: Entry kinds that are structural (spawn sites / handler classes) and
#: therefore stable enough to commit. ``public-api`` entries are an
#: analysis-side over-approximation and stay out of the artifact.
_COMMITTED_ENTRY_KINDS = ("http-handler", "pool", "thread")


def build_model(program: Program) -> dict:
    an = analyze(program)
    locks = [
        {"id": ld.id, "kind": ld.kind, "file": ld.file, "line": ld.line}
        for ld in sorted(an.locks.values(), key=lambda l: l.id)
    ]
    order = [
        {
            "from": a,
            "to": b,
            "sites": sorted(set(sites))[:3],
        }
        for (a, b), sites in sorted(an.order_edges.items())
    ]
    entries = []
    locksets = {}
    for e in sorted(an.entries, key=lambda e: e.id):
        if e.kind not in _COMMITTED_ENTRY_KINDS:
            continue
        entries.append(
            {
                "id": e.id,
                "kind": e.kind,
                "file": e.file,
                "line": e.line,
                "target": e.target,
            }
        )
        if e.key is not None:
            locksets[e.id] = sorted(an.trans_acquires.get(e.key, ()))
    return {
        "schema_version": MODEL_SCHEMA_VERSION,
        "locks": locks,
        "lock_order": order,
        "thread_entries": entries,
        "entry_locksets": locksets,
    }


def to_json(model: dict) -> str:
    """The one serialization the byte-identity contract is defined on."""
    return json.dumps(model, indent=2, sort_keys=True) + "\n"


def render_markdown(model: dict) -> str:
    """The generated section of CONCURRENCY.md (between the markers)."""
    lines = ["## Concurrency model (generated)", ""]
    lines.append(f"Locks: **{len(model['locks'])}** · "
                 f"order edges: **{len(model['lock_order'])}** · "
                 f"thread entrypoints: **{len(model['thread_entries'])}**")
    lines.append("")
    lines.append("### Lock registry")
    lines.append("")
    lines.append("| Lock | Kind | Defined at |")
    lines.append("| --- | --- | --- |")
    for l in model["locks"]:
        lines.append(f"| `{l['id']}` | {l['kind']} | `{l['file']}:{l['line']}` |")
    lines.append("")
    lines.append("### Thread entrypoints")
    lines.append("")
    lines.append("| Entry | Kind | Spawned at | Transitive lock-set |")
    lines.append("| --- | --- | --- | --- |")
    for e in model["thread_entries"]:
        locks = model["entry_locksets"].get(e["id"])
        shown = (
            "<br>".join(f"`{l}`" for l in locks) if locks
            else ("—" if locks is not None else "(unresolved)")
        )
        lines.append(
            f"| `{e['target']}` | {e['kind']} | "
            f"`{e['file']}:{e['line']}` | {shown} |"
        )
    lines.append("")
    lines.append("### Acquisition order")
    lines.append("")
    lines.append("Edges read \"left is held while right is acquired\"; the "
                 "gate fails on any cycle (JGL015).")
    lines.append("")
    lines.append("| Held | Then acquired | Witness |")
    lines.append("| --- | --- | --- |")
    for edge in model["lock_order"]:
        lines.append(
            f"| `{edge['from']}` | `{edge['to']}` | `{edge['sites'][0]}` |"
        )
    lines.append("")
    return "\n".join(lines)

"""graftrace rules JGL015–JGL019: whole-program concurrency findings.

These are :class:`~..core.ProgramRule` subclasses — they see every
module of the run at once and share one memoized :class:`~.flow.Analysis`
per program. Findings anchor to real file:line sites so the ordinary
``# graftlint: disable=`` machinery applies; a suppression here is a
design statement ("this dispatch deliberately happens under the entry
lock") and the gate requires each one to carry a justification.
"""

from __future__ import annotations

from typing import Iterable

from ate_replication_causalml_tpu.analysis.core import (
    Finding,
    Program,
    ProgramRule,
    register_program,
)
from ate_replication_causalml_tpu.analysis.concurrency.flow import (
    Analysis,
    analyze,
    is_lane_lock,
)

#: Attribute types JGL019 never treats as guarded shared data: Events
#: are one-way flags with their own memory semantics, thread-locals are
#: unshared by construction.
_JGL019_EXEMPT_TYPES = {"threading.Event", "threading.local"}


def _site_finding(rule_id: str, rel: str, line: int, message: str) -> Finding:
    return Finding(rule=rule_id, path=rel, line=line, col=1, message=message)


@register_program
class LockOrderInversion(ProgramRule):
    id = "JGL015"
    name = "lock-order-inversion"
    description = (
        "Two or more locks are acquired in conflicting orders on "
        "different call paths (ABBA): a cycle in the acquisition-order "
        "graph is a latent deadlock between the threads that run those "
        "paths."
    )

    def check(self, program: Program) -> Iterable[Finding]:
        an = analyze(program)
        for locks, sites in an.lock_cycles():
            # Anchor on the first witness edge's source line.
            rel, line = _parse_site(sites[0]) if sites else ("<program>", 1)
            yield _site_finding(
                self.id, rel, line,
                "lock-order inversion across {%s}; conflicting edges: %s"
                % (", ".join(locks), "; ".join(sites[:4])),
            )


@register_program
class BlockingUnderLock(ProgramRule):
    id = "JGL016"
    name = "blocking-under-lock"
    description = (
        "A blocking operation (join/recv/accept, untimed queue.get or "
        "Condition.wait, device dispatch) runs while a non-lane lock is "
        "held — every other thread needing that lock stalls for the "
        "full blocking duration."
    )

    def check(self, program: Program) -> Iterable[Finding]:
        an = analyze(program)
        seen: set[tuple[str, int]] = set()
        for key in sorted(an.summaries, key=lambda k: k.id):
            s = an.summaries[key]
            for b in s.blocking:
                held = _non_exempt(b.held)
                if held and (key.rel, b.line) not in seen:
                    seen.add((key.rel, b.line))
                    yield _site_finding(
                        self.id, key.rel, b.line,
                        f"blocking operation ({b.what}) while holding "
                        f"{_fmt_locks(held)} in {key.qual}",
                    )
            for w in s.waits:
                held = _non_exempt(w.held_other)
                if not w.has_timeout and held and (key.rel, w.line) not in seen:
                    seen.add((key.rel, w.line))
                    yield _site_finding(
                        self.id, key.rel, w.line,
                        f"untimed Condition.wait on {w.lock_id} while also "
                        f"holding {_fmt_locks(held)} in {key.qual}",
                    )
            for c in an.edges.get(key, ()):
                held = _non_exempt(c.held)
                if not held or (key.rel, c.line) in seen:
                    continue
                witness = an.may_block.get(c.callee)
                if witness is not None:
                    seen.add((key.rel, c.line))
                    yield _site_finding(
                        self.id, key.rel, c.line,
                        f"call to {c.name} may block ({witness}) while "
                        f"holding {_fmt_locks(held)} in {key.qual}",
                    )


@register_program
class CondWaitOutsidePredicateLoop(ProgramRule):
    id = "JGL017"
    name = "cond-wait-outside-loop"
    description = (
        "Condition.wait outside a predicate re-check loop: spurious "
        "wakeups and notify_all races make a bare wait() return with "
        "the predicate still false."
    )

    def check(self, program: Program) -> Iterable[Finding]:
        an = analyze(program)
        for key in sorted(an.summaries, key=lambda k: k.id):
            for w in an.summaries[key].waits:
                if not w.in_while:
                    yield _site_finding(
                        self.id, key.rel, w.line,
                        f"Condition.wait on {w.lock_id} outside a while-"
                        f"predicate loop in {key.qual}",
                    )


@register_program
class CollectiveWithoutLaneLock(ProgramRule):
    id = "JGL018"
    name = "collective-without-lane-lock"
    description = (
        "A collective launcher (shard_map / shardio commit/reshard/"
        "gather) is reachable without the mesh lane lock: two threads "
        "enqueueing collectives concurrently deadlock the device mesh."
    )

    def check(self, program: Program) -> Iterable[Finding]:
        an = analyze(program)
        for key in sorted(an.summaries, key=lambda k: k.id):
            ctx = an.guaranteed.get(key, set())
            for held, name, line in an.summaries[key].collectives:
                effective = set(held) | ctx
                if not any(is_lane_lock(l) for l in effective):
                    yield _site_finding(
                        self.id, key.rel, line,
                        f"collective launch via {name} in {key.qual} is "
                        f"reachable without the mesh lane lock "
                        f"(locks guaranteed here: {_fmt_locks(effective)})",
                    )


@register_program
class UnguardedCrossThreadWrite(ProgramRule):
    id = "JGL019"
    name = "unguarded-cross-thread-write"
    description = (
        "An instance attribute is written from two or more thread "
        "entrypoints with no lock common to all write sites — the "
        "thread-reachability extension of JGL006/JGL008."
    )

    def check(self, program: Program) -> Iterable[Finding]:
        an = analyze(program)
        groups = _write_groups(an)
        for (rel, cls, attr) in sorted(groups):
            sites = groups[(rel, cls, attr)]
            entries: set[str] = set()
            for w, func in sites:
                entries |= an.entry_reach.get(func, set())
            if len(entries) < 2:
                continue
            common = None
            for w, func in sites:
                eff = set(w.held) | an.guaranteed.get(func, set())
                common = eff if common is None else (common & eff)
            if common:
                continue
            lines = sorted({w.line for w, _ in sites})
            shown = ", ".join(str(l) for l in lines[:4])
            yield _site_finding(
                self.id, rel, lines[0],
                f"{cls}.{attr} is written from {len(entries)} thread "
                f"entrypoints ({_fmt_entries(entries)}) with no common "
                f"lock across its write sites (lines {shown})",
            )


def _write_groups(an: Analysis):
    """(rel, class, attr) -> [(WriteSite, FuncKey)] for attributes that
    are real shared data on concurrency-owning classes."""
    groups: dict = {}
    for key in sorted(an.summaries, key=lambda k: k.id):
        for w in an.summaries[key].writes:
            func_name = w.qual.rsplit(".", 1)[-1]
            if func_name in ("__init__", "__new__"):
                continue
            info = an.conc[key.rel].classes.get(w.cls)
            if info is None or not info.owns_concurrency():
                continue
            if w.attr in info.attr_locks:
                continue
            if info.attr_types.get(w.attr) in _JGL019_EXEMPT_TYPES:
                continue
            groups.setdefault((key.rel, w.cls, w.attr), []).append((w, key))
    return groups


def _non_exempt(held) -> set:
    return {l for l in held if not is_lane_lock(l)}


def _fmt_locks(locks) -> str:
    return "{" + ", ".join(sorted(locks)) + "}" if locks else "{}"


def _fmt_entries(entries) -> str:
    shown = sorted(entries)[:3]
    extra = len(entries) - len(shown)
    return ", ".join(shown) + (f", +{extra} more" if extra > 0 else "")


def _parse_site(site: str) -> tuple[str, int]:
    """Witness strings look like ``lockA -> lockB at rel:line``."""
    at = site.rsplit(" at ", 1)[-1]
    rel, _, line = at.partition(":")
    try:
        return rel, int(line.split()[0])
    except ValueError:
        return rel, 1

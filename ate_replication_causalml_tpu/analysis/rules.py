"""The graftlint rule set (JGL001–JGL014, JGL020–JGL021).

(JGL015–JGL019 are the whole-program concurrency rules in
``analysis/concurrency/rules.py``; JGL020 and JGL021 live here because
they are single-module AST rules like the rest of this file.)

Each rule targets a failure class that has actually bitten (or nearly
bitten) this codebase on TPU — see ADVICE.md and the rule docstrings.
Rules are registered on import; ``core.lint_source`` runs them all
unless a ``select`` list narrows the set.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, Iterator

from ate_replication_causalml_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    register,
)
from ate_replication_causalml_tpu.analysis import scopes
from ate_replication_causalml_tpu.analysis.jaxast import (
    MUTATOR_METHODS,
    FunctionRecord,
    call_form_jit_roots,
    collect_functions,
    mutable_globals,
    own_statements,
    traced_functions,
)

# ---------------------------------------------------------------- JGL001

#: Calls whose result depends on ambient process/backend state. Inside
#: a traced body they execute once, at trace time, and the jit cache is
#: NOT keyed on them — a later change of the ambient state silently
#: reuses the stale executable.
_AMBIENT_CALLS = {
    "jax.default_backend",
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
    "jax.local_device_count",
    "jax.process_index",
    "jax.process_count",
    "os.getenv",
}

_AMBIENT_READ_PREFIXES = ("os.environ", "jax.config.")


@register
class JitAmbientState(Rule):
    """ADVICE.md r5's ``quantile_bins`` bug, generalized: a jitted (or
    transitively traced) function branching on ``jax.default_backend()``
    / ``os.environ`` / a mutable module global bakes that value into the
    cached executable without it appearing in the cache key."""

    id = "JGL001"
    name = "jit-ambient-state"
    description = (
        "jit-traced function reads ambient state (backend, environ, "
        "mutable module global) that is not part of the jit cache key"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        records = collect_functions(module)
        traced = traced_functions(module, records)
        if not traced:
            return
        globals_ = mutable_globals(module)

        for qual, via in traced.items():
            rec = records[qual]
            where = (
                f"jitted function '{rec.name}'"
                if via is None
                else f"'{rec.name}' (traced via jit of '{via}')"
            )
            # Python scoping: a name assigned anywhere in the function
            # (or a parameter) is LOCAL throughout it — a Load of it
            # cannot read the like-named module global. `global` decls
            # re-expose the module binding.
            local_binds = set(rec.param_names())
            global_decls: set[str] = set()
            for n in own_statements(rec.node):
                if isinstance(n, ast.Global):
                    global_decls.update(n.names)
                elif isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)
                ):
                    local_binds.add(n.id)
            local_binds -= global_decls
            skip: set[int] = set()
            for node in own_statements(rec.node):
                if id(node) in skip:
                    continue
                if isinstance(node, ast.Call):
                    fr = module.resolve(node.func)
                    if fr in _AMBIENT_CALLS or (
                        fr and fr.startswith("os.environ.")
                    ):
                        skip.update(id(d) for d in ast.walk(node.func))
                        yield self.finding(
                            module,
                            node,
                            f"{where} calls {fr}() at trace time; the jit "
                            "cache is not keyed on it — hoist the branch "
                            "into an unjitted dispatcher or pass the value "
                            "as a static argument",
                        )
                elif isinstance(node, (ast.Attribute, ast.Name)):
                    if not isinstance(getattr(node, "ctx", None), ast.Load):
                        continue
                    fr = module.resolve(node)
                    if fr and (
                        fr == "os.environ"
                        or any(fr.startswith(p) for p in _AMBIENT_READ_PREFIXES)
                    ):
                        # Attribute chains resolve at every level; flag the
                        # outermost match once, not its sub-chains too.
                        skip.update(id(d) for d in ast.walk(node))
                        yield self.finding(
                            module,
                            node,
                            f"{where} reads ambient state '{fr}' at trace "
                            "time; the jit cache is not keyed on it",
                        )
                    elif (
                        isinstance(node, ast.Name)
                        and node.id in globals_
                        and node.id not in local_binds
                    ):
                        yield self.finding(
                            module,
                            node,
                            f"{where} reads mutable module global "
                            f"'{node.id}' at trace time; the jit cache is "
                            "not keyed on it",
                        )


# ---------------------------------------------------------------- JGL002

_KEY_PARAM_RE = re.compile(r"^(key|rng|prng\w*|\w*_key|\w*_rng)$")

#: replicate-axis key ARRAYS (the scenario matrix's vmapped key
#: batches) — tracked like scalar keys inside scenarios/: feeding the
#: whole axis to two jax.random calls draws the same stream per
#: replicate twice.
_KEY_ARRAY_PARAM_RE = re.compile(r"^(keys|\w*_keys)$")


def _in_scenarios_scope(relpath: str) -> bool:
    return scopes.SCENARIOS.contains(relpath)


def _branches_compatible(a: tuple, b: tuple) -> bool:
    """Whether two If-arm paths can co-execute: incompatible iff they
    take DIFFERENT arms of the same ``if`` statement."""
    arms = dict(a)
    return all(arms.get(if_id, arm) == arm for if_id, arm in b)

_KEY_ORIGINS = {
    "jax.random.key",
    "jax.random.PRNGKey",
    "jax.random.fold_in",
    "jax.random.split",
    "jax.random.wrap_key_data",
    "jax.random.clone",
}


def _is_split_call(module: ModuleInfo, node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and module.resolve(node.func) == "jax.random.split"
    )


@register
class PrngKeyReuse(Rule):
    """A PRNG key consumed by two ``jax.random`` calls yields correlated
    draws (identical, for the same distribution/shape) — the classic
    silent statistics bug. Also flags split results that are partially
    discarded (``_`` targets, never-read names, ``split(k)[1:]``): key
    material that vanishes usually means a consumer was dropped or a
    parent key is being double-spent elsewhere.

    Sanctioned idioms stay quiet: ``key, sub = split(key)`` (rebind in
    the consuming statement) and ``fold_in(key, i)`` (derivation — its
    contract is minting many keys from one live parent; only ``split``
    retires its input).

    Inside ``scenarios/`` (ISSUE 13), where the whole Monte-Carlo
    discipline is ``fold_in(root, cell_id)``, two extra checks arm:
    two ``fold_in`` call SITES with identical (key, data) operands mint
    the same derived key twice (the matrix's correlated-cells bug), and
    replicate-axis key ARRAYS (params named ``keys``/``*_keys``) are
    tracked like scalar keys — consuming the axis in two jax.random
    calls replays every replicate's stream."""

    id = "JGL002"
    name = "prng-key-reuse"
    description = (
        "PRNG key consumed by >=2 jax.random calls, consumed in a loop, "
        "split output partially discarded; in scenarios/: duplicate "
        "fold_in operands or replicate-axis key-array reuse"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for rec in collect_functions(module).values():
            yield from self._check_function(module, rec)

    def _check_function(
        self, module: ModuleInfo, rec: FunctionRecord
    ) -> Iterator[Finding]:
        fn = rec.node
        # All names read anywhere in the function (nested defs included:
        # closures legitimately consume enclosing keys).
        loads = {
            n.id
            for n in ast.walk(fn)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        }
        in_scenarios = _in_scenarios_scope(module.relpath)
        # name -> (bound_loop_depth, consumed_count, first_use_line)
        state: dict[str, list] = {
            p: [0, 0, 0]
            for p in rec.param_names()
            if _KEY_PARAM_RE.match(p)
            or (in_scenarios and _KEY_ARRAY_PARAM_RE.match(p))
        }
        # (key operand dump, data operand dump) -> [(site, branch path)]
        # — the scenarios/ duplicate-derivation check. Operand dumps are
        # TEXTUAL, so two guards keep the check sound: skip operands
        # naming anything reassigned in the function (`key =
        # fold_in(key, 7)` twice folds a DIFFERENT key each time — the
        # rethreading idiom this rule recommends), and never pair sites
        # from mutually exclusive If arms (only one executes).
        fold_sites: dict[tuple, list] = {}
        # Names with >= 2 binding sites: their value can differ
        # between two textually identical operand dumps, so they are
        # excluded from the duplicate-derivation check (a single
        # binding site yields one value per execution — a derived
        # key like `data_key = fold_in(root, cid)` stays checkable).
        # A parameter IS a binding site: `key = fold_in(key, 7)` then
        # folding `key` again folds the rebound value.
        assign_counts: dict[str, int] = {p: 1 for p in rec.param_names()}
        for n in ast.walk(fn):
            if isinstance(n, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
                targets = (n.target,)
            elif isinstance(n, ast.Assign):
                targets = tuple(n.targets)
            else:
                continue
            for t in targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name):
                        assign_counts[el.id] = assign_counts.get(el.id, 0) + 1
        multiply_assigned = {k for k, c in assign_counts.items() if c >= 2}
        findings: list[Finding] = []

        def bind(name: str, depth: int) -> None:
            state[name] = [depth, 0, 0]

        def unbind(name: str) -> None:
            state.pop(name, None)

        def consume(name: str, node: ast.AST, depth: int) -> None:
            st = state.get(name)
            if st is None:
                return
            st[1] += 1
            if st[1] == 1:
                st[2] = node.lineno
                if depth > st[0]:
                    findings.append(
                        self.finding(
                            module,
                            node,
                            f"PRNG key '{name}' is consumed inside a loop "
                            "but bound outside it — every iteration reuses "
                            "the same key; split or fold_in per iteration",
                        )
                    )
            elif st[1] == 2:
                findings.append(
                    self.finding(
                        module,
                        node,
                        f"PRNG key '{name}' is consumed by a second "
                        f"jax.random call (first use at line {st[2]}) — "
                        "split it and give each consumer its own key",
                    )
                )

        def handle_assign(node: ast.Assign | ast.AnnAssign, depth: int) -> None:
            value = node.value
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            origin = (
                isinstance(value, ast.Call)
                and module.resolve(value.func) in _KEY_ORIGINS
            )
            sub_of_split = isinstance(value, ast.Subscript) and _is_split_call(
                module, value.value
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    if origin or sub_of_split:
                        bind(t.id, depth)
                    else:
                        unbind(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)) and origin:
                    split = module.resolve(value.func) == "jax.random.split"
                    for el in t.elts:
                        if not isinstance(el, ast.Name):
                            continue
                        if split and el.id == "_":
                            findings.append(
                                self.finding(
                                    module,
                                    el,
                                    "split output bound to '_' discards key "
                                    "material — size the split to the "
                                    "consumers",
                                )
                            )
                        elif split and el.id not in loads:
                            findings.append(
                                self.finding(
                                    module,
                                    el,
                                    f"split output '{el.id}' is never used — "
                                    "dead key material usually means a "
                                    "dropped consumer",
                                )
                            )
                        else:
                            bind(el.id, depth)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            unbind(el.id)

        def scan_expr(
            node: ast.AST, depth: int, rebound: set[str] = frozenset(),
            branch: tuple = (),
        ) -> None:
            if isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                # Comprehensions ARE loops: a key consumed in the body
                # while bound outside is the same n-identical-draws bug
                # as the `for` form.
                for gen in node.generators:
                    scan_expr(gen.iter, depth, rebound, branch)
                    for cond in gen.ifs:
                        scan_expr(cond, depth + 1, rebound, branch)
                parts = (
                    (node.key, node.value)
                    if isinstance(node, ast.DictComp)
                    else (node.elt,)
                )
                for part in parts:
                    scan_expr(part, depth + 1, rebound, branch)
                return
            if (
                isinstance(node, ast.Subscript)
                and _is_split_call(module, node.value)
                and isinstance(node.slice, ast.Slice)
            ):
                # Anywhere a split output is sliced — assignment,
                # return, call argument — sibling keys vanish.
                findings.append(
                    self.finding(
                        module,
                        node,
                        "slice of jax.random.split output discards sibling "
                        "keys — size the split to the consumers",
                    )
                )
            if isinstance(node, ast.Call):
                fr = module.resolve(node.func)
                if (
                    in_scenarios
                    and fr == "jax.random.fold_in"
                    and len(node.args) >= 2
                ):
                    # Duplicate derivation: two distinct call SITES
                    # folding the same (key, data) pair mint the SAME
                    # key twice — in the cell-id discipline that means
                    # two consumers silently share a stream. One site
                    # reached many times (a loop over cell ids) is the
                    # sanctioned idiom and has one signature per
                    # distinct data expression. Excluded: operands
                    # naming a multiply-assigned variable (textual
                    # equality no longer means value equality), and
                    # site pairs in mutually exclusive If arms.
                    operand_names = {
                        el.id
                        for arg in node.args[:2]
                        for el in ast.walk(arg)
                        if isinstance(el, ast.Name)
                    }
                    if not (operand_names & multiply_assigned):
                        sig = (ast.dump(node.args[0]),
                               ast.dump(node.args[1]))
                        site = (node.lineno, node.col_offset)
                        entries = fold_sites.setdefault(sig, [])
                        if all(s != site for s, _ in entries):
                            clash = next(
                                (s for s, b in entries
                                 if _branches_compatible(b, branch)),
                                None,
                            )
                            if clash is not None:
                                findings.append(
                                    self.finding(
                                        module,
                                        node,
                                        "fold_in duplicates the derivation "
                                        f"at line {clash[0]} — identical "
                                        "(key, data) operands mint the same "
                                        "key twice; give each consumer its "
                                        "own fold constant",
                                    )
                                )
                            entries.append((site, branch))
                # fold_in is derivation, not consumption: it exists to
                # mint many independent keys from one live parent
                # (per-iteration fold_in is what this rule's own
                # message recommends). split, by contrast, retires
                # its input.
                if (
                    fr
                    and fr.startswith("jax.random.")
                    and fr != "jax.random.fold_in"
                ):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Name) and arg.id not in rebound:
                            consume(arg.id, node, depth)
            for child in ast.iter_child_nodes(node):
                scan_expr(child, depth, rebound, branch)

        def rebound_targets(node: ast.Assign | ast.AnnAssign) -> set[str]:
            """Target names of a key-origin assignment whose value also
            consumes them: ``key, sub = split(key)`` / ``key =
            fold_in(key, i)`` is the canonical per-iteration rethreading
            this rule RECOMMENDS — the self-consume is a rebind, not a
            spend."""
            value = node.value
            is_origin = (
                isinstance(value, ast.Call)
                and module.resolve(value.func) in _KEY_ORIGINS
            ) or (
                isinstance(value, ast.Subscript)
                and _is_split_call(module, value.value)
            )
            if not is_origin:
                return set()
            out: set[str] = set()
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                out |= {el.id for el in elts if isinstance(el, ast.Name)}
            return out

        def walk(body: Iterable[ast.stmt], depth: int,
                 branch: tuple = ()) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # separate scope, checked on its own
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    if stmt.value is not None:
                        scan_expr(stmt.value, depth, rebound_targets(stmt),
                                  branch)
                    handle_assign(stmt, depth)
                    continue
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    scan_expr(stmt.iter, depth, branch=branch)
                    # Any tracked name in the (possibly tuple) loop
                    # target is rebound per iteration — `for i, key in
                    # enumerate(split(key, n))` is hygienic.
                    for el in ast.walk(stmt.target):
                        if isinstance(el, ast.Name) and el.id in state:
                            bind(el.id, depth + 1)
                    walk(stmt.body, depth + 1, branch)
                    walk(stmt.orelse, depth, branch)
                    continue
                if isinstance(stmt, ast.While):
                    scan_expr(stmt.test, depth, branch=branch)
                    walk(stmt.body, depth + 1, branch)
                    walk(stmt.orelse, depth, branch)
                    continue
                if isinstance(stmt, (ast.If,)):
                    scan_expr(stmt.test, depth, branch=branch)
                    # The two arms are mutually exclusive: a duplicate
                    # fold_in pair split across them never co-executes.
                    walk(stmt.body, depth, branch + ((id(stmt), 0),))
                    walk(stmt.orelse, depth, branch + ((id(stmt), 1),))
                    continue
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        scan_expr(item.context_expr, depth, branch=branch)
                    walk(stmt.body, depth, branch)
                    continue
                if isinstance(stmt, ast.Try):
                    walk(stmt.body, depth, branch)
                    for h in stmt.handlers:
                        walk(h.body, depth, branch)
                    walk(stmt.orelse, depth, branch)
                    walk(stmt.finalbody, depth, branch)
                    continue
                scan_expr(stmt, depth, branch=branch)

        walk(fn.body, 0)
        yield from findings


# ---------------------------------------------------------------- JGL003

_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "weak_type", "sharding",
    "aval", "nbytes",
}

_TRACE_SAFE_CALLS = {"isinstance", "len", "getattr", "hasattr", "type", "callable"}


@register
class TracedPythonBranch(Rule):
    """``if``/``while`` on a traced value inside a jitted body raises
    ``TracerBoolConversionError`` at best — and at worst (when the value
    happens to be concrete on one path, e.g. under ``disable_jit`` or a
    constant-folded input) silently freezes one branch into the cached
    executable. Use ``lax.cond``/``lax.while_loop``/``jnp.where``."""

    id = "JGL003"
    name = "traced-python-branch"
    description = (
        "Python if/while tests a traced value inside a jitted function "
        "(use lax.cond / jnp.where)"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        records = collect_functions(module)
        call_roots = call_form_jit_roots(module, records)
        for qual, rec in records.items():
            if rec.jitted:
                traced = rec.traced_params()
            elif qual in call_roots:
                # Call-form jit (`run = jax.jit(body, ...)`): the
                # wrapping call carries the statics.
                names, nums = call_roots[qual]
                params = rec.param_names()
                statics = names | {params[i] for i in nums if i < len(params)}
                traced = set(params) - statics - {"self", "cls"}
            else:
                continue
            if not traced:
                continue
            yield from self._scan(module, rec, rec.node.body, traced)

    def _offending_name(
        self, test: ast.expr, traced: set[str]
    ) -> ast.Name | None:
        skip: set[int] = set()
        for node in ast.walk(test):
            if id(node) in skip:
                skip.update(id(c) for c in ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
                # x.shape / x.ndim / x.dtype … are trace-time static.
                skip.update(id(c) for c in ast.iter_child_nodes(node))
            elif isinstance(node, ast.Call):
                fr = isinstance(node.func, ast.Name) and node.func.id
                if fr in _TRACE_SAFE_CALLS:
                    skip.update(id(c) for c in ast.iter_child_nodes(node))
            elif isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                # `x is None` is decided at trace time (tracer vs None).
                skip.update(id(c) for c in ast.iter_child_nodes(node))
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in traced:
                    return node
        return None

    def _scan(
        self,
        module: ModuleInfo,
        rec: FunctionRecord,
        body: Iterable[ast.stmt],
        traced: set[str],
    ) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: its params shadow the jitted fn's tracers.
                inner = traced - {
                    a.arg
                    for a in (
                        stmt.args.posonlyargs + stmt.args.args + stmt.args.kwonlyargs
                    )
                }
                if inner:
                    yield from self._scan(module, rec, stmt.body, inner)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                bad = self._offending_name(stmt.test, traced)
                if bad is not None:
                    kind = "if" if isinstance(stmt, ast.If) else "while"
                    yield self.finding(
                        module,
                        stmt,
                        f"Python `{kind}` on traced value '{bad.id}' inside "
                        f"jitted '{rec.name}' — use lax.cond/lax.while_loop/"
                        "jnp.where, or mark the argument static",
                    )
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if sub:
                    yield from self._scan(module, rec, sub, traced)
            for h in getattr(stmt, "handlers", ()):
                yield from self._scan(module, rec, h.body, traced)


# ---------------------------------------------------------------- JGL004

_F64_NAMES = {"numpy.float64", "numpy.double", "jax.numpy.float64"}
_F64_STRINGS = {"float64", "double", "f8", ">f8", "<f8"}
_JNP_PREFIXES = ("jax.numpy.", "jax.lax.")


def _in_dtype_scope(relpath: str) -> bool:
    return scopes.DTYPE.contains(relpath)


@register
class DtypeDrift(Rule):
    """The numerics contract (BASELINE.json parity to 1e-4) is defined
    under the session dtype policy; a literal ``np.float64`` (or an
    un-dtyped Python ``float()`` fed straight into a jnp op) inside
    ``ops/``/``estimators/`` silently promotes — or silently truncates
    on TPU where f64 is emulated. Intentional f64 islands (the QP
    solver) carry explicit suppressions."""

    id = "JGL004"
    name = "dtype-drift"
    description = (
        "literal float64 dtype or bare float() feeding a jnp op in "
        "ops/ or estimators/ drifts against the x64 policy"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not _in_dtype_scope(module.relpath):
            return
        flagged: set[int] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.Attribute, ast.Name)):
                fr = module.resolve(node)
                if fr in _F64_NAMES and id(node) not in flagged:
                    yield self.finding(
                        module,
                        node,
                        f"literal {fr.rsplit('.', 1)[-1]} dtype pins f64 "
                        "regardless of the x64 policy — derive the dtype "
                        "from the operand or the policy instead",
                    )
                    if isinstance(node, ast.Attribute):
                        flagged.update(id(c) for c in ast.walk(node))
            elif isinstance(node, ast.keyword) and node.arg == "dtype":
                v = node.value
                if isinstance(v, ast.Constant) and v.value in _F64_STRINGS:
                    yield self.finding(
                        module,
                        v,
                        f"dtype={v.value!r} pins f64 regardless of the x64 "
                        "policy — derive the dtype from the operand or the "
                        "policy instead",
                    )
            elif isinstance(node, ast.Call):
                fr = module.resolve(node.func)
                if not (fr and fr.startswith(_JNP_PREFIXES)):
                    continue
                if any(kw.arg == "dtype" for kw in node.keywords):
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if (
                        isinstance(arg, ast.Call)
                        and isinstance(arg.func, ast.Name)
                        and arg.func.id == "float"
                        and arg.args
                    ):
                        yield self.finding(
                            module,
                            arg,
                            f"bare float(...) fed to {fr} without an "
                            "explicit dtype — the weak f64 scalar promotes "
                            "under x64 and truncates elsewhere; pass dtype= "
                            "or cast with the policy dtype",
                        )


# ---------------------------------------------------------------- JGL005


@register
class NonAtomicWrite(Rule):
    """A kill mid-write leaves a truncated artifact beside valid ones —
    the failure mode PR 1 closed by routing every persisted artifact
    through ``observability.export.atomic_write_text`` (tmp file +
    fsync + ``os.replace``). Everything outside that module must use the
    blessed helpers, not ``open(..., 'w')``/``json.dump``."""

    id = "JGL005"
    name = "non-atomic-write"
    description = (
        "open(..., 'w')/json.dump outside observability/export.py — use "
        "atomic_write_text/atomic_write_json"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if scopes.EXPORT_MODULE.contains(module.relpath):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fr = module.resolve(node.func)
            if fr == "json.dump":
                yield self.finding(
                    module,
                    node,
                    "json.dump writes through a live handle — use "
                    "observability.export.atomic_write_json",
                )
                continue
            if fr not in ("open", "os.fdopen", "io.open"):
                continue
            mode = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and ("w" in mode or "x" in mode):
                yield self.finding(
                    module,
                    node,
                    f"non-atomic {fr}(..., {mode!r}) — a kill mid-write "
                    "leaves a truncated file; use observability.export."
                    "atomic_write_text/atomic_write_json (append-mode "
                    "journals are exempt by design)",
                )


# ---------------------------------------------------------------- JGL006

_LOCK_ATTR_NAMES = {"_lock", "lock"}
_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
}
_EXEMPT_FACTORIES = {"threading.local", "itertools.count"}
_CONTAINER_FACTORIES = {
    "dict", "list", "set", "collections.deque", "collections.defaultdict",
    "collections.OrderedDict",
}


def _self_attr(node: ast.expr, self_name: str) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == self_name
    ):
        return node.attr
    return None


@register
class UnlockedSharedState(Rule):
    """The observability registry/event log are mutated from the sweep
    driver, the shard-retry loop, and compile-cache listener threads at
    once; every mutation of a lock-guarded container must hold the
    instance lock or snapshots can tear (and dict/deque invariants can
    corrupt under free-threading)."""

    id = "JGL006"
    name = "unlocked-shared-state"
    description = (
        "observability/ class mutates lock-guarded shared state outside "
        "`with self._lock`"
    )
    #: what the finding message calls the guarded state (subclasses
    #: rescope the rule — JGL008 covers the sweep scheduler/checkpoint).
    _context = "registry/event-log shared state"

    def _in_scope(self, relpath: str) -> bool:
        # observability/slo.py belongs to the SERVING plane's shared-
        # state rule (JGL008) — one rule per file, or every finding
        # there would be reported twice.
        return scopes.OBSERVABILITY_STATE.contains(relpath)

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not self._in_scope(module.relpath):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(module, node)

    def _check_class(
        self, module: ModuleInfo, cls: ast.ClassDef
    ) -> Iterator[Finding]:
        init = next(
            (
                n
                for n in cls.body
                if isinstance(n, ast.FunctionDef) and n.name == "__init__"
            ),
            None,
        )
        if init is None or not init.args.args:
            return
        self_name = init.args.args[0].arg
        locks: set[str] = set()
        shared: set[str] = set()
        for stmt in ast.walk(init):
            # Annotated assignments (`self.done: dict = {}`) declare
            # shared containers just as often as plain ones do.
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets = [stmt.target]
            else:
                continue
            value = stmt.value
            for t in targets:
                attr = _self_attr(t, self_name)
                if attr is None:
                    continue
                resolved = (
                    module.resolve(value.func)
                    if isinstance(value, ast.Call)
                    else None
                )
                if attr in _LOCK_ATTR_NAMES or resolved in _LOCK_FACTORIES:
                    locks.add(attr)
                elif resolved in _EXEMPT_FACTORIES:
                    continue
                elif isinstance(
                    value, (ast.Dict, ast.List, ast.Set)
                ) or resolved in _CONTAINER_FACTORIES:
                    shared.add(attr)
                elif isinstance(value, ast.Constant) and isinstance(
                    value.value, (int, float)
                ):
                    # Mutable scalars (counters): plain rebinding is
                    # atomic-enough, but += is a read-modify-write race.
                    shared.add(attr)
        if not locks or not shared:
            return

        for method in cls.body:
            if not isinstance(method, ast.FunctionDef) or method.name == "__init__":
                continue
            if not method.args.args:
                continue
            m_self = method.args.args[0].arg
            yield from self._scan(
                module, cls, method.body, m_self, locks, shared, locked=False
            )

    def _scan(
        self,
        module: ModuleInfo,
        cls: ast.ClassDef,
        body: Iterable[ast.stmt],
        self_name: str,
        locks: set[str],
        shared: set[str],
        locked: bool,
    ) -> Iterator[Finding]:
        def flag(node: ast.AST, attr: str) -> Finding:
            return self.finding(
                module,
                node,
                f"{cls.name}.{attr} is mutated outside `with self."
                f"{sorted(locks)[0]}` — {self._context} "
                "must be mutated under the instance lock",
            )

        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            now_locked = locked
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    attr = _self_attr(item.context_expr, self_name)
                    if attr in locks:
                        now_locked = True
            if not now_locked:
                mutations = self._mutations_in(stmt, self_name, shared)
                for node, attr in mutations:
                    yield flag(node, attr)
            for attr_name in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr_name, None)
                if sub:
                    yield from self._scan(
                        module, cls, sub, self_name, locks, shared, now_locked
                    )
            for h in getattr(stmt, "handlers", ()):
                yield from self._scan(
                    module, cls, h.body, self_name, locks, shared, now_locked
                )

    def _mutations_in(
        self, stmt: ast.stmt, self_name: str, shared: set[str]
    ) -> list[tuple[ast.AST, str]]:
        out: list[tuple[ast.AST, str]] = []
        # Only this statement's own expression layer — child statements
        # are visited by _scan with their own locked context. Compound
        # statements contribute their header expressions.
        if not hasattr(stmt, "body"):
            nodes: list[ast.AST | None] = [stmt]
        elif isinstance(stmt, (ast.If, ast.While)):
            nodes = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            nodes = [stmt.iter, stmt.target]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            nodes = [i.context_expr for i in stmt.items]
        else:
            nodes = []
        for root in nodes:
            if root is None:
                continue
            for node in ast.walk(root):
                if isinstance(
                    node, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Delete)
                ):
                    if isinstance(node, ast.AnnAssign) and node.value is None:
                        continue  # bare annotation: no mutation
                    targets = (
                        node.targets
                        if isinstance(node, (ast.Assign, ast.Delete))
                        else [node.target]
                    )
                    for t in targets:
                        attr = _self_attr(t, self_name)
                        if attr in shared:
                            out.append((node, attr))
                        elif isinstance(t, ast.Subscript):
                            attr = _self_attr(t.value, self_name)
                            if attr in shared:
                                out.append((node, attr))
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    if node.func.attr in MUTATOR_METHODS:
                        attr = _self_attr(node.func.value, self_name)
                        if attr in shared:
                            out.append((node, attr))
        return out


# ---------------------------------------------------------------- JGL008


@register
class UnlockedSchedulerState(UnlockedSharedState):
    """ISSUE 4's failure class: the sweep scheduler's ready queue /
    outcome buffer / nuisance-cache entries and the checkpoint
    journal's in-memory row map are mutated from a worker pool; any
    mutation outside the sanctioned instance lock can tear the ordered
    commit sequence or interleave journal appends. Same engine as
    JGL006, rescoped to ``scheduler/``, ``serving/`` and the pipeline
    drivers (the ``_Checkpoint`` class lives in ``pipeline.py``).
    ``serving/`` joined with ISSUE 6: the daemon is the most
    thread-shared code in the tree — per-connection reader threads, the
    coalescer's dispatcher, and the degraded-mode reload thread all
    touch the same model/executable/queue state. ISSUE 7 added the
    observability plane: ``observability/slo.py`` (the SLO engine's
    snapshot history is ticked from the dispatcher and read from admin
    probe threads) and the ``serving/admin.py`` endpoint — both serve
    concurrent readers over state the daemon mutates. ISSUE 11's fleet
    layer (``serving/fleet.py``, ``serving/retrain.py``) is squarely in
    scope: the model registry is swapped by rotation threads while the
    dispatcher binds it, and the shedder's burn cache is written from
    the dispatcher and read from every producer."""

    id = "JGL008"
    name = "unlocked-scheduler-state"
    description = (
        "scheduler/, serving/, observability/slo.py or pipeline "
        "checkpoint class mutates lock-guarded shared state outside "
        "the sanctioned instance lock"
    )
    _context = "scheduler/serving/checkpoint shared state"

    def _in_scope(self, relpath: str) -> bool:
        # Only the top-level driver (<pkg>/pipeline.py) hosts
        # _Checkpoint — scopes.SCHEDULER_STATE's top_files matching
        # keeps data/pipeline.py and any nested pipeline.py out (the
        # PR 4 endswith bug this module used to carry).
        return scopes.SCHEDULER_STATE.contains(relpath)


# ---------------------------------------------------------------- JGL007

_BROAD_EXC = {"Exception", "BaseException"}


def _in_resilience_scope(relpath: str) -> bool:
    # Paths allowed to make blanket exception decisions: the resilience
    # layer's whole job is classified handling, and the shard runner's
    # probe/retry loops are the sanctioned swallow sites.
    return scopes.RESILIENCE_EXEMPT.contains(relpath)


@register
class SilentExceptionSwallow(Rule):
    """ISSUE 3's failure class: a bare ``except Exception: pass`` (or a
    ``retriable=(Exception,)`` shard-retry tuple) swallows programming
    errors — the ``TypeError`` that should have killed the run on
    attempt 1 instead burns the retry budget and surfaces, if at all,
    as a mysterious "shard failure". Error-class decisions belong to
    ``resilience.errors.classify``; everywhere else must either narrow
    the type, record the failure, or carry an explicit suppression."""

    id = "JGL007"
    name = "silent-exception-swallow"
    description = (
        "bare `except Exception: pass` or overly-broad retriable= tuple "
        "outside resilience/ and parallel/retry.py"
    )

    def _is_broad(self, module: ModuleInfo, type_node: ast.expr | None) -> bool:
        if type_node is None:  # bare `except:` — broader than broad
            return True
        nodes = (
            type_node.elts
            if isinstance(type_node, ast.Tuple)
            else [type_node]
        )
        return any(module.resolve(n) in _BROAD_EXC for n in nodes)

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if _in_resilience_scope(module.relpath):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler):
                silent = all(
                    isinstance(s, (ast.Pass, ast.Continue)) for s in node.body
                )
                if silent and self._is_broad(module, node.type):
                    label = (
                        "bare `except:`" if node.type is None
                        else "`except Exception`"
                    )
                    yield self.finding(
                        module,
                        node,
                        f"{label} with a pass/continue body swallows "
                        "programming errors silently — narrow the type, "
                        "record the failure, or classify via "
                        "resilience.errors",
                    )
            elif isinstance(node, ast.keyword) and node.arg == "retriable":
                broad = next(
                    (
                        n
                        for n in ast.walk(node.value)
                        if isinstance(n, (ast.Name, ast.Attribute))
                        and module.resolve(n) in _BROAD_EXC
                    ),
                    None,
                )
                if broad is not None:
                    yield self.finding(
                        module,
                        broad,
                        "retriable tuple includes Exception/BaseException — "
                        "this retries programming errors; use the "
                        "classified default (retriable=None) or list the "
                        "transient types",
                    )


# ---------------------------------------------------------------- JGL009

_WALLCLOCK_CALL = "time.time"


@register
class WallClockDuration(Rule):
    """ISSUE 5's timeline contract: every duration in the trace /
    overlap analysis comes from the monotonic clock, because
    ``time.time()`` can step (NTP slew, manual clock set) and a stepped
    difference silently corrupts span durations, backoff budgets and
    bench numbers. Outside ``observability/`` (which records both
    clocks on purpose, keeping the wall-clock anchor in ONE place),
    any ``time.time()`` difference must be ``time.monotonic()`` /
    ``time.perf_counter()`` instead."""

    id = "JGL009"
    name = "wallclock-duration"
    description = (
        "time.time() used in duration arithmetic outside observability/ "
        "— use time.monotonic()/time.perf_counter()"
    )

    def _is_walltime_call(self, module: ModuleInfo, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and module.resolve(node.func) == _WALLCLOCK_CALL
        )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        # The telemetry layer records BOTH clocks deliberately (span
        # records carry ``start_unix`` next to ``start_mono_s``).
        if scopes.WALLCLOCK_EXEMPT.contains(module.relpath):
            return
        # Names bound from time.time() anywhere in the module
        # (name-based, not scope-exact — the linter's stated precision).
        tainted: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and getattr(
                node, "value", None
            ) is not None and self._is_walltime_call(module, node.value):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)

        def is_wall(operand: ast.expr) -> bool:
            if self._is_walltime_call(module, operand):
                return True
            return isinstance(operand, ast.Name) and operand.id in tainted

        for node in ast.walk(module.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                if is_wall(node.left) or is_wall(node.right):
                    yield self.finding(
                        module,
                        node,
                        "duration computed from time.time() — the wall "
                        "clock can step (NTP), silently corrupting the "
                        "difference; use time.monotonic()/"
                        "time.perf_counter() (observability/ owns the "
                        "wall-clock anchor)",
                    )


# ---------------------------------------------------------------- JGL010

#: the host-materialization calls the artifact plane owns: a bare
#: ``np.asarray`` on a jax array is a device_get (a per-shard fetch and
#: host assemble), and both escape the transfer metering.
_HOST_MATERIALIZE_CALLS = {"numpy.asarray", "jax.device_get"}


@register
class UnmeteredHostMaterialization(Rule):
    """ISSUE 8's boundary contract: every byte a nuisance artifact
    moves between host and device goes through ``parallel/shardio.py``,
    which meters it into ``artifact_transfer_bytes_total`` and applies
    the mesh-lane discipline to the collective paths. A bare
    ``np.asarray``/``jax.device_get`` in the scheduler or the sweep
    driver is exactly the PR-4 ``materialized()`` host bounce this PR
    removed — unmetered host bandwidth, invisible to the mesh-scaling
    byte accounting, and (for sharded inputs) a device sync outside the
    sanctioned gather path."""

    id = "JGL010"
    name = "unmetered-host-materialization"
    description = (
        "np.asarray/jax.device_get in scheduler/ or pipeline.py outside "
        "the metered parallel/shardio.py artifact plane"
    )

    def _in_scope(self, relpath: str) -> bool:
        # Same scope shape as JGL008: the scheduler package plus the
        # top-level driver only — data/pipeline.py and any nested
        # pipeline.py do host I/O legitimately.
        return scopes.HOST_TRANSFER.contains(relpath)

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not self._in_scope(module.relpath):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = module.resolve(node.func)
            if name in _HOST_MATERIALIZE_CALLS:
                yield self.finding(
                    module,
                    node,
                    f"{name} host-materializes outside the metered "
                    "artifact plane — route the transfer through "
                    "parallel/shardio.py (gather_host/commit) so the "
                    "bytes are counted and the mesh-lane discipline "
                    "holds",
                )


# ---------------------------------------------------------------- JGL011

#: Function names that ARE the predict path in models/: routing, leaf
#: indexing and CATE scoring. Grow-time code is out of scope — its
#: gathers are the growers' business (and its hot loops were converted
#: separately).
_PREDICT_FN_RE = re.compile(
    r"(predict|route|leaf_index|forest_apply|apply_trees|per_tree)"
)

#: Subscript index names that look like a per-row id vector. Matching
#: is deliberately narrow (exact id-ish tokens), so loop counters
#: (``level``, ``i``) and static shape math never false-positive.
_ROW_ID_NAME_RE = re.compile(
    r"^(node|nodes|ids|idx|node_of_row|leaf_index|li|train_leaf)$"
    r"|(^|_)(node|leaf)_(ids?|idx)(_|$)"
    r"|_ids?$|_idx$"
)

_TAKE_CALLS = {"jax.numpy.take", "numpy.take", "jax.lax.gather"}


@register
class PredictPathRowGather(Rule):
    """ISSUE 12's predict-path contract: per-row dynamic gathers
    (``jnp.take`` / ``codes[node_ids]``) serialize on TPU — measured at
    ~2/3 of forest wall-clock before the routing loops were converted
    (models/causal_forest.py::_tree_route docstring) — and they bypass
    the sanctioned formulations: the exact one-hot matmuls, the PACKED
    contractions (``ops/pack.py`` + ``route_rows_packed``), and the
    Pallas row kernels (``ops/tree_pallas.py``). A gather creeping back
    into a predict-path function is a silent 10×-class regression the
    bit-identity tests cannot catch (the VALUES are right), so the lint
    catches the form."""

    id = "JGL011"
    name = "predict-row-gather"
    description = (
        "jnp.take/[...] per-row dynamic gather in a models/ predict-path "
        "function — use the one-hot/packed contractions or the Pallas "
        "row kernels"
    )

    def _in_scope(self, relpath: str) -> bool:
        return scopes.MODELS.contains(relpath)

    def _is_row_id_index(self, idx: ast.expr) -> bool:
        """A bare row-id Name, or a tuple index carrying one (slices,
        constants and arithmetic are static selection — fine)."""
        if isinstance(idx, ast.Name):
            return bool(_ROW_ID_NAME_RE.search(idx.id))
        if isinstance(idx, ast.Tuple):
            return any(self._is_row_id_index(e) for e in idx.elts)
        return False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not self._in_scope(module.relpath):
            return
        seen: set[int] = set()
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _PREDICT_FN_RE.search(fn.name):
                continue
            for node in ast.walk(fn):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                if isinstance(node, ast.Call):
                    name = module.resolve(node.func)
                    if name in _TAKE_CALLS:
                        yield self.finding(
                            module,
                            node,
                            f"{name} is a per-row dynamic gather in "
                            f"predict-path function {fn.name!r} — "
                            "serializes on TPU; use the one-hot/packed "
                            "contraction or the Pallas row kernels "
                            "(ops/tree_pallas.py::table_lookup)",
                        )
                elif isinstance(node, ast.Subscript) and self._is_row_id_index(
                    node.slice
                ):
                    yield self.finding(
                        module,
                        node,
                        f"[...] indexing by a row-id vector in "
                        f"predict-path function {fn.name!r} is a per-row "
                        "dynamic gather — serializes on TPU; use the "
                        "one-hot/packed contraction or the Pallas row "
                        "kernels",
                    )


# ---------------------------------------------------------------- JGL012

#: Method names whose zero-argument call form blocks FOREVER on the
#: stdlib's synchronization/queue/thread types. `.get()` is included
#: because `queue.Queue().get()` is the classic unbounded consumer;
#: `dict.get()` always takes arguments, so the zero-arg restriction
#: keeps it out of scope.
_BLOCKING_ATTRS = ("acquire", "wait", "join", "get")


@register
class UnboundedBlockingCall(Rule):
    """ISSUE 14's liveness contract: the watchdog can only see a lane
    that keeps stamping heartbeats, and a lane blocked forever in
    ``Lock.acquire()`` / ``Condition.wait()`` / ``Queue.get()`` /
    ``Thread.join()`` *between* its stamped sites is exactly the silent
    wedge the watchdog exists to kill — PR 4's collective-rendezvous
    deadlock sat behind one of these. Every blocking call in the
    long-lived lanes (``serving/``, ``scheduler/``, and the watchdog
    itself) must carry a timeout so the enclosing loop re-checks state
    and re-stamps its heartbeat.

    Precision is deliberate and syntactic (the ISSUE's wording: "no
    timeout argument"): only ZERO-argument calls of the four names are
    flagged — ``cond.wait(w)`` passes even if ``w`` can be None, and
    ``lock.acquire(True)`` passes; the rule catches the idiomatic
    unbounded form, not every reachable one."""

    id = "JGL012"
    name = "unbounded-blocking-call"
    description = (
        "zero-argument Lock.acquire()/Condition.wait()/Queue.get()/"
        "Thread.join() in serving/, scheduler/ or resilience/watchdog.py "
        "— blocks forever outside the watchdog's stamped sites; pass a "
        "timeout and loop"
    )

    def _in_scope(self, relpath: str) -> bool:
        return scopes.UNBOUNDED_JOIN.contains(relpath)

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not self._in_scope(module.relpath):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in _BLOCKING_ATTRS:
                continue
            if node.args or node.keywords:
                # Any argument form passes: a timeout bounds the block,
                # and blocking=False / block=False forms never block at
                # all — the rule targets the idiomatic ZERO-argument
                # wait-forever call only (per its docstring).
                continue
            yield self.finding(
                module,
                node,
                f".{func.attr}() with no timeout blocks forever — the "
                "heartbeat watchdog cannot see a lane wedged here; pass "
                "a timeout and re-check in a loop "
                "(resilience/watchdog.py is the liveness contract)",
            )


# ---------------------------------------------------------------- JGL013

#: chaos-injection entry points and WHICH of their arguments is a site
#: id: {method attr: ((positional index, keyword name), ...)}. The
#: indexes are post-self (call-site view). ``attempt`` counters passed
#: as non-site args (shard_should_fail's third parameter) are the
#: CONSUMER of per-attempt state, not a site — only the listed args
#: must be stable.
_CHAOS_SITE_ARGS: dict[str, tuple[tuple[int, str], ...]] = {
    "shard_should_fail": ((0, "pool"), (1, "shard")),
    "take_serve_fault": ((0, "request_id"),),
    "take_stage_fault": ((0, "method"),),
    "maybe_fail_stage": ((0, "method"),),
    "hang_delay_s": ((1, "site"),),
    "take_rotate_fault": ((1, "site"),),
    "record_daemon_kill": ((0, "name"),),
    "rotate_verify_delay_s": ((0, "site"),),
    "torn_line": ((1, "site"),),
    "truncate_npz": ((1, "site"),),
    "tamper_line": ((1, "site"),),
}

#: calls whose value differs every invocation — a site id derived from
#: one can never reproduce, so planned == observed breaks silently.
_UNSTABLE_SITE_CALLS = {
    "id",
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "uuid.uuid4", "uuid.uuid1",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

#: names that smell like per-attempt / per-call counters. Deliberately
#: narrow — ``request_id`` / ``node_id`` style SITE names must never
#: match.
_ATTEMPTISH_NAME_RE = re.compile(
    r"^(attempt|attempts|attempt_no|n_attempts|counter|counters|"
    r"tries|n_tries|retry_count|retries|seq|seqno|seq_no|nonce)$"
    r"|_(attempt|attempts|counter|seqno|nonce)$"
)


@register
class UnstableChaosSite(Rule):
    """ISSUE 15's composability contract (the PR 14 gotcha as code, not
    prose): chaos selection is the pure hash ``(seed, scope, site)``,
    so *planned == observed* — the property every chaos test and the
    whole campaign engine (``resilience/campaign.py``) asserts — holds
    ONLY while site ids are stable across runs and retries. A site id
    derived from the wall clock, an object identity (``id(batch)``), or
    a per-attempt counter gives every invocation a fresh hash: the
    ``times`` budget never converges, a retrying client never gets
    served, and the campaign's fault accounting silently diverges from
    the plan. The injector methods' site arguments must be
    client-stable names (request ids, node names, model ids, paths)."""

    id = "JGL013"
    name = "unstable-chaos-site"
    description = (
        "chaos-injection site id derived from wall clock, object id or "
        "a per-attempt counter — selection hashes the site, so "
        "planned == observed breaks"
    )

    def _site_args(self, node: ast.Call,
                   spec: tuple[tuple[int, str], ...]) -> list[ast.expr]:
        out = []
        for pos, kw in spec:
            if len(node.args) > pos:
                out.append(node.args[pos])
            for k in node.keywords:
                if k.arg == kw:
                    out.append(k.value)
        return out

    def _unstable_part(self, module: ModuleInfo,
                       expr: ast.expr) -> str | None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                name = module.resolve(sub.func)
                if name in _UNSTABLE_SITE_CALLS:
                    return f"{name}()"
            elif isinstance(sub, ast.Name):
                if _ATTEMPTISH_NAME_RE.search(sub.id):
                    return sub.id
            elif isinstance(sub, ast.Attribute):
                if _ATTEMPTISH_NAME_RE.search(sub.attr):
                    return sub.attr
        return None

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            spec = _CHAOS_SITE_ARGS.get(func.attr)
            if spec is None:
                continue
            for arg in self._site_args(node, spec):
                culprit = self._unstable_part(module, arg)
                if culprit is not None:
                    yield self.finding(
                        module,
                        node,
                        f"chaos site id for .{func.attr}() derives from "
                        f"{culprit} — selection is a pure hash of the "
                        "site, so an unstable id breaks planned == "
                        "observed and the times-budget convergence; use "
                        "a client-stable id (request id, node name, "
                        "model id, path)",
                    )


# ---------------------------------------------------------------- JGL014

#: metric mutator method names (registry.py's Counter.inc /
#: Histogram.observe / Gauge.set) whose KEYWORD arguments are label
#: values — the per-label-key time series the registry materializes.
_METRIC_MUTATOR_ATTRS = ("inc", "observe", "set")

#: names that smell like a per-request / per-connection identifier —
#: unbounded over a daemon's lifetime, so one of these as a label value
#: mints a fresh time series per request. Terminal-word match only:
#: ``model_id`` / ``node_id`` style BOUNDED identifiers must not match.
_REQUEST_SCOPED_NAME_RE = re.compile(
    r"(^|_)(request_id|req_id|rid|trace_id|span_id|session_id|"
    r"client_id|conn_id|uuid|nonce|token|remote_addr|peer|addr)$"
)

#: the sanctioned escape hatch: a label value passed through a
#: fold/sanitize call (``registry.sanitize_label``, the daemon's
#: unknown-model fold) is bounded by construction.
_LABEL_FOLD_CALL_RE = re.compile(r"(sanitize|fold)", re.IGNORECASE)


@register
class UnboundedMetricLabelCardinality(Rule):
    """ISSUE 16's observability-budget contract: the metrics registry
    keeps one monotonic time series per distinct label key, forever —
    ``peek()`` snapshots, ``/varz``, the Prometheus exposition and the
    schema validator all walk every series. A per-request identifier
    (request id, trace id, peer address, nonce) — or any
    fresh-every-call value (``uuid4()``, ``time.time()``) — used as a
    label VALUE turns a bounded family into an unbounded one: memory
    grows with traffic, scrapes slow down linearly, and the statistical
    SLO engine's ``peek`` per tick degrades with it. Label values in
    the serving and observability tiers must come from closed sets
    (model ids, buckets, phases, typed statuses); per-request detail
    belongs in the trace, not the registry. Folding through a
    ``sanitize``/``fold`` helper (``registry.sanitize_label``, the
    dispatcher's unknown-model fold) is the sanctioned escape hatch."""

    id = "JGL014"
    name = "unbounded-metric-label-cardinality"
    description = (
        "per-request identifier or fresh-every-call value used as a "
        "metric label value in serving/ or observability/ — one time "
        "series per request; fold to a closed set (sanitize_label) or "
        "put it in the trace"
    )

    def _in_scope(self, relpath: str) -> bool:
        return scopes.LABEL_CARDINALITY.contains(relpath)

    def _culprit(self, module: ModuleInfo, expr: ast.expr) -> str | None:
        # Sanctioned-fold scan first: a sanitize/fold call ANYWHERE in
        # the value expression bounds it, whatever fed the fold.
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                name = module.resolve(sub.func) or ""
                attr = (sub.func.attr if isinstance(sub.func, ast.Attribute)
                        else name)
                if attr and _LABEL_FOLD_CALL_RE.search(attr):
                    return None
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                name = module.resolve(sub.func)
                if name in _UNSTABLE_SITE_CALLS:
                    return f"{name}()"
            elif isinstance(sub, ast.Name):
                if _REQUEST_SCOPED_NAME_RE.search(sub.id):
                    return sub.id
            elif isinstance(sub, ast.Attribute):
                if _REQUEST_SCOPED_NAME_RE.search(sub.attr):
                    return sub.attr
        return None

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not self._in_scope(module.relpath):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in _METRIC_MUTATOR_ATTRS:
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue  # **labels: the caller's names, not ours
                culprit = self._culprit(module, kw.value)
                if culprit is not None:
                    yield self.finding(
                        module,
                        node,
                        f"label {kw.arg}={culprit} on .{func.attr}() mints "
                        "one time series per request — the registry keeps "
                        "every label key forever; fold to a closed set "
                        "(registry.sanitize_label) or record it in the "
                        "trace instead",
                    )


# ---------------------------------------------------------------- JGL020

#: container-mutator method names that GROW the receiver by one entry
#: per call — the per-iteration accumulation JGL020 is about. ``pop``/
#: ``clear`` shrink; assignment rebinding is a fresh object.
_ACCUMULATOR_METHODS = ("append", "extend", "appendleft", "add")

#: module-scope constructors whose result is a growable container.
_CONTAINER_CTORS = {
    "dict", "list", "set", "collections.deque",
    "collections.defaultdict", "collections.OrderedDict",
}


def _module_container_names(module: ModuleInfo) -> set[str]:
    """Module-level names bound to a growable container at module scope
    (literal or constructor call) — the cross-call persistent state a
    per-cell accumulation leaks into."""
    names: set[str] = set()
    for node in module.tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        for t in targets:
            if not isinstance(t, ast.Name):
                continue
            if isinstance(value, (ast.Dict, ast.List, ast.Set)) or (
                isinstance(value, ast.Call)
                and module.resolve(value.func) in _CONTAINER_CTORS
            ):
                names.add(t.id)
    return names


@register
class UnboundedCellAccumulation(Rule):
    """ISSUE 19's streaming contract, enforced at the AST: in
    ``scenarios/`` the loop axis IS the replicate grid — a million-cell
    run iterates a million times — so appending one host object per
    iteration into state that outlives the call (a module-level
    container, or an attribute of a long-lived ``self``) grows host
    memory O(cells) and silently reintroduces the materialized-rows
    regime the streaming aggregate runner exists to retire. Per-call
    locals are fine (they die with the call and rows mode is an
    explicit opt-in); persistent accumulators must either journal an
    O(1) block record or fold into mergeable sufficient statistics
    (``aggregate.AggState``). The sanctioned escape hatch for a
    deliberately bounded accumulator is the standard suppression
    comment with a rationale."""

    id = "JGL020"
    name = "unbounded-cell-accumulation"
    description = (
        "per-iteration append/extend into a module-level container or "
        "self attribute inside a scenarios/ loop — grows O(cells) "
        "across the run; journal a block record or fold into AggState "
        "sums instead"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if not scopes.SCENARIOS.contains(module.relpath):
            return
        containers = _module_container_names(module)
        seen: set[int] = set()
        for func in ast.walk(module.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            # Python scoping, as in JGL001: a name bound anywhere in
            # the function shadows the like-named module container.
            local_binds = {a.arg for a in (
                func.args.args + func.args.posonlyargs
                + func.args.kwonlyargs
            )}
            global_decls: set[str] = set()
            for n in ast.walk(func):
                if isinstance(n, ast.Global):
                    global_decls.update(n.names)
                elif isinstance(n, ast.Name) and isinstance(
                    n.ctx, (ast.Store, ast.Del)
                ):
                    local_binds.add(n.id)
            local_binds -= global_decls
            for loop in ast.walk(func):
                if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                    continue
                for node in ast.walk(loop):
                    if (
                        not isinstance(node, ast.Call)
                        or id(node) in seen
                        or not isinstance(node.func, ast.Attribute)
                        or node.func.attr not in _ACCUMULATOR_METHODS
                    ):
                        continue
                    # Unwind attribute chains AND pass through calls:
                    # `_BY_COL.setdefault(k, []).append(x)` mutates the
                    # container _BY_COL holds, so the receiver's root
                    # is _BY_COL, not the setdefault result.
                    root = node.func.value
                    while isinstance(root, (ast.Attribute, ast.Call)):
                        root = (root.func if isinstance(root, ast.Call)
                                else root.value)
                    if not isinstance(root, ast.Name):
                        continue
                    if root.id == "self":
                        if not isinstance(node.func.value, ast.Attribute):
                            continue  # self.append: not attribute state
                        culprit = (
                            "self attribute "
                            f"'self.{node.func.value.attr}'"
                        )
                    elif (
                        root.id in containers
                        and root.id not in local_binds
                    ):
                        culprit = f"module-level container '{root.id}'"
                    else:
                        continue
                    seen.add(id(node))
                    yield self.finding(
                        module,
                        node,
                        f".{node.func.attr}() onto {culprit} "
                        "inside a loop accumulates one host "
                        "object per replicate — O(cells) growth across "
                        "the run; journal an O(1) block record or fold "
                        "into AggState sums (scenarios/aggregate.py), or "
                        "keep the accumulator local to the call",
                    )


# ---------------------------------------------------------------- JGL021

#: registry creator functions whose first positional argument is the
#: family name. ``gauge`` is deliberately exempt: gauges are
#: snapshot-time samples with open-ended names (per-entry-point
#: cost_analysis, per-device memory) and no "present at zero on every
#: run" contract.
_FAMILY_CREATOR_ATTRS = ("counter", "histogram", "bucket_histogram")

#: the one sanctioned pre-creation site, parsed from the REAL device.py
#: that sits next to this package (the linter lints this repository;
#: the contract is against this repository's pre-creation list).
_PRECREATION_FUNC = "install_jax_monitoring"

_precreated_cache: frozenset[str] | None = None


def _device_py_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "observability",
        "device.py",
    )


def _module_string_constants(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings — the indirection
    shardio.py uses (``BYTES_FAMILY = "artifact_transfer_bytes_total"``)
    and the only non-literal family-name form this rule resolves."""
    out: dict[str, str] = {}
    for node in tree.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = value.value
    return out


def _module_string_dicts(tree: ast.Module) -> dict[str, set[str]]:
    """Module-level dicts with literal string VALUES, by constant name —
    device.py's ``_CACHE_EVENT_COUNTERS`` event->family maps, whose
    ``.values()`` feed pre-creation loops."""
    out: dict[str, set[str]] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict)):
            continue
        vals = {
            v.value
            for v in node.value.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        }
        for t in node.targets:
            if isinstance(t, ast.Name) and vals:
                out[t.id] = vals
    return out


def _literal_strings(expr: ast.expr) -> set[str]:
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return {
            e.value
            for e in expr.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    return set()


def precreated_families() -> frozenset[str]:
    """The family names ``install_jax_monitoring`` pre-creates, read by
    AST from ``observability/device.py``: literal first args of creator
    calls, plus the strings any ``for``-loop in the function iterates —
    a literal tuple/list, or ``CONST.values()`` of a module-level
    string-valued dict. Cached for the process; an unreadable or
    unparsable device.py yields the empty set (the rule then stays
    silent rather than failing the whole lint on a broken neighbor —
    the parse error surfaces on device.py itself)."""
    global _precreated_cache
    if _precreated_cache is not None:
        return _precreated_cache
    try:
        with open(_device_py_path(), "r", encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError, ValueError):
        _precreated_cache = frozenset()
        return _precreated_cache
    dicts = _module_string_dicts(tree)
    names: set[str] = set()
    for node in tree.body:
        if not (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == _PRECREATION_FUNC
        ):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                func = sub.func
                attr = (
                    func.attr
                    if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else None
                )
                if attr in _FAMILY_CREATOR_ATTRS and sub.args:
                    arg = sub.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                        names.add(arg.value)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                names |= _literal_strings(sub.iter)
                it = sub.iter
                if (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Attribute)
                    and it.func.attr == "values"
                    and isinstance(it.func.value, ast.Name)
                ):
                    names |= dicts.get(it.func.value.id, set())
    _precreated_cache = frozenset(names)
    return _precreated_cache


def _family_creator_kind(module: ModuleInfo, node: ast.Call) -> str | None:
    """``'counter'`` / ``'histogram'`` / ``'bucket_histogram'`` when
    this call mints (or fetches) a registry family, else None. Matched
    on the resolved dotted name so every spelling in the tree counts:
    ``obs.counter``, ``_registry.counter``, bare ``counter`` imported
    from the registry, ``REGISTRY.bucket_histogram``. ``self.``-rooted
    chains are skipped — an injected registry double is the test's
    business, not the shipped contract's."""
    name = module.resolve(node.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[-1] not in _FAMILY_CREATOR_ATTRS or parts[0] == "self":
        return None
    if "observability" in parts or "registry" in parts or "REGISTRY" in parts:
        return parts[-1]
    return None


@register
class MetricFamilyNotPrecreated(Rule):
    """ISSUE 20's metrics-contract closure: ``install_jax_monitoring``
    pre-creates every counter/histogram family at zero so "it never
    happened" is a recorded 0 in metrics.json, not a missing key —
    ``scripts/check_metrics_schema.py`` and every downstream consumer
    (the fleet reconciler, the SLO engine, dashboards diffing runs)
    key on that. A family first created at its emit site exists only
    on runs that take that code path: the export schema then depends
    on traffic, and a zero regresses to an absence. The fix is one
    pre-creation line in device.py (with an identical bucket ladder
    for bucket histograms — the registry rejects a mismatched
    re-creation). Dynamic family names can't be cross-checked
    statically and are skipped; route them through a closed set or a
    pre-created prefix instead."""

    id = "JGL021"
    name = "metric-family-not-precreated"
    description = (
        "counter/histogram family created outside "
        "install_jax_monitoring and missing from its pre-creation "
        "list — the family exists only on runs that take this code "
        "path, so the metrics.json schema depends on traffic; add the "
        "pre-creation line in observability/device.py"
    )

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if scopes.METRIC_FAMILY_ORIGIN.contains(module.relpath):
            return
        precreated = precreated_families()
        if not precreated:
            return  # device.py unreadable here: nothing to check against
        consts = _module_string_constants(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _family_creator_kind(module, node)
            if kind is None or not node.args:
                continue
            arg = node.args[0]
            family: str | None = None
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                family = arg.value
            elif isinstance(arg, ast.Name):
                family = consts.get(arg.id)
            if family is None or family in precreated:
                continue
            yield self.finding(
                module,
                node,
                f"metric family '{family}' ({kind}) is not pre-created "
                "in install_jax_monitoring — it will be missing from "
                "metrics.json on any run that never reaches this line; "
                "add the pre-creation in observability/device.py (same "
                "bucket ladder for bucket histograms)",
            )

"""graftlint incremental result cache (``--cache <dir>``).

The gate re-lints the whole tree on every run; almost none of it
changed. Results are pure functions of (file content, rule set), so a
content-hash cache is exact, not heuristic:

* per-file key — sha256 of the relpath + source; stores that file's
  per-module findings and suppressed findings;
* program key — sha256 over every file's (relpath, content hash);
  stores the whole-program pass (JGL015+) wholesale, so a fully warm
  run parses nothing at all;
* salt — sha256 of the analysis package's own sources plus the
  ``--select`` list. Editing any rule, or changing the selection,
  invalidates everything (cold/warm parity is asserted in tests).

Only keys touched by the current run are written back, so entries for
deleted or renamed files age out instead of accumulating.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

from ate_replication_causalml_tpu.analysis.core import Finding

#: Bump on any change to the cache file layout.
CACHE_SCHEMA_VERSION = 1

_CACHE_BASENAME = "graftlint-cache.json"


def _sha(*parts: str) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode("utf-8"))
        h.update(b"\0")
    return h.hexdigest()


def ruleset_salt(select=None) -> str:
    """Content hash of the analysis package itself — any rule edit must
    read as a different rule set."""
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    h.update(f"schema={CACHE_SCHEMA_VERSION}".encode())
    h.update(repr(sorted(select) if select is not None else None).encode())
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            h.update(os.path.relpath(path, pkg_dir).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _dump(findings: list[Finding]) -> list[dict]:
    return [f.as_dict() for f in findings]


def _load(rows: list[dict]) -> list[Finding]:
    return [Finding(**row) for row in rows]


class ResultCache:
    """Pass to :func:`core.lint_paths` (``cache=``); see the CLI's
    ``--cache`` flag."""

    def __init__(self, cache_dir: str, select=None):
        self.path = os.path.join(cache_dir, _CACHE_BASENAME)
        self.salt = ruleset_salt(select)
        self._entries: dict[str, dict] = {}
        self._live: dict[str, dict] = {}
        try:
            with open(self.path, encoding="utf-8") as f:
                payload = json.load(f)
            if payload.get("salt") == self.salt:
                self._entries = payload.get("entries", {})
        except (OSError, ValueError):
            pass  # cold start: corrupt/absent cache is just empty

    # ── keys ─────────────────────────────────────────────────────────

    @staticmethod
    def _module_key(relpath: str, source: str) -> str:
        return "m:" + _sha(relpath, source)

    @staticmethod
    def _program_key(entries) -> str:
        return "p:" + _sha(*(f"{rel}:{_sha(src or '')}" for _, rel, src in entries))

    # ── lookup / store ───────────────────────────────────────────────

    def _get(self, key: str):
        row = self._entries.get(key)
        if row is None:
            return None
        self._live[key] = row
        return _load(row["findings"]), _load(row["suppressed"])

    def _put(self, key: str, findings, suppressed) -> None:
        row = {"findings": _dump(findings), "suppressed": _dump(suppressed)}
        self._entries[key] = row
        self._live[key] = row

    def get_module(self, relpath: str, source: str):
        return self._get(self._module_key(relpath, source))

    def put_module(self, relpath: str, source: str, findings, suppressed):
        self._put(self._module_key(relpath, source), findings, suppressed)

    def get_program(self, entries):
        return self._get(self._program_key(entries))

    def put_program(self, entries, findings, suppressed):
        self._put(self._program_key(entries), findings, suppressed)

    # ── persistence ──────────────────────────────────────────────────

    def save(self) -> None:
        """Write back only the keys this run touched (atomic: a killed
        lint run never leaves a torn cache, just a stale one)."""
        payload = {
            "schema_version": CACHE_SCHEMA_VERSION,
            "salt": self.salt,
            "entries": dict(sorted(self._live.items())),
        }
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.path) or ".", suffix=".tmp"
        )
        # The write IS atomic (tmp + os.replace) but must not import
        # observability.export — that would drag the runtime package
        # into the jax-free linter, so the two suppressions below are
        # load-bearing, not a shortcut.
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:  # graftlint: disable=JGL005 — tmp half of a tmp+os.replace atomic write; export helpers are off-limits in the jax-free linter
                json.dump(payload, f)  # graftlint: disable=JGL005 — writes the tmp file above; os.replace publishes it atomically
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

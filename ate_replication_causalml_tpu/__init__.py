"""TPU-native causal-inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
R pipeline ``Zoe187419/ATE_replication_causalML`` (the AEA-2018
"Machine Learning and Econometrics" ATE tutorial replication,
``ate_functions.R`` + ``ate_replication.Rmd``): the full ATE estimator
suite — difference-in-means, regression adjustment, IPW, LASSO variants,
AIPW/doubly-robust with sandwich + bootstrap standard errors, Belloni
post-double-selection, double machine learning, approximate residual
balancing, and grf-style honest causal forests — built TPU-first:

* nuisance fits (IRLS logistic GLM, LASSO coordinate descent, honest
  forests) are XLA-lowered JAX routines (Pallas kernels for the hot ops),
* every embarrassingly parallel loop (bootstrap replicates, CV folds,
  trees) runs as ``vmap``/``shard_map`` over a ``jax.sharding.Mesh``,
* rows shard across devices with ``psum`` reductions for the 1M-row regime.

Layer map (SURVEY.md §7.1):
  L0 ``data``       — columnar dataset + schema, synthetic GGL generator,
                      bias injection, R-compatible RNG
  L1 ``ops``        — OLS/WLS, IRLS GLM, LASSO CD, QP/ADMM, bootstrap
  L2 ``estimators`` — the uniform Estimator -> EstimatorResult protocol
  L3 ``models``     — random forest + honest causal forest engines
  L4 ``parallel``   — mesh config, shard_map placement, collectives
  L5 ``pipeline``   — notebook-equivalent driver + plots + checkpointing
"""

__version__ = "0.2.0"

from ate_replication_causalml_tpu.estimators.base import EstimatorResult, ResultTable

__all__ = ["EstimatorResult", "ResultTable", "__version__"]

"""Least-squares core: OLS / WLS with coefficient standard errors.

TPU-native replacement for R's ``stats::lm`` + ``summary.lm`` (LAPACK QR
via ``dqrls``), invoked by the reference at ``ate_functions.R:28, 53, 74,
320, 363``. Instead of translating the QR path we solve the normal
equations with a Cholesky factorization — for the reference's design
matrices (z-scored covariates, p ≤ ~460 even for Belloni's interaction
expansion) this is numerically sound and maps straight onto the MXU as
one large matmul (X^T X) plus a tiny solve. All matmuls request
``precision='highest'`` so float32 inputs get full-precision
accumulation on TPU.

Everything here is jit-safe, static-shaped, and vmap-able (the bootstrap
and CV loops vmap these fits).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_PREC = lax.Precision.HIGHEST


class LstsqResult(NamedTuple):
    """Fit result mirroring what ``summary.lm`` exposes to the estimators:
    coefficients, their standard errors, residuals, and the unscaled
    inverse Gram matrix (for sandwich-style reuse)."""

    coef: jax.Array        # (p,)
    se: jax.Array          # (p,)
    residuals: jax.Array   # (n,)
    xtx_inv: jax.Array     # (p, p)
    sigma2: jax.Array      # scalar: RSS / (n - p)


def _chol_solve(a: jax.Array, b: jax.Array) -> jax.Array:
    """Solve the SPD system ``a x = b`` via Cholesky."""
    chol = jnp.linalg.cholesky(a)
    return jax.scipy.linalg.cho_solve((chol, True), b)


def _spd_inverse(a: jax.Array) -> jax.Array:
    chol = jnp.linalg.cholesky(a)
    return jax.scipy.linalg.cho_solve((chol, True), jnp.eye(a.shape[0], dtype=a.dtype))


def ols(x: jax.Array, y: jax.Array, ridge: float = 0.0) -> LstsqResult:
    """OLS with classical (homoskedastic) standard errors.

    Matches R ``lm`` + ``summary.lm``: ``se_j = sqrt(sigma2 * (X'X)^-1_jj)``
    with ``sigma2 = RSS / (n - p)``. ``ridge`` adds a tiny diagonal for
    rank-deficient designs (R drops aliased columns instead; callers that
    need R's aliasing behavior pre-filter columns).
    """
    n, p = x.shape
    xtx = jnp.matmul(x.T, x, precision=_PREC)
    if ridge:
        xtx = xtx + ridge * jnp.eye(p, dtype=x.dtype)
    xty = jnp.matmul(x.T, y, precision=_PREC)
    xtx_inv = _spd_inverse(xtx)
    coef = jnp.matmul(xtx_inv, xty, precision=_PREC)
    resid = y - jnp.matmul(x, coef, precision=_PREC)
    sigma2 = jnp.sum(resid * resid) / (n - p)
    se = jnp.sqrt(jnp.clip(jnp.diag(xtx_inv) * sigma2, 0.0))
    return LstsqResult(coef=coef, se=se, residuals=resid, xtx_inv=xtx_inv, sigma2=sigma2)


def wls(x: jax.Array, y: jax.Array, weights: jax.Array) -> LstsqResult:
    """Weighted least squares with R ``lm(..., weights=)`` semantics.

    R minimizes ``sum(w_i e_i^2)``; ``summary.lm`` then reports
    ``se = sqrt(sigma2 * (X'WX)^-1_jj)`` with
    ``sigma2 = sum(w e^2) / (n - p)``. Used by the propensity-regression
    estimator (``ate_functions.R:71-75``).
    """
    n, p = x.shape
    xw = x * weights[:, None]
    xtwx = jnp.matmul(xw.T, x, precision=_PREC)
    xtwy = jnp.matmul(xw.T, y, precision=_PREC)
    xtwx_inv = _spd_inverse(xtwx)
    coef = jnp.matmul(xtwx_inv, xtwy, precision=_PREC)
    resid = y - jnp.matmul(x, coef, precision=_PREC)
    sigma2 = jnp.sum(weights * resid * resid) / (n - p)
    se = jnp.sqrt(jnp.clip(jnp.diag(xtwx_inv) * sigma2, 0.0))
    return LstsqResult(coef=coef, se=se, residuals=resid, xtx_inv=xtwx_inv, sigma2=sigma2)


def ols_no_intercept_1d(x: jax.Array, y: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``lm(y ~ 0 + x)`` for a single regressor — the DML residual-on-residual
    regression (``ate_functions.R:363``). Returns (coef, se)."""
    sxx = jnp.sum(x * x)
    coef = jnp.sum(x * y) / sxx
    resid = y - coef * x
    n = x.shape[0]
    sigma2 = jnp.sum(resid * resid) / (n - 1)
    se = jnp.sqrt(sigma2 / sxx)
    return coef, se


def add_intercept(x: jax.Array) -> jax.Array:
    return jnp.concatenate([jnp.ones((x.shape[0], 1), x.dtype), x], axis=1)


def alias_filter(cols, *, with_intercept: bool = True, tol: float = 1e-7):
    """Indices of the columns R's ``lm`` would keep (pivoted-QR aliasing).

    R's ``lm.fit`` runs LINPACK ``dqrdc2``, which walks columns left to
    right and aliases (reports NA for) any column whose R-diagonal falls
    below ``tol`` relative to the column's own norm — i.e. any column
    numerically dependent on *kept earlier* columns, with left-to-right
    preference. This reproduces that rule with sequential modified
    Gram–Schmidt in float64 (host-side: selection logic, not TPU
    compute). ``with_intercept=True`` seeds the basis with the constant
    column (R models have an implicit leading intercept), so constant
    columns alias away as they do in ``lm``.
    """
    # Host-side numpy selection logic replicating LINPACK's f64 — not
    # device compute, so the x64 policy doesn't apply here.
    a = np.asarray(cols, dtype=np.float64)  # graftlint: disable=JGL004
    n = a.shape[0]
    basis: list[np.ndarray] = []
    if with_intercept:
        basis.append(np.full(n, 1.0 / np.sqrt(n)))
    keep: list[int] = []
    for j in range(a.shape[1]):
        v = a[:, j]
        norm0 = np.linalg.norm(v)
        if norm0 == 0.0:
            continue
        r = v.copy()
        for q in basis:
            r -= (q @ r) * q
        # Twice-is-enough re-orthogonalization keeps the test sharp when
        # columns are nearly dependent.
        for q in basis:
            r -= (q @ r) * q
        rnorm = np.linalg.norm(r)
        if rnorm > tol * norm0:
            keep.append(j)
            basis.append(r / rnorm)
    return np.asarray(keep, dtype=np.int64)

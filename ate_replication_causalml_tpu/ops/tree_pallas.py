"""Pallas TPU kernels for the per-ROW side of forest growth.

The histogram kernel (ops/hist_pallas.py) removed the per-node
reduction bottleneck; a round-4 device trace (scripts/trace_fit.py)
showed the remaining grow time concentrated in two per-row stages that
still ran as XLA ops:

  * the training-row leaf-value recording ``leaf_value[node_of_row]``
    (models/forest.py) lowered to a serialized per-row gather —
    ~8 ms/tree at 1M rows, ~25% of the classifier fit;
  * per-level routing (route_rows_blocked) built a (rows, nodes)
    one-hot in HBM per tree per level — ~5 ms/tree in transient
    HBM traffic, lax.map block overhead and thin matmuls.

Both are row-parallel maps with tiny per-node tables — the exact shape
Pallas handles well: stream the rows through VMEM in tiles, keep the
table VMEM-resident across the whole sweep, and emit one output row
per tree. No accumulation across grid steps, so the grid is trivially
sequential-safe.

Both kernels are EXACT (integer compares / one-nonzero-product
selections in f32 — no rounding path), asserted against the XLA
formulations in tests/test_tree_pallas.py.

Like the histogram kernel, each public entry point is wrapped in
``jax.custom_batching.custom_vmap``: the growers call them per tree
under (nested) ``jax.vmap``, and the rule collapses every vmap level
into the kernel's tree axis so one chunk of trees makes ONE kernel
call per level (reference context: grf's C++ core routes rows
per-tree serially, ate_functions.R:169-174 / grf's tree training —
here the whole chunk rides one codes stream).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


from ate_replication_causalml_tpu.ops.hist_pallas import (
    _COMPILER_PARAMS,
    _round_up,
    _VMEM_BUDGET,
)

_TILE = 2048
# Tree-axis chunk for one kernel call. VMEM per tree is tiny for both
# kernels (tables ≤ (M, p+1) f32, transients (M, TILE)); the cap bounds
# the unrolled kernel body / compile time, not memory.
_TREE_CAP = 16


def _pad_rows(a, n_pad, value=0):
    pad = [(0, 0)] * (a.ndim - 1) + [(0, n_pad - a.shape[-1])]
    return jnp.pad(a, pad, constant_values=value)


# ---------------------------------------------------------------------------
# Leaf-value lookup: out[t, row] = table[t, ids[t, row]]
# ---------------------------------------------------------------------------


def _lookup_kernel(table_ref, ids_ref, out_ref, *, n_trees, n_chan, n_slots):
    """One row tile: per-tree K-channel table lookup as a one-hot
    contraction — the one-hot is built ONCE per tree and contracted
    against all K channel tables in a single dot.

    table_ref: (T·K, Lp) f32 — VMEM-resident across the sweep
    ids_ref:   (T, TILE) int32 — slot ids; out-of-range (e.g. -1 pad)
               contributes 0
    out_ref:   (T·K, TILE) f32
    """
    tile = ids_ref.shape[1]
    slot_iota = lax.broadcasted_iota(jnp.int32, (n_slots, tile), 0)
    rows = []
    for t in range(n_trees):  # static unroll — T is the chunk cap
        oh = (ids_ref[t : t + 1, :] == slot_iota).astype(jnp.float32)
        rows.append(
            lax.dot_general(
                table_ref[t * n_chan : (t + 1) * n_chan, :],
                oh,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
    out_ref[:] = rows[0] if n_trees == 1 else jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _table_lookup_batched(table, ids, *, interpret=False):
    """(T, K, L) tables, (T, n) int ids → (T, K, n) f32 values."""
    n_trees, n_chan, n_slots = table.shape
    n = ids.shape[1]
    n_pad = _round_up(max(n, _TILE), _TILE)
    l_pad = _round_up(n_slots, 128)
    table = jnp.pad(
        table.astype(jnp.float32).reshape(n_trees * n_chan, n_slots),
        ((0, 0), (0, l_pad - n_slots)),
    )
    ids = _pad_rows(ids.astype(jnp.int32), n_pad, value=-1)
    out = pl.pallas_call(
        functools.partial(
            _lookup_kernel, n_trees=n_trees, n_chan=n_chan, n_slots=l_pad
        ),
        grid=(n_pad // _TILE,),
        in_specs=[
            pl.BlockSpec((n_trees * n_chan, l_pad), lambda i: (0, 0)),
            pl.BlockSpec((n_trees, _TILE), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((n_trees * n_chan, _TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((n_trees * n_chan, n_pad), jnp.float32),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=_VMEM_BUDGET),
    )(table, ids)
    return out.reshape(n_trees, n_chan, n_pad)[:, :, :n]


@functools.lru_cache(maxsize=None)
def _lookup_vmappable(interpret: bool):
    from jax import custom_batching

    def impl(table, ids):
        t = table.shape[0]
        outs = [
            _table_lookup_batched(
                table[s : s + _TREE_CAP], ids[s : s + _TREE_CAP],
                interpret=interpret,
            )
            for s in range(0, t, _TREE_CAP)
        ]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    @custom_batching.custom_vmap
    def g(table, ids):
        return impl(table, ids)

    @g.def_vmap
    def _rule(axis_size, in_batched, table, ids):  # noqa: ANN001
        table_b, ids_b = in_batched
        if not table_b:
            table = jnp.broadcast_to(table[None], (axis_size,) + table.shape)
        if not ids_b:
            ids = jnp.broadcast_to(ids[None], (axis_size,) + ids.shape)
        b, t = table.shape[0], table.shape[1]
        out = g(
            table.reshape((b * t,) + table.shape[2:]),
            ids.reshape(b * t, ids.shape[2]),
        )
        return out.reshape((b, t) + out.shape[1:]), True

    return g


def table_lookup(table: jax.Array, ids: jax.Array, *,
                 backend: str = "pallas") -> jax.Array:
    """``table[ids]`` for a small per-tree table, without the per-row
    gather (serialized on TPU — measured ~8 ms/tree for the 512-leaf
    lookup at 1M rows, the single largest op of the classifier fit).

    Args:
      table: (L,) per-tree value table, or (K, L) for K channels looked
        up through ONE shared one-hot (the causal leaf payload).
      ids: (n,) int32 slot ids in [0, L); out-of-range yields 0.0.
      backend: "pallas" | "pallas_interpret" | "gather" (the plain XLA
        gather — the right choice on CPU, where gathers are cheap).

    Returns (n,) for a 1-D table, (K, n) for a 2-D one.

    Vmappable: under ``jax.vmap`` (any nesting) the batch axes collapse
    into one tree-batched kernel call, like ``bin_histogram``.
    """
    squeeze = table.ndim == 1
    tab2 = table[None] if squeeze else table
    if backend == "gather":
        # In-range is guaranteed by the growers; keep parity with the
        # kernel's out-of-range→0 contract anyway.
        n_slots = tab2.shape[-1]
        valid = (ids >= 0) & (ids < n_slots)
        out = jnp.where(
            valid[None, :], tab2[:, jnp.clip(ids, 0, n_slots - 1)], 0.0
        )
        return out[0] if squeeze else out
    g = _lookup_vmappable(backend == "pallas_interpret")
    out = g(tab2[None], ids[None])[0]
    return out[0] if squeeze else out


# ---------------------------------------------------------------------------
# Route bits: bit[t, row] = 1[codes[row, feat[t, node]] > thr[t, node]],
# node = ids[t, row]
# ---------------------------------------------------------------------------


def _route_kernel(codes_t_ref, ids_ref, tab_ref, out_ref, *, n_trees, m_nodes):
    """One row tile of tree-batched routing.

    codes_t_ref: (F1, TILE) f32 — transposed codes with a trailing
                 all-ones row (F1 = p + 1)
    ids_ref:     (T, TILE) int32 — current (rev) node ids
    tab_ref:     (T·M, F1) f32 — per-node [feature one-hot | −thr]
    out_ref:     (T, TILE) int32 — route bit (1 = right)

    Per tree: G = tab_t @ codes_t gives every node's margin
    ``code_at_feat − thr`` for every row; the row's own node is selected
    by the node one-hot (single nonzero product — exact in f32), and
    the bit is the sign. One MXU dot + two VPU passes per tree; no
    (rows, M) one-hot ever leaves VMEM.
    """
    tile = ids_ref.shape[1]
    node_iota = lax.broadcasted_iota(jnp.int32, (m_nodes, tile), 0)
    rows = []
    for t in range(n_trees):  # static unroll — T is the chunk cap
        oh = (ids_ref[t : t + 1, :] == node_iota).astype(jnp.float32)
        margin = lax.dot_general(
            tab_ref[t * m_nodes : (t + 1) * m_nodes, :],
            codes_t_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (M, TILE): code − thr if the row were in node m
        at_node = jnp.sum(oh * margin, axis=0, keepdims=True)  # (1, TILE)
        rows.append((at_node > 0).astype(jnp.int32))
    out_ref[:] = rows[0] if n_trees == 1 else jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _route_bits_batched(codes_t, ids, tab, *, interpret=False):
    """codes_t (F1, n) f32, ids (T, n) int32, tab (T, M, F1) f32 →
    (T, n) int32 route bits."""
    n_trees, m_nodes, f1 = tab.shape
    n = ids.shape[1]
    n_pad = _round_up(max(n, _TILE), _TILE)
    codes_t = _pad_rows(codes_t.astype(jnp.float32), n_pad)
    ids = _pad_rows(ids.astype(jnp.int32), n_pad, value=-1)
    tab = tab.astype(jnp.float32).reshape(n_trees * m_nodes, f1)
    out = pl.pallas_call(
        functools.partial(_route_kernel, n_trees=n_trees, m_nodes=m_nodes),
        grid=(n_pad // _TILE,),
        in_specs=[
            pl.BlockSpec((f1, _TILE), lambda i: (0, i)),
            pl.BlockSpec((n_trees, _TILE), lambda i: (0, i)),
            pl.BlockSpec((n_trees * m_nodes, f1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((n_trees, _TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((n_trees, n_pad), jnp.int32),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=_VMEM_BUDGET),
    )(codes_t, ids, tab)
    return out[:, :n]


@functools.lru_cache(maxsize=None)
def _route_vmappable(interpret: bool):
    from jax import custom_batching

    def impl(codes_t, ids, tab):
        t = ids.shape[0]
        outs = [
            _route_bits_batched(
                codes_t, ids[s : s + _TREE_CAP], tab[s : s + _TREE_CAP],
                interpret=interpret,
            )
            for s in range(0, t, _TREE_CAP)
        ]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    @custom_batching.custom_vmap
    def g(codes_t, ids, tab):
        return impl(codes_t, ids, tab)

    @g.def_vmap
    def _rule(axis_size, in_batched, codes_t, ids, tab):  # noqa: ANN001
        codes_b, ids_b, tab_b = in_batched
        if codes_b:
            # Per-slice codes can't share a stream; loop (mirrors the
            # histogram dispatch's fallback — no caller does this today).
            out = jnp.stack([
                g(codes_t[i], ids[i] if ids_b else ids, tab[i] if tab_b else tab)
                for i in range(axis_size)
            ])
            return out, True
        if not ids_b:
            ids = jnp.broadcast_to(ids[None], (axis_size,) + ids.shape)
        if not tab_b:
            tab = jnp.broadcast_to(tab[None], (axis_size,) + tab.shape)
        b, t = ids.shape[0], ids.shape[1]
        out = g(
            codes_t,
            ids.reshape(b * t, ids.shape[2]),
            tab.reshape((b * t,) + tab.shape[2:]),
        )
        return out.reshape(b, t, out.shape[1]), True

    return g


def codes_transposed(codes: jax.Array) -> jax.Array:
    """The (p+1, n) f32 routing operand: transposed bin codes plus an
    all-ones row that carries each node's −threshold through the same
    MXU dot. Built ONCE per fit and shared by every tree/level (an
    (n, p)→(p, n) transpose is one relayout; the old per-level blocked
    routing paid a (rows, M) one-hot build every level instead)."""
    n = codes.shape[0]
    return jnp.concatenate(
        [codes.T.astype(jnp.float32), jnp.ones((1, n), jnp.float32)]
    )


def route_table(best_feat: jax.Array, best_bin: jax.Array, p: int) -> jax.Array:
    """Per-node routing table (M, p+1): [feature one-hot | −threshold].
    With ``codes_transposed``'s ones row, ``tab @ codes_t`` yields the
    margin ``code_at_feat − thr`` whose sign is the route bit — exact,
    since codes and thresholds are small integers in f32 and the
    feature selection has a single nonzero product."""
    feat_oh = jax.nn.one_hot(best_feat, p, dtype=jnp.float32)
    return jnp.concatenate(
        [feat_oh, -best_bin.astype(jnp.float32)[:, None]], axis=1
    )


def route_bits(codes_t: jax.Array, ids: jax.Array, best_feat: jax.Array,
               best_bin: jax.Array, *, backend: str = "pallas") -> jax.Array:
    """Route bit (0 = left, 1 = right) for every row of one tree level:
    ``codes[row, feat[ids[row]]] > bin[ids[row]]`` without a (rows, M)
    one-hot in HBM.

    Args:
      codes_t: (p+1, n) from :func:`codes_transposed` (shared per fit).
      ids: (n,) int32 current node ids in [0, M); -1 yields bit 0.
      best_feat/best_bin: (M,) int32 split tables (rev or interleaved —
        whatever order ``ids`` indexes).
      backend: "pallas" | "pallas_interpret".

    Vmappable over trees: batch axes on ``ids``/tables collapse into
    one tree-batched kernel call per level (codes stay shared).
    """
    p = codes_t.shape[0] - 1
    tab = route_table(best_feat, best_bin, p)
    g = _route_vmappable(backend == "pallas_interpret")
    return g(codes_t, ids[None], tab[None])[0]

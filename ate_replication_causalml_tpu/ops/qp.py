"""Graph-form ADMM QP solver — TPU-native replacement for the native QP
solvers behind ``balanceHD::residualBalance.ate`` (``ate_functions.R:393-398``):
``quadprog::solve.QP`` (Goldfarb–Idnani dual active-set, Fortran) and
``pogs`` (graph-form ADMM, C++/CUDA — the optimizer the reference driver
selects, ``ate_replication.Rmd:243``).

The balancing problem (Athey–Imbens–Wager approximate residual balancing):

    minimize   zeta * ||gamma||_2^2  +  (1 - zeta) * || X^T gamma - m ||_inf^2
    subject to sum(gamma) = 1,   0 <= gamma_i <= ub

POGS poses this in graph form — min f(z) + g(gamma) s.t. z = X^T gamma with
f(z) = (1-zeta)||z - m||_inf^2 and g(gamma) = zeta||gamma||_2^2 + I_C(gamma)
— and alternates proximal steps with a projection onto the graph
{(gamma, z) : z = X^T gamma}. That maps perfectly onto TPU:

  * both prox operators reduce to elementwise clips plus a scalar
    root-find (fixed-iteration bisection under ``lax`` — no data-dependent
    Python control flow);
  * the graph projection is, via Woodbury, one k x k Cholesky factor
    (k = #covariates, tiny) plus two MXU matmuls per iteration;
  * the whole solve is a single ``lax.while_loop`` under ``jit`` —
    batched/vmapped solves (one per treatment arm) share the compiled
    kernel.

Everything here is generic: ``admm_affine_qp`` solves
min f(z) + g(gamma) s.t. z = A gamma for this (f, g) family and is reused
by the balancing estimator for both treatment arms.
"""

from __future__ import annotations

# This module is the repo's ONE sanctioned f64 island: balance_qp_x64
# forces float64 under a local enable_x64() scope regardless of the
# session policy (ADMM dual updates floor at ~1e-3 residuals in f32 —
# see its docstring for the measurements). The literal jnp.float64
# casts are that contract, not drift.
# graftlint: disable-file=JGL004

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ate_replication_causalml_tpu.ops.linalg import _PREC

_BISECT_ITERS = 64
# Iterations during which the ADMM rho may adapt; frozen afterwards so
# the fixed-rho convergence guarantee applies to the tail (Boyd §3.4.1).
# The notebook-scale arms converge at ~160 iterations with adaptation
# live the whole way — 500 leaves ample headroom.
_ADAPT_ITERS = 500


def project_capped_simplex(v: jax.Array, ub: float | jax.Array = jnp.inf) -> jax.Array:
    """Euclidean projection onto {g : sum(g) = 1, 0 <= g_i <= ub}.

    Solved through the scalar dual: g_i(nu) = clip(v_i - nu, 0, ub) with
    sum g_i(nu) = 1; the sum is nonincreasing in nu, so ``nu`` is found by
    fixed-iteration bisection (XLA-friendly, fully vectorized).
    """
    v = jnp.asarray(v)
    ub = jnp.asarray(ub, v.dtype)
    # sum at nu = min(v) - ub is >= min(n*ub, ...) >= 1 for feasible ub;
    # sum at nu = max(v) is 0 <= 1.
    lo = jnp.min(v) - jnp.minimum(ub, 1.0) - 1.0
    hi = jnp.max(v)

    def body(_, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        s = jnp.sum(jnp.clip(v - mid, 0.0, ub))
        too_big = s > 1.0
        return (jnp.where(too_big, mid, lo), jnp.where(too_big, hi, mid))

    lo, hi = lax.fori_loop(0, _BISECT_ITERS, body, (lo, hi))
    nu = 0.5 * (lo + hi)
    return jnp.clip(v - nu, 0.0, ub)


def prox_sq_inf_norm(d: jax.Array, scale: jax.Array) -> jax.Array:
    """prox of q -> scale * ||q||_inf^2 at point ``d``:
    argmin_q scale*||q||_inf^2 + 0.5*||q - d||^2.

    The minimizer clips ``d`` to [-t, t] where t >= 0 solves the monotone
    scalar equation 2*scale*t = sum_i (|d_i| - t)_+ — again bisection.
    """
    a = jnp.abs(d)
    hi0 = jnp.max(a)

    def body(_, bounds):
        lo, hi = bounds
        t = 0.5 * (lo + hi)
        resid = 2.0 * scale * t - jnp.sum(jnp.maximum(a - t, 0.0))
        # resid < 0: t too small -> move lo up.
        return (jnp.where(resid < 0, t, lo), jnp.where(resid < 0, hi, t))

    lo, hi = lax.fori_loop(0, _BISECT_ITERS, body, (jnp.zeros_like(hi0), hi0))
    t = 0.5 * (lo + hi)
    return jnp.clip(d, -t, t)


class QpSolution(NamedTuple):
    gamma: jax.Array        # (n,) balancing weights
    z: jax.Array            # (k,) = X^T gamma at the solution
    primal_resid: jax.Array
    dual_resid: jax.Array
    iters: jax.Array


def balance_qp(
    x: jax.Array,
    target: jax.Array,
    zeta: float = 0.5,
    ub: float = jnp.inf,
    rho: float = 1.0,
    max_iters: int = 4000,
    tol: float = 1e-7,
) -> QpSolution:
    """Solve the approximate-balancing QP (module docstring) by graph-form
    ADMM.

    ``x`` is (n, k) — the arm's covariate matrix; ``target`` is (k,) — the
    population covariate mean to balance toward. Returns weights on the
    arm's rows summing to 1.
    """
    x = jnp.asarray(x)
    n, k = x.shape
    m = jnp.asarray(target, x.dtype)
    zeta = jnp.asarray(zeta, x.dtype)
    eta = 1.0 - zeta

    # Woodbury factor for the graph projection:
    # (I_n + X X^T)^{-1} c = c - X (I_k + X^T X)^{-1} X^T c.
    gram = jnp.eye(k, dtype=x.dtype) + jnp.matmul(x.T, x, precision=_PREC)
    chol = jnp.linalg.cholesky(gram)

    def graph_project(c, d):
        rhs = c + jnp.matmul(x, d, precision=_PREC)
        t = jax.scipy.linalg.cho_solve(
            (chol, True), jnp.matmul(x.T, rhs, precision=_PREC)
        )
        gamma = rhs - jnp.matmul(x, t, precision=_PREC)
        return gamma, jnp.matmul(x.T, gamma, precision=_PREC)

    def prox_g(v, rho_c):
        # argmin zeta*||g||^2 + rho/2*||g - v||^2 + I_C(g)
        return project_capped_simplex(rho_c * v / (2.0 * zeta + rho_c), ub)

    def prox_f(v, rho_c):
        # argmin eta*||z - m||_inf^2 + rho/2*||z - v||^2
        return m + prox_sq_inf_norm(v - m, eta / rho_c)

    # Freeze point for rho adaptation: never later than half the
    # iteration budget, so a short-budget caller (max_iters <= 500)
    # still gets a fixed-rho tail and the convergence-guarantee
    # argument in ``body`` applies in every regime.
    adapt_iters = min(_ADAPT_ITERS, max_iters // 2)

    def cond(state):
        _, _, _, _, _, rp, rd, i = state
        return jnp.logical_and(i < max_iters, jnp.maximum(rp, rd) > tol)

    def body(state):
        g, z, tg, tz, rho_c, _, _, i = state
        g_half = prox_g(g - tg, rho_c)
        z_half = prox_f(z - tz, rho_c)
        g_new, z_new = graph_project(g_half + tg, z_half + tz)
        tg_new = tg + g_half - g_new
        tz_new = tz + z_half - z_new
        rp = jnp.sqrt(
            jnp.sum((g_half - g_new) ** 2) + jnp.sum((z_half - z_new) ** 2)
        )
        # True dual residual carries the rho factor (with scaled duals
        # s^k = rho * (iterate difference)); at the fixed rho = 1 this is
        # exactly the old definition.
        rd = rho_c * jnp.sqrt(
            jnp.sum((g_new - g) ** 2) + jnp.sum((z_new - z) ** 2)
        )
        # Residual-balancing rho adaptation (Boyd et al. §3.4.1): a fixed
        # rho left the notebook-scale arms >1e-4 away after 12k
        # iterations; doubling/halving toward balanced residuals (scaled
        # duals rescaled by rho_old/rho_new) converges the same arms in
        # a few hundred. Adaptation FREEZES after ``adapt_iters`` (Boyd's
        # recipe): with rho eventually fixed, the standard fixed-rho ADMM
        # convergence guarantee applies from that point on — an
        # indefinitely oscillating rho has no such guarantee.
        adapt = i < adapt_iters
        scale = jnp.where(
            adapt & (rp > 10.0 * rd), 2.0,
            jnp.where(adapt & (rd > 10.0 * rp), 0.5, 1.0),
        )
        rho_new = jnp.clip(rho_c * scale, 1e-4, 1e6)
        ratio = rho_c / rho_new
        return (
            g_new, z_new, tg_new * ratio, tz_new * ratio, rho_new, rp, rd, i + 1
        )

    g0 = jnp.full((n,), 1.0 / n, x.dtype)
    z0 = jnp.matmul(x.T, g0, precision=_PREC)
    inf = jnp.asarray(jnp.inf, x.dtype)
    state = (
        g0, z0, jnp.zeros_like(g0), jnp.zeros_like(z0),
        jnp.asarray(rho, x.dtype), inf, inf, jnp.array(0),
    )
    g, z, _, _, _, rp, rd, iters = lax.while_loop(cond, body, state)
    # Final polish: report the feasible iterate (projection of the prox
    # point onto the constraint set) so downstream sums are exact.
    g = project_capped_simplex(g, ub)
    return QpSolution(
        gamma=g, z=jnp.matmul(x.T, g, precision=_PREC),
        primal_resid=rp, dual_resid=rd, iters=iters,
    )


@functools.lru_cache(maxsize=32)
def _balance_qp_jitted_x64(max_iters):
    # Keyed on the ONE graph-shaping scalar (``max_iters`` bounds the
    # while_loop's adapt freeze point, a Python computation); the pure
    # numeric scalars (zeta, ub, rho, tol) enter as traced operands, so
    # a sweep over many configurations reuses one executable per
    # (max_iters, input shape) instead of thrashing the cache
    # (ADVICE r4: >32 distinct scalar combos recompiled on every
    # eviction cycle).
    def run(x, target, zeta, ub, rho, tol):
        return balance_qp(
            x, target, zeta=zeta, ub=ub, rho=rho, max_iters=max_iters, tol=tol
        )

    return jax.jit(run)


def balance_qp_x64(
    x,
    target,
    zeta: float = 0.5,
    ub: float = float("inf"),
    rho: float = 1.0,
    max_iters: int = 4000,
    tol: float = 1e-7,
) -> QpSolution:
    """:func:`balance_qp` forced to float64 regardless of the global x64
    flag — the production configuration for the balancing weights.

    The weights feed a plug-in estimator, and quadprog's dual active-set
    (the reference's solver) returns KKT-exact solutions; ADMM needs the
    1e-7 stationarity tolerance to match it (tests/test_qp_balance.py's
    scipy oracle). In f32 the residuals FLOOR around 1e-3 at notebook
    scale — the dual updates accumulate increments below f32 resolution
    and more iterations make the iterate worse, not better (measured:
    objective 1.9e-4 at 12k f32 iterations vs 5.8e-5 at 162 f64
    iterations with the adaptive rho). TPU executes f64 by emulation —
    slow per FLOP, irrelevant for this tiny one-shot (n_arm × 21) solve,
    and far cheaper than the 12k-iteration f32 crawl it replaces.
    """
    x64 = getattr(jax, "enable_x64", None)
    if x64 is None:  # pre-top-level-API jax
        from jax.experimental import enable_x64 as x64
    with x64():
        sol = _balance_qp_jitted_x64(int(max_iters))(
            jnp.asarray(x, jnp.float64),
            jnp.asarray(target, jnp.float64),
            jnp.float64(zeta),
            jnp.float64(ub),
            jnp.float64(rho),
            jnp.float64(tol),
        )
        jax.block_until_ready(sol)
    return sol


def balance_objective(x, target, gamma, zeta=0.5):
    """The balancing objective at ``gamma`` (for tests/diagnostics)."""
    imbalance = jnp.matmul(x.T, gamma, precision=_PREC) - jnp.asarray(target)
    return zeta * jnp.sum(gamma**2) + (1.0 - zeta) * jnp.max(jnp.abs(imbalance)) ** 2

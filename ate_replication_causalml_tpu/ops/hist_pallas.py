"""Pallas TPU kernel: weighted bin-histogram build for forest split search.

This is THE hot op of both forest engines (SURVEY.md §2.3 — the Fortran
CART core behind ``randomForest`` and the grf C++ honest-split core).
Each tree level needs, per (node, feature, bin) cell, the total bootstrap
weight and the total weighted target:

    hist[k, m, f, b] = Σ_rows  w[k, row] · 1[node(row) = m] · 1[code(row, f) = b]

The pure-XLA formulation (models/forest.py) computes this as
``(node_onehot · w)ᵀ @ bin_onehot`` with the bin one-hot materialised
once in HBM — fine at the reference's 8.9k rows, but the one-hot is
``n × p·n_bins`` f32, i.e. **~5.4 GB at the 1M-row north-star scale**
(BASELINE.md). This kernel never materialises it: rows stream through
VMEM in tiles, both one-hots are built tile-wise with ``broadcasted_iota``
comparisons (VPU), and the per-tile contraction runs on the MXU,
accumulating into a VMEM-resident histogram block across the sequential
grid. HBM traffic drops from O(n·p·n_bins) to O(n·p) — the raw codes.

Layout notes (pallas_guide.md):
  * last dim of every VMEM block is a multiple of 128 lanes: the
    histogram's trailing axis is ``p·n_bins`` (padded to 128); the
    row-tile axis (sublanes) is the contraction axis of the MXU matmul;
  * iota is always ≥2D (``broadcasted_iota``);
  * the output BlockSpec maps every grid step to block (0, 0, 0) so the
    accumulator stays VMEM-resident; it is zeroed at step 0 via
    ``pl.when`` (standard sequential-grid accumulation pattern).

CPU tests run the same kernel with ``interpret=True`` (tests/conftest.py
forces the CPU backend); ``backend="auto"`` picks the compiled kernel on
TPU and the chunked-XLA fallback elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# Row count above which the streaming Pallas kernel beats the XLA
# contraction on TPU. Round-3 within-ONE-window sweep (v5-lite,
# `bench.py --hist-ab`: whole classifier-tree ms/tree, p=21, 64 bins,
# depth 9 — round 2's 400k figure mixed windows with 4× tunnel
# variance):
#
#   rows   9k   30k   100k   200k   400k    1M
#   xla    4.5  6.8   23.3   62.7   187.7  798.6
#   pallas 4.6  8.4   23.2   41.7    82.6  205.0
#   bf16   6.2 10.1   22.1   41.3    80.3  201.6
#
# Crossover ≈ 100k (a wash there; kernel 1.5× at 200k, 3.9× at 1M —
# the XLA path's scatter-built bin one-hot grows superlinearly in HBM
# cost while the kernel streams codes through VMEM). bf16 only wins
# past the crossover, which is exactly where 'auto' can pick it.
_PALLAS_ROWS_THRESHOLD = 150_000


def resolve_hist_backend(
    backend: str,
    allow_onehot: bool = True,
    n_rows: int | None = None,
    n_bins: int | None = None,
    integer_weights: bool = False,
) -> str:
    """The single place the 'auto' policy lives.

    On TPU, 'auto' picks the XLA contraction at reference-like row
    counts and the streaming Pallas kernel past ``_PALLAS_ROWS_THRESHOLD``
    (see the measured crossover table above). Pass ``n_rows`` to enable
    the switch — without it 'auto' stays on the XLA path, which is fine
    at reference scale but ~4× slower than the kernel by 1M rows, so
    large-row callers should always pass it. The kernel only supports
    ``n_bins ≤ 128`` (one feature per 128-lane block minimum), so 'auto'
    also needs ``n_bins`` to choose it — wider binnings stay on XLA,
    which handles any width. Both are bit-exact to each other
    (tests/test_hist_pallas.py) and remain explicitly selectable. On CPU
    the forest engines pass ``allow_onehot=True`` to use the shared
    one-hot matmul (fastest at reference scale).

    ``integer_weights=True`` declares every weight vector integer-valued
    in [-256, 256] (the classifier forests: Poisson counts and counts·y
    with y ∈ {0,1}) — there the bf16 kernel is bit-exact and the fastest
    backend everywhere past the crossover (see table), so 'auto'
    upgrades the kernel pick to ``pallas_bf16``. The caller owns the
    declaration; it is asserted nowhere on the device path."""
    if backend == "auto":
        if jax.default_backend() == "tpu":
            if (
                n_rows is not None
                and n_rows >= _PALLAS_ROWS_THRESHOLD
                and n_bins is not None
                and n_bins <= _LANES
            ):
                return "pallas_bf16" if integer_weights else "pallas"
            return "xla"
        return "onehot" if allow_onehot else "xla"
    return backend


def _hist_kernel(codes_ref, node_ref, w_ref, out_ref, *, n_weights, max_nodes,
                 bw, f_pb, n_bins, in_dtype):
    """One grid step: fold one row tile into one group of feature blocks.

    Grid is (p_groups, n_tiles) with the row-tile axis innermost, so the
    (n_weights·max_nodes, bw·LANES) output block stays VMEM-resident
    across the whole row sweep of its feature group (zeroed at tile 0).
    ``bw`` feature blocks (128 lanes each) per step amortizes the
    per-step grid overhead; ``bw`` is capped by the scoped-VMEM budget.

    codes_ref: (1, TILE, bw·f_pb) int32 — this group's features only
    node_ref:  (TILE, 1)   int32        — node id per row (padded: -1)
    w_ref:     (n_weights, TILE) f32    — weight vectors (padded: 0)
    out_ref:   (1, n_weights·max_nodes, bw·LANES) f32 — group's slice
    """
    tile = codes_ref.shape[1]

    @pl.when(pl.program_id(1) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    # Bin one-hot per 128-lane block, concatenated along lanes. Each
    # feature is compared only against its own block's 128 lanes —
    # pb_pad/LANES (~10× at the GGL shape) less VPU compare work than
    # v1's full-width compares — and each block's lane iota is local, so
    # the compare constant is just code + f·n_bins < 128.
    lane_iota = lax.broadcasted_iota(jnp.int32, (tile, _LANES), 1)
    pieces = []
    for g in range(bw):
        oh_g = jnp.zeros((tile, _LANES), in_dtype)
        for f in range(f_pb):  # static unroll — f_pb = LANES // n_bins
            flat = codes_ref[0, :, g * f_pb + f : g * f_pb + f + 1] + f * n_bins
            oh_g = oh_g + (lane_iota == flat).astype(in_dtype)
        pieces.append(oh_g)
    bin_oh = pieces[0] if bw == 1 else jnp.concatenate(pieces, axis=1)

    # Node one-hot: (TILE, max_nodes). Padded rows carry node=-1 → all 0,
    # which also kills the padded rows' garbage bin one-hot.
    node_iota = lax.broadcasted_iota(jnp.int32, (tile, max_nodes), 1)
    node_oh = (node_ref[:] == node_iota).astype(in_dtype)

    # Weighted node one-hots for every weight vector, stacked on the
    # sublane axis: (n_weights·max_nodes, TILE) @ (TILE, bw·LANES) on
    # the MXU, f32 accumulation regardless of in_dtype.
    lhs = jnp.concatenate(
        [node_oh * w_ref[k, :][:, None].astype(in_dtype) for k in range(n_weights)],
        axis=1,
    )  # (TILE, n_weights*max_nodes)
    out_ref[0] += lax.dot_general(
        lhs,
        bin_oh,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


_VMEM_BUDGET = 100 * 1024 * 1024  # raise Mosaic's 16 MB scoped default


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "n_bins", "tile", "bw", "interpret", "bf16"),
)
def bin_histogram_pallas(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    tile: int = 2048,
    bw: int | None = None,
    interpret: bool = False,
    bf16: bool = False,
) -> jax.Array:
    """Weighted (node, feature, bin) histograms via the Pallas kernel.

    Args:
      codes: (n, p) int32 bin codes in [0, n_bins); n_bins ≤ 128.
      node_of_row: (n,) int32 node ids in [0, max_nodes); rows with ids
        outside the range contribute nothing.
      weights: (K, n) f32 — e.g. (counts, counts·y) for the classifier,
        (counts, counts·ρ) for the causal forest's gradient splits.
      tile: rows per grid step.
      bw: feature blocks (128 lanes each) per grid step; default covers
        all of p in one step (grid = row tiles only).
      bf16: feed the MXU bf16 operands (f32 accumulation). Bit-exact
        whenever the weights are integer-valued in [-256, 256] (one-hots
        are exact 0/1 and small-int bf16 products are exact in f32);
        lossy for general float weights — callers opt in through the
        ``backend="pallas_bf16"`` dispatch string.

    Returns:
      (K, max_nodes, p, n_bins) f32.
    """
    n, p = codes.shape
    k_w = weights.shape[0]
    if n_bins > _LANES:
        raise ValueError(f"n_bins={n_bins} > {_LANES} unsupported")
    # Feature-block the (feat, bin) axis: f_pb features per 128-lane
    # block. Lane layout inside a block is [f_pb × n_bins] + dead pad.
    f_pb = _LANES // n_bins
    p_blocks = -(-p // f_pb)
    if bw is None:
        bw = p_blocks
    bw = min(bw, p_blocks)
    p_groups = -(-p_blocks // bw)
    p_pad = p_groups * bw * f_pb
    n_pad = _round_up(max(n, tile), tile)

    codes = jnp.pad(codes, ((0, n_pad - n), (0, p_pad - p)))
    # (p_groups, n, bw·f_pb): each grid step DMAs one contiguous
    # (tile, bw·f_pb) slab of its own feature group (Mosaic requires the
    # block's trailing dim to be lane-aligned or the full array dim).
    codes_b = codes.reshape(n_pad, p_groups, bw * f_pb).transpose(1, 0, 2)
    node2d = jnp.pad(
        node_of_row.astype(jnp.int32)[:, None], ((0, n_pad - n), (0, 0)),
        constant_values=-1,
    )
    weights = jnp.pad(weights.astype(jnp.float32), ((0, 0), (0, n_pad - n)))

    grid = (p_groups, n_pad // tile)  # row tiles innermost: accumulation
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel, n_weights=k_w, max_nodes=max_nodes,
            bw=bw, f_pb=f_pb, n_bins=n_bins,
            in_dtype=jnp.bfloat16 if bf16 else jnp.float32,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile, bw * f_pb), lambda j, i: (j, i, 0)),
            pl.BlockSpec((tile, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((k_w, tile), lambda j, i: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, k_w * max_nodes, bw * _LANES), lambda j, i: (j, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (p_groups, k_w * max_nodes, bw * _LANES), jnp.float32
        ),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(vmem_limit_bytes=_VMEM_BUDGET),
    )(codes_b, node2d, weights)
    # (p_groups, K·M, bw·LANES) → per 128-lane block keep the live
    # f_pb·n_bins lanes, then restore feature order.
    out = out.reshape(p_groups, k_w * max_nodes, bw, _LANES)[..., : f_pb * n_bins]
    out = out.transpose(1, 0, 2, 3).reshape(k_w, max_nodes, p_pad, n_bins)
    return out[:, :, :p, :]


@functools.partial(jax.jit, static_argnames=("max_nodes", "n_bins", "row_chunk"))
def bin_histogram_xla(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    row_chunk: int = 65536,
) -> jax.Array:
    """Chunked-XLA fallback with the same contract as the kernel: scans
    row chunks so the bin one-hot never exceeds ``row_chunk × p·n_bins``
    (memory-safe at 1M rows, unlike the monolithic one-hot)."""
    n, p = codes.shape
    k_w = weights.shape[0]
    n_pad = _round_up(max(n, 1), row_chunk) if n > row_chunk else n
    if n_pad != n:
        codes = jnp.pad(codes, ((0, n_pad - n), (0, 0)))
        node_of_row = jnp.pad(node_of_row, (0, n_pad - n), constant_values=-1)
        weights = jnp.pad(weights, ((0, 0), (0, n_pad - n)))
    if n_pad <= row_chunk:
        return _hist_chunk_xla(codes, node_of_row, weights, max_nodes, n_bins)

    n_chunks = n_pad // row_chunk
    codes_c = codes.reshape(n_chunks, row_chunk, p)
    node_c = node_of_row.reshape(n_chunks, row_chunk)
    w_c = weights.reshape(k_w, n_chunks, row_chunk).transpose(1, 0, 2)

    def step(acc, chunk):
        c, m, w = chunk
        return acc + _hist_chunk_xla(c, m, w, max_nodes, n_bins), None

    init = jnp.zeros((k_w, max_nodes, p, n_bins), jnp.float32)
    acc, _ = lax.scan(step, init, (codes_c, node_c, w_c))
    return acc


def _hist_chunk_xla(codes, node_of_row, weights, max_nodes, n_bins):
    n, p = codes.shape
    k_w = weights.shape[0]
    flat = codes + jnp.arange(p, dtype=jnp.int32)[None, :] * n_bins
    bin_oh = (
        jnp.zeros((n, p * n_bins), jnp.float32)
        .at[jnp.arange(n)[:, None], flat]
        .set(1.0)
    )
    node_oh = jax.nn.one_hot(node_of_row, max_nodes, dtype=jnp.float32)
    lhs = (node_oh[None, :, :] * weights[:, :, None]).reshape(k_w, n, max_nodes)
    out = jnp.einsum("knm,nb->kmb", lhs, bin_oh)
    return out.reshape(k_w, max_nodes, p, n_bins)


def bin_histogram(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    backend: str = "auto",
) -> jax.Array:
    """Dispatch: compiled Pallas kernel on TPU, chunked XLA elsewhere.

    ``backend``: "auto" | "pallas" | "pallas_bf16" | "pallas_interpret"
    | "xla". ``pallas_bf16`` feeds the MXU bf16 operands (f32
    accumulation) — bit-exact only for integer-valued weights (see
    :func:`bin_histogram_pallas`); callers opt in per forest via their
    ``hist_backend`` argument.
    """
    backend = resolve_hist_backend(backend, allow_onehot=False)
    if backend == "pallas":
        return bin_histogram_pallas(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins
        )
    if backend == "pallas_bf16":
        return bin_histogram_pallas(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins,
            bf16=True,
        )
    if backend == "pallas_interpret":
        return bin_histogram_pallas(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins,
            interpret=True,
        )
    if backend == "xla":
        return bin_histogram_xla(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins
        )
    raise ValueError(f"unknown histogram backend {backend!r}")

"""Pallas TPU kernel: weighted bin-histogram build for forest split search.

This is THE hot op of both forest engines (SURVEY.md §2.3 — the Fortran
CART core behind ``randomForest`` and the grf C++ honest-split core).
Each tree level needs, per (node, feature, bin) cell, the total bootstrap
weight and the total weighted target:

    hist[k, m, f, b] = Σ_rows  w[k, row] · 1[node(row) = m] · 1[code(row, f) = b]

The pure-XLA formulation (models/forest.py) computes this as
``(node_onehot · w)ᵀ @ bin_onehot`` with the bin one-hot materialised
once in HBM — fine at the reference's 8.9k rows, but the one-hot is
``n × p·n_bins`` f32, i.e. **~5.4 GB at the 1M-row north-star scale**
(BASELINE.md). This kernel never materialises it: rows stream through
VMEM in tiles, both one-hots are built tile-wise with ``broadcasted_iota``
comparisons (VPU), and the per-tile contraction runs on the MXU,
accumulating into a VMEM-resident histogram block across the sequential
grid. HBM traffic drops from O(n·p·n_bins) to O(n·p) — the raw codes.

Layout notes (pallas_guide.md):
  * last dim of every VMEM block is a multiple of 128 lanes: the
    histogram's trailing axis is ``p·n_bins`` (padded to 128); the
    row-tile axis (sublanes) is the contraction axis of the MXU matmul;
  * iota is always ≥2D (``broadcasted_iota``);
  * the output BlockSpec maps every grid step to block (0, 0, 0) so the
    accumulator stays VMEM-resident; it is zeroed at step 0 via
    ``pl.when`` (standard sequential-grid accumulation pattern).

CPU tests run the same kernel with ``interpret=True`` (tests/conftest.py
forces the CPU backend); ``backend="auto"`` picks the compiled kernel on
TPU and the chunked-XLA fallback elsewhere.

Round 10 (ISSUE 10) adds a second kernel FORMULATION orthogonal to the
backend: the **in-kernel stable-bin partition** mode
(:func:`_hist_kernel_batched_partition`). The dense contraction pays
every node for every row (useful-FLOP fraction ~1/2^d at depth d); the
partition mode regroups each tile's rows by node id in VMEM (stable —
row order preserved within a node, which preserves f32 accumulation
order) and contracts node-pure 8-row blocks, making FLOPs proportional
to rows with a depth-independent useful fraction. The per-width choice
is the ``ATE_TPU_HIST_MODE`` policy (:func:`resolve_hist_mode` /
:func:`mode_for_width`): dense below the modeled crossover (width 32
for the K=2 classifier, 16 for the K=5 causal engine), partition past
it. ``bench.py --hist-ab`` regenerates the committed per-level
A/B + FLOP-model record (HIST_AB.json).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ate_replication_causalml_tpu.ops.pack import PACK_RADIX, PACK_SLOTS

# Renamed TPUCompilerParams -> CompilerParams across jax releases; one
# local alias (imported by tree_pallas / scripts) serves both without
# mutating the jax module.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None)
if _COMPILER_PARAMS is None:
    _COMPILER_PARAMS = pltpu.TPUCompilerParams


_LANES = 128


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


# Row count above which the streaming Pallas kernel beats the XLA
# contraction on TPU. Measured round-3 second pass (within-ONE-window,
# `bench.py --hist-ab`: whole classifier-tree ms/tree, p=21, 64 bins,
# depth 9, TPU v5e — the DENSE kernel mode; partition-mode TPU
# wall-clock is still TPU-blocked, see below):
#
#   rows    9k   15k   30k   60k   100k   200k    1M
#   xla     5.3  4.9   6.1   8.4   23.3   64.1   ~800 (pre-batching)
#   pallas  4.5  5.2   5.3   7.9    9.7   19.2    —
#   bf16    4.7  4.8   4.5   6.7   10.1   19.1   82.8 (whole tree)
#
# The batched kernel is at-or-better EVERYWHERE measured — including
# the reference's own ~9k-row biased sample (the pre-batching table had
# XLA winning below ~100k; batching amortized the kernel's fixed
# per-row-stream work across the tree chunk). The threshold now only
# guards the untested sub-9k regime — and it bounds the rows the kernel
# actually streams: both streaming growers run mask mode on the FULL n
# they resolve with (the causal subsample is zero-weighted, not
# gathered). The XLA path's scatter-built bin
# one-hot still degrades superlinearly with rows, so the kernel's edge
# grows with n (2.3× at 100k, 3.4× at 200k, ~10× at 1M).
#
# Round 10 (`bench.py --hist-ab`, regenerated — HIST_AB.json is the
# committed record): the harness now also A/Bs the KERNEL MODE per
# level with the analytic FLOP model. At the K=2 classifier shape
# (p=21, 64 bins) the modeled dense:partition total-FLOP ratio by
# kernel width is
#
#   width     1     2     4     8    16    32    64   128
#   ratio  0.05  0.11  0.21  0.42  0.81  1.54  2.77  4.61
#
# — the auto crossover (partition_crossover_width) sits at 32 for K=2
# and 16 for the K=5 causal engine; dense's useful-FLOP fraction decays
# like 1/2^d while partition's is depth-independent. On this CPU image
# the mode wall-times are interpreter-dominated (the record says so in
# its `backend` field); the MXU wall-clock consequence is TPU-blocked
# and belongs to the next hardware round.
_PALLAS_ROWS_THRESHOLD = 8_192


def resolve_hist_backend(
    backend: str,
    allow_onehot: bool = True,
    n_rows: int | None = None,
    n_bins: int | None = None,
    integer_weights: bool = False,
    allow_lossy_bf16: bool = False,
) -> str:
    """The single place the 'auto' policy lives.

    On TPU, 'auto' picks the tree-batched streaming Pallas kernel from
    ``_PALLAS_ROWS_THRESHOLD`` (~8k — at-or-better than the XLA
    contraction at every measured size, ~10× by 1M rows; see the table
    above) and the XLA contraction only below it (the untested sub-9k
    regime). Pass ``n_rows`` to enable the switch — without it 'auto'
    stays on the XLA path, which degrades superlinearly with rows, so
    every sizable caller should pass it. The kernel only supports
    ``n_bins ≤ 128`` (one feature per 128-lane block minimum), so 'auto'
    also needs ``n_bins`` to choose it — wider binnings stay on XLA,
    which handles any width. Both are bit-exact to each other
    (tests/test_hist_pallas.py) and remain explicitly selectable. On CPU
    the forest engines pass ``allow_onehot=True`` to use the shared
    one-hot matmul (fastest at reference scale).

    ``integer_weights=True`` declares every weight vector integer-valued
    in [-256, 256] (the classifier forests: Poisson counts and counts·y
    with y ∈ {0,1}) — there the bf16 kernel is bit-exact (asserted in
    tests/test_hist_pallas.py). Through round 4, 'auto' upgraded such
    fits to ``pallas_bf16``; round 5 dropped the upgrade: the measured
    kernel delta is noise on this chip generation (see table — the MXU
    runs bf16 passes for both operand dtypes, and after the
    transposed-lhs rewrite the kernel is fixed-cost-bound, not
    MXU-bound), while the split static made the flagship's binary-W and
    continuous-Y nuisance fits compile two ~35 s executables where one
    serves both (integer sums are exact in the f32 kernel too). The
    flag is retained so call sites still document the invariant and a
    future MXU-bound regime can re-enable the upgrade;
    ``pallas_bf16`` stays explicitly selectable.

    ``allow_lossy_bf16=True`` upgrades to the bf16 kernel even for
    FLOAT weights: inputs are rounded to bf16 (≤0.4% relative) before
    exact f32 accumulation — statistically tolerable for split search
    (coarser than the quantile binning itself). No caller opts in today:
    after the transposed-lhs rewrite the kernel is not MXU-bound, so the
    rounding was measured to buy ≤1% — kept for a future MXU-bound
    regime (wider feature sets, more channels)."""
    if backend == "auto":
        if jax.default_backend() == "tpu":
            if (
                n_rows is not None
                and n_rows >= _PALLAS_ROWS_THRESHOLD
                and n_bins is not None
                and n_bins <= _LANES
            ):
                if allow_lossy_bf16:
                    return "pallas_bf16"
                return "pallas"
            return "xla"
        return "onehot" if allow_onehot else "xla"
    return backend


def _build_bin_oh(codes, bw, f_pb, n_bins, in_dtype):
    """Tile-local bin one-hot, (rows, bw·LANES) from a (rows, bw·f_pb)
    code array: one 128-lane block per ``f_pb`` features, concatenated
    along lanes. Each feature is compared only against its own block's
    128 lanes — ~10× less VPU compare work at the GGL shape than
    full-width compares. The kernel wrappers pre-offset the codes
    (code + (f mod f_pb)·n_bins, one fused XLA add per kernel call) so
    the per-step work is exactly one compare + accumulate per feature.
    Shared by every kernel (dense and partition — they must stay
    bit-identical per row; tests assert it)."""
    rows = codes.shape[0]
    lane_iota = lax.broadcasted_iota(jnp.int32, (rows, _LANES), 1)
    pieces = []
    for g in range(bw):
        oh_g = jnp.zeros((rows, _LANES), in_dtype)
        for f in range(f_pb):  # static unroll — f_pb = LANES // n_bins
            flat = codes[:, g * f_pb + f : g * f_pb + f + 1]
            oh_g = oh_g + (lane_iota == flat).astype(in_dtype)
        pieces.append(oh_g)
    return pieces[0] if bw == 1 else jnp.concatenate(pieces, axis=1)


def _hist_kernel(codes_ref, node_ref, w_ref, out_ref, *, n_weights, max_nodes,
                 bw, f_pb, n_bins, in_dtype):
    """One grid step: fold one row tile into one group of feature blocks.

    Grid is (p_groups, n_tiles) with the row-tile axis innermost, so the
    (n_weights·max_nodes, bw·LANES) output block stays VMEM-resident
    across the whole row sweep of its feature group (zeroed at tile 0).
    ``bw`` feature blocks (128 lanes each) per step amortizes the
    per-step grid overhead; ``bw`` is capped by the scoped-VMEM budget.

    codes_ref: (1, TILE, bw·f_pb) int32 — this group's features only
    node_ref:  (TILE, 1)   int32        — node id per row (padded: -1)
    w_ref:     (n_weights, TILE) f32    — weight vectors (padded: 0)
    out_ref:   (1, n_weights·max_nodes, bw·LANES) f32 — group's slice
    """
    tile = codes_ref.shape[1]

    @pl.when(pl.program_id(1) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    bin_oh = _build_bin_oh(codes_ref[0], bw, f_pb, n_bins, in_dtype)

    # Node one-hot: (TILE, max_nodes). Padded rows carry node=-1 → all 0,
    # which also kills the padded rows' garbage bin one-hot.
    node_iota = lax.broadcasted_iota(jnp.int32, (tile, max_nodes), 1)
    node_oh = (node_ref[:] == node_iota).astype(in_dtype)

    # Weighted node one-hots for every weight vector, stacked on the
    # sublane axis: (n_weights·max_nodes, TILE) @ (TILE, bw·LANES) on
    # the MXU, f32 accumulation regardless of in_dtype.
    lhs = jnp.concatenate(
        [node_oh * w_ref[k, :][:, None].astype(in_dtype) for k in range(n_weights)],
        axis=1,
    )  # (TILE, n_weights*max_nodes)
    out_ref[0] += lax.dot_general(
        lhs,
        bin_oh,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _hist_kernel_batched(codes_ref, node_ref, w_ref, out_ref, *, n_weights,
                         n_trees, max_nodes, bw, f_pb, n_bins, in_dtype,
                         shared_weights=False):
    """One grid step of the TREE-BATCHED kernel: fold one row tile into
    one feature group's histograms for ``n_trees`` trees at once.

    Motivation (round-3 on-chip ablation, scripts/profile_grow.py): at
    1M rows the per-level kernel cost is ~90% LEVEL-INVARIANT fixed work
    — the bin one-hot VPU build, the codes DMA, and per-grid-step
    overheads — not the MXU matmul (a level-0 single-node histogram
    measured ~21 ms vs ~0.2 ms of matmul FLOPs; bf16's 4× MXU peak moved
    the total ~2%). Trees in a grow chunk share ``codes``, so batching
    them into one kernel call amortizes ALL of that fixed work T-fold:
    bin_oh is built once per tile and contracted against every tree's
    weighted node one-hots in a single MXU dot.

    Layout notes vs the unbatched kernel: nodes arrive as (tile, T) and
    weights as (tile, T·K) blocks — row-tile on the SUBLANE axis — so
    per-tree column slices are natural (tile, 1) strips; the unbatched
    kernel's (K, tile) weight block needed a lane→sublane relayout every
    step.

    codes_ref: (1, TILE, bw·f_pb) int32 — this group's features only
    node_ref:  (T, TILE)  int32         — node id per (tree, row); pad -1
    w_ref:     (T·K, TILE) f32          — weights, tree-major; pad 0 —
               or (K, TILE) with ``shared_weights=True``: ONE weight
               stack shared by every tree (round 5 — the causal
               grower's honest/subsample membership rides in the id
               stream, so its five ρ-decomposition channels are
               tree-invariant; sharing kills the (T·K, n) HBM operand
               and its per-level DMA). The per-(tree, channel) products
               are identical either way — w_row is the same (1, TILE)
               sublane slice — so the output is bit-identical to the
               per-tree layout fed with equal rows.
    out_ref:   (1, T·K·max_nodes, bw·LANES) f32
    """
    @pl.when(pl.program_id(1) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    tile = codes_ref.shape[1]
    bin_oh = _build_bin_oh(codes_ref[0], bw, f_pb, n_bins, in_dtype)

    # TRANSPOSED lhs build: the weighted node one-hots live (nodes, TILE)
    # — rows on the LANE axis — so each tree's node-id strip and each
    # weight vector is a natural (1, TILE) sublane slice broadcast DOWN
    # sublanes (cheap replication), never a single-lane slice broadcast
    # ACROSS 128 lanes (a Mosaic relayout per (tree, channel) per step —
    # measured as the dominant dtype-insensitive kernel cost at 1M rows).
    # The dot contracts lhsᵀ's lane axis against bin_oh's sublane axis —
    # the natural A·B MXU form.
    #
    # ONE DOT PER TREE (PR 10): the pre-PR-10 kernel concatenated
    # every tree into a single (T·K·M, TILE) lhs, so the dot's shape —
    # and with it the f32 reduction association XLA/Eigen picks on the
    # interpret (CPU) backend — depended on the BATCH SIZE T. That made
    # "vmap collapse is bit-identical to per-slice calls" false at ulp
    # level for float weight stacks (the known-red
    # test_shared_custom_vmap_collapses). Per-tree (K·M, TILE) dots make
    # every tree's numbers independent of which batch/chunk it rides in:
    # identical inputs through an identical dot shape, whatever T is.
    # Same total MXU work; the MXU's fixed-order accumulation makes the
    # two layouts bit-equal on hardware anyway.
    node_iota_t = lax.broadcasted_iota(jnp.int32, (max_nodes, tile), 0)
    km = n_weights * max_nodes
    for t in range(n_trees):  # static unroll — T is a chunk-sized constant
        node_row = node_ref[t : t + 1, :]                       # (1, TILE)
        node_oh_t = (node_row == node_iota_t).astype(in_dtype)  # (M, TILE)
        lhs_parts = []
        for k in range(n_weights):
            w_base = k if shared_weights else t * n_weights + k
            w_row = w_ref[w_base : w_base + 1, :]
            lhs_parts.append(node_oh_t * w_row.astype(in_dtype))
        lhs_t = (
            lhs_parts[0] if len(lhs_parts) == 1
            else jnp.concatenate(lhs_parts, axis=0)
        )  # (K·max_nodes, TILE)
        out_ref[0, t * km : (t + 1) * km, :] += lax.dot_general(
            lhs_t,
            bin_oh,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


# Row-block granularity of the partition kernel's per-node contraction:
# after the stable in-tile partition, every B-row block is node-PURE, so
# one (K, B) @ (B, lanes) dot per block lands on exactly one node's
# output rows. 8 = one f32 sublane group.
_PART_BLOCK = 8


def _hist_kernel_batched_partition(codes_ref, node_ref, w_ref, out_ref, *,
                                   n_weights, n_trees, max_nodes, bw, f_pb,
                                   n_bins, in_dtype, shared_weights=False,
                                   pack=False):
    """Partition-mode grid step (ISSUE 10): same contract and
    layouts as :func:`_hist_kernel_batched`, different FLOP structure.

    The dense kernel's MXU contraction multiplies every row against the
    one-hot of EVERY node, so at a level with M live nodes only 1/M of
    its FLOPs touch a (row, its-own-node) pair — the useful fraction
    decays like 1/2^d with depth. This kernel instead STABLY partitions
    each row tile by node id in-kernel and then contracts each node's
    rows once:

      1. per-node counts over the (TILE,) node stream → block-aligned
         region offsets (cumulative counts; regions padded to
         ``_PART_BLOCK`` rows, dropped rows — id −1 / out of range — go
         to a trailing trash region);
      2. every row's destination = its region offset + its stable rank
         (count of EARLIER tile rows with the same id — the partition
         preserves row order within a node, which is what preserves the
         f32 accumulation order of each cell);
      3. rows regroup in VMEM through a one-hot permutation matmul (the
         repo's standard gather-free idiom — per-row gathers serialize
         on TPU): codes and weights permute EXACTLY (each output row
         has one unit product; codes < 2^13 are exact in f32);
      4. the bin one-hot is built ONCE from the partitioned codes —
         the shared codes stream never re-gathers from HBM — and a
         ``fori_loop`` over node-pure B-row blocks runs one small
         (K, B) @ (B, lanes) dot per block, accumulated into that
         block's node rows.

    FLOPs are proportional to ROWS (permutation matmuls: TILE·TP·(C+K);
    block dots: TP·K·lanes), with NO M factor in any matmul — the
    useful-FLOP fraction is depth-independent (see
    :func:`hist_level_flops`).

    Bit-identity vs dense mode: per cell both modes sum the same member
    products in the same row order. On the MXU (fixed sequential-in-K
    accumulation) that makes the two modes bit-identical — asserted by
    the compiled ``@pytest.mark.tpu`` A/B variants. On the CPU interpret
    backend XLA/Eigen folds a long gemm's K axis in 256-wide panels
    (measured, PR 10), so float-weight cells can differ at ulp level
    between the panel fold and the per-block fold; INTEGER-valued weight
    stacks (the classifier engine's counts / counts·y, every f32 sum
    exact below 2^24) are bit-identical in any association and the
    tier-1 A/B matrix asserts them with ``array_equal``.

    ``pack=True`` (ISSUE 12, the NEXT §2 candidate follow-up): the
    codes permutation matmul — the dominant regroup term,
    TILE·TP·C MACs per tree — contracts a PACKED operand instead. The
    tile's per-column static lane offsets are stripped once, three raw
    7-bit codes pack per f32 word (``ops/pack.py``: exact below the
    24-bit mantissa), the per-tree permutation moves ``ceil(C/3)``
    columns (3× fewer permute MACs), and the partitioned words unpack
    and re-offset before the bin one-hot. Packing, permuting a one-hot,
    and unpacking are all exact integer f32 arithmetic, so the
    partitioned CODES are bit-identical to the unpacked path; the only
    observable difference is which lane a zero-weight slack row's
    exact ±0 lands on — packed == unpacked is asserted ``array_equal``
    for float stacks too. Pack/unpack run as matmuls against static
    0/1 selection operands (Mosaic-safe: no strided lane slicing).
    """
    @pl.when(pl.program_id(1) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    tile = codes_ref.shape[1]
    b = _PART_BLOCK
    m1 = max_nodes + 1                       # + trailing trash region
    tp = tile + m1 * b                       # partition buffer rows
    nb = tp // b
    lanes = bw * _LANES
    km = n_weights * max_nodes
    codes_f = codes_ref[0].astype(jnp.float32)          # (TILE, C)

    sub_iota = lax.broadcasted_iota(jnp.int32, (m1, tile), 0)
    tp_iota = lax.broadcasted_iota(jnp.int32, (tp, tile), 0)
    blk_start = lax.broadcasted_iota(jnp.int32, (nb, m1), 0) * b

    if pack:
        # Packed regroup operands, built ONCE per tile (ops/pack.py).
        # _offset_codes baked (c mod f_pb)·n_bins into every column;
        # strip that static offset, pack 3 raw 7-bit codes per f32 word
        # through a static radix-selection matmul, and keep the unpack
        # selectors for after the per-tree permutation. Everything is
        # matmul or elementwise on exact small integers — no strided
        # lane slicing for Mosaic to refuse, no inexact f32 op anywhere.
        c_cols = codes_f.shape[1]
        slots = float(PACK_SLOTS)
        c3 = -(-c_cols // PACK_SLOTS)
        r1, r2 = float(PACK_RADIX), float(PACK_RADIX**2)
        col = lax.broadcasted_iota(jnp.float32, (1, c_cols), 1)
        lane_off = (col - jnp.floor(col / f_pb) * f_pb) * n_bins
        ci = lax.broadcasted_iota(jnp.float32, (c_cols, c3), 0)
        wi = lax.broadcasted_iota(jnp.float32, (c_cols, c3), 1)
        slot = ci - jnp.floor(ci / slots) * slots
        radix = jnp.where(slot > 1.5, r2, jnp.where(slot > 0.5, r1, 1.0))
        pack_mat = jnp.where(jnp.floor(ci / slots) == wi, radix, 0.0)
        unpack_sel = []
        for s in range(PACK_SLOTS):
            wj = lax.broadcasted_iota(jnp.float32, (c3, c_cols), 0)
            cj = lax.broadcasted_iota(jnp.float32, (c3, c_cols), 1)
            unpack_sel.append(
                (cj == slots * wj + float(s)).astype(jnp.float32)
            )
        packed_codes = lax.dot_general(
            codes_f - lane_off, pack_mat,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # (TILE, C3)

    for t in range(n_trees):  # static unroll — T is a chunk-sized constant
        node_row = node_ref[t : t + 1, :]                # (1, TILE)
        in_range = (node_row >= 0) & (node_row < max_nodes)
        node_x = jnp.where(in_range, node_row, max_nodes)
        ohx = (node_x == sub_iota).astype(jnp.int32)     # (M+1, TILE)
        cnt = jnp.sum(ohx, axis=1, keepdims=True)        # (M+1, 1)
        reg = -(-cnt // b) * b                           # block-aligned sizes
        end = jnp.cumsum(reg, axis=0)                    # inclusive ends
        off = end - reg                                  # exclusive starts
        csum = jnp.cumsum(ohx, axis=1)                   # stable ranks + 1
        rank = jnp.sum(ohx * csum, axis=0, keepdims=True) - 1
        base = jnp.sum(ohx * off, axis=0, keepdims=True)
        dst = base + rank                                # (1, TILE) in [0, TP)
        # Gather-free regroup: one-hot permutation matmuls (exact —
        # every output row receives exactly one unit product).
        perm = (tp_iota == dst).astype(jnp.float32)      # (TP, TILE)
        if pack:
            # Permute the 3×-narrower packed words, then unpack and
            # re-offset — identical integers on every real row (slack
            # rows reconstruct to bin 0 of each feature instead of
            # lane 0, killed by their exactly-zero weights either way);
            # 3× fewer permute MACs. Histograms asserted array_equal
            # against pack=False in tests.
            packed_part = lax.dot_general(
                perm, packed_codes,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                            # (TP, C3)
            raw_part = jnp.zeros((tp, c_cols), jnp.float32)
            for s in range(PACK_SLOTS):
                v = jnp.floor(packed_part / (r1 ** s))
                v = v - r1 * jnp.floor(v / r1)
                raw_part = raw_part + lax.dot_general(
                    v, unpack_sel[s],
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            codes_part = (raw_part + lane_off).astype(jnp.int32)
        else:
            codes_part = lax.dot_general(
                perm, codes_f,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)                          # (TP, C)
        if shared_weights:
            w_rows = w_ref[...]                          # (K, TILE)
        else:
            w_rows = w_ref[t * n_weights : (t + 1) * n_weights, :]
        w_part = lax.dot_general(
            w_rows.astype(jnp.float32), perm,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(in_dtype)                               # (K, TP)
        # ONE shared bin one-hot per tile from the partitioned codes —
        # pad/trash rows decode to lane 0 of block 0, killed by their
        # exactly-zero permuted weights.
        bin_oh_part = _build_bin_oh(codes_part, bw, f_pb, n_bins, in_dtype)
        # Block → node map: block start past region m's end ⇒ a later
        # region. Trash blocks get M, slack blocks M+1 — both masked.
        blk_node = jnp.sum(
            (blk_start >= end.reshape(1, m1)).astype(jnp.int32), axis=1
        )                                                # (nb,)
        blk_ok = (blk_node < max_nodes).astype(jnp.float32)
        blk_safe = jnp.where(blk_node < max_nodes, blk_node, 0)

        def body(i, acc):
            wb = lax.dynamic_slice(w_part, (0, i * b), (n_weights, b))
            ob = lax.dynamic_slice(bin_oh_part, (i * b, 0), (b, lanes))
            pb = lax.dot_general(
                wb, ob,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                            # (K, lanes)
            # Invalid (trash/slack) blocks add an exact ±0 to node 0 —
            # the f32 identity everywhere a real sum exists.
            pb = pb * lax.dynamic_index_in_dim(blk_ok, i, keepdims=False)
            row = lax.dynamic_index_in_dim(blk_safe, i, keepdims=False)
            for k in range(n_weights):
                at = (k * max_nodes + row, 0)
                cur = lax.dynamic_slice(acc, at, (1, lanes))
                acc = lax.dynamic_update_slice(acc, cur + pb[k : k + 1], at)
            return acc

        acc = lax.fori_loop(
            0, nb, body, jnp.zeros((km, lanes), jnp.float32)
        )
        out_ref[0, t * km : (t + 1) * km, :] += acc


_VMEM_BUDGET = 100 * 1024 * 1024  # raise Mosaic's 16 MB scoped default


def _offset_codes(codes, n, p, n_pad, p_pad, f_pb, n_bins):
    """Pad codes to (n_pad, p_pad) and pre-offset each feature's codes by
    its within-block lane base (f mod f_pb)·n_bins — once here instead of
    per grid step in the kernel's unrolled compare loop (pad-feature
    columns offset too; their spurious one-hot lanes are sliced off by
    the wrappers). Shared by both kernel wrappers, which must stay
    bit-identical (tests assert it)."""
    codes = jnp.pad(codes, ((0, n_pad - n), (0, p_pad - p)))
    lane_off = (jnp.arange(p_pad, dtype=jnp.int32) % f_pb) * n_bins
    return codes + lane_off[None, :]


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "n_bins", "tile", "bw", "interpret", "bf16"),
)
def bin_histogram_pallas(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    tile: int = 2048,
    bw: int | None = None,
    interpret: bool = False,
    bf16: bool = False,
) -> jax.Array:
    """Weighted (node, feature, bin) histograms via the Pallas kernel.

    Args:
      codes: (n, p) int32 bin codes in [0, n_bins); n_bins ≤ 128.
      node_of_row: (n,) int32 node ids in [0, max_nodes); rows with ids
        outside the range contribute nothing.
      weights: (K, n) f32 — e.g. (counts, counts·y) for the classifier,
        (counts, counts·ρ) for the causal forest's gradient splits.
      tile: rows per grid step.
      bw: feature blocks (128 lanes each) per grid step; default covers
        all of p in one step (grid = row tiles only).
      bf16: feed the MXU bf16 operands (f32 accumulation). Bit-exact
        whenever the weights are integer-valued in [-256, 256] (one-hots
        are exact 0/1 and small-int bf16 products are exact in f32);
        lossy for general float weights — callers opt in through the
        ``backend="pallas_bf16"`` dispatch string.

    Returns:
      (K, max_nodes, p, n_bins) f32.
    """
    n, p = codes.shape
    k_w = weights.shape[0]
    if n_bins > _LANES:
        raise ValueError(f"n_bins={n_bins} > {_LANES} unsupported")
    # Feature-block the (feat, bin) axis: f_pb features per 128-lane
    # block. Lane layout inside a block is [f_pb × n_bins] + dead pad.
    f_pb = _LANES // n_bins
    p_blocks = -(-p // f_pb)
    if bw is None:
        bw = p_blocks
    bw = min(bw, p_blocks)
    p_groups = -(-p_blocks // bw)
    p_pad = p_groups * bw * f_pb
    n_pad = _round_up(max(n, tile), tile)

    codes = _offset_codes(codes, n, p, n_pad, p_pad, f_pb, n_bins)
    # (p_groups, n, bw·f_pb): each grid step DMAs one contiguous
    # (tile, bw·f_pb) slab of its own feature group (Mosaic requires the
    # block's trailing dim to be lane-aligned or the full array dim).
    codes_b = codes.reshape(n_pad, p_groups, bw * f_pb).transpose(1, 0, 2)
    node2d = jnp.pad(
        node_of_row.astype(jnp.int32)[:, None], ((0, n_pad - n), (0, 0)),
        constant_values=-1,
    )
    weights = jnp.pad(weights.astype(jnp.float32), ((0, 0), (0, n_pad - n)))

    grid = (p_groups, n_pad // tile)  # row tiles innermost: accumulation
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel, n_weights=k_w, max_nodes=max_nodes,
            bw=bw, f_pb=f_pb, n_bins=n_bins,
            in_dtype=jnp.bfloat16 if bf16 else jnp.float32,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile, bw * f_pb), lambda j, i: (j, i, 0)),
            pl.BlockSpec((tile, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((k_w, tile), lambda j, i: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, k_w * max_nodes, bw * _LANES), lambda j, i: (j, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (p_groups, k_w * max_nodes, bw * _LANES), jnp.float32
        ),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=_VMEM_BUDGET),
    )(codes_b, node2d, weights)
    # (p_groups, K·M, bw·LANES) → per 128-lane block keep the live
    # f_pb·n_bins lanes, then restore feature order.
    out = out.reshape(p_groups, k_w * max_nodes, bw, _LANES)[..., : f_pb * n_bins]
    out = out.transpose(1, 0, 2, 3).reshape(k_w, max_nodes, p_pad, n_bins)
    return out[:, :, :p, :]


def _batched_layout(codes, n, p, n_bins, tile, bw):
    """The feature-blocked, row-padded codes layout shared by both
    tree-batched wrappers (per-tree and shared-weights — review r5:
    one site for tiling/padding fixes). Returns
    (codes_b, f_pb, bw, p_groups, p_pad, tile, n_pad)."""
    f_pb = _LANES // n_bins
    p_blocks = -(-p // f_pb)
    bw = p_blocks if bw is None else min(bw, p_blocks)
    p_groups = -(-p_blocks // bw)
    p_pad = p_groups * bw * f_pb
    if tile is None:
        # Fixed 2048 rows per grid step. Larger tiles (4096-16384) were
        # tried to amortize per-step costs further, but Mosaic's compile
        # of the unrolled compare/concat body stalls for minutes at
        # those widths on the remote compile service (measured twice,
        # round 3) — the tree batching is where the amortization comes
        # from, not the tile.
        tile = 2048
    n_pad = _round_up(max(n, tile), tile)
    codes = _offset_codes(codes, n, p, n_pad, p_pad, f_pb, n_bins)
    codes_b = codes.reshape(n_pad, p_groups, bw * f_pb).transpose(1, 0, 2)
    return codes_b, f_pb, bw, p_groups, p_pad, tile, n_pad


def _batched_unlayout(out, n_trees, k_w, max_nodes, p_groups, bw, f_pb,
                      n_bins, p_pad, p):
    """Inverse of the kernel's blocked output layout: keep each 128-lane
    block's live lanes, restore feature order, split tree/channel axes."""
    out = out.reshape(p_groups, n_trees * k_w * max_nodes, bw, _LANES)[
        ..., : f_pb * n_bins
    ]
    out = out.transpose(1, 0, 2, 3).reshape(
        n_trees, k_w, max_nodes, p_pad, n_bins
    )
    return out[:, :, :, :p, :]


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "n_bins", "tile", "bw", "interpret", "bf16",
                     "partition", "pack"),
)
def bin_histogram_pallas_batched(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    tile: int | None = None,
    bw: int | None = None,
    interpret: bool = False,
    bf16: bool = False,
    partition: bool = False,
    pack: bool = False,
) -> jax.Array:
    """Tree-batched histograms: T trees sharing one ``codes`` stream.

    Args:
      codes: (n, p) int32 bin codes in [0, n_bins); n_bins ≤ 128.
      node_of_row: (T, n) int32 per-tree node ids; ids outside
        [0, max_nodes) contribute nothing.
      weights: (T, K, n) f32 per-tree weight vectors.
      partition: run the in-kernel stable-bin-partition formulation
        (:func:`_hist_kernel_batched_partition`) instead of the dense
        every-node-per-row contraction. Same contract; FLOPs ∝ rows
        instead of rows × nodes. Bit-identical to dense for
        integer-valued weight stacks everywhere and for all stacks on
        the MXU's fixed accumulation order; ulp-level on the CPU
        interpret backend for float stacks (gemm panel fold — see the
        kernel docstring).

    Returns:
      (T, K, max_nodes, p, n_bins) f32 — bit-identical to T separate
      :func:`bin_histogram_pallas` calls (same tile order, same per-
      element f32 accumulation; asserted in tests/test_hist_pallas.py).

    The batched grid does T× more MXU work per step but builds the bin
    one-hot ONCE per row tile — the measured dominant cost at large n —
    so per-tree cost drops by nearly the fixed-work share (ablation:
    scripts/profile_grow.py). VMEM bounds T: the output block is
    T·K·max_nodes × bw·128 f32 and the lhs operand tile × T·K·max_nodes;
    callers size T via :func:`batched_tree_cap`.
    """
    n, p = codes.shape
    n_trees, k_w = weights.shape[0], weights.shape[1]
    if n_bins > _LANES:
        raise ValueError(f"n_bins={n_bins} > {_LANES} unsupported")
    codes_b, f_pb, bw, p_groups, p_pad, tile, n_pad = _batched_layout(
        codes, n, p, n_bins, tile, bw
    )
    # Lane-major row layouts: node (T, n), weights (T·K, n) — rows on
    # lanes, so the kernel's per-tree strips are sublane slices.
    node_tn = jnp.pad(
        node_of_row.astype(jnp.int32), ((0, 0), (0, n_pad - n)),
        constant_values=-1,
    )
    w_tkn = jnp.pad(
        weights.astype(jnp.float32).reshape(n_trees * k_w, n),
        ((0, 0), (0, n_pad - n)),
    )

    if partition:
        kernel_body = functools.partial(
            _hist_kernel_batched_partition, pack=pack
        )
    else:
        kernel_body = _hist_kernel_batched
    grid = (p_groups, n_pad // tile)
    out = pl.pallas_call(
        functools.partial(
            kernel_body, n_weights=k_w, n_trees=n_trees,
            max_nodes=max_nodes, bw=bw, f_pb=f_pb, n_bins=n_bins,
            in_dtype=jnp.bfloat16 if bf16 else jnp.float32,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile, bw * f_pb), lambda j, i: (j, i, 0)),
            pl.BlockSpec((n_trees, tile), lambda j, i: (0, i)),
            pl.BlockSpec((n_trees * k_w, tile), lambda j, i: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, n_trees * k_w * max_nodes, bw * _LANES), lambda j, i: (j, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (p_groups, n_trees * k_w * max_nodes, bw * _LANES), jnp.float32
        ),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=_VMEM_BUDGET),
    )(codes_b, node_tn, w_tkn)
    return _batched_unlayout(
        out, n_trees, k_w, max_nodes, p_groups, bw, f_pb, n_bins, p_pad, p
    )


@functools.partial(
    jax.jit,
    static_argnames=("max_nodes", "n_bins", "tile", "bw", "interpret", "bf16",
                     "partition", "pack"),
)
def bin_histogram_pallas_batched_shared(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    tile: int | None = None,
    bw: int | None = None,
    interpret: bool = False,
    bf16: bool = False,
    partition: bool = False,
    pack: bool = False,
) -> jax.Array:
    """:func:`bin_histogram_pallas_batched` with ONE weight stack
    shared by every tree: ``weights`` is (K, n), not (T, K, n).

    Same (T, K, max_nodes, p, n_bins) output, bit-identical to the
    per-tree layout fed ``broadcast_to(weights[None], (T, K, n))`` —
    but the kernel DMAs a (K, tile) block per step instead of
    (T·K, tile), and no (T·K, n) HBM operand ever exists. This is the
    round-5 causal-grower contract: honest/subsample membership lives
    in the id stream (-1 drops a row), so the five ρ channels are the
    raw per-row moment stack, invariant across trees
    (models/causal_forest.py::grow_one_streaming).

    ``partition=True`` note: a non-member row is a MASKED ID here but a
    zero WEIGHT in the per-tree layout, so the two layouts partition a
    tile differently (masked ids go to the trash region; zero-weight
    rows stay inside their node's region). The shared-vs-per-tree
    bit-identity therefore holds unconditionally for integer-valued
    stacks (exact sums) and on the MXU's ordered accumulation, but is
    ulp-level on the CPU interpret backend for float stacks — the same
    split as the dense-vs-partition contract.
    """
    n, p = codes.shape
    n_trees = node_of_row.shape[0]
    k_w = weights.shape[0]
    if n_bins > _LANES:
        raise ValueError(f"n_bins={n_bins} > {_LANES} unsupported")
    codes_b, f_pb, bw, p_groups, p_pad, tile, n_pad = _batched_layout(
        codes, n, p, n_bins, tile, bw
    )
    node_tn = jnp.pad(
        node_of_row.astype(jnp.int32), ((0, 0), (0, n_pad - n)),
        constant_values=-1,
    )
    w_kn = jnp.pad(weights.astype(jnp.float32), ((0, 0), (0, n_pad - n)))

    if partition:
        kernel_body = functools.partial(
            _hist_kernel_batched_partition, pack=pack
        )
    else:
        kernel_body = _hist_kernel_batched
    grid = (p_groups, n_pad // tile)
    out = pl.pallas_call(
        functools.partial(
            kernel_body, n_weights=k_w, n_trees=n_trees,
            max_nodes=max_nodes, bw=bw, f_pb=f_pb, n_bins=n_bins,
            in_dtype=jnp.bfloat16 if bf16 else jnp.float32,
            shared_weights=True,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tile, bw * f_pb), lambda j, i: (j, i, 0)),
            pl.BlockSpec((n_trees, tile), lambda j, i: (0, i)),
            pl.BlockSpec((k_w, tile), lambda j, i: (0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, n_trees * k_w * max_nodes, bw * _LANES), lambda j, i: (j, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (p_groups, n_trees * k_w * max_nodes, bw * _LANES), jnp.float32
        ),
        interpret=interpret,
        compiler_params=_COMPILER_PARAMS(vmem_limit_bytes=_VMEM_BUDGET),
    )(codes_b, node_tn, w_kn)
    return _batched_unlayout(
        out, n_trees, k_w, max_nodes, p_groups, bw, f_pb, n_bins, p_pad, p
    )


def kernel_lanes(p: int, n_bins: int) -> int:
    """Lane width of the kernel's histogram block: feature blocks of
    ``LANES // n_bins`` features, each 128 lanes (1408 at the GGL shape
    p=21, 64 bins)."""
    f_pb = max(1, _LANES // n_bins)
    return -(-p // f_pb) * _LANES


def batched_tree_cap(max_nodes: int, n_weights: int, tile: int = 2048,
                     p: int = 21, n_bins: int = 64,
                     partition: bool = False) -> int:
    """Largest tree batch T whose kernel working set fits the scoped-VMEM
    budget: out block (T·K·M, lanes) f32 + lhs (tile, T·K·M) f32 + bin
    one-hot and codes temps. ``p`` and ``n_bins`` size the lane axis —
    the default is the GGL shape; pass the real values for wider
    feature sets or the estimate undercounts VMEM.

    Headroom (round 5, scripts/ab_lhs_variant.py on-chip): T=22 at the
    causal deep shape (K=5, M=64 — 97 MB of out+lhs) compiles and runs
    under the 100 MB budget, so the old 2× halving double-counted
    Mosaic temps; 0.9× with an explicit fixed term matches observed
    fits. The same A/B measured the deep-level MARGINAL cost flat in T
    (~4.7 ms/tree) while the ~4.7 ms per-call fixed work (bin one-hot
    build + codes DMA + grid overhead, level-invariant) divides by T —
    a bigger batch is pure fixed-cost amortization. The 0.9 factor is
    LOAD-BEARING at the flagship scale (round-5 close measured 1.15
    OOMing the chip's HBM via the bigger chunks' (T, n) streams) —
    partition mode keeps it and instead enlarges the FIXED term.

    ``partition=True`` accounts the partition kernel's per-tree
    sequential transients (ISSUE 10): the (TP, TILE) permutation
    one-hot, the partitioned (TP, lanes) bin one-hot and (K, TP)
    weights, where TP = TILE + (M+1)·8. These do NOT scale with T
    (trees unroll sequentially and Mosaic reuses the buffers) so they
    join the fixed term — the cap shrinks, the budget factor stays."""
    lanes = kernel_lanes(p, n_bins)
    per_tree = 4 * n_weights * max_nodes * (lanes + tile)
    fixed = 2 * 4 * tile * lanes
    if partition:
        tp = tile + (max_nodes + 1) * _PART_BLOCK
        fixed += 4 * (tp * tile + tp * lanes + n_weights * tp)
    return max(1, (int(_VMEM_BUDGET * 0.9) - fixed) // max(per_tree, 1))


# ---------------------------------------------------------------------------
# Kernel-mode policy (ISSUE 10): dense vs in-kernel stable-bin partition.
#
# The policy is split exactly like the backend policy PR 2 fixed twice
# (JGL001/JGL003): resolve_hist_mode reads the ENVIRONMENT on the host in
# un-jitted config code and returns a concrete policy string; the pure
# functions below (mode_for_width / the FLOP model) run at trace time on
# STATIC shapes only — no ambient state ever reaches a traced body.
# ---------------------------------------------------------------------------

_HIST_MODE_ENV = "ATE_TPU_HIST_MODE"
HIST_MODES = ("dense", "partition", "auto")

#: ISSUE 12: the packed-code regroup rides the EXISTING hist_mode
#: plumbing as a mode suffix ("partition+pack"), so the growers'
#: config-time-resolved static threads both decisions without a second
#: parameter trickling through every chunk/grow signature. The suffix
#: is attached by the growers (resolve_predict_pack at config time),
#: preserved by mode_for_width's per-width decision, and split off at
#: the kernel dispatchers.
PACK_SUFFIX = "+pack"


def with_pack_mode(mode: str, pack: bool) -> str:
    """Attach the pack suffix to a resolved policy mode ("dense" stays
    packless-capable: the suffix only ever matters where the partition
    regroup runs, but "auto+pack" must survive resolution)."""
    base, _ = split_pack_mode(mode)
    return base + PACK_SUFFIX if pack else base


def split_pack_mode(mode: str) -> tuple[str, bool]:
    """→ (base mode, packed?)."""
    if mode.endswith(PACK_SUFFIX):
        return mode[: -len(PACK_SUFFIX)], True
    return mode, False


def resolve_hist_mode_packed(mode: str | None = None,
                             n_bins: int = 64) -> str:
    """:func:`resolve_hist_mode` plus the ISSUE 12 pack policy: the
    growers' ONE config-time call. An explicit ``+pack`` suffix on
    ``mode`` wins; otherwise ``ATE_TPU_PREDICT_PACK`` decides
    (``ops/pack.py``); either way packing only engages where a 7-bit
    slot is exact (``n_bins`` ≤ 128) — wider-bin forests silently keep
    the identical unpacked path rather than refuse."""
    from ate_replication_causalml_tpu.ops.pack import (
        packable,
        resolve_predict_pack,
    )

    explicit = False
    if isinstance(mode, str):
        mode, explicit = split_pack_mode(mode)
    base = resolve_hist_mode(mode)
    pack = (explicit or resolve_predict_pack(None)) and packable(n_bins)
    return with_pack_mode(base, pack)


def resolve_hist_mode(mode: str | None = None) -> str:
    """The single CONFIG-TIME entry for the kernel-mode policy.

    ``mode`` (a fitter's ``hist_mode=`` argument) wins when given;
    otherwise ``ATE_TPU_HIST_MODE`` (case-insensitive), defaulting to
    "auto" — dense below :func:`partition_crossover_width`, partition at
    and past it. A bad value raises HERE, at config time, never at
    trace time. Deliberately un-jitted (graftlint JGL001): the result is
    passed into the growers as a jit STATIC, so a cached trace can never
    serve a mode chosen under a different environment."""
    raw = mode if mode is not None else os.environ.get(_HIST_MODE_ENV, "auto")
    val = str(raw).strip().lower()
    if val not in HIST_MODES:
        raise ValueError(
            f"{_HIST_MODE_ENV}/hist_mode must be one of {HIST_MODES} "
            f"(case-insensitive), got {raw!r}"
        )
    return val


def hist_level_flops(mode: str, n_rows: int, max_nodes: int, n_weights: int,
                     p: int = 21, n_bins: int = 64, tile: int = 2048) -> dict:
    """Analytic MXU-FLOP model of ONE tree's level histogram (the
    ``bench.py --hist-ab`` record's per-level fields; also what the
    auto-mode crossover is derived from).

    Counts matmul FLOPs only (2 per MAC), mirroring the kernels' real
    layouts (:func:`_batched_layout`): padded rows, feature-blocked
    lanes ``L = ceil(p/f_pb)·128``, code columns ``C = ceil(p/f_pb)·f_pb``.

    ``useful`` is mode-INDEPENDENT by construction — the FLOPs that had
    to happen: every real row × its own node × the live (p·n_bins)
    cells × K channels. Dense total is ``rows_pad·K·M·L`` (every node
    pays every row → useful fraction ~1/M, decaying like 1/2^d with
    depth); partition total is the permutation matmuls + the node-pure
    block dots, ``rows_pad·(TP/tile)·(C + K) + TP_rows·K·L`` — NO M
    factor in any term, so its useful fraction is depth-independent
    (asserted in tests and schema-validated in the bench record).

    ``"partition+pack"`` (ISSUE 12) models the packed regroup: the
    codes permutation contracts ``ceil(C/3)`` packed columns, plus the
    pack matmul (once per tile) and the three unpack selections (per
    tree) — all small against the 3×-shrunk permutation term."""
    mode, packed = split_pack_mode(mode)
    if mode not in ("dense", "partition") or (packed and mode == "dense"):
        raise ValueError(
            f"flop model mode must be dense|partition[+pack], got {mode!r}"
        )
    f_pb = max(1, _LANES // n_bins)
    p_blocks = -(-p // f_pb)
    lanes = p_blocks * _LANES
    c_cols = p_blocks * f_pb
    n_tiles = max(1, -(-n_rows // tile))
    rows_pad = n_tiles * tile
    useful = 2.0 * n_rows * n_weights * p * n_bins
    if mode == "dense":
        total = 2.0 * rows_pad * n_weights * max_nodes * lanes
    else:
        tp = tile + (max_nodes + 1) * _PART_BLOCK
        if packed:
            c3 = -(-c_cols // 3)
            code_perm = (
                tp * tile * c3          # packed codes permutation
                + tile * c_cols * c3    # pack matmul (once per tile)
                + 3 * tp * c3 * c_cols  # unpack selections
            )
        else:
            code_perm = tp * tile * c_cols  # codes permutation matmul
        per_tile = (
            code_perm
            + n_weights * tile * tp     # weight permutation matmul
            + tp * n_weights * lanes    # node-pure block dots
        )
        total = 2.0 * n_tiles * per_tile
    # Deliberately UNclamped: useful ≤ total is a property of a correct
    # model, and validate_hist_ab_record exists to catch a broken one —
    # a max() here would hide exactly the bug the validator checks for.
    return {"useful": useful, "total": total}


@functools.lru_cache(maxsize=None)
def partition_crossover_width(n_weights: int, p: int = 21, n_bins: int = 64,
                              tile: int = 2048) -> int:
    """Smallest kernel width (padded node count, a power of two ≤ 128)
    at which the partition kernel's modeled total FLOPs beat dense's —
    the auto-mode depth crossover. Pure function of static shapes;
    unit-tested with known answers in tests/test_hist_pallas.py. Returns
    256 (an unreachable width) when dense wins everywhere ≤ 128."""
    for width in (1, 2, 4, 8, 16, 32, 64, 128):
        dense = hist_level_flops("dense", tile, width, n_weights, p, n_bins,
                                 tile)
        part = hist_level_flops("partition", tile, width, n_weights, p,
                                n_bins, tile)
        if part["total"] < dense["total"]:
            return width
    return 256


def mode_for_width(mode: str, width: int, n_weights: int, p: int = 21,
                   n_bins: int = 64) -> str:
    """Resolve a config-time policy ("dense" | "partition" | "auto") to
    the concrete kernel mode for ONE kernel width. Pure — callable at
    trace time on jit statics.

    The decision is keyed on the KERNEL width (the padded node count the
    kernel actually allocates), not the grow level: the uniform-width
    floors map several shallow levels onto one width, and deciding per
    width means each width compiles in exactly ONE mode — the partition
    kernel reuses the existing instantiation set instead of multiplying
    it (executable count is a first-class cost, NEXT.md hardware
    lessons).

    A ``+pack`` suffix (ISSUE 12) passes through: the packed regroup is
    a property of the partition kernel only, so "auto+pack" resolves to
    "dense" below the crossover and "partition+pack" past it — dense
    instantiations are byte-identical to the packless policy."""
    mode, pack = split_pack_mode(mode)
    if mode == "auto":
        mode = (
            "partition"
            if width >= partition_crossover_width(n_weights, p, n_bins)
            else "dense"
        )
    elif mode not in ("dense", "partition"):
        raise ValueError(f"unknown histogram mode {mode!r}")
    if mode == "partition" and pack:
        return mode + PACK_SUFFIX
    return mode


@functools.lru_cache(maxsize=None)
def _pallas_batched_vmappable(max_nodes: int, n_bins: int, bf16: bool,
                              interpret: bool, partition: bool = False,
                              pack: bool = False):
    """The tree-batched kernel as a `custom_vmap` callable.

    The forest growers call :func:`bin_histogram` per tree under
    ``jax.vmap`` (and the causal grower under TWO nested vmaps: groups ×
    little-bag trees). A plain vmap of ``pallas_call`` prepends a grid
    dimension — every tree re-streams codes and rebuilds the bin one-hot,
    which the round-3 ablation measured as ~90% of kernel time at 1M
    rows. This wrapper gives vmap a custom rule instead: each vmap level
    FLATTENS its batch axis into the kernel's tree axis, so any nest of
    vmaps collapses to one tree-batched kernel call (chunked to the
    VMEM cap). Grower code stays untouched — the batching transform is
    where the optimization lives.

    When ``codes`` itself is batched (the causal grower's per-group
    subsample gathers), streams can't be shared; the rule falls back to
    a per-slice Python loop, preserving per-slice tree batching.
    """
    from jax import custom_batching

    def impl(codes, node, weights):
        t = node.shape[0]
        cap = batched_tree_cap(
            max_nodes, weights.shape[1], p=codes.shape[1], n_bins=n_bins,
            partition=partition,
        )
        outs = [
            bin_histogram_pallas_batched(
                codes, node[s : s + cap], weights[s : s + cap],
                max_nodes=max_nodes, n_bins=n_bins, bf16=bf16,
                interpret=interpret, partition=partition, pack=pack,
            )
            for s in range(0, t, cap)
        ]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    @custom_batching.custom_vmap
    def g(codes, node, weights):
        return impl(codes, node, weights)

    @g.def_vmap
    def _rule(axis_size, in_batched, codes, node, weights):  # noqa: ANN001
        codes_b, node_b, w_b = in_batched
        if codes_b:
            out = jnp.stack([
                g(
                    codes[i],
                    node[i] if node_b else node,
                    weights[i] if w_b else weights,
                )
                for i in range(axis_size)
            ])
            return out, True
        if not node_b:
            node = jnp.broadcast_to(node[None], (axis_size,) + node.shape)
        if not w_b:
            weights = jnp.broadcast_to(weights[None], (axis_size,) + weights.shape)
        b, t = node.shape[0], node.shape[1]
        out = g(
            codes,
            node.reshape(b * t, node.shape[2]),
            weights.reshape(b * t, weights.shape[2], weights.shape[3]),
        )
        return out.reshape((b, t) + out.shape[1:]), True

    return g


@functools.lru_cache(maxsize=None)
def _pallas_batched_shared_vmappable(max_nodes: int, n_bins: int, bf16: bool,
                                     interpret: bool,
                                     partition: bool = False,
                                     pack: bool = False):
    """The shared-weights tree-batched kernel as a `custom_vmap`
    callable: g(codes (n, p), node (T, n), weights (K, n)).

    Mirrors :func:`_pallas_batched_vmappable`'s collapse rule for the
    causal grower's nested vmaps (groups × little-bag trees), but the
    weight stack NEVER batches — it is the chunk-shared per-row moment
    stack. A vmap level that batches node ids flattens into the tree
    axis; batched codes fall back to a per-slice loop; vmapping the
    WEIGHTS raises (use :func:`bin_histogram` for per-tree stacks —
    the rule fails loudly rather than silently paying the dense
    broadcast)."""
    from jax import custom_batching

    def impl(codes, node, weights):
        t = node.shape[0]
        cap = batched_tree_cap(
            max_nodes, weights.shape[0], p=codes.shape[1], n_bins=n_bins,
            partition=partition,
        )
        outs = [
            bin_histogram_pallas_batched_shared(
                codes, node[s : s + cap], weights,
                max_nodes=max_nodes, n_bins=n_bins, bf16=bf16,
                interpret=interpret, partition=partition, pack=pack,
            )
            for s in range(0, t, cap)
        ]
        return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)

    @custom_batching.custom_vmap
    def g(codes, node, weights):
        return impl(codes, node, weights)

    @g.def_vmap
    def _rule(axis_size, in_batched, codes, node, weights):  # noqa: ANN001
        codes_b, node_b, w_b = in_batched
        if w_b:
            # A batched weight stack contradicts the shared-weights
            # contract (weights are THE chunk-shared operand); no
            # caller does this — fail loudly rather than silently
            # broadcasting at the dense kernel's cost (review r5).
            raise NotImplementedError(
                "bin_histogram_shared: weights must not be vmapped — "
                "use bin_histogram for per-tree weight stacks"
            )
        if codes_b:
            out = jnp.stack([
                g(codes[i], node[i] if node_b else node, weights)
                for i in range(axis_size)
            ])
            return out, True
        if not node_b:
            node = jnp.broadcast_to(node[None], (axis_size,) + node.shape)
        b, t = node.shape[0], node.shape[1]
        out = g(codes, node.reshape(b * t, node.shape[2]), weights)
        return out.reshape((b, t) + out.shape[1:]), True

    return g


def _check_mode(mode: str, backend: str) -> tuple[bool, bool]:
    """Validate a RESOLVED kernel mode against a RESOLVED backend and
    return ``(partition?, packed?)``. 'auto' is not accepted here —
    callers resolve it per kernel width with :func:`mode_for_width` at
    config/trace time (a dispatcher seeing 'auto' means a caller
    skipped the heuristic). The ``+pack`` suffix is only meaningful on
    the partition kernel (ISSUE 12) and is rejected on dense so a
    policy bug surfaces instead of silently dropping."""
    base, pack = split_pack_mode(mode)
    if base not in ("dense", "partition"):
        raise ValueError(
            f"histogram kernel mode must be 'dense' or 'partition' at "
            f"dispatch (resolve 'auto' via mode_for_width), got {mode!r}"
        )
    if pack and base != "partition":
        raise ValueError(
            f"the {PACK_SUFFIX!r} suffix applies to the partition kernel "
            f"only, got {mode!r} (mode_for_width strips it on dense)"
        )
    if base == "partition" and not backend.startswith("pallas"):
        raise ValueError(
            f"mode='partition' requires a pallas backend, got {backend!r}"
        )
    return base == "partition", pack


def bin_histogram_shared(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    backend: str = "auto",
    mode: str = "dense",
) -> jax.Array:
    """:func:`bin_histogram` whose weight stack is SHARED across any
    vmapped tree axes: node_of_row (n,) per tree, weights (K, n) common.

    Under the growers' nested vmaps the node ids flatten into the
    kernel's tree axis exactly as :func:`bin_histogram` does, but the
    weights stay one (K, n) operand — no per-tree broadcast is ever
    materialized. Output per call: (K, max_nodes, p, n_bins),
    bit-identical to ``bin_histogram(codes, ids, weights·mask)`` when
    the caller folds the row mask into the ids (0/1 weights only — the
    causal membership contract)."""
    backend = resolve_hist_backend(
        backend, allow_onehot=False, n_rows=codes.shape[0], n_bins=n_bins
    )
    partition, pack = _check_mode(mode, backend)
    if backend in ("pallas", "pallas_bf16", "pallas_interpret"):
        g = _pallas_batched_shared_vmappable(
            max_nodes, n_bins, backend == "pallas_bf16",
            backend == "pallas_interpret", partition, pack,
        )
        return g(codes, node_of_row[None], weights)[0]
    if backend == "xla":
        return bin_histogram_xla(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins
        )
    raise ValueError(f"unknown histogram backend {backend!r}")


def bin_histogram_batched(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    backend: str = "auto",
    mode: str = "dense",
) -> jax.Array:
    """Tree-batched dispatch with the same contract as :func:`bin_histogram`
    lifted over a leading tree axis: node_of_row (T, n), weights
    (T, K, n) → (T, K, max_nodes, p, n_bins)."""
    backend = resolve_hist_backend(
        backend, allow_onehot=False, n_rows=codes.shape[0], n_bins=n_bins
    )
    partition, pack = _check_mode(mode, backend)
    if backend in ("pallas", "pallas_bf16", "pallas_interpret"):
        g = _pallas_batched_vmappable(
            max_nodes, n_bins, backend == "pallas_bf16",
            backend == "pallas_interpret", partition, pack,
        )
        return g(codes, node_of_row, weights)
    if backend == "xla":
        return jax.vmap(
            lambda ids, w: bin_histogram_xla(
                codes, ids, w, max_nodes=max_nodes, n_bins=n_bins
            )
        )(node_of_row, weights)
    raise ValueError(f"unknown histogram backend {backend!r}")


def node_sums(
    ids: jax.Array,
    weights: jax.Array,
    num_nodes: int,
    backend: str = "auto",
) -> jax.Array:
    """Per-node weighted sums, (num_nodes, K): the degenerate histogram
    with one constant feature. On the streaming backends this reuses the
    batched kernel (codes ≡ 0, n_bins = 128 → a single lane block and
    ONE iota compare per tile), so node reductions — honest-leaf
    payloads, per-level moments — need no (rows, nodes) one-hot in HBM
    and no serialized segment_sum. Vmapping over trees batches through
    the kernel's tree axis like every other dispatch."""
    n = ids.shape[0]
    backend = resolve_hist_backend(backend, allow_onehot=False, n_rows=n,
                                   n_bins=128)
    if backend.startswith("pallas"):
        codes0 = jnp.zeros((n, 1), jnp.int32)
        h = bin_histogram(
            codes0, ids, weights, max_nodes=num_nodes, n_bins=128,
            backend=backend,
        )  # (K, M, 1, 128); only bin 0 is populated
        return h[:, :, 0, 0].T
    oh = jax.nn.one_hot(ids, num_nodes, dtype=jnp.float32)
    return jnp.matmul(oh.T, weights.T)  # (M, K)


def node_sums_shared(
    ids: jax.Array,
    weights: jax.Array,
    num_nodes: int,
    backend: str = "auto",
) -> jax.Array:
    """:func:`node_sums` with the weight stack shared across vmapped
    tree axes (ids (n,) per tree, weights (K, n) common) — the honest
    leaf payload with estimate-half membership folded into the ids."""
    n = ids.shape[0]
    backend = resolve_hist_backend(backend, allow_onehot=False, n_rows=n,
                                   n_bins=128)
    if backend.startswith("pallas"):
        codes0 = jnp.zeros((n, 1), jnp.int32)
        h = bin_histogram_shared(
            codes0, ids, weights, max_nodes=num_nodes, n_bins=128,
            backend=backend,
        )  # (K, M, 1, 128); only bin 0 is populated
        return h[:, :, 0, 0].T
    oh = jax.nn.one_hot(ids, num_nodes, dtype=jnp.float32)
    return jnp.matmul(oh.T, weights.T)  # (M, K)


@functools.partial(jax.jit, static_argnames=("max_nodes", "n_bins", "row_chunk"))
def bin_histogram_xla(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    row_chunk: int = 65536,
) -> jax.Array:
    """Chunked-XLA fallback with the same contract as the kernel: scans
    row chunks so the bin one-hot never exceeds ``row_chunk × p·n_bins``
    (memory-safe at 1M rows, unlike the monolithic one-hot)."""
    n, p = codes.shape
    k_w = weights.shape[0]
    n_pad = _round_up(max(n, 1), row_chunk) if n > row_chunk else n
    if n_pad != n:
        codes = jnp.pad(codes, ((0, n_pad - n), (0, 0)))
        node_of_row = jnp.pad(node_of_row, (0, n_pad - n), constant_values=-1)
        weights = jnp.pad(weights, ((0, 0), (0, n_pad - n)))
    if n_pad <= row_chunk:
        return _hist_chunk_xla(codes, node_of_row, weights, max_nodes, n_bins)

    n_chunks = n_pad // row_chunk
    codes_c = codes.reshape(n_chunks, row_chunk, p)
    node_c = node_of_row.reshape(n_chunks, row_chunk)
    w_c = weights.reshape(k_w, n_chunks, row_chunk).transpose(1, 0, 2)

    def step(acc, chunk):
        c, m, w = chunk
        return acc + _hist_chunk_xla(c, m, w, max_nodes, n_bins), None

    init = jnp.zeros((k_w, max_nodes, p, n_bins), jnp.float32)
    acc, _ = lax.scan(step, init, (codes_c, node_c, w_c))
    return acc


def _hist_chunk_xla(codes, node_of_row, weights, max_nodes, n_bins):
    n, p = codes.shape
    k_w = weights.shape[0]
    flat = codes + jnp.arange(p, dtype=jnp.int32)[None, :] * n_bins
    bin_oh = (
        jnp.zeros((n, p * n_bins), jnp.float32)
        .at[jnp.arange(n)[:, None], flat]
        .set(1.0)
    )
    node_oh = jax.nn.one_hot(node_of_row, max_nodes, dtype=jnp.float32)
    lhs = (node_oh[None, :, :] * weights[:, :, None]).reshape(k_w, n, max_nodes)
    out = jnp.einsum("knm,nb->kmb", lhs, bin_oh)
    return out.reshape(k_w, max_nodes, p, n_bins)


def bin_histogram(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    backend: str = "auto",
    mode: str = "dense",
) -> jax.Array:
    """Dispatch: compiled Pallas kernel on TPU, chunked XLA elsewhere.

    ``backend``: "auto" | "pallas" | "pallas_bf16" | "pallas_interpret"
    | "xla". ``pallas_bf16`` feeds the MXU bf16 operands (f32
    accumulation) — bit-exact only for integer-valued weights (see
    :func:`bin_histogram_pallas`); callers opt in per forest via their
    ``hist_backend`` argument.

    ``mode``: "dense" | "partition" — the kernel FORMULATION (ISSUE 10;
    pallas backends only). The growers resolve their per-level choice
    with :func:`mode_for_width` from the config-time
    :func:`resolve_hist_mode` policy.
    """
    backend = resolve_hist_backend(backend, allow_onehot=False)
    partition, pack = _check_mode(mode, backend)
    if backend in ("pallas", "pallas_bf16", "pallas_interpret"):
        # Through the custom_vmap wrapper: callers vmap this per tree
        # (nested vmaps in the causal grower), and the rule collapses
        # every vmap level into the kernel's tree axis — one tree-batched
        # kernel call per grow level instead of a per-tree grid sweep.
        g = _pallas_batched_vmappable(
            max_nodes, n_bins, backend == "pallas_bf16",
            backend == "pallas_interpret", partition, pack,
        )
        return g(codes, node_of_row[None], weights[None])[0]
    if backend == "xla":
        return bin_histogram_xla(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins
        )
    raise ValueError(f"unknown histogram backend {backend!r}")

"""Pallas TPU kernel: weighted bin-histogram build for forest split search.

This is THE hot op of both forest engines (SURVEY.md §2.3 — the Fortran
CART core behind ``randomForest`` and the grf C++ honest-split core).
Each tree level needs, per (node, feature, bin) cell, the total bootstrap
weight and the total weighted target:

    hist[k, m, f, b] = Σ_rows  w[k, row] · 1[node(row) = m] · 1[code(row, f) = b]

The pure-XLA formulation (models/forest.py) computes this as
``(node_onehot · w)ᵀ @ bin_onehot`` with the bin one-hot materialised
once in HBM — fine at the reference's 8.9k rows, but the one-hot is
``n × p·n_bins`` f32, i.e. **~5.4 GB at the 1M-row north-star scale**
(BASELINE.md). This kernel never materialises it: rows stream through
VMEM in tiles, both one-hots are built tile-wise with ``broadcasted_iota``
comparisons (VPU), and the per-tile contraction runs on the MXU,
accumulating into a VMEM-resident histogram block across the sequential
grid. HBM traffic drops from O(n·p·n_bins) to O(n·p) — the raw codes.

Layout notes (pallas_guide.md):
  * last dim of every VMEM block is a multiple of 128 lanes: the
    histogram's trailing axis is ``p·n_bins`` (padded to 128); the
    row-tile axis (sublanes) is the contraction axis of the MXU matmul;
  * iota is always ≥2D (``broadcasted_iota``);
  * the output BlockSpec maps every grid step to block (0, 0, 0) so the
    accumulator stays VMEM-resident; it is zeroed at step 0 via
    ``pl.when`` (standard sequential-grid accumulation pattern).

CPU tests run the same kernel with ``interpret=True`` (tests/conftest.py
forces the CPU backend); ``backend="auto"`` picks the compiled kernel on
TPU and the chunked-XLA fallback elsewhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def resolve_hist_backend(backend: str, allow_onehot: bool = True) -> str:
    """The single place the 'auto' policy lives.

    Measured on TPU v5-lite (n=100k, p=21, 64 bins, 32-tree chunks):
    the chunked-XLA contraction runs ~36 ms/tree vs ~55 ms/tree for the
    Pallas kernel, and the kernel's VMEM-resident accumulator
    (K·max_nodes × p·n_bins f32) exhausts scoped VMEM for deep trees
    under tree-vmap. So 'auto' is the XLA path everywhere — the fastest
    *and* the memory-robust choice; the kernel remains selectable
    (``backend="pallas"``) and bit-exact (tests/test_hist_pallas.py)
    for platforms/shapes where a fused kernel wins. On CPU the forest
    engines pass ``allow_onehot=True`` to use the shared one-hot matmul
    (fastest at reference scale)."""
    if backend == "auto":
        if jax.default_backend() == "tpu":
            return "xla"
        return "onehot" if allow_onehot else "xla"
    return backend


def _hist_kernel(codes_ref, node_ref, w_ref, out_ref, *, n_weights, max_nodes, p, n_bins):
    """One grid step: fold a row tile into the resident histogram.

    codes_ref: (TILE, p_pad) int32    — bin codes, padded features are 0
    node_ref:  (TILE, 1)   int32      — node id per row (padded rows: -1)
    w_ref:     (n_weights, TILE) f32  — weight vectors (padded rows: 0)
    out_ref:   (n_weights * max_nodes, pb_pad) f32 — accumulator
    """
    tile = codes_ref.shape[0]
    pb_pad = out_ref.shape[-1]

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    # Node one-hot: (TILE, max_nodes). Padded rows carry node=-1 → all 0.
    node_iota = lax.broadcasted_iota(jnp.int32, (tile, max_nodes), 1)
    node_oh = (node_ref[:] == node_iota).astype(jnp.float32)

    # Bin one-hot: (TILE, pb_pad), one 1 per real feature block. Built in
    # one shot from the flat index code + f·n_bins — padded lanes ≥ p·n_bins
    # match nothing because real flat codes are < p·n_bins. (A blockwise
    # (TILE, p, n_bins)-compare + lane-flatten would be ~22× less VPU
    # work, but Mosaic cannot lower that reshape across the lane axis.)
    feat_iota = lax.broadcasted_iota(jnp.int32, (tile, p), 1)
    flat_code = codes_ref[:, :p] + feat_iota * n_bins  # (TILE, p)
    lane_iota = lax.broadcasted_iota(jnp.int32, (tile, pb_pad), 1)
    bin_oh = jnp.zeros((tile, pb_pad), jnp.float32)
    for f in range(p):  # p is small (21 in the GGL schema) — static unroll
        bin_oh = bin_oh + (lane_iota == flat_code[:, f : f + 1]).astype(jnp.float32)

    # Weighted node one-hots for every weight vector, stacked on the
    # sublane axis: (n_weights·max_nodes, TILE) @ (TILE, pb_pad) on MXU.
    lhs = jnp.concatenate(
        [node_oh * w_ref[k, :][:, None] for k in range(n_weights)], axis=1
    )  # (TILE, n_weights*max_nodes)
    out_ref[:] += lax.dot_general(
        lhs,
        bin_oh,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit, static_argnames=("max_nodes", "n_bins", "tile", "interpret")
)
def bin_histogram_pallas(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    tile: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Weighted (node, feature, bin) histograms via the Pallas kernel.

    Args:
      codes: (n, p) int32 bin codes in [0, n_bins).
      node_of_row: (n,) int32 node ids in [0, max_nodes); rows with ids
        outside the range contribute nothing.
      weights: (K, n) f32 — e.g. (counts, counts·y) for the classifier,
        (counts, counts·ρ) for the causal forest's gradient splits.

    Returns:
      (K, max_nodes, p, n_bins) f32.
    """
    n, p = codes.shape
    k_w = weights.shape[0]
    pb = p * n_bins
    pb_pad = _round_up(pb, _LANES)
    n_pad = _round_up(max(n, tile), tile)

    codes = jnp.pad(codes, ((0, n_pad - n), (0, 0)))
    node2d = jnp.pad(
        node_of_row.astype(jnp.int32)[:, None], ((0, n_pad - n), (0, 0)),
        constant_values=-1,
    )
    weights = jnp.pad(weights.astype(jnp.float32), ((0, 0), (0, n_pad - n)))

    grid = (n_pad // tile,)
    out = pl.pallas_call(
        functools.partial(
            _hist_kernel, n_weights=k_w, max_nodes=max_nodes, p=p, n_bins=n_bins
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, p), lambda i: (i, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((k_w, tile), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((k_w * max_nodes, pb_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_w * max_nodes, pb_pad), jnp.float32),
        interpret=interpret,
    )(codes, node2d, weights)
    return out[:, :pb].reshape(k_w, max_nodes, p, n_bins)


@functools.partial(jax.jit, static_argnames=("max_nodes", "n_bins", "row_chunk"))
def bin_histogram_xla(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    row_chunk: int = 65536,
) -> jax.Array:
    """Chunked-XLA fallback with the same contract as the kernel: scans
    row chunks so the bin one-hot never exceeds ``row_chunk × p·n_bins``
    (memory-safe at 1M rows, unlike the monolithic one-hot)."""
    n, p = codes.shape
    k_w = weights.shape[0]
    n_pad = _round_up(max(n, 1), row_chunk) if n > row_chunk else n
    if n_pad != n:
        codes = jnp.pad(codes, ((0, n_pad - n), (0, 0)))
        node_of_row = jnp.pad(node_of_row, (0, n_pad - n), constant_values=-1)
        weights = jnp.pad(weights, ((0, 0), (0, n_pad - n)))
    if n_pad <= row_chunk:
        return _hist_chunk_xla(codes, node_of_row, weights, max_nodes, n_bins)

    n_chunks = n_pad // row_chunk
    codes_c = codes.reshape(n_chunks, row_chunk, p)
    node_c = node_of_row.reshape(n_chunks, row_chunk)
    w_c = weights.reshape(k_w, n_chunks, row_chunk).transpose(1, 0, 2)

    def step(acc, chunk):
        c, m, w = chunk
        return acc + _hist_chunk_xla(c, m, w, max_nodes, n_bins), None

    init = jnp.zeros((k_w, max_nodes, p, n_bins), jnp.float32)
    acc, _ = lax.scan(step, init, (codes_c, node_c, w_c))
    return acc


def _hist_chunk_xla(codes, node_of_row, weights, max_nodes, n_bins):
    n, p = codes.shape
    k_w = weights.shape[0]
    flat = codes + jnp.arange(p, dtype=jnp.int32)[None, :] * n_bins
    bin_oh = (
        jnp.zeros((n, p * n_bins), jnp.float32)
        .at[jnp.arange(n)[:, None], flat]
        .set(1.0)
    )
    node_oh = jax.nn.one_hot(node_of_row, max_nodes, dtype=jnp.float32)
    lhs = (node_oh[None, :, :] * weights[:, :, None]).reshape(k_w, n, max_nodes)
    out = jnp.einsum("knm,nb->kmb", lhs, bin_oh)
    return out.reshape(k_w, max_nodes, p, n_bins)


def bin_histogram(
    codes: jax.Array,
    node_of_row: jax.Array,
    weights: jax.Array,
    *,
    max_nodes: int,
    n_bins: int,
    backend: str = "auto",
) -> jax.Array:
    """Dispatch: compiled Pallas kernel on TPU, chunked XLA elsewhere.

    ``backend``: "auto" | "pallas" | "pallas_interpret" | "xla".
    """
    backend = resolve_hist_backend(backend, allow_onehot=False)
    if backend == "pallas":
        return bin_histogram_pallas(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins
        )
    if backend == "pallas_interpret":
        return bin_histogram_pallas(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins,
            interpret=True,
        )
    if backend == "xla":
        return bin_histogram_xla(
            codes, node_of_row, weights, max_nodes=max_nodes, n_bins=n_bins
        )
    raise ValueError(f"unknown histogram backend {backend!r}")

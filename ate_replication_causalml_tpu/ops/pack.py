"""Packed-code permutation contractions (ISSUE 12, tentpole b).

The predict path's routing and the partition kernel's regroup both
move integer bin codes through exact one-hot matmuls — the repo's
standard gather-free idiom (per-row dynamic gathers serialize on TPU).
Every code is < ``n_bins`` ≤ 128, i.e. 7 bits, but each one rides a
full f32 lane through those contractions. This module packs THREE
pre-offset 7-bit codes per f32 mantissa::

    word = c0 + 128·c1 + 128²·c2          (word < 2^21 ≤ 2^24)

so the permutation/selection matmuls contract a ``ceil(p/3)``-column
operand instead of ``p`` — 3× fewer permute MACs — and the consumer
extracts its slot back with exact f32 arithmetic (divide by a power of
two, floor, subtract): every value involved is an integer below the
24-bit mantissa, so pack → permute → unpack is **bit-exact**, not
approximate. The property tests pin the boundary codes (0 and 127 in
every slot) and the full round trip under vmapped and sharded layouts.

Two consumers (both behind the ONE config-time policy below):

* ``models/forest.py::route_rows_packed`` — the per-level routing
  contraction of ``_tree_route`` / ``apply_trees_chunked`` /
  ``_predict_cate_impl``: the route table carries the packed-WORD
  one-hot plus a slot selector instead of the p-wide feature one-hot.
* ``ops/hist_pallas.py::_hist_kernel_batched_partition`` — the
  in-kernel regroup packs the tile's raw codes once, permutes the
  packed operand per tree, and unpacks before the bin one-hot (the
  NEXT.md §2 candidate follow-up).

Packed contractions run in f32 even on TPU: a packed word (< 2^21)
does NOT fit bf16's 8 mantissa bits, so the bf16 fast path of
``route_rows`` must never see packed operands — the packed formulation
trades that bandwidth halving for the 3× MAC reduction, which is
exactly the A/B ``bench.py --predict-ab`` records.

Policy discipline (the JGL001/JGL003 dispatcher rule PR 2 established,
same shape as ``resolve_hist_mode``): :func:`resolve_predict_pack`
reads ``ATE_TPU_PREDICT_PACK`` on the host in un-jitted config code and
the result enters every jitted body as a concrete STATIC — a cached
trace can never serve a pack decision made under a different
environment. ``auto`` currently resolves to UNPACKED: the identity is
exact either way, and the MAC win's wall-clock consequence is
TPU-blocked on this image (NEXT.md §5) — the default flips only after
a hardware round measures it, exactly like the hist-mode crossover.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

ENV_PACK = "ATE_TPU_PREDICT_PACK"
PACK_MODES = ("0", "1", "auto")

#: codes per packed f32 word and the per-slot radix. 3 slots × 7 bits =
#: 21 bits < the 24-bit f32 mantissa — the largest exact packing.
PACK_SLOTS = 3
PACK_RADIX = 128  # 2^7 — exact for codes < 128, i.e. n_bins ≤ 128


def resolve_predict_pack(pack: bool | str | None = None) -> bool:
    """The single CONFIG-TIME entry for the packed-code policy.

    ``pack`` (a caller's explicit argument — bool or a mode string)
    wins when given; otherwise ``ATE_TPU_PREDICT_PACK`` ("0" | "1" |
    "auto", case-insensitive, default "auto"). A bad value raises HERE,
    at config time, never at trace time. Deliberately un-jitted
    (graftlint JGL001): callers pass the result into jitted bodies as a
    static.

    "auto" resolves to unpacked on this round — packed == unpacked is
    bit-exact, so the choice is pure wall-clock, and that measurement
    is TPU-blocked (the module docstring says why)."""
    if isinstance(pack, bool):
        return pack
    raw = pack if pack is not None else os.environ.get(ENV_PACK, "auto")
    val = str(raw).strip().lower()
    if val not in PACK_MODES:
        raise ValueError(
            f"{ENV_PACK}/pack must be one of {PACK_MODES} "
            f"(case-insensitive) or a bool, got {raw!r}"
        )
    return val == "1"


def packable(n_bins: int) -> bool:
    """Whether codes from an ``n_bins``-bin quantization fit a 7-bit
    slot exactly. ``binarize`` allows up to 256 bins; packing requires
    ≤ 128 — callers gate the packed path on this instead of raising, so
    an opted-in policy degrades to the exact unpacked path rather than
    refusing a wide-bin forest."""
    return int(n_bins) <= PACK_RADIX


def packed_width(p: int) -> int:
    """Packed column count: ``ceil(p / 3)``."""
    return -(-int(p) // PACK_SLOTS)


def pack_codes(codes: jax.Array) -> jax.Array:
    """(rows, p) integer bin codes < 128 → (rows, ceil(p/3)) f32 packed
    words; feature f lands in word ``f // 3``, slot ``f % 3``. Missing
    trailing slots pack as 0 (never read back — no feature maps to
    them). Exact: each word is an integer < 2^21."""
    rows, p = codes.shape
    p3 = packed_width(p)
    cf = jnp.pad(codes.astype(jnp.float32), ((0, 0), (0, p3 * PACK_SLOTS - p)))
    cf = cf.reshape(rows, p3, PACK_SLOTS)
    return (
        cf[:, :, 0]
        + float(PACK_RADIX) * cf[:, :, 1]
        + float(PACK_RADIX**2) * cf[:, :, 2]
    )


def extract_slot(word: jax.Array, slot: jax.Array) -> jax.Array:
    """The 7-bit code at ``slot`` (f32 values in {0, 1, 2}) of packed
    ``word`` — exact f32 arithmetic throughout: dividing by a power of
    two only moves the exponent, and floor/subtract on integers below
    2^24 are exact. Broadcasting follows jnp semantics."""
    r1, r2 = float(PACK_RADIX), float(PACK_RADIX**2)
    div = jnp.where(slot > 1.5, r2, jnp.where(slot > 0.5, r1, 1.0))
    v = jnp.floor(word / div)
    return v - r1 * jnp.floor(v / r1)


def unpack_codes(packed: jax.Array, p: int) -> jax.Array:
    """Inverse of :func:`pack_codes`: (rows, ceil(p/3)) words →
    (rows, p) f32 codes (exact)."""
    rows, p3 = packed.shape
    slots = [
        extract_slot(packed, jnp.float32(s)) for s in range(PACK_SLOTS)
    ]
    out = jnp.stack(slots, axis=2).reshape(rows, p3 * PACK_SLOTS)
    return out[:, :p]


def route_mac_model(rows: int, p: int, levels_nodes: list[int],
                    pack: bool) -> dict:
    """Analytic MAC model of the one-hot ROUTING contractions for one
    tree routed over ``rows`` query rows (the ``bench.py --predict-ab``
    record's fields; mirrors ``route_rows``/``route_rows_packed``).

    Per level with M live nodes the unpacked path contracts
    ``(rows, M) @ (M, 1+p)`` (threshold + feature one-hot broadcast)
    then the ``(rows, p)`` code-permutation dot; the packed path
    contracts ``(rows, M) @ (M, 2+p3)`` (threshold + slot + word
    one-hot) and a ``(rows, p3)`` dot. ``permute`` counts the
    code-permutation dot alone — the term packing divides by exactly
    ``p / ceil(p/3)`` (3× when 3 | p); ``useful`` is mode-independent
    by construction: every row reads ONE code per level, whatever the
    contraction that delivers it."""
    p3 = packed_width(p)
    permute = 0
    table = 0
    useful = 0
    for m in levels_nodes:
        useful += rows
        if pack:
            permute += rows * p3
            table += rows * m * (2 + p3)
        else:
            permute += rows * p
            table += rows * m * (1 + p)
    return {
        "useful_macs": useful,
        "permute_macs": permute,
        "table_macs": table,
        "total_macs": permute + table,
    }

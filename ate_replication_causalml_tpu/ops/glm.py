"""Binomial-logit GLM via IRLS — TPU-native replacement for R ``glm.fit``.

The reference fits logistic regressions for the AIPW outcome model
(``ate_functions.R:156-158, 218-220``), the GLM propensity
(``ate_functions.R:231-234``) and the inline notebook propensity
(``ate_replication.Rmd:164-168``). R's ``glm.fit`` runs iteratively
reweighted least squares with a deviance-based stopping rule
(``epsilon = 1e-8``, ``maxit = 25``); we reproduce that rule exactly so
coefficients agree with R to well below the 1e-4 parity contract
(SURVEY.md §2.3), but run it as a ``lax.while_loop`` of XLA-compiled
WLS solves — one fused (n,p)@(p,) matmul pair per iteration on the MXU.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ate_replication_causalml_tpu.ops.linalg import _PREC, _chol_solve, _spd_inverse


class GlmResult(NamedTuple):
    coef: jax.Array         # (p,)
    se: jax.Array           # (p,)
    fitted: jax.Array       # (n,) response-scale fitted probabilities
    deviance: jax.Array     # scalar
    n_iter: jax.Array       # scalar int
    converged: jax.Array    # scalar bool


def _binomial_deviance(y: jax.Array, mu: jax.Array) -> jax.Array:
    """-2 log-likelihood of Bernoulli observations (R's binomial deviance)."""
    eps = jnp.finfo(mu.dtype).tiny
    ll = y * jnp.log(jnp.maximum(mu, eps)) + (1.0 - y) * jnp.log(jnp.maximum(1.0 - mu, eps))
    return -2.0 * jnp.sum(ll)


def logistic_glm(
    x: jax.Array,
    y: jax.Array,
    epsilon: float = 1e-8,
    max_iter: int = 25,
) -> GlmResult:
    """Fit ``y ~ x`` by binomial-logit IRLS with R ``glm.fit`` semantics.

    ``x`` must already include the intercept column. Convergence is R's
    relative-deviance test ``|dev - dev_old| / (|dev| + 0.1) < epsilon``.
    Standard errors are ``sqrt(diag((X' W X)^-1))`` at the converged
    weights — identical to ``summary.glm``.
    """
    n, p = x.shape
    dtype = x.dtype

    # R's binomial initialization: mustart = (y + 1/2) / 2, eta = logit(mu).
    mu0 = (y + 0.5) / 2.0
    eta0 = jnp.log(mu0 / (1.0 - mu0))
    dev0 = _binomial_deviance(y, mu0)

    def irls_step(eta):
        mu = jax.nn.sigmoid(eta)
        w = jnp.clip(mu * (1.0 - mu), 1e-10)
        z = eta + (y - mu) / w
        xw = x * w[:, None]
        xtwx = jnp.matmul(xw.T, x, precision=_PREC)
        xtwz = jnp.matmul(xw.T, z, precision=_PREC)
        coef = _chol_solve(xtwx, xtwz)
        eta_new = jnp.matmul(x, coef, precision=_PREC)
        mu_new = jax.nn.sigmoid(eta_new)
        return coef, eta_new, _binomial_deviance(y, mu_new)

    def cond(state):
        _, _, dev, dev_old, it, done = state
        return (~done) & (it < max_iter)

    def body(state):
        coef, eta, dev, _, it, _ = state
        coef_new, eta_new, dev_new = irls_step(eta)
        done = jnp.abs(dev_new - dev) / (jnp.abs(dev_new) + 0.1) < epsilon
        return coef_new, eta_new, dev_new, dev, it + 1, done

    init = (jnp.zeros(p, dtype), eta0, dev0, dev0 + 1.0, jnp.array(0), jnp.array(False))
    coef, eta, dev, _, n_iter, converged = lax.while_loop(cond, body, init)

    mu = jax.nn.sigmoid(eta)
    w = jnp.clip(mu * (1.0 - mu), 1e-10)
    xtwx = jnp.matmul((x * w[:, None]).T, x, precision=_PREC)
    se = jnp.sqrt(jnp.clip(jnp.diag(_spd_inverse(xtwx)), 0.0))
    return GlmResult(coef=coef, se=se, fitted=mu, deviance=dev, n_iter=n_iter, converged=converged)


def predict_proba(coef: jax.Array, x: jax.Array) -> jax.Array:
    """Response-scale prediction ``sigmoid(x @ coef)`` (R ``predict(type="response")``)."""
    return jax.nn.sigmoid(jnp.matmul(x, coef, precision=_PREC))

"""Elastic-net / LASSO coordinate descent with glmnet-compatible semantics.

TPU-native replacement for the ``glmnet`` Fortran core (``elnet``/``lognet``)
invoked by the reference at ``ate_functions.R:101, 123, 139, 304-305``.
Matching which λ gets selected — and therefore the reference's LASSO point
estimates — requires reproducing glmnet's *rules*, not its code
(SURVEY.md §7.3 hard part #2):

  * internal standardization with the 1/n (weighted) variance,
  * penalty factors rescaled to mean 1, zero-penalty columns allowed
    (the "keep W unpenalized" trick, ``ate_functions.R:98``),
  * the log-linear λ path from ``λ_max = max_j |<x_j, r>_w|/(α·pf_j)``
    down to ``λ_max·lambda.min.ratio`` (1e-4 when n > p else 1e-2),
    100 values, with gaussian λ reported on the y-sd scale,
  * coordinate-descent convergence ``max_j (Δβ_j)² < thresh`` on the
    standardized scale (glmnet ``thresh=1e-7``),
  * K-fold CV with per-fold refits over the full-data λ path,
    ``lambda.min``/``lambda.1se`` selection, and R-compatible fold
    assignment (``sample(rep(seq(nfolds), length=N))``).

TPU-first shape: the O(n·p) work is two MXU matmuls (the Gram matrix
``X'WX`` and ``X'Wr``); the coordinate sweeps then run on the tiny
(p × p) Gram entirely in registers/VMEM via ``lax.while_loop`` /
``lax.fori_loop``, warm-started along the λ path with ``lax.scan``.
CV folds are just reweighted problems (held-out weight 0), so fold
fits ``vmap`` over a fold-mask matrix — no ragged shapes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ate_replication_causalml_tpu.ops.linalg import _PREC
from ate_replication_causalml_tpu.parallel.mesh import shard_map as _shard_map

DEFAULT_NLAMBDA = 100
DEFAULT_THRESH = 1e-7
MAX_SWEEPS = 2000
MAX_IRLS = 25


class ElnetPath(NamedTuple):
    """A fitted regularization path on the original data scale."""

    lambdas: jax.Array      # (L,)
    intercepts: jax.Array   # (L,)
    coefs: jax.Array        # (L, p)


class CvGlmnetResult(NamedTuple):
    path: ElnetPath         # full-data fit
    cvm: jax.Array          # (L,) mean CV loss
    cvsd: jax.Array         # (L,) SE of CV loss across folds
    lambda_min: jax.Array   # scalar
    lambda_1se: jax.Array   # scalar
    index_min: jax.Array    # scalar int
    index_1se: jax.Array    # scalar int

    def coef_at(self, which: str = "1se") -> tuple[jax.Array, jax.Array]:
        """(intercept, coefs) at lambda.1se (R ``coef(cvfit)`` default) or
        lambda.min."""
        idx = self.index_1se if which == "1se" else self.index_min
        return self.path.intercepts[idx], self.path.coefs[idx]


def _normalize_pf(penalty_factor: jax.Array, p: int) -> jax.Array:
    """glmnet rescales penalty factors to sum to nvars."""
    pf = jnp.asarray(penalty_factor)
    return pf * p / jnp.sum(pf)


def _weighted_standardize(x: jax.Array, weights: jax.Array):
    """glmnet-internal standardization: weighted mean 0, weighted 1/n
    variance 1. Returns (x_std, means, scales)."""
    xm = jnp.einsum("i,ij->j", weights, x)
    xv = jnp.einsum("i,ij->j", weights, x * x) - xm * xm
    xs = jnp.sqrt(jnp.maximum(xv, 1e-30))
    return (x - xm) / xs, xm, xs


def lambda_sequence(lambda_max: jax.Array, n: int, p: int, nlambda: int = DEFAULT_NLAMBDA):
    """glmnet's log-linear path; ratio 1e-4 if n > p else 1e-2."""
    ratio = 1e-4 if n > p else 1e-2
    return lambda_max * jnp.exp(
        jnp.linspace(0.0, float(np.log(ratio)), nlambda, dtype=lambda_max.dtype)
    )


def _cd_sweeps(gram, xty, beta0, lam, alpha, pf, thresh):
    """Coordinate-descent to convergence on the standardized Gram system.

    Solves  min 1/2 β'Gβ - c'β + λ Σ_j pf_j (α|β_j| + (1-α)/2 β_j²)
    where G = X'WX, c = X'Wr (standardized scale, G_jj ≈ 1).
    """
    p = xty.shape[0]
    denom = jnp.diag(gram) + lam * (1.0 - alpha) * pf
    thr_lam = lam * alpha * pf

    def one_coord(j, carry):
        beta, dlx = carry
        gj = xty[j] - jnp.dot(gram[j], beta) + gram[j, j] * beta[j]
        bj = jnp.sign(gj) * jnp.maximum(jnp.abs(gj) - thr_lam[j], 0.0) / denom[j]
        dlx = jnp.maximum(dlx, gram[j, j] * (bj - beta[j]) ** 2)
        return beta.at[j].set(bj), dlx

    def sweep(state):
        beta, _, it = state
        beta, dlx = lax.fori_loop(0, p, one_coord, (beta, jnp.zeros((), beta.dtype)))
        return beta, dlx, it + 1

    def cond(state):
        _, dlx, it = state
        return (dlx >= thresh) & (it < MAX_SWEEPS)

    beta, _, _ = lax.while_loop(
        cond, sweep, (beta0, jnp.full((), jnp.inf, beta0.dtype), jnp.array(0))
    )
    return beta


def elnet_gaussian(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array | None = None,
    penalty_factor: jax.Array | None = None,
    alpha: float = 1.0,
    nlambda: int = DEFAULT_NLAMBDA,
    lambdas: jax.Array | None = None,
    thresh: float = DEFAULT_THRESH,
) -> ElnetPath:
    """Gaussian elastic net over a λ path (glmnet ``family="gaussian"``).

    Observation weights support is what makes CV folds free: a held-out
    row is weight 0 and the fold fit standardizes on training rows only,
    exactly like glmnet's per-fold refit.
    """
    n, p = x.shape
    w = jnp.ones(n, x.dtype) if weights is None else jnp.asarray(weights, x.dtype)
    w = w / jnp.sum(w)
    pf = (
        jnp.ones(p, x.dtype)
        if penalty_factor is None
        else _normalize_pf(penalty_factor, p).astype(x.dtype)
    )

    xs_std, xm, xs = _weighted_standardize(x, w)
    ym = jnp.dot(w, y)
    yv = jnp.dot(w, y * y) - ym * ym
    ys = jnp.sqrt(jnp.maximum(yv, 1e-30))
    v = (y - ym) / ys

    # Gram system on the standardized scale (the only O(n p^2) work —
    # one MXU matmul).
    xw = xs_std * w[:, None]
    gram = jnp.matmul(xw.T, xs_std, precision=_PREC)
    xty = jnp.matmul(xw.T, v, precision=_PREC)

    if lambdas is None:
        g = jnp.abs(xty) / jnp.where(pf > 0, pf, jnp.inf)
        lam_max = jnp.max(g) / max(alpha, 1e-3)
        lams_std = lambda_sequence(lam_max, n, p, nlambda)
    else:
        lams_std = jnp.asarray(lambdas, x.dtype) / ys

    def step(beta, lam):
        beta = _cd_sweeps(gram, xty, beta, lam, alpha, pf, thresh)
        return beta, beta

    _, betas_std = lax.scan(step, jnp.zeros(p, x.dtype), lams_std)

    coefs = betas_std * ys / xs[None, :]
    intercepts = ym - jnp.einsum("lj,j->l", coefs, xm)
    return ElnetPath(lambdas=lams_std * ys, intercepts=intercepts, coefs=coefs)


def lognet_binomial(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array | None = None,
    penalty_factor: jax.Array | None = None,
    alpha: float = 1.0,
    nlambda: int = DEFAULT_NLAMBDA,
    lambdas: jax.Array | None = None,
    thresh: float = DEFAULT_THRESH,
) -> ElnetPath:
    """Binomial-logit elastic net (glmnet ``family="binomial"``):
    outer IRLS quadratic approximation, inner penalized weighted CD,
    warm-started down the λ path."""
    n, p = x.shape
    w_obs = jnp.ones(n, x.dtype) if weights is None else jnp.asarray(weights, x.dtype)
    w_obs = w_obs / jnp.sum(w_obs)
    pf = (
        jnp.ones(p, x.dtype)
        if penalty_factor is None
        else _normalize_pf(penalty_factor, p).astype(x.dtype)
    )

    xs_std, xm, xs = _weighted_standardize(x, w_obs)

    ybar = jnp.dot(w_obs, y)
    if lambdas is None:
        r0 = w_obs * (y - ybar)
        g = jnp.abs(jnp.matmul(xs_std.T, r0, precision=_PREC)) / jnp.where(pf > 0, pf, jnp.inf)
        lam_max = jnp.max(g) / max(alpha, 1e-3)
        lams = lambda_sequence(lam_max, n, p, nlambda)
    else:
        lams = jnp.asarray(lambdas, x.dtype)

    b0_init = jnp.log(ybar / (1.0 - ybar))

    def fit_one(carry, lam):
        beta, b0 = carry

        def irls_body(state):
            beta, b0, _, it = state
            eta = b0 + jnp.matmul(xs_std, beta, precision=_PREC)
            mu = jax.nn.sigmoid(eta)
            wq = jnp.clip(mu * (1.0 - mu), 1e-9) * w_obs
            z_resid = w_obs * (y - mu)  # working residual * weights
            sw = jnp.sum(wq)
            # Quadratic subproblem on standardized x with IRLS weights:
            # gram = X' diag(wq) X, c_j = x_j'(wq * z) with z the working
            # response centered at current fit.
            xwq = xs_std * wq[:, None]
            gram = jnp.matmul(xwq.T, xs_std, precision=_PREC)
            # c = X'[wq*(eta - etabar) + w*(y-mu)] expressed incrementally:
            # keep intercept out of the penalized system by profiling it.
            xbar_w = jnp.matmul(xwq.T, jnp.ones(n, x.dtype), precision=_PREC) / sw
            gram = gram - sw * jnp.outer(xbar_w, xbar_w)
            cvec = (
                jnp.matmul(xwq.T, eta, precision=_PREC)
                - sw * xbar_w * (jnp.dot(wq, eta) / sw)
                + jnp.matmul(xs_std.T, z_resid, precision=_PREC)
                - xbar_w * jnp.sum(z_resid)
            )
            beta_new = _cd_sweeps(gram, cvec, beta, lam, alpha, pf, thresh)
            # Profiled intercept update.
            b0_new = (
                jnp.dot(wq, eta) + jnp.sum(z_resid) - jnp.dot(jnp.matmul(xwq.T, jnp.ones(n, x.dtype), precision=_PREC), beta_new)
            ) / sw
            delta = jnp.maximum(jnp.max((beta_new - beta) ** 2), (b0_new - b0) ** 2)
            return beta_new, b0_new, delta, it + 1

        def irls_cond(state):
            _, _, delta, it = state
            return (delta >= thresh * 10.0) & (it < MAX_IRLS)

        beta, b0, _, _ = lax.while_loop(
            irls_cond,
            irls_body,
            (beta, b0, jnp.full((), jnp.inf, x.dtype), jnp.array(0)),
        )
        return (beta, b0), (beta, b0)

    (_, _), (betas_std, b0s) = lax.scan(fit_one, (jnp.zeros(p, x.dtype), b0_init), lams)

    coefs = betas_std / xs[None, :]
    intercepts = b0s - jnp.einsum("lj,j->l", coefs, xm)
    return ElnetPath(lambdas=lams, intercepts=intercepts, coefs=coefs)


def default_foldid(key: jax.Array, n: int, nfolds: int = 10) -> jax.Array:
    """The fold assignment :func:`cv_glmnet` derives from ``key`` when
    no ``foldid`` is given — exposed so the sweep scheduler can compute
    fold masks once as a declared artifact and pass them in explicitly.
    jax PRNG results are jit-invariant, so
    ``cv_glmnet(x, y, key=k)`` and
    ``cv_glmnet(x, y, foldid=default_foldid(k, n))`` are bit-identical
    (asserted in tests/test_lasso.py)."""
    base = jnp.resize(jnp.arange(1, nfolds + 1), (n,))
    return jax.random.permutation(key, base)


def r_compat_foldid(n: int, nfolds: int, rng) -> np.ndarray:
    """cv.glmnet's fold assignment: ``sample(rep(seq(nfolds), length=N))``
    under R's RNG (host-side, for the parity contract)."""
    base = np.resize(np.arange(1, nfolds + 1), n)
    perm = rng.sample_int(n, n)
    return base[perm]


def _binomial_deviance_loss(y, eta, w):
    mu = jax.nn.sigmoid(eta)
    eps = 1e-10
    ll = y * jnp.log(jnp.maximum(mu, eps)) + (1.0 - y) * jnp.log(jnp.maximum(1.0 - mu, eps))
    return -2.0 * jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), eps)


def cv_glmnet(
    x: jax.Array,
    y: jax.Array,
    family: str = "gaussian",
    alpha: float = 1.0,
    penalty_factor: jax.Array | None = None,
    nfolds: int = 10,
    foldid: jax.Array | None = None,
    key: jax.Array | None = None,
    nlambda: int = DEFAULT_NLAMBDA,
    fold_axis: str | None = None,
) -> CvGlmnetResult:
    """See :func:`_cv_glmnet_impl`. This thin wrapper resolves the
    active mesh *outside* the jit boundary when ``fold_axis`` is given —
    the mesh is then a static (hashable) argument, so a later call under
    a different mesh recompiles instead of silently reusing a stale
    device assignment baked in at trace time."""
    mesh = None
    if fold_axis is not None:
        from ate_replication_causalml_tpu.parallel.mesh import get_mesh

        mesh = get_mesh()
    return _cv_glmnet_impl(
        x, y, family=family, alpha=alpha, penalty_factor=penalty_factor,
        nfolds=nfolds, foldid=foldid, key=key, nlambda=nlambda,
        fold_axis=fold_axis, mesh=mesh,
    )


@functools.partial(
    jax.jit,
    static_argnames=("family", "alpha", "nfolds", "nlambda", "fold_axis", "mesh"),
)
def _cv_glmnet_impl(
    x: jax.Array,
    y: jax.Array,
    family: str = "gaussian",
    alpha: float = 1.0,
    penalty_factor: jax.Array | None = None,
    nfolds: int = 10,
    foldid: jax.Array | None = None,
    key: jax.Array | None = None,
    nlambda: int = DEFAULT_NLAMBDA,
    fold_axis: str | None = None,
    mesh=None,
) -> CvGlmnetResult:
    """K-fold cross-validated elastic net (R ``cv.glmnet``).

    ``foldid`` (1-based, as in R) may come from ``r_compat_foldid`` for
    bit-parity; otherwise folds are drawn from ``key`` on device. Fold
    fits share one vmapped weighted solve — on a mesh, ``fold_axis``
    names the mesh axis to shard the fold batch over (SURVEY.md §2.4:
    CV folds are one of the embarrassingly parallel axes).
    """
    n, p = x.shape
    if foldid is None:
        if key is None:
            key = jax.random.key(0)
        foldid = default_foldid(key, n, nfolds)
    foldid = jnp.asarray(foldid)

    fit = elnet_gaussian if family == "gaussian" else lognet_binomial
    full = fit(x, y, penalty_factor=penalty_factor, alpha=alpha, nlambda=nlambda)

    def fold_fit(k):
        train_w = (foldid != k).astype(x.dtype)
        path = fit(
            x, y, weights=train_w, penalty_factor=penalty_factor, alpha=alpha,
            lambdas=full.lambdas,
        )
        eta = path.intercepts[:, None] + jnp.matmul(path.coefs, x.T, precision=_PREC)
        test_w = 1.0 - train_w
        if family == "gaussian":
            loss = jnp.sum(test_w[None, :] * (y[None, :] - eta) ** 2, axis=1) / jnp.sum(test_w)
        else:
            loss = jax.vmap(lambda e: _binomial_deviance_loss(y, e, test_w))(eta)
        return loss, jnp.sum(test_w)

    if fold_axis is None:
        fold_ids = jnp.arange(1, nfolds + 1)
        losses, fold_n = jax.vmap(fold_fit)(fold_ids)  # (K, L), (K,)
    else:
        # Shard the fold batch over the active mesh's ``fold_axis``:
        # each device fits its folds against replicated data; XLA
        # all_gathers the (K, L) loss matrix. Fold count pads up to a
        # multiple of the axis size (padded ids select no test rows;
        # their losses are sliced off before selection).
        from jax.sharding import PartitionSpec as _P

        ax = mesh.shape[fold_axis]
        k_pad = -(-nfolds // ax) * ax
        fold_ids = jnp.arange(1, k_pad + 1)
        sharded = _shard_map(
            lambda ids: jax.vmap(fold_fit)(ids),
            mesh=mesh,
            in_specs=_P(fold_axis),
            out_specs=(_P(fold_axis), _P(fold_axis)),
            check_vma=False,  # fold_fit closes over replicated x/y/path
        )
        losses, fold_n = sharded(fold_ids)
        losses, fold_n = losses[:nfolds], fold_n[:nfolds]

    cvm, cvsd, idx_min, idx_1se = cv_select(losses, fold_n, nfolds)
    return CvGlmnetResult(
        path=full,
        cvm=cvm,
        cvsd=cvsd,
        lambda_min=full.lambdas[idx_min],
        lambda_1se=full.lambdas[idx_1se],
        index_min=idx_min,
        index_1se=idx_1se,
    )


def cv_select(losses: jax.Array, fold_n: jax.Array, nfolds: int):
    """cv.glmnet's λ-selection rules, isolated so an independent oracle
    can test them (tests/test_lasso.py transcribes glmnet's published
    ``cvstats``/``getOptcv`` R code over random inputs):

      * ``cvstats``: cvm is the fold-size-weighted mean of the per-fold
        losses, cvsd = sqrt(weighted.mean((cvraw − cvm)², w)/(K−1)) with
        w = fold test sizes. A plain mean agrees only to O(1/n) — which
        can flip the selected λ index near ties, a direct 1e-4-parity
        risk for the estimators whose τ̂ depends on λ.
      * ``getOptcv``: lambda.min is the LARGEST λ with cvm ≤ min(cvm),
        lambda.1se the largest λ with cvm ≤ cvm[min] + cvsd[min]; the
        path is decreasing so both are FIRST indices along it.

    Args: losses (K, L) per-fold losses; fold_n (K,) test sizes.
    Returns: (cvm (L,), cvsd (L,), idx_min, idx_1se).
    """
    wts = (fold_n / jnp.sum(fold_n))[:, None]
    cvm = jnp.sum(wts * losses, axis=0)
    cvsd = jnp.sqrt(
        jnp.sum(wts * (losses - cvm[None, :]) ** 2, axis=0)
        / jnp.asarray(nfolds - 1, losses.dtype)
    )
    # argmin/argmax return the first occurrence — the largest λ among
    # exact ties, matching R's max(lambda[cvm <= cvmin]).
    idx_min = jnp.argmin(cvm)
    idx_1se = jnp.argmax(cvm <= cvm[idx_min] + cvsd[idx_min])
    return cvm, cvsd, idx_min, idx_1se


def predict_path(path: ElnetPath, x: jax.Array, index) -> jax.Array:
    """Linear predictor at one path index."""
    return path.intercepts[index] + jnp.matmul(x, path.coefs[index], precision=_PREC)

"""Bootstrap resampling engine — the north-star hot loop.

The reference computes its AIPW bootstrap SE as a **serial** R loop of
B=1000 replicates (``ate_functions.R:188-195``), each replicate being a
with-replacement resample of five precomputed vectors followed by two
means (``ate_functions.R:267-283`` — nuisances are *not* refit). On TPU
this is a pure gather+reduce workload: we ``vmap`` the replicate over a
(B, n) index matrix and ``shard_map`` the replicate axis across the
device mesh — zero inter-device communication until the final SD.

Three resampling modes, chosen by scale:
  * R-compat: indices precomputed by ``utils.rrandom`` on host,
    reproducing R's ``sample(n, n, replace=TRUE)`` stream bit-for-bit
    for the 1e-4 validation contract;
  * exact multinomial: ``jax.random.randint`` (threefry) index draws +
    device gather — the default for n ≤ ~100k (XLA's TPU gather
    sustains ~1.2e8 rows/s, fine at reference scale);
  * Poisson bootstrap: per-(replicate, row) Poisson(1) weights with a
    normalized weighted mean — the large-n default. Random gathers are
    the TPU bottleneck (measured 8.4 ms per 1M-row replicate); Poisson
    weights replace them with streaming RNG + elementwise compares +
    one reduction (0.74 ms per 1M-row replicate, RNG-bound). This is
    the standard massive-data bootstrap (Chamandy et al. 2012,
    "Estimating Uncertainty for Massive Data Streams"): multinomial
    counts conditioned on total n ~ iid Poisson(1), and the normalized
    statistic differs from the exact bootstrap only at O(1/n).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ate_replication_causalml_tpu.parallel.mesh import get_mesh, shard_axis_size
from ate_replication_causalml_tpu.parallel.mesh import shard_map as _shard_map


def bootstrap_indices(key: jax.Array, n: int, n_boot: int) -> jax.Array:
    """(B, n) with-replacement row indices from the on-device threefry stream."""
    return jax.random.randint(key, (n_boot, n), 0, n, dtype=jnp.int32)


def _aipw_tau(w, y, p, mu0, mu1, control_sign=1.0):
    """The reference's AIPW combination (``ate_functions.R:183-185``):
    ``mean(w(y-mu1)/p + (1-w)(y-mu0)/(1-p)) + mean(mu1 - mu0)`` with
    R's ``na.rm=TRUE`` on the first mean.

    **Reference sign quirk** (discovered by the double-robustness
    property test): standard AIPW SUBTRACTS the control augmentation —
    ``w(y-mu1)/p − (1-w)(y-mu0)/(1-p) + mean(mu1-mu0)`` — but the
    reference ADDS it, while its own sandwich influence function
    (``ate_functions.R:197``) uses the standard minus convention. The
    published estimator is therefore consistent only when BOTH nuisances
    are correct (each augmentation term is then mean-zero either way)
    and loses the double-robustness protection the method is named for.
    ``control_sign``: +1.0 reproduces the reference (``compat="r"``,
    the default everywhere — the 1e-4 parity contract needs it), −1.0
    is textbook AIPW (``compat="fixed"``)."""
    est1 = w * (y - mu1) / p + control_sign * (1.0 - w) * (y - mu0) / (1.0 - p)
    est2 = mu1 - mu0
    return jnp.nanmean(est1) + jnp.mean(est2)


def _replicate(idx, w, y, p, mu0, mu1, control_sign=1.0):
    """One bootstrap replicate (``ate_functions.R:267-283``): gather the
    five precomputed vectors, recompute the AIPW combination."""
    return _aipw_tau(w[idx], y[idx], p[idx], mu0[idx], mu1[idx], control_sign)


@functools.partial(jax.jit, static_argnames=())
def aipw_bootstrap_taus(indices, w, y, p, mu0, mu1, control_sign=1.0):
    """All replicates at once: vmap over the (B, n) index matrix."""
    return jax.vmap(
        _replicate, in_axes=(0, None, None, None, None, None, None)
    )(indices, w, y, p, mu0, mu1, control_sign)


def sd(x: jax.Array) -> jax.Array:
    """R ``sd()``: n-1 denominator (``ate_functions.R:195``)."""
    return jnp.std(x, ddof=1)


# Above this row count the exact-gather path switches to Poisson
# weights (gather is the TPU bottleneck; see module docstring).
POISSON_AUTO_THRESHOLD = 100_000


def aipw_bootstrap_se(
    w,
    y,
    p,
    mu0,
    mu1,
    *,
    key: jax.Array | None = None,
    n_boot: int = 1000,
    indices=None,
    style: str = "auto",
    chunk: int | None = None,
    control_sign: float = 1.0,
) -> jax.Array:
    """Bootstrap SE of the AIPW estimator, single-device path.

    Pass ``indices`` (B, n) for the R-compat stream, or ``key`` for the
    on-device fast path. ``style``: 'auto' (multinomial below
    ``POISSON_AUTO_THRESHOLD`` rows, Poisson above), 'multinomial', or
    'poisson'.
    """
    if indices is not None:
        taus = aipw_bootstrap_taus(indices, w, y, p, mu0, mu1, control_sign)
        return sd(taus)
    if key is None:
        raise ValueError("provide either key= or indices=")
    n = w.shape[0]
    if style == "auto":
        style = "poisson" if n > POISSON_AUTO_THRESHOLD else "multinomial"
    if chunk is None:
        chunk = n_boot if n * n_boot <= 2**27 else max(1, 2**27 // n)
        while n_boot % chunk:
            chunk -= 1
    if style == "poisson":
        taus = aipw_bootstrap_taus_poisson(
            w, y, p, mu0, mu1, key=key, n_boot=n_boot, chunk=chunk,
            control_sign=control_sign,
        )
    elif style == "multinomial":
        taus = aipw_bootstrap_taus_chunked(
            w, y, p, mu0, mu1, key=key, n_boot=n_boot, chunk=chunk,
            control_sign=control_sign,
        )
    else:
        raise ValueError(f"unknown bootstrap style {style!r}")
    return sd(taus)


# Poisson(1) CDF through count 11 — P(X > 11) < 3e-9, far below any
# bootstrap resolution; counts are generated by unrolled compares
# against these thresholds (vectorizes on the VPU; no searchsorted).
_POIS1_CDF = tuple(
    float(s)
    for s in __import__("itertools").accumulate(
        2.718281828459045**-1 / __import__("math").factorial(j) for j in range(12)
    )
)
# The same thresholds on the raw uint32 lattice: comparing random bits
# directly skips the bits->float conversion (~9% off the 10k x 1M
# bootstrap on TPU v5-lite; the RNG is the bench's bottleneck).
_POIS1_CDF_U32 = tuple(
    np.uint32(min(t, 1.0 - 2.0**-32) * 2.0**32) for t in _POIS1_CDF
)


def _poisson1_counts(key: jax.Array, shape) -> jax.Array:
    bits = jax.random.bits(key, shape, jnp.uint32)
    c = jnp.zeros(shape, jnp.float32)
    for t in _POIS1_CDF_U32:
        c = c + (bits > t).astype(jnp.float32)
    return c


def aipw_bootstrap_taus_poisson(
    w, y, p, mu0, mu1, *, key: jax.Array, n_boot: int, chunk: int = 25,
    control_sign: float = 1.0,
) -> jax.Array:
    """Poisson-bootstrap replicate taus (the large-n fast path).

    Each replicate reweights rows by iid Poisson(1) counts and computes
    the count-weighted AIPW combination with R's ``na.rm=TRUE``
    semantics on the est1 term: NaN entries (0/0 when an unclipped
    propensity saturates at a row with ``y == mu``) contribute nothing
    to the numerator and are excluded from the denominator count —
    exactly like the point estimate's ``nanmean`` — while ±Inf entries
    propagate, as they do through R's ``mean(..., na.rm=TRUE)``. In the
    common all-finite case this reduces to a weighted mean of
    ``est1 + est2``.
    """
    if n_boot % chunk:
        raise ValueError(f"n_boot={n_boot} must be a multiple of chunk={chunk}")
    w, y, p, mu0, mu1 = map(jnp.asarray, (w, y, p, mu0, mu1))
    est1 = w * (y - mu1) / p + control_sign * (1.0 - w) * (y - mu0) / (1.0 - p)
    notnan = ~jnp.isnan(est1)
    e1 = jnp.where(notnan, est1, 0.0)
    fin = notnan.astype(e1.dtype)
    est2 = mu1 - mu0
    keys = jax.random.split(key, n_boot // chunk)

    def one_chunk(k):
        c = _poisson1_counts(k, (chunk, e1.shape[0])).astype(e1.dtype)
        s_e1 = jnp.sum(c * e1[None, :], axis=1)
        s_fin = jnp.sum(c * fin[None, :], axis=1)
        s_e2 = jnp.sum(c * est2[None, :], axis=1)
        s_c = jnp.sum(c, axis=1)
        return s_e1 / s_fin + s_e2 / s_c

    return jax.lax.map(one_chunk, keys).reshape(-1)


def aipw_bootstrap_taus_chunked(
    w, y, p, mu0, mu1, *, key: jax.Array, n_boot: int, chunk: int = 32,
    control_sign: float = 1.0,
) -> jax.Array:
    """All replicate taus with bounded memory: ``lax.map`` over chunks of
    replicates, each chunk drawing its own (chunk, n) index block.

    At the 1M-row north-star scale a full (10k, 1M) int32 index matrix
    is 40 GB; chunking keeps the working set at ``chunk * n * 4`` bytes
    (128 MB at chunk=32) while XLA pipelines RNG, gather, and reduction.

    Because one index stream resamples all five precomputed vectors
    jointly, each replicate gathers the two per-row terms (``est1`` with
    its possible NaNs, ``est2``), not five vectors; ``nanmean`` on the
    gathered est1 reproduces R's ``na.rm=TRUE`` exactly
    (``ate_functions.R:281``).
    """
    if n_boot % chunk:
        raise ValueError(f"n_boot={n_boot} must be a multiple of chunk={chunk}")
    w, y, p, mu0, mu1 = map(jnp.asarray, (w, y, p, mu0, mu1))
    n = w.shape[0]
    est1 = w * (y - mu1) / p + control_sign * (1.0 - w) * (y - mu0) / (1.0 - p)
    est2 = mu1 - mu0
    keys = jax.random.split(key, n_boot // chunk)

    def one_chunk(k):
        idx = jax.random.randint(k, (chunk, n), 0, n, dtype=jnp.int32)
        g1 = est1.at[idx].get(mode="promise_in_bounds")
        g2 = est2.at[idx].get(mode="promise_in_bounds")
        return jnp.nanmean(g1, axis=1) + jnp.mean(g2, axis=1)

    return jax.lax.map(one_chunk, keys).reshape(-1)


def aipw_bootstrap_se_sharded(
    w,
    y,
    p,
    mu0,
    mu1,
    *,
    key: jax.Array,
    n_boot: int = 10_000,
    axis_name: str = "boot",
    chunk: int | None = None,
    style: str = "auto",
    control_sign: float = 1.0,
) -> jax.Array:
    """Mesh-parallel bootstrap SE: replicates sharded over ``axis_name``.

    Each device draws its own replicate indices from a folded key and
    reduces its taus locally; the only collective is the final
    ``all_gather`` of B scalars for the SD — pure ICI-friendly
    embarrassing parallelism (SURVEY.md §2.4). Data vectors are
    replicated (5 × n floats; at n=1M-row f32 that is ~20 MB/device).
    """
    mesh = get_mesh()
    n_dev = shard_axis_size(mesh, axis_name)
    if n_boot % n_dev:
        raise ValueError(f"n_boot={n_boot} must divide evenly over {n_dev} devices")
    per_dev = n_boot // n_dev
    n = w.shape[0]

    local_style = style
    if local_style == "auto":
        local_style = "poisson" if n > POISSON_AUTO_THRESHOLD else "multinomial"
    local_chunk = chunk
    if local_chunk is None:
        local_chunk = per_dev if n * per_dev <= 2**27 else max(1, 2**27 // n)
        while per_dev % local_chunk:
            local_chunk -= 1
    taus_fn = (
        aipw_bootstrap_taus_poisson if local_style == "poisson" else aipw_bootstrap_taus_chunked
    )

    def shard_fn(key, w, y, p, mu0, mu1):
        my_key = jax.random.fold_in(key[0], jax.lax.axis_index(axis_name))
        taus = taus_fn(
            w, y, p, mu0, mu1, key=my_key, n_boot=per_dev,
            chunk=local_chunk, control_sign=control_sign,
        )
        return jax.lax.all_gather(taus, axis_name, tiled=True)

    fn = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    keys = jnp.broadcast_to(key, (n_dev, *key.shape))
    taus = fn(keys, w, y, p, mu0, mu1)
    return sd(taus)

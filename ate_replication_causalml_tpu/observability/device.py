"""Device / compile instrumentation (the tentpole's part 2, JAX side).

Three capture surfaces, all host-side and all failure-tolerant (a
telemetry probe must never take down the run it observes):

* :func:`record_compiled_cost` — ``jax.stages.Compiled.cost_analysis()``
  (flops / bytes accessed) and ``memory_analysis()`` (where the backend
  implements it) per jitted entry point, as gauges labeled by entry
  name. This is what lets bench.py report MFU from the compiler's own
  FLOP count next to its analytic estimate.
* :func:`record_device_memory` — ``Device.memory_stats()`` gauges per
  local device (TPU reports bytes_in_use / peak_bytes_in_use etc.; CPU
  returns nothing and is skipped).
* :func:`install_jax_monitoring` — bridges ``jax.monitoring``'s
  compilation-cache events (hits / misses / retrieval time / time
  saved) into the registry, and pre-creates every compile-cache counter
  at zero so "cache never used" is visible as an explicit 0 in
  metrics.json rather than a missing key.

``watch_cache_dir`` adds a snapshot-time collector that scans the
persistent-cache directory for entry-count / total-bytes gauges (and
entries written since the watch began — the write counter the cache
API itself does not expose).

JAX is imported lazily inside functions: the observability package
stays importable (and testable) without initializing a backend.
"""

from __future__ import annotations

import os

from ate_replication_causalml_tpu.observability.registry import (
    REGISTRY,
    bucket_histogram,
    counter,
    enabled,
    gauge,
    histogram,
)

_CACHE_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "compile_cache_hits_total",
    "/jax/compilation_cache/cache_misses": "compile_cache_misses_total",
    "/jax/compilation_cache/tasks_using_cache": "compile_cache_tasks_total",
    "/jax/compilation_cache/task_disabled_cache": "compile_cache_disabled_tasks_total",
    "/jax/compilation_cache/compile_requests_use_cache": "compile_cache_requests_total",
}
_CACHE_DURATION_METRICS = {
    "/jax/compilation_cache/compile_time_saved_sec": "compile_cache_time_saved_seconds",
    "/jax/compilation_cache/cache_retrieval_time_sec": "compile_cache_retrieval_seconds",
}

#: jax.monitoring duration events that mean "jax traced / lowered /
#: backend-compiled something", bridged into jax_compiles_total{kind=}.
#: This counter is the serving daemon's steady-state no-compile PROOF
#: (ISSUE 6): after startup, a serving window must leave it unchanged —
#: asserted from the registry, not inferred from timings.
_COMPILE_EVENT_KINDS = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "lower",
    "/jax/core/compile/backend_compile_duration": "backend_compile",
}

#: The shared pad/masked-fraction bucket ladder (ISSUE 12): the
#: daemon and the pre-creation below MUST agree — the registry rejects
#: re-creation of a bucket-histogram family with different bounds.
PAD_FRACTION_BOUNDS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)

_installed = False
_WATCHED_CACHE_DIRS: set[str] = set()


def install_jax_monitoring() -> bool:
    """Register jax.monitoring listeners for the compilation-cache
    events (idempotent; returns whether listeners are active). Always
    pre-creates the counters at zero — the metrics.json contract is
    that cache keys are PRESENT on every run, zero or not."""
    global _installed
    if not enabled():
        return False
    for name in _CACHE_EVENT_COUNTERS.values():
        counter(name, "jax compilation-cache events").inc(0)
    for name in _CACHE_DURATION_METRICS.values():
        histogram(name, "jax compilation-cache durations")
    # The shard retry families are part of the same "present on every
    # instrumented run" contract (scripts/check_metrics_schema.py), but
    # run_shards only creates them when a dispatch loop actually runs —
    # a bench mode that never fans out would otherwise export a pair
    # that fails its own validator.
    for name in ("shard_attempts_total", "shard_retries_total",
                 "shard_failures_total", "shard_backoff_seconds_total"):
        counter(name, "run_shards retry telemetry").inc(0)
    # Resilience-layer families (ISSUE 3): "no chaos injected" and "no
    # torn checkpoint lines" are reported facts, not missing keys.
    counter("chaos_injections_total",
            "faults injected by the chaos harness").inc(0)
    counter("checkpoint_torn_lines_total",
            "unparsable results.jsonl lines skipped on resume").inc(0)
    # Scheduler/cache families (ISSUE 4): present on every instrumented
    # run — a sequential sweep reports zero prefetches, not a missing
    # key (scripts/check_metrics_schema.py REQUIRED_COUNTERS).
    counter("nuisance_cache_requests_total",
            "nuisance artifact cache requests by artifact and hit/miss"
            ).inc(0)
    counter("scheduler_prefetch_total",
            "compile-prefetch lane outcomes by stage and status").inc(0)
    # Histogram-kernel mode family (ISSUE 10): every streaming grow
    # meters its per-level kernel-call plan by {mode, engine} — "the
    # partition kernel never ran" is a recorded 0 on every instrumented
    # run, and a dense-only flagship fit under ATE_TPU_HIST_MODE=auto
    # is visible as such.
    counter("hist_kernel_dispatch_total",
            "streaming histogram kernel calls by kernel mode and engine"
            ).inc(0)
    # Artifact-plane families (ISSUE 8): every byte an artifact moves
    # across a layout boundary is metered (parallel/shardio.py) — "no
    # artifact crossed the host" is a recorded 0, and a nonzero
    # host_bounce path on a scheduled sweep is a regression.
    counter("artifact_transfer_bytes_total",
            "artifact-plane bytes moved by path (host_upload / "
            "device_reshard / device_handoff / host_gather / host_bounce)"
            ).inc(0)
    counter("artifact_reshard_total",
            "artifact-plane shard/gather/reshard calls by compile status"
            ).inc(0)
    # Serving families (ISSUE 6): the daemon's request/reject counters
    # and the compile-event bridge are contract families too — a bench
    # that never serves exports explicit zeros, and the bucket-histogram
    # ladder is fixed here once so every emitter shares it.
    counter("serving_requests_total",
            "CATE serving requests by terminal status").inc(0)
    counter("serving_rejected_total",
            "CATE serving rejections by reason").inc(0)
    counter("jax_compiles_total",
            "jax trace/lower/backend-compile events by kind").inc(0)
    bucket_histogram("serving_request_seconds",
                     "served request latency (enqueue to reply)")
    # Serving lifecycle decomposition families (ISSUE 7): the per-phase
    # seconds counter and the batch close-reason counter are contract
    # families ("no batch ever closed" is a recorded 0); the per-phase
    # bucket-histogram ladder is fixed here once so every emitter
    # shares comparable buckets.
    counter("serving_phase_seconds_total",
            "summed per-request lifecycle phase seconds").inc(0)
    counter("serving_batch_close_total",
            "micro-batch close reasons").inc(0)
    bucket_histogram("serving_phase_seconds",
                     "per-request lifecycle phase durations")
    # Train-to-serve fleet families (ISSUE 11): "nothing ever rotated",
    # "no fleet request was routed" and "no retrain ever retried" are
    # reported facts on every instrumented run — and a nonzero
    # rotations{status=refused} is how a refused corrupt candidate
    # stays auditable after the fact.
    counter("serving_rotations_total",
            "checkpoint hot-swap rotations by model and status").inc(0)
    counter("serving_fleet_requests_total",
            "fleet-routed serving requests by model and terminal status"
            ).inc(0)
    counter("serving_retrain_total",
            "retrain supervisor runs by model and terminal status").inc(0)
    counter("serving_retrain_retries_total",
            "retrain attempts retried after a transient failure").inc(0)
    # Predict-path families (ISSUE 12): the pad/masked split. ``pad``
    # is TRUE waste (unmasked garbage rows a per-bucket dispatch
    # computes and discards); ``masked`` is a fused dispatch's
    # deterministic exact-zero region. Both fractions share one fixed
    # ladder (the daemon must pass the same bounds), and the row-count
    # counters are the REQUIRED_COUNTERS contract pair — "no row was
    # ever padded/masked" is a recorded 0 on every instrumented run.
    counter("serving_pad_rows_total",
            "unmasked pad rows dispatched by per-bucket executables"
            ).inc(0)
    counter("serving_masked_rows_total",
            "masked (exact-zero) rows dispatched by fused executables"
            ).inc(0)
    bucket_histogram(
        "serving_pad_fraction",
        "unmasked pad fraction of per-bucket dispatches (true waste)",
        bounds=PAD_FRACTION_BOUNDS,
    )
    bucket_histogram(
        "serving_masked_fraction",
        "masked fraction of fused-bucket dispatches (exact zeros)",
        bounds=PAD_FRACTION_BOUNDS,
    )
    # Deadline/watchdog/drain families (ISSUE 14): "no lane ever
    # stalled", "no deadline ever expired" and "no drain ever ran" are
    # recorded zeros on every instrumented run — a nonzero
    # watchdog_stalls_total after a serving session is the wedge that
    # used to be silent.
    counter("watchdog_stalls_total",
            "watchdog-detected lane stall episodes").inc(0)
    counter("serving_deadline_exceeded_total",
            "requests rejected typed for an expired deadline, by phase"
            ).inc(0)
    counter("drain_total", "graceful-drain outcomes").inc(0)
    # Scenario-matrix families (ISSUE 13): cell outcomes by column, the
    # batch dispatch meter (vmapped vs sequential — the O(columns)
    # executables contract's denominator), and the per-column AOT
    # compile count. "No matrix ever ran" is a recorded 0 on every
    # instrumented run.
    counter("scenario_cells_total",
            "scenario-matrix cells by column and computed/resumed/failed "
            "status").inc(0)
    counter("scenario_batch_dispatch_total",
            "scenario-matrix batch dispatches by column and "
            "vmapped/sequential mode").inc(0)
    counter("scenario_column_compile_total",
            "scenario column executables AOT-compiled, by column and kind"
            ).inc(0)
    # Streaming-aggregate + frontier families (ISSUE 19): block commits
    # by status (the O(blocks) journal meter) and frontier probe blocks
    # by estimator/status. "No streaming matrix / frontier ever ran" is
    # a recorded 0 on every instrumented run.
    counter("scenario_aggregate_blocks_total",
            "streaming aggregate blocks by column and "
            "computed/resumed/failed status").inc(0)
    counter("scenario_frontier_probes_total",
            "frontier probe blocks by estimator and computed/resumed "
            "status").inc(0)
    # Chaos campaign families (ISSUE 15): episode outcomes per workload
    # and invariant verdicts — "no campaign ever ran" is a recorded 0,
    # and a nonzero {status=violated} after a campaign is the
    # machine-checkable headline the report's repro line expands.
    counter("chaos_campaign_episodes_total",
            "chaos-campaign episodes by workload and green/violated status"
            ).inc(0)
    counter("chaos_invariant_checks_total",
            "campaign invariant evaluations by invariant and verdict"
            ).inc(0)
    # Statistical-health families (ISSUE 16): rows folded into the
    # per-model sketches, sealed drift-window verdicts (the family the
    # stat_drift/stat_calibration SLOs read), and fired drift
    # detectors. "The monitor never saw a row" is a recorded 0.
    counter("serving_stat_rows_total",
            "rows folded into the statistical-health sketches, by model"
            ).inc(0)
    counter("serving_stat_windows_total",
            "sealed statistical-health windows by model, channel and "
            "ok/drift/miscal/sparse status").inc(0)
    counter("stat_drift_events_total",
            "statistical drift detections by model, channel and "
            "psi/ks/calibration detector").inc(0)
    # Fleet-router families (ISSUE 18): forward outcomes per backend,
    # failovers to the next ring owner, and rotation-membership
    # transitions. "The router never ran" is a recorded 0 on every
    # instrumented run, and the fleet manifest's reconciliation reads
    # these same families (scripts/check_metrics_schema.py).
    counter("router_requests_total",
            "router forward attempts by backend and outcome").inc(0)
    counter("router_failover_total",
            "forwards retried against the next ring owner").inc(0)
    counter("router_backend_state",
            "backend rotation-membership transitions").inc(0)
    # Fleet observability plane (ISSUE 20): the router's request-path
    # split (direct / failover / exhausted — the router:failover SLO's
    # denominator) and the router-observed e2e ladder the
    # router:latency SLO burns through. "The router never forwarded" is
    # a recorded 0 on every instrumented run.
    counter("router_request_path_total",
            "router forwards by direct/failover/exhausted path").inc(0)
    bucket_histogram("router_request_seconds",
                     "router-observed forward latency (e2e)")
    # Remaining emit-site families, folded in when JGL021 closed the
    # contract (ISSUE 20): every counter/histogram family minted
    # anywhere in the tree is pre-created HERE, so metrics.json carries
    # the same key set on every instrumented run regardless of which
    # code paths traffic happened to reach.
    counter("serving_batches_total",
            "dispatched micro-batches by bucket").inc(0)
    bucket_histogram("serving_batch_fill",
                     "micro-batch fill ratio (real rows / bucket rows)",
                     bounds=PAD_FRACTION_BOUNDS)
    counter("serving_reloads_total",
            "degraded-mode reload attempts by status").inc(0)
    histogram("scheduler_node_seconds", "per-node execution seconds")
    histogram("scheduler_prefetch_seconds",
              "per-node prefetch compile seconds")
    counter("sweep_stage_total",
            "sweep stages by resume-vs-computed status").inc(0)
    counter("tree_dispatch_total", "forest tree-chunk dispatches").inc(0)
    histogram("tree_dispatch_seconds", "per-dispatch host wall-clock")
    histogram("stage_seconds", "StageTimer stage durations")
    counter("xla_trace_total", "jax.profiler.trace activations").inc(0)
    counter("xprof_trace_total", "whole-run xprof captures").inc(0)
    if _installed:
        return True
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 — no monitoring API on this jax
        return False

    def on_event(event: str, **kwargs) -> None:
        name = _CACHE_EVENT_COUNTERS.get(event)
        if name is not None:
            counter(name).inc(1)

    def on_duration(event: str, duration_secs: float, **kwargs) -> None:
        name = _CACHE_DURATION_METRICS.get(event)
        if name is not None:
            histogram(name).observe(duration_secs)
        kind = _COMPILE_EVENT_KINDS.get(event)
        if kind is not None:
            counter("jax_compiles_total").inc(1, kind=kind)
            histogram("jax_compile_seconds",
                      "jax trace/lower/compile durations by kind"
                      ).observe(duration_secs, kind=kind)

    try:
        monitoring.register_event_listener(on_event)
        monitoring.register_event_duration_secs_listener(on_duration)
    except Exception:  # noqa: BLE001 — listener API drift
        return False
    _installed = True
    return True


def compile_event_count() -> float:
    """Total jax trace/lower/backend-compile events recorded so far (all
    kinds summed). The serving daemon marks this at the end of its
    startup phase and asserts a zero delta over the serving window —
    the "steady state provably never traces or compiles" enforcement.
    Requires :func:`install_jax_monitoring` to be active; 0.0 before
    any event."""
    vals = REGISTRY.peek("jax_compiles_total")
    return float(sum(vals.values())) if vals else 0.0


def _scan_cache_dir(cache_dir: str) -> tuple[int, int]:
    entries = 0
    total = 0
    try:
        with os.scandir(cache_dir) as it:
            for e in it:
                if e.is_file():
                    entries += 1
                    total += e.stat().st_size
    except OSError:
        pass
    return entries, total


def watch_cache_dir(cache_dir: str) -> None:
    """Gauge the persistent-cache directory at every snapshot:
    ``compile_cache_entries`` / ``compile_cache_bytes`` (current state)
    and ``compile_cache_entries_written`` (growth since the watch began
    — this process's writes, assuming no concurrent writer).

    Idempotent per directory: ``enable_persistent_cache`` runs at
    import time in several entry points (rbridge, pipeline.main), and
    stacking one collector per call would both rescan the directory
    repeatedly and reset the entries-written baseline to the latest
    call, erasing writes already counted."""
    if not enabled():
        return
    if cache_dir in _WATCHED_CACHE_DIRS:
        return
    _WATCHED_CACHE_DIRS.add(cache_dir)
    base_entries, _ = _scan_cache_dir(cache_dir)

    def collect() -> None:
        entries, total = _scan_cache_dir(cache_dir)
        g = gauge("compile_cache_entries", "persistent-cache entry files")
        g.set(entries)
        gauge("compile_cache_bytes", "persistent-cache total bytes").set(total)
        gauge(
            "compile_cache_entries_written",
            "entries added since this process enabled the cache",
        ).set(max(0, entries - base_entries))

    REGISTRY.add_collector(collect)
    collect()


def record_compiled_cost(name: str, compiled) -> dict:
    """Record a ``jax.stages.Compiled``'s cost/memory analysis as gauges
    labeled ``entry=name``; returns the captured numbers (possibly
    empty — both analyses are backend-best-effort)."""
    out: dict = {}
    if not enabled():
        return out
    try:
        cost = compiled.cost_analysis()
        # Older jax returns a one-dict list, newer a dict.
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        for key in ("flops", "bytes accessed", "optimal_seconds"):
            v = cost.get(key) if isinstance(cost, dict) else None
            if v is not None and v == v:  # skip NaN placeholders
                out[key.replace(" ", "_")] = float(v)
    # cost_analysis API drifts per jax version (dict vs list, missing on
    # some backends); best-effort probe may swallow anything:
    # graftlint: disable=JGL007
    except Exception:  # noqa: BLE001
        pass
    try:
        mem = compiled.memory_analysis()
        for attr in (
            "temp_size_in_bytes", "argument_size_in_bytes",
            "output_size_in_bytes", "generated_code_size_in_bytes",
        ):
            v = getattr(mem, attr, None)
            if v is not None:
                out[attr] = float(v)
    # memory_analysis is unimplemented on several backends and raises
    # different types per jax version:
    # graftlint: disable=JGL007
    except Exception:  # noqa: BLE001
        pass
    g = gauge("compiled_cost", "cost/memory analysis per jitted entry")
    for key, v in out.items():
        g.set(v, entry=name, stat=key)
    return out


def record_device_memory(context: str = "") -> dict:
    """Per-device ``memory_stats()`` gauges (bytes_in_use,
    peak_bytes_in_use, ...), labeled by device and optional context.
    Returns {device_label: stats}. Skips devices without stats (CPU)."""
    out: dict = {}
    if not enabled():
        return out
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend init failure
        return out
    g = gauge("device_memory_bytes", "Device.memory_stats() per device")
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — unsupported on this platform
            stats = None
        if not stats:
            continue
        label = f"{d.platform}:{d.id}"
        out[label] = stats
        for key, v in stats.items():
            if isinstance(v, (int, float)):
                if context:
                    g.set(float(v), device=label, stat=key, context=context)
                else:
                    g.set(float(v), device=label, stat=key)
    return out

"""Process-global metrics registry (the tentpole's part 1).

Counters, gauges and summary-histograms with labels, thread-safe,
snapshot-able to a plain dict — the machine-readable replacement for
the prints that round-3 (stale-checkpoint resume) and round-5
(cold-start) regressions had to be diagnosed from. Every emitter in the
framework (StageTimer, run_shards, the forest dispatch loops, the
compile-cache listeners) writes into the default registry; the driver
and bench export it as ``metrics.json`` / a Prometheus textfile.

Zero-cost when disabled: ``ATE_TPU_TELEMETRY=0`` turns every mutator
into a single cached-bool check and no allocation. Telemetry is
host-side only — nothing here is ever traced into jitted code, so
estimator outputs are bit-identical with telemetry on or off.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Iterator

_ENV = "ATE_TPU_TELEMETRY"
_enabled_cache: bool | None = None

# metrics.json / events.jsonl schema version — bump on breaking layout
# changes; scripts/check_metrics_schema.py validates against it.
SCHEMA_VERSION = 1

_LABEL_SAFE = re.compile(r"[^A-Za-z0-9_-]")


def enabled() -> bool:
    """Telemetry master switch: on unless ``ATE_TPU_TELEMETRY=0``.
    The env var is read once and cached (the hot paths call this per
    record); tests flip it via :func:`set_enabled`."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = os.environ.get(_ENV, "1") != "0"
    return _enabled_cache


def set_enabled(value: bool | None) -> None:
    """Override the master switch (``None`` re-reads the env var)."""
    global _enabled_cache
    _enabled_cache = value if value is None else bool(value)


def sanitize_label(label: str) -> str:
    """Map any char outside ``[A-Za-z0-9_-]`` to ``_`` — sweep method
    names like ``Causal Forest(GRF)`` and ``Belloni et.al`` become
    trace *directory* names and Prometheus label material verbatim
    otherwise."""
    return _LABEL_SAFE.sub("_", label)


def _label_key(labels: dict) -> str:
    """Canonical string form of a label set (sorted ``k=v`` pairs);
    the empty string for the unlabeled sample. ``,`` and ``=`` inside
    values map to ``_`` — the key's own separators must stay
    unambiguous for every downstream parser (promtext, the schema
    checker); label values are identifiers, not payload."""
    if not labels:
        return ""
    clean = lambda v: str(v).replace(",", "_").replace("=", "_")
    return ",".join(f"{k}={clean(labels[k])}" for k in sorted(labels))


class Counter:
    """Monotonically increasing per-label-set float counter."""

    kind = "counter"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self.samples: dict[str, float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        """Add ``value`` (must be >= 0). ``inc(0, **labels)`` is the
        idiom for pre-creating a labeled sample so "present but zero"
        is distinguishable from "never instrumented" in metrics.json
        (the retry counters on a healthy run)."""
        if not enabled():
            return
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment {value}")
        key = _label_key(labels)
        with self._lock:
            self.samples[key] = self.samples.get(key, 0.0) + value


class Gauge:
    """Last-write-wins per-label-set value."""

    kind = "gauge"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self.samples: dict[str, float] = {}

    def set(self, value: float, **labels) -> None:
        if not enabled():
            return
        with self._lock:
            self.samples[_label_key(labels)] = float(value)

    def add(self, value: float, **labels) -> None:
        if not enabled():
            return
        key = _label_key(labels)
        with self._lock:
            self.samples[key] = self.samples.get(key, 0.0) + float(value)


class Histogram:
    """Summary histogram: count/sum/min/max/last per label set.

    Deliberately bucket-free — the consumers here (regression triage,
    the bench records) want totals and extremes, and a summary exports
    to the Prometheus text format without fixing bucket boundaries
    that million-row and 2k-row runs would never share.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self.samples: dict[str, dict] = {}

    def observe(self, value: float, **labels) -> None:
        if not enabled():
            return
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            s = self.samples.get(key)
            if s is None:
                self.samples[key] = {
                    "count": 1, "sum": value, "min": value,
                    "max": value, "last": value,
                }
            else:
                s["count"] += 1
                s["sum"] += value
                s["min"] = min(s["min"], value)
                s["max"] = max(s["max"], value)
                s["last"] = value


class MetricsRegistry:
    """Thread-safe named-metric store with collector hooks.

    Collectors are zero-arg callables run at :meth:`snapshot` time for
    state that is cheaper to scan than to stream (e.g. the compile-cache
    directory's entry count/bytes). A collector that raises is dropped
    from that snapshot, never fatal — telemetry must not take down a
    run it is observing.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._collectors: list[Callable[[], None]] = []

    def _get(self, cls, name: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self._lock)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def metrics(self) -> Iterator[Counter | Gauge | Histogram]:
        with self._lock:
            return iter(list(self._metrics.values()))

    def peek(self, name: str) -> dict[str, float] | None:
        """Current samples of one metric as ``{label_key: value}`` (the
        ``sum`` for histograms), or None when the family was never
        created. Collector hooks do NOT run — this is the cheap read the
        trace counter-sampler takes on a timer; a full :meth:`snapshot`
        scans the compile-cache directory every call."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return None
            if m.kind == "histogram":
                return {k: float(v["sum"]) for k, v in m.samples.items()}
            return dict(m.samples)

    def snapshot(self) -> dict:
        """Versioned plain-dict snapshot (the metrics.json payload)."""
        for fn in list(self._collectors):
            try:
                fn()
            # A collector callback is third-party observer code; it must
            # never crash a snapshot:
            # graftlint: disable=JGL007
            except Exception:  # noqa: BLE001
                pass
        out = {
            "schema_version": SCHEMA_VERSION,
            "created_unix": time.time(),
            "counters": {},
            "gauges": {},
            "histograms": {},
        }
        with self._lock:
            for m in self._metrics.values():
                if not m.samples:
                    # Families created but never sampled (e.g. touched
                    # while telemetry was disabled) are noise, not data.
                    continue
                section = out[m.kind + "s"]
                section[m.name] = {
                    k: (dict(v) if isinstance(v, dict) else v)
                    for k, v in m.samples.items()
                }
        return out

    def reset(self) -> None:
        """Drop every metric and collector (tests only)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()


#: The process-global default registry every in-tree emitter writes to.
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "") -> Histogram:
    return REGISTRY.histogram(name, help)

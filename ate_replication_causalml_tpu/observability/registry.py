"""Process-global metrics registry (the tentpole's part 1).

Counters, gauges and summary-histograms with labels, thread-safe,
snapshot-able to a plain dict — the machine-readable replacement for
the prints that round-3 (stale-checkpoint resume) and round-5
(cold-start) regressions had to be diagnosed from. Every emitter in the
framework (StageTimer, run_shards, the forest dispatch loops, the
compile-cache listeners) writes into the default registry; the driver
and bench export it as ``metrics.json`` / a Prometheus textfile.

Zero-cost when disabled: ``ATE_TPU_TELEMETRY=0`` turns every mutator
into a single cached-bool check and no allocation. Telemetry is
host-side only — nothing here is ever traced into jitted code, so
estimator outputs are bit-identical with telemetry on or off.
"""

from __future__ import annotations

import bisect
import os
import re
import threading
import time
from typing import Callable, Iterator, Sequence

_ENV = "ATE_TPU_TELEMETRY"
_enabled_cache: bool | None = None

# metrics.json / events.jsonl schema version — bump on breaking layout
# changes; scripts/check_metrics_schema.py validates against it.
SCHEMA_VERSION = 1

_LABEL_SAFE = re.compile(r"[^A-Za-z0-9_-]")


def enabled() -> bool:
    """Telemetry master switch: on unless ``ATE_TPU_TELEMETRY=0``.
    The env var is read once and cached (the hot paths call this per
    record); tests flip it via :func:`set_enabled`."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = os.environ.get(_ENV, "1") != "0"
    return _enabled_cache


def set_enabled(value: bool | None) -> None:
    """Override the master switch (``None`` re-reads the env var)."""
    global _enabled_cache
    _enabled_cache = value if value is None else bool(value)


def sanitize_label(label: str) -> str:
    """Map any char outside ``[A-Za-z0-9_-]`` to ``_`` — sweep method
    names like ``Causal Forest(GRF)`` and ``Belloni et.al`` become
    trace *directory* names and Prometheus label material verbatim
    otherwise."""
    return _LABEL_SAFE.sub("_", label)


def _label_key(labels: dict) -> str:
    """Canonical string form of a label set (sorted ``k=v`` pairs);
    the empty string for the unlabeled sample. ``,`` and ``=`` inside
    values map to ``_`` — the key's own separators must stay
    unambiguous for every downstream parser (promtext, the schema
    checker); label values are identifiers, not payload."""
    if not labels:
        return ""
    clean = lambda v: str(v).replace(",", "_").replace("=", "_")
    return ",".join(f"{k}={clean(labels[k])}" for k in sorted(labels))


def parse_label_key(key: str) -> dict[str, str]:
    """Inverse of :func:`_label_key` for keys the registry itself built:
    ``"backend=b0,outcome=ok"`` → ``{"backend": "b0", "outcome": "ok"}``
    (the empty key → ``{}``). Values were sanitized at write time, so a
    ``split("=", 1)`` per pair is exact — this is THE one parser every
    reader of canonical label keys (the router's request table, the
    admin ``/varz`` body, the fleet reconciliation) must share instead
    of hand-rolling the split."""
    if not key:
        return {}
    return dict(
        pair.split("=", 1) for pair in key.split(",") if "=" in pair
    )


class Counter:
    """Monotonically increasing per-label-set float counter."""

    kind = "counter"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self.samples: dict[str, float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        """Add ``value`` (must be >= 0). ``inc(0, **labels)`` is the
        idiom for pre-creating a labeled sample so "present but zero"
        is distinguishable from "never instrumented" in metrics.json
        (the retry counters on a healthy run)."""
        if not enabled():
            return
        if value < 0:
            raise ValueError(f"counter {self.name}: negative increment {value}")
        key = _label_key(labels)
        with self._lock:
            self.samples[key] = self.samples.get(key, 0.0) + value


class Gauge:
    """Last-write-wins per-label-set value."""

    kind = "gauge"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self.samples: dict[str, float] = {}

    def set(self, value: float, **labels) -> None:
        if not enabled():
            return
        with self._lock:
            self.samples[_label_key(labels)] = float(value)

    def add(self, value: float, **labels) -> None:
        if not enabled():
            return
        key = _label_key(labels)
        with self._lock:
            self.samples[key] = self.samples.get(key, 0.0) + float(value)


class Histogram:
    """Summary histogram: count/sum/min/max/last per label set.

    Deliberately bucket-free — the consumers here (regression triage,
    the bench records) want totals and extremes, and a summary exports
    to the Prometheus text format without fixing bucket boundaries
    that million-row and 2k-row runs would never share. Tail-latency
    consumers (the serving daemon) use :class:`BucketHistogram`.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self.samples: dict[str, dict] = {}

    def observe(self, value: float, **labels) -> None:
        if not enabled():
            return
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            s = self.samples.get(key)
            if s is None:
                self.samples[key] = {
                    "count": 1, "sum": value, "min": value,
                    "max": value, "last": value,
                }
            else:
                s["count"] += 1
                s["sum"] += value
                s["min"] = min(s["min"], value)
                s["max"] = max(s["max"], value)
                s["last"] = value


#: Default bucket bounds for :class:`BucketHistogram`: log-spaced
#: (factor 2) from 100 µs to ~52 s — one fixed ladder that resolves
#: both a sub-millisecond served request and a multi-second AOT
#: compile, so every serving latency family shares comparable buckets.
DEFAULT_LATENCY_BUCKETS: tuple[float, ...] = tuple(
    1e-4 * 2.0**k for k in range(20)
)


class BucketHistogram:
    """Bucketed histogram: fixed ascending upper bounds plus an
    overflow bucket, with count/sum/min/max per label set (ISSUE 6).

    The summary :class:`Histogram` deliberately has no buckets — right
    for bench totals, useless for tail latency. A serving daemon needs
    p50/p95/p99 over thousands of requests without keeping raw samples,
    which is exactly what fixed buckets buy: quantiles are estimated at
    snapshot time as the upper bound of the bucket where the cumulative
    count crosses the quantile (Prometheus-style, conservative), clamped
    to the observed max. Bounds are fixed at family creation —
    re-registering with different bounds raises, since merged samples
    across mismatched ladders would be garbage.
    """

    kind = "bucket_histogram"

    def __init__(self, name: str, help: str, lock: threading.RLock,
                 bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        bounds = tuple(float(b) for b in bounds)
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError(
                f"bucket histogram {name}: bounds must be non-empty and "
                f"strictly ascending, got {bounds!r}"
            )
        self.name = name
        self.help = help
        self.bounds = bounds
        self._lock = lock
        self.samples: dict[str, dict] = {}

    def observe(self, value: float, **labels) -> None:
        if not enabled():
            return
        value = float(value)
        idx = bisect.bisect_left(self.bounds, value)  # le semantics
        key = _label_key(labels)
        with self._lock:
            s = self.samples.get(key)
            if s is None:
                s = self.samples[key] = {
                    "count": 0, "sum": 0.0, "min": value, "max": value,
                    "buckets": [0] * (len(self.bounds) + 1),
                }
            s["count"] += 1
            s["sum"] += value
            s["min"] = min(s["min"], value)
            s["max"] = max(s["max"], value)
            s["buckets"][idx] += 1

    def _quantile(self, s: dict, q: float) -> float:
        target = q * s["count"]
        cum = 0
        for i, c in enumerate(s["buckets"]):
            cum += c
            if cum >= target and c:
                if i >= len(self.bounds):
                    return s["max"]
                return min(self.bounds[i], s["max"])
        return s["max"]

    def snapshot_sample(self, s: dict) -> dict:
        """The metrics.json payload for one label set: raw buckets plus
        the bounds ladder (so a saved snapshot is self-describing) and
        the derived p50/p95/p99."""
        out = dict(s, buckets=list(s["buckets"]), bounds=list(self.bounds))
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            out[key] = self._quantile(s, q)
        return out

    def peek_counts(self) -> dict[str, dict]:
        """Lock-held copy of the raw samples (count/sum/min/max/buckets
        per label set) — the cheap read the SLO engine and the serving
        ``stats`` op take; no quantile derivation, no collector scan."""
        with self._lock:
            return {
                k: dict(s, buckets=list(s["buckets"]))
                for k, s in self.samples.items()
            }

    def good_total_le(self, threshold: float) -> tuple[int, int]:
        """``(good, total)`` observation counts summed across all label
        sets, where *good* means the observation landed in a bucket
        whose upper bound is ≤ ``threshold`` — the conservative
        (Prometheus-style) reading the latency SLOs use: a value inside
        the first bucket straddling the threshold counts as bad."""
        k = bisect.bisect_right(self.bounds, float(threshold))
        good = total = 0
        with self._lock:
            for s in self.samples.values():
                total += s["count"]
                good += sum(s["buckets"][:k])
        return good, total


class MetricsRegistry:
    """Thread-safe named-metric store with collector hooks.

    Collectors are zero-arg callables run at :meth:`snapshot` time for
    state that is cheaper to scan than to stream (e.g. the compile-cache
    directory's entry count/bytes). A collector that raises is dropped
    from that snapshot, never fatal — telemetry must not take down a
    run it is observing.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._collectors: list[Callable[[], None]] = []

    def _get(self, cls, name: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, self._lock)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get(Histogram, name, help)

    def bucket_histogram(
        self, name: str, help: str = "",
        bounds: Sequence[float] | None = None,
    ) -> BucketHistogram:
        """Bucketed (quantile-capable) histogram family. ``bounds``
        fixes the ladder on first creation (default log-spaced
        :data:`DEFAULT_LATENCY_BUCKETS`); passing different bounds for
        an existing family raises — samples across mismatched ladders
        cannot be merged."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = BucketHistogram(
                    name, help, self._lock,
                    bounds=DEFAULT_LATENCY_BUCKETS if bounds is None
                    else bounds,
                )
                self._metrics[name] = m
            elif not isinstance(m, BucketHistogram):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            elif bounds is not None and tuple(
                float(b) for b in bounds
            ) != m.bounds:
                raise ValueError(
                    f"bucket histogram {name!r} already registered with "
                    f"bounds {m.bounds!r}"
                )
            return m

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def family(self, name: str):
        """The metric object registered under ``name`` (or None) — the
        read-only accessor derived readers (the SLO engine, the serving
        ``stats`` op) use to reach bucket counts without growing the
        registry a new family as :meth:`counter`/:meth:`gauge` would."""
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> Iterator[Counter | Gauge | Histogram]:
        with self._lock:
            return iter(list(self._metrics.values()))

    def peek(self, name: str) -> dict[str, float] | None:
        """Current samples of one metric as ``{label_key: value}`` (the
        ``sum`` for histograms), or None when the family was never
        created. Collector hooks do NOT run — this is the cheap read the
        trace counter-sampler takes on a timer; a full :meth:`snapshot`
        scans the compile-cache directory every call."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return None
            if m.kind in ("histogram", "bucket_histogram"):
                return {k: float(v["sum"]) for k, v in m.samples.items()}
            return dict(m.samples)

    def peek_labeled(
        self, name: str
    ) -> list[tuple[dict[str, str], float]] | None:
        """:meth:`peek` with every canonical label key parsed back into
        its label dict: sorted ``[(labels, value), ...]`` (or None when
        the family was never created). Same cheapness contract as peek —
        no collector hooks run."""
        samples = self.peek(name)
        if samples is None:
            return None
        return [
            (parse_label_key(k), v) for k, v in sorted(samples.items())
        ]

    def snapshot(self) -> dict:
        """Versioned plain-dict snapshot (the metrics.json payload)."""
        for fn in list(self._collectors):
            try:
                fn()
            # A collector callback is third-party observer code; it must
            # never crash a snapshot:
            # graftlint: disable=JGL007
            except Exception:  # noqa: BLE001
                pass
        out = {
            "schema_version": SCHEMA_VERSION,
            "created_unix": time.time(),
            "counters": {},
            "gauges": {},
            "histograms": {},
            "bucket_histograms": {},
        }
        with self._lock:
            for m in self._metrics.values():
                if not m.samples:
                    # Families created but never sampled (e.g. touched
                    # while telemetry was disabled) are noise, not data.
                    continue
                section = out[m.kind + "s"]
                render = getattr(m, "snapshot_sample", None)
                section[m.name] = {
                    k: (
                        render(v) if render is not None
                        else dict(v) if isinstance(v, dict) else v
                    )
                    for k, v in m.samples.items()
                }
        return out

    def reset(self) -> None:
        """Drop every metric and collector (tests only)."""
        with self._lock:
            self._metrics.clear()
            self._collectors.clear()


#: The process-global default registry every in-tree emitter writes to.
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "") -> Histogram:
    return REGISTRY.histogram(name, help)


def bucket_histogram(
    name: str, help: str = "", bounds: Sequence[float] | None = None
) -> BucketHistogram:
    return REGISTRY.bucket_histogram(name, help, bounds=bounds)


def peek_labeled(name: str) -> list[tuple[dict[str, str], float]] | None:
    return REGISTRY.peek_labeled(name)

"""Unified telemetry subsystem (SURVEY.md §5.1 grown up).

One import surface for the three parts:

* metrics registry (``counter``/``gauge``/``histogram``, process-global
  ``REGISTRY``) — observability.registry
* structured event log (``span``/``emit``, process-global ``EVENTS``)
  — observability.events
* exporters (``metrics.json`` + ``events.jsonl`` + Prometheus
  textfile, all atomic) — observability.export / observability.promtext
* device & compile capture (cost analysis, memory stats, compile-cache
  listeners) — observability.device
* trace timeline + analyzers (Perfetto export, critical path/overlap,
  the serving report) — observability.trace / .critical_path /
  .serving_report
* SLO engine (declared objectives, multi-window burn rates) —
  observability.slo

Master switch: ``ATE_TPU_TELEMETRY=0`` disables everything at a cached
bool check per hook. All instrumentation is host-side, outside jitted
code — estimator numerics are bit-identical either way.
"""

from __future__ import annotations

import time
from typing import Callable

from ate_replication_causalml_tpu.observability.device import (
    PAD_FRACTION_BOUNDS,
    compile_event_count,
    install_jax_monitoring,
    record_compiled_cost,
    record_device_memory,
    watch_cache_dir,
)
from ate_replication_causalml_tpu.observability.events import (
    EVENTS,
    EventLog,
    emit,
    span,
)
from ate_replication_causalml_tpu.observability.export import (
    atomic_file,
    atomic_write_json,
    atomic_write_text,
    write_events_jsonl,
    write_metrics_json,
    write_run_artifacts,
)
from ate_replication_causalml_tpu.observability.registry import (
    DEFAULT_LATENCY_BUCKETS,
    REGISTRY,
    SCHEMA_VERSION,
    BucketHistogram,
    MetricsRegistry,
    bucket_histogram,
    counter,
    enabled,
    gauge,
    histogram,
    parse_label_key,
    peek_labeled,
    sanitize_label,
    set_enabled,
)
from ate_replication_causalml_tpu.observability.trace import (
    MetricSampler,
    build_trace,
    trace_enabled,
    write_trace_json,
)

__all__ = [
    "PAD_FRACTION_BOUNDS",
    "DEFAULT_LATENCY_BUCKETS",
    "EVENTS", "EventLog", "BucketHistogram", "MetricSampler",
    "MetricsRegistry", "REGISTRY", "SCHEMA_VERSION",
    "atomic_file", "atomic_write_json", "atomic_write_text",
    "bench_record", "bucket_histogram", "build_trace",
    "compile_event_count", "counter",
    "emit", "enabled", "gauge", "histogram", "install_jax_monitoring",
    "instrument_dispatch", "parse_label_key", "peek_labeled",
    "record_compiled_cost", "record_device_memory",
    "sanitize_label", "set_enabled", "span", "trace_enabled",
    "watch_cache_dir",
    "write_events_jsonl", "write_metrics_json", "write_run_artifacts",
    "write_trace_json",
]


def instrument_dispatch(kind: str, fn: Callable[[int], object]):
    """Wrap a shard/dispatch thunk (``fn(i) -> result``) with dispatch
    counters and a duration histogram, labeled ``fit=kind``.

    The duration is the HOST-side dispatch boundary — time to enqueue
    (and, where the runtime blocks, execute) one dispatched executable.
    No sync is added: results are returned exactly as produced, so
    async dispatch semantics and numbers are untouched.
    """
    if not enabled():
        return fn
    c = counter("tree_dispatch_total", "forest tree-chunk dispatches")
    h = histogram("tree_dispatch_seconds", "per-dispatch host wall-clock")
    c.inc(0, fit=kind)

    def wrapped(i: int):
        t0 = time.perf_counter()
        out = fn(i)
        h.observe(time.perf_counter() - t0, fit=kind)
        c.inc(1, fit=kind)
        return out

    return wrapped


def bench_record(**fields) -> dict:
    """Build a bench JSON record THROUGH the registry: the ``value`` /
    ``vs_baseline`` numbers land as gauges labeled by ``metric`` before
    the dict is returned, so BENCH_*.json lines and metrics.json can
    never disagree — they are the same store read twice."""
    record = dict(fields)
    metric = record.get("metric", "unknown")
    g = gauge("bench_value", "north-star bench record values")
    for key in ("value", "vs_baseline"):
        v = record.get(key)
        if isinstance(v, (int, float)):
            g.set(float(v), metric=metric, field=key)
    unit = record.get("unit")
    if unit is not None:
        gauge("bench_unit_info", "bench record unit (info gauge)").set(
            1.0, metric=metric, unit=str(unit)
        )
    emit("bench_record", status="ok", **{
        k: v for k, v in record.items()
        if isinstance(v, (int, float, str, bool))
    })
    return record

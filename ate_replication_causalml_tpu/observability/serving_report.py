"""Serving-session analysis of an exported trace (ISSUE 7).

The overlap report answers "where did the sweep's wall clock go"; this
module answers the serving twin — "where did a request's latency go,
and what did the coalescer do about it" — as a **pure function of the
exported ``trace.json``**: :func:`serving_report` reads only the
trace's ``cat="request"`` / ``cat="batch"`` slices (the daemon's
``serving_request`` / ``serving_batch`` spans) and the
``serving_reject`` instants, so ``scripts/analyze_trace.py`` recomputes
the daemon's own ``serving_report.json`` bit-for-bit from the saved
trace — the property the acceptance tests pin with a byte comparison.

Sections:

* **requests** — terminal-status counts and, for every request slice
  that carries the lifecycle attrs, per-phase duration stats
  (count / sum / p50 / p99 / max for ``coalesce_wait`` / ``queue_wait``
  / ``dispatch`` / ``device`` / ``reply``) — the decomposition that
  says whether a slow p99 was queue wait, coalesce window, pad
  overhead, or device time;
* **batches** — count, per-bucket mix, fill efficiency, mean pad
  fraction, and the close-reason split (window-expiry vs bucket-full
  vs next-wouldn't-fit vs drain) that blames the coalescer's policy;
* **rejects** — the admission/chaos reject timeline (bounded; the
  counters carry exact totals).
* **reconciliation** (present when a ``metrics.json`` snapshot is
  supplied) — ``requests_in_metrics`` (the dispatch-side phase
  histogram's count: EVERY request that completed the lifecycle,
  including raw ``submit()`` callers) vs ``requests_in_trace`` (the
  ``serve_one`` request slices the phase section is built from). The
  difference is ``silent_drops``: requests that are real in the
  metrics but invisible to the trace-derived phase stats — the PR 7
  gotcha, now a reported number the schema checker cross-validates
  instead of a footnote.

Pure stdlib and jax-free, like the critical-path analyzer beside it.
The report stays a pure function of its INPUTS — (trace, metrics
snapshot) — so the analyzer CLI reproduces the daemon's bytes from the
saved artifacts alone.
"""

from __future__ import annotations

import os

#: serving_report.json layout version.
SERVING_SCHEMA_VERSION = 1

SERVING_REPORT_BASENAME = "serving_report.json"
SLO_REPORT_BASENAME = "slo_report.json"

#: request-slice attr suffix -> report phase name, in lifecycle order.
PHASE_KEYS = ("coalesce_wait", "queue_wait", "dispatch", "device", "reply")

#: reject-timeline entries kept verbatim; the counts are always exact.
MAX_REJECT_TIMELINE = 500


def _events(trace: dict) -> list[dict]:
    evs = trace.get("traceEvents")
    return evs if isinstance(evs, list) else []


def has_serving_slices(trace: dict) -> bool:
    """Whether this trace carries a serving session (the analyzer CLI's
    auto-detection)."""
    return any(
        ev.get("cat") in ("request", "batch") and ev.get("ph") == "X"
        for ev in _events(trace)
    )


def index_quantile(sorted_vals: list[float], q: float) -> float:
    """THE conservative index quantile every serving consumer shares
    (this report, the loadgen records) — deterministic on ties, no
    interpolation; a future change to the convention happens here
    once."""
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


def phase_count_from_metrics(metrics: dict | None) -> int | None:
    """Requests the METRICS side decomposed: the ``serving_phase_seconds``
    bucket histogram's count for the ``device`` phase (every phase of a
    decomposed request is recorded exactly once, so any phase's count
    works; ``device`` is the least ambiguous). None only when no
    snapshot was supplied at all; a snapshot without the family means
    zero requests were decomposed — a reported 0, not a missing
    section."""
    if metrics is None:
        return None
    fam = metrics.get("bucket_histograms", {}).get(
        "serving_phase_seconds"
    )
    if not isinstance(fam, dict):
        return 0
    return sum(
        int(s.get("count", 0))
        for key, s in fam.items()
        if "phase=device" in key.split(",") and isinstance(s, dict)
    )


def phase_mark_from_trace(trace: dict) -> int:
    """The daemon's startup phase-count baseline, stamped into the
    trace's ``otherData`` — the quantity that windows the process-
    global metrics count to THIS serving session. One extraction rule,
    shared by the report builder and the schema validator."""
    try:
        return int(
            (trace.get("otherData") or {}).get("serving_phase_mark", 0)
        )
    except (TypeError, ValueError):
        return 0


def serving_report(trace: dict, metrics: dict | None = None) -> dict:
    """The ``serving_report.json`` payload for one exported trace,
    optionally reconciled against the run's ``metrics.json`` snapshot
    (the silent-drop accounting for raw ``submit()`` traffic)."""
    requests: list[dict] = []
    batches: list[dict] = []
    rejects: list[dict] = []
    for ev in _events(trace):
        if ev.get("ph") == "X" and ev.get("cat") == "request":
            requests.append(ev)
        elif ev.get("ph") == "X" and ev.get("cat") == "batch":
            batches.append(ev)
        elif ev.get("name") == "serving_reject":
            rejects.append(ev)

    # ── window envelope (µs -> s, trace-origin-relative) ─────────────
    # Reject instants count on BOTH edges: a reject burst after the
    # last served slice must not land "outside" the report's window.
    starts = [ev["ts"] for ev in requests + batches + rejects]
    ends = [
        ev["ts"] + ev.get("dur", 0.0) for ev in requests + batches
    ] + [ev["ts"] for ev in rejects]
    window_s = (max(ends) - min(starts)) / 1e6 if starts else 0.0

    # ── requests: status counts + phase decomposition ────────────────
    status: dict[str, int] = {}
    phase_vals: dict[str, list[float]] = {k: [] for k in PHASE_KEYS}
    e2e_vals: list[float] = []
    for ev in requests:
        args = ev.get("args", {})
        st = str(args.get("status", "ok"))
        status[st] = status.get(st, 0) + 1
        if all(f"{k}_s" in args for k in PHASE_KEYS):
            for k in PHASE_KEYS:
                phase_vals[k].append(float(args[f"{k}_s"]))
            e2e_vals.append(float(args.get("e2e_s", ev.get("dur", 0.0) / 1e6)))

    def _stats(vals: list[float]) -> dict:
        if not vals:
            return {"count": 0, "sum_s": 0.0, "p50_s": 0.0, "p99_s": 0.0,
                    "max_s": 0.0}
        s = sorted(vals)
        return {
            "count": len(s),
            "sum_s": round(sum(s), 9),
            "p50_s": round(index_quantile(s, 0.50), 9),
            "p99_s": round(index_quantile(s, 0.99), 9),
            "max_s": round(s[-1], 9),
        }

    # ── batches: bucket mix, fill, close reasons ─────────────────────
    by_bucket: dict[str, int] = {}
    close_reasons: dict[str, int] = {}
    fills: list[float] = []
    rows_total = 0
    for ev in batches:
        args = ev.get("args", {})
        bucket = str(args.get("bucket", "?"))
        by_bucket[bucket] = by_bucket.get(bucket, 0) + 1
        reason = str(args.get("close_reason", "?"))
        close_reasons[reason] = close_reasons.get(reason, 0) + 1
        fills.append(float(args.get("fill", 0.0)))
        rows_total += int(args.get("rows", 0))

    # ── rejects: bounded timeline, exact counts ──────────────────────
    by_reason: dict[str, int] = {}
    timeline: list[dict] = []
    for ev in rejects:
        reason = str(ev.get("args", {}).get("reason", "?"))
        by_reason[reason] = by_reason.get(reason, 0) + 1
        if len(timeline) < MAX_REJECT_TIMELINE:
            timeline.append({
                "ts_s": round(ev["ts"] / 1e6, 6),
                "reason": reason,
                "request_id": str(ev.get("args", {}).get("request_id", "")),
            })

    out: dict = {
        "schema_version": SERVING_SCHEMA_VERSION,
        "window_s": round(window_s, 6),
        "requests": {
            "count": len(requests),
            "status": {k: status[k] for k in sorted(status)},
            "with_phases": len(e2e_vals),
            "e2e": _stats(e2e_vals),
            "phases": {k: _stats(phase_vals[k]) for k in PHASE_KEYS},
        },
        "batches": {
            "count": len(batches),
            "rows": rows_total,
            "by_bucket": {k: by_bucket[k] for k in sorted(by_bucket)},
            "fill_mean": round(sum(fills) / len(fills), 6) if fills else 0.0,
            "pad_fraction_mean": (
                round(1.0 - sum(fills) / len(fills), 6) if fills else 0.0
            ),
            "close_reasons": {
                k: close_reasons[k] for k in sorted(close_reasons)
            },
        },
        "rejects": {
            "count": len(rejects),
            "by_reason": {k: by_reason[k] for k in sorted(by_reason)},
            "timeline": timeline,
            "timeline_truncated": max(0, len(rejects) - len(timeline)),
        },
    }
    in_metrics = phase_count_from_metrics(metrics)
    if in_metrics is not None:
        # The phase histogram is process-global; the daemon stamped its
        # startup baseline into the trace's otherData so an earlier
        # serving session in the same process is not misreported as
        # this window's silent drops. The metrics snapshot is taken
        # AFTER the trace is built (the daemon's dump order pins
        # this), so the windowed metrics side can only see MORE
        # decomposed requests, never fewer — silent_drops is the
        # raw-submit() traffic the trace-derived phase section cannot
        # see.
        in_window = max(0, in_metrics - phase_mark_from_trace(trace))
        out["reconciliation"] = {
            "requests_in_metrics": in_window,
            "requests_in_trace": len(e2e_vals),
            "silent_drops": in_window - len(e2e_vals),
        }
    return out


def write_serving_artifacts(outdir: str, trace: dict,
                            metrics: dict | None = None) -> list[str]:
    """Write the ``trace.json`` + ``serving_report.json`` pair for a
    serving session — the one write recipe :meth:`CateServer.stop`, the
    ``dump`` op and the analyzer CLI share, so their bytes can only
    agree. ``metrics`` (the run's metrics.json payload) enables the
    silent-drop reconciliation section. Returns the paths written
    ([] when tracing is disabled)."""
    from ate_replication_causalml_tpu.observability.export import (
        atomic_write_json,
    )
    from ate_replication_causalml_tpu.observability.trace import (
        TRACE_BASENAME,
        trace_enabled,
        write_trace_json,
    )

    if not trace_enabled():
        return []
    tpath = os.path.join(outdir, TRACE_BASENAME)
    write_trace_json(tpath, trace=trace)
    spath = os.path.join(outdir, SERVING_REPORT_BASENAME)
    atomic_write_json(spath, serving_report(trace, metrics=metrics))
    return [tpath, spath]


def render_summary(report: dict) -> str:
    """Human summary for the analyzer CLI."""
    req = report["requests"]
    bat = report["batches"]
    rej = report["rejects"]
    lines = [
        f"serving window {report['window_s']:.3f}s: {req['count']} request "
        f"slice(s), {bat['count']} batch(es), {rej['count']} reject(s)",
    ]
    if req["with_phases"]:
        lines.append(
            f"e2e p50 {req['e2e']['p50_s'] * 1e3:.2f}ms  "
            f"p99 {req['e2e']['p99_s'] * 1e3:.2f}ms "
            f"({req['with_phases']} decomposed)"
        )
        lines.append("phases (p50 / p99 / max ms):")
        for k in PHASE_KEYS:
            st = req["phases"][k]
            lines.append(
                f"  {k:<14s} {st['p50_s'] * 1e3:8.3f} "
                f"{st['p99_s'] * 1e3:8.3f} {st['max_s'] * 1e3:8.3f}"
            )
    if bat["count"]:
        lines.append(
            f"batches: fill {bat['fill_mean']:.2%}, pad "
            f"{bat['pad_fraction_mean']:.2%}, buckets {bat['by_bucket']}, "
            f"close {bat['close_reasons']}"
        )
    if rej["count"]:
        lines.append(f"rejects by reason: {rej['by_reason']}")
    rec = report.get("reconciliation")
    if rec is not None:
        lines.append(
            f"reconciliation: {rec['requests_in_metrics']} in metrics, "
            f"{rec['requests_in_trace']} in trace "
            f"({rec['silent_drops']} silent raw-submit drop(s))"
        )
    return "\n".join(lines)

"""Statistical-health plane for the serving tier (ISSUE 16).

The rest of the observability stack says whether the daemon is *fast*;
this module says whether it is plausibly *right*. A
:class:`StatHealthMonitor` accumulates deterministic, mergeable
sketches (:mod:`.sketch`) per served model over three channels —

* ``cate`` — the served CATE point estimates,
* ``covariate`` — per-request-row covariate means (the cheap
  location summary of the incoming feature distribution),
* ``propensity`` — a logistic squash of the configured propensity
  feature column (overlap/propensity degradation is where AIPW-style
  estimators break first: Chernozhukov et al., arXiv:1608.00060),

— plus an optional propensity-calibration channel (predicted
probability vs empirical treatment over reliability buckets, the
quantity honest-forest coverage work cares about: Wager & Athey,
arXiv:1510.04342). Each channel keeps an all-time ``total`` sketch
(the fleet-mergeable artifact) and a current clock-gridded window;
sealed windows are compared pairwise with PSI and the KS statistic,
and each sealed evaluation lands in the ``serving_stat_windows_total``
counter with a ``status`` label — which is exactly what turns drift
into a burn-rate objective: :func:`~.slo.stat_health_slos` declares
availability-style SLOs over that counter, so "too many drifted
windows" burns budget with the same multi-window machinery latency
does.

Determinism contract (the PR 7 discipline): the sketch totals are
integer-count functions of the served multiset — insertion-order
independent and, because served answers are bit-identical per seed,
byte-identical per seed. The *windowed* detector state is operational
(it reads an injectable clock, ``time.monotonic`` by default) and is
only deterministic under an injected clock; the byte-identity
acceptance replay therefore runs with a window wider than the replay
(no seals — totals only), while the drift-flip proof drives the clock
explicitly (tier-1) or a real small window (@slow). All of it is
host-side: :meth:`StatHealthMonitor.observe` takes already-materialized
host arrays and never touches jax — the zero-compile window cannot see
this plane.

Pure stdlib at import and call time; importable through the jax-free
observability shim (``scripts/analyze_trace.py``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Sequence

from ate_replication_causalml_tpu.observability.sketch import (
    CalibrationSketch,
    FixedBinSketch,
    ks_statistic,
    psi,
)

STAT_HEALTH_SCHEMA_VERSION = 1
STAT_HEALTH_BASENAME = "stat_health.json"

#: window-pair drift thresholds: PSI > 0.25 is the classic "population
#: moved" screen; the KS bound is set above two-sample noise at the
#: minimum window count below.
PSI_DRIFT_THRESHOLD = 0.25
KS_DRIFT_THRESHOLD = 0.30
#: midpoint-ECE above this marks a calibration window miscalibrated.
CALIBRATION_THRESHOLD = 0.10
#: both windows of a pair need at least this much located mass before
#: the detectors are trusted — with 8 bins + tails, PSI's smoothing
#: bias at n=200 is ≈ 2·10/200 = 0.1, comfortably under 0.25.
MIN_WINDOW_COUNT = 200
#: drift-evaluation window width, seconds (``ATE_TPU_STAT_WINDOW``).
DEFAULT_WINDOW_S = 5.0
#: per-channel fixed-bin resolution — deliberately coarse: drift power
#: scales with per-bin mass, and 8 bins + tails keeps stationary PSI
#: noise far from the threshold at MIN_WINDOW_COUNT.
DEFAULT_BINS = 8
#: sealed windows / series entries retained per channel (bounded, like
#: the SLO engine's tick history).
MAX_WINDOWS = 64

#: the distributional channels, in fixed report order.
CHANNELS = ("cate", "covariate", "propensity")

#: fixed sketch ranges per channel. Out-of-range mass is not lost — it
#: lands in the tails, which PSI/KS compare like any other cell.
CHANNEL_RANGES = {
    "cate": (-32.0, 32.0),
    "covariate": (-4.0, 4.0),
    "propensity": (0.0, 1.0),
}

_WINDOW_STATUSES = ("ok", "drift", "sparse")
_CALIBRATION_STATUSES = ("ok", "miscal", "sparse")


def _sigmoid(z: float) -> float:
    if z >= 0.0:
        return 1.0 / (1.0 + math.exp(-z))
    e = math.exp(z)
    return e / (1.0 + e)


class _Channel:
    """One model × channel accumulator: all-time total, current window,
    bounded sealed-window history, and the evaluation series."""

    __slots__ = ("lo", "hi", "bins", "total", "current", "index",
                 "windows", "series")

    def __init__(self, lo: float, hi: float, bins: int):
        self.lo, self.hi, self.bins = lo, hi, bins
        self.total = FixedBinSketch(lo, hi, bins)
        self.current = FixedBinSketch(lo, hi, bins)
        self.index: int | None = None
        self.windows: list[tuple[int, FixedBinSketch]] = []
        self.series: list[dict] = []


class _CalibrationChannel:
    __slots__ = ("buckets", "total", "current", "index", "windows",
                 "series")

    def __init__(self, buckets: int = 10):
        self.buckets = buckets
        self.total = CalibrationSketch(buckets)
        self.current = CalibrationSketch(buckets)
        self.index: int | None = None
        self.windows: list[tuple[int, CalibrationSketch]] = []
        self.series: list[dict] = []


class StatHealthMonitor:
    """Per-model streaming statistical health over served traffic.

    ``observe`` is called by the dispatcher per dispatched batch with
    host-side arrays (any nested iterable of numbers — numpy arrays
    iterate fine); everything else is a read. Thread-safe the
    JGL006/JGL008 way: one instance lock around every state mutation
    and every consistent read.

    ``calibration_cols`` — ``(propensity_col, treatment_col)`` feature
    indices — arms the calibration channel: predicted = logistic of
    the propensity column, empirical = treatment column > 0. Unarmed
    (the default), the channel stays empty and its SLO never spends
    budget (an empty window is zero burn).
    """

    def __init__(
        self,
        model_ids: Sequence[str] = ("default",),
        *,
        window_s: float = DEFAULT_WINDOW_S,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
        bins: int = DEFAULT_BINS,
        psi_threshold: float = PSI_DRIFT_THRESHOLD,
        ks_threshold: float = KS_DRIFT_THRESHOLD,
        calibration_threshold: float = CALIBRATION_THRESHOLD,
        min_count: int = MIN_WINDOW_COUNT,
        max_windows: int = MAX_WINDOWS,
        propensity_col: int = 0,
        calibration_cols: tuple[int, int] | None = None,
    ):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if min_count < 1:
            raise ValueError(f"min_count must be >= 1, got {min_count}")
        self.window_s = float(window_s)
        self._clock = clock
        self._registry = registry
        self._bins = int(bins)
        self._psi_threshold = float(psi_threshold)
        self._ks_threshold = float(ks_threshold)
        self._calibration_threshold = float(calibration_threshold)
        self._min_count = int(min_count)
        self._max_windows = int(max_windows)
        self._propensity_col = int(propensity_col)
        self._calibration_cols = (
            (int(calibration_cols[0]), int(calibration_cols[1]))
            if calibration_cols is not None else None
        )
        self._lock = threading.RLock()
        self._t0: float | None = None
        self._rows: dict[str, int] = {}
        self._drift_events: dict[str, int] = {}
        self._channels: dict[str, dict[str, _Channel]] = {}
        self._calibration: dict[str, _CalibrationChannel] = {}
        for m in model_ids:
            self._ensure_model_locked(str(m))

    # ── accumulation ────────────────────────────────────────────────────

    def _ensure_model_locked(self, model: str) -> None:
        # Callers already hold the instance lock; it is an RLock, so
        # the lexical ``with`` re-enters for free and keeps the
        # mutation visibly guarded (JGL006's contract is syntactic).
        with self._lock:
            if model in self._channels:
                return
            self._channels[model] = {
                ch: _Channel(*CHANNEL_RANGES[ch], self._bins)
                for ch in CHANNELS
            }
            self._calibration[model] = _CalibrationChannel()
            self._rows.setdefault(model, 0)
            self._drift_events.setdefault(model, 0)

    def observe(self, model: str, cate, x, now: float | None = None) -> None:
        """Fold one dispatched batch: served CATE values and the
        matching request rows (row-major iterable of feature rows).
        Host-side only — callers hand in materialized numpy, never a
        traced value."""
        model = str(model) or "default"
        cate_vals = [float(v) for v in cate]
        rows = [[float(v) for v in r] for r in x]
        cov_means = [sum(r) / len(r) for r in rows if r]
        pcol = self._propensity_col
        prop = [
            _sigmoid(r[pcol]) for r in rows if len(r) > pcol
        ]
        calib = None
        if self._calibration_cols is not None:
            pc, tc = self._calibration_cols
            pairs = [
                (_sigmoid(r[pc]), r[tc] > 0.0)
                for r in rows
                if len(r) > pc and len(r) > tc
            ]
            if pairs:
                calib = pairs
        with self._lock:
            self._ensure_model_locked(model)
            if now is None:
                now = self._clock()
            if self._t0 is None:
                self._t0 = now
            idx = int((now - self._t0) // self.window_s)
            self._rows[model] += len(rows)
            for ch_name, vals in (("cate", cate_vals),
                                  ("covariate", cov_means),
                                  ("propensity", prop)):
                ch = self._channels[model][ch_name]
                self._roll_locked(model, ch_name, ch, idx)
                ch.total.update(vals)
                ch.current.update(vals)
            cal = self._calibration[model]
            self._roll_calibration_locked(model, cal, idx)
            if calib:
                p_hat = [p for p, _ in calib]
                treated = [t for _, t in calib]
                cal.total.update(p_hat, treated)
                cal.current.update(p_hat, treated)
        self._emit("serving_stat_rows_total", len(rows), model=model)

    # ── window sealing + evaluation ─────────────────────────────────────

    def _roll_locked(self, model: str, ch_name: str, ch: _Channel,
                     idx: int) -> None:
        if ch.index is None:
            ch.index = idx
            return
        if idx <= ch.index:
            return
        if ch.current.total() > 0:
            self._seal_locked(model, ch_name, ch)
        ch.current = FixedBinSketch(ch.lo, ch.hi, ch.bins)
        ch.index = idx

    def _seal_locked(self, model: str, ch_name: str, ch: _Channel) -> None:
        sealed = (ch.index, ch.current)
        prev = ch.windows[-1] if ch.windows else None
        ch.windows.append(sealed)
        del ch.windows[:-self._max_windows]
        if prev is None:
            return  # a pair detector has nothing to say about window 1
        prev_idx, prev_sketch = prev
        psi_v = psi(prev_sketch, ch.current)
        ks_v = ks_statistic(prev_sketch, ch.current)
        if min(prev_sketch.located(), ch.current.located()) < self._min_count:
            status = "sparse"
        elif psi_v > self._psi_threshold or ks_v > self._ks_threshold:
            status = "drift"
        else:
            status = "ok"
        ch.series.append({
            "index": ch.index,
            "prev_index": prev_idx,
            "psi": round(psi_v, 9),
            "ks": round(ks_v, 9),
            "status": status,
        })
        del ch.series[:-self._max_windows]
        self._emit("serving_stat_windows_total", 1, model=model,
                   channel=ch_name, status=status)
        if status == "drift":
            with self._lock:  # re-entrant; caller holds it already
                self._drift_events[model] += 1
            if psi_v > self._psi_threshold:
                self._emit("stat_drift_events_total", 1, model=model,
                           channel=ch_name, detector="psi")
            if ks_v > self._ks_threshold:
                self._emit("stat_drift_events_total", 1, model=model,
                           channel=ch_name, detector="ks")

    def _roll_calibration_locked(self, model: str,
                                 cal: _CalibrationChannel,
                                 idx: int) -> None:
        if cal.index is None:
            cal.index = idx
            return
        if idx <= cal.index:
            return
        if cal.current.total() > 0:
            err = cal.current.calibration_error()
            if cal.current.located() < self._min_count:
                status = "sparse"
            elif err is not None and err > self._calibration_threshold:
                status = "miscal"
            else:
                status = "ok"
            cal.windows.append((cal.index, cal.current))
            del cal.windows[:-self._max_windows]
            cal.series.append({
                "index": cal.index,
                "error": None if err is None else round(err, 9),
                "status": status,
            })
            del cal.series[:-self._max_windows]
            self._emit("serving_stat_windows_total", 1, model=model,
                       channel="calibration", status=status)
            if status == "miscal":
                with self._lock:  # re-entrant; caller holds it already
                    self._drift_events[model] += 1
                self._emit("stat_drift_events_total", 1, model=model,
                           channel="calibration", detector="calibration")
        cal.current = CalibrationSketch(cal.buckets)
        cal.index = idx

    def _emit(self, name: str, value: int, **labels) -> None:
        if self._registry is not None:
            self._registry.counter(name).inc(value, **labels)

    # ── reads ───────────────────────────────────────────────────────────

    def state_dict(self) -> dict:
        """The raw, JSON-able monitor state — everything
        :func:`stat_health_report` derives from, models sorted and
        channels in fixed order so equal state serializes to equal
        bytes."""
        with self._lock:
            models = {}
            for m in sorted(self._channels):
                chans = {}
                for ch_name in CHANNELS:
                    ch = self._channels[m][ch_name]
                    chans[ch_name] = {
                        "total": ch.total.to_dict(),
                        "current": {
                            "index": ch.index,
                            "sketch": ch.current.to_dict(),
                        },
                        "windows": [
                            {"index": i, "sketch": s.to_dict()}
                            for i, s in ch.windows
                        ],
                        "series": [dict(e) for e in ch.series],
                    }
                cal = self._calibration[m]
                models[m] = {
                    "rows": self._rows[m],
                    "channels": chans,
                    "calibration": {
                        "enabled": self._calibration_cols is not None,
                        "total": cal.total.to_dict(),
                        "current": {
                            "index": cal.index,
                            "sketch": cal.current.to_dict(),
                        },
                        "windows": [
                            {"index": i, "sketch": s.to_dict()}
                            for i, s in cal.windows
                        ],
                        "series": [dict(e) for e in cal.series],
                    },
                }
            return {
                "schema_version": STAT_HEALTH_SCHEMA_VERSION,
                "window_s": self.window_s,
                "bins": self._bins,
                "thresholds": {
                    "psi": self._psi_threshold,
                    "ks": self._ks_threshold,
                    "calibration": self._calibration_threshold,
                    "min_count": self._min_count,
                },
                "models": models,
            }

    def health(self) -> dict:
        """The compact form ``/healthz``, ``/varz`` neighbours and the
        ``stats`` wire op embed."""
        with self._lock:
            models = {}
            for m in sorted(self._channels):
                chans = {}
                for ch_name in CHANNELS:
                    ch = self._channels[m][ch_name]
                    chans[ch_name] = {
                        "count": ch.total.total(),
                        "windows": len(ch.series),
                        "last_status": (
                            ch.series[-1]["status"] if ch.series else None
                        ),
                    }
                cal = self._calibration[m]
                models[m] = {
                    "rows": self._rows[m],
                    "drift_events": self._drift_events[m],
                    "channels": chans,
                    "calibration": {
                        "enabled": self._calibration_cols is not None,
                        "count": cal.total.total(),
                        "last_status": (
                            cal.series[-1]["status"] if cal.series else None
                        ),
                    },
                }
            return {"window_s": self.window_s, "models": models}


# ── the pure report (daemon dump == analyzer recompute, bit for bit) ───


def _summarize_channel(ch_state: dict) -> dict:
    total = FixedBinSketch.from_dict(ch_state["total"])
    series = ch_state["series"]
    psis = [e["psi"] for e in series if e.get("psi") is not None]
    kss = [e["ks"] for e in series if e.get("ks") is not None]
    statuses = [e["status"] for e in series]
    return {
        "count": total.total(),
        "underflow": total.underflow,
        "overflow": total.overflow,
        "nan": total.nan,
        "p50": _round9(total.quantile(0.5)),
        "p90": _round9(total.quantile(0.9)),
        "windows": len(series),
        "ok": statuses.count("ok"),
        "drift": statuses.count("drift"),
        "sparse": statuses.count("sparse"),
        "worst_psi": _round9(max(psis)) if psis else None,
        "worst_ks": _round9(max(kss)) if kss else None,
        "last_status": statuses[-1] if statuses else None,
    }


def _summarize_calibration(cal_state: dict) -> dict:
    total = CalibrationSketch.from_dict(cal_state["total"])
    series = cal_state["series"]
    errors = [e["error"] for e in series if e.get("error") is not None]
    statuses = [e["status"] for e in series]
    return {
        "enabled": bool(cal_state["enabled"]),
        "count": total.total(),
        "error": _round9(total.calibration_error()),
        "windows": len(series),
        "ok": statuses.count("ok"),
        "miscal": statuses.count("miscal"),
        "sparse": statuses.count("sparse"),
        "worst_error": _round9(max(errors)) if errors else None,
        "last_status": statuses[-1] if statuses else None,
    }


def _round9(v):
    return None if v is None else round(float(v), 9)


def stat_health_report(state: dict) -> dict:
    """The full ``stat_health.json`` payload as a PURE function of the
    monitor's raw state — the daemon's dump and
    ``scripts/analyze_trace.py`` both call exactly this, which is what
    makes the analyzer's reproduction bit-for-bit (the PR 7
    discipline). The raw state is embedded verbatim so the file is its
    own recompute input."""
    summary = {}
    drifted = []
    events = 0
    for m in sorted(state["models"]):
        ms = state["models"][m]
        chans = {}
        for ch_name in CHANNELS:
            chans[ch_name] = _summarize_channel(ms["channels"][ch_name])
            if chans[ch_name]["last_status"] == "drift":
                drifted.append(f"{m}:{ch_name}")
            events += chans[ch_name]["drift"]
        cal = _summarize_calibration(ms["calibration"])
        if cal["last_status"] == "miscal":
            drifted.append(f"{m}:calibration")
        events += cal["miscal"]
        summary[m] = {
            "rows": ms["rows"],
            "channels": chans,
            "calibration": cal,
        }
    return {
        "schema_version": STAT_HEALTH_SCHEMA_VERSION,
        "state": state,
        "summary": summary,
        "drift": {"events": events, "drifted": drifted},
    }


def write_stat_health(outdir: str, state: dict) -> dict:
    """THE one write recipe for ``stat_health.json`` — the daemon's
    ``dump_artifacts`` and the analyzer share it, so both emit the same
    bytes for the same state."""
    import os

    from ate_replication_causalml_tpu.observability.export import (
        atomic_write_json,
    )

    report = stat_health_report(state)
    atomic_write_json(os.path.join(outdir, STAT_HEALTH_BASENAME), report)
    return report


def render_summary(report: dict) -> str:
    """One line per model for the analyzer's human output."""
    lines = []
    for m, ms in sorted(report["summary"].items()):
        chans = ms["channels"]
        bits = ", ".join(
            f"{ch}: {c['count']} obs / {c['windows']} win"
            f" ({c['drift']} drift)"
            for ch, c in chans.items()
        )
        lines.append(f"stat_health[{m}]: rows {ms['rows']} — {bits}")
    d = report["drift"]
    lines.append(
        f"stat_health: {d['events']} drift event(s), "
        f"currently drifted: {d['drifted'] or 'none'}"
    )
    return "\n".join(lines)

"""Deterministic, mergeable streaming sketches (ISSUE 16).

Two fixed-shape sketches back the statistical-health plane:

* :class:`FixedBinSketch` — a fixed-edge histogram over ``[lo, hi)``
  with explicit underflow/overflow/NaN tails. State is INTEGER counts
  only (no float accumulators), so merge is exactly associative and
  commutative, the empty sketch is a true identity, and the result is
  independent of insertion order — the properties that let per-daemon
  sketches merge fleet-wide later (ROADMAP item 2) without a
  coordinator or a seed.
* :class:`CalibrationSketch` — fixed buckets over predicted
  probability ``[0, 1]`` carrying ``(count, positives)`` integer pairs
  per bucket. Reliability is read against the bucket midpoint rather
  than a float mean-of-predictions, for the same exact-merge reason.

Window-pair drift statistics over :class:`FixedBinSketch` pairs:

* :func:`psi` — population stability index with Laplace-style ``+0.5``
  smoothing per cell (the classic "PSI > 0.25 means the population
  moved" screening statistic).
* :func:`ks_statistic` — the two-sample Kolmogorov–Smirnov ``D`` over
  the binned CDFs (a lower bound on the exact-sample ``D``; exact when
  the distributions are supported on the bin edges).

Everything here is pure stdlib and jax/numpy-free at import AND call
time: callers hand in plain iterables (numpy arrays iterate fine), and
``scripts/analyze_trace.py`` / ``scripts/check_metrics_schema.py``
import this module through the jax-free observability shim.
"""

from __future__ import annotations

import json
import math
from bisect import bisect_right

SKETCH_SCHEMA_VERSION = 1

# Laplace smoothing mass added per cell before the PSI log-ratio —
# keeps empty cells finite while leaving the statistic deterministic
# (an integer-count function, not an estimator with a seed).
_PSI_SMOOTH = 0.5


class FixedBinSketch:
    """Fixed-edge integer histogram with explicit tails.

    ``n_bins`` uniform bins over ``[lo, hi)``; values below ``lo``
    count into ``underflow``, values at/above ``hi`` into ``overflow``,
    NaNs into ``nan`` (NaN has no distributional location, so it is
    mass-conserved but excluded from quantiles/PSI/KS).
    """

    __slots__ = ("lo", "hi", "n_bins", "counts", "underflow", "overflow",
                 "nan", "_edges", "_width")

    def __init__(self, lo: float, hi: float, n_bins: int):
        if not (n_bins >= 1 and math.isfinite(lo) and math.isfinite(hi)
                and lo < hi):
            raise ValueError(
                f"FixedBinSketch wants finite lo < hi and n_bins >= 1, "
                f"got lo={lo} hi={hi} n_bins={n_bins}"
            )
        self.lo = float(lo)
        self.hi = float(hi)
        self.n_bins = int(n_bins)
        self.counts = [0] * self.n_bins
        self.underflow = 0
        self.overflow = 0
        self.nan = 0
        self._width = (self.hi - self.lo) / self.n_bins
        # Interior edges only: bin i covers [edges[i-1], edges[i]) with
        # the closed/open convention fixed by bisect_right, so a value
        # exactly on an edge lands deterministically in the right bin.
        self._edges = [self.lo + i * self._width
                       for i in range(1, self.n_bins)]

    # ── accumulation ────────────────────────────────────────────────────

    def update(self, values) -> None:
        """Fold an iterable of numbers in (numpy arrays iterate fine)."""
        lo, hi, edges = self.lo, self.hi, self._edges
        counts = self.counts
        for v in values:
            v = float(v)
            if math.isnan(v):
                self.nan += 1
            elif v < lo:
                self.underflow += 1
            elif v >= hi:
                self.overflow += 1
            else:
                counts[bisect_right(edges, v)] += 1

    def add(self, value: float) -> None:
        self.update((value,))

    # ── merge algebra ───────────────────────────────────────────────────

    def compatible(self, other: "FixedBinSketch") -> bool:
        return (self.lo == other.lo and self.hi == other.hi
                and self.n_bins == other.n_bins)

    def merge(self, other: "FixedBinSketch") -> "FixedBinSketch":
        """Pure merge: a NEW sketch whose counts are the cell-wise sum.
        Associative, commutative, and ``FixedBinSketch(lo, hi, n)`` is
        the identity — integer addition, nothing else."""
        if not self.compatible(other):
            raise ValueError(
                f"merge of incompatible sketches: "
                f"({self.lo},{self.hi},{self.n_bins}) vs "
                f"({other.lo},{other.hi},{other.n_bins})"
            )
        out = FixedBinSketch(self.lo, self.hi, self.n_bins)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.underflow = self.underflow + other.underflow
        out.overflow = self.overflow + other.overflow
        out.nan = self.nan + other.nan
        return out

    # ── reads ───────────────────────────────────────────────────────────

    def total(self) -> int:
        """All mass, NaN included — the conservation total."""
        return self.underflow + self.overflow + self.nan + sum(self.counts)

    def located(self) -> int:
        """Mass with a distributional location (everything but NaN)."""
        return self.underflow + self.overflow + sum(self.counts)

    def cells(self) -> list:
        """The extended count vector ``[underflow, *bins, overflow]`` —
        the common support PSI/KS compare over."""
        return [self.underflow, *self.counts, self.overflow]

    def quantile(self, q: float) -> float | None:
        """Binned quantile of the located mass: underflow reads as
        ``lo``, a bin as its midpoint, overflow as ``hi``. None when
        empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile wants 0 <= q <= 1, got {q}")
        n = self.located()
        if n == 0:
            return None
        # Smallest cell whose cumulative count reaches rank ceil(q*n),
        # rank at least 1 — the conservative "type 1" inverse CDF.
        rank = max(1, math.ceil(q * n))
        cum = self.underflow
        if cum >= rank:
            return self.lo
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank:
                return self.lo + (i + 0.5) * self._width
        return self.hi

    # ── serialization (byte-stable) ─────────────────────────────────────

    def to_dict(self) -> dict:
        return {
            "kind": "fixed_bin",
            "lo": self.lo,
            "hi": self.hi,
            "n_bins": self.n_bins,
            "counts": list(self.counts),
            "underflow": self.underflow,
            "overflow": self.overflow,
            "nan": self.nan,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FixedBinSketch":
        if d.get("kind") != "fixed_bin":
            raise ValueError(f"not a fixed_bin sketch dict: {d.get('kind')!r}")
        out = cls(d["lo"], d["hi"], d["n_bins"])
        counts = [int(c) for c in d["counts"]]
        if len(counts) != out.n_bins or any(c < 0 for c in counts):
            raise ValueError("fixed_bin counts shape/sign mismatch")
        out.counts = counts
        out.underflow = int(d["underflow"])
        out.overflow = int(d["overflow"])
        out.nan = int(d["nan"])
        if min(out.underflow, out.overflow, out.nan) < 0:
            raise ValueError("fixed_bin tail counts must be >= 0")
        return out

    def to_json(self) -> str:
        """Canonical byte-stable encoding (sorted keys, no whitespace)."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "FixedBinSketch":
        return cls.from_dict(json.loads(s))


class CalibrationSketch:
    """Reliability buckets over predicted probability ``[0, 1]``.

    Each bucket carries ``(count, positives)`` integers; the
    calibration error reads predicted as the bucket midpoint, so the
    whole sketch stays an integer-count object with exact merges."""

    __slots__ = ("n_buckets", "counts", "positives", "nan")

    def __init__(self, n_buckets: int = 10):
        if n_buckets < 1:
            raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
        self.n_buckets = int(n_buckets)
        self.counts = [0] * self.n_buckets
        self.positives = [0] * self.n_buckets
        self.nan = 0

    def update(self, predicted, outcomes) -> None:
        """Fold paired iterables: predicted probability and the binary
        empirical outcome (anything truthy counts positive). Predicted
        values are clamped to [0, 1]; NaN predictions are mass-counted
        but carry no bucket."""
        n = self.n_buckets
        for p, y in zip(predicted, outcomes):
            p = float(p)
            if math.isnan(p):
                self.nan += 1
                continue
            b = min(n - 1, max(0, int(min(1.0, max(0.0, p)) * n)))
            self.counts[b] += 1
            if y:
                self.positives[b] += 1

    def merge(self, other: "CalibrationSketch") -> "CalibrationSketch":
        if self.n_buckets != other.n_buckets:
            raise ValueError(
                f"merge of incompatible calibration sketches: "
                f"{self.n_buckets} vs {other.n_buckets} buckets"
            )
        out = CalibrationSketch(self.n_buckets)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.positives = [a + b
                         for a, b in zip(self.positives, other.positives)]
        out.nan = self.nan + other.nan
        return out

    def total(self) -> int:
        return self.nan + sum(self.counts)

    def located(self) -> int:
        return sum(self.counts)

    def calibration_error(self) -> float | None:
        """Expected calibration error against bucket midpoints:
        ``Σ_b (n_b / N) · |midpoint_b − positives_b / n_b|``. None when
        no located mass."""
        n = self.located()
        if n == 0:
            return None
        err = 0.0
        for b, (c, pos) in enumerate(zip(self.counts, self.positives)):
            if c == 0:
                continue
            mid = (b + 0.5) / self.n_buckets
            err += (c / n) * abs(mid - pos / c)
        return err

    def to_dict(self) -> dict:
        return {
            "kind": "calibration",
            "n_buckets": self.n_buckets,
            "counts": list(self.counts),
            "positives": list(self.positives),
            "nan": self.nan,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CalibrationSketch":
        if d.get("kind") != "calibration":
            raise ValueError(
                f"not a calibration sketch dict: {d.get('kind')!r}"
            )
        out = cls(d["n_buckets"])
        counts = [int(c) for c in d["counts"]]
        positives = [int(p) for p in d["positives"]]
        if (len(counts) != out.n_buckets
                or len(positives) != out.n_buckets
                or any(c < 0 for c in counts)
                or any(p < 0 for p in positives)
                or any(p > c for c, p in zip(counts, positives))):
            raise ValueError("calibration counts/positives mismatch")
        out.counts = counts
        out.positives = positives
        out.nan = int(d["nan"])
        if out.nan < 0:
            raise ValueError("calibration nan count must be >= 0")
        return out

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str) -> "CalibrationSketch":
        return cls.from_dict(json.loads(s))


# ── window-pair drift statistics ────────────────────────────────────────


def _check_pair(a: FixedBinSketch, b: FixedBinSketch) -> None:
    if not a.compatible(b):
        raise ValueError("drift statistics want sketches over the same "
                         "edges; merge-compatible pairs only")


def psi(a: FixedBinSketch, b: FixedBinSketch) -> float:
    """Population stability index between two compatible sketches over
    the extended cells (underflow + bins + overflow), with ``+0.5``
    smoothing per cell so empty cells stay finite. ``>= 0``, exactly
    ``0.0`` when the smoothed cell fractions coincide."""
    _check_pair(a, b)
    ca, cb = a.cells(), b.cells()
    k = len(ca)
    ta = sum(ca) + _PSI_SMOOTH * k
    tb = sum(cb) + _PSI_SMOOTH * k
    out = 0.0
    for na, nb in zip(ca, cb):
        pa = (na + _PSI_SMOOTH) / ta
        pb = (nb + _PSI_SMOOTH) / tb
        out += (pa - pb) * math.log(pa / pb)
    # Guard the tiny negative float residue when the distributions
    # coincide to rounding.
    return max(0.0, out)


def ks_statistic(a: FixedBinSketch, b: FixedBinSketch) -> float:
    """Two-sample KS ``D`` over the binned CDFs: the max absolute gap
    between cumulative located fractions across the extended cells.
    ``0.0`` when either side is empty (no evidence, not a fit)."""
    _check_pair(a, b)
    ca, cb = a.cells(), b.cells()
    na, nb = sum(ca), sum(cb)
    if na == 0 or nb == 0:
        return 0.0
    d = 0.0
    cum_a = cum_b = 0
    for xa, xb in zip(ca, cb):
        cum_a += xa
        cum_b += xb
        d = max(d, abs(cum_a / na - cum_b / nb))
    return d

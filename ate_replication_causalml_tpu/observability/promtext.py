"""Prometheus textfile exporter (the tentpole's part 3).

Renders the registry into the Prometheus text exposition format so a
long sweep/serving run can be scraped via the node-exporter textfile
collector: point ``--collector.textfile.directory`` at the run's output
directory and the driver's periodic ``metrics.prom`` rewrites become
scrape targets. Histograms export as summaries (``_count``/``_sum``)
plus ``_min``/``_max`` gauges — no fixed bucket boundaries, matching
the registry's summary-histogram semantics.

Also runnable standalone on a saved ``metrics.json``::

    python -m ate_replication_causalml_tpu.observability.promtext \
        results/metrics.json [results/metrics.prom]
"""

from __future__ import annotations

import json
import re
import sys

from ate_replication_causalml_tpu.observability import registry as _registry
from ate_replication_causalml_tpu.observability.export import atomic_write_text

_NAME_SAFE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "ate_tpu_"


def _prom_name(name: str) -> str:
    return _PREFIX + _NAME_SAFE.sub("_", name)


def _prom_labels(label_key: str) -> str:
    """Registry label-key string (``k=v,k2=v2``) → ``{k="v",k2="v2"}``."""
    if not label_key:
        return ""
    parts = []
    for pair in label_key.split(","):
        k, _, v = pair.partition("=")
        v = v.replace("\\", r"\\").replace('"', r"\"")
        parts.append(f'{_NAME_SAFE.sub("_", k)}="{v}"')
    return "{" + ",".join(parts) + "}"


def _labels_with_le(label_key: str, le: str) -> str:
    """Registry label-key plus the Prometheus ``le`` bucket label."""
    base = _prom_labels(label_key)
    pair = f'le="{le}"'
    if not base:
        return "{" + pair + "}"
    return base[:-1] + "," + pair + "}"


def _render_sections(counters: dict, gauges: dict, histograms: dict,
                     bucket_histograms: dict | None = None) -> str:
    lines: list[str] = []

    def family(name: str, ptype: str, samples: dict, render_sample):
        lines.append(f"# TYPE {name} {ptype}")
        for key, val in sorted(samples.items()):
            render_sample(name, _prom_labels(key), val)

    for name, samples in sorted(counters.items()):
        family(
            _prom_name(name), "counter", samples,
            lambda n, lb, v: lines.append(f"{n}{lb} {v!r}"),
        )
    for name, samples in sorted(gauges.items()):
        family(
            _prom_name(name), "gauge", samples,
            lambda n, lb, v: lines.append(f"{n}{lb} {v!r}"),
        )
    for name, samples in sorted(histograms.items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} summary")
        for key, s in sorted(samples.items()):
            lb = _prom_labels(key)
            lines.append(f"{pname}_count{lb} {s['count']!r}")
            lines.append(f"{pname}_sum{lb} {s['sum']!r}")
            lines.append(f"{pname}_min{lb} {s['min']!r}")
            lines.append(f"{pname}_max{lb} {s['max']!r}")
    # Bucketed histograms are REAL Prometheus histograms: cumulative
    # `_bucket{le=...}` series ending at +Inf == `_count`, so quantiles
    # recompute server-side via histogram_quantile().
    for name, samples in sorted((bucket_histograms or {}).items()):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} histogram")
        for key, s in sorted(samples.items()):
            cum = 0
            for bound, c in zip(s["bounds"], s["buckets"]):
                cum += c
                lines.append(
                    f"{pname}_bucket{_labels_with_le(key, repr(bound))} {cum}"
                )
            lines.append(
                f"{pname}_bucket{_labels_with_le(key, '+Inf')} {s['count']}"
            )
            lb = _prom_labels(key)
            lines.append(f"{pname}_sum{lb} {s['sum']!r}")
            lines.append(f"{pname}_count{lb} {s['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_prom_text(
    registry: _registry.MetricsRegistry | None = None,
) -> str:
    snap = (registry or _registry.REGISTRY).snapshot()
    return render_prom_from_snapshot(snap)


def render_prom_from_snapshot(snap: dict) -> str:
    return _render_sections(
        snap.get("counters", {}), snap.get("gauges", {}),
        snap.get("histograms", {}), snap.get("bucket_histograms", {}),
    )


def write_prom_textfile(
    path: str, registry: _registry.MetricsRegistry | None = None
) -> bool:
    """Atomic textfile write (node-exporter reads whole files; a torn
    write would drop the entire scrape). No-op when disabled."""
    if not _registry.enabled():
        return False
    atomic_write_text(path, render_prom_text(registry))
    return True


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or len(argv) > 2:
        print(__doc__, file=sys.stderr)
        return 2
    with open(argv[0]) as f:
        snap = json.load(f)
    text = render_prom_from_snapshot(snap)
    if len(argv) == 2:
        atomic_write_text(argv[1], text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

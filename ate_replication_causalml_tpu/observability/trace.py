"""Chrome/Perfetto trace export of the span EventLog (ISSUE 5 tentpole).

The event log already records every span with both clocks, the owning
thread, and parentage; this module renders those records in the
catapult trace-event format (the JSON ``chrome://tracing`` / Perfetto's
legacy importer read), so a sweep's schedule becomes a picture instead
of a JSONL scroll:

* one timeline track per thread that emitted spans (the scheduler's
  ``sweep-worker-N`` threads, the main thread) plus dedicated tracks
  for each exclusive lane (``lane:mesh``), the compile-prefetch lane
  and the ordered committer — records carry the routing in their attrs
  (``track=`` for a hard override, ``lane=`` for the additional lane-
  occupancy slice);
* flow arrows from each nuisance-artifact fit to the stages that
  declared it in ``needs`` (the attribution the scheduler stamps on its
  ``scheduler_node`` spans), so Perfetto draws the DAG on the timeline;
* counter tracks from ``metric_sample`` point events (see
  :class:`MetricSampler`) — nuisance-cache traffic, backoff seconds,
  device memory — sampled out of the metrics registry while the run is
  in flight;
* point events (chaos injections, retries, prefetch errors) as instant
  markers on the track of their *enclosing span* — a chaos fault shows
  up on the worker/lane that was running the faulted stage.

All timestamps are the records' monotonic clock, shifted so the trace
starts at zero; the wall-clock anchor for the origin rides in the
header (``otherData.wall_anchor_unix``), so absolute times are
recoverable without ever mixing the two clocks inside the timeline.

The exporter is pure stdlib (no jax) and a pure function of the record
list — ``scripts/analyze_trace.py`` re-reads its output and
``observability/critical_path.py`` computes the run's critical path and
overlap report from it.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability import registry as _registry

TRACE_BASENAME = "trace.json"
OVERLAP_BASENAME = "overlap_report.json"

#: trace.json layout version (otherData.trace_schema_version).
TRACE_SCHEMA_VERSION = 1

_TRACE_ENV = "ATE_TPU_TRACE"

#: record name -> trace category. Categories are the analyzer's parse
#: contract: ``node`` slices are the scheduler's execution intervals,
#: ``lane`` slices are their duplicated lane-occupancy view (never
#: counted as busy time twice), ``commit`` and ``prefetch`` feed the
#: serialization-blame section; ``request``/``batch`` are the serving
#: daemon's lifecycle slices (observability/serving_report.py's parse
#: contract, ISSUE 7).
_CATEGORIES = {
    "scheduler_node": "node",
    "commit": "commit",
    "prefetch_compile": "prefetch",
    "serving_request": "request",
    "serving_batch": "batch",
    # The fleet router's forward spans (ISSUE 20):
    # observability/fleet_report.py stitches these to the daemons'
    # ``request`` slices on request id across process boundaries.
    "router_request": "router",
}

_PID = 1

#: track-category sort order in the Perfetto UI: serving connections
#: first (one track per connection thread), then the dispatcher/device
#: track, workers, lanes, the prefetch lane, the committer, counters
#: last.
_SORT = {"conn": 0, "dispatch": 50, "worker": 60, "lane": 100,
         "prefetch": 200, "committer": 300, "counter": 400}


def trace_enabled() -> bool:
    """Host-trace master switch: on whenever telemetry is on, unless
    ``ATE_TPU_TRACE=0``. Tracing is a read of the already-collected
    event log — it never touches estimator numerics."""
    if not _registry.enabled():
        return False
    return os.environ.get(_TRACE_ENV, "1") != "0"


def _track_of(rec: dict) -> tuple[str, str]:
    """(category, name) of the PRIMARY track a record renders on."""
    attrs = rec.get("attrs") or {}
    track = attrs.get("track")
    if track == "committer":
        return ("committer", "committer")
    if track == "prefetch":
        return ("prefetch", "prefetch")
    if track:
        return ("worker", str(track))
    name = rec.get("thread_name") or f"thread-{rec.get('thread', '?')}"
    # Serving track semantics (ISSUE 7): request spans render one track
    # per connection (producer) thread; batch spans render on the
    # dispatcher/device track — the thread that owns the device.
    if rec.get("name") == "serving_request":
        return ("conn", str(name))
    if rec.get("name") == "serving_batch":
        return ("dispatch", str(name))
    # Router forward spans (ISSUE 20) render one track per router
    # connection thread, beside the daemons' conn tracks — the fleet
    # merge then shows request → forward → serve as adjacent rows.
    if rec.get("name") == "router_request":
        return ("conn", str(name))
    return ("worker", str(name))


def _is_instant(rec: dict) -> bool:
    # emit() records start == end; treat sub-microsecond spans the same
    # (they would render as zero-width slices anyway).
    return (rec["end_mono_s"] - rec["start_mono_s"]) * 1e6 < 1.0


def build_trace(records: list[dict] | None = None,
                meta: dict | None = None) -> dict:
    """Render ``records`` (default: the global event log) as a catapult
    trace object. ``meta`` merges into ``otherData`` — the sweep driver
    passes run identity, worker count and wall seconds so the analyzer
    and the Perfetto header agree on the run's envelope."""
    if records is None:
        records = _events.EVENTS.records()
    records = [r for r in records if "start_mono_s" in r]
    events: list[dict] = []
    if records:
        origin_rec = min(records, key=lambda r: r["start_mono_s"])
        origin = origin_rec["start_mono_s"]
        wall_anchor = origin_rec["start_unix"]
    else:
        origin, wall_anchor = 0.0, None
    ts = lambda mono_s: (mono_s - origin) * 1e6  # µs from trace origin

    # ── track registry: deterministic tids from (category, name) ─────
    tracks: dict[tuple[str, str], int] = {}

    def tid(cat: str, name: str) -> int:
        key = (cat, name)
        if key not in tracks:
            tracks[key] = len(tracks) + 1
        return tracks[key]

    # Primary track per span id — instants resolve to their *enclosing
    # span's* track so a chaos injection lands on the worker/lane that
    # was running the faulted stage, not on a synthetic thread row.
    by_id = {r["span_id"]: r for r in records}
    track_cache: dict[str, tuple[str, str]] = {}

    def resolve_track(rec: dict, hops: int = 0) -> tuple[str, str]:
        sid = rec["span_id"]
        if sid in track_cache:
            return track_cache[sid]
        out = _track_of(rec)
        if _is_instant(rec) and "track" not in (rec.get("attrs") or {}):
            parent = by_id.get(rec.get("parent_id") or "")
            if parent is not None and hops < 16:
                out = resolve_track(parent, hops + 1)
        track_cache[sid] = out
        return out

    flow_id = 0
    artifact_slices: dict[str, dict] = {}
    stage_slices: list[dict] = []
    request_slices: list[dict] = []
    batch_by_seq: dict[int, dict] = {}
    counter_series: set[str] = set()

    for rec in sorted(records, key=lambda r: (r["start_mono_s"], r["span_id"])):
        attrs = rec.get("attrs") or {}
        if rec["name"] == "metric_sample":
            # Counter track: one series per metric name.
            metric = str(attrs.get("metric", "metric"))
            counter_series.add(metric)
            events.append({
                "name": metric, "cat": "counter", "ph": "C", "pid": _PID,
                "tid": tid("counter", "counters"),
                "ts": ts(rec["start_mono_s"]),
                "args": {"value": attrs.get("value", 0.0)},
            })
            continue
        cat = _CATEGORIES.get(rec["name"], "span")
        tcat, tname = resolve_track(rec)
        args = {"status": rec.get("status"), "span_id": rec["span_id"]}
        args.update({
            k: v for k, v in attrs.items()
            if isinstance(v, (str, int, float, bool)) and k != "track"
        })
        label = str(
            attrs.get("node") or attrs.get("method") or attrs.get("stage")
            or attrs.get("artifact") or rec["name"]
        )
        if _is_instant(rec):
            events.append({
                "name": label, "cat": cat, "ph": "i", "s": "t", "pid": _PID,
                "tid": tid(tcat, tname), "ts": ts(rec["start_mono_s"]),
                "args": args,
            })
            continue
        slice_ev = {
            "name": label, "cat": cat, "ph": "X", "pid": _PID,
            "tid": tid(tcat, tname), "ts": ts(rec["start_mono_s"]),
            "dur": (rec["end_mono_s"] - rec["start_mono_s"]) * 1e6,
            "args": args,
        }
        events.append(slice_ev)
        lane = attrs.get("lane")
        if lane:
            # Duplicate slice on the lane-occupancy track: the worker
            # tracks show who ran what; the lane track shows WHY two
            # collective launches never overlapped.
            events.append(dict(slice_ev, cat="lane",
                               tid=tid("lane", f"lane:{lane}")))
        if cat == "node":
            if attrs.get("kind") == "artifact":
                artifact_slices[str(attrs.get("node"))] = slice_ev
            elif attrs.get("needs"):
                stage_slices.append(slice_ev)
        elif cat == "request" and attrs.get("batch_seq") is not None:
            request_slices.append(slice_ev)
        elif cat == "batch" and attrs.get("seq") is not None:
            batch_by_seq[int(attrs["seq"])] = slice_ev

    # ── flow arrows: artifact fit -> each consuming stage ─────────────
    for stage_ev in stage_slices:
        needs = [n for n in str(stage_ev["args"].get("needs", "")).split(",") if n]
        for need in needs:
            src = artifact_slices.get(need)
            if src is None:
                continue  # resumed/never-scheduled artifact: no slice
            flow_id += 1
            common = {"cat": "dep", "name": need, "id": flow_id, "pid": _PID}
            events.append(dict(common, ph="s", tid=src["tid"],
                               ts=src["ts"] + src["dur"]))
            events.append(dict(common, ph="f", bp="e", tid=stage_ev["tid"],
                               ts=stage_ev["ts"]))

    # ── serving flow arrows: request → batch → reply (ISSUE 7) ────────
    # One three-point chain per coalesced request: start at the request
    # slice's enqueue, step through the micro-batch it rode on the
    # dispatcher track, finish back on the connection track at reply —
    # Perfetto draws the coalescer's fan-in/fan-out on the timeline.
    for req_ev in request_slices:
        batch_ev = batch_by_seq.get(int(req_ev["args"]["batch_seq"]))
        if batch_ev is None:
            continue  # batch span missing (ring-evicted): no arrow
        flow_id += 1
        common = {"cat": "req", "name": "request",
                  "id": flow_id, "pid": _PID}
        events.append(dict(common, ph="s", tid=req_ev["tid"],
                           ts=req_ev["ts"]))
        events.append(dict(common, ph="t", tid=batch_ev["tid"],
                           ts=batch_ev["ts"]))
        events.append(dict(common, ph="f", bp="e", tid=req_ev["tid"],
                           ts=req_ev["ts"] + req_ev["dur"]))

    # ── metadata: names + deterministic sort order ────────────────────
    meta_events = [{
        "name": "process_name", "ph": "M", "pid": _PID,
        "args": {"name": "ate-sweep"},
    }]
    for (tcat, tname), t in sorted(tracks.items(), key=lambda kv: kv[1]):
        meta_events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": t,
            "args": {"name": tname},
        })
        meta_events.append({
            "name": "thread_sort_index", "ph": "M", "pid": _PID, "tid": t,
            "args": {"sort_index": _SORT.get(tcat, 0) + t},
        })
    other = {
        "trace_schema_version": TRACE_SCHEMA_VERSION,
        "clock": "monotonic",
        "time_unit": "us",
        "mono_origin_s": origin,
        "wall_anchor_unix": wall_anchor,
        "counter_series": sorted(counter_series),
    }
    if meta:
        other.update(meta)
    return {
        "traceEvents": meta_events + events,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def write_trace_json(path: str, records: list[dict] | None = None,
                     meta: dict | None = None,
                     trace: dict | None = None) -> str | None:
    """Atomically write the catapult trace to ``path``; returns the path
    or None when tracing is disabled (no husk files). Callers that also
    analyze the trace pass the prebuilt ``trace`` object — the write
    recipe (compact separators + trailing newline, atomic) lives only
    here."""
    if not trace_enabled():
        return None
    from ate_replication_causalml_tpu.observability.export import (
        atomic_write_text,
    )

    if trace is None:
        trace = build_trace(records, meta=meta)
    # Compact separators: a quick sweep's trace is ~1-2k events and the
    # file is read by machines (Perfetto, the analyzer), not humans.
    atomic_write_text(path, json.dumps(trace, separators=(",", ":")) + "\n")
    return path


def write_trace_artifacts(outdir: str, trace: dict,
                          overlap_needs_nodes: bool = False) -> list[str]:
    """Write the ``trace.json`` + ``overlap_report.json`` pair into
    ``outdir`` — THE one write recipe both the sweep driver and bench
    use. With ``overlap_needs_nodes``, the overlap report is skipped
    when the trace scheduled no nodes (a forest-only bench has no DAG
    to analyze); the sweep always writes it (a fully resumed run's
    empty report is itself the answer). Returns the paths written
    ([] when tracing is disabled)."""
    if not trace_enabled():
        return []
    from ate_replication_causalml_tpu.observability import (
        critical_path as _cpath,
    )
    from ate_replication_causalml_tpu.observability.export import (
        atomic_write_json,
    )

    tpath = os.path.join(outdir, TRACE_BASENAME)
    write_trace_json(tpath, trace=trace)
    paths = [tpath]
    if overlap_needs_nodes and not _cpath.nodes_from_trace(trace):
        return paths
    opath = os.path.join(outdir, OVERLAP_BASENAME)
    atomic_write_json(opath, _cpath.overlap_report(trace))
    paths.append(opath)
    return paths


class MetricSampler:
    """Background sampler turning registry metrics into counter tracks.

    Every ``interval_s`` the sampler reads the configured metric
    families (``registry.peek`` — no collector hooks, so a tick is a
    dict copy under the registry lock, never a filesystem scan) and
    emits one ``metric_sample`` point event per family with the summed
    value. The exporter renders those as Perfetto counter tracks.

    The default 0.5 s interval is deliberate: samples share the span
    event log's 100k-record ring, and a chattier sampler on an
    hour-long run would evict the early scheduler spans — exactly the
    records the critical-path analyzer needs. At 0.5 s, four families
    cost ~29k records/hour, well inside the ring.

    The sweep driver starts a sampler only for multi-worker runs — the
    ``--sequential`` escape hatch promises a zero-thread process, so
    sequential runs take a single inline :meth:`sample_once` at the end
    instead (the track exists; it just has one point).
    """

    DEFAULT_METRICS = (
        "nuisance_cache_requests_total",
        "shard_backoff_seconds_total",
        "device_memory_bytes",
        "scheduler_prefetch_total",
    )

    #: The families the serving daemon samples instead (ISSUE 7): the
    #: live queue depth and the request/reject/batch counters become
    #: Perfetto counter tracks over the serving window.
    SERVING_METRICS = (
        "serving_requests_total",
        "serving_rejected_total",
        "serving_queue_depth",
        "serving_batches_total",
    )

    def __init__(self, metrics: tuple[str, ...] | None = None,
                 interval_s: float = 0.5):
        self.metrics = tuple(metrics) if metrics is not None else self.DEFAULT_METRICS
        self.interval_s = interval_s
        self._stop = threading.Event()
        # Guards the handle: start() runs on the daemon's startup
        # thread while stop() is reachable from per-connection drain
        # threads — unguarded, a double start leaks a sampler and a
        # racing stop can join a half-published handle (JGL019).
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def sample_once(self) -> None:
        if not _registry.enabled():
            return
        for name in self.metrics:
            samples = _registry.REGISTRY.peek(name)
            if not samples:
                continue
            _events.emit(
                "metric_sample", status="sample", metric=name,
                value=float(sum(samples.values())),
            )

    def start(self) -> None:
        if not _registry.enabled():
            return
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="trace-sampler", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float | None = 5.0) -> None:
        """Stop the loop and take one final sample so the counter
        tracks end at the run's closing values."""
        self._stop.set()
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:  # join outside the lock: never block start()
            thread.join(timeout)
        self.sample_once()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample_once()


def run_meta(workers: int | None = None, wall_s: float | None = None,
             **extra) -> dict:
    """The ``otherData`` payload the sweep driver attaches: worker-pool
    width and the run's wall seconds (the analyzer's denominator), plus
    free-form identity fields."""
    out: dict = {"exported_unix": time.time()}
    if workers is not None:
        out["workers"] = int(workers)
    if wall_s is not None:
        out["wall_s"] = float(wall_s)
    out.update(extra)
    return out

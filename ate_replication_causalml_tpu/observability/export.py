"""Exporters: atomic file writes + the metrics.json / events.jsonl pair.

Every JSON artifact the framework persists next to a checkpoint goes
through :func:`atomic_write_text` (tmp file in the target directory +
``os.replace``) — a kill mid-write can no longer leave a truncated
``report.json`` / ``metrics.json`` beside a valid ``results.jsonl``
(the satellite for ``pipeline.py`` and ``StageTimer.dump``).

``write_run_artifacts`` is the driver-facing call: one line in
``run_sweep``/``bench.py`` lands ``metrics.json``, ``events.jsonl`` and
the Prometheus textfile in the output directory. All writers are no-ops
when telemetry is disabled — no empty husk files.
"""

from __future__ import annotations

import contextlib
import json
import os
import stat
import tempfile
import threading
from typing import Iterator

from ate_replication_causalml_tpu.observability import events as _events
from ate_replication_causalml_tpu.observability import registry as _registry

METRICS_BASENAME = "metrics.json"
EVENTS_BASENAME = "events.jsonl"
PROMTEXT_BASENAME = "metrics.prom"

_artifact_mode_cache: int | None = None
_artifact_mode_lock = threading.Lock()


def _artifact_mode() -> int:
    """The mode a plain ``open(path, "w")`` would give a new file —
    0o666 masked by the process umask. Probed race-free by creating a
    throwaway file with requested mode 0o666 and stat-ing it: the
    ``os.umask(0)``-then-restore dance would leave a window in which
    files created by OTHER threads (this module serves multi-threaded
    telemetry) come out world-writable."""
    global _artifact_mode_cache
    if _artifact_mode_cache is None:
        with _artifact_mode_lock:
            if _artifact_mode_cache is None:
                d = tempfile.gettempdir()
                for i in range(100):
                    probe = os.path.join(
                        d, f".ate_umask_probe_{os.getpid()}_{i}"
                    )
                    try:
                        fd = os.open(
                            probe,
                            os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                            0o666,
                        )
                    except FileExistsError:
                        continue
                    except OSError:
                        break  # tempdir uncooperative: fallback below
                    try:
                        _artifact_mode_cache = stat.S_IMODE(
                            os.fstat(fd).st_mode
                        )
                    finally:
                        os.close(fd)
                        try:
                            os.unlink(probe)
                        except OSError:
                            pass
                    break
                if _artifact_mode_cache is None:
                    # Probing must never make a WRITE fail that plain
                    # open(path, "w") would have survived.
                    _artifact_mode_cache = 0o644
    return _artifact_mode_cache


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` to ``path`` atomically: tmp file in the same
    directory (same filesystem — ``os.replace`` must not cross mounts),
    fsync, rename. Binary/streaming writers (the verified ``.npz``
    checkpoint writer) use :func:`atomic_file` directly."""
    _atomic_write(path, text)


@contextlib.contextmanager
def atomic_file(path: str) -> Iterator[str]:
    """Yield a tmp path in ``path``'s directory for the caller to write
    (streaming writers — ``np.savez_compressed`` — never need the whole
    artifact in memory); on a clean exit the tmp is fsynced, given
    ``open(path, "w")``-equivalent permissions and ``os.replace``d over
    ``path``; on an exception it is unlinked. Same-filesystem by
    construction, so the rename is atomic."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp")
    os.close(fd)
    try:
        yield tmp
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        # mkstemp creates 0600; match plain open(path, "w") semantics:
        # an EXISTING artifact keeps its mode (a user-tightened 0600
        # stays 0600), a new one gets the umask-derived default
        # (shared results dirs are read by other uids/groups).
        try:
            mode = stat.S_IMODE(os.stat(path).st_mode)
        except OSError:
            mode = _artifact_mode()
        os.chmod(tmp, mode)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _atomic_write(path: str, data: str) -> None:
    with atomic_file(path) as tmp:
        # No fsync here: atomic_file fsyncs the tmp before the rename.
        with open(tmp, "w") as f:
            f.write(data)


def atomic_write_json(path: str, obj, indent: int | None = 1,
                      sort_keys: bool = False) -> None:
    atomic_write_text(
        path, json.dumps(obj, indent=indent, sort_keys=sort_keys) + "\n"
    )


def write_metrics_json(path: str,
                       registry: _registry.MetricsRegistry | None = None,
                       extra: dict | None = None) -> dict | None:
    """Snapshot ``registry`` (default: the global one) to ``path``.
    ``extra`` merges into the top level (e.g. run identity). Returns the
    snapshot, or None when telemetry is disabled (nothing written)."""
    if not _registry.enabled():
        return None
    snap = (registry or _registry.REGISTRY).snapshot()
    if extra:
        snap.update(extra)
    atomic_write_json(path, snap)
    return snap


def write_events_jsonl(path: str, log: _events.EventLog | None = None) -> bool:
    if not _registry.enabled():
        return False
    atomic_write_text(path, (log or _events.EVENTS).to_jsonl())
    return True


def write_run_artifacts(outdir: str, extra: dict | None = None) -> list[str]:
    """Write metrics.json + events.jsonl + metrics.prom into ``outdir``.
    Returns the paths written ([] when telemetry is disabled)."""
    if not _registry.enabled():
        return []
    from ate_replication_causalml_tpu.observability.promtext import (
        write_prom_textfile,
    )

    paths = []
    mpath = os.path.join(outdir, METRICS_BASENAME)
    write_metrics_json(mpath, extra=extra)
    paths.append(mpath)
    epath = os.path.join(outdir, EVENTS_BASENAME)
    write_events_jsonl(epath)
    paths.append(epath)
    ppath = os.path.join(outdir, PROMTEXT_BASENAME)
    write_prom_textfile(ppath)
    paths.append(ppath)
    return paths

"""Structured event log (the tentpole's part 1, second half).

Span-shaped records — name, start/end in both monotonic and wall time,
status, attributes, parent span — collected in memory and exported as
``results/events.jsonl``. ``StageTimer``/``stage``/the sweep driver are
thin emitters into this log; a reader can reconstruct the whole run's
timeline (what computed, what resumed, what retried, in what nesting)
without parsing prints.

Parentage is tracked per thread: a span opened inside another span on
the same thread records it as parent. The log is ring-buffered
(``max_events``) so a week-long serving run cannot grow it unbounded;
the oldest records are evicted first and evictions are counted in the export header.

Zero-cost when disabled (``ATE_TPU_TELEMETRY=0``): :func:`span` hands
back a shared no-op context manager.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import threading
import time
from typing import Iterator

from ate_replication_causalml_tpu.observability.registry import (
    SCHEMA_VERSION,
    enabled,
)


class Span:
    """One open span. Mutate ``attrs`` / call :meth:`set_status` while
    inside the ``with`` block; the record is appended on exit. Status
    defaults to ``ok`` (``error`` on an exception escaping the block)."""

    __slots__ = (
        "name", "span_id", "parent_id", "status", "attrs",
        "start_unix", "start_mono", "thread", "thread_name",
    )

    def __init__(self, name: str, span_id: str, parent_id: str | None,
                 attrs: dict):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.status = "ok"
        self.attrs = attrs
        self.start_unix = time.time()
        self.start_mono = time.monotonic()
        self.thread = threading.get_ident()
        # The trace exporter (observability/trace.py) names timeline
        # tracks after threads; the ident alone is an opaque integer.
        self.thread_name = threading.current_thread().name

    def set_status(self, status: str) -> None:
        self.status = status

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def _record(self, end_mono: float, end_unix: float) -> dict:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "status": self.status,
            "start_unix": self.start_unix,
            "end_unix": end_unix,
            "start_mono_s": self.start_mono,
            "end_mono_s": end_mono,
            "dur_s": end_mono - self.start_mono,
            "thread": self.thread,
            "thread_name": self.thread_name,
            "attrs": self.attrs,
        }


class _NullSpan:
    """Shared do-nothing span for disabled mode (and a safe object for
    callers that unconditionally ``sp.set_status(...)``)."""

    __slots__ = ()

    def set_status(self, status: str) -> None:
        pass

    def set_attr(self, key: str, value) -> None:
        pass


_NULL_SPAN = _NullSpan()


@contextlib.contextmanager
def _null_ctx() -> Iterator[_NullSpan]:
    yield _NULL_SPAN


class EventLog:
    """Thread-safe in-memory span/event collector."""

    def __init__(self, max_events: int = 100_000):
        self.max_events = max_events
        self._lock = threading.Lock()
        # True ring: at capacity the OLDEST record is evicted — the tail
        # of a dying run (the error spans) is the diagnostic part.
        self._records: collections.deque[dict] = collections.deque(
            maxlen=max_events
        )
        self._dropped = 0
        self._ids = itertools.count(1)
        self._tls = threading.local()

    def _next_id(self) -> str:
        return f"{next(self._ids):08x}"

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _append(self, rec: dict) -> None:
        with self._lock:
            if len(self._records) == self.max_events:
                self._dropped += 1  # deque evicts the oldest record
            self._records.append(rec)

    @contextlib.contextmanager
    def span(self, name: str, parent_id: str | None = None,
             **attrs) -> Iterator[Span | _NullSpan]:
        """Open a span; the record lands in the log when the block
        exits. Exceptions mark status ``error`` (with the exception type
        in attrs) and propagate.

        Parentage is per-thread by default; ``parent_id`` overrides it
        for work handed across threads (the concurrent sweep's worker
        pool opens stage spans on threads where the ``run_sweep`` span
        is not on the local stack)."""
        if not enabled():
            with _null_ctx() as sp:
                yield sp
            return
        stack = self._stack()
        if parent_id is None:
            parent_id = stack[-1].span_id if stack else None
        sp = Span(name, self._next_id(), parent_id, dict(attrs))
        stack.append(sp)
        try:
            yield sp
        except BaseException as e:
            sp.status = "error"
            sp.attrs.setdefault("error_type", type(e).__name__)
            raise
        finally:
            stack.pop()
            self._append(sp._record(time.monotonic(), time.time()))

    def emit(self, name: str, status: str = "event", **attrs) -> None:
        """Zero-duration point event (parented like a span)."""
        if not enabled():
            return
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        sp = Span(name, self._next_id(), parent, dict(attrs))
        sp.status = status
        self._append(sp._record(sp.start_mono, sp.start_unix))

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._dropped = 0

    def to_jsonl(self) -> str:
        """The events.jsonl payload: a versioned header line, then one
        record per line in arrival order."""
        header = {
            "schema_version": SCHEMA_VERSION,
            "kind": "events_header",
            "dropped": self.dropped,
        }
        lines = [json.dumps(header)]
        lines += [json.dumps(r) for r in self.records()]
        return "\n".join(lines) + "\n"


#: The process-global default event log (mirrors registry.REGISTRY).
EVENTS = EventLog()


def span(name: str, parent_id: str | None = None, **attrs):
    return EVENTS.span(name, parent_id=parent_id, **attrs)


def emit(name: str, status: str = "event", **attrs) -> None:
    EVENTS.emit(name, status=status, **attrs)

"""Critical-path / overlap analysis of an exported sweep trace.

Input is the catapult ``trace.json`` written by
:mod:`observability.trace` — specifically its ``cat="node"`` slices
(the scheduler's per-node execution intervals, carrying ``kind``,
``lane``, ``needs`` and ``stage_idx`` attribution) plus the ``commit``
and ``prefetch`` slices. From those, this module answers the questions
PR 4's wall-clock record could not:

* **critical path** — the longest chain of node intervals through the
  union of the declared dependency edges (artifact → consuming stage,
  from each slice's ``needs``) and the per-track execution order (a
  node's implicit predecessor is whatever its worker ran before it).
  Computed as a longest-path DP over that DAG, so the result is a pure
  function of the trace: a sequential run's path is the full execution
  sequence in declared order, and any run's path duration is ≥ its
  longest single node (a one-node chain is always a candidate).
* **per-lane busy/wait** — for every worker track and exclusive lane:
  busy seconds (Σ node durations), wait seconds (wall − busy), nodes.
* **overlap efficiency** — Σ worker busy / (wall × workers): 1.0 means
  every worker computed for the whole run, 1/workers means the run was
  effectively sequential.
* **serialization blame** — mesh-lane occupancy (time the exclusive
  lane was held, the ceiling on collective overlap), committer busy
  time (ordered-commit stall budget), and prefetch outcomes.

Pure stdlib and jax-free: ``scripts/analyze_trace.py`` runs it on any
saved ``trace.json`` without an accelerator stack.
"""

from __future__ import annotations

import dataclasses

#: overlap_report.json layout version.
OVERLAP_SCHEMA_VERSION = 1

#: slack for "predecessor ended before this node started": commit and
#: scheduling bookkeeping can put a dependent's span start a hair
#: before its dependency's recorded end on coarse clocks.
_EPS_S = 1e-3


@dataclasses.dataclass(frozen=True)
class NodeInterval:
    """One scheduler-node execution slice parsed back from the trace."""

    name: str
    kind: str              # "artifact" | "stage"
    lane: str              # "" when unlaned
    track: str             # worker-track name the slice rendered on
    start_s: float
    dur_s: float
    needs: tuple[str, ...]
    stage_idx: int

    @property
    def end_s(self) -> float:
        return self.start_s + self.dur_s


def _track_names(trace: dict) -> dict[int, str]:
    out: dict[int, str] = {}
    for ev in trace.get("traceEvents", ()):
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            out[ev["tid"]] = str(ev.get("args", {}).get("name", ev["tid"]))
    return out


def _slices(trace: dict, cat: str) -> list[dict]:
    return [
        ev for ev in trace.get("traceEvents", ())
        if ev.get("ph") == "X" and ev.get("cat") == cat
    ]


def nodes_from_trace(trace: dict) -> list[NodeInterval]:
    """The scheduler-node intervals, sorted by (start, name). Lane
    duplicates (``cat="lane"``) are deliberately excluded — counting
    them too would double every laned node's busy time."""
    names = _track_names(trace)
    nodes = []
    for ev in _slices(trace, "node"):
        args = ev.get("args", {})
        needs = tuple(
            n for n in str(args.get("needs", "")).split(",") if n
        )
        nodes.append(NodeInterval(
            name=str(args.get("node", ev.get("name", "?"))),
            kind=str(args.get("kind", "stage")),
            lane=str(args.get("lane", "") or ""),
            track=names.get(ev["tid"], str(ev["tid"])),
            start_s=ev["ts"] / 1e6,
            dur_s=ev["dur"] / 1e6,
            needs=needs,
            stage_idx=int(args.get("stage_idx", -1)),
        ))
    nodes.sort(key=lambda n: (n.start_s, n.name))
    return nodes


def critical_path(nodes: list[NodeInterval]) -> tuple[list[dict], float]:
    """Longest chain through dependency + same-track-order edges.

    Returns ``(path, total_seconds)`` where ``path`` lists the chain's
    nodes start-to-finish, each with its execution seconds and the wait
    gap behind its chosen predecessor. Duplicate node names (a refit)
    resolve to the earliest interval — the engine schedules each node
    once, so duplicates only appear in hand-built traces.
    """
    if not nodes:
        return [], 0.0
    by_name: dict[str, NodeInterval] = {}
    for n in nodes:
        by_name.setdefault(n.name, n)
    prev_on_track: dict[NodeInterval, NodeInterval] = {}
    last: dict[str, NodeInterval] = {}
    for n in nodes:  # already start-sorted
        if n.track in last:
            prev_on_track[n] = last[n.track]
        last[n.track] = n

    cp: dict[NodeInterval, float] = {}
    choice: dict[NodeInterval, NodeInterval | None] = {}
    for n in nodes:
        best, best_cp = None, 0.0
        cands = [by_name.get(d) for d in n.needs]
        cands.append(prev_on_track.get(n))
        for c in cands:
            if c is None or c is n or c not in cp:
                continue
            if c.end_s > n.start_s + _EPS_S:
                continue  # not actually a predecessor in this timeline
            # Deterministic tie-break: earlier-declared, then name.
            if best is None or cp[c] > best_cp or (
                cp[c] == best_cp
                and (c.stage_idx, c.name) < (best.stage_idx, best.name)
            ):
                best, best_cp = c, cp[c]
        cp[n] = n.dur_s + best_cp
        choice[n] = best

    tail = max(nodes, key=lambda n: (cp[n], -n.stage_idx, n.name))
    chain: list[NodeInterval] = []
    cur: NodeInterval | None = tail
    while cur is not None and len(chain) <= len(nodes):
        chain.append(cur)
        cur = choice[cur]
    chain.reverse()
    path = []
    for i, n in enumerate(chain):
        wait = 0.0 if i == 0 else max(0.0, n.start_s - chain[i - 1].end_s)
        path.append({
            "name": n.name, "kind": n.kind, "lane": n.lane,
            "track": n.track, "start_s": round(n.start_s, 6),
            "dur_s": round(n.dur_s, 6), "wait_s": round(wait, 6),
        })
    return path, cp[tail]


def track_stats(nodes: list[NodeInterval], wall_s: float) -> dict:
    out: dict[str, dict] = {}
    for n in nodes:
        t = out.setdefault(n.track, {"busy_s": 0.0, "nodes": 0})
        t["busy_s"] += n.dur_s
        t["nodes"] += 1
    for t in out.values():
        t["busy_s"] = round(t["busy_s"], 6)
        t["wait_s"] = round(max(0.0, wall_s - t["busy_s"]), 6)
        t["utilization"] = round(t["busy_s"] / wall_s, 4) if wall_s > 0 else 0.0
    return out


def _run_wall(trace: dict, nodes: list[NodeInterval]) -> float:
    other = trace.get("otherData", {})
    wall = other.get("wall_s")
    if isinstance(wall, (int, float)) and wall > 0:
        return float(wall)
    for ev in _slices(trace, "span"):
        if ev.get("name") == "run_sweep":
            return ev["dur"] / 1e6
    if nodes:
        return max(n.end_s for n in nodes) - min(n.start_s for n in nodes)
    return 0.0


def overlap_report(trace: dict) -> dict:
    """The ``overlap_report.json`` payload for one exported trace."""
    nodes = nodes_from_trace(trace)
    wall_s = _run_wall(trace, nodes)
    other = trace.get("otherData", {})
    workers = other.get("workers")
    if not isinstance(workers, int) or workers < 1:
        workers = max(1, len({n.track for n in nodes}))
    path, cp_s = critical_path(nodes)
    tracks = track_stats(nodes, wall_s)
    busy_total = sum(t["busy_s"] for t in tracks.values())
    denom = wall_s * workers
    lanes: dict[str, dict] = {}
    for n in nodes:
        if not n.lane:
            continue
        lane = lanes.setdefault(n.lane, {"busy_s": 0.0, "nodes": 0})
        lane["busy_s"] += n.dur_s
        lane["nodes"] += 1
    for lane in lanes.values():
        lane["busy_s"] = round(lane["busy_s"], 6)
        lane["occupancy"] = (
            round(lane["busy_s"] / wall_s, 4) if wall_s > 0 else 0.0
        )
    commits = _slices(trace, "commit")
    prefetch = _slices(trace, "prefetch")
    pf_status: dict[str, int] = {}
    for ev in prefetch:
        # Span status "ok" means the warm hook compiled; anything else
        # (the error path re-raises out of the span) keeps its label.
        st = str(ev.get("args", {}).get("status", "ok"))
        st = "compiled" if st == "ok" else st
        pf_status[st] = pf_status.get(st, 0) + 1
    longest = max((n.dur_s for n in nodes), default=0.0)
    return {
        "schema_version": OVERLAP_SCHEMA_VERSION,
        "wall_s": round(wall_s, 6),
        "workers": workers,
        "nodes": len(nodes),
        "tracks": tracks,
        "busy_total_s": round(busy_total, 6),
        "overlap_efficiency": round(busy_total / denom, 4) if denom > 0 else 0.0,
        "critical_path": path,
        "critical_path_s": round(cp_s, 6),
        "critical_path_share": round(cp_s / wall_s, 4) if wall_s > 0 else 0.0,
        "longest_node_s": round(longest, 6),
        "serialization": {
            "lanes": lanes,
            "committer": {
                "busy_s": round(sum(ev["dur"] for ev in commits) / 1e6, 6),
                "commits": len(commits),
            },
            "prefetch": pf_status,
        },
    }

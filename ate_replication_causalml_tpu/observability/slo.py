"""SLO engine: declared objectives over rolling windows (ISSUE 7).

The serving daemon's metrics say what *happened*; this module says
whether that is *acceptable*. An :class:`SLO` declares an objective —
"99.9% of requests succeed", "99% of requests finish under 250 ms" —
and the :class:`SLOEngine` evaluates it over multiple rolling windows
as a **burn rate**: the rate the error budget is being spent, where
1.0 means "exactly on budget" and N means "the budget for the whole
window is gone in 1/N of it" (the multi-window burn-rate alerting shape
from the SRE workbook). Short windows catch fast regressions, long
windows catch slow leaks; the schema checker pins the windows ladder
ascending so a report is always readable smallest-to-largest.

Sources are the existing registry families — no new instrumentation:

* ``latency`` SLOs read a :class:`~.registry.BucketHistogram` (good =
  observations in buckets whose upper bound is ≤ the threshold, the
  conservative Prometheus-style reading);
* ``availability`` SLOs read a labeled counter (good = the samples
  matching ``good_match``, total = all samples).

Determinism: the engine never free-runs. Every window figure is a
difference between two explicit :meth:`SLOEngine.tick` snapshots taken
from an injectable clock, so a test can replay a hand-built histogram
sequence and assert exact burn rates — and two evaluations over the
same snapshots produce bit-identical reports. jax-free by construction
(this module imports only the registry); it must be importable on
hosts that will never initialize a backend.

Consumers: the admin endpoint's ``/healthz``, the daemon's ``stats``
op, and the ``slo_report.json`` written beside ``metrics.json`` at
:meth:`CateServer.stop`/``dump`` (validated by
``scripts/check_metrics_schema.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable

from ate_replication_causalml_tpu.observability import registry as _registry

#: slo_report.json layout version.
SLO_SCHEMA_VERSION = 1

#: Default multi-window ladder (ascending — enforced): 1 min for fast
#: burns, 5 min for sustained ones, 30 min for slow leaks.
DEFAULT_WINDOWS: tuple[float, ...] = (60.0, 300.0, 1800.0)


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declared objective over one registry family."""

    name: str
    #: "latency" (bucket histogram + threshold) or "availability"
    #: (labeled counter + good_match).
    kind: str
    #: target good fraction in (0, 1) — e.g. 0.999 ⇒ a 0.1% budget.
    objective: float
    #: source metric family name in the registry.
    metric: str
    #: rolling windows, seconds, strictly ascending.
    windows_s: tuple[float, ...] = DEFAULT_WINDOWS
    #: latency only: observations ≤ this are good.
    threshold_s: float | None = None
    #: availability only: the ``k=v`` label pairs (comma-separated, ALL
    #: must match) that mark a sample good, matched against the
    #: registry's canonical label key.
    good_match: str = "status=ok"
    #: availability only: pairs restricting which samples count at all —
    #: the per-model scope (``model=tenantA``). Empty = every sample.
    scope_match: str = ""
    #: availability only: alternatives (separated by ``|``) of ``k=v``
    #: pair groups DISQUALIFYING a sample entirely — a sample matching
    #: ANY alternative counts toward neither the totals nor the good
    #: side (a disqualified sample must not bank budget either, e.g. a
    #: ``status=ok`` sample on an ignored channel). The shedder's SLOs
    #: ignore
    #: ``status=rejected_shed`` (shedding must not feed back into the
    #: burn rate that triggered it) and the client-error rejects (a
    #: malformed-request spammer must not burn a tenant's budget and
    #: starve its healthy traffic).
    ignore_match: str = ""

    def __post_init__(self):
        if self.kind not in ("latency", "availability"):
            raise ValueError(f"SLO {self.name}: unknown kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name}: objective must be in (0, 1), "
                f"got {self.objective}"
            )
        windows = tuple(float(w) for w in self.windows_s)
        if not windows or any(w <= 0 for w in windows) or any(
            b <= a for a, b in zip(windows, windows[1:])
        ):
            raise ValueError(
                f"SLO {self.name}: windows must be positive and strictly "
                f"ascending, got {self.windows_s!r}"
            )
        object.__setattr__(self, "windows_s", windows)
        if self.kind == "latency" and self.threshold_s is None:
            raise ValueError(f"SLO {self.name}: latency SLOs need threshold_s")


def default_serving_slos(
    latency_threshold_s: float = 0.25,
    windows_s: tuple[float, ...] = DEFAULT_WINDOWS,
) -> tuple[SLO, ...]:
    """The daemon's stock objectives: 99.9% of requests reach a
    terminal ``ok`` (rejects and errors spend the budget), 99% of
    served requests complete under the latency threshold."""
    return (
        SLO(name="availability", kind="availability", objective=0.999,
            metric="serving_requests_total", windows_s=windows_s),
        SLO(name="latency", kind="latency", objective=0.99,
            metric="serving_request_seconds", windows_s=windows_s,
            threshold_s=latency_threshold_s),
    )


def fleet_slos(
    models: tuple[str, ...],
    objective: float = 0.999,
    windows_s: tuple[float, ...] = DEFAULT_WINDOWS,
    metric: str = "serving_fleet_requests_total",
) -> tuple[SLO, ...]:
    """Per-model availability objectives over the fleet counter
    (ISSUE 11) — one ``fleet:<model>`` SLO per served model, scoped to
    that model's samples so one tenant's burn never spends another's
    budget. Excluded from the totals: shed rejects (the *response* to
    a burn, not part of it — the property that keeps SLO-burn-driven
    shedding from latching) and client-error rejects (bad_request /
    retired_model are the CALLER's fault — the 4xx convention; a
    malformed-request spammer must not burn a tenant's budget until
    the shedder starves its healthy traffic). Server-caused rejects
    (serve_fault / degraded / model_degraded / overloaded) DO spend
    the budget."""
    return tuple(
        SLO(name=f"fleet:{m}", kind="availability", objective=objective,
            metric=metric, windows_s=windows_s,
            scope_match=f"model={m}", good_match="status=ok",
            ignore_match="status=rejected_shed"
                         "|status=rejected_bad_request"
                         "|status=rejected_retired_model")
        for m in models
    )


def stat_health_slos(
    models: tuple[str, ...] = ("default",),
    objective: float = 0.9,
    windows_s: tuple[float, ...] = DEFAULT_WINDOWS,
    metric: str = "serving_stat_windows_total",
) -> tuple[SLO, ...]:
    """Statistical-health objectives (ISSUE 16) over the sealed-window
    counter the :class:`~.stathealth.StatHealthMonitor` emits — the
    ROADMAP item 3 shape: the burn-rate machinery applied to
    statistical health, not just latency. Two per model:

    * ``stat_drift:<model>`` — the fraction of sealed distribution
      windows (cate/covariate/propensity) whose window-pair PSI/KS
      stayed under the drift thresholds. ``sparse`` windows (either
      side under the minimum count) are excluded from the totals
      outright — thin evidence must neither spend nor bank budget —
      and so is the calibration channel, which has its own objective.
    * ``stat_calibration:<model>`` — the fraction of sealed calibration
      windows whose reliability error stayed under threshold; empty
      while the calibration feed is unarmed (an empty window is zero
      burn, the engine's existing contract).

    The default objective tolerates 1 drifted window in 10 before
    burning (``ATE_TPU_STAT_DRIFT_BURN`` overrides) — drift detectors
    are screens, not proofs, and a single boundary-straddling window
    should page nobody."""
    out = []
    for m in models:
        out.append(
            SLO(name=f"stat_drift:{m}", kind="availability",
                objective=objective, metric=metric, windows_s=windows_s,
                scope_match=f"model={m}", good_match="status=ok",
                ignore_match="channel=calibration|status=sparse")
        )
        out.append(
            SLO(name=f"stat_calibration:{m}", kind="availability",
                objective=objective, metric=metric, windows_s=windows_s,
                scope_match=f"channel=calibration,model={m}",
                good_match="status=ok", ignore_match="status=sparse")
        )
    return tuple(out)


def router_slos(
    latency_threshold_s: float = 0.25,
    windows_s: tuple[float, ...] = DEFAULT_WINDOWS,
) -> tuple[SLO, ...]:
    """The fleet router's objectives (ISSUE 20) — the router tier is
    the front door, so its budget is spent on what CLIENTS experience:

    * ``router:availability`` — 99.9% of forwards reach a terminal
      ``ok``. Daemon-typed rejects (``outcome=reject`` — shed /
      bad_request / deadline, the daemon's 4xx convention) are the
      caller's or the *daemon's* budget, not the router's, so they are
      excluded outright; connection errors, protocol errors and
      capacity exhaustion (``unavailable``) DO spend it.
    * ``router:latency`` — 99% of forwards complete under the
      threshold, measured over the router-observed e2e bucket
      histogram (``router_request_seconds``).
    * ``router:failover`` — 99% of forwards land on the first ring
      owner (``path=direct``); a burning failover SLO means a backend
      is flapping even while availability still holds — the early
      warning the breaker state alone does not give.
    """
    return (
        SLO(name="router:availability", kind="availability",
            objective=0.999, metric="router_requests_total",
            windows_s=windows_s, good_match="outcome=ok",
            ignore_match="outcome=reject"),
        SLO(name="router:latency", kind="latency", objective=0.99,
            metric="router_request_seconds", windows_s=windows_s,
            threshold_s=latency_threshold_s),
        SLO(name="router:failover", kind="availability", objective=0.99,
            metric="router_request_path_total", windows_s=windows_s,
            good_match="path=direct"),
    )


def _pairs(spec: str) -> tuple[str, ...]:
    return tuple(p for p in spec.split(",") if p)


def _match(label_key: str, pairs: tuple[str, ...]) -> bool:
    """Whether every ``k=v`` pair appears in the canonical label key."""
    present = label_key.split(",")
    return all(p in present for p in pairs)


class SLOEngine:
    """Rolling-window burn-rate evaluation over registry snapshots.

    :meth:`tick` records the current cumulative (good, total) per SLO;
    :meth:`evaluate` ticks once more and differences the history, so a
    window's figures are always "what happened between two explicit
    clock readings" — injectable-clock deterministic. History is
    bounded by the longest declared window (plus slack), so a
    week-long daemon cannot grow it unbounded.
    """

    def __init__(
        self,
        slos: tuple[SLO, ...] | list[SLO] | None = None,
        registry: _registry.MetricsRegistry | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.slos = tuple(slos) if slos is not None else default_serving_slos()
        names = [s.name for s in self.slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self._registry = registry if registry is not None else _registry.REGISTRY
        self._clock = clock
        self._lock = threading.Lock()
        #: (tick_mono, {slo_name: (good, total)}), oldest first.
        self._history: collections.deque = collections.deque()
        longest = max(
            (w for s in self.slos for w in s.windows_s), default=60.0
        )
        self._retention_s = longest * 1.25 + 1.0

    # ── snapshot side ────────────────────────────────────────────────

    def _totals(self, slo: SLO) -> tuple[float, float]:
        """Current cumulative ``(good, total)`` for one SLO."""
        m = self._registry.family(slo.metric)
        if m is None:
            return 0.0, 0.0
        if slo.kind == "latency":
            if not isinstance(m, _registry.BucketHistogram):
                raise TypeError(
                    f"SLO {slo.name}: metric {slo.metric!r} is {m.kind}, "
                    "latency SLOs need a bucket_histogram"
                )
            good, total = m.good_total_le(slo.threshold_s)
            return float(good), float(total)
        samples = self._registry.peek(slo.metric) or {}
        scope = _pairs(slo.scope_match)
        ignore_alts = [
            _pairs(alt) for alt in slo.ignore_match.split("|") if alt
        ]
        good_pairs = scope + _pairs(slo.good_match)
        total = float(sum(
            v for k, v in samples.items()
            if _match(k, scope)
            and not any(_match(k, alt) for alt in ignore_alts)
        ))
        good = float(sum(
            v for k, v in samples.items()
            if _match(k, good_pairs)
            and not any(_match(k, alt) for alt in ignore_alts)
        ))
        return good, total

    def tick(self) -> float:
        """Record one snapshot; returns its clock reading. The daemon
        ticks after every dispatched batch (cheap: one dict copy per
        family under the registry lock) and the admin/stats/report
        paths tick implicitly via :meth:`evaluate`."""
        now = self._clock()
        totals = {slo.name: self._totals(slo) for slo in self.slos}
        with self._lock:
            self._history.append((now, totals))
            while self._history and (
                now - self._history[0][0] > self._retention_s
            ):
                self._history.popleft()
        return now

    # ── evaluation side ──────────────────────────────────────────────

    @staticmethod
    def _baseline(hist, now: float, window_s: float):
        """The snapshot a window differences against: the NEWEST tick
        at or before ``now - window_s``, or the oldest tick while the
        window is not yet filled (reported via ``actual_s``)."""
        base = hist[0]
        for t, totals in hist:
            if t <= now - window_s:
                base = (t, totals)
            else:
                break
        return base

    def evaluate(self) -> dict:
        """Tick, then render the full ``slo_report.json`` payload."""
        now = self.tick()
        with self._lock:
            hist = list(self._history)
        slos_out = []
        for slo in self.slos:
            cur_good, cur_total = hist[-1][1][slo.name]
            budget = 1.0 - slo.objective
            windows = []
            worst = 0.0
            for w in slo.windows_s:
                bt, btotals = self._baseline(hist, now, w)
                base_good, base_total = btotals[slo.name]
                d_good = cur_good - base_good
                d_total = cur_total - base_total
                err = (
                    max(0.0, 1.0 - d_good / d_total) if d_total > 0 else 0.0
                )
                burn = err / budget
                worst = max(worst, burn)
                windows.append({
                    "window_s": w,
                    "actual_s": round(now - bt, 6),
                    "good": d_good,
                    "total": d_total,
                    "error_rate": round(err, 6),
                    "burn_rate": round(burn, 4),
                })
            slos_out.append({
                "name": slo.name,
                "kind": slo.kind,
                "objective": slo.objective,
                "threshold_s": slo.threshold_s,
                "metric": slo.metric,
                "windows": windows,
                "worst_burn_rate": round(worst, 4),
                # burning = the budget is being spent faster than it
                # accrues in at least one window.
                "burning": worst > 1.0,
            })
        return {"schema_version": SLO_SCHEMA_VERSION, "slos": slos_out}

    def health(self) -> dict:
        """The compact form ``/healthz`` and the ``stats`` op embed:
        per-SLO worst burn rate + the overall burning flag."""
        report = self.evaluate()
        return {
            "burning": any(s["burning"] for s in report["slos"]),
            "slos": {
                s["name"]: {
                    "worst_burn_rate": s["worst_burn_rate"],
                    "burning": s["burning"],
                }
                for s in report["slos"]
            },
        }

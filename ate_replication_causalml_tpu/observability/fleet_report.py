"""Merged fleet observability artifacts (PR 20 tentpole).

Everything here is a PURE function of a ``RouterServer.dump_fleet``
output directory — the router's live dump and the offline
``scripts/fleet_report.py`` both call exactly
:func:`write_fleet_artifacts`, which is what makes the script's
recomputation bit-for-bit (the PR 7 discipline, fleet-wide). Three
artifacts land beside ``fleet_manifest.json``:

* ``fleet_trace.json`` — every process's trace (the router's
  ``router/trace.json`` plus each dumped daemon's
  ``daemon-<name>/trace.json``) re-based onto ONE wall-clock axis via
  each trace's ``otherData.wall_anchor_unix``, with distinct pids per
  process and ``fleet_req`` flow arrows stitching each
  ``router_request`` span to the daemon ``serving_request`` span that
  served the same request id — one Perfetto timeline for the whole
  fleet, kill and failover included.
* ``fleet_report.json`` — the request-level reconciliation: matched
  router↔daemon span pairs, orphans on either side (a routed request
  with no daemon-side span is a lost trace, zero of them is the
  acceptance number for a clean kill+failover episode), the
  per-backend distribution of the residual gap (router ``wait_s``
  minus daemon end-to-end — the wire + framing overhead between the
  tiers), and the router's manifest ok-counts reconciled against each
  daemon's own ``serving_requests_total``.
* ``fleet_stat_health.json`` — every daemon's statistical-health total
  sketches (``stathealth.state_dict`` — integer-count, associatively
  mergeable by construction) folded per model × channel into fleet
  distributions, plus fleet-level ``stat_drift:*`` /
  ``stat_calibration:*`` figures folded from the sealed-window
  statuses.

Reconciliation uses ``≤`` semantics for counter totals: the registry
is process-global, so in-process fleets (the tier-1 rig, the chaos
campaign's router) surface combined counters in every daemon's
``metrics.json`` — a router can never have MORE acknowledged forwards
than its daemons served, but the daemons may report more (their own
clients, shared registries). Jax-free and stdlib-only, like everything
the router imports.
"""

from __future__ import annotations

import json
import os

from ate_replication_causalml_tpu.observability.registry import (
    parse_label_key,
)
from ate_replication_causalml_tpu.observability.serving_report import (
    index_quantile,
)
from ate_replication_causalml_tpu.observability.sketch import (
    CalibrationSketch,
    FixedBinSketch,
)

__all__ = [
    "FLEET_REPORT_BASENAME",
    "FLEET_STAT_HEALTH_BASENAME",
    "FLEET_TRACE_BASENAME",
    "FLEET_REPORT_SCHEMA_VERSION",
    "build_fleet_report",
    "build_fleet_stat_health",
    "build_fleet_trace",
    "load_fleet_dump",
    "write_fleet_artifacts",
]

FLEET_TRACE_BASENAME = "fleet_trace.json"
FLEET_REPORT_BASENAME = "fleet_report.json"
FLEET_STAT_HEALTH_BASENAME = "fleet_stat_health.json"
FLEET_REPORT_SCHEMA_VERSION = 1

#: how many orphan request ids each orphan section lists verbatim (the
#: counts are always exact).
MAX_ORPHAN_IDS = 20


def _read_json(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def load_fleet_dump(outdir: str) -> dict:
    """Read everything the merged artifacts derive from. Raises
    ``ValueError`` when ``fleet_manifest.json`` is missing or unreadable
    (not a fleet dump); every OTHER input is optional — a daemon that
    never dumped, a disabled-tracing router — and simply absent from
    the outputs."""
    manifest = _read_json(os.path.join(outdir, "fleet_manifest.json"))
    if not isinstance(manifest, dict):
        raise ValueError(
            f"{outdir}: no readable fleet_manifest.json — not a fleet dump"
        )
    router_dir = str(manifest.get("router_dir") or "router")
    router_trace = _read_json(
        os.path.join(outdir, router_dir, "trace.json")
    )
    daemons: dict[str, dict] = {}
    backends = manifest.get("backends")
    backends = backends if isinstance(backends, dict) else {}
    for name in sorted(backends):
        entry = backends[name]
        if not (isinstance(entry, dict) and entry.get("dumped")
                and entry.get("dir")):
            continue
        ddir = os.path.join(outdir, str(entry["dir"]))
        daemons[name] = {
            "trace": _read_json(os.path.join(ddir, "trace.json")),
            "metrics": _read_json(os.path.join(ddir, "metrics.json")),
            "stat_health": _read_json(
                os.path.join(ddir, "stat_health.json")
            ),
        }
    return {
        "manifest": manifest,
        "router_trace": router_trace,
        "daemons": daemons,
    }


# ── fleet_trace.json — one wall-clock axis, flow-stitched ────────────


def _anchor(trace: dict | None) -> float | None:
    if not isinstance(trace, dict):
        return None
    a = (trace.get("otherData") or {}).get("wall_anchor_unix")
    return float(a) if isinstance(a, (int, float)) else None


def _spans(trace: dict | None, name: str) -> list[dict]:
    """Complete (ph X) spans named ``name`` carrying a request id."""
    if not isinstance(trace, dict):
        return []
    out = []
    for ev in trace.get("traceEvents") or []:
        if (isinstance(ev, dict) and ev.get("ph") == "X"
                and ev.get("name") == name
                and (ev.get("args") or {}).get("request_id")):
            out.append(ev)
    return out


def build_fleet_trace(dump: dict) -> dict:
    """Merge the per-process traces onto one wall-clock axis.

    Each process keeps its own monotonic-derived ``ts`` values,
    shifted by ``(wall_anchor_unix − min wall_anchor_unix) · 1e6`` —
    the anchors were stamped from the same wall clock, so after the
    shift "simultaneous" means simultaneous across processes to
    wall-clock sync precision. Pids are reassigned (router first, then
    daemons sorted) and each process's ``process_name`` metadata is
    rewritten to its fleet role so the Perfetto track groups read
    ``router`` / ``daemon-<name>``."""
    procs: list[tuple[str, dict]] = []
    if isinstance(dump.get("router_trace"), dict):
        procs.append(("router", dump["router_trace"]))
    for name in sorted(dump.get("daemons") or {}):
        trace = dump["daemons"][name].get("trace")
        if isinstance(trace, dict):
            procs.append((f"daemon-{name}", trace))
    anchors = {pname: _anchor(trace) for pname, trace in procs}
    known = [a for a in anchors.values() if a is not None]
    origin = min(known) if known else 0.0

    events: list[dict] = []
    processes: dict[str, dict] = {}
    pid_of: dict[str, int] = {}
    for pid, (pname, trace) in enumerate(procs, start=1):
        pid_of[pname] = pid
        anchor = anchors[pname]
        shift_us = 0.0 if anchor is None else (anchor - origin) * 1e6
        saw_process_name = False
        for ev in trace.get("traceEvents") or []:
            if not isinstance(ev, dict):
                continue
            ev2 = dict(ev)
            ev2["pid"] = pid
            if isinstance(ev2.get("ts"), (int, float)):
                ev2["ts"] = round(float(ev2["ts"]) + shift_us, 3)
            if ev2.get("ph") == "M" and ev2.get("name") == "process_name":
                ev2["args"] = {"name": pname}
                saw_process_name = True
            events.append(ev2)
        if not saw_process_name:
            events.append({"ph": "M", "pid": pid, "tid": 0,
                           "name": "process_name",
                           "args": {"name": pname}})
        processes[pname] = {
            "pid": pid,
            "wall_anchor_unix": anchor,
            "events": sum(1 for e in trace.get("traceEvents") or []
                          if isinstance(e, dict)),
        }

    # Flow arrows: router_request → serving_request on request id. One
    # s/f pair per router×daemon span match, each under its own flow
    # id so retried ids (failover that reached two daemons) stay
    # unambiguous.
    router_trace = dump.get("router_trace")
    router_shift = (
        0.0 if _anchor(router_trace) is None
        else (_anchor(router_trace) - origin) * 1e6
    )
    daemon_spans: dict[str, list[tuple[str, dict, float]]] = {}
    for pname, trace in procs:
        if pname == "router":
            continue
        shift_us = (
            0.0 if anchors[pname] is None
            else (anchors[pname] - origin) * 1e6
        )
        for ev in _spans(trace, "serving_request"):
            rid = str(ev["args"]["request_id"])
            daemon_spans.setdefault(rid, []).append((pname, ev, shift_us))
    for ev in _spans(router_trace, "router_request"):
        rid = str(ev["args"]["request_id"])
        for k, (pname, dev, shift_us) in enumerate(
            daemon_spans.get(rid, ())
        ):
            flow_id = f"fleet:{rid}" if k == 0 else f"fleet:{rid}/{k}"
            events.append({
                "ph": "s", "cat": "fleet_req", "id": flow_id,
                "name": "fleet_request", "pid": pid_of["router"],
                "tid": ev.get("tid", 0),
                "ts": round(float(ev.get("ts", 0.0)) + router_shift, 3),
            })
            events.append({
                "ph": "f", "bp": "e", "cat": "fleet_req", "id": flow_id,
                "name": "fleet_request", "pid": pid_of[pname],
                "tid": dev.get("tid", 0),
                "ts": round(float(dev.get("ts", 0.0)) + shift_us, 3),
            })

    return {
        "traceEvents": events,
        "otherData": {
            "trace_schema_version": 1,
            "kind": "fleet_trace",
            "clock": "wall-rebased",
            "time_unit": "us",
            "wall_anchor_unix": origin if known else None,
            "processes": processes,
        },
    }


# ── fleet_report.json — request reconciliation ───────────────────────


def _round9(v: float) -> float:
    return round(float(v), 9)


def _gap_stats(vals: list[float]) -> dict:
    s = sorted(vals)
    return {
        "count": len(s),
        "min_s": _round9(s[0]),
        "p50_s": _round9(index_quantile(s, 0.50)),
        "p99_s": _round9(index_quantile(s, 0.99)),
        "max_s": _round9(s[-1]),
    }


def _daemon_ok_count(metrics: dict | None) -> int | None:
    """``serving_requests_total{status=ok}`` summed over every other
    label, via the registry's ONE canonical label-key parser."""
    if not isinstance(metrics, dict):
        return None
    fam = (metrics.get("counters") or {}).get("serving_requests_total")
    if not isinstance(fam, dict):
        return 0
    total = 0
    for key, v in fam.items():
        if parse_label_key(str(key)).get("status") == "ok":
            total += int(v)
    return total


def build_fleet_report(dump: dict) -> dict:
    """Cross-process request reconciliation, pure from the dump."""
    manifest = dump["manifest"]
    daemons = dump.get("daemons") or {}
    router_spans = _spans(dump.get("router_trace"), "router_request")

    daemon_span_ids: dict[str, set[str]] = {}
    daemon_spans_by_rid: dict[str, list[tuple[str, dict]]] = {}
    for name in sorted(daemons):
        ids = set()
        for ev in _spans(daemons[name].get("trace"), "serving_request"):
            rid = str(ev["args"]["request_id"])
            ids.add(rid)
            daemon_spans_by_rid.setdefault(rid, []).append((name, ev))
        daemon_span_ids[name] = ids

    dumped = set(daemon_span_ids)
    matched = 0
    routed_to_undumped = 0
    orphan_router: list[str] = []
    matched_router_rids: set[str] = set()
    gaps: dict[str, list[float]] = {}
    for ev in router_spans:
        args = ev.get("args") or {}
        backend = str(args.get("backend", "-"))
        rid = str(args.get("request_id"))
        if backend == "-" or str(args.get("outcome")) not in (
            "ok", "reject", "error"
        ):
            continue  # never reached a daemon — nothing to match
        if backend not in dumped:
            routed_to_undumped += 1
            continue
        if rid in daemon_span_ids[backend]:
            matched += 1
            matched_router_rids.add(rid)
            wait_s = args.get("wait_s")
            dev = next(
                e for n, e in daemon_spans_by_rid[rid] if n == backend
            )
            if isinstance(wait_s, (int, float)):
                gaps.setdefault(backend, []).append(
                    float(wait_s) - float(dev.get("dur", 0.0)) / 1e6
                )
        else:
            orphan_router.append(rid)
    orphan_daemon = sorted(
        rid for rid in daemon_spans_by_rid
        if rid not in matched_router_rids
        and rid not in {
            str((e.get("args") or {}).get("request_id"))
            for e in router_spans
        }
    )

    # ── counter reconciliation (≤ semantics, see module docstring) ───
    router_req = (manifest.get("router") or {}).get("requests") or {}
    router_ok = {
        b: int((router_req.get(b) or {}).get("ok", 0))
        for b in sorted(daemons)
    }
    daemon_ok = {
        b: _daemon_ok_count(daemons[b].get("metrics"))
        for b in sorted(daemons)
    }
    router_ok_total = sum(router_ok.values())
    daemon_ok_known = [v for v in daemon_ok.values() if v is not None]
    daemon_ok_total = sum(daemon_ok_known) if daemon_ok_known else None
    trace_router_ok: dict[str, int] = {}
    for ev in router_spans:
        args = ev.get("args") or {}
        if str(args.get("outcome")) == "ok":
            b = str(args.get("backend", "-"))
            trace_router_ok[b] = trace_router_ok.get(b, 0) + 1
    manifest_ok_all = {
        b: int((router_req.get(b) or {}).get("ok", 0)) for b in router_req
    }
    # The trace is born-filtered per router; the counters are process-
    # cumulative — the trace can never show MORE oks than the manifest.
    trace_consistent = all(
        n <= manifest_ok_all.get(b, 0)
        for b, n in trace_router_ok.items()
    )
    consistent = (
        daemon_ok_total is None or router_ok_total <= daemon_ok_total
    ) and trace_consistent

    return {
        "schema_version": FLEET_REPORT_SCHEMA_VERSION,
        "kind": "fleet_report",
        "processes": {
            "router": {
                "present": dump.get("router_trace") is not None,
                "wall_anchor_unix": _anchor(dump.get("router_trace")),
                "spans": len(router_spans),
            },
            "daemons": {
                name: {
                    "wall_anchor_unix": _anchor(
                        daemons[name].get("trace")
                    ),
                    "spans": len(daemon_span_ids[name]),
                }
                for name in sorted(daemons)
            },
        },
        "requests": {
            "router_spans": len(router_spans),
            "daemon_spans": sum(
                len(v) for v in daemon_span_ids.values()
            ),
            "matched": matched,
            "routed_to_undumped": routed_to_undumped,
            "orphan_router": len(orphan_router),
            "orphan_router_ids": sorted(orphan_router)[:MAX_ORPHAN_IDS],
            "orphan_daemon": len(orphan_daemon),
            "orphan_daemon_ids": orphan_daemon[:MAX_ORPHAN_IDS],
        },
        "residual_gap": {
            b: _gap_stats(vals) for b, vals in sorted(gaps.items())
        },
        "reconciliation": {
            "router_ok": router_ok,
            "daemon_ok": daemon_ok,
            "router_ok_total": router_ok_total,
            "daemon_ok_total": daemon_ok_total,
            "trace_router_ok": {
                b: trace_router_ok[b] for b in sorted(trace_router_ok)
            },
            "consistent": bool(consistent),
        },
    }


# ── fleet_stat_health.json — folded sketches + fleet drift SLOs ──────


def _merge_sketches(dicts: list[dict], cls):
    merged = None
    for d in dicts:
        sk = cls.from_dict(d)
        merged = sk if merged is None else merged.merge(sk)
    return merged


def _fold_statuses(states: list[dict], model: str, channel: str) -> dict:
    """Sum sealed-window statuses for one model×channel across
    daemons — the fleet-level numerators/denominators the
    ``stat_drift:*`` figures burn from."""
    counts = {"ok": 0, "drift": 0, "sparse": 0, "miscal": 0}
    for st in states:
        ms = (st.get("models") or {}).get(model) or {}
        if channel == "calibration":
            series = (ms.get("calibration") or {}).get("series") or []
        else:
            series = (
                (ms.get("channels") or {}).get(channel) or {}
            ).get("series") or []
        for e in series:
            s = str(e.get("status"))
            if s in counts:
                counts[s] += 1
    return counts


def build_fleet_stat_health(dump: dict, objective: float = 0.9) -> dict:
    """Fold every dumped daemon's stat-health raw state into fleet
    distributions (exact integer merges — the sketches are built for
    this) and fleet ``stat_drift:*`` / ``stat_calibration:*`` figures.
    ``objective`` mirrors ``slo.stat_health_slos``'s default."""
    daemons = dump.get("daemons") or {}
    states: dict[str, dict] = {}
    for name in sorted(daemons):
        rep = daemons[name].get("stat_health")
        if isinstance(rep, dict) and isinstance(rep.get("state"), dict):
            states[name] = rep["state"]
    models_all = sorted({
        m for st in states.values() for m in (st.get("models") or {})
    })

    models_out: dict[str, dict] = {}
    slo_out: dict[str, dict] = {}
    for m in models_all:
        per_model = [st for st in states.values()
                     if m in (st.get("models") or {})]
        chans: dict[str, dict] = {}
        channel_names = sorted({
            ch for st in per_model
            for ch in (st["models"][m].get("channels") or {})
        })
        for ch in channel_names:
            totals = [
                st["models"][m]["channels"][ch]["total"]
                for st in per_model
                if ch in (st["models"][m].get("channels") or {})
            ]
            try:
                merged = _merge_sketches(totals, FixedBinSketch)
            except ValueError:
                chans[ch] = {"error": "incompatible_sketches"}
                continue
            folded = _fold_statuses(per_model, m, ch)
            chans[ch] = {
                "count": merged.total() if merged else 0,
                "underflow": merged.underflow if merged else 0,
                "overflow": merged.overflow if merged else 0,
                "nan": merged.nan if merged else 0,
                "p50": (None if merged is None
                        else _round9_or_none(merged.quantile(0.5))),
                "p90": (None if merged is None
                        else _round9_or_none(merged.quantile(0.9))),
                "windows_ok": folded["ok"],
                "windows_drift": folded["drift"],
                "windows_sparse": folded["sparse"],
            }
        cal_totals = [
            st["models"][m]["calibration"]["total"]
            for st in per_model
            if isinstance(st["models"][m].get("calibration"), dict)
        ]
        try:
            cal_merged = _merge_sketches(cal_totals, CalibrationSketch)
        except ValueError:
            cal_merged = None
        cal_folded = _fold_statuses(per_model, m, "calibration")
        cal = {
            "enabled": any(
                bool((st["models"][m].get("calibration") or {})
                     .get("enabled"))
                for st in per_model
            ),
            "count": cal_merged.total() if cal_merged else 0,
            "error": (None if cal_merged is None
                      else _round9_or_none(
                          cal_merged.calibration_error())),
            "windows_ok": cal_folded["ok"],
            "windows_miscal": cal_folded["miscal"],
            "windows_sparse": cal_folded["sparse"],
        }
        models_out[m] = {
            "rows": sum(
                int(st["models"][m].get("rows", 0)) for st in per_model
            ),
            "channels": chans,
            "calibration": cal,
        }

        # Fleet drift figures: sparse windows excluded outright (the
        # stat_health_slos contract — thin evidence neither spends nor
        # banks budget).
        drift_good = sum(
            chans[ch].get("windows_ok", 0) for ch in chans
        )
        drift_total = drift_good + sum(
            chans[ch].get("windows_drift", 0) for ch in chans
        )
        slo_out[f"stat_drift:{m}"] = _slo_figure(
            drift_good, drift_total, objective
        )
        cal_total = cal["windows_ok"] + cal["windows_miscal"]
        slo_out[f"stat_calibration:{m}"] = _slo_figure(
            cal["windows_ok"], cal_total, objective
        )

    return {
        "schema_version": FLEET_REPORT_SCHEMA_VERSION,
        "kind": "fleet_stat_health",
        "daemons": sorted(states),
        "models": models_out,
        "slo": slo_out,
    }


def _round9_or_none(v):
    return None if v is None else round(float(v), 9)


def _slo_figure(good: int, total: int, objective: float) -> dict:
    ratio = None if total == 0 else _round9(good / total)
    return {
        "objective": objective,
        "good": good,
        "total": total,
        "ratio": ratio,
        "burning": bool(total and good / total < objective),
    }


# ── THE one write recipe (live dump == offline script, byte for byte) ─


def write_fleet_artifacts(outdir: str) -> list[str]:
    """Build and atomically write the merged triple from the on-disk
    dump. Returns the paths written. The router's live ``dump_fleet``
    and the offline ``scripts/fleet_report.py`` both end here — same
    inputs, same pure builders, same compact-separator JSON recipe —
    so recomputing over a committed dump reproduces the committed
    artifacts bit-for-bit."""
    from ate_replication_causalml_tpu.observability.export import (
        atomic_write_json,
        atomic_write_text,
    )

    dump = load_fleet_dump(outdir)
    paths = []
    trace = build_fleet_trace(dump)
    tpath = os.path.join(outdir, FLEET_TRACE_BASENAME)
    # The per-process traces use the compact trace recipe; the merged
    # one matches (machine-read, compared byte-for-byte by tests).
    atomic_write_text(
        tpath, json.dumps(trace, separators=(",", ":")) + "\n"
    )
    paths.append(tpath)
    rpath = os.path.join(outdir, FLEET_REPORT_BASENAME)
    atomic_write_json(rpath, build_fleet_report(dump))
    paths.append(rpath)
    spath = os.path.join(outdir, FLEET_STAT_HEALTH_BASENAME)
    atomic_write_json(spath, build_fleet_stat_health(dump))
    paths.append(spath)
    return paths

"""reticulate bridge — the R-facing API surface.

The north star (BASELINE.json) preserves the reference's ``.Rmd``
entrypoint: an R session loads this module through ``reticulate`` and
calls functions with the *reference's* signatures
(``f(dataset, treatment_var, outcome_var, ...)`` returning a one-row
``data.frame(Method, ATE, lower_ci, upper_ci)`` — SURVEY.md §1), while
every FLOP executes on the TPU backend.

Marshalling contract (kept reticulate-trivial on purpose):

* ``dataset`` arrives as a named list / dict of numeric column vectors
  (R side: ``as.list(df)``). Everything that is neither the treatment
  nor the outcome column is a covariate, in dict order — mirroring the
  notebook's ``df_mod`` whose columns are exactly [covariates, W, Y].
  An explicit ``covariates=`` list overrides that default.
* Results return as plain dicts of scalars (reticulate → one-row
  data.frame). NaN CIs (the no-SE LASSO estimators,
  ``ate_functions.R:107, 129``) pass through as NA.

The R wrappers live in ``r/ate_functions_tpu.R``; the notebook-
equivalent driver is ``r/ate_replication_tpu.Rmd``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.data.schema import DatasetSchema
from ate_replication_causalml_tpu.utils.compile_cache import enable_persistent_cache

# The reticulate session imports this module once (tpu_init); fresh R
# sessions would otherwise recompile the forest executables from
# scratch through the remote compile service.
enable_persistent_cache()

from ate_replication_causalml_tpu.estimators import (
    EstimatorResult,
)
from ate_replication_causalml_tpu import estimators as E


def frame_from_columns(
    dataset,
    treatment_var: str = "W",
    outcome_var: str = "Y",
    covariates=None,
    dtype=jnp.float32,
) -> CausalFrame:
    """Named columns → :class:`CausalFrame` (the bridge's only ingest)."""
    cols = {k: np.asarray(v, dtype=np.float64).ravel() for k, v in dict(dataset).items()}
    if treatment_var not in cols or outcome_var not in cols:
        raise ValueError(
            f"dataset must contain treatment {treatment_var!r} and outcome {outcome_var!r}; "
            f"has {sorted(cols)}"
        )
    if covariates is None:
        covariates = [k for k in cols if k not in (treatment_var, outcome_var)]
    else:
        covariates = [str(c) for c in covariates]
        missing = [c for c in covariates if c not in cols]
        if missing:
            raise ValueError(f"covariates not in dataset: {missing}")
    x = np.stack([cols[c] for c in covariates], axis=1) if covariates else np.zeros(
        (len(cols[treatment_var]), 0)
    )
    schema = DatasetSchema(
        continuous=tuple(covariates), binary=(),
        outcome=outcome_var, treatment=treatment_var,
    )
    return CausalFrame(
        x=jnp.asarray(x, dtype),
        w=jnp.asarray(cols[treatment_var], dtype),
        y=jnp.asarray(cols[outcome_var], dtype),
        schema=schema,
    )


def _row(res: EstimatorResult) -> dict:
    out = {
        "Method": res.method,
        "ATE": float(res.ate),
        "lower_ci": float(res.lower_ci),
        "upper_ci": float(res.upper_ci),
    }
    return out


# --- the reference's public API (ate_functions.R), TPU-backed ----------

def naive_ate(dataset, treatment_var="W", outcome_var="Y", method="naive"):
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    return _row(E.naive_ate(frame, method=method))


def ate_condmean_ols(dataset, treatment_var="W", outcome_var="Y"):
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    return _row(E.ate_condmean_ols(frame))


def prop_score_weight(dataset, p, treatment_var="W", outcome_var="Y",
                      covariates=None, method="Propensity_Weighting"):
    frame = frame_from_columns(dataset, treatment_var, outcome_var, covariates)
    return _row(E.prop_score_weight(frame, np.asarray(p, np.float64), method=method))


def prop_score_ols(dataset, p, treatment_var="W", outcome_var="Y"):
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    return _row(E.prop_score_ols(frame, np.asarray(p, np.float64)))


def logistic_propensity(dataset, treatment_var="W", outcome_var="Y"):
    """The notebook's inline ``glm(W ~ ., binomial)`` propensity
    (``ate_replication.Rmd:164-168``) — returns the fitted vector."""
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    return np.asarray(E.logistic_propensity(frame.x, frame.w), np.float64)


def ate_condmean_lasso(dataset, treatment_var="W", outcome_var="Y", covariates=None):
    frame = frame_from_columns(dataset, treatment_var, outcome_var, covariates)
    return _row(E.ate_condmean_lasso(frame))


def ate_lasso(dataset, treatment_var="W", outcome_var="Y", covariates=None):
    frame = frame_from_columns(dataset, treatment_var, outcome_var, covariates)
    return _row(E.ate_lasso(frame))


def prop_score_lasso(dataset, treatment_var="W", outcome_var="Y", covariates=None):
    """Returns the LASSO-logit propensity vector, like the reference
    (``ate_functions.R:133-146`` returns predictions, not a row)."""
    frame = frame_from_columns(dataset, treatment_var, outcome_var, covariates)
    return np.asarray(E.prop_score_lasso(frame), np.float64)


def doubly_robust(dataset, treatment_var="W", outcome_var="Y", num_trees=100,
                  bootstrap_se=False, seed=12325, compat="r"):
    """``compat="r"`` (default) reproduces the reference's published
    sign-quirked AIPW combination (``ate_functions.R:183`` adds the
    control augmentation); ``"fixed"`` is textbook doubly-robust AIPW
    — see ``estimators.aipw.aipw_tau``."""
    from ate_replication_causalml_tpu.models.forest import rf_oob_propensity

    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    res = E.doubly_robust(
        frame,
        propensity_fn=lambda f: rf_oob_propensity(
            f, jax.random.key(int(seed)), n_trees=int(num_trees)
        ),
        bootstrap_se=bool(bootstrap_se),
        key=jax.random.key(int(seed) + 1),
        compat=compat,
    )
    return _row(res)


def doubly_robust_glm(dataset, treatment_var="W", outcome_var="Y",
                      bootstrap_se=False, seed=0, compat="r"):
    """``compat``: see :func:`doubly_robust`."""
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    res = E.doubly_robust_glm(
        frame, bootstrap_se=bool(bootstrap_se), key=jax.random.key(int(seed)),
        compat=compat,
    )
    return _row(res)


def belloni(dataset, treatment_var="W", outcome_var="Y", covariates=None,
            compat="r"):
    frame = frame_from_columns(dataset, treatment_var, outcome_var, covariates)
    return _row(E.belloni(frame, compat=compat))


def double_ml(dataset, treatment_var="W", outcome_var="Y", num_trees=100, seed=123,
              se_mode="r", crossfit="r"):
    """``se_mode="r"`` reproduces the reference's averaged-SE quirk
    (``ate_functions.R:383``); ``"pooled"`` treats the folds as
    independent. ``crossfit="r"`` reproduces its partial cross-fitting
    (predict-on-full); ``"full"`` is textbook out-of-fold DML — see
    ``estimators.dml.double_ml``."""
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    return _row(E.double_ml(
        frame, n_trees=int(num_trees), key=jax.random.key(int(seed)),
        se_mode=se_mode, crossfit=crossfit,
    ))


def residual_balance_ATE(dataset, treatment_var="W", outcome_var="Y",
                         optimizer="admm", seed=0):
    # The reference's `optimizer=` selects quadprog vs pogs; both map to
    # the same graph-form ADMM solver here (SURVEY.md §2.3).
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    return _row(E.residual_balance_ate(frame, key=jax.random.key(int(seed))))


def causal_forest(dataset, treatment_var="W", outcome_var="Y", num_trees=2000,
                  seed=12345, variance_compat="unbiased"):
    """The notebook's grf block (``ate_replication.Rmd:249-272``):
    returns the AIPW result row plus the deliberately 'incorrect'
    mean-CATE ATE/SE demo. ``variance_compat="grf"`` reproduces grf's
    num_groups between-group df (default: unbiased gn−1)."""
    frame = frame_from_columns(dataset, treatment_var, outcome_var)
    rep = E.causal_forest_report(frame, key=jax.random.key(int(seed)),
                                 n_trees=int(num_trees),
                                 variance_compat=variance_compat)
    out = _row(rep.result)
    out["incorrect_ate"] = float(rep.incorrect_ate)
    out["incorrect_se"] = float(rep.incorrect_se)
    return out


def run_notebook_sweep(n_obs=50_000, seed=1991, outdir=None, quick=False,
                       overrides=None):
    """One-call driver for the R notebook: the full estimator sweep on
    the synthetic GGL panel (SweepConfig defaults mirror the notebook's
    call sites). Returns the rows as a list of dicts for rbind.

    ``overrides``: optional dict of SweepConfig field overrides (e.g.
    ``list(dr_trees = 500L)`` from R) applied last.
    """
    import dataclasses as _dc

    from ate_replication_causalml_tpu.data.pipeline import PrepConfig
    from ate_replication_causalml_tpu.pipeline import SweepConfig, run_sweep

    cfg = SweepConfig(prep=PrepConfig(n_obs=int(n_obs), seed=int(seed)))
    if quick:
        # quick() shrinks tree counts AND the synthetic pool; restore a
        # pool large enough that the caller's n_obs is actually sampled.
        q = cfg.quick()
        cfg = _dc.replace(
            q,
            prep=PrepConfig(n_obs=int(n_obs), seed=int(seed)),
            synthetic_pool=max(q.synthetic_pool, 3 * int(n_obs)),
        )
    if overrides:
        # Coerce at the boundary like every other entry point here: R
        # numerics arrive as Python floats (500, not 500L), and the
        # int-typed SweepConfig fields must stay ints.
        import typing

        hints = typing.get_type_hints(SweepConfig)
        coerced = {}
        for k, v in dict(overrides).items():
            if k not in hints:
                raise ValueError(
                    f"unknown SweepConfig override {k!r}; valid: {sorted(hints)}"
                )
            coerced[k] = int(v) if hints[k] is int else v
        cfg = _dc.replace(cfg, **coerced)
    report = run_sweep(cfg, outdir=outdir, plots=outdir is not None,
                       log=lambda s: None)
    rows = [_row(report.oracle)] + [_row(r) for r in report.results.rows]
    return rows

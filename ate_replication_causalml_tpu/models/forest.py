"""Random-forest classifier engine — TPU-native replacement for the
Breiman–Cutler Fortran CART forest behind R's ``randomForest``.

The reference uses ``randomForest`` for the AIPW propensity (OOB votes,
``ate_functions.R:169-174``) and both DML nuisances
(``ate_functions.R:340-349``). Those forests are *nuisance models* and
are not even seeded in the reference (the ``seed=`` arg is silently
swallowed, SURVEY.md §2.1 #8/#12), so the contract is statistical
fidelity — bootstrap-per-tree, per-node feature subsampling
(mtry = floor(sqrt(p))), Gini split search, OOB vote probabilities —
not bit parity.

TPU-first design (nothing like the Fortran recursion):

  * features are quantile-binned once into uint8 codes; a split is
    "bin > t", so split search is a histogram problem;
  * trees grow **level-wise** to a fixed depth with node masking —
    static shapes, no recursion, XLA-friendly;
  * per-level histograms are computed as **MXU matmuls**:
    ``hist[node, (feat,bin)] = onehot_nodes^T @ onehot_bins`` with the
    per-tree bootstrap counts folded into the node one-hot. The
    feature/bin one-hot is tree-independent and shared; only the tiny
    (n, nodes) node one-hot is per-tree;
  * trees are embarrassingly parallel: ``vmap`` over a tree chunk, and
    the chunk axis can be ``shard_map``'ed over the mesh's tree axis
    (SURVEY.md §2.4: trees are the expert-parallel analogue);
  * bootstrap counts default to Poisson(1) (same large-n argument as
    the bootstrap engine, ops/bootstrap.py) with an exact multinomial
    option; OOB rows are ``count == 0`` either way.
"""

from __future__ import annotations

import dataclasses
import functools
import weakref
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.ops.bootstrap import _poisson1_counts
from ate_replication_causalml_tpu.ops.hist_pallas import (
    bin_histogram,
    mode_for_width,
    node_sums,
    resolve_hist_backend,
    resolve_hist_mode_packed,
)
from ate_replication_causalml_tpu.ops.linalg import _PREC
from ate_replication_causalml_tpu.ops.pack import pack_codes as _pack_codes
from ate_replication_causalml_tpu.ops.pack import (
    packable as _codes_packable,
)
from ate_replication_causalml_tpu.ops.pack import (
    resolve_predict_pack,
)
from ate_replication_causalml_tpu.ops.tree_pallas import (
    codes_transposed,
    route_bits,
    table_lookup,
)
from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.parallel.mesh import shard_map as _shard_map
from ate_replication_causalml_tpu.parallel.retry import require_all, run_shards


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Forest:
    """A fitted level-wise forest.

    ``split_feat``/``split_bin`` index internal nodes per level as
    [0, 2^level) offsets (children of node k at level l are 2k/2k+1 at
    level l+1). A row goes RIGHT when its bin code satisfies
    ``bin > split_bin``. Frozen nodes (pure/empty/no valid split) store
    ``split_feat=0, split_bin=n_bins-1`` — every row routes LEFT, which
    is how a leaf is represented in a fixed-depth tree. ``leaf_value``
    is the bootstrap-weighted P(y=1) in the depth-D leaf; empty leaves
    fall back to the tree's overall bootstrap-weighted rate (they are
    never reached by training rows and only matter for unseen rows).
    """

    split_feat: jax.Array   # (T, D, max_nodes) int32 (frozen nodes: 0)
    split_bin: jax.Array    # (T, D, max_nodes) int32
    leaf_value: jax.Array   # (T, 2^D) float32
    counts: jax.Array       # (T, n) bootstrap counts of the training rows
    bin_edges: jax.Array = dataclasses.field(metadata=dict(static=False), default=None)
    # Training-row leaf values, recorded during growth: the grower
    # already routed every training row, so OOB predictions on the
    # training matrix (the only OOB there is) need no re-routing pass.
    # Costs a second (T, n) array while the forest is alive — for a
    # long-lived forest whose OOB aggregate has been consumed, drop it
    # with ``dataclasses.replace(forest, train_leaf=None)`` (predictions
    # fall back to re-routing).
    train_leaf: jax.Array = dataclasses.field(metadata=dict(static=False), default=None)
    # Order-sensitive fingerprint of the training codes, recorded at fit
    # time so ``predict_forest(oob=True)`` can detect a same-row-count
    # matrix that is NOT the training matrix (permuted / re-standardized)
    # instead of silently returning training-time predictions.
    train_fp: jax.Array = dataclasses.field(metadata=dict(static=False), default=None)

    @property
    def n_trees(self) -> int:
        return self.split_feat.shape[0]

    @property
    def depth(self) -> int:
        return self.split_feat.shape[1]


@jax.jit
def codes_fingerprint(codes: jax.Array) -> jax.Array:
    """Cheap order-sensitive int32 fingerprint of a bin-code matrix:
    Σ codes[i,j]·(31·i + j + 1) with int32 wraparound. Row permutations
    and any code change move it (unlike a plain sum)."""
    n, p = codes.shape
    mix = (
        31 * jnp.arange(n, dtype=jnp.int32)[:, None]
        + jnp.arange(p, dtype=jnp.int32)[None, :]
        + 1
    )
    return jnp.sum(codes * mix, dtype=jnp.int32)


def route_rows(node_oh, best_feat, best_bin, codes_f, node_of_row):
    """Route rows one level down via the shared one-hot matmul: per-node
    (bin threshold, feature one-hot) table broadcast by ``node_oh``,
    then the row's split-feature code as a (rows, p)·(rows, p) dot — no
    per-row gathers (they serialize on TPU and dominated tree
    wall-clock before this formulation).

    On TPU the broadcast matmul runs in bf16 with f32 accumulation:
    every operand is a 0/1 one-hot or an integer bin threshold < 256,
    all exactly representable in bf16's 8 mantissa bits, and each output
    element has a single nonzero product — so the selection is EXACT
    (verified: bit-identical forests and goldens vs the f32 path) while
    the dominant deep-level (rows, nodes) operand halves in HBM (~9%
    per-tree win at 1M rows). On CPU (the test backend) bf16 matmuls
    are software-emulated and ~4× slower, so f32 is used there — same
    numbers either way. Callers enforce n_bins ≤ 256.

    Args:
      node_oh: (rows, M) f32 one-hot of each row's current node.
      best_feat/best_bin: (M,) int32 split table for this level.
      codes_f: (rows, p) f32 bin codes.
      node_of_row: (rows,) int32 current node ids.

    Returns: (rows,) int32 node ids one level down.
    """
    p = codes_f.shape[1]
    # Unlike quantile_bins' path gate, a stale backend baked into a
    # cached trace here costs only bandwidth, never bits: the bf16 and
    # f32 routing matmuls are exact for these operands (see docstring).
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32  # graftlint: disable=JGL001
    route_tab = jnp.concatenate(
        [
            best_bin.astype(dt)[:, None],
            jax.nn.one_hot(best_feat, p, dtype=dt),
        ],
        axis=1,
    )  # (M, 1 + p)
    row_route = jnp.matmul(
        node_oh.astype(dt), route_tab,
        preferred_element_type=jnp.float32,
    )
    code_at_feat = jnp.sum(codes_f * row_route[:, 1:], axis=1)
    return node_of_row * 2 + (code_at_feat > row_route[:, 0]).astype(jnp.int32)


# Row-block size for gather-free routing without the full (rows, M)
# one-hot in HBM (route_rows_blocked): 128k rows × 256 nodes in bf16 is
# 64 MB per tree per block — an 8-tree vmapped chunk keeps ~512 MB of
# transient block one-hots, and 1M rows need only 8 lax.map iterations.
_ROUTE_BLOCK = 131072


def route_rows_packed(node_oh, best_feat, best_bin, packed_f, node_of_row):
    """:func:`route_rows` over PACKED codes (ISSUE 12, ``ops/pack.py``):
    ``packed_f`` is (rows, ceil(p/3)) f32 words carrying three 7-bit
    codes each, and the route table selects the packed WORD (one-hot
    over ``best_feat // 3``) plus a slot index (``best_feat % 3``)
    instead of the p-wide feature one-hot — the permutation contraction
    shrinks 3×. The extracted code is the SAME f32 integer the unpacked
    path reads (divide-by-power-of-two / floor / subtract on integers
    below 2^24 are exact), so the routing decision — and with it every
    downstream byte — is bit-identical; asserted against
    :func:`route_rows` in tests/test_predict_pack.py.

    Runs in f32 even on TPU: a packed word does not fit bf16's mantissa
    (see ops/pack.py) — packing trades route_rows' bf16 bandwidth
    halving for the 3× MAC cut, the A/B ``bench.py --predict-ab``
    records.
    """
    from ate_replication_causalml_tpu.ops.pack import PACK_SLOTS, extract_slot

    p3 = packed_f.shape[1]
    route_tab = jnp.concatenate(
        [
            best_bin.astype(jnp.float32)[:, None],
            (best_feat % PACK_SLOTS).astype(jnp.float32)[:, None],
            jax.nn.one_hot(best_feat // PACK_SLOTS, p3, dtype=jnp.float32),
        ],
        axis=1,
    )  # (M, 2 + p3)
    row_route = jnp.matmul(node_oh, route_tab, precision=_PREC)
    word = jnp.sum(packed_f * row_route[:, 2:], axis=1)
    code = extract_slot(word, row_route[:, 1])
    return node_of_row * 2 + (code > row_route[:, 0]).astype(jnp.int32)


def route_rows_blocked(
    node_of_row, best_feat, best_bin, codes, row_block: int = _ROUTE_BLOCK
):
    """:func:`route_rows` from raw node ids, with rows processed in
    ``lax.map`` blocks so the (rows, M) routing one-hot never
    materializes in HBM — the operand that capped million-row tree
    chunks at 2 vmapped trees (auto_tree_chunk's budget) and with it the
    tree-batched histogram kernel's amortization.

    EXACT: routing is integer compares (one-hot selection of integer bin
    codes/thresholds), so blocking cannot change a single route —
    asserted against the unblocked path in tests/test_forest.py.

    Args:
      node_of_row: (rows,) int32 current node ids.
      best_feat/best_bin: (M,) int32 split table for this level.
      codes: (rows, p) int bin codes (any integer dtype; cast per block).
    """
    m = best_feat.shape[0]
    n = node_of_row.shape[0]
    # Build the block one-hot directly in the routing matmul's dtype
    # (bf16 on TPU — exact for 0/1; see route_rows) instead of f32 +
    # cast: halves the largest transient.
    # Unlike quantile_bins' path gate, a stale backend baked into a
    # cached trace here costs only bandwidth, never bits: the bf16 and
    # f32 routing matmuls are exact for these operands (see docstring).
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32  # graftlint: disable=JGL001

    def blk(args):
        ids, cd = args
        oh = jax.nn.one_hot(ids, m, dtype=dt)
        return route_rows(oh, best_feat, best_bin, cd.astype(jnp.float32), ids)

    if n <= row_block:
        return blk((node_of_row, codes))
    n_blocks = -(-n // row_block)
    n_pad = n_blocks * row_block
    ids_b = jnp.pad(node_of_row, (0, n_pad - n)).reshape(n_blocks, row_block)
    codes_b = jnp.pad(codes, ((0, n_pad - n), (0, 0))).reshape(
        n_blocks, row_block, -1
    )
    out = lax.map(blk, (ids_b, codes_b))
    return out.reshape(n_pad)[:n]


def exact_subsample_mask(key: jax.Array, n: int, s: int) -> jax.Array:
    """Uniform s-of-n subsample as a boolean mask, without a
    permutation.

    ``jax.random.permutation(n)[:s]`` pays a keys+payload sort AND a
    500k-row scatter to build the mask — a round-4 device trace put the
    pair at ~3.5 ms/tree of the causal grow (the little-bag groups draw
    one half-sample each, grf's subsample-without-replacement). This
    draws one u32 per row and takes the rows below the s-th order
    statistic (ONE single-array sort), with ties at the threshold
    broken in index order so the mask has EXACTLY s rows always.

    Distribution: uniform over s-subsets up to the tie-break — a tie
    requires a u32 collision at the threshold (~n/2^32 per row, ~10^-4
    expected tied rows at n=10^6), at which point lower indices win;
    the bias is orders of magnitude below Monte-Carlo noise. Matches
    sampling WITHOUT replacement semantics (grf's subsample), not R's
    ``sample()`` stream — the causal forest is statistically-, not
    bit-, matched to grf (its C++ RNG is different anyway).
    """
    if not 1 <= s <= n:  # s is static
        raise ValueError(f"need 1 <= s <= n, got s={s}, n={n}")
    bits = jax.random.bits(key, (n,), jnp.uint32)
    # The s-th smallest u32 by 32-round binary search on the VALUE
    # domain: each round is one fused O(n) count — ~10× cheaper than
    # the u32 sort it replaces (XLA's stable jnp.sort pays a keys+iota
    # payload sort; a round-5 device trace put it at ~3 ms per group =
    # ~3 s of the 1M fit). Invariant: count(bits ≤ lo) ≤ s−1 and
    # count(bits ≤ hi) ≥ s, so hi converges to the exact s-th order
    # statistic — the same ``kth`` the sort produced, hence a
    # bit-identical mask (asserted against the sort in tests).
    def step(_, bounds):
        lo, hi = bounds  # lo exclusive, hi inclusive candidate
        mid = lo + (hi - lo) // jnp.uint32(2)  # lo < mid+... mid in [lo, hi)
        cnt = jnp.sum((bits <= mid).astype(jnp.int32))
        take_hi = cnt >= s  # s-th smallest is ≤ mid
        return (jnp.where(take_hi, lo, mid), jnp.where(take_hi, mid, hi))

    # Derive the initial bounds FROM the draws (values 0 and 2^32−1):
    # literal constants are cross-device-invariant under shard_map's
    # varying-manifest check, and a fori carry must keep its manifest —
    # inheriting bits' manifest keeps the same loop valid inside the
    # tree-sharded grow and on a single device.
    lo0 = bits[0] & jnp.uint32(0)
    hi0 = bits[0] | jnp.uint32(0xFFFFFFFF)
    # Handle the lo boundary exactly: the search treats lo as exclusive,
    # so start from "−1" via a first explicit check of 0.
    cnt0 = jnp.sum((bits == 0).astype(jnp.int32))
    # After 32 halvings of a 2^32 range, hi − lo == 1 with
    # count(≤lo) < s ≤ count(≤hi) — unless kth == 0, handled below.
    lo, hi = jax.lax.fori_loop(0, 32, step, (lo0, hi0))
    kth = jnp.where(cnt0 >= s, jnp.uint32(0), hi)
    below = bits < kth
    short = s - jnp.sum(below.astype(jnp.int32))
    ties = bits == kth
    take_tie = ties & (jnp.cumsum(ties.astype(jnp.int32)) <= short)
    return below | take_tie


@functools.lru_cache(maxsize=None)
def bitrev_perm(level: int) -> tuple[int, ...]:
    """Bit-reversal permutation of ``2^level`` node ids (an involution).

    The streaming growers index per-level histograms by BIT-REVERSED
    node ids: a node's rev id has its child-side bit as the MSB, so the
    left children of every level occupy rev ids [0, m/2) and the
    full-level histogram assembles as one CONTIGUOUS concatenation of
    [left, parent−left] — the interleaved (2k/2k+1) assembly the dense
    path uses was a strided transposed-layout DMA that a device trace
    measured at ~12 ms/tree at the million-row scale, half the entire
    grow. Interleaved ids still exist per row (for the stored Forest
    layout and leaf indexing); this permutation converts the per-level
    (m,)-sized tables and mtry draws between the two numberings."""
    m = 1 << level
    out = [0] * m
    for r in range(m):
        v = 0
        for i in range(level):
            v |= ((r >> i) & 1) << (level - 1 - i)
        out[r] = v
    return tuple(out)


# Uniform-width floors for the per-level kernel instantiations inside
# the streaming level loop (round 5, VERDICT r4 #7). Every distinct
# (tree-batch, node-count) pair is a separate Mosaic kernel compile, and
# on the remote-compile TPU toolchain ONE batched histogram kernel
# instantiation costs 6-13 s to compile — a depth-9 grow used to pay it
# at every level width 1,1,2,4,8,16,32,64,128. Padding the shallow
# levels to one floor width collapses those to a single instantiation
# per engine ({16,32,64,128} total for depth 9) with BIT-identical
# results: each histogram column / routing margin is an independent
# contraction, node ids never reach the padded columns, and the loop
# slices the output back to the live width.
#
# The floors are PER-ENGINE because the steady-state cost scales with
# K·(floor − native_M) marginal MXU work: the K=2 classifier engine
# measured −15 s cold / +0.5 s steady at the 1M flagship (a clear win),
# but the K=5 causal engine measured +8 s steady with NO cold gain (its
# deep shared-weights instantiations dominate that compile) — so the
# causal grower passes floor 1 (no padding) and the classifier 16/32.
_HIST_M_FLOOR = 16
_ROUTE_M_FLOOR = 32


def streaming_hist_widths(depth: int, hist_floor: int = 1) -> tuple[int, ...]:
    """The kernel widths (padded node counts) the streaming level loop
    actually requests, one per level: level 0 runs at the floor; level
    l ≥ 1 computes LEFT children only (sibling subtraction), so its
    kernel covers max(2^(l−1), floor) nodes. The per-width kernel-mode
    decision (ISSUE 10) and the dispatch meter both key on these."""
    if depth < 1:
        return ()
    return tuple(
        max(1, hist_floor) if level == 0
        else max(1 << (level - 1), hist_floor)
        for level in range(depth)
    )


def hist_partition_active(hist_mode: str, depth: int, hist_floor: int,
                          kernel_weights: int, p: int, n_bins: int) -> bool:
    """Whether ANY level of a streaming grow resolves to the partition
    kernel under ``hist_mode`` — the chunk planners use this to charge
    the partition kernel's fixed VMEM transients
    (ops/hist_pallas.py::batched_tree_cap(partition=True))."""
    return any(
        mode_for_width(
            hist_mode, w, kernel_weights, p, n_bins
        ).startswith("partition")
        for w in streaming_hist_widths(depth, hist_floor)
    )


def _meter_hist_dispatches(engine: str, hist_backend: str, hist_mode: str,
                           depth: int, hist_floor: int, n_chunks: int,
                           kernel_weights: int, p: int, n_bins: int) -> None:
    """Host-side meter of the streaming growers' histogram-kernel
    calls: ``hist_kernel_dispatch_total{mode, engine}`` counts one per
    (grow level × vmapped chunk) — each level of each chunk collapses
    to exactly ONE tree-batched kernel call through the custom_vmap
    rule. Called from INSIDE each host dispatch function (the kernel
    itself runs inside a trace where counting is impossible), so a
    retried dispatch counts its re-issued kernel calls and an aborted
    fit counts only the dispatches that actually ran — the counter
    reflects calls ISSUED, not a plan. Pre-created at zero by
    install_jax_monitoring so every instrumented run carries the
    family."""
    if not (hist_backend.startswith("pallas") and n_chunks > 0):
        return
    per_mode: dict[str, int] = {}
    for w in streaming_hist_widths(depth, hist_floor):
        m = mode_for_width(hist_mode, w, kernel_weights, p, n_bins)
        per_mode[m] = per_mode.get(m, 0) + 1
    for m, levels in per_mode.items():
        obs.counter(
            "hist_kernel_dispatch_total",
            "streaming histogram kernel calls by kernel mode and engine",
        ).inc(levels * n_chunks, mode=m, engine=engine)


def streaming_level_loop(codes, depth, n_bins, hist_fn, tables_fn,
                         route_fn=None, hist_floor=1, route_floor=1):
    """The ONE bit-reversed level loop shared by both streaming growers
    (classifier/regression and ρ-decomposed causal) — the rev-id
    bookkeeping is identical and must stay so, hence one site.

    Per level: full-level histograms assemble as a CONTIGUOUS
    ``concat([left, parent − left])`` in rev node order (sibling
    subtraction without the strided interleave DMA — see
    :func:`bitrev_perm`); splits are chosen by ``tables_fn`` (rev
    order), rows route row-blocked with rev tables, and both id streams
    advance: interleaved ``node_int`` (the stored 2k/2k+1 layout) and
    ``node_rev`` (b·2^level + rev — the new side bit becomes the MSB).

    Args:
      codes: (n, p) int32 bin codes.
      hist_fn: (ids, m) → (K, m, p, n_bins) histogram of rows at the
        given rev node ids (−1 contributes nothing).
      tables_fn: (hist_full, level, perm) → (bf_rev, bb_rev) split
        tables in rev order (``perm`` = that level's bit reversal, for
        re-mapping per-node randomness).
      route_fn: optional (ids, bf_rev, bb_rev) → (n,) int32 route bits
        (1 = right). When given (the device growers pass the Pallas
        route kernel — ops/tree_pallas.py), it replaces the blocked
        one-hot-matmul routing; both are exact integer selections and
        must agree bit-for-bit (asserted in tests/test_tree_pallas.py).

    Returns: (feats (depth, 2^(depth−1)), bins (same), node_int (n,))
    with split tables converted to the stored interleaved layout.
    """
    n = codes.shape[0]
    max_nodes = 1 << (depth - 1)
    node_int = jnp.zeros(n, jnp.int32)
    node_rev = jnp.zeros(n, jnp.int32)
    prev = None
    feats_l, bins_l = [], []
    for level in range(depth):
        m = 1 << level
        if prev is None:
            hist = hist_fn(node_rev, hist_floor)[:, :1]
        else:
            # Left children's rev id == their parent's rev id.
            left_id = jnp.where(node_int % 2 == 0, node_rev, -1)
            hist_left = hist_fn(
                left_id, max(m // 2, hist_floor)
            )[:, : m // 2]
            hist = jnp.concatenate([hist_left, prev - hist_left], axis=1)
        prev = hist
        perm = bitrev_perm(level)
        bf_rev, bb_rev = tables_fn(hist, level, perm)
        if route_fn is None:
            routed = route_rows_blocked(node_rev, bf_rev, bb_rev, codes)
            bit = routed - 2 * node_rev
        else:
            # Zero-padded tables (live node ids never select a padded
            # row, and a zero row keeps every computed margin finite).
            pad = max(0, route_floor - m)
            bit = route_fn(
                node_rev, jnp.pad(bf_rev, (0, pad)), jnp.pad(bb_rev, (0, pad))
            )
        node_int = node_int * 2 + bit
        node_rev = node_rev + bit * m
        perm_a = jnp.asarray(perm, jnp.int32)
        pad = max_nodes - m
        feats_l.append(jnp.pad(bf_rev[perm_a], (0, pad)))
        bins_l.append(
            jnp.pad(bb_rev[perm_a], (0, pad), constant_values=n_bins - 1)
        )
    return jnp.stack(feats_l), jnp.stack(bins_l), node_int


def select_split(score, lk, level_nodes, p, n_bins, mtry, perm=None):
    """Pick each node's best (feature, bin) from the masked score tensor
    with randomForest's per-node mtry feature subsampling. Shared by the
    classifier level loop and BOTH causal formulations (direct and
    ρ-decomposed streaming) — the ≥0.95 split-agreement contract between
    them rides on these staying semantically identical. Nodes with no
    finite score fall back to (feature 0, bin n_bins−1): every row
    routes left.

    ``perm`` (the bit-reversal permutation): when the score rows are in
    REV node order, it re-maps the per-node random draws so node q still
    receives the same mtry subset as in interleaved order — the
    numbering is an internal layout choice, not a statistical one."""
    feat_scores = jax.random.uniform(lk, (level_nodes, p))
    if perm is not None:
        feat_scores = feat_scores[jnp.asarray(perm, jnp.int32)]
    kth = jnp.sort(feat_scores, axis=1)[:, mtry - 1 : mtry]
    score = jnp.where((feat_scores <= kth)[:, :, None], score, jnp.inf)
    flat = score.reshape(level_nodes, p * n_bins)
    best = jnp.argmin(flat, axis=1)
    has_split = jnp.isfinite(jnp.min(flat, axis=1))
    best_feat = jnp.where(has_split, (best // n_bins).astype(jnp.int32), 0)
    best_bin = jnp.where(
        has_split, (best % n_bins).astype(jnp.int32), n_bins - 1
    )
    return best_feat, best_bin


def _f32_sort_key(x: jax.Array) -> jax.Array:
    """Monotone f32 → uint32 key map: k(a) < k(b) iff a sorts before b
    under lax.sort's total order (−NaN < −inf < … < −0 < +0 < … < +inf
    < +NaN). Positive floats get the sign bit set; negatives are
    bit-flipped."""
    u = lax.bitcast_convert_type(x, jnp.uint32)
    return jnp.where(u >> 31 == 1, ~u, u | jnp.uint32(0x80000000))


def _key_to_f32(k: jax.Array) -> jax.Array:
    """Inverse of :func:`_f32_sort_key`."""
    u = jnp.where(
        k >= jnp.uint32(0x80000000), k ^ jnp.uint32(0x80000000), ~k
    )
    return lax.bitcast_convert_type(u, jnp.float32)


def exact_order_stats(x: jax.Array, ranks: jax.Array) -> jax.Array:
    """(p, R) exact order statistics of f32 ``x`` (n, p): column j of the
    result is ``sort(x[:, j])[ranks]`` — bit-identical to sorting,
    including −0/+0 and NaN placement, via a 32-round binary search on
    the uint32 sort-key domain (the smallest key with
    count(≤ key) ≥ rank+1 IS the rank-th key). One fused count-reduction
    per round inside a fori_loop: the compiled graph is ~1/20th of
    ``lax.sort``'s, which is the point — on the remote-compile TPU
    toolchain the (n, p) sort costs ~17 s to COMPILE for ~1 s of
    execution, a first-call tax every fresh-cache fit paid three times
    (same trick as :func:`exact_subsample_mask`, round 5).

    Ranks are processed in chunks of ≤16 under a sequential ``lax.map``
    so the per-round (n, p, chunk) count intermediate stays ~1 GB-
    bounded at the 1M-row flagship even if XLA materializes it — the
    unchunked form OOMed the 16 GB chip when a second fit's binning ran
    while the first fit's (T, n) forest arrays were still resident
    (bench.py's min-of-two protocol).

    Ranks are validated host-side when they are concrete (they are at
    every call site — linspace-derived constants stay concrete even
    under an enclosing trace): an out-of-range rank would otherwise
    leave ``lo`` at its 0xFFFFFFFF search bound, which decodes to a NaN
    bit pattern and silently poisons the caller's quantiles (ADVICE
    r5). Traced ranks skip the check — the binary search itself is
    rank-shape-agnostic."""
    ranks = jnp.asarray(ranks)
    n = x.shape[0]
    if not isinstance(ranks, jax.core.Tracer) and ranks.size:
        rmin, rmax = int(ranks.min()), int(ranks.max())
        if rmin < 0 or rmax >= n:
            raise ValueError(
                f"exact_order_stats: rank(s) out of range for n={n} rows "
                f"(min rank {rmin}, max rank {rmax}; valid range is "
                f"[0, {n - 1}])"
            )
    keys = _f32_sort_key(x)  # (n, p)
    p = x.shape[1]
    r = ranks.shape[0]
    g = min(16, r)
    n_chunks = -(-r // g)
    # Pad with repeats of the last rank; sliced away below.
    ranks_p = jnp.concatenate(
        [ranks, jnp.broadcast_to(ranks[-1:], (n_chunks * g - r,))]
    ).reshape(n_chunks, g)

    def search(ranks_chunk):
        target = (ranks_chunk + 1).astype(jnp.int32)[None, :]  # (1, g)
        lo = jnp.zeros((p, g), jnp.uint32)
        hi = jnp.full((p, g), jnp.uint32(0xFFFFFFFF))

        def step(_, bounds):
            lo, hi = bounds
            mid = lo + (hi - lo) // 2
            cnt = jnp.sum(
                keys[:, :, None] <= mid[None, :, :], axis=0, dtype=jnp.int32
            )
            ok = cnt >= target
            return jnp.where(ok, lo, mid + 1), jnp.where(ok, mid, hi)

        lo, _ = lax.fori_loop(0, 32, step, (lo, hi))
        return lo  # (p, g)

    out = lax.map(search, ranks_p)  # (n_chunks, p, g)
    out = jnp.moveaxis(out, 1, 0).reshape(p, n_chunks * g)[:, :r]
    return _key_to_f32(out)


def quantile_bins(x: jax.Array, n_bins: int = 64) -> jax.Array:
    """Per-feature quantile bin edges, (p, n_bins-1). Computed once and
    shared by every tree (the binned representation is what CART's
    exhaustive threshold scan degrades to at histogram resolution).

    On TPU (f32) this selects the two bracketing order statistics per
    quantile with :func:`_order_stat_quantiles` instead of sorting:
    BIT-identical values (asserted in tests/test_forest.py), ~17 s less
    compile per fresh cache — on the remote-compile toolchain the
    (1M, 21) ``lax.sort`` costs 17.3 s to COMPILE for ~1 s of
    execution, and even trivial eager primitives pay a 1-5 s
    per-executable tax (hence the jitted implementations: ONE
    executable, shared by all three flagship fits). Everywhere else
    ``jnp.quantile`` wins: the search issues ~50× a sort's comparisons,
    which priced a 1-core CPU test-suite run at +10 minutes before this
    gate, while CPU compile is cheap — so CPU (and non-f32) keep the
    sort.

    This wrapper is deliberately NOT jitted (ADVICE.md r5 / graftlint
    JGL001): the backend/dtype gate runs on the host on every call and
    dispatches to one of two separately jitted implementations, so the
    jit caches can never serve a path chosen under a different default
    backend. Inside an enclosing trace the dispatch still happens once
    at trace time — but then the choice is baked into the CALLER's
    cache entry, which owns its own keying."""
    x = jnp.asarray(x)
    if x.dtype != jnp.float32 or jax.default_backend() != "tpu":
        return _quantile_bins_sort(x, n_bins)
    return _quantile_bins_order_stat(x, n_bins)


@functools.partial(jax.jit, static_argnames=("n_bins",))
def _quantile_bins_sort(x: jax.Array, n_bins: int) -> jax.Array:
    """The ``jnp.quantile`` (sort) path — CPU and non-f32 dtypes."""
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return jnp.quantile(x, qs, axis=0).T  # (p, n_bins-1)


@functools.partial(jax.jit, static_argnames=("n_bins",))
def _quantile_bins_order_stat(x: jax.Array, n_bins: int) -> jax.Array:
    """The sort-free TPU f32 path (bit-identical to the sort path)."""
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return _order_stat_quantiles(x, qs)


def _order_stat_quantiles(x: jax.Array, qs: jax.Array) -> jax.Array:
    """The sort-free quantile path: ``jnp.quantile(x, qs, axis=0).T``
    computed from :func:`exact_order_stats` with jnp.quantile's exact
    interpolation arithmetic (weights in qs.dtype, value·weight operand
    order, final cast to x.dtype, NaN poisons the slice)."""
    n = x.shape[0]
    qn = qs * (jnp.asarray(n, qs.dtype) - 1)
    low = jnp.floor(qn)
    high = jnp.ceil(qn)
    hw = qn - low
    lw = jnp.asarray(1, hw.dtype) - hw
    k = qs.shape[0]
    ranks = jnp.concatenate([low, high]).astype(jnp.int32)
    vals = exact_order_stats(x, ranks)  # (p, 2k)
    res = vals[:, :k].astype(qs.dtype) * lw + vals[:, k:].astype(qs.dtype) * hw
    # jnp.quantile poisons a whole slice when it contains any NaN.
    res = jnp.where(jnp.isnan(x).any(axis=0)[:, None], jnp.nan, res)
    return res.astype(x.dtype)


@jax.jit
def binarize(x: jax.Array, edges: jax.Array) -> jax.Array:
    """Map features to int32 bin codes in [0, n_bins).

    The single chokepoint for the n_bins ≤ 256 invariant: every grower
    and predictor routes codes produced here through ``route_rows``,
    whose bf16 broadcast is exact only for integers ≤ 256.

    Computed as a compare-count — code = #{edges < x}, identical to
    ``searchsorted(side="left")`` for non-NaN input (the pipeline
    na.omits upstream) — which XLA fuses into one reduction sweep. The
    vmapped-searchsorted formulation lowered to a serialized binary-
    search while-loop that a device trace measured at 1.13 s per
    million-row fit, more than the entire 32-tree grow it fed.
    """
    n_bins = edges.shape[1] + 1
    if n_bins > 256:
        raise ValueError(
            f"n_bins={n_bins} > 256: bin codes must stay exact in bf16 routing"
        )
    return jnp.sum(
        x[:, :, None] > edges[None, :, :], axis=2, dtype=jnp.int32
    )


def bin_onehot(codes: jax.Array, n_bins: int) -> jax.Array:
    """Shared one-hot bin encoding for the histogram matmuls: one 1 per
    feature block, built by scatter (a dense (n, p, p*n_bins) one_hot
    intermediate would be ~1 GB at reference scale). Tree-independent —
    computed once per forest."""
    n, p = codes.shape
    flat_idx = codes + jnp.arange(p, dtype=jnp.int32)[None, :] * n_bins
    return (
        jnp.zeros((n, p * n_bins), jnp.float32)
        .at[jnp.arange(n)[:, None], flat_idx]
        .set(1.0)
    )


def pick_divisor(total: int, cap: int) -> int:
    """Largest divisor of ``total`` that is ≤ ``cap`` (≥ 1 always).
    ``total // pick_divisor(total, cap)`` is exact — required where the
    result sizes a dispatch loop (a floor division with a non-divisor
    silently drops the tail)."""
    cap = max(1, min(cap, total))
    for d in range(cap, 0, -1):
        if total % d == 0:
            return d
    return 1


def pick_chunk(total: int, chunk: int) -> int:
    """Pick a work-chunk size: prefer the largest divisor of ``total``
    within the budget (zero padding waste); fall back to ceil-padding
    only when ``total`` has no usable divisor (e.g. prime). Callers of
    the fallback MUST handle the padded tail — use :func:`pick_divisor`
    where the loop count is derived by exact division."""
    chunk = max(1, min(chunk, total))
    d = pick_divisor(total, chunk)
    return d if d * 2 >= chunk else chunk


def plan_host_dispatch(total_units: int, unit_chunk: int,
                       target_units: int) -> tuple[int, int, int]:
    """(chunk, super_, n_disp) for a host dispatch loop, with ceil
    padding instead of divisor-fitting (round 4).

    The old policy shrank the vmap chunk to a divisor of the total —
    e.g. the flagship's 500 nuisance trees at the streaming cap 11 →
    chunk 10 — under-filling the histogram kernel's tree batch, and
    fit the superchunk factor to the chunk count, inflating dispatch
    counts when the counts didn't divide (500 trees at 1M rows ran 50
    dispatches of (1, 10); now 23 of (2, 11) — the tunnel charges
    ~80 ms per dispatch). Now the chunk is always the full budget
    width and the last dispatch is padded. Cross-FIT executable
    sharing is NOT a goal here: fits that differ in tree counts
    usually also differ in a jit static (classifier vs regressor
    ``mtry``, depth, rows), so their executables are distinct
    regardless of the key-block shape.

    Padding is bounded by one superchunk: at most ``super_·chunk − 1``
    extra trees are grown and sliced away (≤1.2% at the flagship
    shapes; worst at small fits where a tree costs milliseconds —
    e.g. total=17 at budget 16 grows 32). ADVICE r4 weighed shrinking
    the chunk at small totals (ceil(total/n_chunks) would grow 18 for
    17): rejected, because the chunk is a compile-time static and the
    full-budget width is what keeps the executable shape independent
    of the fit's tree count — relative waste is only ever large where
    absolute waste is milliseconds. ``super_`` does not affect padding
    at all (it only groups chunks per dispatch): n_disp·super_·chunk
    rounds the SAME n_chunks·chunk total.

    Callers split ``n_disp·super_·chunk`` keys (prefix-stable in
    jax.random.split, so every real unit's key — and therefore every
    grown tree — is bit-identical to the divisor policy's) and slice
    the concatenated output back to ``total_units``.
    """
    chunk = max(1, min(unit_chunk, total_units))
    n_chunks = -(-total_units // chunk)
    super_ = max(1, min(target_units // chunk, n_chunks))
    n_disp = -(-n_chunks // super_)
    return chunk, super_, n_disp


# HBM budget for the largest per-level matmul operand of one vmapped
# tree chunk (the (rows, max_nodes) f32 node one-hots). Several live
# operands of comparable size coexist per level (node one-hot, weighted
# lhs, leaf one-hot), plus persistent forest state — 2 GB for the
# single largest keeps the whole chunk inside a 16 GB chip.
_CHUNK_BYTES_BUDGET = 2 << 30

# Trees per dispatched executable AT 100k ROWS: vmapped chunks are
# grouped into superchunks via an inner lax.map so a fit issues few
# dispatches (the remote tunnel charges ~80 ms per call with large
# args) while memory stays bounded by one vmapped chunk. The target
# scales inversely with rows — a single dispatch that runs for minutes
# (e.g. 250 trees × ~0.2 s at 1M rows) trips the remote worker's
# watchdog and kills the process.
_DISPATCH_CHUNK_TARGET = 256


def dispatch_tree_target(n_rows: int) -> int:
    """Trees per dispatch, scaled so one dispatch stays ~O(10 s)."""
    return max(16, _DISPATCH_CHUNK_TARGET * 100_000 // max(n_rows, 1))


def plan_tree_dispatch(
    n_rows: int,
    depth: int,
    per_dev_total: int,
    cap: int = 32,
    trees_per_unit: int = 1,
    leaf_onehot: bool = False,
    streaming: bool = False,
    p: int = 21,
    n_bins: int = 64,
    kernel_weights: int = 2,
    hist_floor: int = _HIST_M_FLOOR,
    hist_partition: bool = False,
) -> tuple[int, int, int]:
    """Dispatch plan for a per-device tree workload: (chunk,
    chunks_per_disp, n_disp). ``chunk`` units vmap together within the
    HBM budget (:func:`auto_tree_chunk`); ``chunks_per_disp`` chunks run
    sequentially inside one dispatched executable, capped so the
    per-device trees of one dispatch stay within
    :func:`dispatch_tree_target` (the remote-worker watchdog budget —
    devices run in parallel, so a dispatch's wall-clock is its
    per-DEVICE work); ``n_disp`` dispatches cover ``per_dev_total``
    units. Shared by the shard_map fitters; unit-tested at the
    million-row scale in tests/test_parallel.py. The tail is
    :func:`plan_host_dispatch` — full-width chunks with ceil padding,
    the same round-4 policy as the host loops (the divisor policy
    under-filled the kernel's tree batch and inflated dispatch
    counts)."""
    budget = auto_tree_chunk(
        n_rows, depth, cap=cap, trees_per_unit=trees_per_unit,
        leaf_onehot=leaf_onehot, streaming=streaming,
        p=p, n_bins=n_bins, kernel_weights=kernel_weights,
        hist_floor=hist_floor, hist_partition=hist_partition,
    )
    return plan_host_dispatch(
        per_dev_total, budget,
        max(1, dispatch_tree_target(n_rows) // trees_per_unit),
    )


def auto_tree_chunk(
    n_rows: int,
    depth: int,
    cap: int,
    trees_per_unit: int = 1,
    leaf_onehot: bool = False,
    streaming: bool = False,
    p: int = 21,
    n_bins: int = 64,
    kernel_weights: int = 2,
    hist_floor: int = _HIST_M_FLOOR,
    hist_partition: bool = False,
) -> int:
    """Trees to grow per compiled chunk: as many as fit the HBM budget,
    capped at ``cap``. The dominant operand is the deepest level's
    (rows, 2^(depth−1)) routing one-hot — or, when the engine also
    builds an honest-leaf one-hot (``leaf_onehot=True``), the
    (rows, 2^depth) leaf payload contraction. ``trees_per_unit`` scales
    for little-bag groups. ``n_rows`` must be the rows the grower
    actually streams (full n for the 'onehot' backend, the subsample
    for the gathered backends).

    ``streaming=True`` (the Pallas histogram backends): routing runs
    row-blocked (:func:`route_rows_blocked`), so the one-hot operand is
    (row_block, width) per tree instead of (rows, width) — at the
    million-row scale this raises the chunk from 2 trees to the kernel's
    own VMEM tree cap, which is what lets the tree-batched histogram
    kernel amortize its fixed per-row-stream work (the measured ~90% of
    kernel time; ops/hist_pallas.py). The chunk is additionally capped
    at one kernel tree-batch so each grow level is exactly one batched
    kernel call."""
    width = 1 << (depth if leaf_onehot else depth - 1)
    rows_eff = min(n_rows, _ROUTE_BLOCK) if streaming else n_rows
    per_tree = 4 * rows_eff * width * trees_per_unit
    # Streaming chunks are kernel-cap-bound, not ``cap``-bound: the
    # round-5 on-chip A/B (ops/hist_pallas.py::batched_tree_cap) showed
    # per-call fixed work amortizing linearly in the batch with flat
    # marginal cost, so the legacy cap only serves as a 2× safety bound
    # against runaway per-chunk HBM (the (T, n) id/weight streams).
    hard_cap = 2 * cap if streaming else cap
    chunk = max(1, min(hard_cap, _CHUNK_BYTES_BUDGET // max(per_tree, 1)))
    if streaming:
        from ate_replication_causalml_tpu.ops.hist_pallas import batched_tree_cap

        # Largest per-level histogram either streaming engine requests:
        # both sibling-subtract (left children only), so the deepest
        # kernel call covers 2^(depth-2) nodes — or, for engines that
        # pad shallow levels (``hist_floor`` > 1, the classifier's
        # uniform-width instantiations), the floor width the padded
        # kernels actually allocate. The causal grower passes
        # ``hist_floor=1`` (it does not pad) so its small-depth chunks
        # are not under-sized.
        kernel_nodes = max(1 << max(0, depth - 2), hist_floor)
        chunk = min(
            chunk,
            max(1, batched_tree_cap(kernel_nodes, kernel_weights, p=p,
                                    n_bins=n_bins, partition=hist_partition,
                                    ) // trees_per_unit),
        )
    return chunk


class ForestPredictions(NamedTuple):
    prob: jax.Array   # mean leaf probability over trees
    vote: jax.Array   # fraction of trees voting class 1 (randomForest "prob")


def _is_binary01(y) -> bool:
    """Host-side check that a concrete target is exactly {0, 1}-valued.

    Decides the per-tree centering policy (a traced 0/1 operand of the
    shared grow executable since round 5): binary targets keep the
    histogram weights integer and need no centering; continuous targets
    are centered per tree so the sibling histogram subtraction never
    cancels a large outcome level against itself in f32 (ADVICE r2: a
    level >> spread regression target loses relative precision on small
    right children). Under a trace the answer is unknowable — fall back
    to the safe continuous policy (center).
    """
    if isinstance(y, jax.core.Tracer):
        return False
    yv = np.asarray(y)
    return bool(np.all((yv == 0) | (yv == 1)))


def fit_forest_classifier(
    x: jax.Array,
    y: jax.Array,
    key: jax.Array,
    n_trees: int = 500,
    depth: int = 9,
    mtry: int | None = None,
    n_bins: int = 64,
    tree_chunk: int | None = None,
    hist_backend: str = "auto",
    hist_mode: str | None = None,
) -> Forest:
    """Fit a classification forest of ``n_trees`` depth-``depth`` trees.

    mtry defaults to floor(sqrt(p)) (randomForest's classification
    default). Trees are grown in chunks of ``tree_chunk`` (default:
    auto-sized to the HBM budget and the kernel's VMEM tree cap — ≤32
    on the XLA/onehot backends, up to 2× that on the streaming
    backends where the kernel cap rules; auto_tree_chunk): one jitted
    chunk executable
    (compiled once), driven by a host loop — bounded device-program size
    and memory, chunk-level progress/retry points (parallel/retry.py),
    identical numbers to a monolithic run since every chunk owns its
    fold-in keys.

    ``hist_mode`` (ISSUE 10): "dense" | "partition" | "auto" — the
    streaming histogram kernel's per-width formulation; defaults to the
    ``ATE_TPU_HIST_MODE`` environment policy ("auto" when unset —
    dense at shallow widths, partition past the measured FLOP
    crossover). Resolved HERE at config time (never at trace time) and
    baked into the chunk executable as a jit static.
    """
    n, p = x.shape
    if mtry is None:
        mtry = max(1, int(np.sqrt(p)))
    y01 = _is_binary01(y)
    hist_backend = resolve_hist_backend(
        hist_backend, n_rows=n, n_bins=n_bins, integer_weights=y01
    )
    hist_mode = resolve_hist_mode_packed(hist_mode, n_bins)
    hist_floor = 1 if hist_backend == "pallas_interpret" else _HIST_M_FLOOR
    # (n_bins ≤ 256 is enforced at the binarize() chokepoint.)
    # Explicit chunks are clamped too: the per-level routing one-hot is
    # (rows, 2^(depth−1)) per vmapped tree — or one row block of it on
    # the streaming (Pallas) backends, where routing is row-blocked and
    # the chunk instead matches the kernel's tree-batch cap.
    auto_chunk = auto_tree_chunk(
        n, depth, cap=32, streaming=hist_backend.startswith("pallas"),
        p=p, n_bins=n_bins,
        # Mirrors the grower's floor choice (interpret mode pads
        # nothing) so the planned chunk matches what the kernels
        # actually allocate.
        hist_floor=hist_floor,
        hist_partition=hist_backend.startswith("pallas")
        and hist_partition_active(hist_mode, depth, hist_floor, 2, p, n_bins),
    )
    tree_chunk = auto_chunk if tree_chunk is None else min(tree_chunk, auto_chunk)
    edges = quantile_bins(x, n_bins)
    codes = binarize(x, edges)  # (n, p) int32
    xb_onehot = bin_onehot(codes, n_bins) if hist_backend == "onehot" else None
    yf = y.astype(jnp.float32)

    # Superchunking: several vmapped chunks per DISPATCH via an inner
    # lax.map (sequential → same memory as one chunk). The remote-device
    # tunnel charges ~80 ms per dispatched executable with large args,
    # so at small auto chunks (million-row fits) a chunk-per-dispatch
    # loop pays minutes of pure overhead. Ceil-padded plan: executable
    # shape independent of n_trees (see plan_host_dispatch).
    tree_chunk, super_, n_disp = plan_host_dispatch(
        n_trees, tree_chunk, dispatch_tree_target(n)
    )
    tree_keys = jax.random.split(key, n_disp * super_ * tree_chunk)

    def chunk_shard(i: int):
        _meter_hist_dispatches(
            "classifier", hist_backend, hist_mode, depth, hist_floor,
            super_, 2, p, n_bins,
        )
        kk = tree_keys[
            i * super_ * tree_chunk : (i + 1) * super_ * tree_chunk
        ].reshape(super_, tree_chunk)
        return _grow_chunk(
            kk, codes, yf, xb_onehot, jnp.float32(not y01),
            depth=depth, mtry=mtry, n_bins=n_bins, hist_backend=hist_backend,
            hist_mode=hist_mode,
        )

    # Elastic host loop (parallel/retry.py, classified retry): a
    # transient device failure (dropped tunnel, preemption) re-runs only
    # that dispatch, while a programming error raises on attempt 1; keys
    # are explicit so the retried dispatch is bit-identical. Telemetry:
    # dispatch counts + per-dispatch host durations, labeled by fitter
    # (recorded at the dispatch boundary — no sync added).
    chunks = require_all(
        run_shards(
            obs.instrument_dispatch("forest_classifier", chunk_shard),
            n_disp,
            pool="forest_classifier",
        )
    )
    cat = lambda j: jnp.concatenate([c[j] for c in chunks], axis=0)[:n_trees]
    return Forest(
        split_feat=cat(0),
        split_bin=cat(1),
        leaf_value=cat(2),
        counts=cat(3),
        bin_edges=edges,
        train_leaf=cat(4),
        train_fp=codes_fingerprint(codes),
    )


@functools.partial(
    jax.jit,
    static_argnames=("depth", "mtry", "n_bins", "hist_backend", "hist_mode"),
)
def _grow_chunk(tree_keys, codes, yf, xb_onehot, center, *, depth, mtry, n_bins,
                hist_backend, hist_mode="dense"):
    """One compiled dispatch of trees. ``tree_keys`` is either (tc,) —
    one vmapped chunk — or (S, tc) — a superchunk: S vmapped chunks run
    sequentially under lax.map (memory of one chunk, one dispatch).
    Module-level jit: the executable is shared by every dispatch of
    every forest with the same shapes/statics.

    ``center`` is a TRACED f32 0/1 scalar (round 5 — it was a jit
    static, which split the flagship's continuous-Y and binary-W
    nuisance fits into two ~35 s compiles of the same graph). 1.0
    (continuous targets) subtracts each tree's bootstrap-weighted mean
    from y before histogram accumulation and re-adds it at the leaves:
    the split criterion is invariant to a per-tree shift (the parent
    totals it adds are constant within each node's argmin domain), but
    the f32 sibling subtraction parent − left no longer cancels a large
    outcome level against itself on small right children. 0.0 (binary
    targets) keeps the weights integer. Both values are BIT-identical
    to the old static branches: ``yf − 0·μ ≡ yf`` and ``yf − 1·μ ≡
    yf − μ`` exactly in IEEE f32 (μ is finite; ±0.0 subtraction
    preserves the sign of every finite yf)."""
    n, p = codes.shape
    max_nodes = 1 << (depth - 1)
    n_leaves = 1 << depth
    if hist_backend.startswith("pallas"):
        # Row-side Pallas kernels (ops/tree_pallas.py): the transposed
        # routing operand is built ONCE per chunk and shared by every
        # tree/level; "pallas_interpret" (the CPU test mode) threads
        # through to both kernels.
        codes_t = codes_transposed(codes)
        row_backend = (
            "pallas_interpret" if hist_backend == "pallas_interpret" else "pallas"
        )

    def grow_one(tree_key):
        ck, gk = jax.random.split(tree_key)
        counts = _poisson1_counts(ck, (n,))
        mu = jnp.sum(counts * yf) / jnp.maximum(jnp.sum(counts), 1e-12)
        yt = yf - center * mu
        base = center * mu

        def hists_for(ids, n_nodes, weights):
            """(len(weights), n_nodes, p, n_bins) histograms; rows with
            id −1 contribute nothing."""
            if hist_backend == "onehot":
                node_oh = jax.nn.one_hot(ids, n_nodes, dtype=jnp.float32)
                return jnp.stack([
                    jnp.matmul(
                        (node_oh * wv[:, None]).T, xb_onehot, precision=_PREC
                    ).reshape(n_nodes, p, n_bins)
                    for wv in weights
                ])
            return bin_histogram(
                codes, ids, jnp.stack(weights),
                max_nodes=n_nodes, n_bins=n_bins, backend=hist_backend,
            )

        def split_tables(hist, lk, level_nodes, perm=None):
            """Scores a full-level (2, m, p, bins) histogram and picks
            per-node splits; rows may be in rev node order (``perm``)."""
            hist_c, hist_y = hist[0], hist[1]
            cl = jnp.cumsum(hist_c, axis=2)
            yl = jnp.cumsum(hist_y, axis=2)
            ct, ytot = cl[:, :, -1:], yl[:, :, -1:]
            cr, yr = ct - cl, ytot - yl
            eps = 1e-12
            # Universal split score: minimizing -(S_L²/c_L + S_R²/c_R) is
            # the SSE-reduction criterion for a regression target and is
            # identical (up to the per-node constant S_parent) to the
            # weighted-Gini criterion when y is 0/1 — so one engine
            # serves both randomForest classification (Gini) and
            # regression (MSE) semantics.
            score = -(
                yl * yl / jnp.maximum(cl, eps) + yr * yr / jnp.maximum(cr, eps)
            )
            score = jnp.where((cl > 0) & (cr > 0), score, jnp.inf)
            return select_split(score, lk, level_nodes, p, n_bins, mtry,
                                perm=perm)

        # Levels are unrolled as a Python loop so level l only computes
        # histograms for its 2^l live nodes (a lax.scan would force every
        # level to the padded final width — ~depth/2× wasted FLOPs).
        # Split tables are padded back to max_nodes for a uniform layout.
        level_keys = jax.random.split(gk, depth)

        if hist_backend.startswith("pallas"):
            # Bit-reversed streaming loop — see streaming_level_loop
            # (shared with the causal grower; the rev-id bookkeeping
            # must stay identical between them).
            weights2 = jnp.stack([counts, counts * yt])
            feats, bins, node_of_row = streaming_level_loop(
                codes, depth, n_bins,
                # Kernel mode per WIDTH (ISSUE 10): ``hist_mode`` is a
                # jit static resolved at config time; mode_for_width is
                # a pure function of static shapes, so this dispatch is
                # fixed at trace time and each kernel width compiles in
                # exactly ONE mode — the partition kernel reuses the
                # uniform-width instantiation set instead of
                # multiplying it.
                hist_fn=lambda ids, m: bin_histogram(
                    codes, ids, weights2, max_nodes=m, n_bins=n_bins,
                    backend=hist_backend,
                    mode=mode_for_width(hist_mode, m, 2, p, n_bins),
                ),
                tables_fn=lambda hist, level, perm: split_tables(
                    hist, level_keys[level], 1 << level, perm=perm
                ),
                route_fn=lambda ids, bf, bb: route_bits(
                    codes_t, ids, bf, bb, backend=row_backend
                ),
                # The uniform floors exist to cut Mosaic kernel
                # instantiations (a remote-compile cost); interpret mode
                # has no compile and would pay the padded widths in
                # eager execution — the CPU suite measured minutes.
                # Bit-identity across floor settings is asserted in
                # tests/test_forest.py::test_grow_floors_bit_identical.
                hist_floor=1 if row_backend == "pallas_interpret"
                else _HIST_M_FLOOR,
                route_floor=1 if row_backend == "pallas_interpret"
                else _ROUTE_M_FLOOR,
            )
        else:
            feats_l, bins_l = [], []

            def emit(bf, bb, level_nodes):
                pad = max_nodes - level_nodes
                feats_l.append(jnp.pad(bf, (0, pad)))
                bins_l.append(
                    jnp.pad(bb, (0, pad), constant_values=n_bins - 1)
                )

            node_of_row, prev = jnp.zeros(n, jnp.int32), None
            for level in range(depth):
                level_nodes = min(1 << level, max_nodes)
                # Histogram subtraction (the LightGBM sibling trick):
                # both weight vectors are level-invariant, so each level
                # computes histograms for LEFT children only — right
                # children come free as parent − left.
                if prev is None:
                    hist = hists_for(
                        node_of_row, level_nodes, (counts, counts * yt)
                    )
                else:
                    half = level_nodes // 2
                    left_id = jnp.where(
                        node_of_row % 2 == 0, node_of_row // 2, -1
                    )
                    hist_left = hists_for(left_id, half, (counts, counts * yt))
                    hist = jnp.stack(
                        [hist_left, prev - hist_left], axis=2
                    ).reshape(2, level_nodes, p, n_bins)
                prev = hist
                bf, bb = split_tables(hist, level_keys[level], level_nodes)
                node_oh = jax.nn.one_hot(
                    node_of_row, level_nodes, dtype=jnp.float32
                )
                node_of_row = route_rows(
                    node_oh, bf, bb, codes.astype(jnp.float32), node_of_row
                )
                emit(bf, bb, level_nodes)
            feats = jnp.stack(feats_l)
            bins = jnp.stack(bins_l)

        # Leaf stats at depth D (bootstrap-weighted), parent-filled where
        # empty by falling back to the overall rate. Streaming backends
        # use the node-sum kernel (scatter-free, batches over the tree
        # vmap like every other dispatch, always f32 — leaf values feed
        # predictions); the dense backends keep segment_sum: the
        # (n, 2^D) one-hot alternative is ~100 MB per tree at depth 9 —
        # gigabytes under the tree vmap — and this runs once per tree.
        if hist_backend.startswith("pallas"):
            # Same rule as the causal grower: leaf payloads stay f32
            # even when split search runs the bf16 kernel.
            leaf_backend = (
                "pallas" if hist_backend == "pallas_bf16" else hist_backend
            )
            ls = node_sums(
                node_of_row, jnp.stack([counts, counts * yt]), n_leaves,
                backend=leaf_backend,
            )  # (L, 2)
            leaf_c, leaf_y = ls[:, 0], ls[:, 1]
        else:
            leaf_c = jax.ops.segment_sum(counts, node_of_row, num_segments=n_leaves)
            leaf_y = jax.ops.segment_sum(
                counts * yt, node_of_row, num_segments=n_leaves
            )
        leaf_value = jnp.where(leaf_c > 0, base + leaf_y / jnp.maximum(leaf_c, 1e-12), mu)
        # Training-row leaf recording: the plain gather serializes
        # per row on TPU (a round-4 device trace measured it at
        # ~8 ms/tree at 1M rows — the largest single op of the fit);
        # the streaming backends run the table-lookup kernel instead.
        train_vals = (
            table_lookup(leaf_value, node_of_row, backend=row_backend)
            if hist_backend.startswith("pallas")
            else leaf_value[node_of_row]
        )
        # Bootstrap counts persist only for the OOB mask (count == 0);
        # uint8 storage is 4× smaller than f32 — (T, n) at a 500-tree ×
        # 1M-row nuisance fit is 2 GB in f32. Counts > 255 clamp to 255:
        # the mask only distinguishes 0 from >0, so the clamp can never
        # flip an in-bag row to OOB the way a wrapping cast could.
        return feats, bins, leaf_value, jnp.minimum(counts, 255).astype(jnp.uint8), train_vals

    if tree_keys.ndim == 1:
        return jax.vmap(grow_one)(tree_keys)
    out = lax.map(lambda kk: jax.vmap(grow_one)(kk), tree_keys)  # (S, tc, …)
    return jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), out
    )


def apply_trees_chunked(
    split_feat, split_bin, codes, depth, post, tree_aux=None,
    tree_chunk: int = 32, row_chunk: int = 65536, pack: bool = False,
):
    """Tiled tree application: route every (tree, row) pair with
    per-level one-hot matmuls (``route_rows``) in bounded
    (tree chunk × row block) tiles, then map ``post(node, aux_t)`` per
    tile. The SINGLE implementation of chunked routing — forest_apply
    and the causal forest's ``compute_leaf_index`` both consume it.

    Per-row gathers serialize on TPU, and unbounded (rows, nodes)
    one-hots would not fit HBM at the million-row scale — hence both the
    matmul routing and the tiling.

    Args:
      split_feat/split_bin: (T, depth, max_nodes) int32 split tables.
      codes: (n, p) int32 bin codes of the query rows.
      post: ``(node_ids (rb,), aux_t) -> (rb,) array`` per-tile output
        (e.g. leaf-value contraction, or the ids themselves).
      tree_aux: optional per-tree array (T, …) passed to ``post``.
      pack: route through the packed-code contraction (ISSUE 12 — one
        :func:`~..ops.pack.pack_codes` per row block, shared by every
        tree and level; bit-identical routing, 3× fewer permute MACs).
        A config-time-resolved static — callers thread
        ``resolve_predict_pack``, never the environment.

    Returns: (T, n) stacked ``post`` outputs.
    """
    n = codes.shape[0]
    codes_f = codes.astype(jnp.float32)
    T = split_feat.shape[0]
    t_chunks = -(-T // tree_chunk)
    t_pad = t_chunks * tree_chunk

    def pad_trees(a):
        return jnp.concatenate(
            [a, jnp.zeros((t_pad - T,) + a.shape[1:], a.dtype)]
        ).reshape(t_chunks, tree_chunk, *a.shape[1:])

    feats_c = pad_trees(split_feat)
    bins_c = pad_trees(split_bin)
    aux_c = None if tree_aux is None else pad_trees(tree_aux)

    rb = min(row_chunk, n)
    n_blocks = -(-n // rb)
    n_pad = n_blocks * rb
    codes_b = jnp.pad(codes_f, ((0, n_pad - n), (0, 0))).reshape(n_blocks, rb, -1)

    def block_fn(codes_blk):
        # ONE packed operand per row block, shared by every tree chunk
        # and level of this block (ISSUE 12).
        packed_blk = _pack_codes(codes_blk) if pack else None

        def one_tree(feats, bins, aux):
            node = jnp.zeros(rb, jnp.int32)
            for level in range(depth):
                m = 1 << level
                node_oh = jax.nn.one_hot(node, m, dtype=jnp.float32)
                if pack:
                    node = route_rows_packed(
                        node_oh, feats[level][:m], bins[level][:m],
                        packed_blk, node,
                    )
                else:
                    node = route_rows(node_oh, feats[level][:m],
                                      bins[level][:m], codes_blk, node)
            return post(node, aux)

        def chunk(fba):
            feats, bins, aux = fba
            if aux is None:
                return jax.vmap(lambda f, b: one_tree(f, b, None))(feats, bins)
            return jax.vmap(one_tree)(feats, bins, aux)

        return lax.map(chunk, (feats_c, bins_c, aux_c)).reshape(t_pad, rb)

    vals = lax.map(block_fn, codes_b)  # (n_blocks, t_pad, rb)
    vals = jnp.moveaxis(vals, 0, 1).reshape(t_pad, n_pad)
    return vals[:T, :n]


@functools.partial(jax.jit, static_argnames=("tree_chunk", "row_chunk"))
def forest_apply(
    forest: Forest,
    codes: jax.Array,
    tree_chunk: int = 32,
    row_chunk: int = 65536,
) -> jax.Array:
    """Leaf value of every (tree, row): (T, n)."""
    return apply_trees_chunked(
        forest.split_feat, forest.split_bin, codes, forest.depth,
        post=lambda node, lv: jnp.matmul(
            jax.nn.one_hot(node, lv.shape[0], dtype=jnp.float32), lv,
            precision=_PREC,
        ),
        tree_aux=forest.leaf_value,
        tree_chunk=tree_chunk, row_chunk=row_chunk,
    )


# predict_forest's OOB fingerprint verdicts, keyed by (id(x),
# id(train_fp)). jax arrays are unhashable, so weak KEYS are out;
# entries are evicted by weakref.finalize when either object dies
# (guarding against id reuse) and the dict is capped as a backstop.
# The stored value is a (shape, dtype) sanity tuple checked on lookup,
# so even a stale id-reused hit must also collide on shape+dtype to
# skip the (defense-in-depth) check. A stale hit can at worst SKIP that
# check, never corrupt.
_FP_VERIFIED: dict = {}
_FP_VERIFIED_CAP = 256


def _remember_fp_verified(x, fp) -> None:
    key = (id(x), id(fp))
    try:
        weakref.finalize(x, _FP_VERIFIED.pop, key, None)
        weakref.finalize(fp, _FP_VERIFIED.pop, key, None)
    except TypeError:
        # Not weakref-able on this backend: an identity key could
        # silently survive gc + id reuse, so skip memoization entirely
        # (repeat calls just re-verify the fingerprint).
        return
    if len(_FP_VERIFIED) >= _FP_VERIFIED_CAP:
        _FP_VERIFIED.clear()
    _FP_VERIFIED[key] = (x.shape, x.dtype)


def _fp_already_verified(x, fp) -> bool:
    return _FP_VERIFIED.get((id(x), id(fp))) == (x.shape, x.dtype)


def predict_forest(forest: Forest, x: jax.Array, oob: bool = False) -> ForestPredictions:
    """Forest predictions for rows ``x``.

    ``vote`` is the randomForest ``predict(type="prob")`` semantics: the
    fraction of trees whose leaf majority-class is 1. With ``oob=True``
    (valid only for the training matrix) each row averages only over
    trees whose bootstrap count for that row is zero — the reference's
    OOB propensity (``ate_functions.R:174``). With ``oob=True`` the
    per-tree leaf values recorded at growth time (``train_leaf``) are
    used directly — ``x`` MUST be the training matrix in training row
    order (a same-shape different matrix is indistinguishable and would
    silently get training predictions); row-count mismatches raise.
    """
    if oob and x.shape[0] != forest.counts.shape[1]:
        # Precise message first: a wrong-size matrix is not a
        # "permuted rows" problem.
        raise ValueError(
            "oob=True is only valid for the training matrix: forest was "
            f"fit on {forest.counts.shape[1]} rows, got {x.shape[0]}"
        )
    if oob and forest.train_leaf is not None:
        # Guard against a same-shape matrix that is not the training
        # matrix (checked only when everything involved is concrete —
        # inside a trace of either x or the forest the fingerprint is
        # symbolic and the caller owns the contract). The verdict is
        # memoized per (x, train_fp) object pair so repeat OOB calls
        # (e.g. both nuisance predictions of a causal-forest fit) don't
        # re-binarize or re-sync; identity keying can at worst SKIP a
        # defense-in-depth check after heavy gc churn, never corrupt.
        concrete = lambda a: not isinstance(a, jax.core.Tracer)
        if (
            forest.train_fp is not None
            and concrete(x)
            and concrete(forest.train_fp)
            and concrete(forest.bin_edges)
        ):
            if not _fp_already_verified(x, forest.train_fp):
                fp = codes_fingerprint(binarize(x, forest.bin_edges))
                if int(fp) != int(forest.train_fp):
                    raise ValueError(
                        "oob=True with recorded training leaves, but x does "
                        "not fingerprint as the training matrix (permuted or "
                        "altered rows?); pass oob=False for new data"
                    )
                _remember_fp_verified(x, forest.train_fp)
        leaf_vals = forest.train_leaf  # (T, n) — recorded during growth
    else:
        codes = binarize(x, forest.bin_edges)
        leaf_vals = forest_apply(forest, codes)  # (T, n)
    if oob:
        prob, vote = _oob_reduce(leaf_vals, forest.counts)
    else:
        prob, vote = _mean_reduce(leaf_vals)
    return ForestPredictions(prob=prob, vote=vote)


@jax.jit
def _oob_reduce(leaf_vals, counts):
    """OOB-masked tree averages as ONE executable. Eager, this was ~8
    primitive-sized executables — each under the persistent cache's
    1 s min-compile threshold, so every fresh process re-paid ~5 s of
    remote compiles for 0.4 s of execution (round 5, VERDICT r4 #7)."""
    votes = (leaf_vals > 0.5).astype(jnp.float32)
    mask = (counts == 0).astype(jnp.float32)  # (T, n)
    denom = jnp.maximum(mask.sum(axis=0), 1.0)
    prob = (leaf_vals * mask).sum(axis=0) / denom
    vote = (votes * mask).sum(axis=0) / denom
    return prob, vote


@jax.jit
def _mean_reduce(leaf_vals):
    votes = (leaf_vals > 0.5).astype(jnp.float32)
    return leaf_vals.mean(axis=0), votes.mean(axis=0)


def _predict_forest_new_rows(forest: Forest, x: jax.Array) -> ForestPredictions:
    """:func:`predict_forest` restricted to ``oob=False`` as one
    traceable body — the AOT serving target. The oob branch needs the
    concrete training-matrix fingerprint check, which a fixed-shape
    serving executable can never perform (and serving rows are new data
    by definition)."""
    codes = binarize(x, forest.bin_edges)
    prob, vote = _mean_reduce(forest_apply(forest, codes))
    return ForestPredictions(prob=prob, vote=vote)


_predict_forest_serving = jax.jit(_predict_forest_new_rows)


def lower_predict_forest(forest: Forest, batch: int) -> jax.stages.Lowered:
    """AOT-lower the classifier-forest predict executable for a fixed
    ``(batch, p)`` query shape (ISSUE 6 — the serving-parity entry point
    next to :func:`~..models.causal_forest.lower_predict_cate`).
    ``.compile()`` yields the executable dispatched as
    ``compiled(forest, x)``; the forest is a runtime argument, so a
    same-shape reload reuses the executable."""
    p = forest.bin_edges.shape[0]
    x_spec = jax.ShapeDtypeStruct((int(batch), p), jnp.float32)
    return _predict_forest_serving.lower(forest, x_spec)


def fit_forest_sharded(
    x: jax.Array,
    y: jax.Array,
    key: jax.Array,
    mesh,
    n_trees: int = 500,
    depth: int = 9,
    mtry: int | None = None,
    n_bins: int = 64,
    axis_name: str = "tree",
    hist_backend: str = "auto",
    hist_mode: str | None = None,
) -> Forest:
    """Tree-parallel forest fit over a mesh axis (SURVEY.md §2.4: trees
    are the expert-parallel analogue).

    Every device grows ``n_trees / axis_size`` trees from its own slice
    of the key array against replicated binned data; the forest arrays
    come back sharded along the tree axis (all_gather is XLA's job when
    a consumer needs them replicated). Numbers are NOT identical to
    :func:`fit_forest_classifier` (keys are partitioned differently),
    but the ensemble is statistically equivalent.

    Scale safety mirrors the host-loop fitter: per-device trees grow in
    HBM-budgeted vmapped chunks (``auto_tree_chunk``), and the per-device
    trees of ONE dispatched executable are capped by
    ``dispatch_tree_target`` — devices run in parallel, so one
    dispatch's wall-clock is its per-DEVICE tree count × per-tree time,
    and an uncapped 1M-row fit would run minutes inside a single
    executable (remote-worker watchdog territory). Multiple dispatches
    run under the elastic host loop (parallel/retry.py).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n, p = x.shape
    if mtry is None:
        mtry = max(1, int(np.sqrt(p)))
    if hist_backend == "onehot":
        raise ValueError(
            "hist_backend='onehot' is not supported on the sharded path "
            "(the shared bin one-hot is not built here); use 'auto'/'xla'/'pallas'"
        )
    y01 = _is_binary01(y)
    hist_backend = resolve_hist_backend(
        hist_backend, allow_onehot=False, n_rows=n, n_bins=n_bins,
        integer_weights=y01,
    )
    hist_mode = resolve_hist_mode_packed(hist_mode, n_bins)
    hist_floor = 1 if hist_backend == "pallas_interpret" else _HIST_M_FLOOR
    axis_size = mesh.shape[axis_name]
    per_dev_total = -(-n_trees // axis_size)
    tree_chunk, chunks_per_disp, n_disp = plan_tree_dispatch(
        n, depth, per_dev_total, streaming=hist_backend.startswith("pallas"),
        p=p, n_bins=n_bins,
        hist_floor=hist_floor,
        hist_partition=hist_backend.startswith("pallas")
        and hist_partition_active(hist_mode, depth, hist_floor, 2, p, n_bins),
    )
    per_disp_dev = chunks_per_disp * tree_chunk

    edges = quantile_bins(x, n_bins)
    codes = binarize(x, edges)
    yf = y.astype(jnp.float32)
    tree_keys = jax.random.split(key, n_disp * axis_size * per_disp_dev).reshape(
        n_disp, axis_size * per_disp_dev
    )

    grow = _sharded_grow_fn(
        mesh, axis_name, chunks_per_disp, tree_chunk,
        depth=depth, mtry=mtry, n_bins=n_bins, hist_backend=hist_backend,
        hist_mode=hist_mode,
    )
    key_sharding = NamedSharding(mesh, P(axis_name))
    center = jnp.float32(not y01)

    def dispatch(i: int):
        # Every device runs its own per-device chunks — the meter
        # counts kernel calls across the mesh, per issued dispatch.
        _meter_hist_dispatches(
            "classifier", hist_backend, hist_mode, depth, hist_floor,
            chunks_per_disp * axis_size, 2, p, n_bins,
        )
        return grow(jax.device_put(tree_keys[i], key_sharding), codes, yf, center)

    parts = require_all(
        run_shards(
            obs.instrument_dispatch("forest_sharded", dispatch),
            n_disp,
            pool="forest_sharded",
        )
    )
    cat = lambda j: jnp.concatenate([c[j] for c in parts], axis=0)[:n_trees]
    return Forest(
        split_feat=cat(0),
        split_bin=cat(1),
        leaf_value=cat(2),
        counts=cat(3),
        bin_edges=edges,
        train_leaf=cat(4),
        train_fp=codes_fingerprint(codes),
    )


@functools.lru_cache(maxsize=64)
def _sharded_grow_fn(mesh, axis_name, chunks_per_disp, tree_chunk, *,
                     depth, mtry, n_bins, hist_backend, hist_mode="dense"):
    """The jitted shard_map grow executable, cached on (mesh, plan,
    statics). Building `jax.jit(shard_map(local_lambda))` inside
    :func:`fit_forest_sharded` gave every CALL a fresh function
    identity — jit re-traced and re-compiled the same computation per
    fit (masked when the persistent cache served the recompile from
    disk; a cache-less CPU child measured it as a 10× inflation of the
    MESH_SCALING forest curve). `jax.sharding.Mesh` is hashable, so the
    executable is shared by every fit with the same plan."""
    from jax.sharding import PartitionSpec as P

    def device_body(keys, codes, yf, center):
        return _grow_chunk(
            keys.reshape(chunks_per_disp, tree_chunk), codes, yf, None, center,
            depth=depth, mtry=mtry, n_bins=n_bins, hist_backend=hist_backend,
            hist_mode=hist_mode,
        )

    return jax.jit(_shard_map(
        device_body,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P()),
        out_specs=P(axis_name),
    ))


def sharded_fit_plan(
    n_rows: int,
    depth: int,
    per_dev_total: int,
    hist_backend: str = "auto",
    n_bins: int = 64,
    p: int = 21,
    hist_mode: str | None = None,
) -> tuple[int, int, int]:
    """The (chunk, chunks_per_disp, n_disp) plan :func:`fit_forest_sharded`
    will actually use, after backend resolution — for callers recording
    dispatch-plan evidence (bench.py --mesh-scaling): quoting
    :func:`plan_tree_dispatch` with default statics can describe a
    different executable layout than the fit being timed."""
    resolved = resolve_hist_backend(
        hist_backend, allow_onehot=False, n_rows=n_rows, n_bins=n_bins,
    )
    mode = resolve_hist_mode_packed(hist_mode, n_bins)
    floor = 1 if resolved == "pallas_interpret" else _HIST_M_FLOOR
    return plan_tree_dispatch(
        n_rows, depth, per_dev_total,
        streaming=resolved.startswith("pallas"), p=p, n_bins=n_bins,
        hist_floor=floor,
        hist_partition=resolved.startswith("pallas")
        and hist_partition_active(mode, depth, floor, 2, p, n_bins),
    )


def fit_forest_regressor_sharded(
    x: jax.Array,
    y: jax.Array,
    key: jax.Array,
    mesh,
    n_trees: int = 500,
    depth: int = 9,
    mtry: int | None = None,
    **kwargs,
) -> Forest:
    """Tree-sharded regression forest: the sharded engine with
    randomForest's regression mtry default (max(1, floor(p/3)))."""
    if mtry is None:
        mtry = max(1, x.shape[1] // 3)
    return fit_forest_sharded(
        x, y, key, mesh, n_trees=n_trees, depth=depth, mtry=mtry, **kwargs
    )


def fit_forest_regressor(
    x: jax.Array,
    y: jax.Array,
    key: jax.Array,
    n_trees: int = 500,
    depth: int = 9,
    mtry: int | None = None,
    n_bins: int = 64,
    tree_chunk: int | None = None,
    hist_backend: str = "auto",
    hist_mode: str | None = None,
) -> Forest:
    """Regression forest — same engine as the classifier (the split
    score is SSE-reduction, see ``level_step``), leaf values are
    bootstrap-weighted means of a continuous target. mtry defaults to
    randomForest's regression default max(1, floor(p/3)).

    This is the nuisance-forest used for grf-style local centering
    (``ate_replication.Rmd:250-255`` fits ``causal_forest`` whose C++
    core first fits Y~X and W~X regression forests).
    """
    if mtry is None:
        mtry = max(1, x.shape[1] // 3)
    return fit_forest_classifier(
        x, y, key, n_trees=n_trees, depth=depth, mtry=mtry,
        n_bins=n_bins, tree_chunk=tree_chunk, hist_backend=hist_backend,
        hist_mode=hist_mode,
    )


def forest_oob_mean(forest: Forest, x: jax.Array) -> jax.Array:
    """OOB leaf-mean prediction on the training matrix (regression
    analogue of the OOB vote; the local-centering estimates Ŷ(x), Ŵ(x)
    in grf are OOB predictions of exactly this kind)."""
    return predict_forest(forest, x, oob=True).prob


def rf_oob_propensity(
    frame: CausalFrame,
    key: jax.Array | None = None,
    n_trees: int = 500,
    depth: int = 9,
    mesh=None,
    **kwargs,
) -> jax.Array:
    """The reference's AIPW propensity: classification forest of W on X,
    OOB vote fractions (``ate_functions.R:169-174``). With a ``mesh``,
    trees shard over its tree axis."""
    if key is None:
        key = jax.random.key(12325)  # the seed the reference *meant* to set
    if mesh is not None:
        forest = fit_forest_sharded(
            frame.x, frame.w, key, mesh, n_trees=n_trees, depth=depth, **kwargs
        )
    else:
        forest = fit_forest_classifier(
            frame.x, frame.w, key, n_trees=n_trees, depth=depth, **kwargs
        )
    return predict_forest(forest, frame.x, oob=True).vote

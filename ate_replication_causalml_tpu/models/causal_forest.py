"""Honest causal forest — TPU-native replacement for grf's C++ core.

The reference's flagship estimator is ``grf::causal_forest(X, Y, W,
num.trees=2000, honesty=TRUE)`` followed by the doubly-robust
``grf::estimate_average_effect(forest)`` (``ate_replication.Rmd:249-272``,
SURVEY.md §2.1 #15, §3.3). grf's core is C++ with std::thread tree
growing; nothing of that design survives here. The TPU-first design:

  * **Local centering** (orthogonalization): regression forests estimate
    Ŷ(x)=E[Y|X] and Ŵ(x)=E[W|X] with OOB predictions (the forest engine's
    histogram-matmul trees, models/forest.py); the causal forest is then
    grown on the residuals ỹ=Y−Ŷ, w̃=W−Ŵ — exactly grf's
    ``precompute.nuisance`` path.
  * **Gradient-based honest splits, level-wise**: trees grow to a fixed
    depth with node masking (static shapes — no recursion, no
    data-dependent tree topology). At each level, per-node moments
    (c, Σw̃, Σỹ, Σw̃², Σw̃ỹ) come from one small MXU matmul; the node-local
    treatment effect τ_node = Cov(w̃,ỹ)/Var(w̃) defines GRF's
    pseudo-outcome ρᵢ = (w̃ᵢ−w̄)·((ỹᵢ−ȳ) − (w̃ᵢ−w̄)·τ_node), and the split
    maximizes the heterogeneity of ρ-means across children — a
    regression-tree split on ρ, again solved by histogram matmuls
    (GRF drops the per-node Var(w̃) scaling of ρ here; it is constant
    within a node so the argmax split is unchanged).
  * **Honesty**: each tree's subsample is split in half; the I half
    chooses splits (computes ρ and the criterion), the J half populates
    leaves. Leaf payloads are the five J-half sufficient statistics
    (count, Σw̃, Σỹ, Σw̃², Σw̃ỹ) — everything predictions need.
  * **Forest-weighted CATE**: grf predicts τ(x) by a forest-kernel
    weighted residual-on-residual regression with weights
    αᵢ(x) = mean_t 1{i ∈ leaf_t(x)}/|leaf_t(x)|. Per tree that is a
    gather of the leaf statistics followed by a normalize-and-average —
    pure bandwidth, batched over all query rows at once.
  * **Bootstrap of little bags**: trees are grown in groups of
    ``ci_group_size`` sharing one half-sample subsample; the CATE
    variance is estimated as V_between − V_within/k over the groups
    (grf's "bootstrap of little bags", truncated at zero).
  * **Tree parallelism**: groups are vmapped in chunks under ``lax.map``
    (bounded memory); the chunk axis is the mesh's tree/expert axis
    (SURVEY.md §2.4).

``average_treatment_effect`` is the grf ≤0.10 ``estimate_average_effect``
equivalent: AIPW over the forest's own nuisances with the influence-
function SE sd(Γ)/√n.
"""

from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ate_replication_causalml_tpu.data.frame import CausalFrame
from ate_replication_causalml_tpu.models.forest import (
    _meter_hist_dispatches,
    apply_trees_chunked,
    route_rows_packed,
    auto_tree_chunk,
    bin_onehot,
    binarize,
    dispatch_tree_target,
    exact_subsample_mask,
    fit_forest_regressor,
    forest_oob_mean,
    hist_partition_active,
    plan_host_dispatch,
    plan_tree_dispatch,
    quantile_bins,
    resolve_hist_backend,
    route_rows,
    select_split,
    streaming_level_loop,
)
from ate_replication_causalml_tpu.ops.hist_pallas import (
    bin_histogram,
    bin_histogram_shared,
    mode_for_width,
    node_sums_shared,
    resolve_hist_mode_packed,
)
from ate_replication_causalml_tpu.ops.linalg import _PREC
from ate_replication_causalml_tpu.ops.pack import (
    pack_codes,
    packable,
    resolve_predict_pack,
)
from ate_replication_causalml_tpu.ops.tree_pallas import (
    codes_transposed,
    route_bits,
    table_lookup,
)
from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.parallel.mesh import shard_map as _shard_map
from ate_replication_causalml_tpu.parallel.retry import require_all, run_shards

_EPS = 1e-12


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CausalForest:
    """A fitted honest causal forest.

    Split layout matches :class:`~..models.forest.Forest` (level-wise,
    children of node k are 2k/2k+1; frozen nodes route everything LEFT).
    ``leaf_stats`` holds the honest (J-half) sufficient statistics per
    depth-D leaf: [count, Σw̃, Σỹ, Σw̃², Σw̃ỹ]. ``in_sample`` marks rows a
    tree saw (either half) — OOB prediction excludes them.
    """

    split_feat: jax.Array   # (T, D, max_nodes) int32
    split_bin: jax.Array    # (T, D, max_nodes) int32
    leaf_stats: jax.Array   # (T, 2^D, 5) float32
    in_sample: jax.Array    # (T, n) bool
    bin_edges: jax.Array    # (p, n_bins-1)
    # Little-bag size the trees were grown with — predictions must group
    # the tree axis the same way, so it travels with the forest (static:
    # it shapes the prediction computation).
    ci_group_size: int = dataclasses.field(metadata=dict(static=True), default=2)

    @property
    def n_trees(self) -> int:
        return self.split_feat.shape[0]

    @property
    def depth(self) -> int:
        return self.split_feat.shape[1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FittedCausalForest:
    """Causal forest + the nuisance estimates it was centered on, bound
    to its training data (the reference predicts on the training set,
    ``ate_replication.Rmd:259``)."""

    forest: CausalForest
    y_hat: jax.Array   # (n,) OOB E[Y|X]
    w_hat: jax.Array   # (n,) OOB E[W|X] — the propensity
    x: jax.Array
    y: jax.Array
    w: jax.Array


class CatePredictions(NamedTuple):
    cate: jax.Array       # τ̂(x) per row
    variance: jax.Array   # little-bags variance estimate per row


class AverageEffect(NamedTuple):
    estimate: jax.Array
    std_err: jax.Array


def _moments_stack(wt: jax.Array, yt: jax.Array) -> jax.Array:
    """(n, 5) per-row sufficient-statistic stack [1, w̃, ỹ, w̃², w̃ỹ]."""
    ones = jnp.ones_like(wt)
    return jnp.stack([ones, wt, yt, wt * wt, wt * yt], axis=1)


def _node_tau(mom: jax.Array):
    """Per-node (w̄, ȳ, τ) from the 5-moment matrix (nodes, 5)."""
    c, sw, sy, sww, swy = (mom[:, i] for i in range(5))
    wbar = sw / jnp.maximum(c, 1.0)
    ybar = sy / jnp.maximum(c, 1.0)
    varw = c * sww - sw * sw
    tau = jnp.where(varw > _EPS, (c * swy - sw * sy) / jnp.maximum(varw, _EPS), 0.0)
    return wbar, ybar, tau


def grow_causal_forest(
    x: jax.Array,
    wt: jax.Array,
    yt: jax.Array,
    key: jax.Array,
    n_trees: int = 2000,
    depth: int = 8,
    mtry: int | None = None,
    n_bins: int = 64,
    min_node: int = 5,
    sample_fraction: float = 0.5,
    ci_group_size: int = 2,
    honesty: bool = True,
    group_chunk: int | None = None,
    hist_backend: str = "auto",
    hist_mode: str | None = None,
) -> CausalForest:
    """Grow the causal forest on *centered* treatment/outcome residuals.

    ``n_trees`` is rounded up to a multiple of ``ci_group_size``; each
    group of trees shares one without-replacement half-sample
    (``sample_fraction`` of rows), and every tree splits its sample into
    honest I (grow) / J (estimate) halves.

    ``hist_mode`` (ISSUE 10): dense | partition | auto kernel
    formulation per level width; defaults to the ``ATE_TPU_HIST_MODE``
    policy, resolved here at config time.
    """
    n, p = x.shape
    if mtry is None:
        # grf's default: min(ceil(sqrt(p) + 20), p)
        mtry = min(int(np.ceil(np.sqrt(p))) + 20, p)
    mtry = min(mtry, p)
    k = ci_group_size
    n_groups = -(-n_trees // k)
    # 'auto' keeps the five ρ-decomposition channels in FULL f32: the
    # lossy-bf16 upgrade (resolve_hist_backend(allow_lossy_bf16=True))
    # was measured at ≤1% post-transpose — the kernel is not MXU-bound —
    # so the input rounding buys nothing. Explicit "pallas_bf16" remains
    # available.
    hist_backend = resolve_hist_backend(hist_backend, n_rows=n, n_bins=n_bins)
    hist_mode = resolve_hist_mode_packed(hist_mode, n_bins)
    edges = quantile_bins(x, n_bins)
    codes = binarize(x, edges)
    xb_onehot = bin_onehot(codes, n_bins) if hist_backend == "onehot" else None
    mom_stack = _moments_stack(wt, yt)  # (n, 5)
    s = max(2, int(n * sample_fraction))

    # The honest-leaf payload contraction builds a (rows, 2^depth)
    # one-hot, and the 'onehot' backend streams full-n rows (mask path)
    # rather than the s-row subsample. An explicitly requested chunk is
    # clamped to the same HBM budget — a chunk that fit the round-1
    # segment_sum path can OOM the one-hot formulation. The streaming
    # (Pallas) backends also run mask mode but have no leaf one-hot and
    # route row-blocked, so their chunk follows the kernel tree cap
    # (5 ρ-decomposition channels — see grow_one_streaming).
    streaming = hist_backend.startswith("pallas")
    chunk_rows = n if (hist_backend == "onehot" or streaming) else s
    auto_chunk = auto_tree_chunk(
        chunk_rows, depth, cap=16, trees_per_unit=k,
        leaf_onehot=not streaming, streaming=streaming, p=p, n_bins=n_bins,
        kernel_weights=5, hist_floor=1,
        hist_partition=streaming
        and hist_partition_active(hist_mode, depth, 1, 5, p, n_bins),
    )
    group_chunk = auto_chunk if group_chunk is None else min(group_chunk, auto_chunk)
    # Superchunking (see forest.py::_DISPATCH_CHUNK_TARGET): several
    # vmapped group chunks per dispatch via an inner lax.map — the
    # remote tunnel charges ~80 ms per dispatched executable, which
    # dominates a chunk-per-dispatch loop at million-row auto chunks.
    # Ceil-padded plan: executable shape independent of n_trees (see
    # forest.py::plan_host_dispatch).
    group_chunk, super_, n_disp = plan_host_dispatch(
        n_groups, group_chunk,
        max(1, dispatch_tree_target(chunk_rows) // k),
    )
    group_keys = jax.random.split(key, n_disp * super_ * group_chunk)

    # Elastic host loop over one compiled chunk executable (shared
    # across chunks and fits): bounded device-program size, and a
    # transient device failure re-runs only that dispatch (keys are
    # explicit, so the retry is bit-identical — parallel/retry.py).
    def chunk_shard(i: int):
        # One collapsed tree-batched kernel call per (level × vmapped
        # chunk) — the nested group×tree vmaps flatten through the
        # custom_vmap rule; metered per issued dispatch.
        _meter_hist_dispatches(
            "causal", hist_backend, hist_mode, depth, 1,
            super_, 5, p, n_bins,
        )
        kk = group_keys[
            i * super_ * group_chunk : (i + 1) * super_ * group_chunk
        ].reshape(super_, group_chunk)
        return _grow_cf_chunk(
            kk,
            codes, wt, yt, mom_stack, xb_onehot,
            depth=depth, mtry=mtry, n_bins=n_bins, min_node=min_node,
            s=s, k=k, honesty=honesty, hist_backend=hist_backend,
            hist_mode=hist_mode,
        )

    chunks = require_all(
        run_shards(
            obs.instrument_dispatch("causal_forest", chunk_shard),
            n_disp,
            pool="causal_forest",
        )
    )
    flat = lambda j: jnp.concatenate(
        [c[j].reshape((-1,) + c[j].shape[2:]) for c in chunks], axis=0
    )[: n_groups * k]
    return CausalForest(
        split_feat=flat(0),
        split_bin=flat(1),
        leaf_stats=flat(2),
        in_sample=flat(3),
        bin_edges=edges,
        ci_group_size=k,
    )


def grow_causal_forest_sharded(
    x: jax.Array,
    wt: jax.Array,
    yt: jax.Array,
    key: jax.Array,
    mesh,
    n_trees: int = 2000,
    depth: int = 8,
    mtry: int | None = None,
    n_bins: int = 64,
    min_node: int = 5,
    sample_fraction: float = 0.5,
    ci_group_size: int = 2,
    honesty: bool = True,
    axis_name: str = "tree",
    group_chunk: int | None = None,
    hist_backend: str = "auto",
    hist_mode: str | None = None,
) -> CausalForest:
    """Mesh-parallel causal-forest grow: little-bag groups shard over the
    mesh's tree axis (SURVEY.md §2.4 — the expert-parallel analogue of
    grf's std::thread tree growing, ``ate_replication.Rmd:250-255``).

    Every device grows its own slice of the group-key array with the
    same per-chunk executable as the host loop (``_grow_cf_chunk``), so
    per-device HBM stays bounded by one vmapped chunk and the per-device
    groups of one dispatch are capped by ``dispatch_tree_target`` (one
    dispatch's wall-clock is per-DEVICE work — an uncapped 1M-row grow
    would run minutes inside one executable). Numbers are NOT identical
    to :func:`grow_causal_forest` (keys partition differently across
    devices) but the forest is statistically equivalent — asserted in
    tests/test_parallel.py.
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    n, p = x.shape
    if mtry is None:
        mtry = min(int(np.ceil(np.sqrt(p))) + 20, p)
    mtry = min(mtry, p)
    k = ci_group_size
    n_groups = -(-n_trees // k)
    s = max(2, int(n * sample_fraction))
    if hist_backend == "onehot":
        raise ValueError(
            "hist_backend='onehot' is not supported on the sharded path "
            "(the shared bin one-hot is not built here); use 'auto'/'xla'/'pallas'"
        )
    hist_backend = resolve_hist_backend(
        hist_backend, allow_onehot=False, n_rows=n, n_bins=n_bins
    )
    hist_mode = resolve_hist_mode_packed(hist_mode, n_bins)
    axis_size = mesh.shape[axis_name]
    per_dev_groups = -(-n_groups // axis_size)
    streaming = hist_backend.startswith("pallas")
    plan_rows = n if streaming else s  # mask mode streams full n
    auto_chunk, chunks_per_disp, n_disp = plan_tree_dispatch(
        plan_rows, depth, per_dev_groups, cap=16, trees_per_unit=k,
        leaf_onehot=not streaming, streaming=streaming, p=p, n_bins=n_bins,
        kernel_weights=5, hist_floor=1,
        hist_partition=streaming
        and hist_partition_active(hist_mode, depth, 1, 5, p, n_bins),
    )
    if group_chunk is not None and group_chunk < auto_chunk:
        # An explicit (smaller) chunk re-plans the dispatch split so the
        # watchdog budget still holds per dispatched executable.
        group_chunk, chunks_per_disp, n_disp = plan_host_dispatch(
            per_dev_groups, group_chunk,
            max(1, dispatch_tree_target(plan_rows) // k),
        )
    else:
        group_chunk = auto_chunk
    per_disp_dev = chunks_per_disp * group_chunk

    edges = quantile_bins(x, n_bins)
    codes = binarize(x, edges)
    mom_stack = _moments_stack(wt, yt)
    group_keys = jax.random.split(
        key, n_disp * axis_size * per_disp_dev
    ).reshape(n_disp, axis_size * per_disp_dev)

    grow = _sharded_cf_grow_fn(
        mesh, axis_name, chunks_per_disp, group_chunk,
        depth=depth, mtry=mtry, n_bins=n_bins, min_node=min_node,
        s=s, k=k, honesty=honesty, hist_backend=hist_backend,
        hist_mode=hist_mode,
    )
    key_sharding = NamedSharding(mesh, P(axis_name))

    def dispatch(i: int):
        # Every device runs its own per-device chunks — the meter
        # counts kernel calls across the mesh, per issued dispatch.
        _meter_hist_dispatches(
            "causal", hist_backend, hist_mode, depth, 1,
            chunks_per_disp * axis_size, 5, p, n_bins,
        )
        return grow(
            jax.device_put(group_keys[i], key_sharding), codes, wt, yt, mom_stack
        )

    parts = require_all(
        run_shards(
            obs.instrument_dispatch("causal_forest_sharded", dispatch),
            n_disp,
            pool="causal_forest_sharded",
        )
    )
    flat = lambda j: jnp.concatenate(
        [c[j].reshape((-1,) + c[j].shape[2:]) for c in parts], axis=0
    )[: n_groups * k]
    return CausalForest(
        split_feat=flat(0),
        split_bin=flat(1),
        leaf_stats=flat(2),
        in_sample=flat(3),
        bin_edges=edges,
        ci_group_size=k,
    )


@functools.lru_cache(maxsize=64)
def _sharded_cf_grow_fn(mesh, axis_name, chunks_per_disp, group_chunk, *,
                        depth, mtry, n_bins, min_node, s, k, honesty,
                        hist_backend, hist_mode="dense"):
    """The jitted shard_map causal-grow executable, cached on (mesh,
    plan, statics) — same reason as forest.py::_sharded_grow_fn: a
    per-call `jax.jit(shard_map(local_lambda))` re-traced and
    re-compiled every fit."""
    from jax.sharding import PartitionSpec as P

    def device_body(keys, codes, wt, yt, mom_stack):
        return _grow_cf_chunk(
            keys.reshape(chunks_per_disp, group_chunk),
            codes, wt, yt, mom_stack, None,
            depth=depth, mtry=mtry, n_bins=n_bins, min_node=min_node,
            s=s, k=k, honesty=honesty, hist_backend=hist_backend,
            hist_mode=hist_mode,
        )

    return jax.jit(_shard_map(
        device_body,
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P(), P()),
        out_specs=P(axis_name),
    ))


@functools.partial(
    jax.jit,
    static_argnames=("depth", "mtry", "n_bins", "min_node", "s", "k",
                     "honesty", "hist_backend", "hist_mode"),
)
def _grow_cf_chunk(group_keys, codes, wt, yt, mom_stack, xb_onehot, *,
                   depth, mtry, n_bins, min_node, s, k, honesty, hist_backend,
                   hist_mode="dense"):
    """One compiled dispatch of little-bag groups, k trees per group
    sharing a half-sample. ``group_keys`` is (gc,) for one vmapped
    chunk or (S, gc) for a superchunk (S chunks sequentially under
    lax.map — one dispatch, memory of one chunk). Module-level jit —
    shared executable."""
    n, p = codes.shape
    max_nodes = 1 << (depth - 1)
    n_leaves = 1 << depth
    if hist_backend.startswith("pallas"):
        # Shared routing operand for the Pallas route kernel — the
        # streaming growers always run mask mode on the shared full-n
        # codes, so one transpose serves every group/tree/level.
        codes_t = codes_transposed(codes)
        # The ONE weight stack every tree's histograms share (round 5):
        # (5, n) channel-major moment rows for the kernel's (K, tile)
        # weight blocks. Membership is per-tree but rides in the id
        # stream, not here.
        mom5 = mom_stack.T

    def grow_one_streaming(codes_g, mom5, grow_mask, est_mask, split_key):
        """Streaming (Pallas) grow: the ρ-decomposed level pipeline.

        GRF's pseudo-outcome is a per-NODE linear combination of five
        level-invariant row quantities:

          ρ = w̃ỹ − w̄ỹ − ȳw̃ + w̄ȳ − τ(w̃² − 2w̄w̃ + w̄²)

        so Σ_cell gw·ρ composes from the histograms of the five channels
        gw·[1, w̃, ỹ, w̃², w̃ỹ] with per-node coefficients (w̄, ȳ, τ).
        Compared to the direct formulation (per-level moments matmul,
        (w̄,ȳ,τ) broadcast, per-row ρ, then a 2-channel histogram) this
        needs ONE kernel call per level and no other row sweeps, the
        channels are level-invariant so sibling subtraction halves the
        kernel matmul (impossible for the direct ρ channel, which
        changes every level), per-node moments fall out of the
        histogram's bin marginal for free, and the honest-leaf payload
        is a node-sum kernel call instead of a (rows, 2^depth) one-hot
        contraction. Routing is row-blocked (route_rows_blocked), so no
        (rows, M) one-hot ever reaches HBM — which lets whole chunks of
        little-bag groups share one codes stream and batch through the
        kernel's tree axis (ops/hist_pallas.py::_pallas_batched_vmappable).

        Round 5 (VERDICT r4 #3): the grow/estimate membership weights
        gw, ew are 0/1 masks, so ``gw·channels`` ≡ "drop non-member
        rows" — which the kernel id stream already expresses with the
        −1 sentinel. Membership therefore rides in the ids
        (``where(grow_mask, ids, −1)``), and the weight stack is the
        RAW per-row moment stack ``mom5`` — identical for every tree —
        through the shared-weights kernel (bin_histogram_shared): the
        per-tree (5, n) channel products, the honest ew products, and
        the kernel's (T·5, n) weight DMA all disappear. Histograms are
        bit-identical (1·mom ≡ mom, masked-id ≡ 0·mom — asserted in
        tests/test_hist_pallas.py).

        Numerically safe because w̃, ỹ are locally-centered residuals
        (means ≈ 0 by construction — fit_causal_forest always passes
        w−ŵ, y−ŷ), so the uncentered channel sums carry no catastrophic
        cancellation. Split selection is algebraically identical to the
        direct path; f32 rounding can flip exact ties only
        (equivalence asserted statistically in tests).
        """
        p_feat = codes_g.shape[1]

        def tables_fn(hist, level, perm):
            # Per-node totals = the bin marginal of any one feature.
            mom_nodes = hist[:, :, 0, :].sum(axis=2).T        # (m, 5)
            wbar, ybar, tau = _node_tau(mom_nodes)
            s_cum = jnp.cumsum(hist, axis=3)                   # (5, m, p, b)
            bc = lambda v: v[:, None, None]
            cl = s_cum[0]
            rl = (
                s_cum[4]
                - bc(wbar) * s_cum[2]
                + bc(2.0 * tau * wbar - ybar) * s_cum[1]
                + bc(wbar * ybar - tau * wbar * wbar) * s_cum[0]
                - bc(tau) * s_cum[3]
            )
            ct, rt = cl[:, :, -1:], rl[:, :, -1:]
            cr, rr = ct - cl, rt - rl
            score = -(
                rl * rl / jnp.maximum(cl, _EPS) + rr * rr / jnp.maximum(cr, _EPS)
            )
            score = jnp.where((cl >= min_node) & (cr >= min_node), score, jnp.inf)
            return select_split(
                score, split_key[level], 1 << level, p_feat, n_bins, mtry,
                perm=perm,
            )

        feats, bins, node_int = streaming_level_loop(
            codes_g, depth, n_bins,
            # Per-WIDTH kernel mode (ISSUE 10): hist_mode is a config-
            # time-resolved jit static; each width compiles in exactly
            # one mode, reusing the existing instantiation set.
            hist_fn=lambda ids, m: bin_histogram_shared(
                codes_g, jnp.where(grow_mask, ids, -1), mom5,
                max_nodes=m, n_bins=n_bins, backend=hist_backend,
                mode=mode_for_width(hist_mode, m, 5, p, n_bins),
            ),
            tables_fn=tables_fn,
            route_fn=lambda ids, bf, bb: route_bits(
                codes_t, ids, bf, bb,
                backend=(
                    "pallas_interpret"
                    if hist_backend == "pallas_interpret" else "pallas"
                ),
            ),
        )
        # Leaf payloads feed predictions directly — keep them full f32
        # even when the split search runs the lossy-bf16 kernel (the
        # payload is one node-sum call per tree, not the bottleneck).
        leaf_backend = "pallas" if hist_backend == "pallas_bf16" else hist_backend
        leaf_stats = node_sums_shared(
            jnp.where(est_mask, node_int, -1), mom5, n_leaves,
            backend=leaf_backend,
        )  # (L, 5)
        return feats, bins, leaf_stats

    def grow_one(codes_g, wt_g, yt_g, mom_g, oh_g, base, idx, tree_key):
        """Grow one honest tree.

        Dispatch (see ``grow_group``): the 'onehot' AND streaming
        (pallas) backends run MASK mode — rows stay full-n, ``base`` is
        the subsample mask, ``idx=None`` — because their shared operands
        (the (n, p·n_bins) one-hot / the kernel codes stream and the
        chunk-level ``codes_t`` route operand) must stay shared across
        vmapped groups; gathering would copy them per group AND
        misalign the full-n route operand. Only the 'xla' backend
        gathers the group's s-row half-sample (``idx``), with ``base``
        all-ones. The honesty Bernoulli is always drawn in full-n row
        space and gathered, so every backend sees the same honest
        partition from the same key.
        """
        rows = codes_g.shape[0]
        streaming = hist_backend.startswith("pallas")
        if honesty:
            bern_full = jax.random.bernoulli(tree_key, 0.5, (n,)).astype(jnp.float32)
            bern = bern_full if idx is None else bern_full[idx]
            if streaming:
                # Membership rides in the kernel id stream (boolean
                # masks; no per-tree f32 weight vectors — see
                # grow_one_streaming). Same bernoulli draw, same key:
                # the RNG stream and the resulting splits are
                # bit-unchanged.
                base_b = base > 0.0
                bern_b = bern > 0.0
                grow_mask = base_b & bern_b
                est_mask = base_b & ~bern_b
            else:
                gw = base * bern
                ew = base * (1.0 - bern)
        elif streaming:
            grow_mask = est_mask = base > 0.0
        else:
            gw = ew = base
        # FROZEN RNG stream (graftlint JGL002 would be right for new
        # code): the honesty bernoulli spends tree_key directly and the
        # level keys drop split slot 0 — replays of the original
        # key-threading whose draws the goldens and the grf parity
        # suite pin bit-for-bit. Rethreading would orphan every golden.
        split_key = jax.random.split(tree_key, depth + 1)[1:]  # graftlint: disable=JGL002
        if streaming:
            return grow_one_streaming(
                codes_g, mom5, grow_mask, est_mask, split_key
            )

        def level_step(node_of_row, lk, level_nodes):
            # TPU-first level pipeline: every per-node → per-row lookup
            # runs through ONE (rows, M) node one-hot and MXU matmuls —
            # per-row dynamic gathers (wbar[node], bf[node], …) serialize
            # on TPU and measured ~2/3 of tree wall-clock; the matmul
            # broadcast is two orders of magnitude cheaper.
            node_oh = jax.nn.one_hot(node_of_row, level_nodes, dtype=jnp.float32)
            # Per-node moments: (M, rows) @ (rows, 5) — segment_sum is a
            # serialized scatter-add on TPU.
            mom = jnp.matmul(
                node_oh.T, gw[:, None] * mom_g, precision=_PREC
            )  # (M, 5)
            wbar, ybar, tau = _node_tau(mom)
            # Broadcast (w̄, ȳ, τ) of each row's node: (rows, M) @ (M, 3).
            row_nt = jnp.matmul(
                node_oh, jnp.stack([wbar, ybar, tau], axis=1), precision=_PREC
            )
            wc = wt_g - row_nt[:, 0]
            yc = yt_g - row_nt[:, 1]
            rho = wc * (yc - wc * row_nt[:, 2])

            if hist_backend == "onehot":
                gw_oh = node_oh * gw[:, None]
                hist_c = jnp.matmul(gw_oh.T, oh_g, precision=_PREC).reshape(
                    level_nodes, p, n_bins
                )
                hist_r = jnp.matmul(
                    (gw_oh * rho[:, None]).T, oh_g, precision=_PREC
                ).reshape(level_nodes, p, n_bins)
            else:
                hist_c, hist_r = bin_histogram(
                    codes_g,
                    node_of_row,
                    jnp.stack([gw, gw * rho]),
                    max_nodes=level_nodes,
                    n_bins=n_bins,
                    backend=hist_backend,
                )

            cl = jnp.cumsum(hist_c, axis=2)
            rl = jnp.cumsum(hist_r, axis=2)
            ct, rt = cl[:, :, -1:], rl[:, :, -1:]
            cr, rr = ct - cl, rt - rl
            # Heterogeneity criterion: maximize Σ_child (Σρ)²/c — the
            # regression-split score on the pseudo-outcome.
            score = -(
                rl * rl / jnp.maximum(cl, _EPS) + rr * rr / jnp.maximum(cr, _EPS)
            )
            score = jnp.where((cl >= min_node) & (cr >= min_node), score, jnp.inf)
            best_feat, best_bin = select_split(
                score, lk, level_nodes, p, n_bins, mtry
            )
            node_of_row = route_rows(
                node_oh, best_feat, best_bin, codes_g.astype(jnp.float32), node_of_row
            )
            return node_of_row, (best_feat, best_bin)

        # Unrolled levels: level l computes moments/histograms only for
        # its 2^l live nodes (a scan would pad every level to the final
        # width — ~depth/2× wasted FLOPs). Split tables pad to max_nodes.
        node_of_row = jnp.zeros(rows, jnp.int32)
        feats_l, bins_l = [], []
        for level in range(depth):
            level_nodes = min(1 << level, max_nodes)
            node_of_row, (bf, bb) = level_step(
                node_of_row, split_key[level], level_nodes
            )
            pad = max_nodes - level_nodes
            feats_l.append(jnp.pad(bf, (0, pad)))
            bins_l.append(jnp.pad(bb, (0, pad), constant_values=n_bins - 1))
        feats = jnp.stack(feats_l)
        bins = jnp.stack(bins_l)
        # Honest leaf payloads as one more (L, rows) @ (rows, 5) one-hot
        # matmul (a TPU segment_sum lowers to a serialized scatter-add).
        leaf_oh = jax.nn.one_hot(node_of_row, n_leaves, dtype=jnp.float32)
        leaf_stats = jnp.matmul(
            leaf_oh.T, ew[:, None] * mom_g, precision=_PREC
        )  # (L, 5)
        return feats, bins, leaf_stats

    def grow_group(group_key):
        sk, tk = jax.random.split(group_key)
        # Exact s-of-n half-sample via the order-statistic mask (round
        # 4): kills the permutation's payload sort + 500k-row scatter
        # (~3.5 ms/tree of the 1M grow). The gather path derives its
        # index vector from the SAME mask (ascending row order — order
        # is statistically irrelevant and every backend sees the same
        # subsample from the same key).
        in_mask = exact_subsample_mask(sk, n, s)
        tree_keys = jax.random.split(tk, k)
        vone = jax.vmap(
            grow_one, in_axes=(None, None, None, None, None, None, None, 0)
        )
        if hist_backend == "onehot" or hist_backend.startswith("pallas"):
            # Mask mode: every tree streams the SHARED full-n codes with
            # subsample-masked weights. For the streaming backends this
            # is what lets a whole chunk of little-bag groups collapse
            # into tree-batched kernel calls (per-group gathered codes
            # would fence batching at k trees); the honest partition is
            # identical either way (same keys, same in_mask).
            feats, bins, stats = vone(
                codes, wt, yt, mom_stack, xb_onehot,
                in_mask.astype(jnp.float32), None, tree_keys,
            )
        else:
            idx = jnp.nonzero(in_mask, size=s)[0]
            feats, bins, stats = vone(
                codes[idx], wt[idx], yt[idx], mom_stack[idx], None,
                jnp.ones((s,), jnp.float32), idx, tree_keys,
            )
        return feats, bins, stats, jnp.broadcast_to(in_mask, (k, n))

    if group_keys.ndim == 1:
        return jax.vmap(grow_group)(group_keys)
    out = lax.map(lambda kk: jax.vmap(grow_group)(kk), group_keys)  # (S, gc, …)
    return jax.tree_util.tree_map(
        lambda a: a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:]), out
    )


def fit_causal_forest(
    frame: CausalFrame,
    key: jax.Array | None = None,
    n_trees: int = 2000,
    depth: int = 8,
    nuisance_trees: int = 500,
    nuisance_depth: int = 9,
    hist_backend: str = "auto",
    hist_mode: str | None = None,
    mesh=None,
    axis_name: str = "tree",
    **grow_kwargs,
) -> FittedCausalForest:
    """End-to-end grf-equivalent fit: OOB nuisance forests for Ŷ, Ŵ,
    then the honest causal forest on the residuals
    (``ate_replication.Rmd:250-255``).

    With ``mesh`` given, both nuisance fits and the causal grow shard
    trees/little-bag groups over the mesh's ``axis_name`` axis — the
    whole flagship fit scales across chips (grf scales the same work
    across std::threads)."""
    if key is None:
        key = jax.random.key(12345)  # the seed grf is given (Rmd:255)
    ky, kw, kc = jax.random.split(key, 3)
    x, w, y = frame.x, frame.w, frame.y
    if mesh is not None:
        if hist_backend == "onehot":
            raise ValueError(
                "hist_backend='onehot' is single-device only (the shared "
                "bin one-hot is not built on the sharded path); use "
                "'auto', 'xla' or 'pallas' with a mesh"
            )
        from ate_replication_causalml_tpu.models.forest import (
            fit_forest_regressor_sharded,
        )

        fit_reg = functools.partial(
            fit_forest_regressor_sharded, mesh=mesh, axis_name=axis_name,
            n_trees=nuisance_trees, depth=nuisance_depth,
            hist_backend=hist_backend, hist_mode=hist_mode,
        )
    else:
        fit_reg = functools.partial(
            fit_forest_regressor, n_trees=nuisance_trees, depth=nuisance_depth,
            hist_backend=hist_backend, hist_mode=hist_mode,
        )
    fy = fit_reg(x, y, ky)
    y_hat = forest_oob_mean(fy, x)
    # Free each nuisance forest as soon as its OOB estimates exist: the
    # (T, n) train_leaf/counts arrays are multi-GB at the million-row
    # scale and the causal grow needs the headroom.
    del fy
    fw = fit_reg(x, w, kw)
    w_hat = forest_oob_mean(fw, x)
    del fw
    if mesh is not None:
        forest = grow_causal_forest_sharded(
            x, w - w_hat, y - y_hat, kc, mesh, n_trees=n_trees, depth=depth,
            axis_name=axis_name, hist_backend=hist_backend,
            hist_mode=hist_mode, **grow_kwargs,
        )
    else:
        forest = grow_causal_forest(
            x, w - w_hat, y - y_hat, kc, n_trees=n_trees, depth=depth,
            hist_backend=hist_backend, hist_mode=hist_mode, **grow_kwargs,
        )
    return FittedCausalForest(forest=forest, y_hat=y_hat, w_hat=w_hat, x=x, y=y, w=w)


def _tree_route(feats, bins, codes, depth, packed=None):
    """Leaf index of every query row down one tree: (n,) int32.

    Per-level one-hot matmuls, not gathers: per-row dynamic gathers
    serialize on TPU (measured ~2/3 of forest wall-clock before the
    grow loop was converted the same way). All quantities are small
    ints in f32, so comparisons are exact.

    ``packed`` (ISSUE 12): the caller's shared :func:`pack_codes`
    operand — when given, every level routes through the 3×-narrower
    packed contraction (``route_rows_packed``; bit-identical routing).
    """
    rows = codes.shape[0]
    codes_f = codes.astype(jnp.float32)
    node = jnp.zeros(rows, jnp.int32)
    for level in range(depth):
        m = 1 << level
        node_oh = jax.nn.one_hot(node, m, dtype=jnp.float32)
        if packed is not None:
            node = route_rows_packed(
                node_oh, feats[level][:m], bins[level][:m], packed, node
            )
        else:
            node = route_rows(
                node_oh, feats[level][:m], bins[level][:m], codes_f, node
            )
    return node


def _tree_route_stream(feats, bins, codes_t, depth, backend="pallas"):
    """:func:`_tree_route` on the Pallas route kernel — same integer
    selections bit-for-bit, no (rows, M) one-hot in HBM. ``codes_t`` is
    the shared :func:`codes_transposed` operand. Vmapping over trees
    collapses into tree-batched kernel calls per level. Levels keep
    their exact table widths: the uniform-floor padding that pays for
    itself on the K=2 grow kernels (models/forest.py::_HIST_M_FLOOR)
    measured +0.2 s steady for −1 s cold here — not worth it on the
    per-fit predict path."""
    rows = codes_t.shape[1]
    node = jnp.zeros(rows, jnp.int32)
    for level in range(depth):
        m = 1 << level
        node = node * 2 + route_bits(
            codes_t, node, feats[level][:m], bins[level][:m], backend=backend
        )
    return node


def _resolve_pack_for(forest: CausalForest, pack) -> bool:
    """Config-time pack resolution for one forest: the policy
    (``ATE_TPU_PREDICT_PACK`` or an explicit argument) AND the 7-bit
    exactness bound — a forest binned wider than 128 keeps the
    identical unpacked path silently (ops/pack.py::packable)."""
    return resolve_predict_pack(pack) and packable(
        int(forest.bin_edges.shape[1]) + 1
    )


def _leaf_index_dtype(depth: int):
    # Leaf ids are < 2^depth: store the (T, n) cache in the smallest
    # integer type (int32 would be 8 GB at 2000 trees × 1M rows — the
    # exact scale the cache exists for).
    return jnp.uint8 if depth <= 8 else (
        jnp.int16 if depth <= 15 else jnp.int32
    )


@functools.partial(
    jax.jit, static_argnames=("tree_chunk", "row_chunk", "pack")
)
def _compute_leaf_index_impl(
    forest: CausalForest, x: jax.Array, tree_chunk: int, row_chunk: int,
    pack: bool,
) -> jax.Array:
    codes = binarize(x, forest.bin_edges)
    depth = forest.depth
    return apply_trees_chunked(
        forest.split_feat, forest.split_bin, codes, depth,
        post=lambda node, _: node.astype(_leaf_index_dtype(depth)),
        tree_chunk=tree_chunk, row_chunk=row_chunk, pack=pack,
    )


def compute_leaf_index(
    forest: CausalForest, x: jax.Array, tree_chunk: int = 32,
    row_chunk: int = 65536, pack: bool | str | None = None,
) -> jax.Array:
    """Per-(tree, row) leaf indices for a fixed query matrix: (T, n).

    Routing is the only per-tree traversal in CATE scoring; everything
    else is contractions and reductions. Precomputing it once per
    (forest, dataset) makes every further
    ``predict_cate(..., leaf_index=...)`` call — repeated scoring of the
    same rows, oob or not — routing-free (NEXT.md round-1 #6). Rows are
    processed in ``row_chunk`` blocks so the per-level (rows, nodes)
    one-hots stay bounded at the million-row scale, exactly as in
    :func:`predict_cate`.

    An un-jitted dispatcher (the JGL001 discipline): ``pack``
    (``ATE_TPU_PREDICT_PACK`` when None — ISSUE 12's 3×-fewer-MAC
    packed routing, bit-identical output) resolves HERE on the host and
    enters the jitted body as a static.
    """
    return _compute_leaf_index_impl(
        forest, x, tree_chunk, row_chunk, _resolve_pack_for(forest, pack)
    )


@functools.lru_cache(maxsize=32)
def _sharded_leaf_index_fn(mesh, axis_name, tree_chunk, row_chunk, pack):
    """The jitted shard_map leaf-index executable, cached on
    (mesh, plan, statics) like ``_sharded_cf_grow_fn`` — per-call
    re-wrapping would re-trace every rotation."""
    from jax.sharding import PartitionSpec as P

    def device_body(forest, xs):
        # Rows are independent: each device routes ITS row slice with
        # the exact integer selections — identical bytes to the serial
        # build's same columns, whatever the blocking.
        codes = binarize(xs, forest.bin_edges)
        depth = forest.depth
        return apply_trees_chunked(
            forest.split_feat, forest.split_bin, codes, depth,
            post=lambda node, _: node.astype(_leaf_index_dtype(depth)),
            tree_chunk=tree_chunk, row_chunk=row_chunk, pack=pack,
        )

    return jax.jit(_shard_map(
        device_body,
        mesh=mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=P(None, axis_name),
    ))


def compute_leaf_index_sharded(
    forest: CausalForest,
    x,
    mesh=None,
    axis_name: str | None = None,
    tree_chunk: int = 32,
    row_chunk: int = 65536,
    pack: bool | str | None = None,
) -> np.ndarray:
    """:func:`compute_leaf_index` as a row-sharded mesh program
    (ISSUE 12, tentpole a — ROADMAP 5a's serial-prefix killer).

    The flagship leaf-index cache build is a pure per-row routing sweep
    — BENCH_r05 measured it at 8.0 s as a SERIAL prefix on every model
    load/rotation. Rows are independent, so the build shards perfectly:
    the query matrix row-shards over the mesh's data axis (padded to a
    shard-divisible row count; jax 0.4.37 rejects uneven shards), every
    device routes its slice through all trees with the same exact
    integer selections, and the (T, n) result assembles column-sharded.
    **Sharded == serial bit-identity (dtype included) holds exactly**
    — routing is integer compares, unaffected by row blocking — and is
    asserted at 1/2/4/8 devices in tier-1.

    Every byte that crosses a layout boundary moves through the
    artifact plane (``parallel/shardio.py``) and is metered into
    ``artifact_transfer_bytes_total{artifact="leaf_index..."}``: one
    upload/reshard of the query rows in, one host gather of the index
    out. Returns the HOST (numpy, read-only) (T, n) index — the form
    the serving fleet stores against a checkpoint; consumers upload it
    with their predict operands (``predict_cate(leaf_index=...)``
    accepts it directly).

    The daemon's rotation path calls this BEFORE the swap instant
    (serving/daemon.py) so a hot-swap binds a warm index instead of
    paying the serial build on the first post-rotation predict.
    """
    from ate_replication_causalml_tpu.parallel import shardio
    from ate_replication_causalml_tpu.parallel.mesh import get_mesh

    mesh = get_mesh() if mesh is None else mesh
    axis_name = axis_name or mesh.axis_names[0]
    d = int(mesh.shape[axis_name])
    n = int(np.shape(x)[0])
    n_pad = -(-n // d) * d
    pack_flag = _resolve_pack_for(forest, pack)
    with obs.span("leaf_index_sharded_build", rows=n, devices=d,
                  trees=forest.n_trees):
        if isinstance(x, jax.Array):
            xp = jnp.pad(x.astype(jnp.float32), ((0, n_pad - n), (0, 0)))
            xs = shardio.reshard(
                xp, shardio.row_sharding(mesh, n_pad, axis_name),
                artifact="leaf_index_x",
            )
        else:
            xp = np.pad(
                np.asarray(x, np.float32), ((0, n_pad - n), (0, 0))
            )
            xs = shardio.commit(
                xp, shardio.row_sharding(mesh, n_pad, axis_name),
                artifact="leaf_index_x",
            )
        li = _sharded_leaf_index_fn(
            mesh, axis_name, tree_chunk, row_chunk, pack_flag
        )(forest, xs)
        host = shardio.gather_host(li, artifact="leaf_index")
    return host[:, :n]


def _grf_df_flag(variance_compat: str) -> jnp.float32:
    """Validate ``variance_compat`` on the host and map it to the
    traced 0/1 df-selector operand of :func:`_predict_cate_impl`."""
    if variance_compat not in ("unbiased", "grf"):
        raise ValueError(
            f"variance_compat must be 'unbiased' or 'grf', got {variance_compat!r}"
        )
    return jnp.float32(variance_compat == "grf")


def _tau_from_sums(S, M):
    """α-weighted residual-on-residual regression from accumulated
    normalized moments S (…, 5) over M valid trees: the 2×2 local
    least-squares solve (intercept + slope) grf performs with forest
    kernel weights. Returns (tau, var) — ``var`` is the pooled Var(w̃)
    under the forest weights, i.e. the (intercept-profiled) Hessian of
    the local moment condition; ``var > _EPS`` is the validity mask."""
    Mc = jnp.maximum(M, 1.0)
    mw, my, mww, mwy = (S[..., i] / Mc for i in (1, 2, 3, 4))
    var = mww - mw * mw
    tau = jnp.where(var > _EPS, (mwy - mw * my) / jnp.maximum(var, _EPS), 0.0)
    return tau, var


def predict_cate(
    forest: CausalForest,
    x: jax.Array,
    oob: bool = True,
    tree_chunk: int = 32,
    row_chunk: int = 65536,
    leaf_index: jax.Array | None = None,
    row_backend: str | None = None,
    variance_compat: str = "unbiased",
    pack: bool | str | None = None,
) -> CatePredictions:
    """Forest-weighted CATE τ̂(x) with little-bags variance. The little-
    bag grouping (``forest.ci_group_size``) travels with the forest.

    ``oob=True`` (training matrix only) excludes each tree's own
    subsample from its contributions — the grf semantics for in-sample
    ``predict(forest)`` (``ate_replication.Rmd:259``).

    ``leaf_index`` — the (T, n) routing from :func:`compute_leaf_index`
    for this exact ``x``: skips tree traversal entirely, so repeated
    scoring of the same rows is one one-hot contraction per tree.
    Results are identical with or without it.

    ``pack`` — the packed-code routing policy (ISSUE 12;
    ``ATE_TPU_PREDICT_PACK`` when None): 3 codes per f32 word through
    the routing contractions, 3× fewer permute MACs, output
    bit-identical either way (matmul row backend; the Pallas row
    kernels have no packed formulation and ignore it).

    Rows are processed in blocks of ``row_chunk`` (rows are independent
    in every aggregation), bounding the (rows, nodes) one-hot operands
    at the million-row scale.

    This entry point is an unjitted dispatcher (graftlint JGL001, the
    same latent bug ADVICE.md r5 flagged on ``quantile_bins``): with
    the jitted body resolving ``row_backend=None`` from
    ``jax.default_backend()`` at trace time, the cache entry was keyed
    on ``None`` — a backend change after the first call would silently
    reuse the stale kernel path. The backend is now resolved on the
    host on every call and enters the jitted implementation as a
    concrete static argument.
    """
    # On TPU the per-row stages run the Pallas row kernels
    # (ops/tree_pallas.py): routing without the per-level (rows, M)
    # one-hot, leaf-payload broadcast without the (rows, L) one-hot.
    # Both are exact integer/one-nonzero selections — identical output
    # to the matmul formulations (the CPU/test path below).
    # ``row_backend``: None = auto ("pallas" on TPU, matmul elsewhere);
    # "pallas_interpret" lets CPU tests exercise the kernel path;
    # "matmul" forces the one-hot formulation anywhere.
    if row_backend is None:
        row_backend = "pallas" if jax.default_backend() == "tpu" else "matmul"
    if row_backend not in ("pallas", "pallas_interpret", "matmul"):
        raise ValueError(
            "row_backend must be 'pallas', 'pallas_interpret' or 'matmul', "
            f"got {row_backend!r}"
        )
    # The compat flag enters as a traced 0/1 OPERAND (PR 10): both df
    # conventions dispatch the SAME executable, so their shared
    # between-variance numerator is bit-identical — the documented
    # exact (gn−1)/gn ratio holds on every row (validated at config
    # time here, never at trace time).
    # ``pack`` (ISSUE 12): the packed-code routing policy
    # (ATE_TPU_PREDICT_PACK when None) — resolved here on the host,
    # entering the jitted body as a static; output bit-identical either
    # way (asserted in tests/test_predict_pack.py).
    return _predict_cate_traced(
        forest, x, oob, tree_chunk, row_chunk, leaf_index, row_backend,
        _grf_df_flag(variance_compat), _resolve_pack_for(forest, pack),
    )


_PREDICT_CATE_STATICS = ("oob", "tree_chunk", "row_chunk", "row_backend",
                         "pack")


def _predict_cate_impl(
    forest: CausalForest,
    x: jax.Array,
    oob: bool,
    tree_chunk: int,
    row_chunk: int,
    leaf_index: jax.Array | None,
    row_backend: str,
    grf_df: jax.Array,
    pack: bool = False,
) -> CatePredictions:
    """:func:`predict_cate`'s traceable body (``row_backend`` concrete;
    ``grf_df`` a traced f32 0/1 scalar selecting the between-group df —
    an OPERAND, not a static, so both variance_compat modes share one
    executable and their truncated between-variance is bit-identical;
    see the df comment below). Jitted twice: :data:`_predict_cate_traced`
    (the dispatcher's body) and :func:`_predict_cate_aot_fn` (the
    serving wrapper — flag closed over, optional buffer donation; see
    :func:`lower_predict_cate`)."""
    if oob and x.shape[0] != forest.in_sample.shape[1]:
        raise ValueError(
            "oob=True is only valid for the training matrix: forest was "
            f"fit on {forest.in_sample.shape[1]} rows, got {x.shape[0]}; "
            "pass oob=False for new data"
        )
    codes = binarize(x, forest.bin_edges)
    n = codes.shape[0]
    T, depth = forest.n_trees, forest.depth
    n_leaves = 1 << depth
    k = forest.ci_group_size
    n_groups = T // k

    streaming = row_backend != "matmul"

    def per_tree(feats, bins, leaf_stats, in_row, li, codes_b, codes_t_b,
                 packed_b):
        if li is not None:
            node = li
        elif streaming:
            node = _tree_route_stream(
                feats, bins, codes_t_b, depth, backend=row_backend
            )
        else:
            node = _tree_route(feats, bins, codes_b, depth, packed=packed_b)
        if streaming:
            stats = table_lookup(
                leaf_stats.T, node, backend=row_backend
            ).T  # (rows, 5)
        else:
            # Leaf payload broadcast as one (rows, L) @ (L, 5)
            # contraction — a per-row gather serializes on TPU.
            leaf_oh = jax.nn.one_hot(node, n_leaves, dtype=jnp.float32)
            stats = jnp.matmul(leaf_oh, leaf_stats, precision=_PREC)  # (rows, 5)
        cnt = stats[:, 0]
        valid = cnt > 0
        if oob:
            valid = valid & ~in_row
        m = jnp.where(valid[:, None], stats / jnp.maximum(cnt, 1.0)[:, None], 0.0)
        return m, valid  # normalized per-tree moments; m[:,0] == valid

    # Chunked accumulation over groups: per-group sums feed the
    # little-bags variance; the global sum feeds the pooled CATE.
    group_chunk = max(1, tree_chunk // k)
    n_chunks = -(-n_groups // group_chunk)
    pad_groups = n_chunks * group_chunk - n_groups

    def reshape_groups(a):
        a = a.reshape((n_groups * k,) + a.shape[1:])
        if pad_groups:
            pad = jnp.zeros((pad_groups * k,) + a.shape[1:], a.dtype)
            a = jnp.concatenate([a, pad], axis=0)
        return a.reshape(n_chunks, group_chunk, k, *a.shape[1:])

    feats_g = reshape_groups(forest.split_feat[: n_groups * k])
    bins_g = reshape_groups(forest.split_bin[: n_groups * k])
    stats_g = reshape_groups(forest.leaf_stats[: n_groups * k])

    # Row blocking: pad rows to a whole number of blocks and put the
    # block axis first on every per-row array (padded rows compute
    # garbage that is sliced away at the end; real rows are unaffected
    # because every aggregation is per-row).
    rb = min(row_chunk, n)
    n_blocks = -(-n // rb)
    n_pad = n_blocks * rb

    codes_b = jnp.pad(codes, ((0, n_pad - n), (0, 0))).reshape(n_blocks, rb, -1)

    def block_tree_rows(a):
        """(T, n) per-(tree, row) array → (n_blocks, n_chunks, gc, k, rb)
        with the row-block axis leading, rows padded to n_pad."""
        a = jnp.pad(reshape_groups(a), ((0, 0),) * 3 + ((0, n_pad - n),))
        return jnp.moveaxis(
            a.reshape(n_chunks, group_chunk, k, n_blocks, rb), 3, 0
        )

    # in_sample is per TRAINING row — only meaningful (and only
    # shape-compatible) when the query rows are the training rows.
    in_b = block_tree_rows(forest.in_sample[: n_groups * k]) if oob else None
    li_b = None if leaf_index is None else block_tree_rows(leaf_index[: n_groups * k])

    def block_fn(xs):
        codes_blk, in_blk, li_blk = xs  # (rb, p), (n_chunks, gc, k, rb), …
        # With a precomputed leaf_index routing is skipped entirely, so
        # the transposed route operand is never read — don't build it.
        codes_t_blk = (
            codes_transposed(codes_blk)
            if streaming and leaf_index is None
            else None
        )
        # ONE packed operand per row block, shared across every tree
        # chunk and level (ISSUE 12; matmul routing only — the Pallas
        # route kernel has no packed formulation, and a cached routing
        # skips the contraction entirely).
        packed_blk = (
            pack_codes(codes_blk)
            if pack and not streaming and leaf_index is None
            else None
        )

        def chunk_fn(args):
            feats, bins, stats, inr, li = args  # (gc, k, …)
            vargs = [feats, bins, stats]
            if inr is not None:
                vargs.append(inr)
            if li is not None:
                vargs.append(li)

            def one(f, b, s, *rest):
                rest = list(rest)
                i = rest.pop(0) if inr is not None else None
                l = rest.pop(0) if li is not None else None
                return per_tree(f, b, s, i, l, codes_blk, codes_t_blk,
                                packed_blk)

            m, valid = jax.vmap(jax.vmap(one))(*vargs)
            # m: (gc, k, rb, 5) per-tree normalized moments. The
            # little-bags variance is grf's SANDWICH form: evaluate the
            # (intercept-profiled) score ψ_t = A_t − τ̂·B_t at the pooled
            # τ̂, take between/within-group variance of ψ, divide by the
            # pooled Hessian² — never solve τ per group (a 2-tree group
            # with near-zero Var(w̃) would explode; exactly what grf's
            # compute_variance avoids by working on ψ values).
            mw, my, mww, mwy = (m[..., i] for i in (1, 2, 3, 4))
            A_t = mwy - mw * my                 # per-tree Cov(w̃,ỹ)
            B_t = mww - mw * mw                 # per-tree Var(w̃)
            # grf counts only groups whose EVERY tree produced a valid
            # (nonempty, oob-allowed) prediction.
            ok_g = valid.all(axis=1).astype(jnp.float32)   # (gc, rb)
            A_g = A_t.mean(axis=1)
            B_g = B_t.mean(axis=1)
            # ψ is linear in τ: accumulate at the CHUNK's own pooled τ_c
            # (scores near a solution are ~0, so every accumulated term
            # is small — no f32 cancellation at large CATE levels) and
            # shift to the global τ̂ afterwards via ψ(τ̂)=ψ(τ_c)−δ·B.
            S_sum = m.sum(axis=(0, 1))                     # (rb, 5)
            M_sum = m[..., 0].sum(axis=(0, 1))             # (rb,)
            tau_c, _ = _tau_from_sums(S_sum, M_sum)        # (rb,)
            P_t = A_t - tau_c[None, None, :] * B_t
            P_g = A_g - tau_c[None, :] * B_g
            devP = (P_t - P_g[:, None, :]) * ok_g[:, None, :]
            devB = (B_t - B_g[:, None, :]) * ok_g[:, None, :]
            return (
                S_sum,
                M_sum,
                tau_c,
                ok_g.sum(axis=0),                          # groups counted
                (ok_g * P_g).sum(axis=0),                  # Σψ_g
                (ok_g * B_g).sum(axis=0),                  # ΣB_g
                (ok_g * P_g * P_g).sum(axis=0),            # Σψ_g²
                (ok_g * B_g * B_g).sum(axis=0),            # ΣB_g²
                (ok_g * P_g * B_g).sum(axis=0),            # Σψ_gB_g
                (devP * devP).sum(axis=(0, 1)),            # within SSψ
                (devP * devB).sum(axis=(0, 1)),            # within SSψB
                (devB * devB).sum(axis=(0, 1)),            # within SSB
            )

        outs = lax.map(chunk_fn, (feats_g, bins_g, stats_g, in_blk, li_blk))
        (S_c, M_c, tau_c, gn_c, gP_c, gB_c, gPP_c, gBB_c, gPB_c,
         w2_c, wPB_c, wBB_c) = outs
        # Global pooled τ̂ and Hessian for this row block (chunks cover
        # every group, so this is the forest-wide solve).
        S_b = S_c.sum(axis=0)
        M_b = M_c.sum(axis=0)
        tau_b, H_b = _tau_from_sums(S_b, M_b)              # (rb,), (rb,)
        # Shift each chunk's ψ-moments from its τ_c to τ̂ (δ is tiny).
        d = tau_b[None, :] - tau_c                         # (n_chunks, rb)
        gn = gn_c.sum(axis=0)
        SP = (gP_c - d * gB_c).sum(axis=0)
        SP2 = (gPP_c - 2.0 * d * gPB_c + d * d * gBB_c).sum(axis=0)
        ssw = (w2_c - 2.0 * d * wPB_c + d * d * wBB_c).sum(axis=0)
        return S_b, M_b, tau_b, H_b, gn, SP, SP2, ssw

    S_b, M_b, tau_b, H_b, gn_b, SP_b, SP2_b, ssw_b = lax.map(
        block_fn, (codes_b, in_b, li_b)
    )

    def unblock(a):  # (n_blocks, rb, …) -> (n, …)
        return a.reshape((n_pad,) + a.shape[2:])[:n]

    tau = unblock(tau_b)
    H = unblock(H_b)
    gn, SP, SP2, ssw = (unblock(a) for a in (gn_b, SP_b, SP2_b, ssw_b))

    # Bootstrap of little bags, sandwich form (grf ≤0.10
    # compute_variance with the intercept profiled out):
    #   Var(τ̂) = max(V_between(ψ) − V_within(ψ)/k, 0) / H²
    # with ψ evaluated at the pooled τ̂ and H the pooled Var(w̃).
    # df quirk pair (VERDICT r3 #7): grf normalizes the between-group
    # variance by num_groups; the default here is the unbiased gn−1.
    # ``variance_compat="grf"`` reproduces grf's divisor for true-grf
    # comparisons at small group counts (at the notebook's 1000 groups
    # the ratio is 999/1000 — far below the estimator's own Monte-Carlo
    # noise). grf's half-sample "Bayes debiasing" correction is skipped
    # by both sides (grf only applies it when ci_group_size > 1
    # subsampling leaves it well-defined).
    #
    # ``grf_df`` is a TRACED 0/1 scalar, not a jit static (PR 10): as a
    # static, the two compat modes compiled SEPARATE executables, and
    # XLA was free to associate the f32 cancellation ``SP2 − gn·ψ̄²``
    # differently in each — on rows where the true between-variance is
    # ≈ 0 the two executables' truncation residue disagreed at ulp
    # level and the documented exact (gn−1)/gn ratio did not hold
    # (the known-red test_variance_compat_grf_df_ratio). One shared
    # executable makes the numerator bit-identical by construction; the
    # where() selects between the exact same df values the old static
    # branches produced.
    ngr = jnp.maximum(gn, 1.0)
    mean_psi = SP / ngr
    between_df = jnp.where(grf_df > 0, ngr, jnp.maximum(gn - 1.0, 1.0))
    v_between = jnp.maximum(SP2 - gn * mean_psi * mean_psi, 0.0) / between_df
    v_within = ssw / jnp.maximum(gn * (k - 1.0), 1.0)
    var_psi = jnp.maximum(v_between - v_within / k, 0.0)
    variance = jnp.where(
        H > _EPS, var_psi / jnp.maximum(H, _EPS) ** 2, 0.0
    )
    return CatePredictions(cate=tau, variance=variance)


_predict_cate_traced = functools.partial(
    jax.jit, static_argnames=_PREDICT_CATE_STATICS
)(_predict_cate_impl)

# The serving (donated-buffer) variant lives in _predict_cate_aot_fn
# below: donation is part of the executable's calling convention
# (offline callers must keep their inputs), and the AOT wrapper also
# closes over the df flag so the compiled serving signature stays
# ``compiled(forest, x, None)``.


# The dispatcher keeps the jitted body's cache controls (tests rebuild
# traces with monkeypatched internals via predict_cate.clear_cache()).
predict_cate.clear_cache = _predict_cate_traced.clear_cache


@functools.lru_cache(maxsize=None)
def _predict_cate_aot_fn(grf: bool, donate: bool):
    """The AOT (serving) jit wrapper with the df-selector flag CLOSED
    OVER as a constant: keeps the compiled signature at
    ``compiled(forest, x, None)`` while the offline dispatcher threads
    the flag as a runtime operand (one executable for both compat
    modes). Cached so repeated lowers reuse one function identity."""

    def body(forest, x, oob, tree_chunk, row_chunk, leaf_index, row_backend,
             pack):
        return _predict_cate_impl(
            forest, x, oob, tree_chunk, row_chunk, leaf_index, row_backend,
            jnp.float32(grf), pack,
        )

    kw: dict = dict(static_argnames=_PREDICT_CATE_STATICS)
    if donate:
        kw["donate_argnums"] = (1,)
    return jax.jit(body, **kw)


@functools.lru_cache(maxsize=None)
def _predict_cate_aot_masked_fn(grf: bool, donate: bool):
    """The FUSED-bucket AOT wrapper (ISSUE 12, tentpole c): same body,
    plus a traced (batch,) f32 0/1 row-mask operand applied to the
    outputs — the round-5 traced-0/1-flag discipline. Real rows
    multiply by 1.0 (``1·x ≡ x`` exactly: fused dispatch is
    bit-identical to per-bucket dispatch for every served row), masked
    rows multiply their finite garbage by 0.0 and contribute EXACTLY
    zero — a fused executable's pad region is deterministic, never
    garbage. Compiled signature: ``compiled(forest, x, mask, None)``
    (the trailing ``None`` is still the empty leaf_index pytree)."""

    def body(forest, x, mask, oob, tree_chunk, row_chunk, leaf_index,
             row_backend, pack):
        out = _predict_cate_impl(
            forest, x, oob, tree_chunk, row_chunk, leaf_index, row_backend,
            jnp.float32(grf), pack,
        )
        return CatePredictions(cate=out.cate * mask,
                               variance=out.variance * mask)

    kw: dict = dict(static_argnames=_PREDICT_CATE_STATICS)
    if donate:
        kw["donate_argnums"] = (1,)
    return jax.jit(body, **kw)


def _resolve_lower_config(forest, batch, row_backend, donate,
                          variance_compat):
    """The shared config-time preamble of both AOT lowers: backend
    default, donation gating (ONE warning, never jax's per-dispatch
    stream), compat validation, and the query ShapeDtypeStruct — one
    site, so the fused and per-bucket executables can never drift on
    resolution behavior."""
    if row_backend is None:
        row_backend = "pallas" if jax.default_backend() == "tpu" else "matmul"
    backend = jax.default_backend()
    if donate is None:
        donate = backend == "tpu"
    elif donate and backend != "tpu":
        _warn_donation_unsupported(backend)
        donate = False
    _grf_df_flag(variance_compat)  # validate at config time
    p = forest.bin_edges.shape[0]
    x_spec = jax.ShapeDtypeStruct((int(batch), p), jnp.float32)
    return row_backend, donate, x_spec


def lower_predict_cate(
    forest: CausalForest,
    batch: int,
    *,
    oob: bool = False,
    tree_chunk: int = 32,
    row_chunk: int = 65536,
    row_backend: str | None = None,
    variance_compat: str = "unbiased",
    donate: bool | None = None,
    pack: bool | str | None = None,
) -> jax.stages.Lowered:
    """AOT-lower the CATE predict executable for a fixed ``(batch, p)``
    query shape (ISSUE 6, the serving daemon's startup phase).

    Returns a ``jax.stages.Lowered``; ``.compile()`` yields the
    executable the daemon dispatches as ``compiled(forest, x, None)``
    (the trailing ``None`` is the empty ``leaf_index`` pytree — serving
    rows are new data, never the cached training routing). The forest
    enters as a RUNTIME argument, not a closed-over constant, so a
    degraded-mode checkpoint reload with identical shapes reuses the
    same executable without recompiling.

    ``donate=None`` donates the query buffer only on TPU — the CPU
    backend ignores donation with a warning per call, which a daemon
    would emit thousands of times. An EXPLICIT ``donate=True`` on a
    backend that does not implement donation is gated the same way
    (ISSUE 7 satellite): one Python warning here, at startup/lower
    time, and the non-donated executable — never jax's per-dispatch
    warning stream out of a serving loop."""
    row_backend, donate, x_spec = _resolve_lower_config(
        forest, batch, row_backend, donate, variance_compat
    )
    # The AOT path closes over the df flag as a trace-time CONSTANT so
    # the compiled call signature stays ``compiled(forest, x, None)``
    # (the serving daemon's documented contract). Serving never needs
    # cross-compat bit-identity — each daemon compiles one convention.
    fn = _predict_cate_aot_fn(variance_compat == "grf", donate)
    return fn.lower(
        forest, x_spec, oob, tree_chunk, row_chunk, None, row_backend,
        _resolve_pack_for(forest, pack),
    )


def lower_predict_cate_masked(
    forest: CausalForest,
    batch: int,
    *,
    oob: bool = False,
    tree_chunk: int = 32,
    row_chunk: int = 65536,
    row_backend: str | None = None,
    variance_compat: str = "unbiased",
    donate: bool | None = None,
    pack: bool | str | None = None,
) -> jax.stages.Lowered:
    """:func:`lower_predict_cate` for a FUSED bucket group (ISSUE 12):
    the executable additionally takes a traced (batch,) f32 row-mask
    and is dispatched as ``compiled(forest, x, mask, None)``. One
    masked executable serves every bucket of its fusion group — the
    serving daemon's executable count per model DROPS — with real rows
    bit-identical to the per-bucket dispatch (×1.0 is exact) and masked
    rows exactly zero. Same donation gating as the unmasked lower
    (shared preamble — the two lowers cannot drift)."""
    row_backend, donate, x_spec = _resolve_lower_config(
        forest, batch, row_backend, donate, variance_compat
    )
    mask_spec = jax.ShapeDtypeStruct((int(batch),), jnp.float32)
    fn = _predict_cate_aot_masked_fn(variance_compat == "grf", donate)
    return fn.lower(
        forest, x_spec, mask_spec, oob, tree_chunk, row_chunk, None,
        row_backend, _resolve_pack_for(forest, pack),
    )


_donation_warned = False


def _warn_donation_unsupported(backend: str) -> None:
    """One process-wide warning for donate=True on a backend that
    ignores donation (jax 0.4.37 warns per CALL otherwise — a serving
    daemon would emit it once per dispatched batch, thousands of times
    an hour). Startup-time, then silence."""
    global _donation_warned
    if _donation_warned:
        return
    _donation_warned = True
    warnings.warn(
        f"lower_predict_cate: buffer donation is not implemented on the "
        f"{backend!r} backend; compiling the non-donated executable "
        "(warned once per process)",
        RuntimeWarning,
        stacklevel=3,
    )


@functools.partial(jax.jit, static_argnames=("clip",))
def _aipw_from_cate(w, y, y_hat, w_hat, tau_i, clip=0.01):
    e = jnp.clip(w_hat, clip, 1.0 - clip)
    wt = w - e
    yt = y - y_hat
    gamma = tau_i + wt / (e * (1.0 - e)) * (yt - wt * tau_i)
    est = gamma.mean()
    se = jnp.sqrt(gamma.var(ddof=1) / gamma.shape[0])
    return est, se


def average_treatment_effect(
    fitted: FittedCausalForest, cate: CatePredictions | None = None
) -> AverageEffect:
    """The grf ≤0.10 ``estimate_average_effect`` equivalent
    (``ate_replication.Rmd:265``): AIPW over the forest's own OOB
    nuisances with doubly-robust scores
    Γᵢ = τ̂(xᵢ) + (Wᵢ−ê)/(ê(1−ê))·(ỹᵢ − w̃ᵢ·τ̂(xᵢ)); SE = sd(Γ)/√n."""
    if cate is None:
        cate = predict_cate(fitted.forest, fitted.x, oob=True)
    est, se = _aipw_from_cate(
        fitted.w, fitted.y, fitted.y_hat, fitted.w_hat, cate.cate
    )
    return AverageEffect(estimate=est, std_err=se)


def incorrect_forest_ate(cate: CatePredictions):
    """The notebook's deliberate negative example
    (``ate_replication.Rmd:258-262``): ATE as the plain mean of CATE
    predictions, SE as sqrt(mean per-point variance). Printed as
    'Incorrect ATE: 0.083 (SE: 0.198)' in ``ate_replication.md:294``."""
    return cate.cate.mean(), jnp.sqrt(cate.variance.mean())

"""Telemetry-layer tests (observability/): registry semantics, span
nesting, disabled-mode no-ops, atomic writes, the retry/dispatch/cache
instrumentation, and the quick-sweep integration contract — metrics.json
and events.jsonl written beside report.json, valid under
scripts/check_metrics_schema.py, with per-stage records for every
``SWEEP_METHODS`` entry and bit-identical estimator output with
telemetry on vs off."""

import json
import os
import sys

import pytest

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability.events import EventLog
from ate_replication_causalml_tpu.observability.registry import MetricsRegistry

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import check_metrics_schema as cms  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Each test starts from an empty global registry/event log with
    telemetry ON (the env default), and leaves no override behind."""
    obs.set_enabled(True)
    obs.REGISTRY.reset()
    obs.EVENTS.clear()
    yield
    obs.set_enabled(None)


# ── registry semantics ──────────────────────────────────────────────────


def test_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("hits", "help text")
    c.inc()
    c.inc(2.5, pool="a")
    c.inc(0, pool="b")  # pre-created, exported as explicit zero
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("level")
    g.set(3, k="v")
    g.set(7, k="v")  # last write wins
    g.add(1.5)
    h = reg.histogram("lat")
    for v in (2.0, 0.5, 4.0):
        h.observe(v, op="x")
    snap = reg.snapshot()
    assert snap["schema_version"] == obs.SCHEMA_VERSION
    assert snap["counters"]["hits"] == {"": 1.0, "pool=a": 2.5, "pool=b": 0.0}
    assert snap["gauges"]["level"] == {"k=v": 7.0, "": 1.5}
    s = snap["histograms"]["lat"]["op=x"]
    assert (s["count"], s["sum"], s["min"], s["max"], s["last"]) == (3, 6.5, 0.5, 4.0, 4.0)
    # A name cannot change kind.
    with pytest.raises(TypeError):
        reg.gauge("hits")
    # Same name + kind returns the same metric object.
    assert reg.counter("hits") is c


def test_bucket_histogram_quantiles_and_snapshot():
    """ISSUE 6: fixed-ladder histogram with snapshot-time p50/p95/p99 —
    the quantile is the bucket's upper bound (Prometheus-style,
    conservative), clamped to the observed max, and the snapshot passes
    the schema checker's internal-consistency rules."""
    reg = MetricsRegistry()
    h = reg.bucket_histogram("lat", "t", bounds=(0.001, 0.01, 0.1, 1.0))
    for v in (0.0005, 0.002, 0.003, 0.05, 0.5, 3.0):
        h.observe(v, status="ok")
    snap = reg.snapshot()
    s = snap["bucket_histograms"]["lat"]["status=ok"]
    assert s["count"] == 6 and s["min"] == 0.0005 and s["max"] == 3.0
    assert s["buckets"] == [1, 2, 1, 1, 1]  # +1 overflow slot
    assert s["bounds"] == [0.001, 0.01, 0.1, 1.0]
    assert s["p50"] == 0.01          # 3rd of 6 falls in the <=0.01 bucket
    assert s["p95"] == s["p99"] == 3.0  # overflow clamps to max
    assert cms._check_bucket_sample("lat", "status=ok", s) == []
    # A single observation reports itself at every quantile.
    h.observe(0.02, status="one")
    s1 = reg.snapshot()["bucket_histograms"]["lat"]["status=one"]
    assert s1["p50"] == s1["p99"] == 0.02
    # Default ladder is the shared log-spaced one.
    assert reg.bucket_histogram("other").bounds == obs.DEFAULT_LATENCY_BUCKETS
    # Kind conflicts and ladder conflicts are refused.
    with pytest.raises(TypeError):
        reg.histogram("lat")
    with pytest.raises(ValueError):
        reg.bucket_histogram("lat", bounds=(1.0, 2.0))
    with pytest.raises(ValueError):
        reg.bucket_histogram("bad", bounds=(2.0, 1.0))
    # peek() exposes the sum like the summary histogram.
    assert reg.peek("lat")["status=ok"] == pytest.approx(3.5555)


def test_bucket_histogram_prom_export_is_cumulative():
    from ate_replication_causalml_tpu.observability.promtext import (
        render_prom_from_snapshot,
    )

    reg = MetricsRegistry()
    h = reg.bucket_histogram("lat", "t", bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v, op="x")
    text = render_prom_from_snapshot(reg.snapshot())
    assert "# TYPE ate_tpu_lat histogram" in text
    assert 'ate_tpu_lat_bucket{op="x",le="0.1"} 1' in text
    assert 'ate_tpu_lat_bucket{op="x",le="1.0"} 2' in text
    assert 'ate_tpu_lat_bucket{op="x",le="+Inf"} 3' in text
    assert 'ate_tpu_lat_count{op="x"} 3' in text


def test_schema_checker_rejects_inconsistent_bucket_sample():
    good = {"count": 2, "sum": 1.0, "min": 0.1, "max": 0.9,
            "buckets": [1, 1, 0], "bounds": [0.5, 1.0],
            "p50": 0.5, "p95": 0.9, "p99": 0.9}
    assert cms._check_bucket_sample("f", "", good) == []
    bad_sum = dict(good, buckets=[1, 0, 0])
    assert any("sum to" in e for e in cms._check_bucket_sample("f", "", bad_sum))
    bad_len = dict(good, buckets=[1, 1])
    assert any("len(bounds)+1" in e for e in cms._check_bucket_sample("f", "", bad_len))
    bad_q = dict(good, p50=0.95)
    assert any("quantiles" in e for e in cms._check_bucket_sample("f", "", bad_q))
    missing = {"count": 1}
    assert cms._check_bucket_sample("f", "", missing)


def test_collector_runs_at_snapshot_and_is_crash_proof():
    reg = MetricsRegistry()
    reg.add_collector(lambda: reg.gauge("scanned").set(42))
    reg.add_collector(lambda: 1 / 0)  # must not take down the snapshot
    assert reg.snapshot()["gauges"]["scanned"] == {"": 42.0}


def test_sanitize_label():
    assert obs.sanitize_label("Causal Forest(GRF)") == "Causal_Forest_GRF_"
    assert obs.sanitize_label("Belloni et.al") == "Belloni_et_al"
    assert obs.sanitize_label("ok_name-9") == "ok_name-9"


# ── event log / spans ───────────────────────────────────────────────────


def test_span_nesting_and_jsonl_roundtrip():
    log = EventLog()
    with log.span("outer", run="r1"):
        with log.span("inner") as sp:
            sp.set_status("computed")
            sp.set_attr("method", "naive")
        log.emit("ping", status="event", n=1)
    recs = log.records()
    # Children close (and record) before their parent.
    assert [r["name"] for r in recs] == ["inner", "ping", "outer"]
    outer = recs[2]
    assert recs[0]["parent_id"] == outer["span_id"]
    assert recs[1]["parent_id"] == outer["span_id"]
    assert outer["parent_id"] is None
    assert recs[0]["status"] == "computed"
    assert recs[0]["attrs"]["method"] == "naive"
    assert all(r["end_mono_s"] >= r["start_mono_s"] for r in recs)
    # JSONL: versioned header + one record per line, schema-clean.
    lines = log.to_jsonl().splitlines()
    assert json.loads(lines[0])["kind"] == "events_header"
    assert cms.validate_events(lines) == []


def test_span_error_status_propagates():
    log = EventLog()
    with pytest.raises(RuntimeError):
        with log.span("boom"):
            raise RuntimeError("x")
    (rec,) = log.records()
    assert rec["status"] == "error"
    assert rec["attrs"]["error_type"] == "RuntimeError"


def test_event_log_ring_buffer_evicts_oldest():
    log = EventLog(max_events=2)
    for i in range(5):
        log.emit("e", i=i)
    # True ring: the NEWEST records survive (the tail of a dying run is
    # the diagnostic part); evictions are counted.
    assert [r["attrs"]["i"] for r in log.records()] == [3, 4]
    assert log.dropped == 3
    assert json.loads(log.to_jsonl().splitlines()[0])["dropped"] == 3


# ── disabled mode ───────────────────────────────────────────────────────


def test_disabled_mode_is_a_noop(tmp_path):
    obs.set_enabled(False)
    obs.counter("c").inc(5)
    obs.gauge("g").set(1)
    obs.histogram("h").observe(2)
    with obs.span("s") as sp:
        sp.set_status("anything")  # must not raise on the null span
        sp.set_attr("k", "v")
    obs.emit("e")
    snap = obs.REGISTRY.snapshot()
    assert snap["counters"] == {} and snap["gauges"] == {}
    assert obs.EVENTS.records() == []
    # Exporters write nothing — no empty husk files.
    assert obs.write_run_artifacts(str(tmp_path)) == []
    assert os.listdir(tmp_path) == []
    # instrument_dispatch returns the function unwrapped.
    fn = lambda i: i
    assert obs.instrument_dispatch("kind", fn) is fn


def test_env_var_controls_enabled(monkeypatch):
    obs.set_enabled(None)
    monkeypatch.setenv("ATE_TPU_TELEMETRY", "0")
    assert obs.enabled() is False
    obs.set_enabled(None)
    monkeypatch.setenv("ATE_TPU_TELEMETRY", "1")
    assert obs.enabled() is True


# ── atomic writes ───────────────────────────────────────────────────────


def test_atomic_write_json_no_tmp_residue(tmp_path):
    path = str(tmp_path / "sub" / "x.json")
    obs.atomic_write_json(path, {"a": [1, 2]})
    assert json.load(open(path)) == {"a": [1, 2]}
    obs.atomic_write_json(path, {"a": 3})  # overwrite in place
    assert json.load(open(path)) == {"a": 3}
    assert os.listdir(os.path.dirname(path)) == ["x.json"]


def test_stage_timer_dump_is_valid_json(tmp_path):
    from ate_replication_causalml_tpu.utils.profiling import StageTimer

    t = StageTimer()
    with t.stage("a"):
        pass
    path = str(tmp_path / "timings.json")
    t.dump(path)
    assert set(json.load(open(path))) == {"a"}
    # The stage also landed in the registry histogram and event log.
    snap = obs.REGISTRY.snapshot()
    assert "stage=a" in snap["histograms"]["stage_seconds"]
    assert any(r["name"] == "stage" for r in obs.EVENTS.records())


# ── retry / dispatch instrumentation ────────────────────────────────────


def test_run_shards_healthy_exports_zero_retry_counters():
    from ate_replication_causalml_tpu.parallel.retry import run_shards

    outs = run_shards(lambda i: i, 3, pool="p0")
    assert [o.result for o in outs] == [0, 1, 2]
    c = obs.REGISTRY.snapshot()["counters"]
    assert c["shard_attempts_total"]["pool=p0"] == 3.0
    # Present-but-zero: a healthy run still exports the retry keys.
    assert c["shard_retries_total"]["pool=p0"] == 0.0
    assert c["shard_failures_total"]["pool=p0"] == 0.0
    assert c["shard_backoff_seconds_total"]["pool=p0"] == 0.0


def test_run_shards_counts_retries_failures_and_events():
    from ate_replication_causalml_tpu.parallel.retry import (
        inject_failures,
        run_shards,
    )

    fn = inject_failures(lambda i: i, {0: 1, 2: 5})
    outs = run_shards(fn, 3, max_attempts=3, backoff_s=0.001, pool="p1")
    assert outs[0].ok and outs[1].ok and not outs[2].ok
    c = obs.REGISTRY.snapshot()["counters"]
    # shard0: 2 attempts; shard1: 1; shard2: 3.
    assert c["shard_attempts_total"]["pool=p1"] == 6.0
    # shard0 retried once, shard2 twice.
    assert c["shard_retries_total"]["pool=p1"] == 3.0
    assert c["shard_failures_total"]["pool=p1"] == 1.0
    assert c["shard_backoff_seconds_total"]["pool=p1"] > 0.0
    names = [r["name"] for r in obs.EVENTS.records()]
    assert names.count("shard_retry") == 3
    assert names.count("shard_failed") == 1


def test_instrument_dispatch_records_counts_and_durations():
    wrapped = obs.instrument_dispatch("fitX", lambda i: i * 2)
    assert [wrapped(i) for i in range(4)] == [0, 2, 4, 6]
    snap = obs.REGISTRY.snapshot()
    assert snap["counters"]["tree_dispatch_total"]["fit=fitX"] == 4.0
    assert snap["histograms"]["tree_dispatch_seconds"]["fit=fitX"]["count"] == 4


# ── promtext / schema checker ───────────────────────────────────────────


def test_promtext_renders_and_escapes():
    obs.counter("req_total").inc(3, method='Causal Forest("GRF")')
    obs.gauge("mem").set(1.0)
    obs.histogram("lat").observe(0.5, op="fit")
    from ate_replication_causalml_tpu.observability.promtext import (
        render_prom_text,
    )

    text = render_prom_text()
    assert "# TYPE ate_tpu_req_total counter" in text
    assert 'method="Causal Forest(\\"GRF\\")"' in text
    assert "ate_tpu_lat_count" in text and "ate_tpu_lat_sum" in text


def test_check_metrics_schema_cli_roundtrip(tmp_path):
    # Build a registry that satisfies the required families, export it,
    # and run the standalone checker exactly as CI/ops would.
    from ate_replication_causalml_tpu.parallel.retry import run_shards

    obs.install_jax_monitoring()
    run_shards(lambda i: i, 1)
    with obs.span("root"):
        obs.emit("child")
    paths = obs.write_run_artifacts(str(tmp_path))
    assert [os.path.basename(p) for p in paths] == [
        "metrics.json", "events.jsonl", "metrics.prom",
    ]
    assert cms.main([str(tmp_path)]) == 0
    # A truncated metrics.json must fail loudly.
    with open(os.path.join(tmp_path, "metrics.json"), "w") as f:
        f.write('{"schema_version": 1')
    assert cms.main([str(tmp_path)]) == 1


# ── quick-sweep integration ─────────────────────────────────────────────


@pytest.mark.slow
def test_quick_sweep_telemetry_integration(tmp_path):
    """One MICRO sweep (same shapes as test_pipeline_driver's, so the
    in-process executables are shared): the telemetry artifacts land
    beside report.json, pass the schema checker with every SWEEP_METHODS
    stage plus the oracle, and carry dispatch/retry/cache counters. A
    resume run re-exports with status=resumed stages, and a
    telemetry-off run produces bit-identical estimator output with no
    artifacts.

    @slow since PR 19's budget rebalance (~88 s, the largest single
    displaceable wall): tier-1 keeps an in-engine telemetry-on run with
    schema validation through the campaign rig (which *refuses* to run
    without telemetry and validates its report against the checker),
    plus every registry/span/export/dispatch unit test above; the
    full-sweep per-stage export contract and the telemetry-on/off
    bit-identity leg ride here."""
    from test_pipeline_driver import MICRO

    from ate_replication_causalml_tpu.pipeline import SWEEP_METHODS, run_sweep

    out = str(tmp_path / "sweep")
    report = run_sweep(MICRO, outdir=out, plots=False, log=lambda s: None)

    mpath = os.path.join(out, "metrics.json")
    epath = os.path.join(out, "events.jsonl")
    required = list(SWEEP_METHODS) + ["oracle"]
    errors = cms.validate_pair(mpath, epath, require_stages=required)
    assert errors == [], errors
    assert os.path.exists(os.path.join(out, "metrics.prom"))

    snap = json.load(open(mpath))
    stage_samples = snap["counters"]["sweep_stage_total"]
    for m in required:
        assert stage_samples.get(f"method={m},status=computed") == 1.0, m
    # Forest fits dispatched through the instrumented elastic loop.
    assert sum(snap["counters"]["tree_dispatch_total"].values()) > 0
    assert sum(snap["counters"]["shard_attempts_total"].values()) > 0
    # Healthy run: retry counters present AND zero.
    assert sum(snap["counters"]["shard_retries_total"].values()) == 0.0
    # Compile-cache counters present (zero here: the test harness runs
    # cache-less by design — presence is the contract).
    assert "compile_cache_hits_total" in snap["counters"]
    assert "compile_cache_misses_total" in snap["counters"]

    # events.jsonl: a sweep_stage span per stage, nested under run_sweep.
    recs = [json.loads(l) for l in open(epath).read().splitlines()[1:]]
    by_id = {r["span_id"]: r for r in recs}
    stages = [r for r in recs if r["name"] == "sweep_stage"]
    assert sorted(r["attrs"]["method"] for r in stages) == sorted(required)
    for r in stages:
        assert r["status"] == "computed"
        assert by_id[r["parent_id"]]["name"] == "run_sweep"

    # Resume: stages re-export as status=resumed.
    obs.REGISTRY.reset()
    obs.EVENTS.clear()
    report2 = run_sweep(MICRO, outdir=out, plots=False, log=lambda s: None)
    snap2 = json.load(open(mpath))
    for m in required:
        key = f"method={m},status=resumed"
        assert snap2["counters"]["sweep_stage_total"].get(key) == 1.0, m

    # Telemetry off: the driver writes no artifacts and returns the
    # same numbers (run via the resume path — the disabled-mode
    # mutators are unit-tested above; estimator numerics never see
    # telemetry at all, it is host-side only).
    obs.set_enabled(False)
    for name in ("metrics.json", "events.jsonl", "metrics.prom"):
        os.remove(os.path.join(out, name))
    report3 = run_sweep(MICRO, outdir=out, plots=False, log=lambda s: None)
    assert not os.path.exists(os.path.join(out, "metrics.json"))
    assert not os.path.exists(os.path.join(out, "events.jsonl"))
    for m in SWEEP_METHODS:
        assert report3.results[m].ate == report2.results[m].ate == report.results[m].ate
    assert report3.oracle.ate == report.oracle.ate
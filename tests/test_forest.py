"""Forest engine tests: split quality, OOB semantics, and the RF-backed
estimators (AIPW-RF, DML)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.estimators.aipw import doubly_robust
from ate_replication_causalml_tpu.estimators.dml import chernozhukov, double_ml
from ate_replication_causalml_tpu.estimators.naive import naive_ate
from ate_replication_causalml_tpu.models.forest import (
    binarize,
    fit_forest_classifier,
    forest_apply,
    predict_forest,
    quantile_bins,
    rf_oob_propensity,
)

RNG = np.random.default_rng(0)


def _classification_problem(n=2000, p=6):
    x = RNG.normal(size=(n, p))
    logits = 1.5 * x[:, 0] - 1.0 * x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (RNG.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32)


def test_binarize_roundtrip():
    x = jnp.asarray(RNG.normal(size=(500, 3)), jnp.float32)
    edges = quantile_bins(x, 16)
    codes = np.asarray(binarize(x, edges))
    assert codes.min() >= 0 and codes.max() <= 15
    # Monotone: larger value -> same or larger bin.
    col = np.asarray(x[:, 0])
    order = np.argsort(col)
    assert np.all(np.diff(codes[order, 0]) >= 0)


def test_quantile_bins_bit_identical_to_jnp_quantile():
    """The TPU f32 order-statistic path (round 5 — lax.sort costs ~17 s
    to compile on the remote TPU toolchain; CPU keeps the sort) must be
    BIT-identical to jnp.quantile: same bracketing order statistics
    (ties, ±0.0, value duplication included), same interpolation
    arithmetic, same NaN-poisons-the-slice semantics. Goldens generated
    through either path ride on this equality; the helper is called
    directly because quantile_bins itself dispatches by backend."""
    from ate_replication_causalml_tpu.models.forest import (
        _order_stat_quantiles,
        exact_order_stats,
    )

    rng = np.random.default_rng(11)
    base = rng.normal(size=(997, 4)).astype(np.float32)
    base[:, 1] = np.round(base[:, 1])          # heavy ties
    base[:200, 2] = -0.0                        # signed-zero runs
    base[200:400, 2] = 0.0
    for n_bins in (16, 64):
        for arr in (base, base[:5]):            # tiny n: low == high ranks
            x = jnp.asarray(arr)
            qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
            ref = jnp.quantile(x, qs, axis=0).T
            np.testing.assert_array_equal(
                np.asarray(_order_stat_quantiles(x, qs)), np.asarray(ref)
            )
            # The public entry agrees regardless of which path it picks.
            np.testing.assert_array_equal(
                np.asarray(quantile_bins(x, n_bins)), np.asarray(ref)
            )
    # NaN slice poisoning matches.
    xn = base.copy()
    qs16 = jnp.linspace(0, 1, 17)[1:-1]
    xn[3, 0] = np.nan
    got = np.asarray(_order_stat_quantiles(jnp.asarray(xn), qs16))
    ref = np.asarray(jnp.quantile(jnp.asarray(xn), qs16, axis=0).T)
    np.testing.assert_array_equal(got, ref)
    assert np.isnan(got[0]).all() and not np.isnan(got[1:]).any()
    # The selection itself is bit-identical to sort-then-gather.
    x = jnp.asarray(base)
    ranks = jnp.asarray([0, 1, 496, 995, 996], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(exact_order_stats(x, ranks)),
        np.asarray(jnp.sort(x, axis=0))[np.asarray(ranks)].T,
    )


def test_exact_order_stats_rejects_out_of_range_ranks():
    """ADVICE r5: an out-of-range rank used to fall through the binary
    search with lo at its 0xFFFFFFFF bound — which decodes to a NaN bit
    pattern and silently poisons the quantiles. Ranks are concrete at
    every call site, so the bounds check is host-side and raises."""
    from ate_replication_causalml_tpu.models.forest import exact_order_stats

    x = jnp.asarray(RNG.normal(size=(50, 3)), jnp.float32)
    with pytest.raises(ValueError, match=r"out of range.*max rank 50"):
        exact_order_stats(x, jnp.asarray([0, 50], jnp.int32))  # n == 50
    with pytest.raises(ValueError, match="out of range"):
        exact_order_stats(x, jnp.asarray([-1], jnp.int32))
    # Boundary ranks stay valid…
    ok = exact_order_stats(x, jnp.asarray([0, 49], jnp.int32))
    np.testing.assert_array_equal(
        np.asarray(ok),
        np.asarray(jnp.sort(x, axis=0))[np.asarray([0, 49])].T,
    )
    # …and the enclosing-jit call sites keep working (linspace-derived
    # ranks are concrete at trace time; the check runs there).
    edges = quantile_bins(x, 8)
    assert edges.shape == (3, 7)
    # Traced ranks (shape-only knowledge) skip the host-side check.
    traced = jax.jit(lambda r: exact_order_stats(x, r))(
        jnp.asarray([0, 49], jnp.int32)
    )
    np.testing.assert_array_equal(np.asarray(traced), np.asarray(ok))


def _run_grow_floors_compare(backend):
    """Shared body of the grow-floor bit-identity contract: run(1, 1)
    vs run(16, 32) on the given Pallas histogram/route backend."""
    from ate_replication_causalml_tpu.models.forest import streaming_level_loop
    from ate_replication_causalml_tpu.ops.hist_pallas import bin_histogram
    from ate_replication_causalml_tpu.ops.tree_pallas import (
        codes_transposed,
        route_bits,
    )

    rng = np.random.default_rng(5)
    n, p, n_bins, depth = 700, 5, 16, 5
    x = jnp.asarray(rng.normal(size=(n, p)), jnp.float32)
    edges = quantile_bins(x, n_bins)
    codes = binarize(x, edges)
    codes_t = codes_transposed(codes)
    counts = jnp.asarray(rng.poisson(1.0, n), jnp.float32)
    y = jnp.asarray(rng.normal(size=n), jnp.float32)
    weights = jnp.stack([counts, counts * y])
    lk = jax.random.split(jax.random.key(2), depth)

    def run(hist_floor, route_floor):
        from ate_replication_causalml_tpu.models.forest import select_split

        def tables_fn(hist, level, perm):
            hist_c, hist_y = hist[0], hist[1]
            cl = jnp.cumsum(hist_c, axis=2)
            yl = jnp.cumsum(hist_y, axis=2)
            ct, ytot = cl[:, :, -1:], yl[:, :, -1:]
            cr, yr = ct - cl, ytot - yl
            score = -(yl * yl / jnp.maximum(cl, 1e-12)
                      + yr * yr / jnp.maximum(cr, 1e-12))
            score = jnp.where((cl > 0) & (cr > 0), score, jnp.inf)
            return select_split(score, lk[level], 1 << level, p, n_bins, 3,
                                perm=perm)

        return streaming_level_loop(
            codes, depth, n_bins,
            hist_fn=lambda ids, m: bin_histogram(
                codes, ids, weights, max_nodes=m, n_bins=n_bins,
                backend=backend,
            ),
            tables_fn=tables_fn,
            route_fn=lambda ids, bf, bb: route_bits(
                codes_t, ids, bf, bb, backend=backend
            ),
            hist_floor=hist_floor,
            route_floor=route_floor,
        )

    base = run(1, 1)
    padded = run(16, 32)
    for a, b in zip(base, padded):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grow_floors_bit_identical():
    """The uniform-width kernel floors (round 5 — fewer Mosaic
    instantiations on TPU) must not change ANY bit of the level loop's
    outputs: padded histogram columns are never selected (ids < live m)
    and are sliced away; zero-padded route-table rows are never indexed.
    Asserted on the shared streaming_level_loop directly, since the
    production growers pick floors by backend.

    The histogram backend here must be the (interpret-mode) Pallas
    kernel — the engine the floors actually pad in production. Its
    per-column accumulation order is fixed by the kernel's row-tile
    loop, independent of M, so padding is bit-exact; the XLA matmul
    backend makes NO such guarantee (its reduction blocking follows the
    output shape — observed one-ulp histogram shifts under the suite's
    opt-level-1 flags), which is one more reason the floors are applied
    only on the kernel path."""
    _run_grow_floors_compare("pallas_interpret")


@pytest.mark.tpu
@pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="compiled Mosaic kernels need real TPU hardware",
)
def test_grow_floors_bit_identical_tpu_pallas():
    """ADVICE r5: the interpret-mode variant above validates the
    padding logic, but a future Mosaic kernel change could break
    M-independence only in the COMPILED kernel (tile-size selection,
    accumulation layout). On real hardware, run the same run(1,1) ==
    run(16,32) comparison through the production `pallas` backend so
    CI-on-TPU catches that class; skipped on CPU where Mosaic cannot
    compile."""
    _run_grow_floors_compare("pallas")


def test_route_rows_blocked_exact():
    """Row-blocked routing must be BIT-identical to the one-shot one-hot
    route — routing is integer compares, so blocking can't change it."""
    from ate_replication_causalml_tpu.models.forest import (
        route_rows,
        route_rows_blocked,
    )

    rng = np.random.default_rng(5)
    n, p, n_bins, m = 1000, 7, 16, 8
    codes = jnp.asarray(rng.integers(0, n_bins, (n, p)), jnp.int32)
    ids = jnp.asarray(rng.integers(0, m, n), jnp.int32)
    bf = jnp.asarray(rng.integers(0, p, m), jnp.int32)
    bb = jnp.asarray(rng.integers(0, n_bins, m), jnp.int32)
    oh = jax.nn.one_hot(ids, m, dtype=jnp.float32)
    want = route_rows(oh, bf, bb, codes.astype(jnp.float32), ids)
    got = route_rows_blocked(ids, bf, bb, codes, row_block=128)  # 8 blocks
    assert jnp.array_equal(got, want)
    # Vmapped over a tree axis (how the grow chunk uses it).
    ids_t = jnp.stack([ids, (ids + 3) % m])
    got_t = jax.vmap(lambda i_: route_rows_blocked(i_, bf, bb, codes, row_block=128))(
        ids_t
    )
    want_t = jnp.stack([
        route_rows(
            jax.nn.one_hot(i_, m, dtype=jnp.float32), bf, bb,
            codes.astype(jnp.float32), i_,
        )
        for i_ in ids_t
    ])
    assert jnp.array_equal(got_t, want_t)


def test_streaming_chunk_raises_tree_batch():
    """The streaming (Pallas) chunk policy must beat the 2-tree HBM cap
    at the million-row scale — that width is the histogram kernel's
    amortization factor."""
    from ate_replication_causalml_tpu.models.forest import auto_tree_chunk

    dense = auto_tree_chunk(1_000_000, 9, cap=32)
    stream = auto_tree_chunk(1_000_000, 9, cap=32, streaming=True)
    assert dense <= 2
    assert stream >= 8
    # Causal little-bag groups (2 trees/unit, full-level histograms).
    cf = auto_tree_chunk(
        500_000, 8, cap=16, trees_per_unit=2, leaf_onehot=True, streaming=True
    )
    assert cf >= 2


def test_forest_learns_signal():
    x, y = _classification_problem()
    forest = fit_forest_classifier(x, y, jax.random.key(0), n_trees=64, depth=7)
    pred = predict_forest(forest, x)
    # In-sample probability should separate classes strongly.
    auc_proxy = np.mean(np.asarray(pred.prob)[np.asarray(y) == 1]) - np.mean(
        np.asarray(pred.prob)[np.asarray(y) == 0]
    )
    assert auc_proxy > 0.3
    # OOB is honest: worse than in-sample but still informative.
    oob = predict_forest(forest, x, oob=True)
    oob_sep = np.mean(np.asarray(oob.vote)[np.asarray(y) == 1]) - np.mean(
        np.asarray(oob.vote)[np.asarray(y) == 0]
    )
    assert 0.1 < oob_sep <= auc_proxy + 0.05


def test_oob_mask_semantics():
    x, y = _classification_problem(n=600)
    forest = fit_forest_classifier(x, y, jax.random.key(1), n_trees=32, depth=6)
    counts = np.asarray(forest.counts)
    assert counts.shape == (32, 600)
    # Poisson(1) bootstrap: ~36.8% of rows OOB per tree.
    oob_frac = (counts == 0).mean()
    assert 0.30 < oob_frac < 0.44


def test_forest_apply_shapes_and_determinism():
    x, y = _classification_problem(n=400)
    forest = fit_forest_classifier(x, y, jax.random.key(2), n_trees=16, depth=5)
    codes = binarize(x, forest.bin_edges)
    leaf_a = forest_apply(forest, codes)
    leaf_b = forest_apply(forest, codes)
    assert leaf_a.shape == (16, 400)
    np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))
    # Same key -> same forest.
    forest2 = fit_forest_classifier(x, y, jax.random.key(2), n_trees=16, depth=5)
    np.testing.assert_array_equal(np.asarray(forest.split_feat), np.asarray(forest2.split_feat))




@pytest.fixture(scope="module")
def rf_prop(prep_small):
    """One 128-tree OOB propensity shared by the calibration and AIPW
    tests (VERDICT r2 #8: the fit, not the assertions, is the cost)."""
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    return frame32, np.asarray(
        rf_oob_propensity(frame32, jax.random.key(3), n_trees=128, depth=8))


def test_rf_oob_propensity_calibration(prep_small, rf_prop):
    _, frame_mod, _ = prep_small
    _, p = rf_prop
    w = np.asarray(frame_mod.w)
    assert p.shape == w.shape
    assert 0.0 <= p.min() and p.max() <= 1.0
    # Propensities should be higher for treated units on average
    # (selection made treatment predictable).
    assert p[w == 1].mean() > p[w == 0].mean() + 0.05


def test_aipw_rf_estimator(prep_small, rf_prop):
    _, frame_mod, _ = prep_small
    frame32, p_oob = rf_prop
    res = doubly_robust(
        frame32,
        propensity_fn=lambda f: p_oob,
        bootstrap_se=True,
        n_boot=500,
        key=jax.random.key(5),
    )
    assert np.isfinite(res.ate) and res.se > 0
    naive = naive_ate(frame_mod)
    assert abs(res.ate - 0.095) < abs(naive.ate - 0.095)


@pytest.fixture(scope="module")
def dml_r_default(prep_small):
    """The reference-mode double_ml fit both DML tests compare against —
    computed once per worker (round 5: the two tests re-ran the same
    96-tree fit; the computation is deterministic in (frame, key))."""
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    return frame32, double_ml(
        frame32, n_trees=96, depth=8, key=jax.random.key(6)
    )


def test_double_ml(prep_small, dml_r_default):
    _, frame_mod, _ = prep_small
    _, res = dml_r_default
    assert np.isfinite(res.ate) and res.se > 0
    naive = naive_ate(frame_mod)
    assert abs(res.ate - 0.095) < abs(naive.ate - 0.095) + 0.02
    # Pooled SE differs from the reference's averaged SE.
    res_p = double_ml(frame_mod.astype(jnp.float32), n_trees=96, depth=8,
                      key=jax.random.key(6), se_mode="pooled")
    assert abs(res_p.ate - res.ate) < 1e-6
    assert res_p.se != res.se


# @slow: the heavier crossfit='full' variant; test_double_ml keeps the
# default path (and its R-reference comparison) in tier-1 (budget).
@pytest.mark.slow
def test_double_ml_full_crossfit(prep_small, dml_r_default):
    """crossfit='full' (textbook DML: out-of-fold nuisances everywhere,
    one pooled residual OLS) must also de-bias the biased sample, and
    must genuinely differ from the reference's partial-cross-fitting
    path (whose nuisances predict in-sample on their own training
    fold)."""
    _, frame_mod, _ = prep_small
    frame32, res_r = dml_r_default
    res_f = double_ml(frame32, n_trees=96, depth=8, key=jax.random.key(6),
                      crossfit="full")
    assert np.isfinite(res_f.ate) and res_f.se > 0
    naive = naive_ate(frame_mod)
    assert abs(res_f.ate - 0.095) < abs(naive.ate - 0.095) + 0.02
    assert res_f.ate != res_r.ate  # different estimator, same seed
    import pytest

    with pytest.raises(ValueError, match="crossfit"):
        double_ml(frame32, crossfit="FULL")


def test_chernozhukov_residual_regression(prep_small):
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    n = frame32.n
    tau, se = chernozhukov(
        frame32, np.arange(n // 2), np.arange(n // 2, n), n_trees=64, depth=7,
        key=jax.random.key(7),
    )
    assert np.isfinite(float(tau)) and float(se) > 0


def test_superchunk_never_drops_trees(monkeypatch):
    """Regression: a non-divisor superchunk size once silently dropped
    trailing chunks (480 of 500 trees at exactly 100k rows). With
    pick_divisor the dispatch loop must cover every requested tree for
    awkward chunk/target combinations."""
    import ate_replication_causalml_tpu.models.forest as fm

    x, y = _classification_problem(n=300)
    # Force the historically-failing arithmetic: chunks of 20 (25 chunks
    # for 500 trees) with a dispatch target of 12 chunks.
    monkeypatch.setattr(fm, "auto_tree_chunk", lambda *a, **k: 20)
    monkeypatch.setattr(fm, "dispatch_tree_target", lambda n_rows: 12 * 20)
    forest = fm.fit_forest_classifier(x, y, jax.random.key(3), n_trees=500, depth=4)
    assert forest.n_trees == 500
    assert np.isfinite(np.asarray(forest.leaf_value)).all()


def test_center_invariance_binary():
    """The per-tree centering option (ADVICE r2) must not change SPLIT
    decisions — the criterion is invariant to a per-tree shift of y —
    and leaf values must agree after the add-back. Asserted directly on
    the chunk grower with center forced both ways."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.forest import (
        _grow_chunk,
        binarize,
        quantile_bins,
    )

    rng = np.random.default_rng(8)
    n = 1500
    x = jnp.asarray(rng.normal(size=(n, 6)), jnp.float32)
    y = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))
    edges = quantile_bins(x, 32)
    codes = binarize(x, edges)
    keys = jax.random.split(jax.random.key(0), 8)
    kw = dict(depth=5, mtry=2, n_bins=32, hist_backend="xla")
    off = _grow_chunk(keys, codes, y, None, jnp.float32(0.0), **kw)
    on = _grow_chunk(keys, codes, y, None, jnp.float32(1.0), **kw)
    # Invariance is exact in exact arithmetic (the shift adds a per-node
    # constant to every candidate's score); in f32 rare near-ties flip —
    # measured 97% identical splits with the flips confined to
    # no-consequence nodes (training predictions agree to ~1e-8).
    same = np.mean(
        (np.asarray(off[0]) == np.asarray(on[0]))
        & (np.asarray(off[1]) == np.asarray(on[1]))
    )
    assert same > 0.9, same
    pred_off = np.asarray(off[4]).mean(axis=0)  # forest-mean train pred
    pred_on = np.asarray(on[4]).mean(axis=0)
    np.testing.assert_allclose(pred_on, pred_off, rtol=0, atol=1e-4)


def test_offset_target_split_stability():
    """ADVICE r2 scenario: a regression target at a large offset
    (level >> spread). With per-tree centering the fitted structure must
    match the zero-level fit — without it, the f32 sibling subtraction
    parent − left loses the small right-child signal entirely."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.forest import (
        _is_binary01,
        fit_forest_regressor,
        predict_forest,
    )

    rng = np.random.default_rng(9)
    n = 2000
    x = jnp.asarray(rng.normal(size=(n, 5)), jnp.float32)
    signal = 0.8 * np.asarray(x[:, 0]) + 0.3 * np.asarray(x[:, 1])
    y0 = jnp.asarray((signal + 0.2 * rng.normal(size=n)).astype(np.float32))
    offset = 1000.0
    assert not _is_binary01(y0)  # continuous target → centered path
    f_base = fit_forest_regressor(x, y0, jax.random.key(3), n_trees=20,
                                  depth=6, hist_backend="xla")
    f_off = fit_forest_regressor(x, y0 + offset, jax.random.key(3), n_trees=20,
                                 depth=6, hist_backend="xla")
    # Same keys → same bootstrap/feature draws; centering makes the
    # split search see (almost) the same residuals, so the vast
    # majority of split decisions must coincide (f32 rounding of
    # y + 1000 can flip rare near-ties).
    same = np.mean(
        (np.asarray(f_base.split_feat) == np.asarray(f_off.split_feat))
        & (np.asarray(f_base.split_bin) == np.asarray(f_off.split_bin))
    )
    assert same > 0.9, same
    pred_base = np.asarray(predict_forest(f_base, x).prob)
    pred_off = np.asarray(predict_forest(f_off, x).prob) - offset
    # A rare flipped near-tie split reroutes a few rows; the ensemble
    # must agree everywhere else.
    diff = np.abs(pred_off - pred_base)
    assert diff.mean() < 0.02, diff.mean()
    assert (diff < 0.05).mean() > 0.97, (diff < 0.05).mean()
    # The fit itself must track the signal.
    assert np.corrcoef(pred_base, signal)[0, 1] > 0.9


def test_exact_subsample_mask():
    """The order-statistic half-sample mask: exactly s rows for every
    key, uniform inclusion, and deterministic per key."""
    import jax
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.forest import exact_subsample_mask

    n, s = 10_001, 4_567
    reps = 40
    counts = jnp.zeros(n)
    for i in range(reps):
        m = exact_subsample_mask(jax.random.key(i), n, s)
        assert int(m.sum()) == s, i
        counts = counts + m
    # Uniform inclusion PER ROW (the mean is s/n by construction —
    # exact size — so test the extremes): every row's inclusion rate
    # is Binomial(reps, s/n)-plausible. 6-sigma band with the n-way
    # multiplicity ≈ certain to pass for a uniform sampler, and a
    # sampler biased toward any index range (e.g. always the lowest s
    # rows) pins rows at rate 0 or 1 and fails immediately.
    import numpy as _np

    rate = _np.asarray(counts) / reps
    sd = (s / n * (1 - s / n) / reps) ** 0.5
    assert rate.min() > s / n - 6 * sd, rate.min()
    assert rate.max() < s / n + 6 * sd, rate.max()
    # Deterministic per key.
    a = exact_subsample_mask(jax.random.key(3), n, s)
    b = exact_subsample_mask(jax.random.key(3), n, s)
    assert bool(jnp.array_equal(a, b))
    # Forced-tie regime: many duplicate bit values (tiny n with a
    # constant-bits monkeypatch is overkill — s = n-1 and s = 1 hit the
    # tie-break code path boundaries).
    for s2 in (1, n - 1, n):
        m = exact_subsample_mask(jax.random.key(9), n, s2)
        assert int(m.sum()) == s2, s2


def test_exact_subsample_mask_matches_sort_kth():
    """The round-5 binary-search selection returns the SAME mask as the
    sort-based order statistic it replaced (same draws, same kth, same
    index tie-break) — including degenerate s and a forced-tie regime."""
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from ate_replication_causalml_tpu.models.forest import exact_subsample_mask

    n = 5_003
    for key_i, s in ((0, 1), (1, 2_501), (2, n - 1), (3, n), (4, 777)):
        key = jax.random.key(key_i)
        bits = jax.random.bits(key, (n,), jnp.uint32)
        kth = jnp.sort(bits)[s - 1]
        below = bits < kth
        short = s - jnp.sum(below.astype(jnp.int32))
        ties = bits == kth
        ref = below | (ties & (jnp.cumsum(ties.astype(jnp.int32)) <= short))
        got = exact_subsample_mask(key, n, s)
        assert bool(jnp.array_equal(got, ref)), (key_i, s)

    # Direct check of the kth==0 boundary the search special-cases:
    # all-zero bits means kth == 0 and the first s indices win.
    import ate_replication_causalml_tpu.models.forest as _f

    orig = jax.random.bits
    try:
        jax.random.bits = lambda *a, **k: jnp.zeros(a[1], jnp.uint32)
        m = _f.exact_subsample_mask(jax.random.key(0), 100, 7)
        assert int(m.sum()) == 7
        assert bool(m[:7].all()) and not bool(m[7:].any())
    finally:
        jax.random.bits = orig

"""Forest engine tests: split quality, OOB semantics, and the RF-backed
estimators (AIPW-RF, DML)."""

import jax
import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.estimators.aipw import doubly_robust
from ate_replication_causalml_tpu.estimators.dml import chernozhukov, double_ml
from ate_replication_causalml_tpu.estimators.naive import naive_ate
from ate_replication_causalml_tpu.models.forest import (
    binarize,
    fit_forest_classifier,
    forest_apply,
    predict_forest,
    quantile_bins,
    rf_oob_propensity,
)

RNG = np.random.default_rng(0)


def _classification_problem(n=2000, p=6):
    x = RNG.normal(size=(n, p))
    logits = 1.5 * x[:, 0] - 1.0 * x[:, 1] + 0.5 * x[:, 2] * x[:, 3]
    y = (RNG.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32)


def test_binarize_roundtrip():
    x = jnp.asarray(RNG.normal(size=(500, 3)), jnp.float32)
    edges = quantile_bins(x, 16)
    codes = np.asarray(binarize(x, edges))
    assert codes.min() >= 0 and codes.max() <= 15
    # Monotone: larger value -> same or larger bin.
    col = np.asarray(x[:, 0])
    order = np.argsort(col)
    assert np.all(np.diff(codes[order, 0]) >= 0)


def test_forest_learns_signal():
    x, y = _classification_problem()
    forest = fit_forest_classifier(x, y, jax.random.key(0), n_trees=64, depth=7)
    pred = predict_forest(forest, x)
    # In-sample probability should separate classes strongly.
    auc_proxy = np.mean(np.asarray(pred.prob)[np.asarray(y) == 1]) - np.mean(
        np.asarray(pred.prob)[np.asarray(y) == 0]
    )
    assert auc_proxy > 0.3
    # OOB is honest: worse than in-sample but still informative.
    oob = predict_forest(forest, x, oob=True)
    oob_sep = np.mean(np.asarray(oob.vote)[np.asarray(y) == 1]) - np.mean(
        np.asarray(oob.vote)[np.asarray(y) == 0]
    )
    assert 0.1 < oob_sep <= auc_proxy + 0.05


def test_oob_mask_semantics():
    x, y = _classification_problem(n=600)
    forest = fit_forest_classifier(x, y, jax.random.key(1), n_trees=32, depth=6)
    counts = np.asarray(forest.counts)
    assert counts.shape == (32, 600)
    # Poisson(1) bootstrap: ~36.8% of rows OOB per tree.
    oob_frac = (counts == 0).mean()
    assert 0.30 < oob_frac < 0.44


def test_forest_apply_shapes_and_determinism():
    x, y = _classification_problem(n=400)
    forest = fit_forest_classifier(x, y, jax.random.key(2), n_trees=16, depth=5)
    codes = binarize(x, forest.bin_edges)
    leaf_a = forest_apply(forest, codes)
    leaf_b = forest_apply(forest, codes)
    assert leaf_a.shape == (16, 400)
    np.testing.assert_array_equal(np.asarray(leaf_a), np.asarray(leaf_b))
    # Same key -> same forest.
    forest2 = fit_forest_classifier(x, y, jax.random.key(2), n_trees=16, depth=5)
    np.testing.assert_array_equal(np.asarray(forest.split_feat), np.asarray(forest2.split_feat))


def test_rf_oob_propensity_calibration(prep_small):
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    p = np.asarray(rf_oob_propensity(frame32, jax.random.key(3), n_trees=128, depth=8))
    w = np.asarray(frame_mod.w)
    assert p.shape == w.shape
    assert 0.0 <= p.min() and p.max() <= 1.0
    # Propensities should be higher for treated units on average
    # (selection made treatment predictable).
    assert p[w == 1].mean() > p[w == 0].mean() + 0.05


def test_aipw_rf_estimator(prep_small):
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    res = doubly_robust(
        frame32,
        propensity_fn=lambda f: rf_oob_propensity(f, jax.random.key(4), n_trees=128, depth=8),
        bootstrap_se=True,
        n_boot=500,
        key=jax.random.key(5),
    )
    assert np.isfinite(res.ate) and res.se > 0
    naive = naive_ate(frame_mod)
    assert abs(res.ate - 0.095) < abs(naive.ate - 0.095)


def test_double_ml(prep_small):
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    res = double_ml(frame32, n_trees=96, depth=8, key=jax.random.key(6))
    assert np.isfinite(res.ate) and res.se > 0
    naive = naive_ate(frame_mod)
    assert abs(res.ate - 0.095) < abs(naive.ate - 0.095) + 0.02
    # Pooled SE differs from the reference's averaged SE.
    res_p = double_ml(frame_mod.astype(jnp.float32), n_trees=96, depth=8,
                      key=jax.random.key(6), se_mode="pooled")
    assert abs(res_p.ate - res.ate) < 1e-6
    assert res_p.se != res.se


def test_chernozhukov_residual_regression(prep_small):
    _, frame_mod, _ = prep_small
    frame32 = frame_mod.astype(jnp.float32)
    n = frame32.n
    tau, se = chernozhukov(
        frame32, np.arange(n // 2), np.arange(n // 2, n), n_trees=64, depth=7,
        key=jax.random.key(7),
    )
    assert np.isfinite(float(tau)) and float(se) > 0


def test_superchunk_never_drops_trees(monkeypatch):
    """Regression: a non-divisor superchunk size once silently dropped
    trailing chunks (480 of 500 trees at exactly 100k rows). With
    pick_divisor the dispatch loop must cover every requested tree for
    awkward chunk/target combinations."""
    import ate_replication_causalml_tpu.models.forest as fm

    x, y = _classification_problem(n=300)
    # Force the historically-failing arithmetic: chunks of 20 (25 chunks
    # for 500 trees) with a dispatch target of 12 chunks.
    monkeypatch.setattr(fm, "auto_tree_chunk", lambda *a, **k: 20)
    monkeypatch.setattr(fm, "dispatch_tree_target", lambda n_rows: 12 * 20)
    forest = fm.fit_forest_classifier(x, y, jax.random.key(3), n_trees=500, depth=4)
    assert forest.n_trees == 500
    assert np.isfinite(np.asarray(forest.leaf_value)).all()

"""End-to-end estimator tests on synthetic GGL-like data.

Mirrors the reference's implicit validation strategy (SURVEY.md §4):
the RCT difference-in-means on the unbiased sample is the oracle; the
naive estimate on the biased sample must be badly wrong; the adjustment
estimators must land near the oracle.
"""

import jax
import numpy as np
import pytest

from ate_replication_causalml_tpu.estimators.aipw import (
    aipw_sandwich_se,
    aipw_tau,
    clip_propensity,
    doubly_robust_glm,
)
from ate_replication_causalml_tpu.estimators.base import EstimatorResult, ResultTable
from ate_replication_causalml_tpu.estimators.ipw import (
    logistic_propensity,
    prop_score_ols,
    prop_score_weight,
)
from ate_replication_causalml_tpu.estimators.naive import naive_ate
from ate_replication_causalml_tpu.estimators.ols import ate_condmean_ols

TRUE_ATE = 0.095


def test_oracle_brackets_truth(raw_small, prep_small):
    frame, _, _ = prep_small
    res = naive_ate(frame, method="oracle")
    # The oracle must agree with the *population* difference-in-means of
    # the finite synthetic population it was subsampled from (the
    # nominal 0.095 carries generator noise of ~0.01 at n=20k on top of
    # the subsampling noise).
    w = raw_small["treat_neighbors"]
    y = raw_small["outcome_voted"]
    pop = y[w == 1].mean() - y[w == 0].mean()
    assert abs(res.ate - pop) < 3.5 * res.se
    assert abs(res.ate - TRUE_ATE) < 0.06


def test_bias_injection_biases_naive(prep_small):
    frame, frame_mod, dropped = prep_small
    assert frame_mod.n == frame.n - len(dropped)
    assert len(dropped) > 0.4 * frame.n  # the injection removes most rows
    naive = naive_ate(frame_mod)
    oracle = naive_ate(frame)
    # The constructed selection pushes the naive estimate well below the oracle.
    assert naive.ate < oracle.ate - 0.03


def test_direct_method_reduces_bias(prep_small):
    frame, frame_mod, _ = prep_small
    res = ate_condmean_ols(frame_mod)
    naive = naive_ate(frame_mod)
    assert abs(res.ate - TRUE_ATE) < abs(naive.ate - TRUE_ATE)


def test_ipw_pair(prep_small):
    _, frame_mod, _ = prep_small
    p = logistic_propensity(frame_mod.x, frame_mod.w)
    p_np = np.asarray(p)
    assert ((p_np > 0) & (p_np < 1)).all()
    psw = prop_score_weight(frame_mod, p)
    psols = prop_score_ols(frame_mod, p)
    naive = naive_ate(frame_mod)
    for res in (psw, psols):
        assert np.isfinite(res.ate) and np.isfinite(res.se)
        assert abs(res.ate - TRUE_ATE) < abs(naive.ate - TRUE_ATE) + 0.02


def test_aipw_glm_sandwich_and_bootstrap(prep_small):
    _, frame_mod, _ = prep_small
    sand = doubly_robust_glm(frame_mod, bootstrap_se=False)
    boot = doubly_robust_glm(
        frame_mod, bootstrap_se=True, n_boot=1000, key=jax.random.key(42)
    )
    # Same point estimate; SEs in the same ballpark (bootstrap vs IF).
    assert abs(sand.ate - boot.ate) < 1e-9
    assert sand.se > 0 and boot.se > 0
    assert 0.5 < sand.se / boot.se < 2.0
    assert abs(sand.ate - TRUE_ATE) < 0.05


def test_aipw_core_matches_numpy(prep_small):
    _, frame_mod, _ = prep_small
    rng = np.random.default_rng(0)
    n = frame_mod.n
    w = np.asarray(frame_mod.w)
    y = np.asarray(frame_mod.y)
    p = rng.uniform(0.1, 0.9, n)
    mu0 = rng.uniform(0.1, 0.9, n)
    mu1 = rng.uniform(0.1, 0.9, n)
    tau = float(aipw_tau(w, y, p, mu0, mu1))
    est1 = w * (y - mu1) / p + (1 - w) * (y - mu0) / (1 - p)
    want = est1.mean() + (mu1 - mu0).mean()
    np.testing.assert_allclose(tau, want, atol=1e-12)
    se = float(aipw_sandwich_se(w, y, p, mu0, mu1, tau))
    ii = (w * y) / p - mu1 * (w - p) / p - (((1 - w) * y / (1 - p)) + (mu0 * (w - p) / (1 - p))) - want
    np.testing.assert_allclose(se, np.sqrt((ii**2).sum() / n**2), atol=1e-12)


def _dr_property_data():
    """Confounded DGP with an ASYMMETRIC confounder (E[x] != 0), so the
    reference's sign quirk cannot cancel by symmetry."""
    rng = np.random.default_rng(42)
    n, tau = 200_000, 0.3
    x1 = rng.normal(size=n) + 0.7
    p_true = 1.0 / (1.0 + np.exp(-(0.8 * x1 - 0.4)))
    w = (rng.uniform(size=n) < p_true).astype(np.float64)
    # E[Y | x, w] = 0.5*x1 + tau*w — confounded through x1.
    y = 0.5 * x1 + tau * w + 0.1 * rng.normal(size=n)
    mu0_true = 0.5 * x1
    mu1_true = 0.5 * x1 + tau
    mu_wrong = np.zeros(n)               # ignores the confounder
    p_wrong = np.full(n, w.mean())       # ignores the confounder
    return tau, x1, p_true, w, y, mu0_true, mu1_true, mu_wrong, p_wrong


def test_aipw_double_robustness_property_fixed_mode():
    """The defining AIPW property (SURVEY.md §4): with ``compat="fixed"``
    (textbook AIPW) the combination stays consistent when EITHER
    nuisance is misspecified, as long as the other is correct.
    Closed-form nuisances, no fitting — this pins the combination
    formula itself. The doubly-wrong case is the negative control:
    if it were not visibly biased the property test would prove
    nothing."""
    tau, _, p_true, w, y, mu0_t, mu1_t, mu_w, p_w = _dr_property_data()
    j = jax.numpy.asarray
    n = w.shape[0]
    f = lambda p, m0, m1: float(
        aipw_tau(j(w), j(y), j(p), j(m0), j(m1), compat="fixed")
    )
    se = 3.0 / np.sqrt(n)  # generous MC tolerance
    assert abs(f(p_true, mu_w, mu_w) - tau) < se      # p right, mu wrong
    assert abs(f(p_w, mu0_t, mu1_t) - tau) < se       # mu right, p wrong
    assert abs(f(p_w, mu_w, mu_w) - tau) > 0.05       # both wrong: biased
    naive = y[w == 1].mean() - y[w == 0].mean()
    assert abs(naive - tau) > 0.05                    # confounding is real
    # Both nuisances right: consistent too, of course.
    assert abs(f(p_true, mu0_t, mu1_t) - tau) < se


def test_aipw_reference_sign_quirk_pinned():
    """The reference's published combination ADDS the control
    augmentation (``ate_functions.R:183``) where standard AIPW
    subtracts it. Pin the quirk's observable consequences so nobody
    'fixes' compat="r" into silent parity breakage: (a) with both
    nuisances correct the r-formula is still consistent (each
    augmentation term is mean-zero); (b) with only the propensity
    correct it is NOT (double robustness lost) — while the fixed mode
    is; (c) the two modes differ by exactly twice the control
    augmentation term."""
    tau, _, p_true, w, y, mu0_t, mu1_t, mu_w, _ = _dr_property_data()
    j = jax.numpy.asarray
    n = w.shape[0]
    se = 3.0 / np.sqrt(n)
    r = lambda p, m0, m1: float(aipw_tau(j(w), j(y), j(p), j(m0), j(m1)))
    assert abs(r(p_true, mu0_t, mu1_t) - tau) < se        # both right: ok
    est_r_bad = r(p_true, mu_w, mu_w)
    assert abs(est_r_bad - tau) > 0.05, est_r_bad          # NOT doubly robust
    # Exact algebraic relation between the modes:
    fixed = float(aipw_tau(j(w), j(y), j(p_true), j(mu_w), j(mu_w), compat="fixed"))
    ctrl = np.mean((1.0 - w) * (y - mu_w) / (1.0 - p_true))
    assert est_r_bad - fixed == pytest.approx(2.0 * ctrl, rel=1e-5)


def test_doubly_robust_glm_compat_threads_through_bootstrap(prep_small):
    """compat='fixed' must reach every layer: the point estimate AND the
    bootstrap replicates (a sign applied to the point estimate only
    would silently bootstrap the wrong statistic). On the biased sample
    the two modes must produce different estimates (asymmetric data) and
    each mode's bootstrap must resample its own combination."""
    _, frame_mod, _ = prep_small
    key = jax.random.key(3)
    r_mode = doubly_robust_glm(frame_mod, bootstrap_se=True, n_boot=200, key=key)
    f_mode = doubly_robust_glm(
        frame_mod, bootstrap_se=True, n_boot=200, key=key, compat="fixed"
    )
    assert r_mode.ate != f_mode.ate  # point-estimate threading
    # Bootstrap threading: under the SHARED key the index streams are
    # identical, so the only way the bootstrap SDs can differ is the
    # replicates resampling different combinations — a bootstrap that
    # ignored compat would produce exactly equal SEs here.
    assert r_mode.se != f_mode.se
    for res in (r_mode, f_mode):
        assert np.isfinite(res.ate) and res.se > 0
        # Sanity (not a threading probe): each mode's bootstrap SD is in
        # the neighborhood of its own sandwich SE.
        sandwich = doubly_robust_glm(
            frame_mod,
            bootstrap_se=False,
            compat="r" if res is r_mode else "fixed",
        )
        assert 0.5 * sandwich.se < res.se < 2.0 * sandwich.se

    with pytest.raises(ValueError, match="compat"):
        doubly_robust_glm(frame_mod, compat="R")


def test_clip_propensity():
    p = np.array([0.0, 0.2, 0.5, 1.0, 0.9])
    got = np.asarray(clip_propensity(p))
    np.testing.assert_allclose(got, [0.2, 0.2, 0.5, 0.9, 0.9])


def test_result_table_roundtrip():
    t = ResultTable()
    t.append(EstimatorResult.from_point_se("oracle", 0.095, 0.005))
    t.append(EstimatorResult.point_only("Usual LASSO", 0.025))
    s = t.to_json()
    t2 = ResultTable.from_json(s)
    assert t2.methods() == ["oracle", "Usual LASSO"]
    assert t2["Usual LASSO"].lower_ci == t2["Usual LASSO"].ate

"""End-to-end estimator tests on synthetic GGL-like data.

Mirrors the reference's implicit validation strategy (SURVEY.md §4):
the RCT difference-in-means on the unbiased sample is the oracle; the
naive estimate on the biased sample must be badly wrong; the adjustment
estimators must land near the oracle.
"""

import jax
import numpy as np

from ate_replication_causalml_tpu.estimators.aipw import (
    aipw_sandwich_se,
    aipw_tau,
    clip_propensity,
    doubly_robust_glm,
)
from ate_replication_causalml_tpu.estimators.base import EstimatorResult, ResultTable
from ate_replication_causalml_tpu.estimators.ipw import (
    logistic_propensity,
    prop_score_ols,
    prop_score_weight,
)
from ate_replication_causalml_tpu.estimators.naive import naive_ate
from ate_replication_causalml_tpu.estimators.ols import ate_condmean_ols

TRUE_ATE = 0.095


def test_oracle_brackets_truth(raw_small, prep_small):
    frame, _, _ = prep_small
    res = naive_ate(frame, method="oracle")
    # The oracle must agree with the *population* difference-in-means of
    # the finite synthetic population it was subsampled from (the
    # nominal 0.095 carries generator noise of ~0.01 at n=20k on top of
    # the subsampling noise).
    w = raw_small["treat_neighbors"]
    y = raw_small["outcome_voted"]
    pop = y[w == 1].mean() - y[w == 0].mean()
    assert abs(res.ate - pop) < 3.5 * res.se
    assert abs(res.ate - TRUE_ATE) < 0.06


def test_bias_injection_biases_naive(prep_small):
    frame, frame_mod, dropped = prep_small
    assert frame_mod.n == frame.n - len(dropped)
    assert len(dropped) > 0.4 * frame.n  # the injection removes most rows
    naive = naive_ate(frame_mod)
    oracle = naive_ate(frame)
    # The constructed selection pushes the naive estimate well below the oracle.
    assert naive.ate < oracle.ate - 0.03


def test_direct_method_reduces_bias(prep_small):
    frame, frame_mod, _ = prep_small
    res = ate_condmean_ols(frame_mod)
    naive = naive_ate(frame_mod)
    assert abs(res.ate - TRUE_ATE) < abs(naive.ate - TRUE_ATE)


def test_ipw_pair(prep_small):
    _, frame_mod, _ = prep_small
    p = logistic_propensity(frame_mod.x, frame_mod.w)
    p_np = np.asarray(p)
    assert ((p_np > 0) & (p_np < 1)).all()
    psw = prop_score_weight(frame_mod, p)
    psols = prop_score_ols(frame_mod, p)
    naive = naive_ate(frame_mod)
    for res in (psw, psols):
        assert np.isfinite(res.ate) and np.isfinite(res.se)
        assert abs(res.ate - TRUE_ATE) < abs(naive.ate - TRUE_ATE) + 0.02


def test_aipw_glm_sandwich_and_bootstrap(prep_small):
    _, frame_mod, _ = prep_small
    sand = doubly_robust_glm(frame_mod, bootstrap_se=False)
    boot = doubly_robust_glm(
        frame_mod, bootstrap_se=True, n_boot=1000, key=jax.random.key(42)
    )
    # Same point estimate; SEs in the same ballpark (bootstrap vs IF).
    assert abs(sand.ate - boot.ate) < 1e-9
    assert sand.se > 0 and boot.se > 0
    assert 0.5 < sand.se / boot.se < 2.0
    assert abs(sand.ate - TRUE_ATE) < 0.05


def test_aipw_core_matches_numpy(prep_small):
    _, frame_mod, _ = prep_small
    rng = np.random.default_rng(0)
    n = frame_mod.n
    w = np.asarray(frame_mod.w)
    y = np.asarray(frame_mod.y)
    p = rng.uniform(0.1, 0.9, n)
    mu0 = rng.uniform(0.1, 0.9, n)
    mu1 = rng.uniform(0.1, 0.9, n)
    tau = float(aipw_tau(w, y, p, mu0, mu1))
    est1 = w * (y - mu1) / p + (1 - w) * (y - mu0) / (1 - p)
    want = est1.mean() + (mu1 - mu0).mean()
    np.testing.assert_allclose(tau, want, atol=1e-12)
    se = float(aipw_sandwich_se(w, y, p, mu0, mu1, tau))
    ii = (w * y) / p - mu1 * (w - p) / p - (((1 - w) * y / (1 - p)) + (mu0 * (w - p) / (1 - p))) - want
    np.testing.assert_allclose(se, np.sqrt((ii**2).sum() / n**2), atol=1e-12)


def test_clip_propensity():
    p = np.array([0.0, 0.2, 0.5, 1.0, 0.9])
    got = np.asarray(clip_propensity(p))
    np.testing.assert_allclose(got, [0.2, 0.2, 0.5, 0.9, 0.9])


def test_result_table_roundtrip():
    t = ResultTable()
    t.append(EstimatorResult.from_point_se("oracle", 0.095, 0.005))
    t.append(EstimatorResult.point_only("Usual LASSO", 0.025))
    s = t.to_json()
    t2 = ResultTable.from_json(s)
    assert t2.methods() == ["oracle", "Usual LASSO"]
    assert t2["Usual LASSO"].lower_ci == t2["Usual LASSO"].ate

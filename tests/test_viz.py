"""Figure content assertions (VERDICT round 1, weak #5): the charts must
contain the drawn data, not merely exist as non-empty PNG files."""

import numpy as np
import pytest

from ate_replication_causalml_tpu.estimators.base import EstimatorResult
from ate_replication_causalml_tpu.viz import notebook_figures, pointrange_figure


def _row(method, ate, half=0.02):
    return EstimatorResult(
        method=method, ate=ate, lower_ci=ate - half, upper_ci=ate + half
    )


ORACLE = _row("naive", 0.095, 0.011)
ROWS = [
    _row("naive", 0.003, 0.027),
    _row("Direct Method", 0.078),
    _row("Doubly Robust", 0.080),
]


def test_pointrange_marks_carry_plotted_arrays():
    chart = pointrange_figure(ROWS, oracle=ORACLE)
    assert [m.method for m in chart.marks] == [r.method for r in ROWS]
    for mark, r in zip(chart.marks, ROWS):
        assert mark.ate == pytest.approx(float(r.ate))
        assert mark.lower == pytest.approx(float(r.lower_ci))
        assert mark.upper == pytest.approx(float(r.upper_ci))
    lo, hi, center = chart.oracle_band
    assert (lo, hi, center) == pytest.approx(
        (float(ORACLE.lower_ci), float(ORACLE.upper_ci), float(ORACLE.ate))
    )


def test_pointrange_axes_actually_drawn():
    """Introspect the matplotlib artists: every CI segment and point
    marker must exist on the axes with the right coordinates — a
    refactor that fills the metadata but draws nothing must fail."""
    chart = pointrange_figure(ROWS, oracle=ORACLE)
    ax = chart.figure.axes[0]
    segments = []   # (xdata, ydata) of 2-point CI lines
    points = []     # (x, y) of single-point markers
    for line in ax.lines:
        x, y = np.asarray(line.get_xdata(), float), np.asarray(line.get_ydata(), float)
        if x.size == 2 and y.size == 2 and y[0] == y[1]:
            segments.append((tuple(x), y[0]))
        elif x.size == 1:
            points.append((x[0], y[0]))
    for mark in chart.marks:
        assert ((mark.lower, mark.upper), mark.y) in [
            (s, yy) for s, yy in segments
        ] or any(
            np.allclose(s, (mark.lower, mark.upper)) and yy == mark.y
            for s, yy in segments
        )
        assert any(
            np.isclose(px, mark.ate) and np.isclose(py, mark.y) for px, py in points
        )
    # Oracle band: an axvspan patch spanning [lower, upper] and the
    # center line at the oracle ATE.
    spans = [p.get_extents() for p in ax.patches]
    assert any(
        np.isclose(p.get_x(), chart.oracle_band[0])
        and np.isclose(p.get_x() + p.get_width(), chart.oracle_band[1])
        for p in ax.patches
        if hasattr(p, "get_x")
    ), f"no oracle band patch found among {len(spans)} patches"
    # y tick labels are the method names, top-down.
    labels = [t.get_text() for t in ax.get_yticklabels()]
    assert labels == [r.method for r in ROWS]


def test_notebook_figures_fail_on_blank(tmp_path, monkeypatch):
    """notebook_figures must raise when a chart comes back with no drawn
    rows (the blank-axes regression VERDICT asked to make impossible)."""
    import ate_replication_causalml_tpu.viz as viz

    paths = notebook_figures(ROWS, ORACLE, str(tmp_path))
    assert len(paths) == 3
    for p in paths:
        import os

        assert os.path.getsize(p) > 0

    real = viz.pointrange_figure

    def blank(results, oracle=None, title="", path=None, **kw):
        chart = real([], oracle=oracle, title=title, path=path, **kw)
        return chart

    monkeypatch.setattr(viz, "pointrange_figure", blank)
    with pytest.raises(RuntimeError, match="did not draw"):
        notebook_figures(ROWS, ORACLE, str(tmp_path))

"""ISSUE 10: the in-kernel stable-bin-partition histogram mode.

Contracts pinned here:

* **Dense/partition bit-identity** across the A/B matrix (rows ×
  width × weight-stack mode) wherever the per-cell sums are
  order-exact: every INTEGER-valued weight stack (the classifier
  engine's counts / counts·y∈{0,1} — f32 sums below 2^24 are exact in
  any association). Both kernels sum the same member products in the
  same stable row order, so on the MXU's fixed sequential-in-K
  accumulation the identity extends to FLOAT stacks too — asserted by
  the compiled ``@pytest.mark.tpu`` variants. On the CPU interpret
  backend XLA/Eigen folds a long gemm's K axis in 256-wide panels
  (measured, PR 10 — see _hist_kernel_batched_partition's
  docstring), so float stacks are pinned here at a few-ulp tolerance
  with the association rationale, exactly like the batched-vs-single
  comparison in test_hist_pallas.py.
* **The mode policy**: env parsing at config time, the pure crossover
  heuristic, the per-width decision (one mode per kernel width — the
  instantiation set is reused, not multiplied), and the FLOP model's
  internal consistency (useful ≤ total; useful is mode-independent;
  dense's useful fraction decays like 1/width while partition's is
  depth-independent).
* **Grower integration**: binary-classifier fits are bit-identical
  across modes end-to-end; the causal ρ-decomposed grower agrees at
  the statistical contract; kernel dispatches are metered into
  ``hist_kernel_dispatch_total{mode, engine}``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.ops.hist_pallas import (
    _check_mode,
    bin_histogram_batched,
    bin_histogram_pallas_batched,
    bin_histogram_pallas_batched_shared,
    bin_histogram_shared,
    hist_level_flops,
    mode_for_width,
    partition_crossover_width,
    resolve_hist_mode,
)

ON_TPU = jax.default_backend() == "tpu"


def _numpy_hist(codes, node, weights, max_nodes, n_bins):
    k_w, n = weights.shape
    p = codes.shape[1]
    out = np.zeros((k_w, max_nodes, p, n_bins), np.float64)
    for i in range(n):
        m = node[i]
        if 0 <= m < max_nodes:
            for f in range(p):
                out[:, m, f, codes[i, f]] += weights[:, i]
    return out


def _case(n, width, k_w, trees=2, p=5, n_bins=16, integer=True, seed=0):
    rng = np.random.default_rng(seed)
    codes = jnp.asarray(rng.integers(0, n_bins, (n, p)), jnp.int32)
    nodes = jnp.asarray(rng.integers(-1, width, (trees, n)), jnp.int32)
    if integer:
        w = rng.poisson(1.0, (trees, k_w, n)).astype(np.float32)
        w[:, 1:] *= rng.integers(-2, 3, (trees, k_w - 1, n)).astype(np.float32)
    else:
        w = rng.uniform(-2, 2, (trees, k_w, n)).astype(np.float32)
    return codes, nodes, jnp.asarray(w)


def test_partition_matches_numpy_truth():
    codes, nodes, w = _case(1000, 8, 2, integer=False, seed=1)
    got = bin_histogram_pallas_batched(
        codes, nodes, w, max_nodes=8, n_bins=16, tile=256, interpret=True,
        partition=True,
    )
    for t in range(nodes.shape[0]):
        truth = _numpy_hist(np.asarray(codes), np.asarray(nodes[t]),
                            np.asarray(w[t]), 8, 16)
        np.testing.assert_allclose(np.asarray(got[t]), truth, rtol=0, atol=1e-4)


# The A/B matrix (acceptance): kernel widths through depth 9 — the
# streaming growers' deepest level at depth 9 runs width 2^7 = 128.
@pytest.mark.parametrize("width", [1, 2, 4, 8, 16, 32, 64, 128])
def test_partition_bit_identical_integer_all_widths(width):
    """Per-tree layout (the classifier engine's stack shape), integer
    weights: dense and partition modes are BIT-identical at every
    kernel width — exact sums are association-invariant, so this holds
    on every backend and jaxlib."""
    codes, nodes, w = _case(1000, width, 2, seed=width)
    kw = dict(max_nodes=width, n_bins=16, tile=256, interpret=True)
    dense = bin_histogram_pallas_batched(codes, nodes, w, **kw)
    part = bin_histogram_pallas_batched(codes, nodes, w, partition=True, **kw)
    assert jnp.array_equal(dense, part)


@pytest.mark.parametrize("n,width", [(9216, 16), (9216, 128), (65536, 64)])
def test_partition_bit_identical_integer_large_rows(n, width):
    """The multi-tile regime (default 2048-row tiles): 9k rows (the
    reference's own scale) and a 64k-row cell. Cross-tile accumulation
    order is the SAME out_ref += per-tile fold in both modes."""
    codes, nodes, w = _case(n, width, 2, trees=1, seed=n + width)
    kw = dict(max_nodes=width, n_bins=16, interpret=True)
    dense = bin_histogram_pallas_batched(codes, nodes, w, **kw)
    part = bin_histogram_pallas_batched(codes, nodes, w, partition=True, **kw)
    assert jnp.array_equal(dense, part)


@pytest.mark.parametrize("width", [1, 8, 64, 128])
def test_partition_shared_weights_bit_identical_integer(width):
    """The causal grower's kernel shape: ONE shared (K=5, n) stack with
    membership folded into the id stream. Integer-valued stacks are
    bit-identical across modes; the 5-stream layout and the −1 masking
    flow through the partition (masked rows land in the trash region
    and contribute nothing)."""
    rng = np.random.default_rng(width + 7)
    n = 1000
    codes = jnp.asarray(rng.integers(0, 16, (n, 5)), jnp.int32)
    member = rng.integers(0, 2, (3, n)).astype(np.int32)
    nodes = rng.integers(0, width, (3, n)).astype(np.int32)
    ids = jnp.asarray(np.where(member > 0, nodes, -1).astype(np.int32))
    shared = jnp.asarray(
        rng.integers(-3, 4, (5, n)).astype(np.float32)
    )
    kw = dict(max_nodes=width, n_bins=16, tile=256, interpret=True)
    dense = bin_histogram_pallas_batched_shared(codes, ids, shared, **kw)
    part = bin_histogram_pallas_batched_shared(
        codes, ids, shared, partition=True, **kw
    )
    assert jnp.array_equal(dense, part)


def test_partition_float_ulp_on_cpu_interpret():
    """The causal 5-stream FLOAT stack under interpret mode: the two
    modes sum each cell's member products in the same row order, but
    XLA:CPU reduces dense's long gemm in 256-wide K panels while the
    partition kernel folds node-pure 8-row blocks — a pure f32
    reassociation, bounded at a few ulp of the cell magnitudes
    (measured 1e-6-scale on this image). On the MXU both modes
    accumulate sequentially in K, and the @tpu variant below asserts
    exact equality there. Bit-exactness for every INTEGER stack is the
    unconditional contract (tests above)."""
    rng = np.random.default_rng(11)
    n = 2048
    codes = jnp.asarray(rng.integers(0, 16, (n, 5)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, 16, (2, n)), jnp.int32)
    wt = rng.normal(size=n).astype(np.float32) * 0.5
    yt = rng.normal(size=n).astype(np.float32)
    mom5 = jnp.asarray(np.stack([np.ones_like(wt), wt, yt, wt * wt, wt * yt]))
    kw = dict(max_nodes=16, n_bins=16, tile=256, interpret=True)
    dense = bin_histogram_pallas_batched_shared(codes, ids, mom5, **kw)
    part = bin_histogram_pallas_batched_shared(
        codes, ids, mom5, partition=True, **kw
    )
    np.testing.assert_allclose(
        np.asarray(part), np.asarray(dense), rtol=1e-5, atol=1e-5
    )


@pytest.mark.tpu
@pytest.mark.skipif(not ON_TPU, reason="compiled Mosaic kernels need TPU")
@pytest.mark.parametrize("width", [16, 64, 128])
def test_partition_bit_identical_float_tpu_compiled(width):
    """On real hardware the MXU accumulates every dot sequentially in
    K, so the stable partition preserves each cell's f32 accumulation
    order EXACTLY — dense and partition must be bit-identical for
    float stacks too, through the COMPILED kernels."""
    rng = np.random.default_rng(width)
    n = 65536
    codes = jnp.asarray(rng.integers(0, 64, (n, 21)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, width, (4, n)), jnp.int32)
    mom5 = jnp.asarray(rng.normal(size=(5, n)).astype(np.float32))
    kw = dict(max_nodes=width, n_bins=64)
    dense = bin_histogram_pallas_batched_shared(codes, ids, mom5, **kw)
    part = bin_histogram_pallas_batched_shared(
        codes, ids, mom5, partition=True, **kw
    )
    assert jnp.array_equal(dense, part)


def test_partition_through_dispatch_and_vmap():
    """The dispatcher + custom_vmap path: partition mode collapses
    nested vmaps into tree-batched partition kernel calls exactly like
    dense mode, and per-slice calls match the collapsed call (per-tree
    numerics are batch-size-independent in BOTH modes since PR 10)."""
    rng = np.random.default_rng(3)
    n = 700
    codes = jnp.asarray(rng.integers(0, 16, (n, 5)), jnp.int32)
    nodes = jnp.asarray(rng.integers(0, 8, (4, n)), jnp.int32)
    weights = jnp.asarray(rng.poisson(1.0, (4, 2, n)).astype(np.float32))

    def one(nd, w):
        return bin_histogram_batched(
            codes, nd[None], w[None], max_nodes=8, n_bins=16,
            backend="pallas_interpret", mode="partition",
        )[0]

    got = jax.vmap(one)(nodes, weights)
    want = jnp.stack([one(nodes[t], weights[t]) for t in range(4)])
    assert jnp.array_equal(got, want)


def test_partition_floors_bit_identical():
    """Width padding (the uniform-instantiation floors) cannot change a
    partition-mode bit EVEN FOR FLOAT weights: the per-block dots never
    see the padded width — node 0..m_live regions are laid out
    identically and padded nodes own zero blocks. (Dense mode's floor
    invariance rests on the M-independence of the kernel's dot
    association — test_forest.py::test_grow_floors_bit_identical.)"""
    rng = np.random.default_rng(9)
    n = 1000
    codes = jnp.asarray(rng.integers(0, 16, (n, 5)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, 4, (2, n)), jnp.int32)
    mom = jnp.asarray(rng.normal(size=(5, n)).astype(np.float32))
    kw = dict(n_bins=16, tile=256, interpret=True, partition=True)
    live = bin_histogram_pallas_batched_shared(codes, ids, mom, max_nodes=4, **kw)
    padded = bin_histogram_pallas_batched_shared(
        codes, ids, mom, max_nodes=16, **kw
    )
    assert jnp.array_equal(live, padded[:, :, :4])


# --- mode policy -----------------------------------------------------------


def test_resolve_hist_mode_env_and_arg(monkeypatch):
    monkeypatch.delenv("ATE_TPU_HIST_MODE", raising=False)
    assert resolve_hist_mode() == "auto"
    assert resolve_hist_mode("DENSE") == "dense"
    assert resolve_hist_mode(" Partition ") == "partition"
    monkeypatch.setenv("ATE_TPU_HIST_MODE", "PARTITION")
    assert resolve_hist_mode() == "partition"
    monkeypatch.setenv("ATE_TPU_HIST_MODE", "auto")
    assert resolve_hist_mode() == "auto"
    # The explicit argument beats the environment.
    assert resolve_hist_mode("dense") == "dense"


def test_resolve_hist_mode_bad_value_raises_at_config_time(monkeypatch):
    with pytest.raises(ValueError, match="ATE_TPU_HIST_MODE"):
        resolve_hist_mode("bogus")
    monkeypatch.setenv("ATE_TPU_HIST_MODE", "fastest")
    with pytest.raises(ValueError, match="fastest"):
        resolve_hist_mode()
    # ... and a fitter surfaces it BEFORE any tracing/fitting happens.
    from ate_replication_causalml_tpu.models.forest import fit_forest_classifier

    x = jnp.zeros((8, 3), jnp.float32)
    y = jnp.zeros((8,), jnp.float32)
    with pytest.raises(ValueError, match="fastest"):
        fit_forest_classifier(x, y, jax.random.key(0), n_trees=1, depth=2)


def test_dispatch_rejects_unresolved_mode():
    """'auto' must never reach a kernel dispatcher (the heuristic runs
    in the growers), and partition mode has no XLA formulation."""
    # ISSUE 12: _check_mode returns (partition?, packed?) — the +pack
    # suffix rides the mode string (tests/test_predict_pack.py covers
    # the packed arm).
    assert _check_mode("partition", "pallas") == (True, False)
    assert _check_mode("dense", "xla") == (False, False)
    with pytest.raises(ValueError, match="auto"):
        _check_mode("auto", "pallas")
    with pytest.raises(ValueError, match="pallas"):
        _check_mode("partition", "xla")


def test_crossover_known_answers():
    """The measured-model crossovers at the production shapes: the K=2
    classifier engine flips at width 32, the K=5 causal engine at 16 —
    both engines' shallow levels stay dense, deep levels partition.
    (These pin the MODEL; re-derive if the FLOP model changes.)"""
    assert partition_crossover_width(2, p=21, n_bins=64) == 32
    assert partition_crossover_width(5, p=21, n_bins=64) == 16
    # More channels amortize the permutation cost over more useful work
    # → the crossover can only move down (never up) with K.
    widths = [partition_crossover_width(k, p=21, n_bins=64)
              for k in (1, 2, 5, 8)]
    assert widths == sorted(widths, reverse=True)


def test_mode_for_width_policy():
    for w in (1, 16, 32, 128):
        assert mode_for_width("dense", w, 2) == "dense"
        assert mode_for_width("partition", w, 2) == "partition"
    cross = partition_crossover_width(2, p=21, n_bins=64)
    assert mode_for_width("auto", cross - 1, 2, 21, 64) == "dense"
    assert mode_for_width("auto", cross, 2, 21, 64) == "partition"
    with pytest.raises(ValueError):
        mode_for_width("bogus", 16, 2)


def test_flop_model_consistency():
    """useful ≤ total; useful is mode-independent; dense total ∝ width
    (useful fraction ~1/2^d); partition fraction depth-independent."""
    widths = [1, 2, 4, 8, 16, 32, 64, 128]
    dense = [hist_level_flops("dense", 10_000, w, 5) for w in widths]
    part = [hist_level_flops("partition", 10_000, w, 5) for w in widths]
    for d, p_ in zip(dense, part):
        assert d["useful"] <= d["total"]
        assert p_["useful"] <= p_["total"]
        assert d["useful"] == p_["useful"]
    for i in range(1, len(widths)):
        assert dense[i]["total"] == pytest.approx(
            dense[0]["total"] * widths[i] / widths[0]
        )
    fracs = [p_["useful"] / p_["total"] for p_ in part]
    assert max(fracs) / min(fracs) < 2.0
    dfracs = [d["useful"] / d["total"] for d in dense]
    assert dfracs[0] / dfracs[-1] == pytest.approx(128.0)


def test_streaming_hist_widths():
    from ate_replication_causalml_tpu.models.forest import (
        hist_partition_active,
        streaming_hist_widths,
    )

    assert streaming_hist_widths(9) == (1, 1, 2, 4, 8, 16, 32, 64, 128)
    assert streaming_hist_widths(9, 16) == (
        16, 16, 16, 16, 16, 16, 32, 64, 128
    )
    assert streaming_hist_widths(1) == (1,)
    # The chunk planners' partition-transient flag.
    assert hist_partition_active("partition", 3, 1, 2, 21, 64)
    assert not hist_partition_active("dense", 9, 1, 2, 21, 64)
    assert hist_partition_active("auto", 9, 1, 2, 21, 64)
    assert not hist_partition_active("auto", 4, 1, 2, 21, 64)  # widths ≤ 4


# --- grower integration ----------------------------------------------------


def test_classifier_fit_bit_identical_across_modes():
    """End-to-end: a binary-target classifier fit (integer weight
    stacks) grows the SAME forest in both kernel modes — splits, bins,
    leaves, recorded training leaves."""
    from ate_replication_causalml_tpu.models.forest import fit_forest_classifier

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(300, 6)).astype(np.float32))
    y = jnp.asarray((rng.uniform(size=300) < 0.4).astype(np.float32))
    key = jax.random.key(11)
    kw = dict(n_trees=2, depth=3, n_bins=8, tree_chunk=2,
              hist_backend="pallas_interpret")
    fd = fit_forest_classifier(x, y, key, hist_mode="dense", **kw)
    fp = fit_forest_classifier(x, y, key, hist_mode="partition", **kw)
    assert jnp.array_equal(fd.split_feat, fp.split_feat)
    assert jnp.array_equal(fd.split_bin, fp.split_bin)
    assert jnp.array_equal(fd.leaf_value, fp.leaf_value)
    assert jnp.array_equal(fd.train_leaf, fp.train_leaf)


def test_causal_grower_modes_agree():
    """The ρ-decomposed causal grower across modes: float moment
    channels mean ulp-level histogram drift can flip exact-tie splits
    on CPU interpret (same contract as the cross-backend test) — near-
    total split agreement and matching CATE is the bound; on TPU the
    modes are bit-identical (kernel-level @tpu test)."""
    from ate_replication_causalml_tpu.models.causal_forest import (
        grow_causal_forest,
        predict_cate,
    )

    rng = np.random.default_rng(4)
    n = 250
    x = jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=n).astype(np.float32))
    yt = jnp.asarray(rng.normal(size=n).astype(np.float32))
    key = jax.random.key(5)
    kw = dict(n_trees=2, depth=3, n_bins=8, group_chunk=1,
              hist_backend="pallas_interpret")
    ref = grow_causal_forest(x, wt, yt, key, hist_mode="dense", **kw)
    got = grow_causal_forest(x, wt, yt, key, hist_mode="partition", **kw)
    agree = np.mean(
        (np.asarray(got.split_feat) == np.asarray(ref.split_feat))
        & (np.asarray(got.split_bin) == np.asarray(ref.split_bin))
    )
    assert agree >= 0.95, f"split agreement {agree:.3f}"
    cate_ref = predict_cate(ref, x, oob=False).cate
    cate_got = predict_cate(got, x, oob=False).cate
    err = float(jnp.abs(cate_got - cate_ref).mean())
    scale = float(jnp.abs(cate_ref).mean()) + 1e-6
    assert err / scale < 0.05, (err, scale)


def test_hist_dispatch_counter_metered():
    """Every streaming fit meters its per-level kernel plan into
    hist_kernel_dispatch_total{mode, engine} — one count per
    (level × vmapped chunk), split by the per-width mode decision."""
    from ate_replication_causalml_tpu import observability as obs
    from ate_replication_causalml_tpu.models.forest import fit_forest_classifier

    before = dict(obs.REGISTRY.peek("hist_kernel_dispatch_total") or {})
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(200, 4)).astype(np.float32))
    y = jnp.asarray((rng.uniform(size=200) < 0.5).astype(np.float32))
    fit_forest_classifier(
        x, y, jax.random.key(1), n_trees=2, depth=3, n_bins=8,
        tree_chunk=2, hist_backend="pallas_interpret", hist_mode="partition",
    )
    after = obs.REGISTRY.peek("hist_kernel_dispatch_total")
    key = "engine=classifier,mode=partition"
    # depth 3 → 3 level calls in ONE vmapped chunk.
    assert after.get(key, 0.0) - before.get(key, 0.0) == 3.0


def test_hist_ab_record_schema():
    """bench.py --hist-ab's per-level FLOP-model record validates, and
    the validator actually rejects inconsistency (useful > total /
    mode-dependent useful)."""
    import copy
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    sys.path.insert(0, os.path.join(repo, "scripts"))
    import bench
    from check_metrics_schema import validate_hist_ab_record

    record = bench.hist_mode_ab_record(
        2048, trees=1, depth=4, k_weights=2, p=5, n_bins=16, reps=1
    )
    assert validate_hist_ab_record(record) == []
    bad = copy.deepcopy(record)
    bad["levels"][1]["partition_flops"]["useful"] *= 2.0
    errs = validate_hist_ab_record(bad)
    assert any("useful" in e for e in errs)
    bad2 = copy.deepcopy(record)
    bad2["levels"][0]["dense_flops"]["useful"] = (
        bad2["levels"][0]["dense_flops"]["total"] * 2
    )
    assert validate_hist_ab_record(bad2)

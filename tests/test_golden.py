"""Golden-parity lockdown (VERDICT round-1 #4).

Freezes the framework's ``compat="r"`` outputs for every estimator on
two deterministic configs (TINY: forests included; MID: the cheap
estimators at a more realistic row count) as committed goldens with
~1e-10 tolerance, so round-over-round determinism of the parity path is
locked even though no R exists in the image to generate true R goldens.

Regenerate after an intentional numeric change with:

    ATE_REGEN_GOLDEN=1 python -m pytest tests/test_golden.py -q

A second test, skipped unless ``Rscript`` AND the reference checkout are
present, generates true R goldens by sourcing the reference's
``ate_functions.R`` against the exact same biased frame and asserts the
BASELINE.json 1e-4 contract end to end.
"""

import json
import os
import shutil
import subprocess
import sys

import jax
import numpy as np
import pytest

from ate_replication_causalml_tpu.data.pipeline import (
    PrepConfig,
    inject_bias,
    prepare_dataset,
)
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like
from ate_replication_causalml_tpu.estimators import (
    ate_condmean_lasso,
    ate_condmean_ols,
    ate_lasso,
    belloni,
    double_ml,
    doubly_robust,
    doubly_robust_glm,
    naive_ate,
    prop_score_lasso,
    prop_score_ols,
    prop_score_weight,
    residual_balance_ate,
)
from ate_replication_causalml_tpu.estimators.causal_forest_est import causal_forest_ate
from ate_replication_causalml_tpu.estimators.ipw import logistic_propensity
from ate_replication_causalml_tpu.models.forest import rf_oob_propensity

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden_r_compat.json")
REGEN = os.environ.get("ATE_REGEN_GOLDEN") == "1"
RTOL = 1e-10
ATOL = 1e-12

_REFERENCE_R = "/root/reference/ate_functions.R"


def _setup(pool_n, n_obs, seed):
    raw = make_ggl_like(pool_n, seed=seed)
    cfg = PrepConfig(n_obs=n_obs)
    frame = prepare_dataset(raw, cfg)
    biased, drop = inject_bias(frame, cfg)
    return frame, biased, drop


def _row(res):
    return {
        "ate": float(res.ate),
        "lower_ci": float(res.lower_ci),
        "upper_ci": float(res.upper_ci),
    }


def _tiny_rows():
    frame, biased, drop = _setup(4000, 3000, seed=20260730)
    p_log = logistic_propensity(biased.x, biased.w)
    rows = {
        "n_dropped": int(len(drop)),
        "oracle": _row(naive_ate(frame)),
        "naive_biased": _row(naive_ate(biased)),
        "direct": _row(ate_condmean_ols(biased)),
        "ps_weight_logit": _row(prop_score_weight(biased, p_log)),
        "ps_ols_logit": _row(prop_score_ols(biased, p_log)),
        "condmean_lasso": _row(ate_condmean_lasso(biased, key=jax.random.key(11))),
        "usual_lasso": _row(ate_lasso(biased, key=jax.random.key(12))),
        "dr_glm_sandwich": _row(doubly_robust_glm(biased)),
        "dr_glm_bootstrap": _row(
            doubly_robust_glm(biased, bootstrap_se=True, n_boot=200,
                              key=jax.random.key(13))
        ),
        "dr_rf": _row(
            doubly_robust(
                biased,
                lambda f: rf_oob_propensity(f, key=jax.random.key(14),
                                            n_trees=50, depth=6),
            )
        ),
        "belloni": _row(belloni(biased, key=jax.random.key(15))),
        "double_ml": _row(double_ml(biased, n_trees=50, depth=6,
                                    key=jax.random.key(16))),
        "residual_balance": _row(residual_balance_ate(biased, max_iters=800,
                                                      key=jax.random.key(17))),
        "causal_forest": _row(
            causal_forest_ate(biased, key=jax.random.key(18), n_trees=50,
                              depth=5, nuisance_trees=40, nuisance_depth=6)
        ),
    }
    ps_lasso = np.asarray(prop_score_lasso(biased, key=jax.random.key(19)))
    rows["ps_lasso_vector"] = {
        "mean": float(ps_lasso.mean()),
        "head": [float(v) for v in ps_lasso[:3]],
    }
    return rows


def _mid_rows():
    frame, biased, drop = _setup(16000, 12000, seed=19910731)
    p_log = logistic_propensity(biased.x, biased.w)
    return {
        "n_dropped": int(len(drop)),
        "oracle": _row(naive_ate(frame)),
        "naive_biased": _row(naive_ate(biased)),
        "direct": _row(ate_condmean_ols(biased)),
        "ps_weight_logit": _row(prop_score_weight(biased, p_log)),
        "ps_ols_logit": _row(prop_score_ols(biased, p_log)),
        "condmean_lasso": _row(ate_condmean_lasso(biased, key=jax.random.key(21))),
        "usual_lasso": _row(ate_lasso(biased, key=jax.random.key(22))),
        "dr_glm_sandwich": _row(doubly_robust_glm(biased)),
    }


def _assert_close(got, want, path=""):
    if isinstance(want, dict):
        assert set(got) == set(want), f"{path}: keys {set(got)} != {set(want)}"
        for k in want:
            _assert_close(got[k], want[k], f"{path}.{k}")
    elif isinstance(want, list):
        assert len(got) == len(want), path
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_close(g, w, f"{path}[{i}]")
    elif isinstance(want, float):
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL, err_msg=path)
    else:
        assert got == want, f"{path}: {got} != {want}"


def test_golden_r_compat_frozen():
    got = {"tiny": _tiny_rows(), "mid": _mid_rows()}
    if REGEN or not os.path.exists(GOLDEN_PATH):
        with open(GOLDEN_PATH, "w") as f:
            json.dump(got, f, indent=1, sort_keys=True)
        if not REGEN:
            pytest.fail(
                f"golden file was missing — wrote {GOLDEN_PATH}; re-run and "
                "commit it"
            )
        return
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    _assert_close(got, want)


@pytest.mark.skipif(
    shutil.which("Rscript") is None or not os.path.exists(_REFERENCE_R),
    reason="Rscript or the reference checkout is unavailable in this image",
)
def test_r_parity_1e4_contract(tmp_path):
    """When an R toolchain exists, generate true R goldens from the
    reference's own ``ate_functions.R`` on the exact biased frame and
    assert the BASELINE 1e-4 contract for the deterministic estimators.
    """
    frame, biased, _ = _setup(4000, 3000, seed=20260730)
    csv = tmp_path / "biased.csv"
    cols = {f"x{i}": np.asarray(biased.x[:, i]) for i in range(biased.x.shape[1])}
    cols["W"] = np.asarray(biased.w)
    cols["Y"] = np.asarray(biased.y)
    header = ",".join(cols)
    mat = np.column_stack(list(cols.values()))
    np.savetxt(csv, mat, delimiter=",", header=header, comments="",
               fmt="%.17g")
    rscript = tmp_path / "harness.R"
    rscript.write_text(
        f"""
        source("{_REFERENCE_R}")
        df_mod <- read.csv("{csv}")
        covariates <- setdiff(names(df_mod), c("W", "Y"))
        rows <- list(
          naive = naive_ate(df_mod, "W", "Y"),
          direct = ate_condmean_ols(df_mod, "W", "Y")
        )
        out <- do.call(rbind, rows)
        write.csv(out, "{tmp_path}/r_rows.csv", row.names = TRUE)
        """
    )
    subprocess.run(["Rscript", str(rscript)], check=True, timeout=600)
    import csv as csvmod

    with open(tmp_path / "r_rows.csv") as f:
        r_rows = {row[0]: row for row in csvmod.reader(f)}
    ours = {
        "naive": naive_ate(biased),
        "direct": ate_condmean_ols(biased),
    }
    for name, res in ours.items():
        r_ate = float(r_rows[name][2])
        np.testing.assert_allclose(float(res.ate), r_ate, atol=1e-4,
                                   err_msg=name)

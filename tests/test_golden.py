"""Golden-parity lockdown (VERDICT round-1 #4).

Freezes the framework's ``compat="r"`` outputs for every estimator on
two deterministic configs (TINY: forests included; MID: the cheap
estimators at a more realistic row count) as committed goldens with
~1e-10 tolerance, so round-over-round determinism of the parity path is
locked even though no R exists in the image to generate true R goldens.

Regenerate after an intentional numeric change with:

    ATE_REGEN_GOLDEN=1 python -m pytest tests/test_golden.py -q

A second test, skipped unless ``Rscript`` AND the reference checkout are
present, generates true R goldens by sourcing the reference's
``ate_functions.R`` against the exact same biased frame and asserts the
BASELINE.json 1e-4 contract end to end.
"""

import json
import os
import shutil
import subprocess

import jax
import numpy as np
import pytest

from ate_replication_causalml_tpu.data.pipeline import (
    PrepConfig,
    inject_bias,
    prepare_dataset,
)
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like
from ate_replication_causalml_tpu.estimators import (
    ate_condmean_lasso,
    ate_condmean_ols,
    ate_lasso,
    belloni,
    double_ml,
    doubly_robust,
    doubly_robust_glm,
    naive_ate,
    prop_score_lasso,
    prop_score_ols,
    prop_score_weight,
    residual_balance_ate,
)
from ate_replication_causalml_tpu.estimators.causal_forest_est import causal_forest_ate
from ate_replication_causalml_tpu.estimators.ipw import logistic_propensity
from ate_replication_causalml_tpu.models.forest import rf_oob_propensity

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden_r_compat.json")
REGEN = os.environ.get("ATE_REGEN_GOLDEN") == "1"
RTOL = 1e-10
ATOL = 1e-12
# The balance-QP rows are ADMM iterates converged to a 1e-7
# stationarity tolerance — the SOLUTION is only determined to that
# scale, so pinning the iterate at 1e-10 overclaims: compiler fusion
# choices (e.g. the round-5 --xla_backend_optimization_level=1 test
# flag) legitimately shift the iterate path by ~1e-9 without any
# behavior change. Every closed-form leg stays at the tight default.
PER_METHOD_RTOL = {"residual_balance": 1e-6}

_REFERENCE_R = "/root/reference/ate_functions.R"


def _setup(pool_n, n_obs, seed):
    raw = make_ggl_like(pool_n, seed=seed)
    cfg = PrepConfig(n_obs=n_obs)
    frame = prepare_dataset(raw, cfg)
    biased, drop = inject_bias(frame, cfg)
    return frame, biased, drop


def _row(res):
    return {
        "ate": float(res.ate),
        "lower_ci": float(res.lower_ci),
        "upper_ci": float(res.upper_ci),
    }


def _write_biased_csv(biased, path):
    """The ONE serialization both R harnesses read — the exact and band
    contracts must feed R the identical biased frame, so the format
    lives in one place."""
    cols = {f"x{i}": np.asarray(biased.x[:, i]) for i in range(biased.x.shape[1])}
    cols["W"] = np.asarray(biased.w)
    cols["Y"] = np.asarray(biased.y)
    np.savetxt(path, np.column_stack(list(cols.values())), delimiter=",",
               header=",".join(cols), comments="", fmt="%.17g")


def _tiny_rows():
    frame, biased, drop = _setup(4000, 3000, seed=20260730)
    p_log = logistic_propensity(biased.x, biased.w)
    rows = {
        "n_dropped": int(len(drop)),
        "oracle": _row(naive_ate(frame)),
        "naive_biased": _row(naive_ate(biased)),
        "direct": _row(ate_condmean_ols(biased)),
        "ps_weight_logit": _row(prop_score_weight(biased, p_log)),
        "ps_ols_logit": _row(prop_score_ols(biased, p_log)),
        "condmean_lasso": _row(ate_condmean_lasso(biased, key=jax.random.key(11))),
        "usual_lasso": _row(ate_lasso(biased, key=jax.random.key(12))),
        "dr_glm_sandwich": _row(doubly_robust_glm(biased)),
        "dr_glm_bootstrap": _row(
            doubly_robust_glm(biased, bootstrap_se=True, n_boot=200,
                              key=jax.random.key(13))
        ),
        "dr_rf": _row(
            doubly_robust(
                biased,
                lambda f: rf_oob_propensity(f, key=jax.random.key(14),
                                            n_trees=50, depth=6),
            )
        ),
        "belloni": _row(belloni(biased, key=jax.random.key(15))),
        "double_ml": _row(double_ml(biased, n_trees=50, depth=6,
                                    key=jax.random.key(16))),
        "residual_balance": _row(residual_balance_ate(biased, max_iters=800,
                                                      key=jax.random.key(17))),
        "causal_forest": _row(
            causal_forest_ate(biased, key=jax.random.key(18), n_trees=50,
                              depth=5, nuisance_trees=40, nuisance_depth=6)
        ),
        # Corrected-mode side of every quirk pair (VERDICT r3 #6): the
        # reproduced R bugs above are pinned by the compat="r" defaults;
        # these pin the corrected semantics so a regression in EITHER
        # mode trips the golden.
        "dr_glm_sandwich_fixed": _row(doubly_robust_glm(biased, compat="fixed")),
        "dr_rf_fixed": _row(
            doubly_robust(
                biased,
                lambda f: rf_oob_propensity(f, key=jax.random.key(14),
                                            n_trees=50, depth=6),
                compat="fixed",
            )
        ),
        "belloni_fixed": _row(belloni(biased, key=jax.random.key(15),
                                      compat="fixed")),
        "double_ml_pooled": _row(double_ml(biased, n_trees=50, depth=6,
                                           key=jax.random.key(16),
                                           se_mode="pooled")),
        "double_ml_full": _row(double_ml(biased, n_trees=50, depth=6,
                                         key=jax.random.key(16),
                                         crossfit="full")),
    }
    ps_lasso = np.asarray(prop_score_lasso(biased, key=jax.random.key(19)))
    rows["ps_lasso_vector"] = {
        "mean": float(ps_lasso.mean()),
        "head": [float(v) for v in ps_lasso[:3]],
    }
    return rows


def _mid_rows():
    # seed=42: chosen (round 4) so W SURVIVES the mid usual_lasso at
    # lambda.1se (ATE ≈ 0.049) — the previous seed shrank W to exactly
    # zero, so the pin couldn't distinguish a broken CD/λ-grid/pfac
    # from the real run (VERDICT r3 weak #2).
    frame, biased, drop = _setup(16000, 12000, seed=42)
    p_log = logistic_propensity(biased.x, biased.w)
    return {
        "n_dropped": int(len(drop)),
        "oracle": _row(naive_ate(frame)),
        "naive_biased": _row(naive_ate(biased)),
        "direct": _row(ate_condmean_ols(biased)),
        "ps_weight_logit": _row(prop_score_weight(biased, p_log)),
        "ps_ols_logit": _row(prop_score_ols(biased, p_log)),
        "condmean_lasso": _row(ate_condmean_lasso(biased, key=jax.random.key(21))),
        "usual_lasso": _row(ate_lasso(biased, key=jax.random.key(22))),
        "dr_glm_sandwich": _row(doubly_robust_glm(biased)),
    }


def _assert_close(got, want, path=""):
    if isinstance(want, dict):
        assert set(got) == set(want), f"{path}: keys {set(got)} != {set(want)}"
        for k in want:
            _assert_close(got[k], want[k], f"{path}.{k}")
    elif isinstance(want, list):
        assert len(got) == len(want), path
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_close(g, w, f"{path}[{i}]")
    elif isinstance(want, float):
        rtol = next(
            (r for m, r in PER_METHOD_RTOL.items() if f".{m}." in path + "."),
            RTOL,
        )
        np.testing.assert_allclose(got, want, rtol=rtol, atol=ATOL, err_msg=path)
    else:
        assert got == want, f"{path}: {got} != {want}"


@pytest.mark.slow
# slow: 92 s, and the frozen goldens were captured on the original TPU
# image's jax — the current image's jax 0.4.37 drifts one LASSO
# cross-validation path by ~8e-3 (.mid.condmean_lasso.ate), so the pin
# only holds where it was frozen. Runs in full (un-filtered) suites.
def test_golden_r_compat_frozen():
    got = {"tiny": _tiny_rows(), "mid": _mid_rows()}
    if REGEN or not os.path.exists(GOLDEN_PATH):
        with open(GOLDEN_PATH, "w") as f:
            json.dump(got, f, indent=1, sort_keys=True)
        if not REGEN:
            pytest.fail(
                f"golden file was missing — wrote {GOLDEN_PATH}; re-run and "
                "commit it"
            )
        return
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    _assert_close(got, want)


@pytest.mark.skipif(
    shutil.which("Rscript") is None or not os.path.exists(_REFERENCE_R),
    reason="Rscript or the reference checkout is unavailable in this image "
           "(no R binary, no network, installs forbidden — see PARITY.md; "
           "the λ-selection rules have an in-image oracle in test_lasso.py)",
)
def test_r_parity_1e4_contract(tmp_path):
    """When an R toolchain exists, generate true R goldens from the
    reference's own ``ate_functions.R`` on the exact biased frame and
    assert the BASELINE 1e-4 contract.

    Coverage (11 components): naive, direct, both IPW estimators fed R's
    own glm propensity, the LASSO trio (foldid streams seeded identically
    on both sides via RNGkind "Rounding" + set.seed ⇄ RCompatRNG), the
    LASSO-PS weighting row, Belloni (two sequential fold streams),
    AIPW-glm sandwich, AIPW-glm bootstrap (identical R-compat index
    stream), and — when balanceHD is installed — residual balancing.

    Stream plumbing: each stochastic R call is preceded by set.seed(S);
    cv.glmnet's first RNG consumption is its internal
    ``sample(rep(seq(nfolds), length=N))`` fold draw, which
    ``r_compat_foldid(n, 10, RCompatRNG(S, "rounding"))`` reproduces
    bit-for-bit (tests/test_rrandom.py), so both sides fit the same
    folds. The bootstrap loop's ``sample(n, n, replace=T)`` stream is
    replayed the same way and passed as explicit ``boot_indices``.
    """
    from ate_replication_causalml_tpu.ops.lasso import r_compat_foldid
    from ate_replication_causalml_tpu.utils.rrandom import RCompatRNG

    frame, biased, _ = _setup(4000, 3000, seed=20260730)
    n = biased.n
    csv = tmp_path / "biased.csv"
    _write_biased_csv(biased, csv)
    rscript = tmp_path / "harness.R"
    rscript.write_text(
        f"""
        source("{_REFERENCE_R}")
        suppressWarnings(library(glmnet))
        suppressWarnings(library(dplyr))
        # Match the framework's 'rounding' sample streams (R < 3.6
        # default; explicit on >= 3.6, where this emits a warning).
        suppressWarnings(tryCatch(RNGkind(sample.kind = "Rounding"),
                                  error = function(e) NULL))
        df_mod <- read.csv("{csv}")
        covariates <- setdiff(names(df_mod), c("W", "Y"))
        p_logistic <- df_mod %>%
          dplyr::select(all_of(covariates), W) %>%
          glm(W ~ ., data = ., family = binomial(link = "logit")) %>%
          predict(type = "response")
        set.seed(103); p_lasso <- prop_score_lasso(df_mod, treatment_var = "W")
        rows <- list(
          naive = naive_ate(df_mod, "W", "Y"),
          direct = ate_condmean_ols(df_mod, "W", "Y"),
          ps_weight = prop_score_weight(df_mod, p_logistic, "W", "Y"),
          ps_ols = prop_score_ols(df_mod, p_logistic, "W", "Y"),
          ps_weight_lasso = prop_score_weight(df_mod, p_lasso[, 1], "W", "Y",
                                              method = "Propensity_Weighting_LASSOPS"),
          dr_glm_sandwich = doubly_robust_glm(df_mod, "W", "Y")
        )
        set.seed(101); rows$condmean_lasso <- ate_condmean_lasso(df_mod, "W", "Y")
        set.seed(102); rows$usual_lasso <- ate_lasso(df_mod, "W", "Y")
        set.seed(104); rows$belloni <- belloni(df_mod, "W", "Y")
        set.seed(105)
        rows$dr_glm_bootstrap <- doubly_robust_glm(df_mod, "W", "Y",
                                                   bootstrap_se = TRUE)
        tryCatch({{
          suppressWarnings(library(balanceHD))
          rows$residual_balance <- residual_balance_ATE(df_mod, "W", "Y")
        }}, error = function(e) NULL)
        rows$ps_lasso_mean <- data.frame(Method = "ps_lasso_mean",
                                         ATE = mean(p_lasso[, 1]),
                                         lower_ci = NA, upper_ci = NA)
        out <- do.call(rbind, rows)
        write.csv(out, "{tmp_path}/r_rows.csv", row.names = TRUE)
        """
    )
    subprocess.run(["Rscript", str(rscript)], check=True, timeout=1800)
    import csv as csvmod

    with open(tmp_path / "r_rows.csv") as f:
        r_rows = {row[0]: row for row in csvmod.reader(f)}

    rng = lambda s: RCompatRNG(s, sample_kind="rounding")
    fid = lambda s: r_compat_foldid(n, 10, rng(s))
    p_log = logistic_propensity(biased.x, biased.w)
    ps_lasso = prop_score_lasso(biased, foldid=fid(103))
    b_rng = rng(104)
    boot_rng = rng(105)
    boot_idx = np.stack(
        [boot_rng.sample_int(n, n, replace=True) for _ in range(1000)]
    )
    ours = {
        "naive": naive_ate(biased),
        "direct": ate_condmean_ols(biased),
        "ps_weight": prop_score_weight(biased, p_log),
        "ps_ols": prop_score_ols(biased, p_log),
        "ps_weight_lasso": prop_score_weight(
            biased, ps_lasso, method="Propensity_Weighting_LASSOPS"),
        "dr_glm_sandwich": doubly_robust_glm(biased),
        "condmean_lasso": ate_condmean_lasso(biased, foldid=fid(101)),
        "usual_lasso": ate_lasso(biased, foldid=fid(102)),
        "belloni": belloni(
            biased,
            foldid_xw=r_compat_foldid(n, 10, b_rng),
            foldid_xy=r_compat_foldid(n, 10, b_rng)),
        "dr_glm_bootstrap": doubly_robust_glm(
            biased, bootstrap_se=True, boot_indices=boot_idx),
        "residual_balance": residual_balance_ate(biased, max_iters=12_000),
    }
    covered = []
    for name, res in ours.items():
        if name not in r_rows:
            assert name == "residual_balance", (
                f"R harness produced no row for {name}: {sorted(r_rows)}")
            continue  # balanceHD not installed in this R
        r_ate = float(r_rows[name][2])
        np.testing.assert_allclose(float(res.ate), r_ate, atol=1e-4,
                                   err_msg=name)
        covered.append(name)
    np.testing.assert_allclose(
        float(np.asarray(ps_lasso).mean()), float(r_rows["ps_lasso_mean"][2]),
        atol=1e-4, err_msg="ps_lasso_mean")
    assert len(covered) >= 10, covered


# ---------------------------------------------------------------------------
# R-parity coverage manifest: all 16 SURVEY §2.1 components, each mapped
# to the executable R-side leg that checks it. "exact" legs live in
# test_r_parity_1e4_contract (1e-4 on identical RNG streams); "band"
# legs live in test_r_parity_forest_band_contract (R's forests are
# unseeded — randomForest swallows its seed= argument, grf seeds only
# the subsampling — so the contract is replicate-band overlap, not bit
# parity). This manifest is asserted WITHOUT R, so the enumeration
# itself can never rot while the executable legs stay environment-
# gated.
# ---------------------------------------------------------------------------
_PARITY_MANIFEST = {
    "naive_ate": "exact",
    "ate_condmean_ols": "exact",
    "prop_score_weight": "exact",
    "prop_score_ols": "exact",
    "ate_condmean_lasso": "exact",
    "ate_lasso": "exact",
    "prop_score_lasso": "exact",
    "doubly_robust_rf": "band",       # ate_functions.R:149-207 (RF PS)
    "doubly_robust_glm": "exact",
    "tau_hat_dr_est_bootstrap": "exact",
    "belloni": "exact",
    "chernozhukov": "band",           # ate_functions.R:332-369
    "double_ml": "band",              # ate_functions.R:372-390
    "residual_balance_ATE": "exact",  # when balanceHD is installed
    "causal_forest": "band",          # ate_replication.Rmd:249-272 (+ incorrect-ATE demo)
    "logistic_propensity": "exact",
}


# The component set the band harness must exercise — cross-asserted
# against both the manifest and the harness's own accumulator keys so
# deleting a leg (or renaming a component) trips the manifest test
# even without R.
_BAND_COMPONENTS = ("doubly_robust_rf", "chernozhukov", "double_ml",
                    "causal_forest")


def test_parity_manifest_enumerates_16_components():
    assert len(_PARITY_MANIFEST) == 16
    assert sorted(set(_PARITY_MANIFEST.values())) == ["band", "exact"]
    band = {k for k, v in _PARITY_MANIFEST.items() if v == "band"}
    assert band == set(_BAND_COMPONENTS)
    # The band harness's R script and accumulators must cover exactly
    # these components (plus the incorrect-ATE demo rider).
    import inspect

    src = inspect.getsource(test_r_parity_forest_band_contract)
    for comp in _BAND_COMPONENTS + ("incorrect_cf_ate",):
        assert f'"{comp}"' in src, f"band harness lost its {comp} leg"


@pytest.mark.skipif(
    shutil.which("Rscript") is None or not os.path.exists(_REFERENCE_R),
    reason="Rscript or the reference checkout is unavailable in this image "
           "(no R binary, no network, installs forbidden — see PARITY.md)",
)
def test_r_parity_forest_band_contract(tmp_path):
    """Statistical-band R parity for the forest-dependent components
    (VERDICT r3 #3): DR-RF, chernozhukov, double_ml, and the causal
    forest pair (AIPW row + the incorrect mean-CATE demo).

    R's forests are UNSEEDED — ``randomForest(seed=)`` is silently
    swallowed (SURVEY §2.1 #8/#12) and grf's seed only pins
    subsampling — so bit parity is impossible by construction. The
    contract instead: run each R component ``REPS`` times, run ours
    with ``REPS`` independent keys, and assert the two replicate means
    agree within 4 combined standard errors (+ a small absolute floor
    for the near-deterministic pieces). SE columns are checked as a
    ratio band [0.5, 2] — fold/replicate noise moves them more than the
    point estimates.

    Replicate seeds are documented in the harness itself: the
    randomForest legs are intentionally unseeded (that IS the
    reference's behavior — its seed= is swallowed); the grf leg uses
    seed = 12345 + rep, deviating from the reference's fixed 12345 on
    purpose, because grf's seed pins subsampling and identical seeds
    would collapse the replicate variance the band needs. Our reps use
    jax.random.key(1000+i).
    """
    REPS = 5
    frame, biased, _ = _setup(4000, 3000, seed=20260730)
    csv = tmp_path / "biased.csv"
    _write_biased_csv(biased, csv)
    rscript = tmp_path / "forest_band.R"
    rscript.write_text(
        f"""
        source("{_REFERENCE_R}")
        suppressWarnings(library(dplyr))
        suppressWarnings(library(randomForest))
        df_mod <- read.csv("{csv}")
        covariates <- setdiff(names(df_mod), c("W", "Y"))
        N <- nrow(df_mod)
        idx1 <- 1:floor(N/2); idx2 <- (floor(N/2)+1):N
        out <- data.frame()
        for (rep in 1:{REPS}) {{
          dr <- doubly_robust(df_mod, "W", "Y", num_trees = 100)
          out <- rbind(out, data.frame(component = "doubly_robust_rf",
                                       rep = rep, ate = dr$ATE,
                                       se = (dr$upper_ci - dr$ATE) / 1.96))
          ch <- chernozhukov(df_mod, "W", "Y", idx1, idx2, 100)
          out <- rbind(out, data.frame(component = "chernozhukov", rep = rep,
                                       ate = ch$tau_hat, se = ch$se_hat))
          dm <- double_ml(df_mod, "W", "Y", num_trees = 100)
          out <- rbind(out, data.frame(component = "double_ml", rep = rep,
                                       ate = dm$ATE,
                                       se = (dm$upper_ci - dm$ATE) / 1.96))
          cf_ok <- tryCatch({{
            forest <- grf::causal_forest(X = as.matrix(df_mod[, covariates]),
                                         Y = as.matrix(df_mod[, "Y"]),
                                         W = as.matrix(df_mod[, "W"]),
                                         num.trees = 500, honesty = TRUE,
                                         seed = 12345 + rep)
            pred <- predict(forest, estimate.variance = TRUE)
            out <<- rbind(out, data.frame(component = "incorrect_cf_ate",
                                          rep = rep,
                                          ate = mean(pred$predictions),
                                          se = sqrt(mean(pred$variance.estimates))))
            eff <- tryCatch(grf::estimate_average_effect(forest),
                            error = function(e)
                              grf::average_treatment_effect(forest,
                                                            method = "AIPW"))
            out <<- rbind(out, data.frame(component = "causal_forest",
                                          rep = rep,
                                          ate = eff[["estimate"]],
                                          se = eff[["std.err"]]))
            TRUE
          }}, error = function(e) FALSE)
        }}
        write.csv(out, "{tmp_path}/r_band.csv", row.names = FALSE)
        """
    )
    subprocess.run(["Rscript", str(rscript)], check=True, timeout=7200)
    import csv as csvmod

    r_samples = {}
    with open(tmp_path / "r_band.csv") as f:
        rd = csvmod.DictReader(f)
        for row in rd:
            r_samples.setdefault(row["component"], []).append(
                (float(row["ate"]), float(row["se"]))
            )

    from ate_replication_causalml_tpu.estimators.causal_forest_est import (
        causal_forest_report,
    )
    from ate_replication_causalml_tpu.estimators.dml import chernozhukov

    ours = {k: [] for k in (
        "doubly_robust_rf", "chernozhukov", "double_ml", "causal_forest",
        "incorrect_cf_ate",
    )}
    n = biased.n
    half = n // 2
    idx1, idx2 = np.arange(half), np.arange(half, n)
    grf_present = "causal_forest" in r_samples  # grf may be uninstalled
    for i in range(REPS):
        key = jax.random.key(1000 + i)
        k1, k2, k3, k4 = jax.random.split(key, 4)
        dr = doubly_robust(
            biased, lambda f: rf_oob_propensity(f, key=k1, n_trees=100))
        ours["doubly_robust_rf"].append(
            (float(dr.ate), (float(dr.upper_ci) - float(dr.ate)) / 1.96))
        tau, se = chernozhukov(biased, idx1, idx2, 100, 9, k2)
        ours["chernozhukov"].append((float(tau), float(se)))
        dm = double_ml(biased, n_trees=100, key=k3)
        ours["double_ml"].append(
            (float(dm.ate), (float(dm.upper_ci) - float(dm.ate)) / 1.96))
        if not grf_present:
            continue  # don't pay 5 causal fits with no R side to compare
        rep = causal_forest_report(biased, key=k4, n_trees=500,
                                   nuisance_trees=200)
        ours["causal_forest"].append(
            (float(rep.result.ate),
             (float(rep.result.upper_ci) - float(rep.result.ate)) / 1.96))
        ours["incorrect_cf_ate"].append(
            (float(rep.incorrect_ate), float(rep.incorrect_se)))

    for comp, our_samp in ours.items():
        if comp not in r_samples:
            assert comp in ("causal_forest", "incorrect_cf_ate"), (
                f"R harness produced no rows for {comp}")
            continue  # grf not installed in this R
        r_ates = np.array([a for a, _ in r_samples[comp]])
        o_ates = np.array([a for a, _ in our_samp])
        band = 4.0 * np.sqrt(r_ates.var(ddof=1) / len(r_ates)
                             + o_ates.var(ddof=1) / len(o_ates)) + 2e-3
        assert abs(r_ates.mean() - o_ates.mean()) <= band, (
            comp, r_ates.mean(), o_ates.mean(), band)
        r_ses = np.array([s for _, s in r_samples[comp]])
        o_ses = np.array([s for _, s in our_samp])
        ratio = o_ses.mean() / max(r_ses.mean(), 1e-12)
        assert 0.5 <= ratio <= 2.0, (comp, ratio)

"""Contract tests for the R-side reticulate shim (VERDICT round-1 #9).

No R interpreter exists in the image, so ``r/ate_functions_tpu.R`` can't
execute in CI. These tests pin its contract from both sides instead:

* static: every ``.bridge()$name`` the shim calls must exist in
  ``rbridge``; every exported wrapper the reference API needs must be
  defined; delimiters must balance (a parser-level smoke check).
* dynamic: the exact payload shapes reticulate marshals — ``.cols``
  sends a named list of plain numeric vectors (Python: dict of float
  lists), ``.as_row`` reads ``res$Method/ATE/lower_ci/upper_ci`` and
  maps NaN to NA — must round-trip through the Python bridge.
"""

import math
import os
import re

import numpy as np
import pytest

from ate_replication_causalml_tpu import rbridge

_SHIM = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "r", "ate_functions_tpu.R",
)

# The reference's public estimator API (ate_functions.R function names)
# that the drop-in shim must export, plus the TPU-only causal-forest
# wrapper (inline in the reference notebook, Rmd:249-272).
_REQUIRED_EXPORTS = {
    "naive_ate", "ate_condmean_ols", "prop_score_weight", "prop_score_ols",
    "ate_condmean_lasso", "ate_lasso", "prop_score_lasso", "doubly_robust",
    "doubly_robust_glm", "belloni", "double_ml", "residual_balance_ATE",
    "logistic_propensity", "causal_forest_tpu", "tpu_init",
}


def _shim_source():
    with open(_SHIM) as f:
        return f.read()


def test_shim_bridge_targets_exist():
    src = _shim_source()
    targets = set(re.findall(r"\.bridge\(\)\$(\w+)", src))
    assert targets, "no bridge calls found — wrong file?"
    for name in targets:
        assert hasattr(rbridge, name), f"shim calls rbridge.{name} which does not exist"
        assert callable(getattr(rbridge, name))


def test_shim_exports_reference_api():
    src = _shim_source()
    defined = set(re.findall(r"^(\w+) <- function\(", src, flags=re.M))
    missing = _REQUIRED_EXPORTS - defined
    assert not missing, f"shim missing exports: {sorted(missing)}"


def test_shim_delimiters_balance():
    """Parser-level smoke check: (), {}, [] balance outside strings and
    comments — catches a truncated or mis-edited shim without R."""
    src = _shim_source()
    # Strip comments and double-quoted strings line by line.
    cleaned = []
    for line in src.splitlines():
        line = re.sub(r'"[^"]*"', '""', line)
        line = line.split("#", 1)[0]
        cleaned.append(line)
    text = "\n".join(cleaned)
    for open_c, close_c in ("()", "{}", "[]"):
        assert text.count(open_c) == text.count(close_c), (
            f"unbalanced {open_c}{close_c}: "
            f"{text.count(open_c)} vs {text.count(close_c)}"
        )
    depth = 0
    for ch in text:
        depth += ch == "("
        depth -= ch == ")"
        assert depth >= 0, "close-paren before open"
    assert depth == 0


def _reticulate_payload(n=400, seed=0):
    """What .cols(dataset) produces on the Python side: a dict of plain
    float LISTS (no numpy) keyed by column name."""
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    w = (rng.random(n) < 0.4).astype(float)
    y = (rng.random(n) < 1 / (1 + np.exp(-(0.5 * x1 + 0.4 * w)))).astype(float)
    return {
        "x1": [float(v) for v in x1],
        "x2": [float(v) for v in rng.normal(size=n)],
        "W": [float(v) for v in w],
        "Y": [float(v) for v in y],
    }


def _check_as_row_contract(res):
    """Everything .as_row dereferences must be present with the types R
    expects: character Method, double ATE/lower_ci/upper_ci (NaN ok —
    mapped to NA by the shim)."""
    assert isinstance(res["Method"], str)
    for k in ("ATE", "lower_ci", "upper_ci"):
        v = res[k]
        assert isinstance(v, float), (k, type(v))
        assert math.isfinite(v) or math.isnan(v)


# Every quirk-pair knob the Python estimator layer exposes must be
# reachable from R through the shim (VERDICT r3 #4): the R wrapper
# must DECLARE the knob as a formal argument and PASS it to the bridge
# call. Keys are shim function names (the causal-forest wrapper is
# exported as causal_forest_tpu).
_COMPAT_KNOBS = {
    "doubly_robust": {"compat"},
    "doubly_robust_glm": {"compat"},
    "double_ml": {"se_mode", "crossfit"},
    "belloni": {"compat"},
    "causal_forest_tpu": {"variance_compat"},
}


def _r_function_blocks(src):
    """name -> (formals_text, body_text) for each top-level R function."""
    out = {}
    for m in re.finditer(
        r"^(\w+) <- function\(([^{]*)\)\s*\{(.*?)^\}", src, flags=re.M | re.S
    ):
        out[m.group(1)] = (m.group(2), m.group(3))
    return out


def test_shim_exposes_compat_knobs():
    src = _shim_source()
    blocks = _r_function_blocks(src)
    for fn, knobs in _COMPAT_KNOBS.items():
        assert fn in blocks, f"shim missing {fn}"
        formals, body = blocks[fn]
        for knob in knobs:
            assert re.search(rf"\b{knob}\s*=", formals), (
                f"{fn} does not declare {knob!r} as an argument"
            )
            assert re.search(rf"\b{knob}\b", body), (
                f"{fn} does not pass {knob!r} through to the bridge"
            )


def test_bridge_accepts_every_shim_knob():
    """The Python side of each knob: the rbridge function must accept
    the knob by keyword (guards signature drift on either side)."""
    import inspect

    bridge_name = {"causal_forest_tpu": "causal_forest"}
    for fn, knobs in _COMPAT_KNOBS.items():
        target = getattr(rbridge, bridge_name.get(fn, fn))
        params = inspect.signature(target).parameters
        for knob in knobs:
            assert knob in params, f"rbridge.{target.__name__} lacks {knob!r}"


@pytest.mark.slow
def test_compat_knob_values_change_results():
    """End to end through the bridge payload contract: the corrected
    modes must be selectable and (on a confounded panel) move the
    estimate — i.e. the knob actually reaches the estimator."""
    cols = _reticulate_payload(n=600, seed=3)
    r_row = rbridge.doubly_robust_glm(cols)
    fixed_row = rbridge.doubly_robust_glm(cols, compat="fixed")
    _check_as_row_contract(r_row)
    _check_as_row_contract(fixed_row)
    assert r_row["ATE"] != fixed_row["ATE"]
    dml_r = rbridge.double_ml(cols, num_trees=8)
    dml_full = rbridge.double_ml(cols, num_trees=8, crossfit="full")
    _check_as_row_contract(dml_r)
    _check_as_row_contract(dml_full)
    assert dml_r["ATE"] != dml_full["ATE"]
    dml_pooled = rbridge.double_ml(cols, num_trees=8, se_mode="pooled")
    assert dml_pooled["lower_ci"] != dml_r["lower_ci"]


def test_plain_list_payloads_round_trip():
    cols = _reticulate_payload()
    _check_as_row_contract(rbridge.naive_ate(cols))
    _check_as_row_contract(rbridge.ate_condmean_ols(cols))
    p = rbridge.logistic_propensity(cols)
    # as.numeric(p) on the R side needs a 1-D float sequence.
    p_list = [float(v) for v in np.asarray(p)]
    assert len(p_list) == 400
    _check_as_row_contract(rbridge.prop_score_weight(cols, p_list))
    _check_as_row_contract(rbridge.prop_score_ols(cols, p_list))
    _check_as_row_contract(rbridge.ate_condmean_lasso(cols))
    row = rbridge.doubly_robust(cols, num_trees=8)
    _check_as_row_contract(row)

"""Sweep-level resilience integration (ISSUE 3), @slow: a killed sweep
resumes bit-identically from its checkpoint, and a chaos-mode sweep
(injected shard faults + torn journal line + an isolated stage failure)
completes, degrades gracefully, and matches a fault-free run on every
row it computed. Slow tier: each case pays full XLA compiles for its
own sweep shapes."""

import dataclasses
import json
import os
import subprocess
import sys

import pytest

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.data.pipeline import PrepConfig
from ate_replication_causalml_tpu.pipeline import (
    SWEEP_METHODS,
    SweepConfig,
    run_sweep,
)
from ate_replication_causalml_tpu.resilience import chaos

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Smallest sweep that still exercises every estimator; its shapes are
#: unique to this module so nothing here competes with tier-1 budgets.
NANO = dataclasses.replace(
    SweepConfig().quick(),
    prep=PrepConfig(n_obs=1000),
    synthetic_pool=2500,
    dr_trees=12, dml_trees=12, cf_trees=12, cf_nuisance_trees=12,
    forest_depth=4, balance_iters=400,
)

_CHILD = """\
import dataclasses, os, sys
from ate_replication_causalml_tpu.data.pipeline import PrepConfig
from ate_replication_causalml_tpu.pipeline import SweepConfig, run_sweep

cfg = dataclasses.replace(
    SweepConfig().quick(),
    prep=PrepConfig(n_obs=1000),
    synthetic_pool=2500,
    dr_trees=12, dml_trees=12, cf_trees=12, cf_nuisance_trees=12,
    forest_depth=4, balance_iters=400,
)
out = sys.argv[1]
die_after = int(sys.argv[2])
done = {"n": 0}

def log(s):
    print(s, flush=True)
    if ": ate=" in s and "[resume]" not in s:
        done["n"] += 1
        if done["n"] == die_after:
            os._exit(42)  # kill between stages, skipping every finally

run_sweep(cfg, outdir=out, plots=False, log=log)
print("SWEEP_DONE", flush=True)
"""


def _child_sweep(outdir: str, die_after: int = -1) -> subprocess.CompletedProcess:
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop(chaos.ENV_VAR, None)
    return subprocess.run(
        [sys.executable, "-c", _CHILD, outdir, str(die_after)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900,
    )


def _rows(path: str) -> dict[str, dict]:
    out = {}
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("method") != "__config__":
                out[rec["method"]] = rec
    return out


def _payload(rec: dict) -> dict:
    return {k: rec.get(k) for k in ("ate", "lower_ci", "upper_ci", "se", "status")}


def test_killed_sweep_resumes_bit_identically(tmp_path):
    out = str(tmp_path / "killed")
    proc = _child_sweep(out, die_after=4)
    assert proc.returncode == 42, proc.stderr[-2000:]
    survivors = _rows(os.path.join(out, "results.jsonl"))
    assert len(survivors) == 4  # oracle + 3 estimator rows landed pre-kill

    # Rerun with the same outdir: survivors resume, the rest compute.
    proc2 = _child_sweep(out)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    assert proc2.stdout.count("[resume]") == 4
    assert "SWEEP_DONE" in proc2.stdout
    final = _rows(os.path.join(out, "results.jsonl"))
    assert set(final) == set(SWEEP_METHODS) | {"oracle"}
    for m, rec in survivors.items():
        assert _payload(final[m]) == _payload(rec), m  # resumed untouched

    # Fault-free reference run in a fresh outdir: every row bit-equal
    # (the jsonl float repr round-trips exactly, so dict equality is
    # bit-identity).
    ref_out = str(tmp_path / "ref")
    proc3 = _child_sweep(ref_out)
    assert proc3.returncode == 0, proc3.stderr[-2000:]
    ref = _rows(os.path.join(ref_out, "results.jsonl"))
    assert set(ref) == set(final)
    for m in ref:
        assert _payload(final[m]) == _payload(ref[m]), m


CHAOS_SPEC = (
    "shard:p=0.3,seed=11;"          # ~30% of dispatches fail once, retried
    "fs:torn_write;"                # first journal append lands torn
    "stage:fail=residual_balancing"  # one estimator exhausts its budget
)


def test_chaos_sweep_degrades_and_matches_fault_free_run(tmp_path):
    o_chaos = str(tmp_path / "chaos")
    o_clean = str(tmp_path / "clean")
    logs: list[str] = []
    obs.REGISTRY.reset()
    obs.EVENTS.clear()
    with chaos.override(CHAOS_SPEC):
        rep_chaos = run_sweep(NANO, outdir=o_chaos, plots=False,
                              log=logs.append)

    # The sweep completed and degraded exactly where told to.
    assert [r.method for r in rep_chaos.results] == list(SWEEP_METHODS)
    assert "residual_balancing" in rep_chaos.failures
    failed_row = rep_chaos.results["residual_balancing"]
    assert failed_row.status == "failed"
    assert any("[FAILED] residual_balancing" in l for l in logs)
    md = open(os.path.join(o_chaos, "REPORT.md")).read()
    assert "| residual_balancing | ✗ failed | — | — |" in md
    assert "### Degraded stages" in md
    # Chaos is auditable: injections counted and exported.
    metrics = json.load(open(os.path.join(o_chaos, "metrics.json")))
    chaos_c = metrics["counters"]["chaos_injections_total"]
    assert sum(chaos_c.values()) >= 2  # shard faults + torn write + stage
    assert "scope=stage" in chaos_c
    # The torn journal line is on disk (first append, the oracle row).
    journal = open(os.path.join(o_chaos, "results.jsonl")).read().splitlines()
    torn = [l for l in journal if l.strip() and not _parses(l)]
    assert len(torn) == 1

    # Fault-free reference: every successfully computed chaos row is
    # bit-identical to it (retried shards replay their own keys).
    chaos.reset()
    rep_clean = run_sweep(NANO, outdir=o_clean, plots=False,
                          log=lambda s: None)
    assert not rep_clean.failures
    for m in SWEEP_METHODS:
        if m == "residual_balancing":
            continue
        assert rep_chaos.results[m].ate == rep_clean.results[m].ate, m
        assert rep_chaos.results[m].se == rep_clean.results[m].se or (
            rep_chaos.results[m].se != rep_chaos.results[m].se
            and rep_clean.results[m].se != rep_clean.results[m].se
        ), m  # equal, or both NaN (the no-SE LASSO rows)
    assert rep_chaos.oracle.ate == rep_clean.oracle.ate

    # Resume the chaos outdir with chaos off: the failed row and the
    # torn row recompute; the sweep now matches the clean run fully.
    logs2: list[str] = []
    rep_resumed = run_sweep(NANO, outdir=o_chaos, plots=False,
                            log=logs2.append)
    assert any("[retry] residual_balancing" in l for l in logs2)
    assert not rep_resumed.failures
    for m in SWEEP_METHODS:
        assert rep_resumed.results[m].ate == rep_clean.results[m].ate, m
    md2 = open(os.path.join(o_chaos, "REPORT.md")).read()
    assert "✗ failed" not in md2


def _parses(line: str) -> bool:
    try:
        json.loads(line)
        return True
    except json.JSONDecodeError:
        return False


def test_concurrent_chaos_matches_sequential_clean_and_cross_mode_resume(tmp_path):
    """ISSUE 4 acceptance: chaos + the concurrent scheduler + resume,
    cross-checked against the SEQUENTIAL scheduler. A chaotic concurrent
    sweep must (a) degrade exactly where told, (b) keep the journal in
    declared order despite worker completion order, (c) be bit-identical
    to a fault-free sequential run on every computed row, and (d) heal
    fully when the sequential scheduler resumes the concurrent run's
    checkpoint (mode is not part of the fingerprint — either mode may
    resume the other's journal)."""
    o_seq = str(tmp_path / "seq")
    o_chaos = str(tmp_path / "chaos")
    rep_seq = run_sweep(NANO, outdir=o_seq, plots=False, log=lambda s: None,
                        scheduler="sequential")
    assert not rep_seq.failures

    logs: list[str] = []
    with chaos.override(CHAOS_SPEC):
        rep_chaos = run_sweep(NANO, outdir=o_chaos, plots=False,
                              log=logs.append, scheduler="concurrent",
                              workers=4)
    assert "residual_balancing" in rep_chaos.failures
    assert any("[FAILED] residual_balancing" in l for l in logs)

    # (b) journal order: the torn line (first append — the oracle row)
    # stays in place; every parsable row follows declared order.
    journal = open(os.path.join(o_chaos, "results.jsonl")).read().splitlines()
    parsable = [json.loads(l)["method"] for l in journal
                if l.strip() and _parses(l)]
    expected = ["__config__", "oracle"] + list(SWEEP_METHODS)
    assert parsable == [m for m in expected if m in parsable]
    nonempty = [l for l in journal if l.strip()]
    assert len(nonempty) - len(parsable) == 1  # exactly one torn row
    assert "oracle" not in parsable  # the torn row is the first append

    # (c) every computed row bit-identical to the sequential clean run.
    for m in SWEEP_METHODS:
        if m == "residual_balancing":
            continue
        assert rep_chaos.results[m].ate == rep_seq.results[m].ate, m
        assert rep_chaos.results[m].se == rep_seq.results[m].se or (
            rep_chaos.results[m].se != rep_chaos.results[m].se
            and rep_seq.results[m].se != rep_seq.results[m].se
        ), m
    assert rep_chaos.oracle.ate == rep_seq.oracle.ate

    # (d) sequential resume of the concurrent chaotic outdir: failed +
    # torn rows recompute; the result matches the sequential clean run.
    chaos.reset()
    logs2: list[str] = []
    rep_resumed = run_sweep(NANO, outdir=o_chaos, plots=False,
                            log=logs2.append, scheduler="sequential")
    assert any("[retry] residual_balancing" in l for l in logs2)
    assert not rep_resumed.failures
    for m in SWEEP_METHODS:
        assert rep_resumed.results[m].ate == rep_seq.results[m].ate, m

"""Device-resident artifact plane (ISSUE 8): reshard round-trip
bit-identity across 1/2/4/8 virtual devices for every declared artifact
value shape, compile-once caching of the shard/gather/reshard paths,
byte metering, and the one-host-round-trip regression replacing the
PR-4 ``materialized()`` double copy.

Cheap by design: every program here is a compiled identity over tiny
arrays — no estimator compute (tier-1 budget note in CHANGES.md)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.parallel import shardio
from ate_replication_causalml_tpu.parallel.mesh import DATA_AXIS
from ate_replication_causalml_tpu.scheduler import (
    ArtifactSpec,
    StageSpec,
    SweepEngine,
)

N = 1024  # divides every tested axis size


def _mesh(d):
    return Mesh(np.asarray(jax.devices()[:d]), (DATA_AXIS,))


def _artifact_values():
    """The value shapes the sweep/bench declare as sharded artifacts:
    a propensity vector (lasso_ps / rf_oob_propensity / p_fold), a 2-D
    design matrix (the panel), and the (mu0, mu1) pytree."""
    rng = np.random.default_rng(3)
    vec = rng.standard_normal(N).astype(np.float32)
    mat = rng.standard_normal((N, 5)).astype(np.float32)
    return {
        "vec": vec,
        "mat": mat,
        "mu_pair": (vec + 1.0, (vec - 1.0).astype(np.float64)),
    }


def _host_leaves(tree):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]


@pytest.mark.parametrize("d", [1, 2, 4, 8])
def test_roundtrip_bit_identity_across_device_counts(d):
    mesh = _mesh(d)
    rs = shardio.row_sharding(mesh, N)
    rep = NamedSharding(mesh, P())
    for name, val in _artifact_values().items():
        tag = f"rt_{name}"
        dev = shardio.commit(val, rs, artifact=tag)
        for leaf in jax.tree_util.tree_leaves(dev):
            assert leaf.sharding == rs
        # host round trip is bit-identical, dtype included
        for a, b in zip(_host_leaves(val),
                        _host_leaves(shardio.gather_host(dev, artifact=tag))):
            assert a.dtype == b.dtype
            assert np.array_equal(a, b)
        # reshard away and back: still bit-identical
        back = shardio.reshard(
            shardio.reshard(dev, rep, artifact=tag), rs, artifact=tag
        )
        for a, b in zip(_host_leaves(val),
                        _host_leaves(shardio.gather_host(back, artifact=tag))):
            assert np.array_equal(a, b)


def test_row_sharding_uneven_rows_fall_back_replicated():
    mesh = _mesh(8)
    assert shardio.row_sharding(mesh, 1001).is_fully_replicated
    assert shardio.row_sharding(mesh, N) == NamedSharding(mesh, P(DATA_AXIS))


def test_pad_to_multiple_units():
    assert shardio.pad_to_multiple(0, 8) == 8
    assert shardio.pad_to_multiple(1, 8) == 8
    assert shardio.pad_to_multiple(8, 8) == 8
    assert shardio.pad_to_multiple(9, 8) == 16
    assert shardio.pad_to_multiple(1001, 8) == 1008
    assert shardio.pad_to_multiple(7, 1) == 7


def test_pad_rows_rejects_disagreeing_leaves():
    with pytest.raises(ValueError, match="disagree"):
        shardio.pad_rows((np.zeros(3), np.zeros(4)), 8)


@pytest.mark.parametrize("d", [1, 2, 4, 8])
@pytest.mark.parametrize("n", [1001, N])
def test_padded_shard_roundtrip_bit_identity(d, n):
    """ISSUE 13 satellite: the pad-to-divisible + row-mask helper lifts
    the uneven-rows→replicated fallback — every leaf lands EVENLY
    row-sharded (never replicated), the mask gates exactly the real
    rows, and the gather inverts the transform bit-identically at every
    device count, uneven row counts included."""
    mesh = _mesh(d)
    rng = np.random.default_rng(17)
    val = {
        "vec": rng.standard_normal(n).astype(np.float32),
        "mat": rng.standard_normal((n, 3)).astype(np.float64),
    }
    dev, mask, n_out = shardio.shard_rows_padded(val, mesh, artifact="pad_rt")
    assert n_out == n
    padded = shardio.pad_to_multiple(n, d)
    for leaf in jax.tree_util.tree_leaves(dev):
        assert leaf.shape[0] == padded
        assert leaf.sharding == NamedSharding(mesh, P(DATA_AXIS))
        assert not leaf.sharding.is_fully_replicated or d == 1
    assert mask.shape == (padded,)
    np.testing.assert_array_equal(
        np.asarray(mask), shardio.row_mask(n, padded)
    )
    back = shardio.gather_rows_padded(dev, n, artifact="pad_rt")
    for a, b in zip(_host_leaves(val), _host_leaves(back)):
        assert a.dtype == b.dtype
        assert np.array_equal(a, b)
    assert back["vec"].flags.writeable is False
    # Masked reductions over the padded shards equal the unpadded
    # truth EXACTLY: pad rows are exact zeros and the mask is exact
    # 0/1, so no pad contribution survives the sum. Integer-valued f32
    # so the sum is association-invariant (the sharded reduction's
    # per-shard partials may reassociate; exact sums don't care).
    ints = rng.integers(-1000, 1000, n).astype(np.float32)
    ints_dev, imask, _ = shardio.shard_rows_padded(ints, mesh,
                                                   artifact="pad_sum")
    total = jax.jit(lambda v, m: (v * m.astype(v.dtype)).sum())(
        ints_dev, imask
    )
    assert float(total) == float(ints.sum())


def _delta(family, before):
    after = obs.REGISTRY.peek(family) or {}
    return {k: v - before.get(k, 0.0) for k, v in after.items()
            if v != before.get(k, 0.0)}


def test_reshard_path_compiles_once_and_meters():
    mesh = _mesh(4)
    # Unique shape so earlier tests cannot have pre-seeded this path.
    v = np.arange(4 * 37, dtype=np.float32).reshape(4, 37)
    rs = NamedSharding(mesh, P(DATA_AXIS))
    rep = NamedSharding(mesh, P())
    before = dict(obs.REGISTRY.peek(shardio.CALLS_FAMILY) or {})
    dev = shardio.commit(v, rs, artifact="once")         # host upload
    r1 = shardio.reshard(dev, rep, artifact="once")      # compiles
    shardio.reshard(dev, rep, artifact="once")           # cached fn
    shardio.reshard(r1, rep, artifact="once")            # already there
    calls = _delta(shardio.CALLS_FAMILY, before)
    assert calls.get("artifact=once,status=upload") == 1
    assert calls.get("artifact=once,status=compiled") == 1
    assert calls.get("artifact=once,status=cached") == 1
    assert calls.get("artifact=once,status=noop") == 1


def test_byte_paths_metered_exactly():
    mesh = _mesh(8)
    v = np.arange(2048, dtype=np.float32)
    rs = shardio.row_sharding(mesh, v.size)
    before = dict(obs.REGISTRY.peek(shardio.BYTES_FAMILY) or {})
    dev = shardio.commit(v, rs, artifact="bytes_t")
    shardio.handoff(dev, artifact="bytes_t")
    host = shardio.gather_host(dev, artifact="bytes_t")
    bounced = shardio.host_bounce(dev, artifact="bytes_t")
    moved = _delta(shardio.BYTES_FAMILY, before)
    assert moved.get("artifact=bytes_t,path=host_upload") == v.nbytes
    assert moved.get("artifact=bytes_t,path=device_handoff") == v.nbytes
    assert moved.get("artifact=bytes_t,path=host_gather") == v.nbytes
    # the gather's internal all-gather is device traffic and is metered
    assert moved.get("artifact=bytes_t,path=device_reshard") == v.nbytes
    # the legacy double copy records BOTH crossings — the before-number
    assert moved.get("artifact=bytes_t,path=host_bounce") == 2 * v.nbytes
    assert np.array_equal(host, v)
    assert np.array_equal(np.asarray(bounced), v)
    # The host form is shared by every consumer: read-only, so an
    # in-place write fails loudly instead of corrupting the cache.
    assert host.flags.writeable is False
    with pytest.raises(ValueError):
        host[0] = 0.0


def test_unlaned_consumers_pay_one_host_round_trip():
    """The materialized() regression (ISSUE 8 satellite): a mesh-lane
    sharded artifact consumed by unlaned stages crosses the host ONCE —
    one metered gather shared by every host consumer — never the legacy
    np.asarray→jnp.asarray double copy (host_bounce must stay zero on
    any scheduled run)."""
    raw = np.arange(4096, dtype=np.float32)
    mesh = _mesh(8)
    rs = shardio.row_sharding(mesh, raw.size)
    got = {}
    arts = [ArtifactSpec("reg_p", fit=lambda c: jax.numpy.asarray(raw),
                         key=("k",), exclusive="mesh", sharding=rs)]
    stages = [
        StageSpec("u1", run=lambda c: got.setdefault("u1", c.get("reg_p")),
                  needs=("reg_p",)),
        StageSpec("u2", run=lambda c: got.setdefault("u2", c.get("reg_p")),
                  needs=("reg_p",)),
    ]
    before = dict(obs.REGISTRY.peek(shardio.BYTES_FAMILY) or {})
    SweepEngine(arts, stages, workers=2, prefetch=False).run()
    moved = _delta(shardio.BYTES_FAMILY, before)
    assert moved.get("artifact=reg_p,path=host_gather") == raw.nbytes
    assert not any("path=host_bounce" in k for k in moved)
    assert isinstance(got["u1"], np.ndarray)
    assert np.array_equal(got["u1"], raw)
    assert got["u2"] is got["u1"]
    assert got["u1"].flags.writeable is False


def test_edge_byte_plan():
    for nb in (1, 4096, 1 << 22):
        assert shardio.edge_byte_plan(nb, "mesh", "mesh") == {
            "host_bytes": 0, "device_bytes": nb, "legacy_host_bytes": 2 * nb,
        }
        for producer, consumer in (("mesh", None), (None, None),
                                   ("mesh", "other")):
            plan = shardio.edge_byte_plan(nb, producer, consumer)
            assert plan["host_bytes"] == nb and plan["device_bytes"] == 0
            assert plan["legacy_host_bytes"] == 2 * nb

"""Native host-runtime tests: the C++ R-compat RNG must bit-match the
NumPy implementation (which is itself validated against published R
streams in test_rrandom.py), and the C++ CSV reader must agree with the
NumPy loader."""

import numpy as np
import pytest

from ate_replication_causalml_tpu.native import (
    NativeRCompatRNG,
    make_rcompat_rng,
    native_available,
    native_status,
    read_csv_native,
)
from ate_replication_causalml_tpu.utils.rrandom import RCompatRNG

pytestmark = pytest.mark.skipif(
    not native_available(), reason=f"native build unavailable: {native_status()}"
)


@pytest.mark.parametrize("seed", [1991, 0, 12325, 2**31 - 1])
def test_runif_bit_matches_python(seed):
    a = NativeRCompatRNG(seed).runif(2000)
    b = RCompatRNG(seed).runif(2000)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("kind", ["rounding", "rejection"])
def test_sample_with_replacement_matches(kind):
    a = NativeRCompatRNG(1991, kind).sample_int(8937, 8937, replace=True)
    b = RCompatRNG(1991, kind).sample_int(8937, 8937, replace=True)
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("kind", ["rounding", "rejection"])
def test_sample_without_replacement_matches(kind):
    a = NativeRCompatRNG(7, kind).sample_n_rows(229461, 50000)
    b = RCompatRNG(7, kind).sample_n_rows(229461, 50000)
    np.testing.assert_array_equal(a, b)


def test_stream_interleaving_matches():
    """runif / sample calls drawing from one stream, in sequence."""
    a = NativeRCompatRNG(42)
    b = RCompatRNG(42)
    np.testing.assert_array_equal(a.runif(7), b.runif(7))
    np.testing.assert_array_equal(a.sample_int(100, 10), b.sample_int(100, 10))
    np.testing.assert_array_equal(a.runif(630), b.runif(630))  # crosses a block
    np.testing.assert_array_equal(
        a.sample_int(50, 50, replace=True), b.sample_int(50, 50, replace=True)
    )


def test_factory_backends():
    nat = make_rcompat_rng(1991, backend="auto")
    py = make_rcompat_rng(1991, backend="python")
    assert isinstance(py, RCompatRNG)
    np.testing.assert_array_equal(nat.runif(10), py.runif(10))


def test_csv_reader_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    mat = rng.normal(size=(50, 4)).round(6)
    path = tmp_path / "t.csv"
    header = "a,b,c,d"
    lines = [header]
    for i, row in enumerate(mat):
        cells = [f"{v:.6f}" for v in row]
        if i == 3:
            cells[1] = "NA"   # R's missing marker
        if i == 7:
            cells[2] = ""     # blank field
        lines.append(",".join(cells))
    path.write_text("\n".join(lines) + "\n")

    names, out = read_csv_native(str(path))
    assert names == ["a", "b", "c", "d"]
    assert out.shape == (50, 4)
    expect = mat.copy()
    expect[3, 1] = np.nan
    expect[7, 2] = np.nan
    np.testing.assert_allclose(out, expect, rtol=0, atol=1e-9)


def test_csv_reader_no_trailing_newline(tmp_path):
    path = tmp_path / "t.csv"
    path.write_text("x,y\n1,2\n3,4")
    names, out = read_csv_native(str(path))
    assert names == ["x", "y"]
    np.testing.assert_array_equal(out, [[1.0, 2.0], [3.0, 4.0]])


def test_csv_reader_skips_blank_lines(tmp_path):
    """Blank lines are not rows (genfromtxt semantics) — a stray blank
    line must not shift the R-seeded subsample draw."""
    path = tmp_path / "t.csv"
    path.write_text("a,b\n1,2\n\n3,4\n\r\n5,6\n")
    _, out = read_csv_native(str(path))
    np.testing.assert_array_equal(out, [[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])


def test_csv_reader_short_rows_are_nan(tmp_path):
    """Missing trailing fields read as NaN, never uninitialized memory."""
    path = tmp_path / "t.csv"
    path.write_text("a,b,c\n1,2,3\n4,5\n7,8,9\n")
    _, out = read_csv_native(str(path))
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(out[0], [1.0, 2.0, 3.0])
    np.testing.assert_array_equal(out[1, :2], [4.0, 5.0])
    assert np.isnan(out[1, 2])
    np.testing.assert_array_equal(out[2], [7.0, 8.0, 9.0])


def test_sanitizer_clean(tmp_path):
    """Build the native runtime + selftest under ASan/UBSan and run it
    (SURVEY.md §5.2 — sanitizers for the only native code in the
    framework). Catches leaks, overflow, UB in the RNG/CSV cores."""
    import os
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain for the sanitizer build")

    here = os.path.dirname(
        __import__("ate_replication_causalml_tpu.native", fromlist=["x"]).__file__
    )
    exe = str(tmp_path / "selftest")
    subprocess.run(
        ["g++", "-O1", "-g", "-std=c++17",
         "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
         os.path.join(here, "rcompat.cpp"),
         os.path.join(here, "rcompat_selftest.cpp"),
         "-o", exe],
        check=True, capture_output=True, text=True,
    )
    out = subprocess.run(
        [exe], check=True, capture_output=True, text=True,
        env={**os.environ, "ASAN_OPTIONS": "detect_leaks=1"},
    )
    assert "all checks passed" in out.stdout


def test_csv_reader_all_missing_line(tmp_path):
    path = tmp_path / "t.csv"
    path.write_text("a,b\n,\nNA,7\n")
    _, out = read_csv_native(str(path))
    assert out.shape == (2, 2)
    assert np.isnan(out[0]).all()
    assert np.isnan(out[1, 0]) and out[1, 1] == 7.0

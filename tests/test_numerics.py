"""OLS/WLS/GLM numerics vs independent float64 NumPy references."""

import jax.numpy as jnp
import numpy as np

from ate_replication_causalml_tpu.ops.glm import logistic_glm, predict_proba
from ate_replication_causalml_tpu.ops.linalg import ols, ols_no_intercept_1d, wls

RNG = np.random.default_rng(0)


def _design(n=500, p=6):
    x = RNG.normal(size=(n, p))
    beta = RNG.normal(size=p + 1)
    return x, beta


def test_ols_matches_numpy_lstsq():
    x, beta = _design()
    xd = np.column_stack([np.ones(len(x)), x])
    y = xd @ beta + RNG.normal(scale=0.5, size=len(x))
    fit = ols(jnp.asarray(xd), jnp.asarray(y))
    want, *_ = np.linalg.lstsq(xd, y, rcond=None)
    np.testing.assert_allclose(np.asarray(fit.coef), want, atol=1e-8)
    # Classical SEs: sqrt(diag((X'X)^-1) * RSS/(n-p))
    resid = y - xd @ want
    sigma2 = resid @ resid / (len(y) - xd.shape[1])
    se_want = np.sqrt(np.diag(np.linalg.inv(xd.T @ xd)) * sigma2)
    np.testing.assert_allclose(np.asarray(fit.se), se_want, atol=1e-8)


def test_wls_matches_closed_form():
    x, beta = _design()
    xd = np.column_stack([np.ones(len(x)), x])
    y = xd @ beta + RNG.normal(scale=0.5, size=len(x))
    wts = RNG.uniform(0.2, 3.0, size=len(x))
    fit = wls(jnp.asarray(xd), jnp.asarray(y), jnp.asarray(wts))
    xtwx = xd.T @ (xd * wts[:, None])
    want = np.linalg.solve(xtwx, xd.T @ (wts * y))
    np.testing.assert_allclose(np.asarray(fit.coef), want, atol=1e-8)
    resid = y - xd @ want
    sigma2 = (wts * resid**2).sum() / (len(y) - xd.shape[1])
    se_want = np.sqrt(np.diag(np.linalg.inv(xtwx)) * sigma2)
    np.testing.assert_allclose(np.asarray(fit.se), se_want, atol=1e-8)


def test_ols_no_intercept_1d():
    x = RNG.normal(size=400)
    y = 2.5 * x + RNG.normal(scale=0.3, size=400)
    coef, se = ols_no_intercept_1d(jnp.asarray(x), jnp.asarray(y))
    want = (x @ y) / (x @ x)
    np.testing.assert_allclose(float(coef), want, atol=1e-10)
    resid = y - want * x
    se_want = np.sqrt((resid @ resid) / (len(x) - 1) / (x @ x))
    np.testing.assert_allclose(float(se), se_want, atol=1e-10)


def _numpy_irls(xd, y, tol=1e-8, max_iter=25):
    """Independent reference implementation of R glm.fit binomial IRLS."""
    mu = (y + 0.5) / 2.0
    eta = np.log(mu / (1 - mu))
    dev = -2 * np.sum(y * np.log(mu) + (1 - y) * np.log(1 - mu))
    coef = np.zeros(xd.shape[1])
    for _ in range(max_iter):
        mu = 1 / (1 + np.exp(-eta))
        w = np.clip(mu * (1 - mu), 1e-10, None)
        z = eta + (y - mu) / w
        coef = np.linalg.solve(xd.T @ (xd * w[:, None]), xd.T @ (w * z))
        eta = xd @ coef
        mu = 1 / (1 + np.exp(-eta))
        dev_new = -2 * np.sum(
            y * np.log(np.clip(mu, 1e-300, None)) + (1 - y) * np.log(np.clip(1 - mu, 1e-300, None))
        )
        if abs(dev_new - dev) / (abs(dev_new) + 0.1) < tol:
            dev = dev_new
            break
        dev = dev_new
    return coef, mu


def test_logistic_glm_matches_reference_irls():
    x, _ = _design(n=2000, p=5)
    xd = np.column_stack([np.ones(len(x)), x])
    logits = xd @ np.array([-0.4, 0.8, -0.5, 0.3, 0.0, 1.1])
    y = (RNG.random(len(x)) < 1 / (1 + np.exp(-logits))).astype(float)
    fit = logistic_glm(jnp.asarray(xd), jnp.asarray(y))
    want_coef, want_mu = _numpy_irls(xd, y)
    assert bool(fit.converged)
    np.testing.assert_allclose(np.asarray(fit.coef), want_coef, atol=1e-7)
    np.testing.assert_allclose(np.asarray(fit.fitted), want_mu, atol=1e-7)
    # SEs positive and sane
    assert np.all(np.asarray(fit.se) > 0)


def test_glm_predict_counterfactual():
    x, _ = _design(n=800, p=4)
    w = (RNG.random(len(x)) < 0.4).astype(float)
    xd = np.column_stack([np.ones(len(x)), x, w])
    logits = xd @ np.array([-0.2, 0.5, -0.3, 0.2, 0.1, 0.7])
    y = (RNG.random(len(x)) < 1 / (1 + np.exp(-logits))).astype(float)
    fit = logistic_glm(jnp.asarray(xd), jnp.asarray(y))
    xd1 = xd.copy()
    xd1[:, -1] = 1.0
    p1 = predict_proba(fit.coef, jnp.asarray(xd1))
    assert p1.shape == (len(x),)
    assert np.all((np.asarray(p1) > 0) & (np.asarray(p1) < 1))

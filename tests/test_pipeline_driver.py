"""L5 driver tests: the full sweep on tiny sizes, checkpoint/resume
semantics, and figure output (``ate_replication.Rmd`` end-to-end,
SURVEY.md §3.1)."""

import dataclasses
import json
import os

import pytest

from ate_replication_causalml_tpu.data.pipeline import PrepConfig
from ate_replication_causalml_tpu.pipeline import SweepConfig, run_sweep

TINY = dataclasses.replace(
    SweepConfig().quick(),
    # Round 5: 3000 rows / 50 trees -> 2000 / 32 (the sweep's cost is
    # XLA compiles plus Belloni's CPU coordinate descent, both scaling
    # with rows; every driver assertion below is scale-free except the
    # oracle tolerance, which stays 4-sigma-safe at n=2000).
    prep=PrepConfig(n_obs=2000),
    synthetic_pool=4000,
    dr_trees=32, dml_trees=32, cf_trees=32, cf_nuisance_trees=32,
    forest_depth=5,
)

EXPECTED_METHODS = [
    "naive", "Direct Method", "Propensity_Weighting", "Propensity_Regression",
    "Propensity_Weighting_LASSOPS", "Single-equation LASSO", "Usual LASSO",
    "Doubly Robust with Random Forest PS",
    "Doubly Robust with logistic regression PS", "Belloni et.al",
    "Double Machine Learning", "residual_balancing", "Causal Forest(GRF)",
]


def test_full_sweep_and_resume(tmp_path):
    out = str(tmp_path / "sweep")
    logs = []
    report = run_sweep(TINY, outdir=out, plots=True, log=logs.append)

    # Parallel-axis composition (VERDICT r2 #5): on this 8-device test
    # backend the sweep must activate the tree + fold meshes.
    assert any("mesh: 8 devices" in l for l in logs), logs[:3]
    # All 13 estimator rows in notebook order, plus the oracle.
    assert report.results.methods() == EXPECTED_METHODS
    assert report.oracle.method == "oracle"
    assert report.n_dropped > 0 and report.n_biased > 0
    assert report.incorrect_cf_ate is not None
    # The synthetic RCT oracle should land near the generator's target.
    assert abs(report.oracle.ate - TINY.true_ate) < 0.06
    # Outputs on disk: results, report, three figures.
    assert os.path.exists(os.path.join(out, "results.jsonl"))
    rep = json.load(open(os.path.join(out, "report.json")))
    assert len(rep["results"]) == len(EXPECTED_METHODS)
    assert len(report.figure_paths) == 3
    for p in report.figure_paths:
        assert os.path.getsize(p) > 10_000
    # The rendered replication document (VERDICT r2 #7): section-by-
    # section mirror of ate_replication.md.
    md = open(os.path.join(out, "REPORT.md")).read()
    assert f"## [1] {report.n_dropped}" in md
    assert "Incorrect ATE:" in md
    for m in EXPECTED_METHODS:
        assert f"| {m} |" in md
    for fig in report.figure_paths:
        assert os.path.basename(fig) in md

    # Trace artifacts (ISSUE 5) ride the same concurrent run for free:
    # catapult-valid trace.json + an internally consistent overlap
    # report (Σ busy ≤ wall × workers; critical path ≥ longest node).
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    import check_metrics_schema as _cms

    assert _cms.validate_trace_files(out) == []
    rep_ov = json.load(open(os.path.join(out, "overlap_report.json")))
    assert rep_ov["nodes"] == 21  # 14 stages + 7 artifacts
    assert rep_ov["busy_total_s"] <= rep_ov["wall_s"] * rep_ov["workers"] + 1e-6
    assert rep_ov["critical_path_s"] >= rep_ov["longest_node_s"] - 1e-9
    assert "mesh" in rep_ov["serialization"]["lanes"]

    # The journal keeps the declared notebook order even though the
    # default scheduler ran stages concurrently (ISSUE 4: commits are
    # ordered; completion order must never leak into results.jsonl).
    methods_on_disk = [
        json.loads(l)["method"]
        for l in open(os.path.join(out, "results.jsonl"))
        if l.strip()
    ]
    assert methods_on_disk == ["__config__", "oracle"] + EXPECTED_METHODS

    # Resume: every stage must come from the checkpoint, same numbers.
    logs2 = []
    report2 = run_sweep(TINY, outdir=out, plots=False, log=logs2.append)
    resumed = [l for l in logs2 if "[resume]" in l]
    assert len(resumed) == len(EXPECTED_METHODS) + 1  # + oracle
    for m in EXPECTED_METHODS:
        assert abs(report2.results[m].ate - report.results[m].ate) < 1e-12
    assert report2.incorrect_cf_ate == report.incorrect_cf_ate


# Checkpoint/config-mechanics tests only exercise the driver plumbing —
# they run a MICRO sweep (separate shapes compile once into the
# persistent cache; execution is seconds) so the full-size TINY sweep
# runs exactly once per suite (VERDICT r2 #8).
MICRO = dataclasses.replace(
    TINY,
    prep=PrepConfig(n_obs=1200),
    synthetic_pool=3000,
    dr_trees=16, dml_trees=16, cf_trees=16, cf_nuisance_trees=16,
    forest_depth=4, balance_iters=600,
)


@pytest.mark.slow
def test_changed_config_invalidates_checkpoint(tmp_path):
    """One MICRO sweep writes a real checkpoint; the invalidation
    mechanics are then asserted directly on ``_Checkpoint`` with the
    fingerprints ``run_sweep`` itself would construct (a changed config
    reprs differently, so its fingerprint differs) — a second full
    sweep only re-exercised the estimator stages the first one already
    covered, at ~2 min of XLA compiles (suite wall-clock, VERDICT r2
    #8). The resume-on-match leg runs end-to-end in
    ``test_full_sweep_and_resume``.

    @slow since ISSUE 15 (the documented tier-1 budget swap): the
    chaos-campaign acceptance rig (tests/test_campaign.py) runs TWO
    micro sweeps at exactly these MICRO shapes (a fault-free reference
    and a 4-scope chaos episode) and displaced this test's single
    sweep from the tier-1 budget. The _Checkpoint fingerprint/stale
    mechanics this test pins directly stay covered in tier-1 by the
    campaign's journal-integrity invariant plus the no-jax checkpoint
    units in tests/test_resilience.py; the sequential-scheduler escape
    hatch stays covered by the traced sequential micro sweep in
    tests/test_trace.py."""
    from ate_replication_causalml_tpu.pipeline import _Checkpoint

    out = str(tmp_path / "sweep")
    # Sequential on purpose: this test covers checkpoint plumbing, not
    # the scheduler (the full sweep above and the observability
    # integration keep the concurrent default), and a cold-trace
    # concurrent sweep is ~1.25x slower on the 2-core CI host (GIL-
    # serial first-touch tracing) — tier-1 budget.
    run_sweep(MICRO, outdir=out, plots=False, log=lambda s: None,
              scheduler="sequential")
    # report.json must be strict JSON (the no-SE LASSO rows carry NaN
    # internally; on disk they must be null).
    import json as _json

    txt = open(os.path.join(out, "report.json")).read()
    assert "NaN" not in txt
    _json.loads(txt)

    changed = dataclasses.replace(MICRO, dr_trees=MICRO.dr_trees + 1)
    assert repr(changed) != repr(MICRO)
    path = os.path.join(out, "results.jsonl")

    # The on-disk fingerprint embeds the config repr — the link that
    # makes "changed config => different fingerprint" actually hold for
    # run_sweep (pipeline.py builds f"{config!r}|csv=...|...").
    header = _json.loads(open(path).readline())
    assert repr(MICRO) in header["fingerprint"]

    # Same fingerprint: rows resume.
    same = _Checkpoint(path, header["fingerprint"], log=lambda s: None)
    assert same.get("naive") is not None

    # Any differing fingerprint (as a changed config produces, per the
    # repr assertions above): the checkpoint is set aside, nothing
    # resumes, a fresh header appears.
    logs = []
    fresh = _Checkpoint(path, header["fingerprint"] + "|changed", log=logs.append)
    assert any("different config" in l for l in logs)
    assert os.path.exists(path + ".stale")
    assert fresh.get("naive") is None
    new_header = _json.loads(open(path).readline())
    assert new_header["fingerprint"] == header["fingerprint"] + "|changed"


@pytest.mark.slow
def test_sweep_no_outdir_runs_in_memory():
    # @slow since ISSUE 13 (the documented tier-1 budget swap): the
    # scenario-matrix acceptance module (tests/test_scenarios.py,
    # ~35 s) displaced this ~40 s run. What this test added over the
    # rest of tier-1 was thin by then — the sequential escape hatch is
    # exercised by test_changed_config_invalidates_checkpoint's MICRO
    # sweep (itself @slow since ISSUE 15; the MICRO shapes' compiles
    # are now paid in tier-1 by the campaign rig's sweep episodes in
    # tests/test_campaign.py) and by the traced
    # sequential micro sweep in tests/test_trace.py; only the
    # outdir=None plumbing branch (checkpoint + exports disabled) is
    # unique here, and it keeps end-to-end coverage in this tier.
    report = run_sweep(MICRO, outdir=None, plots=False, log=lambda s: None,
                       scheduler="sequential")
    assert len(report.results) == len(EXPECTED_METHODS)

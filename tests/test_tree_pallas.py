"""Exactness contracts for the row-side Pallas kernels
(ops/tree_pallas.py): leaf-table lookup and tree-batched routing.

Both kernels replace XLA formulations in the streaming growers, so
they must be BIT-identical to them — lookups select a single table
entry via a one-nonzero-product contraction, routing is an integer
compare — no rounding path exists in either.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.models.forest import (
    route_rows,
    route_rows_blocked,
)
from ate_replication_causalml_tpu.ops.tree_pallas import (
    codes_transposed,
    route_bits,
    table_lookup,
)


def test_table_lookup_matches_gather():
    rng = np.random.default_rng(0)
    n, L = 5000, 512
    table = jnp.asarray(rng.normal(size=L), jnp.float32)
    ids = jnp.asarray(rng.integers(0, L, n), jnp.int32)
    got = table_lookup(table, ids, backend="pallas_interpret")
    assert jnp.array_equal(got, table[ids])
    # The gather fallback obeys the same contract.
    assert jnp.array_equal(table_lookup(table, ids, backend="gather"), table[ids])


def test_table_lookup_out_of_range_is_zero():
    table = jnp.asarray([1.0, 2.0, 3.0], jnp.float32)
    ids = jnp.asarray([0, -1, 2, 3], jnp.int32)
    want = jnp.asarray([1.0, 0.0, 3.0, 0.0], jnp.float32)
    got = table_lookup(table, ids, backend="pallas_interpret")
    assert jnp.array_equal(got, want)
    assert jnp.array_equal(table_lookup(table, ids, backend="gather"), want)


def test_table_lookup_vmap_collapses():
    """Vmapped (and nested-vmapped) calls must equal per-tree calls —
    the rule flattens batch axes into the kernel's tree axis."""
    rng = np.random.default_rng(1)
    t, n, L = 5, 700, 64
    tables = jnp.asarray(rng.normal(size=(t, L)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, L, (t, n)), jnp.int32)
    got = jax.vmap(
        lambda tb, i: table_lookup(tb, i, backend="pallas_interpret")
    )(tables, ids)
    want = jnp.stack([tables[i][ids[i]] for i in range(t)])
    assert jnp.array_equal(got, want)
    # Nested vmap (groups × trees), mirroring the causal grower.
    tables2 = tables[:4].reshape(2, 2, L)
    ids2 = ids[:4].reshape(2, 2, n)
    got2 = jax.vmap(
        jax.vmap(lambda tb, i: table_lookup(tb, i, backend="pallas_interpret"))
    )(tables2, ids2)
    assert jnp.array_equal(got2, want[:4].reshape(2, 2, n))


def test_table_lookup_multichannel():
    """A (K, L) table looks all K channels up through one shared
    one-hot — bit-identical to K separate gathers."""
    rng = np.random.default_rng(9)
    K, L, n = 5, 256, 3000
    table = jnp.asarray(rng.normal(size=(K, L)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, L, n), jnp.int32)
    got = table_lookup(table, ids, backend="pallas_interpret")
    want = table[:, ids]
    assert got.shape == (K, n)
    assert jnp.array_equal(got, want)
    assert jnp.array_equal(table_lookup(table, ids, backend="gather"), want)


@pytest.mark.slow
def test_predict_cate_kernel_path_matches_matmul():
    """predict_cate's Pallas row path (TPU default) must reproduce the
    matmul formulation exactly — routing and leaf broadcast are both
    exact selections."""
    from ate_replication_causalml_tpu.models.causal_forest import (
        grow_causal_forest,
        predict_cate,
    )

    rng = np.random.default_rng(11)
    n, p = 3000, 5
    x = jnp.asarray(rng.normal(size=(n, p)), jnp.float32)
    w = jnp.asarray(rng.normal(size=n), jnp.float32)
    y = jnp.asarray(0.8 * w * (x[:, 0] > 0) + rng.normal(size=n), jnp.float32)
    forest = grow_causal_forest(
        x, w, y, jax.random.key(3), n_trees=8, depth=4,
        hist_backend="pallas_interpret",
    )
    base = predict_cate(forest, x, oob=True, row_backend="matmul")
    kern = predict_cate(forest, x, oob=True, row_backend="pallas_interpret")
    np.testing.assert_allclose(
        np.asarray(kern.cate), np.asarray(base.cate), rtol=0, atol=0
    )
    np.testing.assert_allclose(
        np.asarray(kern.variance), np.asarray(base.variance), rtol=0, atol=0
    )


def test_route_bits_matches_blocked_route():
    """The Pallas route must agree bit-for-bit with the one-hot-matmul
    route at every level width, including the vmapped tree case."""
    rng = np.random.default_rng(2)
    n, p, n_bins = 3000, 7, 16
    codes = jnp.asarray(rng.integers(0, n_bins, (n, p)), jnp.int32)
    codes_t = codes_transposed(codes)
    for m in (1, 2, 8, 64):
        ids = jnp.asarray(rng.integers(0, m, n), jnp.int32)
        bf = jnp.asarray(rng.integers(0, p, m), jnp.int32)
        bb = jnp.asarray(rng.integers(0, n_bins, m), jnp.int32)
        routed = route_rows_blocked(ids, bf, bb, codes)
        want_bit = routed - 2 * ids
        got_bit = route_bits(codes_t, ids, bf, bb, backend="pallas_interpret")
        assert jnp.array_equal(got_bit, want_bit), f"m={m}"


def test_route_bits_vmap_collapses():
    rng = np.random.default_rng(3)
    t, n, p, n_bins, m = 3, 900, 5, 8, 4
    codes = jnp.asarray(rng.integers(0, n_bins, (n, p)), jnp.int32)
    codes_t = codes_transposed(codes)
    ids = jnp.asarray(rng.integers(0, m, (t, n)), jnp.int32)
    bf = jnp.asarray(rng.integers(0, p, (t, m)), jnp.int32)
    bb = jnp.asarray(rng.integers(0, n_bins, (t, m)), jnp.int32)
    got = jax.vmap(
        lambda i, f, b: route_bits(codes_t, i, f, b, backend="pallas_interpret")
    )(ids, bf, bb)
    want = jnp.stack([
        route_rows(
            jax.nn.one_hot(ids[i], m, dtype=jnp.float32), bf[i], bb[i],
            codes.astype(jnp.float32), ids[i],
        )
        - 2 * ids[i]
        for i in range(t)
    ])
    assert jnp.array_equal(got, want)


def test_streaming_grower_unchanged_by_route_kernel():
    """The classifier streaming path (which now routes and records
    leaves through the new kernels) must produce the same forest as the
    XLA backend — same splits, same leaf values, same train_leaf."""
    from ate_replication_causalml_tpu.models.forest import fit_forest_classifier

    rng = np.random.default_rng(4)
    n, p = 4000, 6
    x = jnp.asarray(rng.normal(size=(n, p)), jnp.float32)
    y = (rng.random(n) < (0.3 + 0.4 * (x[:, 0] > 0))).astype(np.float32)
    y = jnp.asarray(y)
    key = jax.random.key(7)
    f_pal = fit_forest_classifier(
        x, y, key, n_trees=4, depth=5, hist_backend="pallas_interpret"
    )
    f_xla = fit_forest_classifier(
        x, y, key, n_trees=4, depth=5, hist_backend="xla"
    )
    assert jnp.array_equal(f_pal.split_feat, f_xla.split_feat)
    assert jnp.array_equal(f_pal.split_bin, f_xla.split_bin)
    np.testing.assert_allclose(
        np.asarray(f_pal.leaf_value), np.asarray(f_xla.leaf_value),
        rtol=0, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(f_pal.train_leaf), np.asarray(f_xla.train_leaf),
        rtol=0, atol=1e-6,
    )


@pytest.mark.slow
# slow only for runtime (an interpret-mode grow + two predicts); green
# since PR 10 — see the shared-executable note below.
def test_variance_compat_grf_df_ratio():
    """variance_compat="grf" divides the between-group variance by
    num_groups instead of gn−1. With ci_group_size=1 the within-group
    correction vanishes identically (every group is one tree: ψ_t −
    ψ̄_group ≡ 0, an exact f32 subtraction of a value from itself), so
    the final variances differ by exactly (gn−1)/gn wherever they are
    positive.

    FIXED in PR 10 (this was a known-red cell since PR 1). Root cause
    of the historical 11/2500-row drift: ``gn`` — the number of groups
    counted into the variance — is PER ROW (a group only counts where
    every one of its trees produced a valid prediction), and a row that
    routes to an EMPTY honest leaf in one tree has gn < n_trees with an
    exactly different df ratio (gn−1)/gn. The old assertion hardcoded
    gn = 6 for every row; which rows hit an empty leaf shifts with any
    ulp-level change to the grown forest (jaxlib drift, suite x64/opt
    flags perturbing the f64 quantile edges), so the test was red on
    this image with 11 rows at exactly (5−1)/5. The assertion now
    states the REAL contract: every row's ratio is exactly (g−1)/g for
    its own integer g ≤ 6, with the full-forest value 5/6 on the vast
    majority. (PR 10 also made the two compat modes share ONE
    executable — the df selector is a traced 0/1 operand, not a jit
    static — so the truncated between-variance numerator is
    bit-identical across the two calls by construction, never just by
    compiler accident.)"""
    from ate_replication_causalml_tpu.models.causal_forest import (
        grow_causal_forest,
        predict_cate,
    )

    rng = np.random.default_rng(21)
    n, p = 2500, 5
    x = jnp.asarray(rng.normal(size=(n, p)), jnp.float32)
    w = jnp.asarray(rng.normal(size=n), jnp.float32)
    y = jnp.asarray(1.2 * w * (x[:, 1] > 0) + rng.normal(size=n), jnp.float32)
    forest = grow_causal_forest(
        x, w, y, jax.random.key(5), n_trees=6, depth=4, ci_group_size=1,
        hist_backend="pallas_interpret",
    )
    unb = predict_cate(forest, x, oob=False)
    grf = predict_cate(forest, x, oob=False, variance_compat="grf")
    np.testing.assert_allclose(
        np.asarray(grf.cate), np.asarray(unb.cate), rtol=0, atol=0
    )
    vu = np.asarray(unb.variance)
    vg = np.asarray(grf.variance)
    pos = vu > 0
    assert pos.any()
    ratio = vg[pos] / vu[pos]
    # Exact per-row df semantics: ratio == (g−1)/g for that row's own
    # valid-group count g ∈ {2..6} (g=1 makes both dfs 1 → ratio 1).
    allowed = np.asarray([(g - 1) / g for g in range(2, 7)] + [1.0])
    dist = np.abs(ratio[:, None] - allowed[None, :]).min(axis=1)
    np.testing.assert_allclose(dist, 0.0, atol=2e-6)
    # The full-forest ratio 5/6 must be the bulk — empty-leaf routing
    # is a tail event at this shape.
    frac_full = np.mean(np.abs(ratio - 5 / 6) < 1e-5)
    assert frac_full > 0.95, frac_full
    # Zero-variance rows agree exactly (same truncation, same
    # executable).
    np.testing.assert_array_equal(vg[~pos], vu[~pos])

"""Failure-detection / elastic-recovery tests (SURVEY.md §5.3): fault
injection proves retried shards reproduce the lost work exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.parallel.retry import (
    inject_failures,
    probe_devices,
    require_all,
    run_shards,
)


def _shard(i: int) -> float:
    key = jax.random.fold_in(jax.random.key(0), i)
    return float(jax.random.normal(key, ()).sum())


def test_probe_devices_all_healthy():
    healthy = probe_devices()
    assert len(healthy) == jax.device_count() == 8


def test_run_shards_clean():
    outs = run_shards(_shard, 6)
    assert all(o.ok and o.attempts == 1 for o in outs)
    vals = require_all(outs)
    assert vals == [_shard(i) for i in range(6)]


def test_retry_recovers_identical_results():
    flaky = inject_failures(_shard, {1: 1, 4: 2})
    outs = run_shards(flaky, 6, max_attempts=3, backoff_s=0.0)
    assert [o.attempts for o in outs] == [1, 2, 1, 1, 3, 1]
    assert all(o.ok for o in outs)
    # Determinism: retried shards produced exactly the clean values.
    assert require_all(outs) == [_shard(i) for i in range(6)]


def test_exhausted_retries_reported_not_raised():
    flaky = inject_failures(_shard, {2: 99})
    outs = run_shards(flaky, 4, max_attempts=2, backoff_s=0.0)
    assert [o.ok for o in outs] == [True, True, False, True]
    assert "injected fault" in outs[2].error
    with pytest.raises(RuntimeError, match="1/4 shards failed"):
        require_all(outs)
    # Partial coverage is usable: surviving shards carry results.
    ok_vals = [o.result for o in outs if o.ok]
    assert len(ok_vals) == 3


def test_bootstrap_se_survives_shard_loss():
    """Statistical end-to-end: an SE estimated from the surviving
    bootstrap shards is close to the full-coverage SE."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=20_000)

    def boot_shard(i):
        k = jax.random.fold_in(jax.random.key(42), i)
        idx = jax.random.randint(k, (50, x.shape[0]), 0, x.shape[0])
        return np.asarray(jnp.take(jnp.asarray(x), idx, axis=0).mean(axis=1))

    full = np.concatenate(require_all(run_shards(boot_shard, 8)))
    flaky = inject_failures(boot_shard, {3: 99})
    outs = run_shards(flaky, 8, max_attempts=1, backoff_s=0.0)
    partial = np.concatenate([o.result for o in outs if o.ok])
    assert len(partial) == 350
    assert abs(partial.std(ddof=1) - full.std(ddof=1)) < 0.2 * full.std(ddof=1)
"""Test harness: 8 virtual CPU devices so mesh/shard_map code paths are
exercised without TPU hardware (SURVEY.md §4 — the "fake backend" the
reference lacks). Must run before JAX initializes its backend."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

from ate_replication_causalml_tpu.utils.hostdevices import (
    xla_flags_with_device_count,
)

# REPLACE any inherited device-count flag (appending only-if-absent
# keeps a smaller inherited count and silently under-provisions every
# mesh test — see utils/hostdevices.py). On old jax this flag is the
# only provisioning path; XLA reads it at backend init, after imports.
_flags, _ = xla_flags_with_device_count(os.environ.get("XLA_FLAGS", ""), 8)
if "xla_backend_optimization_level" not in _flags:
    # The suite is ~90% XLA:CPU compile (round 5: the module-standard
    # causal fit measured 63 s cold / 6.4 s warm). Opt level 1 HALVES
    # compile with identical warm wall-clock (32.0/6.4 vs 62.9/6.4;
    # level 0 tripled execution — rejected). Tests only — the
    # TPU production path never sees this flag. Golden/bit-identity
    # tests run under it and pass: the fusion decisions it skips do
    # not change f32 accumulation order in the contraction paths the
    # goldens pin.
    _flags = (_flags + " --xla_backend_optimization_level=1").strip()
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax: no such option — the XLA_FLAGS device-count override
    # above is what actually provisions the 8 virtual devices there.
    pass

# Persistent XLA compilation cache — OPT-IN via ATE_TEST_CACHE=1.
# Round 3 hit reproducible late-suite segfaults on this image's jaxlib.
# Root cause (established by elimination): XLA:CPU's
# backend_compile_and_load itself crashes after ~160 executables are
# compiled in one long-lived process — pytest.ini therefore splits the
# suite across xdist workers, which is the actual fix. The cache stays
# opt-in because it compounds the failure mode: a write crashed mid-
# entry leaves a truncated file that segfaults the next run's READ, and
# entries from a different jaxlib/container SIGILL on load (XLA:CPU AOT
# results embed compile-machine features like "+prefer-no-gather") —
# hence the host-flags+jax-version cache-dir key when it is enabled.
if os.environ.get("ATE_TEST_CACHE") == "1":
    from ate_replication_causalml_tpu.utils.compile_cache import _host_tag  # noqa: E402

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), f".jax_cache-{_host_tag()}"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
else:
    # Kill switch honored by enable_persistent_cache(): rbridge/pipeline
    # call it at import, which re-enabled the cache mid-suite and kept
    # the segfaulting serializer in the loop.
    os.environ.setdefault("ATE_NO_COMPILE_CACHE", "1")

# Strict-precision mode for R-parity tests; the TPU production path runs
# float32/bfloat16 by construction (frames are built with explicit dtypes).
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from ate_replication_causalml_tpu.data.pipeline import PrepConfig, inject_bias, prepare_dataset
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like


@pytest.fixture(scope="session")
def raw_small():
    return make_ggl_like(n=20_000, seed=7, true_ate=0.095)


@pytest.fixture(scope="session")
def prep_small(raw_small):
    cfg = PrepConfig(n_obs=8_000, seed=1991)
    frame = prepare_dataset(raw_small, cfg, dtype=np.float64)
    frame_mod, dropped = inject_bias(frame, cfg)
    return frame, frame_mod, dropped

"""Test harness: 8 virtual CPU devices so mesh/shard_map code paths are
exercised without TPU hardware (SURVEY.md §4 — the "fake backend" the
reference lacks). Must run before JAX initializes its backend."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

# Persistent XLA compilation cache: the forest/estimator graphs take
# 10-20 s each to compile on CPU and dominate suite wall-clock; steady-
# state execution is <1 s. Cached executables survive across processes.
# The directory is keyed by a host-CPU fingerprint: XLA:CPU AOT results
# embed the COMPILE machine's feature set, and loading one compiled in
# a different container (different CPU flags) SIGILLs/segfaults mid-
# suite (observed: "+prefer-no-gather is not supported on the host
# machine ... could lead to execution errors such as SIGILL").
from ate_replication_causalml_tpu.utils.compile_cache import _host_tag  # noqa: E402

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(__file__), f".jax_cache-{_host_tag()}"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# Strict-precision mode for R-parity tests; the TPU production path runs
# float32/bfloat16 by construction (frames are built with explicit dtypes).
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from ate_replication_causalml_tpu.data.pipeline import PrepConfig, inject_bias, prepare_dataset
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like


@pytest.fixture(scope="session")
def raw_small():
    return make_ggl_like(n=20_000, seed=7, true_ate=0.095)


@pytest.fixture(scope="session")
def prep_small(raw_small):
    cfg = PrepConfig(n_obs=8_000, seed=1991)
    frame = prepare_dataset(raw_small, cfg, dtype=np.float64)
    frame_mod, dropped = inject_bias(frame, cfg)
    return frame, frame_mod, dropped

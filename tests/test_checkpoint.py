"""Checkpoint round-trip tests for fitted models (SURVEY.md §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.models.forest import fit_forest_classifier
from ate_replication_causalml_tpu.ops.glm import logistic_glm
from ate_replication_causalml_tpu.ops.linalg import add_intercept
from ate_replication_causalml_tpu.utils.checkpoint import load_fitted, save_fitted

RNG = np.random.default_rng(5)


def _tree_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for xa, xb in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_forest_roundtrip(tmp_path):
    x = jnp.asarray(RNG.normal(size=(300, 4)), jnp.float32)
    y = (x[:, 0] > 0).astype(jnp.float32)
    forest = fit_forest_classifier(x, y, jax.random.key(0), n_trees=8, depth=4)
    path = str(tmp_path / "forest.npz")
    save_fitted(path, forest)
    restored = load_fitted(path)
    assert type(restored).__name__ == "Forest"
    _tree_equal(forest, restored)


def test_glm_namedtuple_roundtrip(tmp_path):
    x = add_intercept(jnp.asarray(RNG.normal(size=(200, 3)), jnp.float32))
    w = (RNG.random(200) < 0.4).astype(np.float32)
    fit = logistic_glm(x, jnp.asarray(w))
    path = str(tmp_path / "glm.npz")
    save_fitted(path, fit)
    restored = load_fitted(path)
    assert type(restored).__name__ == type(fit).__name__
    _tree_equal(tuple(fit), tuple(restored))


def test_nested_container_roundtrip(tmp_path):
    obj = {
        "taus": jnp.arange(5.0),
        "meta": {"method": "aipw", "n_boot": 1000, "ok": True, "missing": None},
        "folds": [jnp.ones(3), jnp.zeros(2)],
        "pair": (1.5, "x"),
    }
    path = str(tmp_path / "obj.npz")
    save_fitted(path, obj)
    r = load_fitted(path, device=False)
    assert r["meta"] == obj["meta"]
    assert isinstance(r["pair"], tuple) and r["pair"] == (1.5, "x")
    np.testing.assert_array_equal(r["taus"], np.arange(5.0))
    assert isinstance(r["folds"][0], np.ndarray)


def test_unpicklable_rejected(tmp_path):
    with pytest.raises(TypeError):
        save_fitted(str(tmp_path / "bad.npz"), {"fn": lambda: None})


def test_dotted_dict_keys_do_not_collide(tmp_path):
    """Dict keys containing '.' must not alias each other's arrays."""
    obj = {"a": {"b": np.ones(3)}, "a.b": np.zeros(3)}
    path = str(tmp_path / "dots.npz")
    save_fitted(path, obj)
    r = load_fitted(path, device=False)
    np.testing.assert_array_equal(r["a"]["b"], np.ones(3))
    np.testing.assert_array_equal(r["a.b"], np.zeros(3))


def test_float64_roundtrip_exact(tmp_path):
    """64-bit arrays round-trip exactly even when x64 is disabled in
    the loading process (they stay host NumPy rather than truncating)."""
    v = np.array([1.0 + 1e-12, 2.0], dtype=np.float64)
    path = str(tmp_path / "f64.npz")
    save_fitted(path, {"v": v, "i": np.int64(2**40) + np.arange(2)})
    r = load_fitted(path)  # device=True
    assert np.asarray(r["v"]).dtype == np.float64
    np.testing.assert_array_equal(np.asarray(r["v"]), v)
    assert np.asarray(r["i"]).dtype == np.int64


def test_stage_timer_accumulates():
    from ate_replication_causalml_tpu.utils.profiling import StageTimer

    t = StageTimer()
    with t.stage("a"):
        pass
    with t.stage("a"):
        pass
    with t.stage("b"):
        pass
    assert set(t.seconds) == {"a", "b"}
    assert t.seconds["a"] >= 0 and "TOTAL" in t.report()

"""CATE serving subsystem tests (ISSUE 6).

Three layers, matched to the tier-1 budget:

* the no-jax serving core — protocol framing (incl. torn frames),
  coalescer deadline/bucket math, admission reject ordering, the
  lifecycle + checkpoint-reload state machine — pure-host, ~ms each;
* ONE module-scoped in-process daemon over a synthetic micro forest
  (no fit — serving doesn't care how the forest was trained), proving
  the acceptance criteria: a ≥100-request window across ≥2 buckets with
  ZERO jax compile events and served values bit-identical to offline
  ``predict_cate``, then degraded-mode chaos serving (planned faults
  exactly, recovery reloads, bit-identical to the fault-free stream);
* the subprocess stdio round-trip (@slow — process startup + its own
  AOT compiles are redundant with the in-process window).

The offline reference is computed BEFORE the daemon starts: the
no-compile window term is process-global by design (a real daemon
process runs nothing else), so the reference trace must not pollute it.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.serving import loadgen, protocol
from ate_replication_causalml_tpu.serving.admission import (
    AdmissionController,
    InvalidTransition,
    ReloadSupervisor,
    ServingLifecycle,
)
from ate_replication_causalml_tpu.serving.coalescer import (
    PHASES,
    BucketPlan,
    Coalescer,
    PendingRequest,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "scripts"))
import check_metrics_schema as cms  # noqa: E402


# ── protocol framing ────────────────────────────────────────────────────


def test_frame_roundtrip_with_arrays():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    i = np.array([1, 2, 3], dtype=np.int64)
    buf = protocol.encode_frame({"op": "predict", "id": "r1"},
                                {"x": x, "idx": i})
    header, arrays = protocol.read_frame(io.BytesIO(buf))
    assert header == {"op": "predict", "id": "r1"}
    assert np.array_equal(arrays["x"], x) and arrays["x"].dtype == x.dtype
    assert np.array_equal(arrays["idx"], i)


def test_frame_roundtrip_header_only_and_clean_eof():
    buf = protocol.encode_frame({"ok": True})
    stream = io.BytesIO(buf + protocol.encode_frame({"second": 2}))
    assert protocol.read_frame(stream) == ({"ok": True}, {})
    assert protocol.read_frame(stream) == ({"second": 2}, {})
    assert protocol.read_frame(stream) is None  # EOF at a boundary
    assert protocol.read_frame(io.BytesIO(b"")) is None


@pytest.mark.parametrize("cut", [1, 3, 4, 7, -5, -1])
def test_torn_frames_raise(cut):
    """EOF anywhere inside a frame — in the length prefix, the header,
    or the array payload — is a typed ProtocolError, never a hang or a
    partial decode."""
    buf = protocol.encode_frame(
        {"op": "predict"}, {"x": np.ones((4, 3), np.float32)}
    )
    torn = buf[:cut] if cut > 0 else buf[:len(buf) + cut]
    with pytest.raises(protocol.ProtocolError, match="torn|truncated"):
        protocol.read_frame(io.BytesIO(torn))


def test_frame_rejects_garbage_and_oversize():
    with pytest.raises(protocol.ProtocolError, match="header length"):
        protocol.decode_frame(b"\x00\x00\x00\x0a{}")  # hlen > body
    with pytest.raises(protocol.ProtocolError, match="JSON"):
        protocol.decode_frame(b"\x00\x00\x00\x02xy")
    with pytest.raises(protocol.ProtocolError, match="trailing"):
        protocol.decode_frame(protocol.encode_frame({"a": 1})[4:] + b"zz")
    # A hostile length prefix must be refused before allocation.
    huge = (protocol.MAX_FRAME_BYTES + 1).to_bytes(4, "big")
    with pytest.raises(protocol.ProtocolError, match="MAX_FRAME_BYTES"):
        protocol.read_frame(io.BytesIO(huge))
    # Declared array bigger than the frame.
    bad = protocol.encode_frame({"arrays": {"x": {
        "dtype": "float32", "shape": [1000, 1000]}}})
    with pytest.raises(protocol.ProtocolError, match="truncated"):
        protocol.read_frame(io.BytesIO(bad))
    # Non-numeric dtypes have no raw-buffer wire form; np.frombuffer on
    # dtype "O" raises a PLAIN ValueError, which must be wrapped typed
    # (a bare ValueError escapes serve_stream and kills the connection
    # replyless).
    for dt in ("O", "U4", "M8[ns]"):
        evil = protocol.encode_frame({"arrays": {"x": {
            "dtype": dt, "shape": [1]}}})
        with pytest.raises(protocol.ProtocolError, match="non-numeric"):
            protocol.read_frame(io.BytesIO(evil))


# ── bucket plan + coalescer ─────────────────────────────────────────────


def test_bucket_plan_parse_and_lookup():
    plan = BucketPlan.parse("64,1,8,8")
    assert plan.sizes == (1, 8, 64)
    assert plan.bucket_for(1) == 1
    assert plan.bucket_for(2) == 8
    assert plan.bucket_for(8) == 8
    assert plan.bucket_for(9) == 64
    assert plan.bucket_for(64) == 64
    assert plan.bucket_for(65) is None
    with pytest.raises(ValueError):
        plan.bucket_for(0)
    for bad in ("", "0,4", "-1,4", "a,b"):
        with pytest.raises(ValueError):
            BucketPlan.parse(bad)


class _FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _req(rid, rows, clock):
    return PendingRequest(rid, None, rows, clock())


def test_coalescer_flushes_when_full():
    """A burst that exactly fills the largest bucket dispatches at once
    — no window wait — and rides that bucket at fill 1.0."""
    clock = _FakeClock()
    co = Coalescer(BucketPlan.parse("4,16"), window_s=10.0, clock=clock)
    for i in range(4):
        co.submit(_req(f"r{i}", 4, clock))
    batch = co.next_batch(timeout=0)
    assert batch is not None
    assert [r.request_id for r in batch.requests] == ["r0", "r1", "r2", "r3"]
    assert batch.rows == 16 and batch.bucket == 16 and batch.fill == 1.0
    assert co.next_batch(timeout=0) is None  # drained


def test_coalescer_flushes_when_next_would_overflow():
    """Head-of-line blocking is refused: when the next waiter cannot
    fit, the packed prefix flushes immediately and the big request
    leads the next batch."""
    clock = _FakeClock()
    co = Coalescer(BucketPlan.parse("4,16"), window_s=10.0, clock=clock)
    co.submit(_req("small", 6, clock))
    co.submit(_req("big", 14, clock))
    first = co.next_batch(timeout=0)
    assert [r.request_id for r in first.requests] == ["small"]
    assert first.bucket == 16 and first.rows == 6
    # The big request is now alone — not full, so it waits out its OWN
    # window rather than flushing on the heels of the first batch.
    assert co.next_batch(timeout=0) is None
    clock.t += 10.0
    second = co.next_batch(timeout=0)
    assert [r.request_id for r in second.requests] == ["big"]


def test_coalescer_window_deadline_flushes_partial():
    """A lone request waits only until the OLDEST waiter's window
    expires, then flushes padded to the smallest fitting bucket."""
    clock = _FakeClock()
    co = Coalescer(BucketPlan.parse("4,16"), window_s=0.5, clock=clock)
    co.submit(_req("r0", 3, clock))
    assert co.next_batch(timeout=0) is None  # window not expired
    clock.t += 0.49
    assert co.next_batch(timeout=0) is None
    clock.t += 0.02  # oldest is now past its window
    batch = co.next_batch(timeout=0)
    assert batch is not None
    assert batch.rows == 3 and batch.bucket == 4 and batch.fill == 0.75


def test_coalescer_window_is_oldest_waiter_not_newest():
    clock = _FakeClock()
    co = Coalescer(BucketPlan.parse("16",), window_s=1.0, clock=clock)
    co.submit(_req("r0", 2, clock))
    clock.t += 0.9
    co.submit(_req("r1", 2, clock))  # newer arrival must not reset r0
    clock.t += 0.2
    batch = co.next_batch(timeout=0)
    assert batch is not None and batch.rows == 4


def test_coalescer_oversize_and_close_semantics():
    clock = _FakeClock()
    co = Coalescer(BucketPlan.parse("4"), window_s=10.0, clock=clock)
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        co.submit(_req("big", 5, clock))
    co.submit(_req("r0", 1, clock))
    co.close()
    with pytest.raises(RuntimeError, match="closed"):
        co.submit(_req("r1", 1, clock))
    # Close drains immediately (no window wait), then None forever.
    batch = co.next_batch(timeout=0)
    assert batch is not None and batch.rows == 1
    assert co.next_batch(timeout=0) is None


def test_coalescer_close_reasons_and_lifecycle_marks():
    """ISSUE 7: every closed batch reports WHY it closed (precedence:
    full > next-wouldn't-fit > window > drain), carries the close clock
    and a sequence number, and stamps its requests' lifecycle marks."""
    clock = _FakeClock()
    co = Coalescer(BucketPlan.parse("4,16"), window_s=1.0, clock=clock)
    for i in range(4):
        co.submit(_req(f"r{i}", 4, clock))
    b1 = co.next_batch(timeout=0)
    assert b1.close_reason == "bucket_full" and b1.seq == 1
    assert b1.closed_mono == clock.t
    co.submit(_req("small", 6, clock))
    co.submit(_req("big", 14, clock))
    b2 = co.next_batch(timeout=0)
    assert b2.close_reason == "next_wont_fit" and b2.seq == 2
    clock.t += 1.0
    b3 = co.next_batch(timeout=0)
    assert b3.close_reason == "window_expired" and b3.seq == 3
    co.submit(_req("last", 1, clock))
    co.close()
    b4 = co.next_batch(timeout=0)
    assert b4.close_reason == "drain" and b4.seq == 4
    req = b4.requests[0]
    assert req.batch_closed_mono == clock.t and req.batch_seq == 4
    assert req.batch_bucket == 4 and req.batch_fill == 0.25


def test_pending_request_phase_seconds_telescopes():
    """The phase decomposition is consecutive mark differences, so the
    sum IS the end-to-end latency (the ±1 µs acceptance bound is pure
    float rounding); unresolved/partial requests decompose to None."""
    r = PendingRequest("x", None, 2, 100.0)
    assert r.phase_seconds() is None
    r.batch_closed_mono = 100.002
    r.picked_mono = 100.003
    r.device_start_mono = 100.0035
    r.device_end_mono = 100.010
    r.resolve(("c", "v"), 100.0105)
    ph = r.phase_seconds()
    assert list(ph) == list(PHASES)
    assert all(v >= 0 for v in ph.values())
    assert abs(
        sum(ph.values()) - (r.resolved_mono - r.enqueued_mono)
    ) < 1e-12


# ── loadgen: the deterministic open-loop schedule (no jax, no daemon) ──


def test_loadgen_schedule_seed_determinism():
    """Same seed ⇒ IDENTICAL schedule (ids, arrival times, row mix) and
    identical query payloads — the property that makes chaos replays
    coordinated and round-to-round records comparable."""
    kw = dict(rate_hz=100.0, mix="1:4,8:2,32:1")
    s1 = loadgen.build_schedule(7, 50, **kw)
    s2 = loadgen.build_schedule(7, 50, **kw)
    assert s1 == s2
    assert loadgen.build_schedule(8, 50, **kw) != s1
    assert [s.request_id for s in s1] == [f"r{i}" for i in range(50)]
    assert all(b.t_s >= a.t_s for a, b in zip(s1, s1[1:]))
    assert {s.rows for s in s1} <= {1, 8, 32}
    q1 = loadgen.build_queries(7, s1, 5)
    q2 = loadgen.build_queries(7, s1, 5)
    assert all(np.array_equal(a, b) for a, b in zip(q1, q2))
    assert all(
        q.shape == (s.rows, 5) and q.dtype == np.float32
        for q, s in zip(q1, s1)
    )
    # A different seed changes the payload bytes too.
    q3 = loadgen.build_queries(8, s1, 5)
    assert not all(np.array_equal(a, b) for a, b in zip(q1, q3))


def test_loadgen_mix_parsing():
    assert loadgen.parse_mix("1,8") == ((1, 1.0), (8, 1.0))
    assert loadgen.parse_mix("1:4, 8:2") == ((1, 4.0), (8, 2.0))
    for bad in ("", "0:1", "4:-1", "a:b"):
        with pytest.raises(ValueError):
            loadgen.parse_mix(bad)
    with pytest.raises(ValueError):
        loadgen.build_schedule(0, 0)
    with pytest.raises(ValueError):
        loadgen.build_schedule(0, 5, rate_hz=0.0)


def test_loadgen_apply_shift_prefix_identity():
    """The seeded mid-stream shift knob (ISSUE 16): everything BEFORE
    --shift-at is byte-identical to the unshifted build of the same
    seed (a shifted/unshifted pair isolates the drift detector's flip,
    nothing else), the tail is deterministically transformed, and bad
    specs are typed errors."""
    kw = dict(rate_hz=100.0, mix="1:2,8:1")
    sched = loadgen.build_schedule(5, 40, **kw)
    queries = loadgen.build_queries(5, sched, 4)

    s2, q2 = loadgen.apply_shift(sched, queries, shift_at=25,
                                 shift_kind="covariate", shift_delta=2.5)
    assert s2 == sched  # covariate shift never touches the schedule
    for i in range(25):
        assert np.array_equal(q2[i], queries[i])
    for i in range(25, 40):
        assert np.array_equal(q2[i][:, 0], queries[i][:, 0] + np.float32(2.5))
        assert np.array_equal(q2[i][:, 1:], queries[i][:, 1:])
    # The inputs themselves were not mutated (pure transform).
    q_again = loadgen.build_queries(5, sched, 4)
    assert all(np.array_equal(a, b) for a, b in zip(queries, q_again))

    s3, q3 = loadgen.apply_shift(sched, queries, shift_at=25,
                                 shift_kind="checkpoint", shift_model="b")
    assert all(np.array_equal(a, b) for a, b in zip(q3, queries))
    assert s3[:25] == sched[:25]
    assert all(s.model == "b" for s in s3[25:])
    assert all(
        s.request_id == o.request_id and s.t_s == o.t_s and s.rows == o.rows
        for s, o in zip(s3[25:], sched[25:])
    )

    with pytest.raises(ValueError):
        loadgen.apply_shift(sched, queries, shift_at=-1)
    with pytest.raises(ValueError):
        loadgen.apply_shift(sched, queries, shift_at=len(sched) + 1)
    with pytest.raises(ValueError):
        loadgen.apply_shift(sched, queries, shift_at=5, shift_kind="nope")
    with pytest.raises(ValueError):
        loadgen.apply_shift(sched, queries, shift_at=5,
                            shift_kind="checkpoint")  # needs shift_model


# ── admission + lifecycle + reload state machine ───────────────────────


def test_admission_reject_ordering():
    adm = AdmissionController(max_depth=2)
    assert adm.try_admit() and adm.try_admit()
    assert not adm.try_admit()  # full: typed reject, never queue
    adm.release()
    assert adm.try_admit()      # freed slot admits the NEXT arrival
    assert not adm.try_admit()
    adm.release()
    adm.release()
    assert adm.depth == 0
    with pytest.raises(RuntimeError, match="without a matching admit"):
        adm.release()
    with pytest.raises(ValueError):
        AdmissionController(0)


def test_lifecycle_legal_path_and_invalid_transitions():
    lc = ServingLifecycle()
    assert lc.state == "starting" and not lc.can_serve()
    with pytest.raises(InvalidTransition):
        lc.mark_recovered()          # not degraded yet
    assert not lc.mark_fault("early")  # faults before ready don't own recovery
    lc.mark_ready()
    assert lc.can_serve()
    with pytest.raises(InvalidTransition):
        lc.mark_ready()              # double-ready
    assert lc.mark_fault("boom")     # first reporter owns recovery
    assert lc.state == "degraded"
    assert not lc.mark_fault("boom2")  # concurrent reporters coalesce
    lc.mark_recovered()
    assert lc.can_serve() and lc.reload_count == 1 and lc.fault_count == 3
    lc.mark_stopped()
    lc.mark_stopped()                # idempotent
    assert lc.state == "stopped"


def test_reload_supervisor_state_machine():
    """The checkpoint-reload state machine without jax: a failed reload
    STAYS degraded (a corrupt checkpoint never rotates into service);
    an explicit retry that verifies goes back to serving; the installed
    model is exactly the reloaded object."""
    lc = ServingLifecycle()
    lc.mark_ready()
    attempts = []
    installed = []

    def flaky_reload():
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("digest mismatch")
        return {"model": len(attempts)}

    sup = ReloadSupervisor(lc, flaky_reload, installed.append, inline=True)
    assert sup.report_fault("chaos")       # owns recovery; reload FAILS
    assert lc.state == "degraded" and installed == []
    assert not sup.report_fault("again")   # degraded: coalesced, no own
    assert lc.state == "degraded"
    assert sup.retry()                     # second attempt verifies
    assert lc.state == "serving"
    assert installed == [{"model": 2}]
    assert not sup.retry()                 # nothing to do while serving


def test_reload_supervisor_background_thread():
    lc = ServingLifecycle()
    lc.mark_ready()
    gate = threading.Event()
    installed = []

    def slow_reload():
        gate.wait(5)
        return "m2"

    sup = ReloadSupervisor(lc, slow_reload, installed.append)
    assert sup.report_fault("x")
    assert lc.state == "degraded"  # recovery in flight, requests reject
    gate.set()
    sup.join(5)
    assert lc.state == "serving" and installed == ["m2"]


# ── admin endpoint handlers (no daemon — duck-typed stub) ──────────────


class _StubSLO:
    def health(self):
        return {"burning": False, "slos": {}}


class _StubServer:
    """The duck-typed surface handle_admin_path touches."""

    def __init__(self):
        self.lifecycle = ServingLifecycle()
        self.slo = _StubSLO()
        #: liveness surface (ISSUE 14): per-lane heartbeat ages + the
        #: watchdog's stall verdict, both reflected in /healthz.
        self.ages = {"dispatch": 0.01}
        self.stalled = ()

    def heartbeat_ages(self):
        return dict(self.ages)

    def stalled_lanes(self):
        return tuple(self.stalled)

    def compile_events_in_window(self):
        return 0.0


def _admin_http_get(stub, path):
    """Drive the REAL stdlib request handler over a socketpair — no
    bound port, no daemon — and return (status, body_bytes)."""
    import socket as socketlib

    from ate_replication_causalml_tpu.serving.admin import (
        AdminRequestHandler,
    )

    class _Srv:
        cate_server = stub

    a, b = socketlib.socketpair()
    try:
        a.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        a.shutdown(socketlib.SHUT_WR)
        AdminRequestHandler(b, ("socketpair", 0), _Srv())
        b.close()
        data = b""
        while True:
            chunk = a.recv(65536)
            if not chunk:
                break
            data += chunk
    finally:
        a.close()
    status = int(data.split(b" ", 2)[1])
    body = data.split(b"\r\n\r\n", 1)[1]
    return status, body


def test_admin_handlers_flip_with_lifecycle():
    """healthz/readyz are lifecycle-aware: readyz is 200 ONLY while
    serving (degraded ⇒ 503 — the chaos-visible probe), healthz stays
    200 while alive (a degraded daemon is recovering, not dead) and
    goes 503 only when stopped."""
    import json as jsonlib

    stub = _StubServer()
    status, body = _admin_http_get(stub, "/readyz")
    assert status == 503 and jsonlib.loads(body)["state"] == "starting"
    assert _admin_http_get(stub, "/healthz")[0] == 200

    stub.lifecycle.mark_ready()
    status, body = _admin_http_get(stub, "/readyz")
    assert status == 200 and jsonlib.loads(body)["ready"] is True

    stub.lifecycle.mark_fault("chaos")
    status, body = _admin_http_get(stub, "/readyz")
    assert status == 503 and jsonlib.loads(body)["state"] == "degraded"
    status, body = _admin_http_get(stub, "/healthz")
    payload = jsonlib.loads(body)
    assert status == 200 and payload["state"] == "degraded"
    assert "slo" in payload
    # Liveness detail (ISSUE 14): the body carries per-lane heartbeat
    # ages, and a STALLED dispatcher flips healthz to 503 even though
    # the process (and its lifecycle) look alive — the pre-watchdog
    # 200-while-wedged was the black-hole failure mode. readyz keeps
    # its lifecycle-only semantics throughout.
    assert payload["heartbeats"] == {"dispatch": 0.01}
    assert payload["stalled_lanes"] == []
    stub.stalled = ("dispatch",)
    status, body = _admin_http_get(stub, "/healthz")
    assert status == 503
    assert jsonlib.loads(body)["stalled_lanes"] == ["dispatch"]
    assert _admin_http_get(stub, "/readyz")[0] == 503  # still lifecycle
    stub.stalled = ()
    assert _admin_http_get(stub, "/healthz")[0] == 200

    stub.lifecycle.mark_recovered()
    assert _admin_http_get(stub, "/readyz")[0] == 200

    stub.lifecycle.mark_stopped()
    assert _admin_http_get(stub, "/readyz")[0] == 503
    assert _admin_http_get(stub, "/healthz")[0] == 503

    # Unknown routes 404 and name the routes; /varz is valid JSON.
    status, body = _admin_http_get(stub, "/nope")
    assert status == 404 and b"/metrics" in body
    status, body = _admin_http_get(stub, "/varz")
    assert status == 200 and isinstance(jsonlib.loads(body), dict)


# ── the in-process daemon (micro synthetic forest, shared fixture) ─────


N_REQUESTS = 120
_SIZES = (1, 3, 4, 9, 16)  # cycles across both buckets of "4,16"


def _synthetic_forest(rng):
    """A structurally valid CausalForest from random arrays — serving
    doesn't care how the forest was trained, and skipping the fit keeps
    the fixture seconds, not minutes."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.causal_forest import CausalForest

    T, D, n, p, nb = 8, 3, 50, 4, 8
    return CausalForest(
        split_feat=jnp.asarray(
            rng.integers(0, p, size=(T, D, 1 << D)).astype(np.int32)
        ),
        split_bin=jnp.asarray(
            rng.integers(0, nb - 1, size=(T, D, 1 << D)).astype(np.int32)
        ),
        leaf_stats=jnp.asarray(
            (np.abs(rng.normal(size=(T, 1 << D, 5))) + 0.5).astype(np.float32)
        ),
        in_sample=jnp.asarray(rng.uniform(size=(T, n)) < 0.5),
        bin_edges=jnp.asarray(
            np.sort(rng.normal(size=(p, nb - 1)), axis=1).astype(np.float32)
        ),
        ci_group_size=2,
    )


@pytest.fixture(scope="module")
def serving_rig(tmp_path_factory):
    """Checkpoint + offline reference + ONE running daemon. The offline
    predict_cate reference is traced BEFORE startup so the daemon's
    no-compile window stays clean (the window term is process-global)."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.causal_forest import predict_cate
    from ate_replication_causalml_tpu.serving.daemon import (
        CateServer,
        ServeConfig,
    )
    from ate_replication_causalml_tpu.utils.checkpoint import save_fitted

    rng = np.random.default_rng(0)
    forest = _synthetic_forest(rng)
    ckpt = str(tmp_path_factory.mktemp("serve") / "forest.npz")
    save_fitted(ckpt, forest)

    xs = [
        rng.normal(size=(_SIZES[i % len(_SIZES)], 4)).astype(np.float32)
        for i in range(N_REQUESTS)
    ]
    off = predict_cate(
        forest, jnp.asarray(np.concatenate(xs)), oob=False,
        row_backend="matmul",
    )
    offline = (np.asarray(off.cate), np.asarray(off.variance))

    server = CateServer(ServeConfig(
        checkpoint=ckpt,
        buckets=BucketPlan.parse("4,16"),
        window_s=0.002,
        max_depth=16,
        retry_after_s=0.005,
        # The whole ISSUE 7 plane is ACTIVE for every test in this
        # module — admin endpoint (ephemeral port), SLO engine, phase
        # tracing — and the teardown stop() still asserts the window
        # compiled nothing (acceptance criterion).
        admin_port=0,
    ))
    phases = server.startup()
    yield dict(server=server, forest=forest, ckpt=ckpt, xs=xs,
               offline=offline, phases=phases)
    # stop() ENFORCES the zero-compile window over everything every
    # test in this module did — including the chaos reloads.
    server.stop()


def _submit_retry(server, rid, x, on_fault=None):
    from ate_replication_causalml_tpu.serving.daemon import RejectedRequest

    for _ in range(300):
        try:
            return server.submit(rid, x)
        except RejectedRequest as rej:
            if rej.code == "serve_fault" and on_fault is not None:
                on_fault(rid)
            elif rej.code not in ("overloaded", "degraded", "serve_fault"):
                raise
            time.sleep(rej.retry_after_s or 0.002)
    raise AssertionError(f"no progress on {rid}")


def test_serving_window_zero_compile_and_bit_identity(serving_rig):
    """THE acceptance criterion: ≥100 requests across ≥2 buckets, zero
    jax compile/trace events in the registry during the window, served
    values bit-identical to offline predict_cate on the same rows."""
    server = serving_rig["server"]
    xs = serving_rig["xs"]
    offc, offv = serving_rig["offline"]
    mark = server.compile_events_in_window()

    # First few requests SEQUENTIALLY (each coalesces alone, waits out
    # the window, rides the small bucket + the serve_one span path) ...
    n_seq = 5
    results = []
    for i in range(n_seq):
        results.append(server.serve_one(f"r{i}", xs[i]))
    # ... then the rest as a pipelined burst (admission-retried like a
    # real client), which packs the large bucket.
    reqs = [
        _submit_retry(server, f"r{i}", xs[i])
        for i in range(n_seq, N_REQUESTS)
    ]
    for r in reqs:
        assert r.wait(30), f"request {r.request_id} never served"
        assert r.error is None, r.error
        results.append(r.result)

    off = 0
    for i, (cate, var) in enumerate(results):
        rows = xs[i].shape[0]
        assert np.array_equal(cate, offc[off:off + rows])
        assert np.array_equal(var, offv[off:off + rows])
        off += rows

    # Zero-compile proof, from the registry (not timings).
    assert server.compile_events_in_window() == mark == 0.0
    # ≥2 buckets actually used.
    from ate_replication_causalml_tpu import observability as obs

    batches = obs.REGISTRY.peek("serving_batches_total")
    used = {k for k, v in batches.items() if v > 0 and k}
    assert {"bucket=4", "bucket=16"} <= used
    # The startup phases were recorded and exported as gauges.
    assert set(serving_rig["phases"]) == {"load", "aot", "warm"}
    assert all(v >= 0 for v in serving_rig["phases"].values())


def test_degraded_mode_chaos_serving(serving_rig):
    """Acceptance criterion 2: under a seeded serve: spec the daemon
    faults EXACTLY the planned requests (selection is the pure hash of
    the client ids), recovers by re-verifying + reloading the
    checkpoint, never crashes, and the retried stream's answers are
    bit-identical to the fault-free offline reference. ISSUE 7 makes
    the degradation VISIBLE: ``/readyz`` flips to 503 while degraded
    and the availability SLO shows a burn-rate spike."""
    from ate_replication_causalml_tpu.serving.admin import handle_admin_path

    server = serving_rig["server"]
    xs = serving_rig["xs"]
    offc, offv = serving_rig["offline"]
    ids = [f"r{i}" for i in range(N_REQUESTS)]

    faulted: list[str] = []
    readyz_codes: list[int] = []
    results: dict[str, tuple] = {}

    def on_fault(rid):
        faulted.append(rid)
        # Probe readiness at the instant of the typed reject: the
        # lifecycle moved to DEGRADED before the reject raised, so a
        # load balancer polling /readyz sees the chaos window.
        readyz_codes.append(handle_admin_path(server, "/readyz")[0])

    with chaos.override("serve:p=0.25,seed=11"):
        for i, rid in enumerate(ids):
            req = _submit_retry(server, rid, xs[i], on_fault=on_fault)
            assert req.wait(30) and req.error is None
            results[rid] = req.result

    expected = [
        rid for rid in ids if chaos._unit(11, "serve", rid) < 0.25
    ]
    assert faulted == expected and len(expected) > 0
    # Chaos-degraded serving is admin-visible: at least one probe (in
    # practice nearly all — the background reload takes ≥ a checkpoint
    # load) caught readyz=503, and the availability SLO burned budget.
    assert 503 in readyz_codes
    avail = server.slo.health()["slos"]["availability"]
    assert avail["worst_burn_rate"] > 0.0
    # The daemon recovered (reload count advanced, state is serving).
    assert server.lifecycle.state == "serving"
    assert server.lifecycle.reload_count >= 1
    # Chaos stream == fault-free reference, bit for bit.
    off = 0
    for i, rid in enumerate(ids):
        cate, var = results[rid]
        rows = xs[i].shape[0]
        assert np.array_equal(cate, offc[off:off + rows])
        assert np.array_equal(var, offv[off:off + rows])
        off += rows
    # A faulted id consumed its budget: replaying it chaos-free-attempt
    # 2+ serves (already proven by the retry loop converging).


def test_serving_rejects_are_typed(serving_rig):
    from ate_replication_causalml_tpu.serving.daemon import RejectedRequest

    server = serving_rig["server"]
    with pytest.raises(RejectedRequest, match="bad_request"):
        server.serve_one("bad1", np.ones((3,), np.float32))  # 1-D
    with pytest.raises(RejectedRequest, match="features"):
        server.serve_one("bad2", np.ones((2, 9), np.float32))
    with pytest.raises(RejectedRequest, match="rows"):
        server.serve_one("bad3", np.ones((17, 4), np.float32))  # > max bucket
    # Unconvertible query payloads (strings etc.) are a typed reject at
    # the submit layer, not a connection-killing ValueError.
    with pytest.raises(RejectedRequest, match="float32"):
        server.serve_one("bad4", np.array([["a", "b", "c", "d"]]))


def test_stream_roundtrip_over_socketpair(serving_rig):
    """The wire layer against the live daemon — a real socket, the real
    client, no subprocess: predict + ping + stats round-trip, and a
    torn frame kills only the connection."""
    import socket as socketlib

    from ate_replication_causalml_tpu.serving.client import CateClient
    from ate_replication_causalml_tpu.serving.daemon import serve_stream

    server = serving_rig["server"]
    xs = serving_rig["xs"]
    offc, offv = serving_rig["offline"]

    a, b = socketlib.socketpair()
    rw = b.makefile("rwb")
    t = threading.Thread(target=serve_stream, args=(server, rw, rw),
                         daemon=True)
    t.start()
    with CateClient(a.makefile("rb"), a.makefile("wb"), sock=a) as client:
        assert client.ping()["state"] == "serving"
        cate, var = client.predict(xs[0], request_id="wire0")
        assert np.array_equal(cate, offc[:xs[0].shape[0]])
        assert np.array_equal(var, offv[:xs[0].shape[0]])
        stats = client.stats()
        assert stats["compile_events_in_window"] == 0
        assert stats["state"] == "serving"
    t.join(5)
    assert not t.is_alive()

    # Torn frame: connection dies typed, the daemon keeps serving.
    a2, b2 = socketlib.socketpair()
    rw2 = b2.makefile("rwb")
    t2 = threading.Thread(target=serve_stream, args=(server, rw2, rw2),
                          daemon=True)
    t2.start()
    frame = protocol.encode_frame({"op": "ping"})
    a2.sendall(frame[:len(frame) - 2])
    a2.close()
    t2.join(5)
    assert not t2.is_alive()
    assert server.lifecycle.state == "serving"


# ── the observability plane on the live daemon (ISSUE 7) ───────────────


def test_request_phase_decomposition_sums_to_latency(serving_rig):
    """THE acceptance criterion: every served request's lifecycle marks
    telescope — coalesce_wait + queue_wait + dispatch + device + reply
    equals the end-to-end latency within ±1 µs — and each phase is
    non-negative with sane batch linkage."""
    server = serving_rig["server"]
    xs = serving_rig["xs"]
    reqs = [server.submit(f"ph{i}", xs[i]) for i in range(6)]
    for r in reqs:
        assert r.wait(30) and r.error is None
    for r in reqs:
        ph = r.phase_seconds()
        assert ph is not None and list(ph) == list(PHASES)
        assert all(v >= -1e-9 for v in ph.values()), ph
        e2e = r.resolved_mono - r.enqueued_mono
        assert abs(sum(ph.values()) - e2e) <= 1e-6, (ph, e2e)
        assert r.batch_seq >= 1 and r.batch_bucket in (4, 16)
        assert 0.0 < r.batch_fill <= 1.0
    # The registry's per-phase families saw every phase of every batch.
    stats = server.phase_stats()
    assert set(stats) == set(PHASES)
    assert len({s["count"] for s in stats.values()}) == 1
    reasons = server.close_reason_counts()
    assert sum(reasons.values()) > 0
    assert set(reasons) <= {"bucket_full", "next_wont_fit",
                            "window_expired", "drain"}
    assert 0.0 <= server.pad_fraction_mean() < 1.0


@pytest.mark.slow
def test_live_admin_endpoint_over_http(serving_rig):
    """The rig's real admin endpoint (ephemeral port, running inside
    the no-compile window): /metrics is scrape-able Prometheus text,
    /readyz is 200 while serving, /varz carries the serving counters,
    and the stats op reports the bound port.

    @slow since ISSUE 16 (tier-1 budget): every payload asserted here
    is produced by handle_admin_path, which
    test_stat_health_plane_on_live_rig now exercises tier-1 in-process
    (same dict, no socket); what this adds is only the HTTP framing of
    an already-covered core, and its budget pays for the statistical-
    health plane assertions instead."""
    import urllib.request

    server = serving_rig["server"]
    # Self-sufficient under `-m slow` (no tier-1 neighbour has pushed
    # traffic yet): populate the phase histograms before scraping.
    for i in range(3):
        server.serve_one(f"adm{i}", serving_rig["xs"][i])
    port = server.stats()["admin_port"]
    assert isinstance(port, int) and port > 0

    def get(path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read()

    status, body = get("/metrics")
    assert status == 200
    assert b"ate_tpu_serving_requests_total" in body
    assert b"ate_tpu_serving_phase_seconds_bucket" in body
    status, body = get("/readyz")
    assert status == 200 and json.loads(body)["ready"] is True
    status, body = get("/healthz")
    payload = json.loads(body)
    assert status == 200 and payload["state"] == "serving"
    assert payload["compile_events_in_window"] == 0
    assert "availability" in payload["slo"]["slos"]
    status, body = get("/varz")
    varz = json.loads(body)
    assert "serving_requests_total" in varz
    assert "serving_batch_close_total" in varz


def test_stat_health_plane_on_live_rig(serving_rig):
    """The statistical-health plane on the live rig (ISSUE 16): the
    traffic this module already pushed through the dispatcher fed the
    streaming sketches HOST-SIDE (the module teardown still proves the
    zero-compile window — sketch updates never trace), the ``stats``
    wire op and ``/healthz`` carry the monitor's compact state, the
    ``serving_stat_*`` families counted every row, and the per-model
    drift/calibration SLOs are declared beside availability. Drives
    handle_admin_path in-process — the HTTP framing of these same
    payloads is @slow (see test_live_admin_endpoint_over_http)."""
    from ate_replication_causalml_tpu import observability as obs
    from ate_replication_causalml_tpu.serving.admin import handle_admin_path

    server = serving_rig["server"]
    sh = server.stats()["stat_health"]
    assert sh["window_s"] > 0
    default = sh["models"]["default"]
    assert default["rows"] > 0  # rig traffic reached the sketches
    # Every channel sketched every served row of the default model.
    for ch in ("cate", "covariate", "propensity"):
        assert default["channels"][ch]["count"] == default["rows"]
    # Calibration is opt-in and the rig did not opt in.
    assert default["calibration"]["enabled"] is False

    # The registry's serving_stat_* families agree with the monitor.
    rows = obs.REGISTRY.peek("serving_stat_rows_total")
    assert rows.get("model=default", 0) == default["rows"]

    # /healthz embeds the same compact form, and the statistical SLOs
    # are declared per model next to the availability ladder.
    code, ctype, body = handle_admin_path(server, "/healthz")
    assert code == 200 and ctype == "application/json"
    payload = json.loads(body)
    assert payload["stat_health"]["models"]["default"]["rows"] == \
        default["rows"]
    assert {"stat_drift:default", "stat_calibration:default"} <= \
        set(payload["slo"]["slos"])
    # An unshifted, well-calibrated rig must NOT be burning the drift
    # SLO (the shifted counterpart flips it — see the @slow replay).
    assert payload["slo"]["slos"]["stat_drift:default"]["burning"] is False


@pytest.mark.slow
def test_loadgen_inprocess_replay_against_rig(serving_rig):
    """A seeded open-loop replay against the live daemon: every
    scheduled request serves, the record carries offered vs achieved
    rate and client latencies, and retryable rejects (if any) were
    absorbed under the same ids.

    @slow since ISSUE 12 (tier-1 budget): the fleet rig's multi-tenant
    replay ACROSS A LIVE ROTATION (tests/test_fleet.py) runs the same
    loadgen core against a daemon in tier-1 with strictly more at
    stake, making this single-tenant replay redundant coverage; the
    budget pays for the fused-bucket + rotation-prewarm rig instead."""
    server = serving_rig["server"]
    schedule = loadgen.build_schedule(
        3, 24, rate_hz=3000.0, mix="1:2,4:1,16:1", id_prefix="lg",
    )
    queries = loadgen.build_queries(3, schedule, 4)
    record = loadgen.run_inprocess(server, schedule, queries, timeout_s=30.0)
    assert record["requests"] == record["served"] == 24
    assert record["rows_offered"] == sum(s.rows for s in schedule)
    assert record["p50_s"] <= record["p99_s"] <= record["max_s"]
    assert record["duration_s"] > 0 and record["achieved_rate_hz"] > 0


def test_serving_artifact_export_round_trip(serving_rig, tmp_path):
    """THE acceptance criterion: the served session exports trace.json
    + serving_report.json + slo_report.json that pass
    check_metrics_schema.py, the trace carries the serving tracks and
    request→batch→reply flow arrows, phase sums equal e2e latency, and
    analyze_trace.py reproduces serving_report.json BIT-FOR-BIT."""
    server = serving_rig["server"]
    outdir = str(tmp_path / "dump")
    paths = server.dump_artifacts(outdir)
    names = {os.path.basename(p) for p in paths}
    assert {"metrics.json", "events.jsonl", "metrics.prom", "trace.json",
            "serving_report.json", "slo_report.json",
            "stat_health.json"} <= names

    # Full schema contract: metrics/events pair + every trace artifact.
    assert cms.validate_pair(
        os.path.join(outdir, "metrics.json"),
        os.path.join(outdir, "events.jsonl"),
    ) == []
    assert cms.validate_trace_files(outdir) == []

    with open(os.path.join(outdir, "trace.json")) as f:
        trace = json.load(f)
    meta_names = {
        ev["args"]["name"] for ev in trace["traceEvents"]
        if ev.get("ph") == "M" and ev.get("name") == "thread_name"
    }
    assert "serving-dispatch" in meta_names  # the device track
    cats = {ev.get("cat") for ev in trace["traceEvents"]}
    assert {"request", "batch"} <= cats
    # request→batch→reply flow chains exist and are complete.
    req_flows = [ev for ev in trace["traceEvents"]
                 if ev.get("cat") == "req"]
    assert {ev["ph"] for ev in req_flows} == {"s", "t", "f"}

    with open(os.path.join(outdir, "serving_report.json")) as f:
        rep = json.load(f)
    req = rep["requests"]
    assert req["with_phases"] >= 5 and rep["batches"]["count"] > 0
    assert sum(rep["batches"]["close_reasons"].values()) == \
        rep["batches"]["count"]
    # Aggregate phase-sum == aggregate e2e (±1 µs per request).
    phase_sum = sum(
        req["phases"][k]["sum_s"] for k in req["phases"]
    )
    assert abs(phase_sum - req["e2e"]["sum_s"]) <= 1e-6 * max(
        1, req["with_phases"]
    )
    # The chaos test ran earlier in this module: its rejects are on the
    # timeline with ids.
    assert rep["rejects"]["count"] > 0
    assert rep["rejects"]["by_reason"].get("serve_fault", 0) > 0

    with open(os.path.join(outdir, "slo_report.json")) as f:
        slo = json.load(f)
    ladders = [
        [w["window_s"] for w in s["windows"]] for s in slo["slos"]
    ]
    assert all(lad == sorted(lad) and len(set(lad)) == len(lad)
               for lad in ladders)

    # stat_health.json (ISSUE 16): the exported report embeds the raw
    # monitor state and is a pure function of it — recomputing from the
    # embedded state reproduces the artifact bit-for-bit, in-process.
    from ate_replication_causalml_tpu.observability import stathealth

    sh_path = os.path.join(outdir, stathealth.STAT_HEALTH_BASENAME)
    sh_before = open(sh_path, "rb").read()
    dumped = json.loads(sh_before)
    assert dumped["state"]["models"]["default"]["rows"] > 0
    stathealth.write_stat_health(outdir, dumped["state"])
    assert open(sh_path, "rb").read() == sh_before

    # Analyzer CLI reproduces serving_report.json AND stat_health.json
    # bit-for-bit.
    import analyze_trace

    before = open(os.path.join(outdir, "serving_report.json"), "rb").read()
    assert analyze_trace.main([os.path.join(outdir, "trace.json")]) == 0
    after = open(os.path.join(outdir, "serving_report.json"), "rb").read()
    assert after == before
    assert open(sh_path, "rb").read() == sh_before
    # ... and the analyzer's overlap report on a pure serving trace is
    # still schema-valid (degenerate, not broken).
    assert cms.validate_trace_files(outdir) == []


@pytest.mark.slow
def test_dump_op_over_wire(serving_rig, tmp_path):
    """The `dump` op: a live client triggers the full artifact export
    without stopping the daemon. (@slow since ISSUE 11: the export
    recipe, schema gate and analyzer reproduction are already covered
    tier-1 by test_serving_artifact_export_round_trip and the fleet
    rig's artifact test — this adds only the wire framing of `dump`,
    and its budget paid for the multi-tenant rotation replay.)"""
    import socket as socketlib

    from ate_replication_causalml_tpu.serving.client import CateClient
    from ate_replication_causalml_tpu.serving.daemon import serve_stream

    server = serving_rig["server"]
    a, b = socketlib.socketpair()
    rw = b.makefile("rwb")
    t = threading.Thread(target=serve_stream, args=(server, rw, rw),
                         daemon=True)
    t.start()
    outdir = str(tmp_path / "wiredump")
    with CateClient(a.makefile("rb"), a.makefile("wb"), sock=a) as client:
        paths = client.dump(outdir)
        assert paths and all(os.path.exists(p) for p in paths)
        assert client.ping()["state"] == "serving"  # still serving
    t.join(5)
    assert cms.validate_trace_files(outdir) == []


def test_startup_refuses_corrupt_checkpoint(tmp_path):
    """A torn/tampered checkpoint fails startup typed — the daemon must
    refuse to serve, not serve wrong numbers."""
    from ate_replication_causalml_tpu.resilience.errors import (
        CheckpointCorrupt,
    )
    from ate_replication_causalml_tpu.serving.daemon import (
        CateServer,
        ServeConfig,
    )
    from ate_replication_causalml_tpu.utils.checkpoint import save_fitted

    rng = np.random.default_rng(3)
    ckpt = str(tmp_path / "forest.npz")
    save_fitted(ckpt, _synthetic_forest(rng))
    with open(ckpt, "r+b") as f:
        f.truncate(os.path.getsize(ckpt) * 2 // 3)
    server = CateServer(ServeConfig(checkpoint=ckpt))
    with pytest.raises(CheckpointCorrupt):
        server.startup()
    server.stop()  # stop before startup completed: clean, no window


# ── subprocess round-trip (@slow: redundant AOT + process startup) ─────


@pytest.mark.slow
def test_subprocess_stdio_daemon_roundtrip(serving_rig):
    """scripts/serve.py --stdio end to end: spawn, predict a few mixed
    batches, read stats (zero-compile window), shutdown, exit 0.

    Reuses the rig's checkpoint and PRE-STARTUP offline reference: the
    parent process must do no jax tracing here, or the still-running
    rig server's strict stop() would (correctly) flag the parent-side
    compiles at module teardown."""
    from ate_replication_causalml_tpu.serving.client import CateClient

    xs = serving_rig["xs"]
    offc, _ = serving_rig["offline"]

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_backend_optimization_level=1")
    client = CateClient.spawn_stdio(
        [sys.executable, os.path.join(_REPO, "scripts", "serve.py"),
         "--checkpoint", serving_rig["ckpt"], "--stdio",
         "--buckets", "4,16", "--window-ms", "1"],
        env=env, cwd=_REPO,
    )
    try:
        assert client.ping()["state"] == "serving"
        offp = 0
        for i in range(4):
            cate, _ = client.predict(xs[i], request_id=f"sub{i}")
            assert np.array_equal(cate, offc[offp:offp + xs[i].shape[0]])
            offp += xs[i].shape[0]
        stats = client.stats()
        assert stats["compile_events_in_window"] == 0
        assert stats["state"] == "serving"
        assert set(stats["startup_seconds"]) == {"load", "aot", "warm"}
        client.shutdown()
    finally:
        client.close()
    assert client._proc.returncode == 0


def _loadgen_replay(ckpt, seed, requests, rate, mix, *, stat_window_s,
                    dump_dir=None, shift_at=None, shift_delta=6.0):
    """One scripts/loadgen.py --spawn replay in a subprocess (its own
    daemon, its own zero-compile window, its own env) returning the
    parsed one-line JSON record."""
    import subprocess

    cmd = [sys.executable, os.path.join(_REPO, "scripts", "loadgen.py"),
           "--spawn", "--checkpoint", ckpt, "--features", "4",
           "--requests", str(requests), "--seed", str(seed),
           "--rate", str(rate), "--mix", mix, "--buckets", "4,16"]
    if dump_dir is not None:
        cmd += ["--dump-dir", dump_dir]
    if shift_at is not None:
        cmd += ["--shift-at", str(shift_at),
                "--shift-kind", "covariate",
                "--shift-delta", str(shift_delta)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_backend_optimization_level=1",
               ATE_TPU_STAT_WINDOW=str(stat_window_s))
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=_REPO,
                          env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_shifted_replay_flips_drift_slo_unshifted_stays_green(serving_rig):
    """ISSUE 16's acceptance pair, end to end over real daemons: a
    seeded replay with a mid-stream covariate shift flips the per-model
    ``stat_drift`` SLO to burning within a bounded number of windows,
    while the SAME seed replayed unshifted stays green — the flip is
    attributable to the shift and nothing else (the two streams share
    a byte-identical prefix, pinned tier-1 by
    test_loadgen_apply_shift_prefix_identity). @slow: two daemon
    spawns; the detector flip itself is covered tier-1 in-process with
    an injected clock (tests/test_stathealth.py)."""
    ckpt = serving_rig["ckpt"]
    # 0.2 s windows over a ~1 s, ~9600 rows/s stream: every sealed
    # window holds >> MIN_WINDOW_COUNT rows, so none are sparse, and
    # the shift boundary lands in the middle of the window ladder.
    kw = dict(requests=800, rate=800.0, mix="8:1,16:1", stat_window_s=0.2)

    green = _loadgen_replay(ckpt, 11, **kw)
    assert green["served"] == 800
    sh = green["server"]["stat_health"]["models"]["default"]
    assert sh["rows"] > 0
    assert sh["drift_events"] == 0
    slo = green["server"]["slo"]["slos"]
    assert slo["stat_drift:default"]["burning"] is False

    burning = _loadgen_replay(ckpt, 11, shift_at=400, **kw)
    assert burning["served"] == 800
    assert burning["shift"] == {"at": 400, "kind": "covariate",
                                "delta": 6.0}
    sh = burning["server"]["stat_health"]["models"]["default"]
    assert sh["drift_events"] > 0  # the detector fired on the boundary
    slo = burning["server"]["slo"]["slos"]
    assert slo["stat_drift:default"]["burning"] is True
    assert slo["stat_drift:default"]["worst_burn_rate"] > 1.0


@pytest.mark.slow
def test_stat_health_artifact_byte_identical_per_seed(serving_rig, tmp_path):
    """Same seed, two fresh daemon processes ⇒ byte-identical
    stat_health.json (ISSUE 16 determinism criterion), and the analyzer
    CLI (a third process, jax-free) reproduces the artifact bit-for-bit
    from its own embedded state. The replay pins ATE_TPU_STAT_WINDOW
    huge so window sealing cannot depend on wall-clock timing — the
    sketch state is then a pure function of the seeded stream."""
    import subprocess

    ckpt = serving_rig["ckpt"]
    kw = dict(requests=60, rate=500.0, mix="4:1,16:1", stat_window_s=1e9)
    dirs = [str(tmp_path / d) for d in ("a", "b")]
    for d in dirs:
        rec = _loadgen_replay(ckpt, 23, dump_dir=d, **kw)
        assert rec["served"] == 60
        assert os.path.exists(os.path.join(d, "stat_health.json"))

    blobs = [open(os.path.join(d, "stat_health.json"), "rb").read()
             for d in dirs]
    assert blobs[0] == blobs[1]
    state = json.loads(blobs[0])["state"]
    assert state["models"]["default"]["rows"] > 0

    # Analyzer reproduction, subprocess (the jax-free recompute path).
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "analyze_trace.py"),
         os.path.join(dirs[0], "trace.json")],
        capture_output=True, text=True, cwd=_REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    after = open(os.path.join(dirs[0], "stat_health.json"), "rb").read()
    assert after == blobs[0]

"""Horizontal serving fleet tests (ISSUE 18).

Three layers, matched to the tier-1 budget:

* the jax-free router core — the deterministic consistent-hash ring
  (seed/process determinism, balance bounds at 3/5/8 backends, minimal
  movement on add/retire), backend-spec parsing, the ``ATE_TPU_ROUTER_*``
  env family, the per-backend circuit breaker's full state machine on
  an injectable clock, probe-driven eviction/readmission against stub
  daemons behind a REAL admin plane, mid-stream failover, the typed
  ``backend_unavailable`` reject, the client's connection_lost
  reconnect-and-resubmit discipline, the rolling ``rotate_all``
  against stub backends, the ``daemon:`` chaos grammar, and the fleet
  manifest validator's corruption cases — pure-host, ~ms each;
* ONE in-process TWO-backend micro fleet over real :class:`CateServer`
  daemons (both ``strict_no_compile=False`` — the no-compile window
  term is process-global, the documented PR 6/7 gotcha) proving the
  acceptance contract end to end: a seeded multi-model replay through
  the router is bit-identical per model version to the offline
  reference, ``rotate_all`` rolls the fleet with zero downtime and
  zero post-swap compiles per daemon, and the merged fleet dump passes
  ``validate_fleet_dump``;
* the 3-daemon SUBPROCESS campaign episode (real ``scripts/serve.py``
  processes, a real ``SIGKILL`` mid-replay, the full invariant
  registry) displaced to ``@slow`` — the tier-1 budget swap this
  module's in-process micro fleet pays for (ISSUE 18 satellite: one
  fleet rig in tier-1, the kill -9 episode in the slow tier).
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ate_replication_causalml_tpu import observability as obs
from ate_replication_causalml_tpu.observability import (
    fleet_report as freport,
)
from ate_replication_causalml_tpu.resilience import chaos
from ate_replication_causalml_tpu.serving import protocol
from ate_replication_causalml_tpu.serving import router as rt
from ate_replication_causalml_tpu.serving.admin import AdminServer
from ate_replication_causalml_tpu.serving.client import (
    CONNECTION_LOST,
    CateClient,
    ServingError,
    ServingUnavailable,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "scripts"))
import check_metrics_schema as cms  # noqa: E402

KEYS = [f"model-{i}" for i in range(3000)]


@pytest.fixture(autouse=True, scope="module")
def _clean_registry_after_module():
    """The registry is process-global and `test_serving`'s live rig
    (which runs after this module) asserts its counters EQUAL its own
    monitor's view — leave the world as empty as `test_resilience`
    leaves it, so this module's fleet traffic can't leak forward."""
    yield
    obs.REGISTRY.reset()
    obs.EVENTS.clear()


def _delta(name: str, before: dict) -> dict:
    """Per-label-key counter delta vs a peek() snapshot — the registry
    is process-global, so every assertion here is a delta."""
    now = obs.REGISTRY.peek(name) or {}
    out = {}
    for key, v in now.items():
        d = v - before.get(key, 0)
        if d:
            out[key] = d
    return out


# ── the consistent-hash ring (pure) ────────────────────────────────────


def test_ring_deterministic_across_instances_and_orders():
    """Same members => bit-identical assignment, whatever the
    construction order — sha256 positions, no process seed."""
    a = rt.ConsistentHashRing(("b0", "b1", "b2"))
    b = rt.ConsistentHashRing(("b2", "b0", "b1"))
    assert a.backends == b.backends == ("b0", "b1", "b2")
    assert a.assignment(KEYS[:500]) == b.assignment(KEYS[:500])
    # owners() is the distinct clockwise failover order, owner first.
    for key in KEYS[:50]:
        owners = a.owners(key)
        assert owners[0] == a.owner(key)
        assert sorted(owners) == ["b0", "b1", "b2"]
        assert a.owners(key, 2) == owners[:2]


def test_ring_balance_bounds_at_3_5_8_backends():
    """The tier-1 balance pin: at vnodes=64 every backend's share of
    3000 keys stays within [0.7, 1.35] x ideal (measured headroom over
    the observed [0.8, 1.23] envelope; sha256 makes this exact)."""
    for n in (3, 5, 8):
        ring = rt.ConsistentHashRing([f"b{i}" for i in range(n)])
        counts = collections.Counter(ring.owner(k) for k in KEYS)
        assert set(counts) == {f"b{i}" for i in range(n)}
        ideal = len(KEYS) / n
        for name, c in sorted(counts.items()):
            assert 0.7 * ideal <= c <= 1.35 * ideal, (n, name, c)


def test_ring_minimal_movement_on_add_and_retire():
    """Membership change moves ONLY the changed backend's keys: every
    key that changed owner after an add routes to the new backend, and
    every key that changed owner after a retire came from the retired
    one. True by construction (all other vnode positions persist)."""
    base = rt.ConsistentHashRing(("a", "b", "c", "d"))
    grown = base.with_backend("e")
    moved = [k for k in KEYS if base.owner(k) != grown.owner(k)]
    assert moved  # the new backend took real ownership
    assert all(grown.owner(k) == "e" for k in moved)
    # ~1/5 of keys move, never a reshuffle.
    assert len(moved) < len(KEYS) * 0.4

    shrunk = base.without_backend("b")
    moved2 = [k for k in KEYS if base.owner(k) != shrunk.owner(k)]
    assert moved2
    assert all(base.owner(k) == "b" for k in moved2)
    assert len(moved2) < len(KEYS) * 0.5
    # Eviction + readmission round-trips to the identical assignment
    # (the router keeps ONE immutable ring and walks past dead owners).
    back = shrunk.with_backend("b")
    assert back.assignment(KEYS[:500]) == base.assignment(KEYS[:500])


def test_ring_validation():
    with pytest.raises(ValueError, match="duplicate"):
        rt.ConsistentHashRing(("a", "a", "b"))
    with pytest.raises(ValueError, match="at least one"):
        rt.ConsistentHashRing(())
    with pytest.raises(ValueError, match="vnodes"):
        rt.ConsistentHashRing(("a",), vnodes=0)


# ── backend specs + env config ─────────────────────────────────────────


def test_parse_backend_specs_roundtrip_and_raises():
    specs = rt.parse_backend_specs(
        "b0=127.0.0.1:7771@8871, b1=10.0.0.2:7772@8872,"
    )
    assert specs == (
        rt.BackendSpec("b0", "127.0.0.1", 7771, 8871),
        rt.BackendSpec("b1", "10.0.0.2", 7772, 8872),
    )
    for bad in ("", "b0", "b0=host", "b0=host:1", "b0=host:x@2",
                "b0=host:1@y", "b0=host:0@2", "b0=host:1@70000",
                "b0=h:1@2,b0=h:3@4"):
        with pytest.raises(ValueError):
            rt.parse_backend_specs(bad)


def test_router_config_from_env_and_overrides(monkeypatch):
    spec = "b0=127.0.0.1:7771@8871"
    monkeypatch.setenv("ATE_TPU_ROUTER_VNODES", "16")
    monkeypatch.setenv("ATE_TPU_ROUTER_PROBE_S", "0.5")
    monkeypatch.setenv("ATE_TPU_ROUTER_FAILURES", "5")
    monkeypatch.setenv("ATE_TPU_ROUTER_COOLDOWN_S", "2.5")
    monkeypatch.setenv("ATE_TPU_ROUTER_FAILOVER", "0")  # 0 is legal
    monkeypatch.setenv("ATE_TPU_ROUTER_RETRY_AFTER_S", "0.2")
    cfg = rt.RouterConfig.from_env(spec)
    assert (cfg.vnodes, cfg.probe_interval_s, cfg.failure_threshold,
            cfg.cooldown_s, cfg.failover_hops, cfg.retry_after_s) == \
        (16, 0.5, 5, 2.5, 0, 0.2)
    # explicit overrides win over the env
    assert rt.RouterConfig.from_env(spec, vnodes=8).vnodes == 8
    # config-time raise on a bad knob (the repo-wide env discipline)
    monkeypatch.setenv("ATE_TPU_ROUTER_VNODES", "zero")
    with pytest.raises(ValueError, match="ATE_TPU_ROUTER_VNODES"):
        rt.RouterConfig.from_env(spec)
    monkeypatch.setenv("ATE_TPU_ROUTER_VNODES", "16")
    monkeypatch.setenv("ATE_TPU_ROUTER_FAILURES", "0")
    with pytest.raises(ValueError, match="ATE_TPU_ROUTER_FAILURES"):
        rt.RouterConfig.from_env(spec)


def test_router_outcomes_vocabulary_shared_with_validator():
    """The fleet-manifest validator's outcome vocabulary IS the
    router's — a drift here would let the validator pass dumps the
    router never writes (or reject ones it does)."""
    assert tuple(cms._ROUTER_OUTCOMES) == rt.OUTCOMES


# ── the circuit breaker (injectable clock) ─────────────────────────────


def test_circuit_breaker_state_machine():
    clock = [0.0]
    br = rt.CircuitBreaker(threshold=3, cooldown_s=1.0,
                           clock=lambda: clock[0])
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed" and br.allow()  # below threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clock[0] = 0.5
    assert not br.allow()                       # cooldown not elapsed
    clock[0] = 1.0
    assert br.allow()                           # the half-open trial
    assert br.state == "half_open"
    assert not br.allow()                       # exactly ONE trial out
    br.record_failure()                         # trial failed
    assert br.state == "open"                   # re-opened, re-armed
    clock[0] = 1.5
    assert not br.allow()
    clock[0] = 2.0
    assert br.allow()
    br.record_success()                         # trial succeeded
    assert br.state == "closed" and br.allow()
    # success reset the consecutive-failure count
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"
    with pytest.raises(ValueError):
        rt.CircuitBreaker(threshold=0)


# ── stub daemons behind a REAL admin plane + wire loop (no jax) ────────


class _StubLifecycle:
    def __init__(self):
        self.state = "serving"


class _StubSLO:
    @staticmethod
    def health():
        return {"burning": [], "worst_burn": 0.0}


class _StubDaemon:
    """Duck-types exactly what ``handle_admin_path`` and the router's
    wire ops touch: lifecycle.state, compile_events_in_window(),
    slo.health(), model_bindings() — no jax anywhere."""

    def __init__(self, name: str, fill: float):
        self.name = name
        self.fill = float(fill)
        self.version = 1
        self.lifecycle = _StubLifecycle()
        self.slo = _StubSLO()
        self.served: list[str] = []
        self.rotations: list[tuple[str, str]] = []
        self.die_midstream = False

    def compile_events_in_window(self) -> int:
        return 0

    def model_bindings(self) -> dict:
        return {
            m: {"version": self.version, "checkpoint": f"/{self.name}.npz"}
            for m in ("default", "m2", "m3")
        }


class _StubWire:
    """A daemon-wire stand-in speaking the real length-prefixed
    protocol, answering predict with a backend-identifying fill value
    (so a reply proves WHICH backend served it)."""

    def __init__(self, stub: _StubDaemon):
        self.stub = stub
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.1)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._accept, daemon=True, name=f"stubwire-{stub.name}"
        )
        self._thread.start()

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._stream, args=(conn,),
                             daemon=True).start()

    def _stream(self, conn: socket.socket) -> None:
        with conn:
            rw = conn.makefile("rwb")
            while not self._stop.is_set():
                try:
                    frame = protocol.read_frame(rw)
                except (protocol.ProtocolError, OSError):
                    return
                if frame is None:
                    return
                header, arrays = frame
                rid = str(header.get("id", ""))
                op = header.get("op")
                if op == "predict":
                    if self.stub.die_midstream:
                        return  # close replyless: the kill -9 signature
                    self.stub.served.append(rid)
                    n = int(arrays["x"].shape[0])
                    reply = {
                        "ok": True, "id": rid,
                        "model": str(header.get("model") or "default"),
                        "model_version": self.stub.version,
                    }
                    out = {
                        "cate": np.full(n, self.stub.fill, np.float32),
                        "variance": np.zeros(n, np.float32),
                    }
                elif op == "rotate":
                    self.stub.version += 1
                    self.stub.rotations.append((
                        str(header.get("model")),
                        str(header.get("checkpoint")),
                    ))
                    reply, out = {"ok": True, "id": rid,
                                  "status": "rotated"}, {}
                elif op == "stats":
                    reply, out = {"ok": True, "stats": {
                        "compile_events_in_window": 0,
                    }}, {}
                else:
                    reply, out = {"ok": False, "id": rid,
                                  "error": "bad_request",
                                  "message": f"stub: unknown op {op!r}"}, {}
                try:
                    protocol.write_frame(rw, reply, out)
                except (OSError, ValueError):
                    return

    def kill(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self._thread.join(2.0)


@pytest.fixture
def stub_fleet():
    """Factory for an N-stub fleet fronted by a RouterServer; tears
    everything down whatever the test did."""
    created: list[tuple] = []

    def make(n: int = 3, **cfg_overrides):
        stubs: dict[str, _StubDaemon] = {}
        wires: dict[str, _StubWire] = {}
        admins: list[AdminServer] = []
        specs = []
        for i in range(n):
            name = f"s{i}"
            stub = _StubDaemon(name, fill=float(i + 1))
            wire = _StubWire(stub)
            adm = AdminServer(stub)
            aport = adm.start(0)
            stubs[name] = stub
            wires[name] = wire
            admins.append(adm)
            specs.append(rt.BackendSpec(name, "127.0.0.1", wire.port, aport))
        cfg = dict(probe_interval_s=0.05, probe_timeout_s=2.0,
                   connect_timeout_s=2.0, io_timeout_s=5.0,
                   failure_threshold=2, cooldown_s=0.2)
        cfg.update(cfg_overrides)
        router = rt.RouterServer(rt.RouterConfig(
            backends=tuple(specs), **cfg
        ))
        created.append((router, wires, admins))
        return router, stubs, wires

    yield make
    for router, wires, admins in created:
        router.stop()
        for w in wires.values():
            w.kill()
        for a in admins:
            a.stop()


def _predict(router: rt.RouterServer, rid: str, model: str, n: int = 3):
    return router.forward_predict(
        {"op": "predict", "id": rid, "model": model},
        {"x": np.zeros((n, 4), np.float32)},
    )


def test_probe_backend_reads_the_real_admin_plane(stub_fleet):
    """probe_backend against a REAL AdminServer over a stub: readiness,
    the ISSUE 14 liveness distinction, and the model-binding table the
    router builds its routing view from (ISSUE 18 satellite)."""
    router, stubs, _ = stub_fleet(1)
    spec = router.config.backends[0]
    ready, alive, models = rt.probe_backend(spec)
    assert (ready, alive) == (True, True)
    assert models["default"]["version"] == 1
    assert set(models) == {"default", "m2", "m3"}
    # Not ready (degraded) is still alive — evicted but not dead.
    stubs["s0"].lifecycle.state = "degraded"
    assert rt.probe_backend(spec)[:2] == (False, True)
    # Stopped is neither.
    stubs["s0"].lifecycle.state = "stopped"
    assert rt.probe_backend(spec)[:2] == (False, False)
    # An unreachable admin port is simply out of rotation, not an error.
    gone = rt.BackendSpec("x", "127.0.0.1", spec.port, _free_port())
    assert rt.probe_backend(gone, timeout_s=0.5) == (False, False, {})


def _free_port() -> int:
    with socket.create_server(("127.0.0.1", 0)) as s:
        return s.getsockname()[1]


def test_router_routes_on_the_ring_and_builds_table_from_probes(
        stub_fleet):
    router, stubs, _ = stub_fleet(3)
    router.start(probe=False)  # one synchronous probe round, no thread
    assert router.in_rotation() == ("s0", "s1", "s2")
    for name in stubs:
        assert router.bound_version(name, "default") == 1
    for model in ("default", "m2", "m3"):
        owner = router.ring.owner(model)
        reply, out = _predict(router, f"rt-{model}", model)
        assert reply["ok"] and reply["model"] == model
        # The fill value proves the ring owner served it.
        assert float(out["cate"][0]) == stubs[owner].fill
        assert f"rt-{model}" in stubs[owner].served


def test_probe_driven_eviction_and_readmission(stub_fleet):
    router, stubs, _ = stub_fleet(3)
    router.start(probe=False)
    model = "default"
    owner = router.ring.owner(model)
    second = router.ring.owners(model, 2)[1]
    before = obs.REGISTRY.peek("router_backend_state") or {}

    stubs[owner].lifecycle.state = "degraded"
    router.prober.probe_once()
    assert owner not in router.in_rotation()
    reply, out = _predict(router, "ev0", model)
    assert reply["ok"]
    assert float(out["cate"][0]) == stubs[second].fill  # next ring owner

    stubs[owner].lifecycle.state = "serving"
    router.prober.probe_once()
    assert owner in router.in_rotation()
    reply, out = _predict(router, "ev1", model)
    assert float(out["cate"][0]) == stubs[owner].fill  # keys came back
    d = _delta("router_backend_state", before)
    assert d.get(f"backend={owner},state=evicted") == 1
    assert d.get(f"backend={owner},state=admitted") == 1


def test_midstream_death_fails_over_then_breaker_opens(stub_fleet):
    """A backend dying mid-frame costs one metered failover per
    forward until its breaker opens; after that the dead backend is
    not even attempted (no failover hop — the next owner is simply
    first)."""
    router, stubs, _ = stub_fleet(3, failure_threshold=2, cooldown_s=30.0)
    router.start(probe=False)
    model = "default"
    owner = router.ring.owner(model)
    second = router.ring.owners(model, 2)[1]
    assert _predict(router, "fo-warm", model)[0]["ok"]  # pool warmed

    stubs[owner].die_midstream = True
    req_before = obs.REGISTRY.peek("router_requests_total") or {}
    fo_before = obs.REGISTRY.peek("router_failover_total") or {}
    for i in range(2):  # two failures trip the threshold-2 breaker
        reply, out = _predict(router, f"fo{i}", model)
        assert reply["ok"]
        assert float(out["cate"][0]) == stubs[second].fill
    assert sum(_delta("router_failover_total", fo_before).values()) == 2
    d = _delta("router_requests_total", req_before)
    assert d.get(f"backend={owner},outcome=connection_error") == 2
    assert d.get(f"backend={second},outcome=ok") == 2
    assert router.stats()["backends"][owner]["breaker"] == "open"

    # Breaker open: the dead owner is skipped outright — same answer,
    # zero additional failover hops.
    fo_mark = obs.REGISTRY.peek("router_failover_total") or {}
    reply, out = _predict(router, "fo-open", model)
    assert reply["ok"] and float(out["cate"][0]) == stubs[second].fill
    assert _delta("router_failover_total", fo_mark) == {}


def test_exhausted_candidates_is_a_typed_retryable_reject(stub_fleet):
    router, stubs, _ = stub_fleet(2)
    router.start(probe=False)
    for name in stubs:
        router.set_cordon(name, True)
    assert router.in_rotation() == ()
    before = obs.REGISTRY.peek("router_requests_total") or {}
    reply, out = _predict(router, "un0", "default")
    assert reply["ok"] is False
    assert reply["error"] == rt.BACKEND_UNAVAILABLE
    assert reply["id"] == "un0"
    assert reply["retry_after_s"] == router.config.retry_after_s
    assert out == {}
    assert _delta("router_requests_total", before) == {
        "backend=-,outcome=unavailable": 1,
    }
    assert rt.BACKEND_UNAVAILABLE in __import__(
        "ate_replication_causalml_tpu.serving.client", fromlist=["RETRYABLE"]
    ).RETRYABLE


def test_wire_serving_and_client_absorbs_backend_unavailable(stub_fleet):
    """End to end over TCP, jax-free: serve_socket + handle_router_op +
    a real CateClient. The typed ``backend_unavailable`` reject is
    absorbed by the client's retry discipline the moment capacity
    returns."""
    router, stubs, _ = stub_fleet(2)
    router.start(probe=False)
    bound: list[int] = []
    bound_evt = threading.Event()

    def on_bound(port: int) -> None:
        bound.append(port)
        bound_evt.set()

    t = threading.Thread(
        target=rt.serve_socket, args=(router,),
        kwargs=dict(port=0, on_bound=on_bound), daemon=True,
    )
    t.start()
    assert bound_evt.wait(10)
    client = CateClient.connect("127.0.0.1", bound[0], timeout=10.0)
    try:
        x = np.zeros((3, 4), np.float32)
        cate, var, header = client.predict_full(x, request_id="wr0",
                                                model="m2")
        owner = router.ring.owner("m2")
        assert header["model"] == "m2" and header["model_version"] == 1
        assert float(cate[0]) == stubs[owner].fill

        # All capacity cordoned: the reject is typed and retryable —
        # an exhausted budget surfaces it as ServingUnavailable.
        for name in stubs:
            router.set_cordon(name, True)
        with pytest.raises(ServingUnavailable) as ei:
            client.predict_full(x, request_id="wr1", max_retries=1)
        assert ei.value.code == rt.BACKEND_UNAVAILABLE
        assert client.retry_counts[rt.BACKEND_UNAVAILABLE] >= 1

        # Capacity back: the SAME client (same connection) recovers.
        for name in stubs:
            router.set_cordon(name, False)
        cate, _, header = client.predict_full(x, request_id="wr2")
        assert header["ok"] and len(cate) == 3
    finally:
        client.close()
        router.stop()
        t.join(5)
    assert not t.is_alive()


def test_handle_router_op_surface(stub_fleet, monkeypatch):
    router, _, _ = stub_fleet(2)
    router.start(probe=False)
    sup = rt.FleetSupervisor(router)
    reply, _, stop = rt.handle_router_op(router, sup, {"op": "ping"}, {})
    assert reply["ok"] and reply["role"] == "router"
    assert reply["in_rotation"] == ["s0", "s1"]
    assert not stop
    reply, _, _ = rt.handle_router_op(router, sup, {"op": "stats"}, {})
    assert set(reply["stats"]["backends"]) == {"s0", "s1"}
    assert reply["stats"]["ring"]["vnodes"] == router.config.vnodes
    monkeypatch.delenv("ATE_TPU_METRICS_DIR", raising=False)
    reply, _, _ = rt.handle_router_op(router, sup, {"op": "dump"}, {})
    assert reply["error"] == "bad_request"
    reply, _, _ = rt.handle_router_op(router, sup, {"op": "rotate_all"}, {})
    assert reply["error"] == "bad_request"  # checkpoint required
    reply, _, _ = rt.handle_router_op(router, sup, {"op": "wat"}, {})
    assert reply["error"] == "bad_request"
    reply, _, stop = rt.handle_router_op(router, sup, {"op": "shutdown"}, {})
    assert reply["ok"] and stop


def test_rolling_rotation_over_stub_fleet(stub_fleet):
    """rotate_all against 3 stub backends: one drained daemon at a
    time, every rotation probe-confirmed at the advanced version,
    exactly one rotate per daemon, zero downtime as a CHECKED number
    (min_in_rotation), and the cordon/uncordon transitions metered."""
    router, stubs, _ = stub_fleet(3)
    router.start(probe=False)
    before = obs.REGISTRY.peek("router_backend_state") or {}
    sup = rt.FleetSupervisor(router)
    result = sup.rotate_all("/pub/model-v2.npz", model="default",
                            timeout_s=10.0)
    assert result["statuses"] == {n: "rotated" for n in stubs}
    assert result["versions"] == {n: 2 for n in stubs}
    assert result["post_swap_compiles"] == {n: 0 for n in stubs}
    assert result["zero_downtime"] is True
    assert result["min_in_rotation"] == 2  # one cordoned at a time
    # The rotation is visible exactly once per daemon, same checkpoint.
    for stub in stubs.values():
        assert stub.rotations == [("default", "/pub/model-v2.npz")]
        assert stub.version == 2
    d = _delta("router_backend_state", before)
    for name in stubs:
        assert d.get(f"backend={name},state=cordoned") == 1
        assert d.get(f"backend={name},state=uncordoned") == 1
    assert router.in_rotation() == ("s0", "s1", "s2")  # all readmitted


def test_rotate_all_refuses_to_cordon_the_last_backend(stub_fleet):
    """Cordoning the only live backend IS downtime — the supervisor
    refuses that daemon's turn instead of taking the fleet out."""
    router, stubs, _ = stub_fleet(1)
    router.start(probe=False)
    sup = rt.FleetSupervisor(router)
    result = sup.rotate_all("/pub/model-v2.npz", timeout_s=5.0)
    assert result["statuses"] == {"s0": "refused_no_capacity"}
    assert result["zero_downtime"] is False
    assert stubs["s0"].rotations == []  # never touched
    assert router.in_rotation() == ("s0",)  # and never cordoned


def test_dump_fleet_manifest_and_orphan_detection(stub_fleet, tmp_path):
    """Stubs answer the daemon ``dump`` op with a typed bad_request, so
    the manifest records dumped=False honestly — and the validator
    still reconciles the router's own counters; a daemon-* directory
    the manifest does not account for is flagged."""
    router, _, _ = stub_fleet(2)
    router.start(probe=False)
    assert _predict(router, "dm0", "default")[0]["ok"]
    outdir = str(tmp_path / "fleet_dump")
    manifest = router.dump_fleet(outdir)
    assert manifest["kind"] == "fleet_manifest"
    assert set(manifest["backends"]) == {"s0", "s1"}
    for entry in manifest["backends"].values():
        assert entry["in_rotation"] is True
        assert entry["dumped"] is False  # stubs cannot dump
    assert manifest["router"]["failover_total"] >= 0
    assert cms.validate_fleet_dump(outdir) == []
    # An orphan daemon dir means the manifest lies about membership.
    os.makedirs(os.path.join(outdir, "daemon-zz"))
    assert any("daemon-zz" in e for e in cms.validate_fleet_dump(outdir))


# ── the fleet-manifest validator's corruption cases (no jax) ───────────


def _write_manifest(tmp_path, manifest: dict) -> str:
    outdir = str(tmp_path)
    with open(os.path.join(outdir, "fleet_manifest.json"), "w") as f:  # graftlint: disable=JGL005
        json.dump(manifest, f)
    return outdir


def _manifest(**kw) -> dict:
    base = {
        "schema_version": 1,
        "kind": "fleet_manifest",
        "backends": {"b0": {"in_rotation": False, "dumped": False}},
        "router": {"requests": {"b0": {"ok": 3},
                                "-": {"unavailable": 1}},
                   "failover_total": 0},
    }
    base.update(kw)
    return base


def _write_fleet_triple(outdir: str) -> None:
    """Complete a hand-written manifest dir into a full dump: a
    minimal router trace plus the merged triple the validator now
    requires, generated through the same pure builders the live
    ``dump_fleet`` runs."""
    rdir = os.path.join(outdir, "router")
    os.makedirs(rdir, exist_ok=True)
    with open(os.path.join(rdir, "trace.json"), "w") as f:  # graftlint: disable=JGL005
        json.dump({"traceEvents": [],
                   "otherData": {"wall_anchor_unix": 100.0}}, f)
    freport.write_fleet_artifacts(outdir)


def test_validate_fleet_dump_corruptions(tmp_path):
    ok = tmp_path / "ok"
    ok.mkdir()
    _write_manifest(ok, _manifest())
    _write_fleet_triple(str(ok))
    assert cms.validate_fleet_dump(str(ok)) == []

    cases = {
        "kind": (_manifest(kind="nope"), "kind"),
        "schema": (_manifest(schema_version=99), "schema_version"),
        "nobackends": (_manifest(backends={}), "backends missing"),
        "norouter": (_manifest(router={}), "router section"),
        "failover": (_manifest(router={
            "requests": {}, "failover_total": -1}), "failover_total"),
        "outcome": (_manifest(router={
            "requests": {"b0": {"weird": 1}}, "failover_total": 0,
        }), "unknown router outcome"),
        "nullbackend": (_manifest(router={
            "requests": {"-": {"ok": 2}}, "failover_total": 0,
        }), "null backend"),
        "ghost": (_manifest(router={
            "requests": {"zz": {"ok": 2}}, "failover_total": 0,
        }), "unknown backend"),
        "dumpedmissing": (_manifest(backends={
            "b0": {"in_rotation": True, "dumped": True},
        }), "not a directory"),
    }
    for name, (manifest, needle) in cases.items():
        d = tmp_path / name
        d.mkdir()
        errors = cms.validate_fleet_dump(_write_manifest(d, manifest))
        assert any(needle in e for e in errors), (name, errors)
    # The merged triple is REQUIRED beside the manifest (PR 20).
    bare = tmp_path / "bare"
    bare.mkdir()
    errors = cms.validate_fleet_dump(_write_manifest(bare, _manifest()))
    for basename in ("fleet_trace.json", "fleet_report.json",
                     "fleet_stat_health.json"):
        assert any(basename in e for e in errors), errors


def _tamper(outdir: str, basename: str, mutate) -> None:
    path = os.path.join(outdir, basename)
    with open(path) as f:  # graftlint: disable=JGL005
        payload = json.load(f)
    mutate(payload)
    with open(path, "w") as f:  # graftlint: disable=JGL005
        json.dump(payload, f)


def test_validate_fleet_artifact_corruptions(tmp_path):
    """Corruption-rejection for the merged triple (PR 20 satellite):
    every tamper is one field away from the honestly-generated
    artifacts, and each trips its own named check — including the
    cross-check against the manifest the artifacts claim to
    describe."""
    def fresh(name: str) -> str:
        d = tmp_path / name
        d.mkdir()
        _write_manifest(d, _manifest())
        _write_fleet_triple(str(d))
        return str(d)

    cases = [
        ("fleet_trace.json", "otherData.kind",
         lambda p: p["otherData"].__setitem__("kind", "nope")),
        ("fleet_trace.json", "pids not distinct",
         lambda p: p["otherData"]["processes"].__setitem__(
             "ghost", dict(p["otherData"]["processes"]["router"]))),
        ("fleet_trace.json", "before the re-based origin",
         lambda p: p["traceEvents"].append(
             {"ph": "X", "name": "router_request", "pid": 1, "tid": 1,
              "ts": -5000.0, "dur": 10.0})),
        ("fleet_trace.json", "does not cross processes",
         lambda p: p["traceEvents"].extend([
             {"ph": "s", "cat": "fleet_req", "id": "fleet:x",
              "name": "fleet_request", "pid": 1, "tid": 1, "ts": 1.0},
             {"ph": "f", "bp": "e", "cat": "fleet_req", "id": "fleet:x",
              "name": "fleet_request", "pid": 1, "tid": 2, "ts": 2.0},
         ])),
        ("fleet_report.json", "consistent is not True",
         lambda p: p["reconciliation"].__setitem__("consistent", False)),
        ("fleet_report.json", "requests.matched",
         lambda p: p["requests"].__setitem__("matched", -1)),
        ("fleet_report.json", "the manifest says",
         lambda p: p["reconciliation"].__setitem__(
             "router_ok", {"b0": 7})),
        ("fleet_stat_health.json", "kind",
         lambda p: p.__setitem__("kind", "nope")),
        ("fleet_stat_health.json", "daemons list missing",
         lambda p: p.__setitem__("daemons", None)),
    ]
    for i, (basename, needle, mutate) in enumerate(cases):
        outdir = fresh(f"t{i}")
        assert cms.validate_fleet_dump(outdir) == []
        _tamper(outdir, basename, mutate)
        errors = cms.validate_fleet_dump(outdir)
        assert any(needle in e for e in errors), (basename, needle,
                                                  errors)


def test_fleet_report_script_recomputes_committed_dump_byte_identical(
        tmp_path):
    """The offline reproducibility acceptance gate (PR 20): the
    COMMITTED dump under tests/data/fleet_dump — captured once from a
    real 2-daemon micro fleet — revalidates clean, and
    ``scripts/fleet_report.py --check`` (run jax-free, as on a laptop)
    recomputes all three merged artifacts bit-for-bit."""
    src = os.path.join(_REPO, "tests", "data", "fleet_dump")
    dst = str(tmp_path / "fleet_dump")
    shutil.copytree(src, dst)
    assert cms.validate_fleet_dump(dst) == []
    proc = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "fleet_report.py"),
         dst, "--check"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "byte-identical" in proc.stdout
    # The committed fixture is rich enough to mean something: all
    # three processes on the merged axis, stitched flow arrows, and a
    # fully matched request set.
    with open(os.path.join(dst, "fleet_trace.json")) as f:  # graftlint: disable=JGL005
        trace = json.load(f)
    assert set(trace["otherData"]["processes"]) == {
        "router", "daemon-b0", "daemon-b1",
    }
    assert any(e.get("cat") == "fleet_req"
               for e in trace["traceEvents"])
    with open(os.path.join(dst, "fleet_report.json")) as f:  # graftlint: disable=JGL005
        report = json.load(f)
    assert report["requests"]["matched"] == report["requests"][
        "router_spans"] > 0
    assert report["requests"]["orphan_router"] == 0
    assert report["requests"]["orphan_daemon"] == 0
    assert report["reconciliation"]["consistent"] is True


# ── router request telemetry + admin plane (PR 20, no jax) ─────────────


_PHASE_ATTRS = ("connect_s", "send_s", "wait_s", "reply_s")


def _router_spans(since: float) -> list[dict]:
    return [r for r in obs.EVENTS.records()
            if r["name"] == "router_request"
            and r["start_mono_s"] >= since]


def _assert_telescopes(rec: dict) -> None:
    a = rec["attrs"]
    phase_sum = sum(a[k] for k in _PHASE_ATTRS)
    assert abs(phase_sum - a["e2e_s"]) <= 1e-6, a


def test_forward_phases_telescope_to_e2e(stub_fleet):
    """Every forward is a ``router_request`` span whose four phase
    attrs sum to the router-observed e2e (the PR 7 ±1 µs discipline:
    contiguous perf_counter marks, every instant in exactly one
    bucket)."""
    router, _, _ = stub_fleet(2)
    router.start(probe=False)
    t0 = time.monotonic()
    for i in range(6):
        assert _predict(router, f"ph{i}", "default")[0]["ok"]
    recs = _router_spans(t0)
    assert len(recs) == 6
    for rec in recs:
        _assert_telescopes(rec)
        a = rec["attrs"]
        assert rec["status"] == "ok"
        assert a["outcome"] == "ok"
        assert a["path"] == "direct"
        assert a["hops"] == 0
        assert a["request_id"].startswith("ph")


def test_failover_span_telescopes_and_meters_path(stub_fleet):
    """A mid-stream death still telescopes — the phase buckets
    ACCUMULATE across hops — and the span + path counter record the
    failover; the breaker flip lands as a ``router_breaker`` instant
    on its own track."""
    router, stubs, _ = stub_fleet(3, failure_threshold=2, cooldown_s=30.0)
    router.start(probe=False)
    model = "default"
    owner = router.ring.owner(model)
    second = router.ring.owners(model, 2)[1]
    assert _predict(router, "tw", model)[0]["ok"]
    stubs[owner].die_midstream = True
    before = obs.REGISTRY.peek("router_request_path_total") or {}
    t0 = time.monotonic()
    for i in range(2):
        assert _predict(router, f"tf{i}", model)[0]["ok"]
    recs = _router_spans(t0)
    assert len(recs) == 2
    for rec in recs:
        _assert_telescopes(rec)
        assert rec["attrs"]["path"] == "failover"
        assert rec["attrs"]["hops"] == 1
        assert rec["attrs"]["backend"] == second
        assert rec["attrs"]["outcome"] == "ok"
    assert _delta("router_request_path_total", before) == {
        "path=failover": 2,
    }
    # Two failures tripped the threshold-2 breaker → exactly one
    # closed→open instant for the dead owner.
    flips = [r for r in obs.EVENTS.records()
             if r["name"] == "router_breaker"
             and r["start_mono_s"] >= t0
             and r["attrs"].get("backend") == owner]
    assert [f["attrs"]["state"] for f in flips] == ["open"]
    assert all(f["attrs"]["track"] == "router-breaker" for f in flips)
    # The e2e histogram metered both forwards under outcome=ok.
    hist = obs.REGISTRY.snapshot()["bucket_histograms"][
        "router_request_seconds"]
    assert hist["outcome=ok"]["count"] >= 2


def test_unavailable_reject_span_is_exhausted_path(stub_fleet):
    router, stubs, _ = stub_fleet(2)
    router.start(probe=False)
    for name in stubs:
        router.set_cordon(name, True)
    t0 = time.monotonic()
    reply, _ = _predict(router, "ux0", "default")
    assert reply["error"] == rt.BACKEND_UNAVAILABLE
    (rec,) = _router_spans(t0)
    _assert_telescopes(rec)
    assert rec["attrs"]["path"] == "exhausted"
    assert rec["attrs"]["backend"] == "-"
    assert rec["attrs"]["outcome"] == "unavailable"
    assert rec["status"] == "error"


def test_probe_tick_emits_instant_and_slo_sample(stub_fleet):
    router, _, _ = stub_fleet(2)
    router.start(probe=False)
    t0 = time.monotonic()
    router.prober.probe_once()
    ticks = [r for r in obs.EVENTS.records()
             if r["name"] == "router_probe" and r["start_mono_s"] >= t0]
    assert len(ticks) == 1
    assert ticks[0]["attrs"] == {
        "track": "router-probe", "backends": 2, "ready": 2,
    }
    health = router.slo.health()
    assert set(health) == {"burning", "slos"}
    assert "router:availability" in health["slos"]


def test_router_admin_routes_and_readyz_flip(stub_fleet):
    """The daemon's HTTP shell with the router's path resolver: GET-only
    /metrics /healthz /readyz /fleetz, and /readyz goes 503 the moment
    the LAST backend leaves rotation (a router fronting an empty fleet
    can take no traffic)."""
    router, stubs, _ = stub_fleet(2)
    router.start(probe=False)
    assert _predict(router, "adm0", "default")[0]["ok"]

    code, ctype, body = rt.handle_router_admin_path(router, "/metrics")
    assert code == 200 and ctype.startswith("text/plain")
    assert b"router_requests_total" in body

    code, _, body = rt.handle_router_admin_path(router, "/healthz")
    health = json.loads(body)
    assert code == 200
    assert health["role"] == "router" and health["state"] == "routing"
    assert health["breakers"] == {"s0": "closed", "s1": "closed"}
    assert set(health["slo"]) == {"burning", "slos"}

    code, _, body = rt.handle_router_admin_path(router, "/fleetz")
    assert code == 200
    assert set(json.loads(body)["backends"]) == {"s0", "s1"}

    code, _, body = rt.handle_router_admin_path(router, "/nope")
    assert code == 404
    assert json.loads(body)["routes"] == list(rt.ROUTER_ADMIN_ROUTES)

    # readyz flips exactly when the last backend cordons, and back.
    assert rt.handle_router_admin_path(router, "/readyz")[0] == 200
    router.set_cordon("s0", True)
    assert rt.handle_router_admin_path(router, "/readyz")[0] == 200
    router.set_cordon("s1", True)
    code, _, body = rt.handle_router_admin_path(router, "/readyz")
    assert code == 503
    assert json.loads(body) == {"ready": False, "role": "router",
                                "in_rotation": []}
    router.set_cordon("s1", False)
    assert rt.handle_router_admin_path(router, "/readyz")[0] == 200


def test_router_admin_over_real_http(stub_fleet):
    """The resolver mounted on a real AdminServer — one HTTP shell,
    two brains (ISSUE 20 satellite): the wire answers match the pure
    handler, and stopping the router flips /healthz to 503."""
    import urllib.error
    import urllib.request

    router, _, _ = stub_fleet(1)
    router.start(probe=False)
    admin = AdminServer(router, handler=rt.handle_router_admin_path,
                        thread_name="router-admin")
    port = admin.start(0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=10) as resp:
            assert resp.status == 200
            assert json.load(resp)["ready"] is True
        router.stop()
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
            raise AssertionError("healthz should be 503 once stopped")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.load(e)["state"] == "stopped"
    finally:
        admin.stop()


def test_router_and_fleet_analyzer_import_jax_free():
    """The router process and the offline fleet analyzer stay jax-free
    (acceptance: import-guard). Run in a subprocess with the parent
    package stubbed — the pattern scripts/fleet_report.py itself uses —
    so this asserts the MODULES' own imports, not the estimator
    stack's."""
    code = "\n".join([
        "import os, sys, types",
        f"sys.path.insert(0, {_REPO!r})",
        "pkg = types.ModuleType('ate_replication_causalml_tpu')",
        "pkg.__path__ = [os.path.join(",
        f"    {_REPO!r}, 'ate_replication_causalml_tpu')]",
        "sys.modules['ate_replication_causalml_tpu'] = pkg",
        "from ate_replication_causalml_tpu.serving import router",
        "from ate_replication_causalml_tpu.observability import (",
        "    fleet_report)",
        "router.handle_router_admin_path  # touch the admin plane too",
        "assert 'jax' not in sys.modules, 'jax leaked into the router'",
    ])
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_validate_fleet_dump_reconciles_daemon_vs_router(tmp_path):
    """The router cannot claim more successful forwards to a backend
    than that backend's daemon recorded serving."""
    ddir = tmp_path / "daemon-b0"
    ddir.mkdir()
    with open(ddir / "metrics.json", "w") as f:  # graftlint: disable=JGL005
        json.dump({"schema_version": 1, "counters": {
            "serving_requests_total": {"status=ok": 1},
        }, "gauges": {}, "histograms": {}, "bucket_histograms": {}}, f)
    with open(ddir / "events.jsonl", "w") as f:  # graftlint: disable=JGL005
        f.write("")
    outdir = _write_manifest(tmp_path, _manifest(
        backends={"b0": {"in_rotation": True, "dumped": True}},
        router={"requests": {"b0": {"ok": 5}}, "failover_total": 0},
    ))
    errors = cms.validate_fleet_dump(outdir)
    assert any("claims 5 successful forwards" in e for e in errors)
    # Per-daemon artifact errors carry the backend name.
    assert any(e.startswith("fleet[b0]:") for e in errors)


# ── client reconnect-and-resubmit (ISSUE 18 satellite, no jax) ─────────


def test_client_reconnects_and_resubmits_same_request_id():
    """A dead TCP connection mid-stream is a typed retryable
    ``connection_lost``: the client reconnects to the original address
    and resubmits under the SAME request id (ids are the idempotency
    key — this is what makes a kill -9'd daemon behind a router
    invisible to a well-behaved client)."""
    seen: list[str] = []
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def serve() -> None:
        # Connection 1: read one frame, then die replyless.
        conn, _ = srv.accept()
        rw = conn.makefile("rwb")
        header, _ = protocol.read_frame(rw)
        seen.append(str(header["id"]))
        conn.close()
        # Connection 2 (the client's redial): serve the resubmission.
        conn2, _ = srv.accept()
        rw2 = conn2.makefile("rwb")
        header2, arrays2 = protocol.read_frame(rw2)
        seen.append(str(header2["id"]))
        n = int(arrays2["x"].shape[0])
        protocol.write_frame(rw2, {
            "ok": True, "id": header2["id"], "model": "default",
            "model_version": 1,
        }, {"cate": np.arange(n, dtype=np.float32),
            "variance": np.zeros(n, np.float32)})
        conn2.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    client = CateClient.connect("127.0.0.1", port, timeout=10.0)
    try:
        cate, var, header = client.predict_full(
            np.zeros((3, 4), np.float32), request_id="rc0", max_retries=4
        )
    finally:
        client.close()
        srv.close()
        t.join(5)
    assert seen == ["rc0", "rc0"]  # same id on both connections
    assert header["ok"]
    assert np.array_equal(cate, np.arange(3, dtype=np.float32))
    assert client.retry_counts.get(CONNECTION_LOST) == 1
    assert client.backoff_s_total >= 0.0


def test_connection_loss_is_terminal_but_typed_without_an_address():
    """Over a socketpair/stdio transport there is nothing to re-dial:
    the loss surfaces immediately as a typed ServingError, never a
    reconnect loop."""
    a, b = socket.socketpair()
    client = CateClient(a.makefile("rb"), a.makefile("wb"), sock=a)
    b.close()
    with pytest.raises(ServingError, match=CONNECTION_LOST):
        client.predict(np.zeros((2, 4), np.float32), request_id="nl0")
    assert client.retry_counts.get(CONNECTION_LOST) is None
    client.close()


# ── the daemon: chaos scope (grammar + plan, no jax) ───────────────────


def test_daemon_chaos_scope_parse_and_validation():
    cfg = chaos.parse_chaos("daemon:kill=1,seed=7")
    assert cfg.scope("daemon") == {"kill": 1, "seed": 7}
    for bad in ("daemon:kill=-1", "daemon:nope=1", "daemon:kill=x"):
        with pytest.raises(chaos.ChaosSpecError):
            chaos.parse_chaos(bad)
    # Unarmed scope: no plan.
    off = chaos.ChaosInjector(chaos.parse_chaos("serve:p=0.1"))
    assert off.daemon_kill_plan(("b0", "b1", "b2")) == ()


def test_daemon_kill_plan_deterministic_capped_recorded_once():
    inj = chaos.ChaosInjector(chaos.parse_chaos("daemon:kill=1,seed=7"))
    names = ("b0", "b1", "b2")
    plan = inj.daemon_kill_plan(names)
    assert len(plan) == 1 and plan[0] in names
    # Pure (seed, "daemon", name) selection: recomputable from the
    # spec alone, by anyone — the invariant registry's contract.
    expected = min(names, key=lambda n: chaos._unit(7, "daemon", n))
    assert plan == (expected,)
    fresh = chaos.ChaosInjector(chaos.parse_chaos("daemon:kill=1,seed=7"))
    assert fresh.daemon_kill_plan(names) == plan
    # A different seed draws (possibly) different victims — and k is
    # ALWAYS capped at fleet size - 1: killing everyone proves nothing.
    greedy = chaos.ChaosInjector(chaos.parse_chaos("daemon:kill=9,seed=7"))
    assert len(greedy.daemon_kill_plan(names)) == 2
    assert greedy.daemon_kill_plan(("only",)) == ()
    # kill=0 is a no-op plan.
    none = chaos.ChaosInjector(chaos.parse_chaos("daemon:kill=0,seed=7"))
    assert none.daemon_kill_plan(names) == ()
    # One SIGKILL per victim, EVER: the second record is refused.
    before = obs.REGISTRY.peek("chaos_injections_total") or {}
    assert inj.record_daemon_kill(plan[0]) is True
    assert inj.record_daemon_kill(plan[0]) is False
    assert _delta("chaos_injections_total", before) == {"scope=daemon": 1}


def test_campaign_daemon_atom_and_fleet_workload_registration():
    """The campaign knows the scope (seeded atoms parse clean) and the
    fleet workload is registered but OPT-IN only — absent from
    WORKLOAD_ORDER, so existing per-seed plans are byte-stable."""
    from ate_replication_causalml_tpu.resilience import campaign

    d = campaign.Draw(3, "t")
    atom = campaign.draw_atom("fleet", "daemon", d)
    assert atom.startswith("daemon:kill=1,seed=")
    assert campaign.draw_atom("fleet", "daemon", d) == atom  # pure draw
    chaos.parse_chaos(atom)  # grammar-valid
    assert "daemon" in campaign._SCOPE_ORDER
    assert campaign.WORKLOADS["fleet"].scopes == ("daemon",)
    assert "fleet" not in campaign.WORKLOAD_ORDER
    assert "daemon" not in campaign.NONDETERMINISTIC_SCOPES


# ── graftlint coverage of the new module (ISSUE 18 satellite) ──────────


def test_graftlint_jgl008_and_jgl012_cover_the_router_module():
    """serving/router.py is inside both concurrency rules' path scopes
    (zero new suppressions): unlocked shared state and zero-arg
    blocking forms must fire there exactly as in the daemon."""
    from ate_replication_causalml_tpu.analysis.core import lint_source

    shared_state = (
        "import threading\n"
        "class Router:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._backends = {}\n"
        "    def bad(self, k, v):\n"
        "        self._backends[k] = v\n"
    )
    res = lint_source(shared_state, relpath="pkg/serving/router.py",
                      select=["JGL008"])
    assert [f.line for f in res.findings] == [7]

    unbounded = (
        "def probe_loop(lock, t):\n"
        "    lock.acquire()\n"
        "    t.join()\n"
    )
    res = lint_source(unbounded, relpath="pkg/serving/router.py",
                      select=["JGL012"])
    assert [f.line for f in res.findings] == [2, 3]
    bounded = (
        "def probe_loop(lock, t):\n"
        "    lock.acquire(True, 0.5)\n"
        "    t.join(5.0)\n"
    )
    res = lint_source(bounded, relpath="pkg/serving/router.py",
                      select=["JGL012"])
    assert res.findings == []


# ── THE tier-1 micro fleet: 2 in-process daemons behind the router ─────


def _synthetic_forest(rng):
    """Same micro-forest shape as the PR 6/7/11 serving rigs."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.causal_forest import CausalForest

    T, D, n, p, nb = 8, 3, 50, 4, 8
    return CausalForest(
        split_feat=jnp.asarray(
            rng.integers(0, p, size=(T, D, 1 << D)).astype(np.int32)
        ),
        split_bin=jnp.asarray(
            rng.integers(0, nb - 1, size=(T, D, 1 << D)).astype(np.int32)
        ),
        leaf_stats=jnp.asarray(
            (np.abs(rng.normal(size=(T, 1 << D, 5))) + 0.5).astype(np.float32)
        ),
        in_sample=jnp.asarray(rng.uniform(size=(T, n)) < 0.5),
        bin_edges=jnp.asarray(
            np.sort(rng.normal(size=(p, nb - 1)), axis=1).astype(np.float32)
        ),
        ci_group_size=2,
    )


def test_micro_fleet_replay_rotation_bit_identity_and_dump(tmp_path):
    """THE tier-1 acceptance rig (ISSUE 18 budget swap: ONE in-process
    2-backend fleet here; the 3-daemon subprocess + SIGKILL episode is
    @slow below). A seeded multi-model replay through the router is
    bit-identical per model version to the offline reference computed
    BEFORE any daemon started; a mid-stream ``rotate_all`` rolls the
    default model across both daemons with zero downtime and zero
    post-swap compiles; the merged fleet dump validates and
    reconciles. Both daemons run ``strict_no_compile=False`` — the
    no-compile window term is process-global and this test IS two
    daemons in one process (the campaign's fleet workload proves the
    strict contract per-subprocess)."""
    import jax.numpy as jnp

    from ate_replication_causalml_tpu.models.causal_forest import predict_cate
    from ate_replication_causalml_tpu.serving import daemon as daemon_mod
    from ate_replication_causalml_tpu.serving import loadgen
    from ate_replication_causalml_tpu.serving.coalescer import BucketPlan
    from ate_replication_causalml_tpu.serving.daemon import (
        CateServer,
        ServeConfig,
    )
    from ate_replication_causalml_tpu.utils.checkpoint import save_fitted

    rng = np.random.default_rng(18)
    forests = {
        ("default", 1): _synthetic_forest(rng),
        ("m2", 1): _synthetic_forest(rng),
        ("default", 2): _synthetic_forest(rng),  # the rotation candidate
    }
    ckpts = {}
    for (model, version), forest in forests.items():
        ckpts[(model, version)] = str(tmp_path / f"{model}-v{version}.npz")
        save_fitted(ckpts[(model, version)], forest)

    n_requests = 36
    schedule = loadgen.build_schedule(
        9, n_requests, rate_hz=4000.0, mix="1:2,3:1,4:1", id_prefix="mf",
        models=("default", "m2"),
    )
    queries = loadgen.build_queries(9, schedule, 4)

    # Offline references BEFORE any daemon exists: full-stream
    # predictions per (model, version) — the bit-identity partition.
    offs, off = [], 0
    for q in queries:
        offs.append(off)
        off += q.shape[0]
    cat = jnp.asarray(np.concatenate(queries))
    refs = {}
    for key, forest in forests.items():
        out = predict_cate(forest, cat, oob=False, row_backend="matmul")
        refs[key] = (np.asarray(out.cate), np.asarray(out.variance))

    servers, admins, daemon_threads, ports = [], [], [], {}
    router = None
    serve_thread = None
    client = None
    try:
        specs = []
        for name in ("b0", "b1"):
            server = CateServer(ServeConfig(
                checkpoint=ckpts[("default", 1)],
                fleet=(("m2", ckpts[("m2", 1)]),),
                buckets=BucketPlan.parse("4,16"),
                window_s=0.002,
                max_depth=32,
                retry_after_s=0.005,
                strict_no_compile=False,
            ))
            server.startup()
            servers.append(server)
            adm = AdminServer(server)
            aport = adm.start(0)
            admins.append(adm)
            bound_evt = threading.Event()

            def on_bound(port: int, _name=name, _evt=bound_evt) -> None:
                ports[_name] = port
                _evt.set()

            t = threading.Thread(
                target=daemon_mod.serve_socket, args=(server,),
                kwargs=dict(port=0, on_bound=on_bound), daemon=True,
                name=f"fleet-daemon-{name}",
            )
            t.start()
            daemon_threads.append(t)
            assert bound_evt.wait(30)
            specs.append(rt.BackendSpec(name, "127.0.0.1",
                                        ports[name], aport))

        router = rt.RouterServer(rt.RouterConfig(
            backends=tuple(specs), probe_interval_s=0.05,
        ))
        router.start()
        assert router.in_rotation() == ("b0", "b1")
        for name in ("b0", "b1"):
            assert router.bound_version(name, "default") == 1
            assert router.bound_version(name, "m2") == 1

        router_bound: list[int] = []
        router_evt = threading.Event()
        serve_thread = threading.Thread(
            target=rt.serve_socket, args=(router,),
            kwargs=dict(port=0, on_bound=lambda p: (
                router_bound.append(p), router_evt.set())),
            daemon=True, name="fleet-router",
        )
        serve_thread.start()
        assert router_evt.wait(10)
        client = CateClient.connect("127.0.0.1", router_bound[0],
                                    timeout=60.0)

        supervisor = rt.FleetSupervisor(router)
        req_before = obs.REGISTRY.peek("router_requests_total") or {}
        replies = []
        rotation = None
        for i, sched in enumerate(schedule):
            if i == n_requests // 2:
                # The rolling rotation lands INSIDE the stream.
                rotation = supervisor.rotate_all(
                    ckpts[("default", 2)], model="default", timeout_s=60.0
                )
            replies.append(client.predict_full(
                queries[i], request_id=sched.request_id,
                model=sched.model, max_retries=32,
            ))

        # Zero downtime, zero post-swap compiles, probe-confirmed v2 —
        # checked numbers, per daemon.
        assert rotation is not None
        assert rotation["statuses"] == {"b0": "rotated", "b1": "rotated"}
        assert rotation["versions"] == {"b0": 2, "b1": 2}
        assert rotation["post_swap_compiles"] == {"b0": 0, "b1": 0}
        assert rotation["zero_downtime"] is True
        assert rotation["min_in_rotation"] >= 1

        # Bit-identity per model version: whichever daemon served it,
        # the bytes must equal the offline reference for the version
        # the reply header binds.
        versions_seen = set()
        for i, (sched, (cate, var, header)) in enumerate(
                zip(schedule, replies)):
            model = sched.model or "default"
            version = int(header["model_version"])
            versions_seen.add((model, version))
            assert model == header["model"]
            refc, refv = refs[(model, version)]
            lo, hi = offs[i], offs[i] + queries[i].shape[0]
            assert np.array_equal(cate, refc[lo:hi]), sched.request_id
            assert np.array_equal(var, refv[lo:hi]), sched.request_id
        assert ("default", 1) in versions_seen
        assert ("default", 2) in versions_seen  # the new forest served
        assert ("m2", 1) in versions_seen
        assert ("m2", 2) not in versions_seen  # only default rotated

        # Every forward this test drove landed ok — no silent drops,
        # no unavailability window during the roll (counter deltas: the
        # registry is process-global).
        d = _delta("router_requests_total", req_before)
        assert set(d) <= {"backend=b0,outcome=ok", "backend=b1,outcome=ok"}
        assert sum(d.values()) == n_requests
        assert client.retry_counts == {}  # nothing was even retried

        # The merged fleet dump validates end to end: per-daemon
        # artifact sets + the manifest's reconciliation.
        dump_dir = str(tmp_path / "fleet_dump")
        manifest = router.dump_fleet(dump_dir)
        assert all(e["dumped"] for e in manifest["backends"].values())
        assert cms.validate_fleet_dump(dump_dir) == []

        # The merged triple (PR 20): every router span matched to a
        # daemon span on its request id — zero orphans through the
        # mid-stream rotation — and the reconciliation agrees with the
        # manifest (validate_fleet_dump above already cross-checked).
        with open(os.path.join(dump_dir, "fleet_report.json")) as f:  # graftlint: disable=JGL005
            freport_doc = json.load(f)
        req = freport_doc["requests"]
        assert req["router_spans"] == n_requests
        assert req["matched"] == n_requests
        assert req["orphan_router"] == 0
        assert req["orphan_daemon"] == 0
        assert freport_doc["reconciliation"]["consistent"] is True
        assert freport_doc["reconciliation"]["router_ok_total"] == \
            n_requests
        with open(os.path.join(dump_dir, "fleet_trace.json")) as f:  # graftlint: disable=JGL005
            ftrace = json.load(f)
        assert set(ftrace["otherData"]["processes"]) == {
            "router", "daemon-b0", "daemon-b1",
        }
        # Every router span in the merged timeline telescopes: the four
        # phase args sum to e2e (±1 µs) — through the failover-capable
        # path, on REAL daemons.
        # The daemons share this process's event ring, so their dumps
        # carry copies of the router spans too — the ROUTER process's
        # copies are the born-filtered canonical set.
        router_pid = ftrace["otherData"]["processes"]["router"]["pid"]
        merged_router_spans = [
            e for e in ftrace["traceEvents"]
            if e.get("ph") == "X" and e.get("name") == "router_request"
            and e.get("pid") == router_pid
        ]
        assert len(merged_router_spans) == n_requests
        for ev in merged_router_spans:
            a = ev["args"]
            phase_sum = (a["connect_s"] + a["send_s"] + a["wait_s"]
                         + a["reply_s"])
            assert abs(phase_sum - a["e2e_s"]) <= 1e-6, a
        assert any(e.get("cat") == "fleet_req"
                   for e in ftrace["traceEvents"])
        # Byte-identity of the offline recomputation, in process.
        with open(os.path.join(dump_dir, "fleet_trace.json"), "rb") as f:  # graftlint: disable=JGL005
            trace_bytes = f.read()
        freport.write_fleet_artifacts(dump_dir)
        with open(os.path.join(dump_dir, "fleet_trace.json"), "rb") as f:  # graftlint: disable=JGL005
            assert f.read() == trace_bytes

        # Shut the daemons down over the wire, then the router.
        for name in ("b0", "b1"):
            reply, _ = router.call_backend(name, {"op": "shutdown"})
            assert reply["ok"]
    finally:
        if client is not None:
            client.close()
        if router is not None:
            router.stop()
        if serve_thread is not None:
            serve_thread.join(10)
        for t in daemon_threads:
            t.join(10)
        for adm in admins:
            adm.stop()
        for server in servers:
            if server.lifecycle.state != "stopped":
                server.stop()
    assert all(not t.is_alive() for t in daemon_threads)


# ── the subprocess kill -9 episode (@slow: the tier-1 budget swap) ─────


@pytest.mark.slow
def test_fleet_campaign_episode_sigkill_invariants(tmp_path):
    """ISSUE 18 acceptance, full strength: the campaign's ``fleet``
    workload spawns THREE real ``scripts/serve.py`` subprocesses behind
    the router, SIGKILLs the chaos-selected victim mid-replay, and the
    complete invariant registry judges the episode against its
    fault-free reference — zero silent drops, bit-identity per model
    version, the rotation visible exactly once per daemon, survivors
    exiting clean. Displaced from tier-1 by the in-process micro fleet
    above (the documented budget swap)."""
    from ate_replication_causalml_tpu.resilience import campaign

    # Seed 0 (not 7): the chaos-selected victim must OWN ring keys so
    # the SIGKILL actually produces failover traffic — under seed 0 the
    # victim is b2, which owns "default" and "m3", and the first three
    # post-kill requests are all victim-owned (checked when the seed
    # was chosen; the schedule and the kill plan are both pure
    # functions of it).
    verdicts = campaign.run_repro(
        "fleet", 0, "daemon:kill=1,seed=0", str(tmp_path),
        scale="micro", log=lambda s: None,
    )
    by = {v.invariant: v for v in verdicts}
    failed = [v for v in verdicts if v.verdict == "fail"]
    assert not failed, [(v.invariant, v.detail) for v in failed]
    # The fleet-specific invariants actually judged (not skipped).
    assert by["fleet_failover"].verdict == "pass"
    assert by["bit_identity"].verdict == "pass"
    assert sorted(by["fleet_failover"].data["killed"]) == [
        min(("b0", "b1", "b2"),
            key=lambda n: chaos._unit(0, "daemon", n))
    ]

    # PR 20: the merged fleet timeline tells the chaos story. The
    # SIGKILL instant, the victim's breaker opening, and a failover
    # flow arrow into a SURVIVING daemon all appear on the one
    # wall-clock axis — and no request-id span is orphaned by the kill
    # (these are real subprocesses: each daemon's ring holds only its
    # own spans, so the orphan check has teeth here).
    (victim,) = by["fleet_failover"].data["killed"]
    dump_dir = str(tmp_path / "episode" / "fleet_dump")
    assert cms.validate_fleet_dump(dump_dir) == []
    with open(os.path.join(dump_dir, "fleet_trace.json")) as f:  # graftlint: disable=JGL005
        ftrace = json.load(f)
    procs = ftrace["otherData"]["processes"]
    router_pid = procs["router"]["pid"]
    events = ftrace["traceEvents"]
    assert any(
        e.get("name") == "chaos_inject" and e.get("pid") == router_pid
        and (e.get("args") or {}).get("site") == f"daemon/{victim}"
        for e in events
    )
    assert any(
        e.get("name") == "router_breaker" and e.get("pid") == router_pid
        and (e.get("args") or {}).get("backend") == victim
        and (e.get("args") or {}).get("state") == "open"
        for e in events
    )
    failover_rids = {
        (e.get("args") or {}).get("request_id")
        for e in events
        if e.get("ph") == "X" and e.get("name") == "router_request"
        and e.get("pid") == router_pid
        and (e.get("args") or {}).get("path") == "failover"
    }
    assert failover_rids  # the kill landed mid-replay
    survivor_pids = {
        p["pid"] for name, p in procs.items()
        if name.startswith("daemon-") and name != f"daemon-{victim}"
    }
    flow_finish = {
        e.get("id"): e for e in events
        if e.get("cat") == "fleet_req" and e.get("ph") == "f"
    }
    assert any(
        f"fleet:{rid}" in flow_finish
        and flow_finish[f"fleet:{rid}"]["pid"] in survivor_pids
        for rid in failover_rids
    )
    with open(os.path.join(dump_dir, "fleet_report.json")) as f:  # graftlint: disable=JGL005
        fleet_rep = json.load(f)
    assert fleet_rep["requests"]["orphan_router"] == 0
    assert fleet_rep["requests"]["orphan_daemon"] == 0
    assert fleet_rep["reconciliation"]["consistent"] is True

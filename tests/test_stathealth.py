"""Statistical-health plane unit tests (ISSUE 16): sketch known
answers and merge algebra, the drift monitor's window-flip under an
injected clock, the statistical SLOs' burn semantics, report purity
(dump == recompute, byte for byte), the schema validator's corruption
matrix, and the ``stat_drift`` invariant.

Entirely jax-free and clock-injected — every figure here is asserted
exactly or within explicit tolerance; no sleeps, no daemon.
"""

from __future__ import annotations

import json
import math
import os
import sys

import numpy as np
import pytest

from ate_replication_causalml_tpu.observability import stathealth as sh
from ate_replication_causalml_tpu.observability.registry import (
    MetricsRegistry,
)
from ate_replication_causalml_tpu.observability.sketch import (
    CalibrationSketch,
    FixedBinSketch,
    ks_statistic,
    psi,
)
from ate_replication_causalml_tpu.observability.slo import (
    SLOEngine,
    stat_health_slos,
)
from ate_replication_causalml_tpu.resilience import invariants as inv

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
))
import check_metrics_schema as cms  # noqa: E402


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ── sketch core ───────────────────────────────────────────────────────


def test_fixed_bin_assignment_and_tails():
    s = FixedBinSketch(0.0, 4.0, 4)
    s.update([0.0, 0.5, 1.0, 1.5, 3.999, 4.0, -0.1, float("nan")])
    # Edge values land deterministically: 1.0 belongs to bin 1
    # ([1, 2)), 4.0 overflows, 0.0 is bin 0.
    assert s.counts == [2, 2, 0, 1]
    assert s.underflow == 1 and s.overflow == 1 and s.nan == 1
    # located = everything with a distributional position (tails
    # included — they are comparable cells); only NaN is unlocated.
    assert s.total() == 8 and s.located() == 7
    assert s.cells() == [1, 2, 2, 0, 1, 1]


def test_fixed_bin_quantiles():
    s = FixedBinSketch(0.0, 4.0, 4)
    s.update([0.5, 1.5, 2.5, 3.5])
    assert s.quantile(0.5) == 1.5   # rank 2 of 4 → bin-1 midpoint
    assert s.quantile(1.0) == 3.5
    assert s.quantile(0.01) == 0.5  # rank clamps to 1
    assert FixedBinSketch(0.0, 1.0, 2).quantile(0.5) is None
    u = FixedBinSketch(0.0, 1.0, 2)
    u.add(-5.0)
    assert u.quantile(0.5) == 0.0   # underflow reports the lower bound


def test_psi_known_answer():
    """10 observations moving entirely from bin 0 to bin 1: with the
    +0.5 Laplace smoothing over 6 extended cells each side normalizes
    by 13, and PSI = 2 · (10/13) · ln(10.5/0.5)."""
    a = FixedBinSketch(0.0, 1.0, 4)
    a.update([0.1] * 10)
    b = FixedBinSketch(0.0, 1.0, 4)
    b.update([0.3] * 10)
    expected = 2.0 * (10.0 / 13.0) * math.log(21.0)
    assert psi(a, b) == pytest.approx(expected, rel=1e-12)
    assert psi(a, a) == 0.0


def test_ks_known_answer_and_empty_contract():
    a = FixedBinSketch(0.0, 1.0, 4)
    a.update([0.1] * 7)
    b = FixedBinSketch(0.0, 1.0, 4)
    b.update([0.9] * 3)
    assert ks_statistic(a, b) == 1.0  # disjoint supports: max CDF gap
    assert ks_statistic(a, a) == 0.0
    empty = FixedBinSketch(0.0, 1.0, 4)
    assert ks_statistic(a, empty) == 0.0  # either side empty → 0, not NaN
    assert psi(empty, empty) == 0.0


def test_merge_algebra_and_compatibility():
    def build(vals):
        s = FixedBinSketch(-2.0, 2.0, 8)
        s.update(vals)
        return s

    rng = np.random.default_rng(3)
    a, b, c = (build(rng.normal(size=40)) for _ in range(3))
    # commutative + associative, empty identity — the properties that
    # make fleet-wide merging order-free (ROADMAP item 2).
    assert a.merge(b).to_json() == b.merge(a).to_json()
    assert a.merge(b).merge(c).to_json() == a.merge(b.merge(c)).to_json()
    empty = FixedBinSketch(-2.0, 2.0, 8)
    assert a.merge(empty).to_json() == a.to_json()
    # merge is pure: inputs untouched
    before = a.to_json()
    a.merge(b)
    assert a.to_json() == before
    with pytest.raises(ValueError, match="incompatible"):
        a.merge(FixedBinSketch(-2.0, 2.0, 4))


def test_insertion_order_determinism_and_serialization():
    vals = list(np.random.default_rng(7).normal(size=100))
    fwd = FixedBinSketch(-3.0, 3.0, 8)
    fwd.update(vals)
    rev = FixedBinSketch(-3.0, 3.0, 8)
    rev.update(reversed(vals))
    one_at_a_time = FixedBinSketch(-3.0, 3.0, 8)
    for v in vals:
        one_at_a_time.add(v)
    assert fwd.to_json() == rev.to_json() == one_at_a_time.to_json()
    # byte-stable round trip
    assert FixedBinSketch.from_json(fwd.to_json()).to_json() == fwd.to_json()
    with pytest.raises(ValueError):
        FixedBinSketch.from_dict({"kind": "fixed_bin", "lo": 0.0, "hi": 1.0,
                                  "n_bins": 2, "counts": [1, -1],
                                  "underflow": 0, "overflow": 0, "nan": 0,
                                  "schema_version": 1})


def test_calibration_sketch_known_answers():
    cal = CalibrationSketch(10)
    cal.update([0.95] * 100, [True] * 95 + [False] * 5)
    # bucket-9 midpoint 0.95 vs observed 95/100: perfectly calibrated.
    assert cal.calibration_error() == 0.0
    off = CalibrationSketch(10)
    off.update([0.95] * 100, [True] * 50 + [False] * 50)
    assert off.calibration_error() == pytest.approx(0.45)
    assert CalibrationSketch(10).calibration_error() is None
    merged = cal.merge(off)
    assert merged.counts[9] == 200 and merged.positives[9] == 145
    assert CalibrationSketch.from_json(cal.to_json()).to_json() \
        == cal.to_json()
    with pytest.raises(ValueError, match="positives"):
        CalibrationSketch.from_dict({"kind": "calibration", "n_buckets": 2,
                                     "counts": [1, 0], "positives": [2, 0],
                                     "nan": 0, "schema_version": 1})


# ── monitor: window flip under an injected clock ──────────────────────


def _feed(mon, rng, n_batches=10, rows=30, shift=0.0, model="default"):
    for _ in range(n_batches):
        x = rng.normal(size=(rows, 4)).astype(np.float32)
        x[:, 0] += shift
        mon.observe(model, x[:, 0] * 0.5, x)


def test_monitor_flags_drift_exactly_at_the_shift_boundary():
    """The tier-1 drift-flip proof: same-seed steady traffic stays ok
    window after window; a mid-stream covariate shift flips exactly ONE
    window pair per channel to drift (the pre/post boundary), and the
    shifted steady state is ok again — drift means CHANGE, not level."""
    clk = _Clock()
    reg = MetricsRegistry()
    mon = sh.StatHealthMonitor(("default",), window_s=1.0, clock=clk,
                               registry=reg, min_count=50)
    rng = np.random.default_rng(0)
    for _ in range(4):
        _feed(mon, rng)
        clk.t += 1.0
    for _ in range(3):
        _feed(mon, rng, shift=2.5)
        clk.t += 1.0
    _feed(mon, rng, shift=2.5)  # seals window 6 with a same-dist pair
    state = mon.state_dict()
    for ch in sh.CHANNELS:
        series = state["models"]["default"]["channels"][ch]["series"]
        statuses = [e["status"] for e in series]
        assert statuses.count("drift") == 1, (ch, statuses)
        # the drifted pair is exactly the boundary: windows 3 → 4
        flip = next(e for e in series if e["status"] == "drift")
        assert (flip["prev_index"], flip["index"]) == (3, 4)
        assert statuses[-1] == "ok"
    # counters mirror the series
    windows = reg.peek("serving_stat_windows_total")
    drift_keys = [k for k in windows if "status=drift" in k]
    assert len(drift_keys) == len(sh.CHANNELS)
    assert all("model=default" in k for k in drift_keys)
    events = reg.peek("stat_drift_events_total")
    assert sum(events.values()) >= len(sh.CHANNELS)
    health = mon.health()
    assert health["models"]["default"]["drift_events"] == len(sh.CHANNELS)


def test_monitor_sparse_windows_never_alarm():
    """Below min_count the pair detectors are statistically meaningless
    — the window is typed sparse, never drift, and the SLOs ignore it
    (budget must not burn on thin traffic)."""
    clk = _Clock()
    mon = sh.StatHealthMonitor(("default",), window_s=1.0, clock=clk,
                               min_count=200)
    rng = np.random.default_rng(1)
    for shift in (0.0, 4.0, 0.0):
        _feed(mon, rng, n_batches=1, rows=20, shift=shift)
        clk.t += 1.0
    _feed(mon, rng, n_batches=1, rows=20)
    state = mon.state_dict()
    for ch in sh.CHANNELS:
        series = state["models"]["default"]["channels"][ch]["series"]
        assert series and all(e["status"] == "sparse" for e in series)


def test_monitor_first_window_has_no_pair():
    clk = _Clock()
    mon = sh.StatHealthMonitor(("default",), window_s=1.0, clock=clk)
    _feed(mon, np.random.default_rng(2), n_batches=1)
    clk.t += 1.0
    _feed(mon, np.random.default_rng(2), n_batches=1)
    state = mon.state_dict()
    for ch in sh.CHANNELS:
        cstate = state["models"]["default"]["channels"][ch]
        assert len(cstate["windows"]) == 1  # sealed, but nothing to pair
        assert cstate["series"] == []


def test_monitor_calibration_channel_opt_in():
    """Unarmed, the calibration channel stays empty (its SLO can never
    burn); armed with (propensity_col, treatment_col) it types windows
    ok when treatment follows the propensity and miscal when it is
    anti-correlated."""
    clk = _Clock()
    rng = np.random.default_rng(5)

    def feed(mon, flip):
        for _ in range(10):
            x = rng.normal(size=(40, 4)).astype(np.float32)
            p = 1.0 / (1.0 + np.exp(-x[:, 0]))
            treated = rng.random(40) < (1.0 - p if flip else p)
            x[:, 1] = np.where(treated, 1.0, -1.0)
            mon.observe("default", x[:, 0], x)

    unarmed = sh.StatHealthMonitor(("default",), window_s=1.0, clock=clk)
    feed(unarmed, flip=False)
    cal = unarmed.state_dict()["models"]["default"]["calibration"]
    assert cal["enabled"] is False and cal["total"]["counts"] == [0] * 10

    clk = _Clock()
    armed = sh.StatHealthMonitor(("default",), window_s=1.0, clock=clk,
                                 min_count=200, calibration_cols=(0, 1))
    for flip in (False, False, True, True):
        feed(armed, flip)
        clk.t += 1.0
    feed(armed, flip=True)
    series = armed.state_dict()["models"]["default"]["calibration"]["series"]
    statuses = [e["status"] for e in series]
    assert statuses[0] == "ok" and "miscal" in statuses


# ── statistical SLOs ──────────────────────────────────────────────────


def test_stat_slos_burn_on_drift_and_stay_green_otherwise():
    """The end-to-end tier-1 flip: monitor + engine over one registry.
    Unshifted steady state never burns; persistent distribution churn
    burns the drift SLO while the (unarmed) calibration SLO stays
    green. This is the in-process twin of the @slow replay proof."""
    clk = _Clock()
    reg = MetricsRegistry()
    eng = SLOEngine(stat_health_slos(("default",), windows_s=(10.0, 50.0)),
                    registry=reg, clock=clk)
    mon = sh.StatHealthMonitor(("default",), window_s=1.0, clock=clk,
                               registry=reg, min_count=50)
    rng = np.random.default_rng(0)
    for _ in range(6):
        _feed(mon, rng)
        clk.t += 1.0
        eng.tick()
    green = eng.health()
    assert green["burning"] is False
    assert green["slos"]["stat_drift:default"]["worst_burn_rate"] == 0.0
    # oscillating shift: every sealed pair crosses a distribution change
    for w in range(6):
        _feed(mon, rng, shift=2.5 if w % 2 == 0 else 0.0)
        clk.t += 1.0
        eng.tick()
    burning = eng.health()
    assert burning["slos"]["stat_drift:default"]["burning"] is True
    assert burning["slos"]["stat_calibration:default"]["burning"] is False


def test_stat_drift_slo_ignores_calibration_and_sparse_samples():
    """The ignore contract: calibration windows and sparse windows are
    excluded from BOTH sides of the drift SLO's ratio — calibration ok
    windows must not pad `good` above `total`, and sparse windows must
    not burn."""
    clk = _Clock()
    reg = MetricsRegistry()
    eng = SLOEngine(stat_health_slos(("m",), windows_s=(10.0,)),
                    registry=reg, clock=clk)
    eng.tick()  # empty baseline — the deltas below are the window
    c = reg.counter("serving_stat_windows_total")
    c.inc(4, model="m", channel="cate", status="drift")
    c.inc(6, model="m", channel="calibration", status="ok")
    c.inc(5, model="m", channel="covariate", status="sparse")
    clk.t += 1.0
    eng.tick()
    health = eng.health()
    drift = health["slos"]["stat_drift:m"]
    # 4 drift / 4 counted windows: error rate 1.0 against a 0.9
    # objective → burn 10. Were calibration's 6 ok windows counted as
    # good, the error rate would read 0 and mask the drift entirely.
    assert drift["burning"] is True
    assert drift["worst_burn_rate"] == pytest.approx(10.0)


def test_stat_health_slo_declarations():
    slos = stat_health_slos(("a", "b"), objective=0.95)
    names = [s.name for s in slos]
    assert names == ["stat_drift:a", "stat_calibration:a",
                     "stat_drift:b", "stat_calibration:b"]
    for s in slos:
        assert s.metric == "serving_stat_windows_total"
        assert s.objective == 0.95
        assert s.good_match == "status=ok"


# ── report purity + byte identity ─────────────────────────────────────


def _populated_monitor(calibration=False):
    clk = _Clock()
    mon = sh.StatHealthMonitor(
        ("default",), window_s=1.0, clock=clk, min_count=50,
        calibration_cols=(0, 1) if calibration else None,
    )
    rng = np.random.default_rng(11)
    for shift in (0.0, 0.0, 3.0):
        _feed(mon, rng, shift=shift)
        clk.t += 1.0
    _feed(mon, rng, shift=3.0)
    return mon


def test_report_is_pure_function_of_state_through_json():
    """The analyzer contract: the dumped report embeds its own input;
    recomputing from the JSON round-tripped state reproduces the report
    exactly (no hidden floats, no dict-order dependence)."""
    state = _populated_monitor().state_dict()
    report = sh.stat_health_report(state)
    round_tripped = json.loads(json.dumps(report))
    assert sh.stat_health_report(round_tripped["state"]) == round_tripped
    assert report["drift"]["events"] >= 1
    assert sh.render_summary(report)  # renders without KeyError


def test_state_is_batch_split_invariant():
    """Totals are integer functions of the served multiset: the same
    rows fed as one batch or thirty produce byte-identical state —
    the per-seed byte-identity claim reduced to its mechanism."""
    x = np.random.default_rng(4).normal(size=(60, 4)).astype(np.float32)
    cate = x[:, 0] * 0.5

    def run(splits):
        clk = _Clock()
        mon = sh.StatHealthMonitor(("default",), window_s=1e9, clock=clk)
        for part in np.array_split(np.arange(60), splits):
            mon.observe("default", cate[part], x[part])
        return json.dumps(mon.state_dict(), sort_keys=True)

    assert run(1) == run(30) == run(60)


def test_write_stat_health_rewrite_is_byte_identical(tmp_path):
    """The same discipline scripts/analyze_trace.py relies on: write,
    reload the artifact, write again from its embedded state — the
    file bytes must not move."""
    state = _populated_monitor(calibration=True).state_dict()
    sh.write_stat_health(str(tmp_path), state)
    path = tmp_path / sh.STAT_HEALTH_BASENAME
    first = path.read_bytes()
    dumped = json.loads(first)
    sh.write_stat_health(str(tmp_path), dumped["state"])
    assert path.read_bytes() == first


# ── schema validator corruption matrix ────────────────────────────────


def _clean_report():
    return sh.stat_health_report(
        _populated_monitor(calibration=True).state_dict()
    )


def test_validator_accepts_clean_report():
    assert cms.validate_stat_health(_clean_report()) == []


@pytest.mark.parametrize("mutate,expect", [
    (lambda r: r.pop("state"), "missing schema_version or state"),
    (lambda r: r["state"].pop("models"), "state.models missing"),
    (lambda r: r["state"]["models"]["default"]["channels"].pop("cate"),
     "channels !="),
    (lambda r: r["state"]["models"]["default"]["channels"]["cate"]
     ["windows"][0]["sketch"]["counts"].__setitem__(0, 10**6),
     "mass not conserved"),
    (lambda r: r["state"]["models"]["default"]["channels"]["cate"]
     ["windows"].reverse(), "indices not ascending"),
    (lambda r: r["state"]["models"]["default"]["channels"]["cate"]
     ["series"][0].__setitem__("psi", -0.5), "PSI out of range"),
    (lambda r: r["state"]["models"]["default"]["channels"]["cate"]
     ["series"][0].__setitem__("ks", 1.5), "KS out of"),
    (lambda r: r["state"]["models"]["default"]["channels"]["cate"]
     ["series"][0].__setitem__("status", "vibes"), "unknown window status"),
    (lambda r: r["state"]["models"]["default"]["calibration"]["total"]
     ["positives"].__setitem__(9, 10**6), "positives exceed"),
    (lambda r: r["state"]["models"]["default"].__setitem__("rows", -3),
     "rows must be an int"),
])
def test_validator_corruption_matrix(mutate, expect):
    report = _clean_report()
    mutate(report)
    errors = cms.validate_stat_health(report)
    assert errors and any(expect in e for e in errors), errors


def test_validator_windows_reverse_needs_two_windows():
    # the reverse-corruption above is only meaningful with >= 2 sealed
    # windows; pin the fixture so the matrix cannot silently weaken.
    report = _clean_report()
    windows = report["state"]["models"]["default"]["channels"]["cate"][
        "windows"]
    assert len(windows) >= 2


def test_required_counters_include_stat_families():
    for fam in ("serving_stat_rows_total", "serving_stat_windows_total",
                "stat_drift_events_total"):
        assert fam in cms.REQUIRED_COUNTERS


# ── the stat_drift invariant ──────────────────────────────────────────


def _episode_dir(tmp_path, name, with_report=True):
    d = tmp_path / name
    d.mkdir()
    (d / inv.SUMMARY_BASENAME).write_text(json.dumps(
        {"workload": "serving", "seed": 1}
    ))
    if with_report:
        sh.write_stat_health(
            str(d), _populated_monitor(calibration=True).state_dict()
        )
    return inv.RunArtifacts(str(d))


def test_stat_drift_invariant_pass_fail_skip(tmp_path):
    ep = _episode_dir(tmp_path, "ep")
    ref = _episode_dir(tmp_path, "ref")
    verdict = inv.REGISTRY["stat_drift"].fn(ep, ref)
    assert verdict.verdict == "pass", verdict.detail

    # tamper with a window count: mass conservation must fail
    path = os.path.join(ep.outdir, sh.STAT_HEALTH_BASENAME)
    report = json.loads(open(path).read())
    report["state"]["models"]["default"]["channels"]["cate"]["windows"][0][
        "sketch"]["counts"][0] += 7
    # keep the report consistent with the tampered state so the purity
    # check passes and the MASS check is what fires
    tampered = sh.stat_health_report(report["state"])
    with open(path, "w") as f:
        json.dump(tampered, f, indent=1)
    verdict = inv.REGISTRY["stat_drift"].fn(inv.RunArtifacts(ep.outdir), ref)
    assert verdict.verdict == "fail"
    assert "mass not conserved" in verdict.detail

    # a report whose summary was hand-edited fails the purity recompute
    with open(path, "w") as f:
        report = sh.stat_health_report(
            _populated_monitor().state_dict()
        )
        report["drift"]["events"] = 999
        json.dump(report, f, indent=1)
    verdict = inv.REGISTRY["stat_drift"].fn(inv.RunArtifacts(ep.outdir), ref)
    assert verdict.verdict == "fail"
    assert "pure function" in verdict.detail

    empty = _episode_dir(tmp_path, "empty", with_report=False)
    verdict = inv.REGISTRY["stat_drift"].fn(empty, ref)
    assert verdict.verdict == "skip"


def test_stat_drift_invariant_is_registered_for_serving():
    assert "stat_drift" in inv.registered_names()
    assert inv.REGISTRY["stat_drift"].workloads == ("serving", "rotation")

"""SLO engine unit tests (ISSUE 7): burn-rate math on hand-built
histogram/counter sequences with KNOWN answers, declared-objective
validation, window-baseline selection, and the slo_report /
serving_report schema validators.

Entirely jax-free and clock-injected — every figure here is asserted
exactly, no sleeps, no daemon."""

from __future__ import annotations

import os
import sys

import pytest

from ate_replication_causalml_tpu.observability.registry import (
    MetricsRegistry,
)
from ate_replication_causalml_tpu.observability.slo import (
    SLO,
    SLOEngine,
    default_serving_slos,
)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
))
import check_metrics_schema as cms  # noqa: E402


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def test_slo_declaration_validation():
    ok = dict(name="x", kind="latency", objective=0.9,
              metric="m", windows_s=(1.0, 10.0), threshold_s=0.1)
    SLO(**ok)
    with pytest.raises(ValueError, match="kind"):
        SLO(**{**ok, "kind": "vibes"})
    for bad in (0.0, 1.0, -1.0, 2.0):
        with pytest.raises(ValueError, match="objective"):
            SLO(**{**ok, "objective": bad})
    for bad_w in ((), (10.0, 1.0), (1.0, 1.0), (-1.0, 2.0)):
        with pytest.raises(ValueError, match="windows"):
            SLO(**{**ok, "windows_s": bad_w})
    with pytest.raises(ValueError, match="threshold_s"):
        SLO(**{**ok, "threshold_s": None})
    with pytest.raises(ValueError, match="duplicate"):
        SLOEngine((SLO(**ok), SLO(**ok)), registry=MetricsRegistry())


def test_latency_burn_rate_known_answers():
    """The core math, end to end: 8 good + 2 bad in a 10 s window
    against a 90% objective is error 0.2 / budget 0.1 = burn 2.0
    (burning); a clean follow-up decade drops the short window to 0
    while the long window still shows the historical 2% = burn 0.2."""
    reg = MetricsRegistry()
    h = reg.bucket_histogram("lat", bounds=(0.1, 0.2, 0.4))
    clock = _Clock(0.0)
    slo = SLO(name="lat", kind="latency", objective=0.9, metric="lat",
              windows_s=(10.0, 100.0), threshold_s=0.2)
    eng = SLOEngine((slo,), registry=reg, clock=clock)
    eng.tick()  # baseline at t=0: (0, 0)

    for _ in range(8):
        h.observe(0.05)   # good (≤ threshold bucket)
    for _ in range(2):
        h.observe(0.35)   # bad (lands past the 0.2 bound)
    clock.t = 10.0
    rep = eng.evaluate()
    (s,) = rep["slos"]
    w10, w100 = s["windows"]
    assert (w10["good"], w10["total"]) == (8.0, 10.0)
    assert w10["error_rate"] == pytest.approx(0.2)
    assert w10["burn_rate"] == pytest.approx(2.0)
    assert s["burning"] is True and s["worst_burn_rate"] == pytest.approx(2.0)

    for _ in range(90):
        h.observe(0.05)
    clock.t = 100.0
    rep = eng.evaluate()
    (s,) = rep["slos"]
    w10, w100 = s["windows"]
    # Short window (baseline = the t=10 tick): 90 good / 90 → clean.
    assert (w10["good"], w10["total"]) == (90.0, 90.0)
    assert w10["burn_rate"] == 0.0
    # Long window (baseline = the t=0 tick): 98/100 → 2% = 0.2 burn.
    assert w100["error_rate"] == pytest.approx(0.02)
    assert w100["burn_rate"] == pytest.approx(0.2)
    assert s["burning"] is False
    assert s["worst_burn_rate"] == pytest.approx(0.2)
    # The report passes its own schema validator.
    assert cms.validate_slo_report(rep) == []


def test_latency_threshold_is_conservative_bucket_edge():
    """An observation in the bucket STRADDLING the threshold counts
    bad (Prometheus-style conservative reading): threshold 0.15 over
    bounds (0.1, 0.2) credits only the ≤0.1 bucket."""
    reg = MetricsRegistry()
    h = reg.bucket_histogram("lat", bounds=(0.1, 0.2))
    clock = _Clock(0.0)
    eng = SLOEngine(
        (SLO(name="l", kind="latency", objective=0.5, metric="lat",
             windows_s=(10.0,), threshold_s=0.15),),
        registry=reg, clock=clock,
    )
    eng.tick()
    h.observe(0.05)   # ≤ 0.1: good
    h.observe(0.12)   # in the 0.2 bucket: conservatively BAD
    clock.t = 5.0
    (s,) = eng.evaluate()["slos"]
    assert (s["windows"][0]["good"], s["windows"][0]["total"]) == (1.0, 2.0)


def test_availability_good_match_and_labels():
    """Availability counts the good_match label pair against ALL
    samples — ok vs rejected/error/timeout — and an empty window is
    zero burn, not a divide-by-zero."""
    reg = MetricsRegistry()
    clock = _Clock(0.0)
    eng = SLOEngine(
        (SLO(name="avail", kind="availability", objective=0.5,
             metric="reqs", windows_s=(10.0,)),),
        registry=reg, clock=clock,
    )
    rep = eng.evaluate()  # family does not even exist yet
    assert rep["slos"][0]["windows"][0]["burn_rate"] == 0.0

    c = reg.counter("reqs")
    c.inc(3, status="ok")
    c.inc(2, status="rejected_overloaded")
    c.inc(1, status="error")
    clock.t = 5.0
    (s,) = eng.evaluate()["slos"]
    w = s["windows"][0]
    assert (w["good"], w["total"]) == (3.0, 6.0)
    assert w["error_rate"] == pytest.approx(0.5)
    assert w["burn_rate"] == pytest.approx(1.0)  # budget 0.5
    assert s["burning"] is False  # exactly on budget, not over


def test_window_baseline_selection_and_actual_s():
    """A window picks the NEWEST tick at or before its start; while
    history is shorter than the window it differences against the
    oldest tick and reports the truth in actual_s."""
    reg = MetricsRegistry()
    h = reg.bucket_histogram("lat", bounds=(1.0,))
    clock = _Clock(0.0)
    eng = SLOEngine(
        (SLO(name="l", kind="latency", objective=0.9, metric="lat",
             windows_s=(100.0,), threshold_s=1.0),),
        registry=reg, clock=clock,
    )
    eng.tick()           # t=0
    h.observe(0.5)
    clock.t = 5.0
    (s,) = eng.evaluate()["slos"]
    w = s["windows"][0]
    assert w["actual_s"] == pytest.approx(5.0)  # window not yet filled
    assert (w["good"], w["total"]) == (1.0, 1.0)


def test_history_retention_is_bounded():
    reg = MetricsRegistry()
    reg.bucket_histogram("lat", bounds=(1.0,))
    clock = _Clock(0.0)
    eng = SLOEngine(
        (SLO(name="l", kind="latency", objective=0.9, metric="lat",
             windows_s=(10.0,), threshold_s=1.0),),
        registry=reg, clock=clock,
    )
    for i in range(1000):
        clock.t = float(i)
        eng.tick()
    # retention = 10 * 1.25 + 1 = 13.5 s of ticks, not 1000.
    assert len(eng._history) <= 16


def test_default_serving_slos_shape():
    slos = default_serving_slos(latency_threshold_s=0.1)
    assert [s.name for s in slos] == ["availability", "latency"]
    assert slos[1].threshold_s == 0.1
    assert all(s.windows_s == slos[0].windows_s for s in slos)


def test_kind_mismatch_raises():
    """A latency SLO pointed at a counter family is a config bug and
    must raise, not silently report zero."""
    reg = MetricsRegistry()
    reg.counter("reqs").inc(1, status="ok")
    eng = SLOEngine(
        (SLO(name="l", kind="latency", objective=0.9, metric="reqs",
             windows_s=(10.0,), threshold_s=1.0),),
        registry=reg, clock=_Clock(0.0),
    )
    with pytest.raises(TypeError, match="bucket_histogram"):
        eng.tick()


# ── the report validators reject corrupted artifacts ───────────────────


def test_slo_report_validator_rejects_corruption():
    reg = MetricsRegistry()
    reg.bucket_histogram("lat", bounds=(1.0,))
    eng = SLOEngine(
        (SLO(name="l", kind="latency", objective=0.9, metric="lat",
             windows_s=(10.0, 60.0), threshold_s=1.0),),
        registry=reg, clock=_Clock(0.0),
    )
    good = eng.evaluate()
    assert cms.validate_slo_report(good) == []
    # Windows out of order (the "burn-rate windows monotone" gate).
    bad = {**good, "slos": [dict(good["slos"][0])]}
    bad["slos"][0]["windows"] = list(reversed(bad["slos"][0]["windows"]))
    assert any("ascending" in e for e in cms.validate_slo_report(bad))
    # Hand-edited worst burn.
    bad2 = {**good, "slos": [dict(good["slos"][0],
                                  worst_burn_rate=99.0)]}
    assert any("worst_burn_rate" in e for e in cms.validate_slo_report(bad2))
    # good > total must fail.
    bad3 = {**good, "slos": [dict(good["slos"][0])]}
    bad3["slos"][0]["windows"] = [
        dict(bad3["slos"][0]["windows"][0], good=5.0, total=1.0)
    ]
    assert any("exceeds total" in e for e in cms.validate_slo_report(bad3))


def test_serving_report_validator_rejects_corruption():
    phases = {
        k: {"count": 2, "sum_s": 0.2, "p50_s": 0.1, "p99_s": 0.1,
            "max_s": 0.1}
        for k in ("coalesce_wait", "queue_wait", "dispatch", "device",
                  "reply")
    }
    good = {
        "schema_version": 1,
        "window_s": 1.0,
        "requests": {"count": 2, "status": {"ok": 2}, "with_phases": 2,
                     "e2e": {"count": 2, "sum_s": 1.0, "p50_s": 0.5,
                             "p99_s": 0.5, "max_s": 0.5},
                     "phases": phases},
        "batches": {"count": 2, "rows": 4, "by_bucket": {"4": 2},
                    "fill_mean": 0.5, "pad_fraction_mean": 0.5,
                    "close_reasons": {"bucket_full": 1,
                                      "window_expired": 1}},
        "rejects": {"count": 1, "by_reason": {"overloaded": 1},
                    "timeline": [{"ts_s": 0.1, "reason": "overloaded",
                                  "request_id": "r1"}],
                    "timeline_truncated": 0},
    }
    assert cms.validate_serving_report(good) == []
    # Σ close-reasons must equal the batch count.
    bad = {**good, "batches": dict(good["batches"],
                                   close_reasons={"bucket_full": 1})}
    assert any("close reasons" in e
               for e in cms.validate_serving_report(bad))
    # Torn phase histograms (unequal counts across phases) must fail.
    torn = {k: dict(v) for k, v in phases.items()}
    torn["device"] = dict(torn["device"], count=1)
    bad2 = {**good, "requests": dict(good["requests"], phases=torn)}
    assert any("differ across phases" in e
               for e in cms.validate_serving_report(bad2))
    # Quantiles out of order.
    bad3 = {**good, "requests": dict(
        good["requests"],
        phases={**phases, "reply": dict(phases["reply"], p50_s=9.0)},
    )}
    assert any("out of order" in e for e in cms.validate_serving_report(bad3))

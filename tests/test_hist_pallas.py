"""Pallas histogram kernel vs chunked-XLA reference vs numpy truth.

The kernel runs in interpret mode here (conftest forces the CPU
backend); on TPU the same kernel compiles via Mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.ops.hist_pallas import (
    bin_histogram,
    bin_histogram_pallas,
    bin_histogram_xla,
)


def _numpy_hist(codes, node, weights, max_nodes, n_bins):
    k_w, n = weights.shape
    p = codes.shape[1]
    out = np.zeros((k_w, max_nodes, p, n_bins), np.float64)
    for i in range(n):
        m = node[i]
        if 0 <= m < max_nodes:
            for f in range(p):
                out[:, m, f, codes[i, f]] += weights[:, i]
    return out


@pytest.fixture(scope="module")
def case():
    rng = np.random.default_rng(0)
    n, p, n_bins, max_nodes = 1000, 7, 16, 8
    codes = rng.integers(0, n_bins, (n, p)).astype(np.int32)
    node = rng.integers(0, max_nodes, n).astype(np.int32)
    weights = rng.poisson(1.0, (2, n)).astype(np.float32)
    weights[1] *= rng.uniform(-1, 1, n).astype(np.float32)
    return codes, node, weights, max_nodes, n_bins


def test_pallas_interpret_matches_numpy(case):
    codes, node, weights, max_nodes, n_bins = case
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    got = bin_histogram_pallas(
        jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
        max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_xla_fallback_matches_numpy(case):
    codes, node, weights, max_nodes, n_bins = case
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    got = bin_histogram_xla(
        jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
        max_nodes=max_nodes, n_bins=n_bins,
    )
    np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_xla_chunked_path(case):
    codes, node, weights, max_nodes, n_bins = case
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    got = bin_histogram_xla(
        jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
        max_nodes=max_nodes, n_bins=n_bins, row_chunk=128,
    )
    np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_out_of_range_nodes_drop(case):
    codes, node, weights, max_nodes, n_bins = case
    node = node.copy()
    node[:100] = -1  # padded/inactive rows must contribute nothing
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    for backend in ("pallas_interpret", "xla"):
        got = bin_histogram(
            jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
            max_nodes=max_nodes, n_bins=n_bins, backend=backend,
        )
        np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_vmap_over_trees(case):
    """The forest engine vmaps the histogram over a tree chunk — node ids
    and weights are per-tree, codes shared."""
    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(1)
    nodes_t = np.stack([node, rng.integers(0, max_nodes, node.shape[0]).astype(np.int32)])
    weights_t = np.stack([weights, rng.poisson(1.0, weights.shape).astype(np.float32)])

    def one(nd, w):
        return bin_histogram_pallas(
            jnp.asarray(codes), nd, w, max_nodes=max_nodes, n_bins=n_bins,
            tile=256, interpret=True,
        )

    got = jax.vmap(one)(jnp.asarray(nodes_t), jnp.asarray(weights_t))
    for t in range(2):
        truth = _numpy_hist(codes, nodes_t[t], weights_t[t], max_nodes, n_bins)
        np.testing.assert_allclose(np.asarray(got[t]), truth, rtol=0, atol=1e-4)


def test_batched_kernel_matches_per_tree(case):
    """bin_histogram_pallas_batched must be BIT-identical to T separate
    per-tree kernel calls (same tile order, same f32 accumulation) and
    match numpy truth — the grow chunks rely on this to keep goldens."""
    from ate_replication_causalml_tpu.ops.hist_pallas import (
        bin_histogram_batched,
        bin_histogram_pallas_batched,
    )

    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(2)
    T = 3
    nodes_t = np.stack(
        [node] + [rng.integers(-1, max_nodes, node.shape[0]).astype(np.int32)
                  for _ in range(T - 1)]
    )
    weights_t = np.stack(
        [weights] + [rng.normal(size=weights.shape).astype(np.float32)
                     for _ in range(T - 1)]
    )
    got = bin_histogram_pallas_batched(
        jnp.asarray(codes), jnp.asarray(nodes_t), jnp.asarray(weights_t),
        max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
    )
    per_tree = jnp.stack([
        bin_histogram_pallas(
            jnp.asarray(codes), jnp.asarray(nodes_t[t]), jnp.asarray(weights_t[t]),
            max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
        )
        for t in range(T)
    ])
    # Float weights: identical up to f32 summation rounding (the CPU
    # interpret backend may re-associate the wider batched matmul; on
    # the MXU both shapes accumulate in the same systolic order).
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(per_tree), rtol=1e-6, atol=1e-5
    )
    # Integer weights: every partial sum is exact in f32 → BIT-identical
    # regardless of association order.
    w_int = jnp.asarray(np.abs(weights_t).round())
    got_i = bin_histogram_pallas_batched(
        jnp.asarray(codes), jnp.asarray(nodes_t), w_int,
        max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
    )
    per_i = jnp.stack([
        bin_histogram_pallas(
            jnp.asarray(codes), jnp.asarray(nodes_t[t]), w_int[t],
            max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
        )
        for t in range(T)
    ])
    assert jnp.array_equal(got_i, per_i)
    for t in range(T):
        truth = _numpy_hist(codes, nodes_t[t], weights_t[t], max_nodes, n_bins)
        np.testing.assert_allclose(np.asarray(got[t]), truth, rtol=0, atol=1e-4)
    # The dispatch wrapper's XLA path agrees too (used on CPU/test hosts).
    got_xla = bin_histogram_batched(
        jnp.asarray(codes), jnp.asarray(nodes_t), jnp.asarray(weights_t),
        max_nodes=max_nodes, n_bins=n_bins, backend="xla",
    )
    np.testing.assert_allclose(np.asarray(got_xla), np.asarray(got), rtol=0, atol=1e-4)


def test_custom_vmap_collapses_to_batched(case):
    """vmap (and nested vmap) over the pallas dispatch must produce the
    same numbers as per-tree calls — the rule flattens every vmap level
    into the kernel's tree axis (the growers rely on this transform)."""
    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(3)
    codes_j = jnp.asarray(codes)
    nodes_t = jnp.asarray(
        np.stack([node] + [rng.integers(0, max_nodes, node.shape[0]).astype(np.int32)
                           for _ in range(3)])
    )
    weights_t = jnp.asarray(
        rng.poisson(1.0, (4,) + weights.shape).astype(np.float32)
    )

    def one(nd, w):
        return bin_histogram(
            codes_j, nd, w, max_nodes=max_nodes, n_bins=n_bins,
            backend="pallas_interpret",
        )

    got = jax.vmap(one)(nodes_t, weights_t)
    want = jnp.stack([one(nodes_t[t], weights_t[t]) for t in range(4)])
    assert jnp.array_equal(got, want)  # integer weights → exact

    # Nested vmap (the causal grower: groups × little-bag trees).
    nodes_g = nodes_t.reshape(2, 2, -1)
    weights_g = weights_t.reshape(2, 2, *weights.shape)
    got_nested = jax.vmap(jax.vmap(one))(nodes_g, weights_g)
    assert jnp.array_equal(got_nested.reshape(got.shape), want)

    # Batched codes (per-group gathers): falls back to per-slice loops.
    codes_g = jnp.stack([codes_j, codes_j[::-1]])

    def one_c(cd, nd, w):
        return bin_histogram(
            cd, nd, w, max_nodes=max_nodes, n_bins=n_bins,
            backend="pallas_interpret",
        )

    got_c = jax.vmap(one_c)(codes_g, nodes_g[0], weights_g[0])
    want_c = jnp.stack([one_c(codes_g[i], nodes_g[0, i], weights_g[0, i])
                        for i in range(2)])
    assert jnp.array_equal(got_c, want_c)


def test_batched_tree_cap_positive():
    from ate_replication_causalml_tpu.ops.hist_pallas import batched_tree_cap

    assert batched_tree_cap(128, 2) >= 8   # classifier/causal deepest level
    assert batched_tree_cap(256, 2) >= 4
    assert batched_tree_cap(1 << 12, 2) >= 1  # degenerate: never zero


def test_forest_identical_across_backends():
    """Same key → bit-identical splits and leaves whether the level
    histograms come from the Pallas kernel (interpret), the chunked-XLA
    path, or the shared-one-hot matmul.

    Bit-identity holds everywhere for *integer-weight* channels (counts,
    counts·y∈{0,1} — exact in f32 in any summation order). For the causal
    forest's continuous ρ channel it holds on CPU but is tolerance-level
    on real TPU (~2e-3 relative accumulation-order noise, which can flip
    near-tie splits); the downstream ATE was verified statistically
    equivalent across backends on TPU (0.4391 vs 0.4394, SE 0.034)."""
    from ate_replication_causalml_tpu.models.forest import fit_forest_classifier

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(400, 6)).astype(np.float32))
    y = jnp.asarray((rng.uniform(size=400) < 0.4).astype(np.float32))
    key = jax.random.key(11)
    kw = dict(n_trees=4, depth=4, n_bins=16, tree_chunk=4)
    ref = fit_forest_classifier(x, y, key, hist_backend="onehot", **kw)
    for backend in ("pallas_interpret", "xla"):
        got = fit_forest_classifier(x, y, key, hist_backend=backend, **kw)
        np.testing.assert_array_equal(np.asarray(got.split_feat), np.asarray(ref.split_feat))
        np.testing.assert_array_equal(np.asarray(got.split_bin), np.asarray(ref.split_bin))
        np.testing.assert_allclose(
            np.asarray(got.leaf_value), np.asarray(ref.leaf_value), atol=1e-5
        )


def test_causal_forest_equivalent_across_backends():
    """The streaming (Pallas) causal grower uses the ρ-DECOMPOSED level
    pipeline (5 level-invariant channels composed with per-node
    coefficients — see grow_one_streaming) which is algebraically
    identical to the direct onehot/xla formulation but not bit-identical:
    f32 rounding can flip exact-tie splits. Contract: same keys → near-
    total split agreement and matching honest leaf statistics wherever
    the routing agrees."""
    from ate_replication_causalml_tpu.models.causal_forest import (
        grow_causal_forest,
        predict_cate,
    )

    rng = np.random.default_rng(4)
    n = 300
    x = jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=n).astype(np.float32))
    yt = jnp.asarray(rng.normal(size=n).astype(np.float32))
    key = jax.random.key(5)
    kw = dict(n_trees=4, depth=4, n_bins=16, group_chunk=2)
    ref = grow_causal_forest(x, wt, yt, key, hist_backend="onehot", **kw)
    got = grow_causal_forest(x, wt, yt, key, hist_backend="pallas_interpret", **kw)
    agree = np.mean(
        (np.asarray(got.split_feat) == np.asarray(ref.split_feat))
        & (np.asarray(got.split_bin) == np.asarray(ref.split_bin))
    )
    assert agree >= 0.95, f"split agreement {agree:.3f}"
    cate_ref = predict_cate(ref, x, oob=False).cate
    cate_got = predict_cate(got, x, oob=False).cate
    # Tie flips move a handful of rows between sibling leaves; the
    # forest-level prediction must stay essentially the same.
    err = float(jnp.abs(cate_got - cate_ref).mean())
    scale = float(jnp.abs(cate_ref).mean()) + 1e-6
    assert err / scale < 0.05, (err, scale)


def test_rho_composition_matches_direct():
    """Unit check of the streaming grower's algebra: Σ_cell gw·ρ composed
    from the five channel histograms equals the directly-computed
    ρ-weighted histogram (ρ from the same node's w̄, ȳ, τ)."""
    from ate_replication_causalml_tpu.ops.hist_pallas import bin_histogram_xla

    rng = np.random.default_rng(6)
    n, p, n_bins, m = 2000, 4, 8, 4
    codes = jnp.asarray(rng.integers(0, n_bins, (n, p)), jnp.int32)
    ids = jnp.asarray(rng.integers(0, m, n), jnp.int32)
    gw = jnp.asarray(rng.poisson(1.0, n), jnp.float32)
    wt = jnp.asarray(rng.normal(size=n) * 0.5, jnp.float32)
    yt = jnp.asarray(rng.normal(size=n), jnp.float32)

    ch = jnp.stack([jnp.ones_like(wt), wt, yt, wt * wt, wt * yt]) * gw[None, :]
    hist5 = bin_histogram_xla(codes, ids, ch, max_nodes=m, n_bins=n_bins)
    tot = np.asarray(hist5[:, :, 0, :].sum(axis=2))  # (5, m)
    c, sw, sy, sww, swy = tot
    wbar = sw / np.maximum(c, 1.0)
    ybar = sy / np.maximum(c, 1.0)
    varw = c * sww - sw * sw
    tau = np.where(varw > 1e-12, (c * swy - sw * sy) / np.maximum(varw, 1e-12), 0.0)

    h = np.asarray(hist5)
    bc = lambda v: v[:, None, None]
    rho_hist_composed = (
        h[4] - bc(wbar) * h[2] + bc(2 * tau * wbar - ybar) * h[1]
        + bc(wbar * ybar - tau * wbar**2) * h[0] - bc(tau) * h[3]
    )

    # Direct: per-row ρ with each row's node coefficients.
    ids_np = np.asarray(ids)
    wc = np.asarray(wt) - wbar[ids_np]
    yc = np.asarray(yt) - ybar[ids_np]
    rho = wc * (yc - wc * tau[ids_np])
    direct = np.asarray(
        bin_histogram_xla(
            codes, ids, jnp.asarray((np.asarray(gw) * rho)[None, :], jnp.float32),
            max_nodes=m, n_bins=n_bins,
        )
    )[0]
    np.testing.assert_allclose(rho_hist_composed, direct, rtol=2e-4, atol=2e-4)


def test_resolve_backend_row_aware_policy(monkeypatch):
    """'auto' picks the streaming kernel only on TPU and only past the
    measured row threshold; explicit choices always pass through."""
    import ate_replication_causalml_tpu.ops.hist_pallas as hp

    monkeypatch.setattr(hp.jax, "default_backend", lambda: "cpu")
    assert hp.resolve_hist_backend("auto") == "onehot"
    assert hp.resolve_hist_backend("auto", allow_onehot=False) == "xla"
    assert hp.resolve_hist_backend("auto", n_rows=10**7) == "onehot"

    monkeypatch.setattr(hp.jax, "default_backend", lambda: "tpu")
    assert hp.resolve_hist_backend("auto") == "xla"
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD - 1, n_bins=64
    ) == "xla"
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD, n_bins=64
    ) == "pallas"
    # Reference-scale (~9k-row biased sample) and up runs the kernel
    # since the tree-batched rewrite.
    assert hp.resolve_hist_backend("auto", n_rows=9_000, n_bins=64) == "pallas"
    # The kernel caps at 128 bins; wider binnings stay on XLA even at
    # large row counts (where round-1 'auto' would have crashed).
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD, n_bins=200
    ) == "xla"
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD
    ) == "xla"  # n_bins unknown -> no kernel
    for explicit in ("xla", "pallas", "pallas_bf16", "pallas_interpret", "onehot"):
        assert hp.resolve_hist_backend(explicit, n_rows=10**7, n_bins=64) == explicit


def test_bf16_kernel_bit_exact_for_integer_weights(case):
    """The bf16 MXU path must be BIT-exact against f32 whenever every
    weight is integer-valued in [-256, 256] — the condition under which
    'auto' upgrades integer-weight forests to pallas_bf16."""
    codes, node, _, max_nodes, n_bins = case
    rng = np.random.default_rng(3)
    counts = rng.poisson(1.0, case[0].shape[0]).astype(np.float32)
    y01 = rng.integers(0, 2, counts.shape[0]).astype(np.float32)
    weights = np.stack([counts, counts * y01])
    args = (jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights))
    kw = dict(max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True)
    f32 = bin_histogram_pallas(*args, **kw)
    bf16 = bin_histogram_pallas(*args, bf16=True, **kw)
    np.testing.assert_array_equal(np.asarray(f32), np.asarray(bf16))
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    np.testing.assert_array_equal(np.asarray(bf16), truth.astype(np.float32))


def test_resolve_backend_bf16_policy(monkeypatch):
    """Round 5: 'auto' resolves integer-weight fits to the SAME f32
    kernel as continuous fits (one shared grow executable — the bf16
    delta is noise on this chip generation, and integer sums are exact
    in both). Only the explicit opt-in (allow_lossy_bf16) picks bf16."""
    import ate_replication_causalml_tpu.ops.hist_pallas as hp

    monkeypatch.setattr(hp.jax, "default_backend", lambda: "tpu")
    big = hp._PALLAS_ROWS_THRESHOLD
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, integer_weights=True) == "pallas"
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, integer_weights=False) == "pallas"
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, allow_lossy_bf16=True) == "pallas_bf16"
    # Below the threshold / off-TPU the flag changes nothing.
    assert hp.resolve_hist_backend(
        "auto", n_rows=1000, n_bins=64, integer_weights=True) == "xla"
    monkeypatch.setattr(hp.jax, "default_backend", lambda: "cpu")
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, integer_weights=True) == "onehot"


def test_shared_weights_kernel_bit_identical(case):
    """Round-5 contract: the shared-weights kernel with membership in
    the id stream is BIT-identical to the per-tree kernel fed the
    equivalent 0/1-masked weights (the causal grower's honest/subsample
    fold — models/causal_forest.py::grow_one_streaming)."""
    from ate_replication_causalml_tpu.ops.hist_pallas import (
        bin_histogram_pallas_batched,
        bin_histogram_pallas_batched_shared,
    )

    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(7)
    t = 3
    n = codes.shape[0]
    # Per-tree 0/1 membership masks and per-tree node streams.
    member = rng.integers(0, 2, (t, n)).astype(np.float32)
    nodes_t = rng.integers(0, max_nodes, (t, n)).astype(np.int32)
    shared_w = rng.uniform(-2, 2, (5, n)).astype(np.float32)

    # Old formulation: per-tree weights = mask · shared channels.
    w_per_tree = member[:, None, :] * shared_w[None, :, :]  # (T, 5, n)
    ref = bin_histogram_pallas_batched(
        jnp.asarray(codes), jnp.asarray(nodes_t), jnp.asarray(w_per_tree),
        max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
    )
    # New formulation: membership folded into ids, weights shared.
    ids_masked = np.where(member > 0, nodes_t, -1).astype(np.int32)
    got = bin_histogram_pallas_batched_shared(
        jnp.asarray(codes), jnp.asarray(ids_masked), jnp.asarray(shared_w),
        max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_shared_custom_vmap_collapses(case):
    """bin_histogram_shared under nested vmaps (groups × trees) returns
    the same histograms as per-slice calls, with the weight stack never
    batched.

    FIXED in PR 10 (was the known-red f32-ulp cell carried since
    PR 1): the batched kernel used to concatenate every tree into ONE
    (T·K·M, TILE) dot, so the reduction association XLA:CPU picked
    depended on the batch size T and the collapsed call (T=6) drifted
    at ulp level from the per-slice calls (T=3) for float weights. The
    kernel now issues one (K·M, TILE) dot PER TREE — every tree's
    numbers are independent of the batch it rides in, so this holds
    with array_equal for float stacks too, on any backend."""
    from ate_replication_causalml_tpu.ops.hist_pallas import bin_histogram_shared

    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(11)
    g, t = 2, 3
    n = codes.shape[0]
    nodes_gt = rng.integers(-1, max_nodes, (g, t, n)).astype(np.int32)
    shared_w = rng.uniform(-2, 2, (4, n)).astype(np.float32)

    def one(ids):
        return bin_histogram_shared(
            jnp.asarray(codes), ids, jnp.asarray(shared_w),
            max_nodes=max_nodes, n_bins=n_bins, backend="pallas_interpret",
        )

    got = jax.vmap(jax.vmap(one))(jnp.asarray(nodes_gt))
    for i in range(g):
        for j in range(t):
            ref = one(jnp.asarray(nodes_gt[i, j]))
            np.testing.assert_array_equal(
                np.asarray(got[i, j]), np.asarray(ref)
            )


def test_node_sums_shared_matches_masked_node_sums(case):
    from ate_replication_causalml_tpu.ops.hist_pallas import (
        node_sums,
        node_sums_shared,
    )

    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(13)
    n = codes.shape[0]
    member = rng.integers(0, 2, n).astype(np.float32)
    shared_w = rng.uniform(-2, 2, (5, n)).astype(np.float32)
    ref = node_sums(
        jnp.asarray(node), jnp.asarray(member[None, :] * shared_w), max_nodes,
        backend="pallas_interpret",
    )
    got = node_sums_shared(
        jnp.asarray(np.where(member > 0, node, -1).astype(np.int32)),
        jnp.asarray(shared_w), max_nodes, backend="pallas_interpret",
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

"""Pallas histogram kernel vs chunked-XLA reference vs numpy truth.

The kernel runs in interpret mode here (conftest forces the CPU
backend); on TPU the same kernel compiles via Mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ate_replication_causalml_tpu.ops.hist_pallas import (
    bin_histogram,
    bin_histogram_pallas,
    bin_histogram_xla,
)


def _numpy_hist(codes, node, weights, max_nodes, n_bins):
    k_w, n = weights.shape
    p = codes.shape[1]
    out = np.zeros((k_w, max_nodes, p, n_bins), np.float64)
    for i in range(n):
        m = node[i]
        if 0 <= m < max_nodes:
            for f in range(p):
                out[:, m, f, codes[i, f]] += weights[:, i]
    return out


@pytest.fixture(scope="module")
def case():
    rng = np.random.default_rng(0)
    n, p, n_bins, max_nodes = 1000, 7, 16, 8
    codes = rng.integers(0, n_bins, (n, p)).astype(np.int32)
    node = rng.integers(0, max_nodes, n).astype(np.int32)
    weights = rng.poisson(1.0, (2, n)).astype(np.float32)
    weights[1] *= rng.uniform(-1, 1, n).astype(np.float32)
    return codes, node, weights, max_nodes, n_bins


def test_pallas_interpret_matches_numpy(case):
    codes, node, weights, max_nodes, n_bins = case
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    got = bin_histogram_pallas(
        jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
        max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_xla_fallback_matches_numpy(case):
    codes, node, weights, max_nodes, n_bins = case
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    got = bin_histogram_xla(
        jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
        max_nodes=max_nodes, n_bins=n_bins,
    )
    np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_xla_chunked_path(case):
    codes, node, weights, max_nodes, n_bins = case
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    got = bin_histogram_xla(
        jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
        max_nodes=max_nodes, n_bins=n_bins, row_chunk=128,
    )
    np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_out_of_range_nodes_drop(case):
    codes, node, weights, max_nodes, n_bins = case
    node = node.copy()
    node[:100] = -1  # padded/inactive rows must contribute nothing
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    for backend in ("pallas_interpret", "xla"):
        got = bin_histogram(
            jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights),
            max_nodes=max_nodes, n_bins=n_bins, backend=backend,
        )
        np.testing.assert_allclose(np.asarray(got), truth, rtol=0, atol=1e-4)


def test_vmap_over_trees(case):
    """The forest engine vmaps the histogram over a tree chunk — node ids
    and weights are per-tree, codes shared."""
    codes, node, weights, max_nodes, n_bins = case
    rng = np.random.default_rng(1)
    nodes_t = np.stack([node, rng.integers(0, max_nodes, node.shape[0]).astype(np.int32)])
    weights_t = np.stack([weights, rng.poisson(1.0, weights.shape).astype(np.float32)])

    def one(nd, w):
        return bin_histogram_pallas(
            jnp.asarray(codes), nd, w, max_nodes=max_nodes, n_bins=n_bins,
            tile=256, interpret=True,
        )

    got = jax.vmap(one)(jnp.asarray(nodes_t), jnp.asarray(weights_t))
    for t in range(2):
        truth = _numpy_hist(codes, nodes_t[t], weights_t[t], max_nodes, n_bins)
        np.testing.assert_allclose(np.asarray(got[t]), truth, rtol=0, atol=1e-4)


def test_forest_identical_across_backends():
    """Same key → bit-identical splits and leaves whether the level
    histograms come from the Pallas kernel (interpret), the chunked-XLA
    path, or the shared-one-hot matmul.

    Bit-identity holds everywhere for *integer-weight* channels (counts,
    counts·y∈{0,1} — exact in f32 in any summation order). For the causal
    forest's continuous ρ channel it holds on CPU but is tolerance-level
    on real TPU (~2e-3 relative accumulation-order noise, which can flip
    near-tie splits); the downstream ATE was verified statistically
    equivalent across backends on TPU (0.4391 vs 0.4394, SE 0.034)."""
    from ate_replication_causalml_tpu.models.forest import fit_forest_classifier

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(400, 6)).astype(np.float32))
    y = jnp.asarray((rng.uniform(size=400) < 0.4).astype(np.float32))
    key = jax.random.key(11)
    kw = dict(n_trees=4, depth=4, n_bins=16, tree_chunk=4)
    ref = fit_forest_classifier(x, y, key, hist_backend="onehot", **kw)
    for backend in ("pallas_interpret", "xla"):
        got = fit_forest_classifier(x, y, key, hist_backend=backend, **kw)
        np.testing.assert_array_equal(np.asarray(got.split_feat), np.asarray(ref.split_feat))
        np.testing.assert_array_equal(np.asarray(got.split_bin), np.asarray(ref.split_bin))
        np.testing.assert_allclose(
            np.asarray(got.leaf_value), np.asarray(ref.leaf_value), atol=1e-5
        )


def test_causal_forest_identical_across_backends():
    from ate_replication_causalml_tpu.models.causal_forest import grow_causal_forest

    rng = np.random.default_rng(4)
    n = 300
    x = jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=n).astype(np.float32))
    yt = jnp.asarray(rng.normal(size=n).astype(np.float32))
    key = jax.random.key(5)
    kw = dict(n_trees=4, depth=4, n_bins=16, group_chunk=2)
    ref = grow_causal_forest(x, wt, yt, key, hist_backend="onehot", **kw)
    got = grow_causal_forest(x, wt, yt, key, hist_backend="pallas_interpret", **kw)
    np.testing.assert_array_equal(np.asarray(got.split_feat), np.asarray(ref.split_feat))
    np.testing.assert_array_equal(np.asarray(got.split_bin), np.asarray(ref.split_bin))
    np.testing.assert_allclose(
        np.asarray(got.leaf_stats), np.asarray(ref.leaf_stats), atol=1e-4
    )


def test_resolve_backend_row_aware_policy(monkeypatch):
    """'auto' picks the streaming kernel only on TPU and only past the
    measured row threshold; explicit choices always pass through."""
    import ate_replication_causalml_tpu.ops.hist_pallas as hp

    monkeypatch.setattr(hp.jax, "default_backend", lambda: "cpu")
    assert hp.resolve_hist_backend("auto") == "onehot"
    assert hp.resolve_hist_backend("auto", allow_onehot=False) == "xla"
    assert hp.resolve_hist_backend("auto", n_rows=10**7) == "onehot"

    monkeypatch.setattr(hp.jax, "default_backend", lambda: "tpu")
    assert hp.resolve_hist_backend("auto") == "xla"
    assert hp.resolve_hist_backend("auto", n_rows=100_000, n_bins=64) == "xla"
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD, n_bins=64
    ) == "pallas"
    # The kernel caps at 128 bins; wider binnings stay on XLA even at
    # large row counts (where round-1 'auto' would have crashed).
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD, n_bins=200
    ) == "xla"
    assert hp.resolve_hist_backend(
        "auto", n_rows=hp._PALLAS_ROWS_THRESHOLD
    ) == "xla"  # n_bins unknown -> no kernel
    for explicit in ("xla", "pallas", "pallas_bf16", "pallas_interpret", "onehot"):
        assert hp.resolve_hist_backend(explicit, n_rows=10**7, n_bins=64) == explicit


def test_bf16_kernel_bit_exact_for_integer_weights(case):
    """The bf16 MXU path must be BIT-exact against f32 whenever every
    weight is integer-valued in [-256, 256] — the condition under which
    'auto' upgrades integer-weight forests to pallas_bf16."""
    codes, node, _, max_nodes, n_bins = case
    rng = np.random.default_rng(3)
    counts = rng.poisson(1.0, case[0].shape[0]).astype(np.float32)
    y01 = rng.integers(0, 2, counts.shape[0]).astype(np.float32)
    weights = np.stack([counts, counts * y01])
    args = (jnp.asarray(codes), jnp.asarray(node), jnp.asarray(weights))
    kw = dict(max_nodes=max_nodes, n_bins=n_bins, tile=256, interpret=True)
    f32 = bin_histogram_pallas(*args, **kw)
    bf16 = bin_histogram_pallas(*args, bf16=True, **kw)
    np.testing.assert_array_equal(np.asarray(f32), np.asarray(bf16))
    truth = _numpy_hist(codes, node, weights, max_nodes, n_bins)
    np.testing.assert_array_equal(np.asarray(bf16), truth.astype(np.float32))


def test_resolve_backend_bf16_upgrade(monkeypatch):
    """integer_weights=True upgrades the large-row TPU kernel pick to the
    (bit-exact there, measured faster) bf16 kernel — and nothing else."""
    import ate_replication_causalml_tpu.ops.hist_pallas as hp

    monkeypatch.setattr(hp.jax, "default_backend", lambda: "tpu")
    big = hp._PALLAS_ROWS_THRESHOLD
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, integer_weights=True) == "pallas_bf16"
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, integer_weights=False) == "pallas"
    # Below the threshold / off-TPU the flag changes nothing.
    assert hp.resolve_hist_backend(
        "auto", n_rows=1000, n_bins=64, integer_weights=True) == "xla"
    monkeypatch.setattr(hp.jax, "default_backend", lambda: "cpu")
    assert hp.resolve_hist_backend(
        "auto", n_rows=big, n_bins=64, integer_weights=True) == "onehot"

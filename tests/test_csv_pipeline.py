"""End-to-end CSV ingest: a GGL-schema CSV on disk → native reader →
prepare → bias injection → estimator — the reference's actual entry path
(``read.csv``, ``ate_replication.Rmd:33``). The real
socialpresswgeooneperhh_NEIGH.csv is gitignored upstream, so the file
here is the synthetic generator's output written in CSV form."""

import numpy as np
import pytest

from ate_replication_causalml_tpu.data.pipeline import (
    PrepConfig,
    inject_bias,
    load_raw_csv,
    prepare_dataset,
)
from ate_replication_causalml_tpu.data.schema import GGL_SCHEMA
from ate_replication_causalml_tpu.data.synthetic import make_ggl_like
from ate_replication_causalml_tpu.estimators import ate_condmean_ols, naive_ate


@pytest.fixture(scope="module")
def csv_path(tmp_path_factory):
    raw = make_ggl_like(n=12_000, seed=11, true_ate=0.095)
    cols = list(raw)
    mat = np.stack([np.asarray(raw[c], np.float64) for c in cols], axis=1)
    # Sprinkle NA rows to exercise na.omit, plus an extra column the
    # schema should ignore.
    lines = [",".join(cols + ["extraneous"])]
    for i, row in enumerate(mat):
        cells = [repr(float(v)) for v in row] + ["1"]
        if i % 997 == 0:
            cells[3] = "NA"
        lines.append(",".join(cells))
    path = tmp_path_factory.mktemp("csv") / "ggl.csv"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_load_raw_csv_roundtrip(csv_path):
    raw = load_raw_csv(csv_path)
    assert set(raw) == set(GGL_SCHEMA.all_columns)
    n = len(raw[GGL_SCHEMA.treatment])
    assert n == 12_000
    # NA markers came through as NaN in the right column.
    col3 = raw[GGL_SCHEMA.all_columns[3]]
    assert np.isnan(col3[0])


def test_csv_to_estimates(csv_path):
    raw = load_raw_csv(csv_path)
    cfg = PrepConfig(n_obs=8_000, seed=1991)
    frame = prepare_dataset(raw, cfg)
    # Reference order (Rmd:41-44 then :93): sample n_obs, THEN na.omit —
    # so the sampled NA rows come off the top of n_obs.
    assert 7_900 < frame.n < 8_000
    assert np.isfinite(np.asarray(frame.x)).all()
    frame_mod, dropped = inject_bias(frame, cfg)
    assert len(dropped) > 0
    oracle = naive_ate(frame)
    direct = ate_condmean_ols(frame_mod)
    assert np.isfinite(oracle.ate) and np.isfinite(direct.ate)
    # Bias injection bites; the direct method lands nearer the oracle
    # than the naive estimate on the biased sample does.
    naive_biased = naive_ate(frame_mod)
    assert abs(direct.ate - oracle.ate) < abs(naive_biased.ate - oracle.ate)